"""Comms-lean split finding (ISSUE 10): reduce-scatter gain sharding,
compressed collectives, slab-pipelined overlap — parallel/comms.py and
its wiring through the fused rounds, the granular surface, and the
streaming trainers.

Contracts pinned here:
- default-path bit-identity: N-partition trees == 1-partition trees
  under split_comms=reduce_scatter (structure exact, leaf values to
  float tolerance — the same contract test_distributed.py holds for the
  allreduce path);
- reduce_scatter parity vs allreduce across classes x missing x ragged
  F/P remainders x streaming;
- bf16 / int32_fixed wire dtypes hold their COMPUTED error bound and
  the split-agreement contract; int32_fixed merges are bit-stable under
  reduction order (integer sums commute);
- slab-pipelined overlap phasing is BIT-identical (collectives are
  elementwise — phasing cannot change a single value);
- the corrected hist_allreduce_bytes counter witnesses the >= 2x
  per-level payload reduction IN-PROCESS on a multi-device run (the
  acceptance criterion, not a docs claim).
"""

import numpy as np
import pytest

from ddt_tpu.backends import get_backend
from ddt_tpu.config import TrainConfig
from ddt_tpu.data import datasets
from ddt_tpu.data.quantizer import quantize
from ddt_tpu.driver import Driver
from ddt_tpu.parallel import comms
from ddt_tpu.telemetry import counters as tele_counters


def _fit(Xb, y, **kw):
    kw.setdefault("n_trees", 3)
    kw.setdefault("max_depth", 4)
    kw.setdefault("n_bins", 31)
    kw.setdefault("backend", "tpu")
    cfg = TrainConfig(**kw)
    be = get_backend(cfg)
    return Driver(be, cfg, log_every=10**9).fit(Xb, y), be


def _assert_same_structure(a, b):
    np.testing.assert_array_equal(a.feature, b.feature)
    np.testing.assert_array_equal(a.threshold_bin, b.threshold_bin)
    np.testing.assert_array_equal(a.is_leaf, b.is_leaf)
    np.testing.assert_array_equal(a.default_left, b.default_left)


def _assert_same_trees(a, b):
    _assert_same_structure(a, b)
    np.testing.assert_allclose(a.leaf_value, b.leaf_value,
                               rtol=2e-4, atol=2e-5)


@pytest.fixture(scope="module")
def binary_data():
    X, y = datasets.synthetic_binary(4096, n_features=10, seed=11)
    Xb, _ = quantize(X, n_bins=31, seed=11)
    return Xb, y


# --------------------------------------------------------------------- #
# the collectives themselves
# --------------------------------------------------------------------- #

def test_reduce_scatter_matches_psum_slice():
    """reduce_scatter over the tuple (hosts, rows) pod axes: each shard
    holds its contiguous block of the full sum, in flattened axis
    order."""
    import jax

    from ddt_tpu.parallel import mesh as mesh_lib

    P = jax.sharding.PartitionSpec
    mesh = jax.make_mesh((2, 4), ("hosts", "rows"))
    x = np.arange(8 * 16, dtype=np.float32).reshape(8, 16)

    def f(a):
        return comms.reduce_scatter(a, ("hosts", "rows"), dim=1)

    g = mesh_lib.shard_map(f, mesh=mesh, in_specs=P(("hosts", "rows")),
                           out_specs=P(None, ("hosts", "rows")))
    out = np.asarray(g(x)).reshape(-1)
    np.testing.assert_allclose(out, x.sum(axis=0), rtol=1e-6)


def test_reduce_scatter_requires_alignment():
    import jax

    from ddt_tpu.parallel import mesh as mesh_lib

    P = jax.sharding.PartitionSpec
    mesh = jax.make_mesh((8,), ("rows",))

    def f(a):
        return comms.reduce_scatter(a, "rows", dim=1)

    g = mesh_lib.shard_map(f, mesh=mesh, in_specs=P("rows"),
                           out_specs=P(None, "rows"))
    with pytest.raises(ValueError, match="multiple"):
        g(np.zeros((8, 12), np.float32))          # 12 % 8 != 0


def test_int32_fixed_merge_is_order_independent():
    """The int32_fixed selling point: quantized partials sum in INTEGER
    arithmetic, so any reduction order produces bitwise-identical merged
    histograms (f32 psum order was the old nondeterminism seam). Host
    twin of comms.hist_reduce's quantize -> int-sum -> dequantize."""
    rng = np.random.default_rng(0)
    P = 8
    parts = rng.standard_normal((P, 4, 5, 16, 2)).astype(np.float32)
    m = np.abs(parts).max()
    cap = ((1 << 30) - 1) // P
    q = np.round(parts / (m / cap)).astype(np.int64)
    orders = [np.arange(P), np.arange(P)[::-1],
              rng.permutation(P), rng.permutation(P)]
    sums = [q[o].cumsum(axis=0)[-1] for o in orders]
    for s in sums[1:]:
        np.testing.assert_array_equal(sums[0], s)   # bitwise


@pytest.mark.parametrize("dtype", ["bf16", "int32_fixed"])
def test_hist_reduce_holds_computed_error_bound(dtype):
    """Merged histograms under a compressed wire dtype sit within
    comms.comms_error_bound of the exact f32 merge."""
    import jax

    from ddt_tpu.parallel import mesh as mesh_lib

    P = jax.sharding.PartitionSpec
    n_dev = 8
    mesh = jax.make_mesh((n_dev,), ("rows",))
    rng = np.random.default_rng(3)
    parts = rng.standard_normal((n_dev, 2, 6, 16, 2)).astype(np.float32)

    def f(a):
        return comms.hist_reduce(a[0], "rows", comms_dtype=dtype)

    g = mesh_lib.shard_map(f, mesh=mesh, in_specs=P("rows"),
                           out_specs=P())
    got = np.asarray(g(parts))
    exact = parts.astype(np.float64).sum(axis=0)
    bound = comms.comms_error_bound(dtype, n_dev, float(np.abs(parts).max()))
    assert bound > 0
    assert float(np.abs(got - exact).max()) <= bound


def test_comms_error_bound_f32_is_zero():
    assert comms.comms_error_bound("f32", 8, 123.0) == 0.0
    with pytest.raises(ValueError):
        comms.comms_error_bound("fp8", 8, 1.0)


def test_combine_shard_winners_global_tiebreak():
    """Cross-shard combine reproduces the single-device argmax exactly:
    max gain, ties broken by the smallest GLOBAL flattened candidate
    index — including the missing-bin rule that the RIGHT-direction
    block precedes the LEFT block regardless of shard."""
    import jax
    import jax.numpy as jnp

    from ddt_tpu.parallel import mesh as mesh_lib

    P = jax.sharding.PartitionSpec
    mesh = jax.make_mesh((2,), ("rows",))
    # Shard 0 proposes feature 0 with dl=True; shard 1 proposes feature
    # 5 with dl=False — equal gains. Global flattened order puts the
    # RIGHT (dl=False) block first, so shard 1 must win under
    # missing_bin even though shard 0 comes first.
    gains = np.array([[2.0], [2.0]], np.float32)
    feats = np.array([[0], [5]], np.int32)
    bins_ = np.array([[3], [1]], np.int32)
    dls = np.array([[True], [False]])

    def f(g, ft, b, d):
        return comms.combine_shard_winners(
            g[0], ft[0], b[0], d[0], "rows",
            n_features=8, n_bins=16, missing_bin=True)

    g = mesh_lib.shard_map(
        f, mesh=mesh, in_specs=(P("rows"),) * 4,
        out_specs=(P(), P(), P(), P()))
    ga, fa, ba, da = (np.asarray(x) for x in g(
        jnp.asarray(gains), jnp.asarray(feats), jnp.asarray(bins_),
        jnp.asarray(dls)))
    assert fa[0] == 5 and ba[0] == 1 and not da[0]
    # Same-direction tie: smallest feature wins regardless of shard.
    dls2 = np.array([[False], [False]])
    ga, fa, ba, da = (np.asarray(x) for x in g(
        jnp.asarray(gains), jnp.asarray(feats), jnp.asarray(bins_),
        jnp.asarray(dls2)))
    assert fa[0] == 0 and ba[0] == 3


def test_resolve_split_comms():
    assert comms.resolve_split_comms(
        "auto", distributed=True) == "reduce_scatter"
    assert comms.resolve_split_comms(
        "auto", distributed=False) == "allreduce"
    # ISSUE 11: reduce-scatter COMPOSES with a sharded feature axis on
    # the 2D mesh — the old refusal is gone; the resolver keys on
    # whether a ROW wire exists.
    assert comms.resolve_split_comms(
        "auto", distributed=True, feature_partitions=2,
        row_shards=4) == "reduce_scatter"
    assert comms.resolve_split_comms(
        "reduce_scatter", distributed=True, feature_partitions=2,
        row_shards=4) == "reduce_scatter"
    # A pure feature mesh (Pr=1) has no row wire: nothing to scatter.
    assert comms.resolve_split_comms(
        "auto", distributed=True, feature_partitions=4,
        row_shards=1) == "allreduce"
    assert comms.resolve_split_comms(
        "reduce_scatter", distributed=True,
        row_shards=1) == "allreduce"
    assert comms.resolve_split_comms(
        "reduce_scatter", distributed=False) == "allreduce"
    with pytest.raises(ValueError, match="split_comms"):
        comms.resolve_split_comms("ring", distributed=True)


def test_config_validates_comms_fields():
    with pytest.raises(ValueError, match="split_comms"):
        TrainConfig(split_comms="ring")
    with pytest.raises(ValueError, match="hist_comms_dtype"):
        TrainConfig(hist_comms_dtype="fp8")
    with pytest.raises(ValueError, match="hist_comms_slabs"):
        TrainConfig(hist_comms_slabs=-1)


# --------------------------------------------------------------------- #
# bit-identity + parity (the acceptance contracts)
# --------------------------------------------------------------------- #

@pytest.mark.parametrize("n_partitions", [2, 8])
def test_reduce_scatter_partitioned_equals_single(n_partitions,
                                                  binary_data):
    Xb, y = binary_data
    e1, _ = _fit(Xb, y)
    eN, be = _fit(Xb, y, n_partitions=n_partitions,
                  split_comms="reduce_scatter")
    assert be.split_comms == "reduce_scatter"
    _assert_same_trees(e1, eN)


def test_auto_resolves_reduce_scatter_on_mesh(binary_data):
    Xb, y = binary_data
    e1, _ = _fit(Xb, y)
    eA, be = _fit(Xb, y, n_partitions=8)            # default split_comms
    assert be.split_comms == "reduce_scatter"
    _assert_same_trees(e1, eA)


def test_reduce_scatter_pod_mesh_ragged_features():
    """(hosts, rows) tuple axes + F=9 over 8 row shards: the scatter
    pads F to 16, the pad columns are masked out of gain, and the
    combine maps slab winners back to global ids."""
    X, y = datasets.synthetic_binary(4001, n_features=9, seed=5)
    Xb, _ = quantize(X, n_bins=31, seed=5)
    e1, _ = _fit(Xb, y)
    eP, be = _fit(Xb, y, host_partitions=2, n_partitions=4,
                  split_comms="reduce_scatter")
    assert be.split_comms == "reduce_scatter"
    _assert_same_trees(e1, eP)
    assert e1.feature.max() < 9


@pytest.mark.parametrize("case", ["softmax", "missing"])
def test_reduce_scatter_parity_vs_allreduce(case):
    kw = {}
    if case == "softmax":
        X, y = datasets.synthetic_multiclass(2000, n_features=12, seed=3)
        kw = dict(loss="softmax", n_classes=3)
    else:
        X, y = datasets.synthetic_binary(3000, n_features=7, seed=9)
        X = X.copy()
        X[::11, 2] = np.nan
        kw = dict(missing_policy="learn")
    Xb, _ = quantize(X, n_bins=31, seed=3,
                     missing_policy=("learn" if case == "missing"
                                     else "zero"))
    ar, _ = _fit(Xb, y, n_partitions=8, split_comms="allreduce", **kw)
    rs, _ = _fit(Xb, y, n_partitions=8, split_comms="reduce_scatter", **kw)
    _assert_same_structure(ar, rs)
    np.testing.assert_allclose(ar.leaf_value, rs.leaf_value,
                               rtol=2e-4, atol=2e-5)


def test_reduce_scatter_streaming_matches_in_memory(binary_data):
    """The streamed device loop under an rs mesh grows the in-memory
    trainer's exact trees (the streamed==in-memory contract, extended
    to the scattered collective)."""
    from ddt_tpu.streaming import fit_streaming

    Xb, y = binary_data

    def chunk_fn(c):
        s = slice(c * 1024, (c + 1) * 1024)
        return Xb[s], y[s]

    e_mem, _ = _fit(Xb, y)
    cfg = TrainConfig(n_trees=3, max_depth=4, n_bins=31, backend="tpu",
                      n_partitions=4, split_comms="reduce_scatter")
    e_str = fit_streaming(chunk_fn, 4, cfg)
    _assert_same_structure(e_mem, e_str)


@pytest.mark.parametrize("dtype", ["bf16", "int32_fixed"])
def test_compressed_wire_split_agreement(dtype, binary_data):
    """Split-agreement contract: on well-separated data (gains far above
    the computed wire-error bound) the compressed merge picks identical
    splits in BOTH collective modes."""
    Xb, y = binary_data
    e1, _ = _fit(Xb, y)
    for mode in ("allreduce", "reduce_scatter"):
        eC, _ = _fit(Xb, y, n_partitions=8, split_comms=mode,
                     hist_comms_dtype=dtype)
        _assert_same_structure(e1, eC)


def test_slab_pipelined_overlap_is_bitwise(binary_data):
    """Overlap phasing must be invisible: f32/bf16 collectives are
    elementwise, so slabs=3 and slabs=1 produce BIT-identical models
    (leaf values included — stronger than the cross-partition
    contract)."""
    Xb, y = binary_data
    for mode, dtype in (("allreduce", "f32"), ("reduce_scatter", "f32"),
                        ("reduce_scatter", "bf16")):
        eA, _ = _fit(Xb, y, n_partitions=8, split_comms=mode,
                     hist_comms_dtype=dtype, hist_comms_slabs=1)
        eB, _ = _fit(Xb, y, n_partitions=8, split_comms=mode,
                     hist_comms_dtype=dtype, hist_comms_slabs=3)
        _assert_same_structure(eA, eB)
        np.testing.assert_array_equal(eA.leaf_value, eB.leaf_value)


def test_slab_pipelined_int32_fixed_split_agreement(binary_data):
    """int32_fixed derives its fixed-point scale PER collective, so
    slab phasing changes the quantization grid (documented carve-out —
    parallel/comms.hist_reduce): not bitwise vs slabs=1, but the grids
    stay inside the error bound and split agreement holds on
    well-separated data."""
    Xb, y = binary_data
    eA, _ = _fit(Xb, y, n_partitions=8, hist_comms_dtype="int32_fixed",
                 hist_comms_slabs=1)
    eB, _ = _fit(Xb, y, n_partitions=8, hist_comms_dtype="int32_fixed",
                 hist_comms_slabs=3)
    _assert_same_structure(eA, eB)
    np.testing.assert_allclose(eA.leaf_value, eB.leaf_value,
                               rtol=2e-4, atol=2e-5)


def test_resolve_comms_slabs():
    assert comms.resolve_comms_slabs(0, distributed=False) == 1
    assert comms.resolve_comms_slabs(
        0, distributed=True, platform="cpu") == 1
    assert comms.resolve_comms_slabs(
        0, distributed=True, platform="tpu") == comms._AUTO_SLABS
    assert comms.resolve_comms_slabs(5, distributed=False) == 5
    with pytest.raises(ValueError):
        comms.resolve_comms_slabs(-2, distributed=True)


# --------------------------------------------------------------------- #
# streamed sibling subtraction (the PR 6 leftover)
# --------------------------------------------------------------------- #

def test_streamed_subtraction_matches_in_memory(binary_data):
    """Both streaming loops with hist_subtraction=on grow the in-memory
    subtraction trainer's trees — half the streamed histogram payload
    per level >= 1 (left children only; right assembled on host)."""
    from ddt_tpu.streaming import fit_streaming

    Xb, y = binary_data

    def chunk_fn(c):
        s = slice(c * 1024, (c + 1) * 1024)
        return Xb[s], y[s]

    e_mem, _ = _fit(Xb, y, hist_subtraction="on")
    e_plain, _ = _fit(Xb, y)
    _assert_same_structure(e_mem, e_plain)   # the trick changes nothing
    cfg = TrainConfig(n_trees=3, max_depth=4, n_bins=31,
                      hist_subtraction="on")
    for backend in ("tpu", "cpu"):           # device + host loops
        e_str = fit_streaming(chunk_fn, 4, cfg.replace(backend=backend))
        _assert_same_structure(e_mem, e_str)


def test_streamed_subtraction_on_mesh(binary_data):
    from ddt_tpu.streaming import fit_streaming

    Xb, y = binary_data

    def chunk_fn(c):
        s = slice(c * 1024, (c + 1) * 1024)
        return Xb[s], y[s]

    e_mem, _ = _fit(Xb, y, hist_subtraction="on")
    cfg = TrainConfig(n_trees=3, max_depth=4, n_bins=31, backend="tpu",
                      n_partitions=4, hist_subtraction="on")
    e_str = fit_streaming(chunk_fn, 4, cfg)
    _assert_same_structure(e_mem, e_str)


# --------------------------------------------------------------------- #
# the corrected payload counter (acceptance witness)
# --------------------------------------------------------------------- #

def test_hist_allreduce_bytes_back_compat():
    """Positional-only calls return the historical estimate exactly."""
    assert tele_counters.hist_allreduce_bytes(2, 3, 4) \
        == (1 + 2) * 3 * 4 * 8 + 4 * 8


def test_hist_allreduce_bytes_effective_model():
    base = tele_counters.hist_allreduce_bytes(4, 8, 16)
    # Subtraction halves levels >= 1 (histogram part only).
    sub = tele_counters.hist_allreduce_bytes(4, 8, 16, subtraction=True)
    leaf = (1 << 4) * 8
    hist_base = base - leaf
    expected_sub = sum(
        ((1 << d) if d == 0 else (1 << d) // 2) * 8 * 16 * 8
        for d in range(4))
    assert sub == expected_sub + leaf
    assert sub < hist_base  # strictly less traffic
    # bf16 halves the histogram bytes.
    bf = tele_counters.hist_allreduce_bytes(4, 8, 16, comms_dtype="bf16")
    assert bf == (hist_base // 2) + leaf
    # reduce_scatter over 8 shards: per-device slab + winner tuples.
    rs = tele_counters.hist_allreduce_bytes(4, 8, 16, partitions=8,
                                            mode="reduce_scatter")
    assert rs < base
    assert base / rs >= 2.0


def test_collective_counter_witnesses_2x_reduction(binary_data):
    """The acceptance criterion, witnessed in-process: a multi-device
    training run under reduce_scatter records <= half the allreduce
    mode's collective bytes through the CORRECTED counter."""
    Xb, y = binary_data
    deltas = {}
    for mode in ("allreduce", "reduce_scatter"):
        s0 = tele_counters.snapshot()
        _, be = _fit(Xb, y, n_partitions=8, split_comms=mode)
        deltas[mode] = tele_counters.delta(s0)["collective_bytes_est"]
        assert deltas[mode] == 3 * be.collective_bytes_per_tree(10)
    assert deltas["allreduce"] / deltas["reduce_scatter"] >= 2.0


def test_partition_phases_carry_effective_bytes(binary_data, tmp_path):
    """Mesh runs' partition_phases events carry the EFFECTIVE (mode-
    aware) payload estimate, and the manifest carries the resolved comms
    extras the report's comms line renders."""
    import json

    from ddt_tpu.telemetry.report import read_events, render, summarize

    Xb, y = binary_data
    log = tmp_path / "run.jsonl"
    cfg = TrainConfig(n_trees=2, max_depth=3, n_bins=31, backend="tpu",
                      n_partitions=8)
    be = get_backend(cfg)
    Driver(be, cfg, log_every=10**9, run_log=str(log)).fit(Xb, y)
    events = read_events(str(log))
    man = next(e for e in events if e["event"] == "run_manifest")
    assert man["split_comms"] == "reduce_scatter"
    assert man["hist_comms_dtype"] == "f32"
    parts = [e for e in events if e["event"] == "partition_phases"]
    assert parts
    per_tree = be.collective_bytes_per_tree(10)
    for p in parts:
        for lane in p["partitions"]:
            assert lane["hist_allreduce_bytes"] \
                == per_tree * p.get("rounds", 1)
    s = summarize(events)
    assert s["comms"]["split_comms"] == "reduce_scatter"
    text = render(s)
    assert "split_comms=reduce_scatter" in text
    json.dumps(s)                                  # JSON-clean


def test_roofline_comms_row():
    """roofline_table renders a comms row from the effective collective
    bytes: verdict 'comms' when the wire utilization rivals the carrying
    phase's HBM leg, 'overlapped' when hidden."""
    from ddt_tpu.telemetry.costmodel import roofline_table

    phases = [{"phase": "hist", "ms_total": 1000.0, "ms_per_call": 10.0,
               "calls": 100, "share": 1.0}]
    cost = [{"op": "hist", "phase": "hist", "flops": 1e9,
             "bytes_accessed": 1e6, "calls": 100, "platform": "cpu"}]
    hot = roofline_table(phases, cost,
                         counters={"collective_bytes_est": int(20e9)},
                         wallclock_s=1.0)
    row = next(r for r in hot if r["phase"] == "comms")
    assert row["verdict"] == "comms"
    assert row["coll_util"] > 0
    cold = roofline_table(phases, cost,
                          counters={"collective_bytes_est": 10_000},
                          wallclock_s=1.0)
    row = next(r for r in cold if r["phase"] == "comms")
    assert row["verdict"] == "overlapped"
    none = roofline_table(phases, cost, counters={}, wallclock_s=1.0)
    assert all(r["phase"] != "comms" for r in none)


def test_bench_hist_comms_ab_smoke():
    """The paired A/B arm runs on the CPU multi-device pod mesh (tier-1
    twin of the chip-gated bench arm) and stamps the deterministic
    payload ratio."""
    from ddt_tpu.bench import bench_hist_comms_ab

    out = bench_hist_comms_ab(rows=20_000, features=12, bins=31,
                              depth=3, iters=1, reps=2)
    assert out["kernel"] == "hist_comms_ab"
    assert out["payload_ratio"] >= 2.0
    assert out["mrows_rs"] > 0 and out["mrows_allreduce"] > 0
    assert out["ratio_allreduce_over_rs"] > 0