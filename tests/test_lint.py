"""ddtlint: repo-wide gate + per-checker fixture tests (tier-1,
marker-free so `pytest -m 'not slow'` always runs it).

Two layers, deliberately independent:
* fixture tests — each rule against minimal positive/negative snippets
  (tests/lint_fixtures/), so a checker that goes blind or noisy fails
  even while the repo gate stays green;
* the gate — the real tree against the ratchet baseline
  (tools/ddtlint/baseline.json): any NEW finding fails, and any STALE
  baseline entry fails too (fixed findings must be ratcheted out, the
  baseline only ever shrinks).
"""

import json
import os
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from tools.ddtlint import callgraph, checkers, runner, shardspec  # noqa: E402
from tools.ddtlint import configflow, telemetrycontract  # noqa: E402
from tools.ddtlint import threadmodel  # noqa: E402
from tools.ddtlint import tsan_audit  # noqa: E402
from tools.ddtlint.findings import assign_fingerprints  # noqa: E402

FIXTURES = os.path.join(REPO, "tests", "lint_fixtures")
GATE_PATHS = ["ddt_tpu/", "tests/"]


def _fixture_src(fname: str) -> str:
    with open(os.path.join(FIXTURES, fname), encoding="utf-8") as f:
        return f.read()


def _marker_lines(src: str, rule: str) -> set:
    return {i for i, line in enumerate(src.splitlines(), start=1)
            if f"# LINT: {rule}" in line}


def _lint_src(synthetic_path: str, src: str, rule: str):
    return runner.run_on_source(
        synthetic_path, src, mesh_axes=runner.mesh_axis_names(REPO),
        layout_rules=runner.layout_rule_patterns(REPO), rules={rule})


def _flagged_lines(fname: str, synthetic_path: str, rule: str) -> set:
    findings = _lint_src(synthetic_path, _fixture_src(fname), rule)
    assert all(f.rule == rule for f in findings), findings
    return {f.line for f in findings}


# (rule, positive fixture, negative fixture, synthetic path for scoping)
CASES = [
    ("traced-branch", "traced_branch_pos.py", "traced_branch_neg.py",
     "ddt_tpu/ops/fixture_mod.py"),
    ("host-sync", "host_sync_pos.py", "host_sync_neg.py",
     "ddt_tpu/ops/grow.py"),
    ("dtype-drift", "dtype_drift_pos.py", "dtype_drift_neg.py",
     "ddt_tpu/ops/fixture_mod.py"),
    ("collective-consistency", "collective_pos.py", "collective_neg.py",
     "ddt_tpu/ops/fixture_mod.py"),
    ("broad-except", "broad_except_pos.py", "broad_except_neg.py",
     "ddt_tpu/fixture_mod.py"),
    ("no-print", "no_print_pos.py", "no_print_neg.py",
     "ddt_tpu/fixture_mod.py"),
    ("pallas-interpret", "pallas_interpret_pos.py",
     "pallas_interpret_neg.py", "ddt_tpu/ops/fixture_mod.py"),
    ("pallas-vmem-guard", "pallas_vmem_pos.py",
     "pallas_vmem_neg.py", "ddt_tpu/ops/fixture_mod.py"),
    ("named-scope", "named_scope_pos.py", "named_scope_neg.py",
     "ddt_tpu/ops/fixture_mod.py"),
    ("atomic-artifact-write", "atomic_write_pos.py", "atomic_write_neg.py",
     "ddt_tpu/models/fixture_mod.py"),
    ("raw-phase-timing", "raw_timing_pos.py", "raw_timing_neg.py",
     "ddt_tpu/ops/fixture_mod.py"),
    ("serve-blocking-io", "serve_blocking_pos.py", "serve_blocking_neg.py",
     "ddt_tpu/serve/engine.py"),
    ("one-home-collective", "one_home_collective_pos.py",
     "one_home_collective_neg.py", "ddt_tpu/ops/fixture_mod.py"),
    # ddtlint v2 (ISSUE 13): the serve-tier thread/lock pass...
    ("lock-order", "lock_order_pos.py", "lock_order_neg.py",
     "ddt_tpu/serve/batcher.py"),
    ("cross-role-state", "cross_role_pos.py", "cross_role_neg.py",
     "ddt_tpu/serve/engine.py"),
    ("blocking-under-lock", "blocking_under_lock_pos.py",
     "blocking_under_lock_neg.py", "ddt_tpu/serve/batcher.py"),
    ("lock-release", "lock_release_pos.py", "lock_release_neg.py",
     "ddt_tpu/serve/batcher.py"),
    # ...and the mechanized sharding-spec contract.
    ("handbuilt-partition-spec", "handbuilt_spec_pos.py",
     "handbuilt_spec_neg.py", "ddt_tpu/backends/fixture_mod.py"),
    ("axis-name-literal", "axis_literal_pos.py", "axis_literal_neg.py",
     "ddt_tpu/ops/fixture_mod.py"),
    ("layout-rule-coverage", "layout_coverage_pos.py",
     "layout_coverage_neg.py", "ddt_tpu/backends/fixture_mod.py"),
    # ddtlint v3 (ISSUE 16): the config-flow contract pass (fixtures
    # embed their own mini-contract anchors so the single-file model
    # resolves)...
    ("jit-cache-key-coverage", "cache_key_pos.py", "cache_key_neg.py",
     "ddt_tpu/backends/fixture_mod.py"),
    ("fingerprint-field-coverage", "fingerprint_pos.py",
     "fingerprint_neg.py", "ddt_tpu/utils/fixture_mod.py"),
    ("config-field-orphan", "config_orphan_pos.py", "config_orphan_neg.py",
     "ddt_tpu/fixture_mod.py"),
    # ...and the mechanized telemetry-schema contract.
    ("undeclared-event-kind", "event_kind_pos.py", "event_kind_neg.py",
     "ddt_tpu/telemetry/fixture_mod.py"),
    ("undeclared-event-extra", "event_extra_pos.py", "event_extra_neg.py",
     "ddt_tpu/telemetry/fixture_mod.py"),
    ("counter-direction-missing", "counter_direction_pos.py",
     "counter_direction_neg.py", "ddt_tpu/telemetry/fixture_mod.py"),
    ("event-schema-additivity", "schema_additivity_pos.py",
     "schema_additivity_neg.py", "ddt_tpu/telemetry/fixture_mod.py"),
]


@pytest.mark.parametrize("rule,pos,_neg,path",
                         CASES, ids=[c[0] for c in CASES])
def test_checker_fires_on_seeded_violations(rule, pos, _neg, path):
    src = _fixture_src(pos)
    want = _marker_lines(src, rule)
    assert want, f"fixture {pos} has no LINT markers for {rule}"
    got = _flagged_lines(pos, path, rule)
    assert got == want, (
        f"{rule}: flagged lines {sorted(got)} != expected markers "
        f"{sorted(want)} in {pos}")


@pytest.mark.parametrize("rule,_pos,neg,path",
                         CASES, ids=[c[0] for c in CASES])
def test_checker_silent_on_clean_code(rule, _pos, neg, path):
    got = _flagged_lines(neg, path, rule)
    assert got == set(), f"{rule}: false positives at lines {sorted(got)} " \
                         f"in {neg}"


def test_one_home_collective_exempts_comms_module():
    """parallel/comms.py IS the one home: the same raw-collective source
    must not be flagged there (or outside ddt_tpu/ — tools and tests
    spell collectives freely)."""
    src = _fixture_src("one_home_collective_pos.py")
    for path in ("ddt_tpu/parallel/comms.py", "tests/test_comms.py",
                 "tools/ddtlint/fixture_mod.py"):
        findings = runner.run_on_source(path, src,
                                        rules={"one-home-collective"})
        assert findings == [], (path, [f.render() for f in findings])


def test_serve_blocking_io_exempts_transport_and_other_layers():
    """The rule is scoped to the serving HOT-LOOP modules only: the
    same blocking source must not be flagged in the HTTP transport
    layer (its blocking is the caller's thread), the cli, or non-serve
    library code (which other rules govern)."""
    src = _fixture_src("serve_blocking_pos.py")
    for path in ("ddt_tpu/serve/http.py", "ddt_tpu/cli.py",
                 "ddt_tpu/streaming.py", "scripts/serve_smoke.py"):
        findings = runner.run_on_source(path, src,
                                        rules={"serve-blocking-io"})
        assert findings == [], (path, [f.render() for f in findings])


def test_atomic_artifact_write_covers_registry():
    """ISSUE 9: the registry is an artifact-owning module — the SAME
    violating source that fires under models/ must fire under
    ddt_tpu/registry/, while the export staging layer stays exempt
    (its writes land in a staging dir published by one atomic dir
    rename — see the checker doc)."""
    src = _fixture_src("atomic_write_pos.py")
    want = _marker_lines(src, "atomic-artifact-write")
    got = _flagged_lines("atomic_write_pos.py",
                         "ddt_tpu/registry/store.py",
                         "atomic-artifact-write")
    assert got == want, (sorted(got), sorted(want))
    for exempt in ("ddt_tpu/export/aot.py", "scripts/registry_smoke.py"):
        findings = runner.run_on_source(
            exempt, src, rules={"atomic-artifact-write"})
        assert findings == [], (exempt,
                                [f.render() for f in findings])


def test_no_print_exempts_cli_and_non_library_paths():
    """The rule is scoped to LIBRARY code: the same print-bearing source
    must not be flagged when it lives in the CLI (stdout is its
    interface) or outside ddt_tpu/ (tools, tests)."""
    src = _fixture_src("no_print_pos.py")
    for path in ("ddt_tpu/cli.py", "tools/ddtlint/__main__.py",
                 "tests/test_cli.py", "scripts/telemetry_smoke.py"):
        findings = runner.run_on_source(path, src, rules={"no-print"})
        assert findings == [], (path, [f.render() for f in findings])


def test_suppression_hygiene_fires():
    src = _fixture_src("suppressions_pos.supp")
    findings = checkers.check_suppressions("ddt_tpu/native/fix.supp", src)
    assert {f.line_text for f in findings} == {
        "race:_contig_to_contig", "race:array_dealloc"}


def test_suppression_hygiene_silent_with_audit_tag():
    src = _fixture_src("suppressions_neg.supp")
    assert checkers.check_suppressions("ddt_tpu/native/fix.supp", src) == []


def test_repo_tsan_supp_passes_hygiene():
    with open(os.path.join(REPO, "ddt_tpu/native/tsan.supp"),
              encoding="utf-8") as f:
        src = f.read()
    findings = checkers.check_suppressions("ddt_tpu/native/tsan.supp", src)
    assert findings == [], [f.render() for f in findings]


# --------------------------------------------------------------------- #
# threadmodel pass: the real serve tier + mutation-style hazard seeding
# --------------------------------------------------------------------- #
def _read_repo(rel: str) -> str:
    with open(os.path.join(REPO, rel), encoding="utf-8") as f:
        return f.read()


def _mut_lines(src: str, marker: str) -> set:
    return {i for i, line in enumerate(src.splitlines(), start=1)
            if marker in line}


def test_thread_model_real_serve_tier():
    """The analyzer's model of the ACTUAL serve tier: the injected
    dispatch callable gives ServeEngine._dispatch both roles, the swap
    publish is the one declared atomic-publish attr, and the clean tree
    carries zero thread findings."""
    import ast as ast_mod

    trees, sources = {}, {}
    for rel in ("ddt_tpu/serve/batcher.py", "ddt_tpu/serve/engine.py",
                "ddt_tpu/serve/fleet.py", "ddt_tpu/serve/control.py",
                "ddt_tpu/serve/http.py", "ddt_tpu/robustness/watchdog.py"):
        sources[rel] = _read_repo(rel)
        trees[rel] = ast_mod.parse(sources[rel])
    m = threadmodel.build(trees, sources)
    assert m.findings == [], [f.render() for f in m.findings]
    disp = m.methods[("ddt_tpu/serve/engine.py", "ServeEngine",
                      "_dispatch")]
    assert disp.roles == {"dispatcher", "handler"}
    loop = m.methods[("ddt_tpu/serve/batcher.py", "MicroBatcher", "_loop")]
    assert loop.roles == {"dispatcher"}
    assert ("ServeEngine", "_model") in m.published
    assert ("MicroBatcher", "_closed") in m.guarded
    # the fleet tier (ISSUE 15): its single dispatcher thread is a
    # thread root, the shared per-batch body carries both roles, and
    # the fleet's cross-role state is Condition-guarded throughout
    fl = m.methods[("ddt_tpu/serve/fleet.py", "FleetEngine", "_loop")]
    assert fl.roles == {"dispatcher"}
    shared = m.methods[("ddt_tpu/serve/engine.py", "", "dispatch_batch")]
    assert shared.roles == {"dispatcher", "handler"}
    assert m.guarded[("FleetEngine", "_closed")] == "_cv"
    assert m.guarded[("FleetEngine", "_rr")] == "_cv"
    # watchdog: single-role, no locks — nothing inferred, nothing flagged
    assert not any(c.locks for c in m.classes.values()
                   if c.path.endswith("watchdog.py"))


#: (rule, mutation applied to a copy of serve/batcher.py, marker)
_BATCHER_MUTATIONS = [
    ("lock-order", (
        "\n"
        "    def _mut_path_a(self):\n"
        "        with self._cv:\n"
        "            with self._gate:  # MUT-HAZARD\n"
        "                pass\n"
        "\n"
        "    def _mut_path_b(self):\n"
        "        with self._gate:\n"
        "            with self._cv:  # MUT-HAZARD\n"
        "                pass\n")),
    ("cross-role-state", (
        "\n"
        "    def retune(self, ms):\n"
        "        self.max_wait_s = ms / 1e3  # MUT-HAZARD\n")),
    ("lock-release", (
        "\n"
        "    def grab_unsafe(self):\n"
        "        self._gate.acquire()  # MUT-HAZARD\n"
        "        self._q.clear()\n"
        "        self._gate.release()\n")),
]


@pytest.mark.parametrize("rule,appendix", _BATCHER_MUTATIONS,
                         ids=[m[0] for m in _BATCHER_MUTATIONS])
def test_mutated_batcher_hazards_detected(rule, appendix):
    """Mutation-style acceptance (ISSUE 13): inject each thread hazard
    into a COPY of the real serve/batcher.py and assert the exact rule
    fires at the exact injected location — proving the pass catches the
    hazard in production-shaped code, not just minimal fixtures."""
    src = _read_repo("ddt_tpu/serve/batcher.py") + appendix
    want = _mut_lines(src, "# MUT-HAZARD")
    assert want
    findings = _lint_src("ddt_tpu/serve/batcher.py", src, rule)
    got = {f.line for f in findings}
    assert got == want, (rule, sorted(got), sorted(want),
                         [f.render() for f in findings])


def test_mutated_batcher_blocking_under_gate():
    """Blocking call injected INSIDE the dispatch gate of the real
    batcher loop — the lock-scope upgrade of serve-blocking-io."""
    src = _read_repo("ddt_tpu/serve/batcher.py")
    target = ("                with self._gate:\n"
              "                    self._dispatch(batch, depth)")
    assert target in src
    src = src.replace(target, (
        "                with self._gate:\n"
        "                    time.sleep(0.001)  # MUT-HAZARD\n"
        "                    self._dispatch(batch, depth)"))
    want = _mut_lines(src, "# MUT-HAZARD")
    findings = _lint_src("ddt_tpu/serve/batcher.py", src,
                         "blocking-under-lock")
    assert {f.line for f in findings} == want, \
        [f.render() for f in findings]


#: (rule, mutation appended to a copy of backends/tpu.py)
_TPU_MUTATIONS = [
    ("handbuilt-partition-spec", (
        "\n\n"
        "def _mut_handbuilt(mesh):\n"
        "    return jax.sharding.NamedSharding(\n"
        "        mesh, jax.sharding.PartitionSpec(None))  # MUT-HAZARD\n")),
    ("axis-name-literal", (
        "\n\n"
        'MUT_ROW_AXIS = "rows"  # MUT-HAZARD\n')),
    ("layout-rule-coverage", (
        "\n\n"
        "def _mut_coverage(lay):\n"
        '    return lay.spec("operand_no_rule_matches")  # MUT-HAZARD\n')),
]


@pytest.mark.parametrize("rule,appendix", _TPU_MUTATIONS,
                         ids=[m[0] for m in _TPU_MUTATIONS])
def test_mutated_backend_hazards_detected(rule, appendix):
    """Same mutation-style acceptance for the sharding-spec contract:
    each hazard seeded into a copy of the real backends/tpu.py fires
    the expected rule at the injected line (and ONLY there — the rest
    of the backend is clean under the new rules)."""
    src = _read_repo("ddt_tpu/backends/tpu.py") + appendix
    want = _mut_lines(src, "# MUT-HAZARD")
    assert want
    findings = _lint_src("ddt_tpu/backends/tpu.py", src, rule)
    got = {f.line for f in findings}
    assert got == want, (rule, sorted(got), sorted(want),
                         [f.render() for f in findings])


def test_branch_release_does_not_clear_fallthrough_hold():
    """A release() on ONE branch (early-return fast path) must not mark
    the lock free for the fall-through (review finding): the
    over-holding bias means branchy releases can only ADD findings,
    never hide one."""
    src = ("import threading\n"
           "import time\n\n\n"
           "class C:\n"
           "    def __init__(self):\n"
           "        self._lk = threading.Lock()\n\n"
           "    def f(self, fast):\n"
           "        self._lk.acquire()\n"
           "        if fast:\n"
           "            self._lk.release()\n"
           "            return None\n"
           "        time.sleep(1.0)\n"
           "        self._lk.release()\n"
           "        return 1\n")
    fs = _lint_src("ddt_tpu/serve/engine.py", src, "blocking-under-lock")
    assert [f.line for f in fs] == [14], [f.render() for f in fs]
    # ...and a straight-line release DOES clear the hold.
    linear = src.replace(
        "        if fast:\n"
        "            self._lk.release()\n"
        "            return None\n"
        "        time.sleep(1.0)\n",
        "        self._lk.release()\n"
        "        time.sleep(1.0)\n")
    assert _lint_src("ddt_tpu/serve/engine.py", linear,
                     "blocking-under-lock") == []


def test_stale_atomic_publish_annotation_flagged():
    """The annotation grammar's staleness half: `# ddtlint:
    atomic-publish` on a line that stores nothing is a finding (under
    suppression-hygiene — an annotation IS a suppression), while a
    real attribute store keeps it legal."""
    stale = ("class E:\n"
             "    def f(self):\n"
             "        x = 1  # ddtlint: atomic-publish\n"
             "        return x\n")
    fs = _lint_src("ddt_tpu/serve/engine.py", stale,
                   "suppression-hygiene")
    assert [f.line for f in fs] == [3], [f.render() for f in fs]
    fresh = ("class E:\n"
             "    def f(self, v):\n"
             "        self.model = v  # ddtlint: atomic-publish\n")
    assert _lint_src("ddt_tpu/serve/engine.py", fresh,
                     "suppression-hygiene") == []


def test_serving_doc_thread_model_in_sync():
    """docs/SERVING.md embeds the analyzer's stable (no line numbers)
    model dump between ddtlint:thread-model markers; a serve change
    that moves the model must regenerate the doc block — that diff is
    the review artifact ISSUE 13 asks for."""
    import ast as ast_mod
    import re as re_mod

    trees, sources = {}, {}
    for rel in ("ddt_tpu/serve/__init__.py", "ddt_tpu/serve/batcher.py",
                "ddt_tpu/serve/engine.py", "ddt_tpu/serve/fleet.py",
                "ddt_tpu/serve/control.py", "ddt_tpu/serve/drift.py",
                "ddt_tpu/serve/http.py", "ddt_tpu/serve/metrics.py",
                "ddt_tpu/robustness/watchdog.py",
                "ddt_tpu/telemetry/statusd.py"):
        sources[rel] = _read_repo(rel)
        trees[rel] = ast_mod.parse(sources[rel])
    model = threadmodel.build(trees, sources)
    block = threadmodel.explain(model, details=False).strip()
    doc = _read_repo("docs/SERVING.md")
    mm = re_mod.search(
        r"<!-- ddtlint:thread-model:begin -->\s*```\n(.*?)```\s*"
        r"<!-- ddtlint:thread-model:end -->", doc, re_mod.DOTALL)
    assert mm, "SERVING.md lost its thread-model markers"
    assert mm.group(1).strip() == block, (
        "docs/SERVING.md thread-model block is out of date — "
        "regenerate with `python -m tools.ddtlint --explain-threads` "
        "(stable form: drop the [file:line] suffixes)")


def test_explain_threads_cli():
    proc = subprocess.run(
        [sys.executable, "-m", "tools.ddtlint", "--explain-threads"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr
    assert "lock-order edges:" in proc.stdout
    assert "MicroBatcher._gate" in proc.stdout


# --------------------------------------------------------------------- #
# configflow pass: the real contract tree + mutation-style acceptance
# --------------------------------------------------------------------- #
def _configflow_sources(mutate=None):
    """The real contract anchors (config.py, checkpoint.py) plus every
    TRACE_SCOPE file, parsed; `mutate` maps relpath -> callable(src) ->
    src for mutation-style tests."""
    import ast as ast_mod

    rels = ["ddt_tpu/config.py", "ddt_tpu/utils/checkpoint.py"]
    for dirpath, dirnames, fns in os.walk(os.path.join(REPO, "ddt_tpu")):
        dirnames[:] = [d for d in dirnames if d not in runner.SKIP_DIRS]
        for fn in fns:
            rel = os.path.relpath(os.path.join(dirpath, fn),
                                  REPO).replace(os.sep, "/")
            if rel.endswith(".py") and configflow.in_trace_scope(rel):
                rels.append(rel)
    trees, sources = {}, {}
    for rel in rels:
        src = _read_repo(rel)
        if mutate and rel in mutate:
            src = mutate[rel](src)
            assert src is not None
        sources[rel] = src
        trees[rel] = ast_mod.parse(src)
    return trees, sources


def test_config_model_real_tree_clean_and_resolved():
    """The analyzer's model of the ACTUAL contracts: all three anchors
    resolve, the clean tree carries zero config-flow findings, the
    cache-key trailing term is exactly `seed`, and the five
    deliberately contract-less fields are annotation-covered (their
    trace-inert annotations suppressed a would-be orphan — so they are
    `used`, not stale)."""
    trees, sources = _configflow_sources()
    m = configflow.build(trees, sources)
    assert m.resolved
    assert m.findings == [], [f"{f.rule} {f.path}:{f.line}"
                              for f in m.findings]
    assert "grad_dtype" in m.covered and "subsample" in m.covered
    assert m.cache_reads == {"seed"}
    assert m.traced_reads, "no jit-reachable cfg reads found — the " \
        "cache-key rule went blind"
    inert = {name for name, site in m.fields.items() if site in m.used}
    assert inert == {"n_trees", "mesh_shape", "fault_plan",
                     "straggler_repartition", "straggler_skew_threshold"}


def test_jit_fields_removal_replay_detected():
    """ACCEPTANCE (ISSUE 16): replay the PR 14 grad_dtype bug — delete
    `"grad_dtype"` from a copy of the real _JIT_FIELDS tuple and the
    cache-key rule must fire at real traced read sites."""
    def drop_grad_dtype(src):
        out = src.replace('\n    "grad_dtype",\n', "\n")
        assert out != src
        return out

    trees, sources = _configflow_sources(
        {"ddt_tpu/backends/__init__.py": drop_grad_dtype})
    m = configflow.build(trees, sources)
    hits = [f for f in m.findings if f.rule == "jit-cache-key-coverage"]
    assert hits, "grad_dtype removal from _JIT_FIELDS went undetected"
    assert all("grad_dtype" in f.message for f in hits)
    assert {f.path for f in hits} == {"ddt_tpu/backends/tpu.py"}


def test_mutated_config_contractless_field_detected():
    """Mutation-style acceptance: a new TrainConfig field that joins no
    contract (not in _JIT_FIELDS, popped out of the fingerprint,
    unannotated) fires config-field-orphan at the injected declaration
    in a copy of the real config.py."""
    def add_field(src):
        anchor = "    straggler_skew_threshold: float = 2.0"
        i = src.index(anchor)
        eol = src.index("\n", i)
        return (src[:eol + 1]
                + "    mut_orphan_knob: int = 0  # MUT-HAZARD\n"
                + src[eol + 1:])

    def pop_field(src):
        out = src.replace('for k in ("n_trees",',
                          'for k in ("mut_orphan_knob", "n_trees",')
        assert out != src
        return out

    trees, sources = _configflow_sources({
        "ddt_tpu/config.py": add_field,
        "ddt_tpu/utils/checkpoint.py": pop_field,
    })
    m = configflow.build(trees, sources)
    hits = [f for f in m.findings if f.rule == "config-field-orphan"]
    want = _mut_lines(sources["ddt_tpu/config.py"], "# MUT-HAZARD")
    assert {(f.path, f.line) for f in hits} == \
        {("ddt_tpu/config.py", ln) for ln in want}, \
        [f"{f.rule} {f.path}:{f.line}" for f in m.findings]


def test_mutated_checkpoint_stale_exclude_detected():
    """A fingerprint exclude entry naming no current field (the renamed-
    field hazard) fires at the injected tuple element in a copy of the
    real checkpoint.py."""
    def stale(src):
        out = src.replace(
            'for k in ("n_trees",',
            'for k in ("zz_renamed_knob",  # MUT-HAZARD\n'
            '              "n_trees",')
        assert out != src
        return out

    trees, sources = _configflow_sources(
        {"ddt_tpu/utils/checkpoint.py": stale})
    m = configflow.build(trees, sources)
    hits = [f for f in m.findings if f.rule == "fingerprint-field-coverage"]
    want = _mut_lines(sources["ddt_tpu/utils/checkpoint.py"],
                      "# MUT-HAZARD")
    assert {f.line for f in hits} == want, \
        [f"{f.rule} {f.path}:{f.line}" for f in m.findings]
    assert all(f.path == "ddt_tpu/utils/checkpoint.py" for f in hits)


def test_fingerprint_explicit_enumeration_must_be_total():
    """The non-asdict arm: a fingerprint that enumerates fields by hand
    must enumerate all of them (or exclude the rest)."""
    src = ("import dataclasses\n\n\n"
           "@dataclasses.dataclass(frozen=True)\n"
           "class TrainConfig:\n"
           "    max_depth: int = 6\n"
           "    n_bins: int = 255\n"
           "    seed: int = 0\n\n\n"
           "def _cfg_fingerprint(cfg):\n"
           "    return {'max_depth': cfg.max_depth}\n")
    fs = _lint_src("ddt_tpu/utils/fixture_mod.py", src,
                   "fingerprint-field-coverage")
    fp_line = src.splitlines().index("def _cfg_fingerprint(cfg):") + 1
    assert [f.line for f in fs] == [fp_line], [f.render() for f in fs]
    assert "n_bins" in fs[0].message and "seed" in fs[0].message


def test_trace_inert_annotation_hygiene():
    """The annotation grammar's two failure shapes: a reason-less
    `# ddtlint: trace-inert` always flags (unreviewable exemption), and
    one that suppresses nothing flags as stale once the contract model
    fully resolves — both under suppression-hygiene, like every other
    annotation."""
    import re as re_mod

    base = _fixture_src("config_orphan_neg.py")
    reasonless = re_mod.sub(r"# ddtlint: trace-inert — [^\n]*",
                            "# ddtlint: trace-inert", base, count=1)
    fs = _lint_src("ddt_tpu/fixture_mod.py", reasonless,
                   "suppression-hygiene")
    assert len(fs) == 1 and "without a" in fs[0].message, \
        [f.render() for f in fs]
    stale = base.replace(
        "    seed: int = 0",
        "    seed: int = 0  # ddtlint: trace-inert — seed already keys "
        "the cache")
    fs = _lint_src("ddt_tpu/fixture_mod.py", stale, "suppression-hygiene")
    assert len(fs) == 1 and "stale" in fs[0].message, \
        [f.render() for f in fs]


# --------------------------------------------------------------------- #
# telemetrycontract pass: the real catalogs + mutation-style acceptance
# --------------------------------------------------------------------- #
def _telemetry_trees(mutate=None):
    import ast as ast_mod

    trees = {}
    for rel in runner._walk_py(["ddt_tpu/"], REPO):
        if not (rel.endswith(".py") and telemetrycontract.in_scope(rel)):
            continue
        src = _read_repo(rel)
        if mutate and rel in mutate:
            src = mutate[rel](src)
        trees[rel] = ast_mod.parse(src)
        if mutate and rel in mutate:
            trees[rel]._mut_src = src
    return trees


def test_telemetry_model_real_tree_clean():
    """The real catalogs resolve, every emit site checks clean, the
    epilogue counters are seen, and every published counter has a valid
    direction (the converted state this PR establishes)."""
    m = telemetrycontract.build(_telemetry_trees())
    assert m.findings == [], [f"{f.rule} {f.path}:{f.line}"
                              for f in m.findings]
    assert m.schema_version == telemetrycontract.PINNED_SCHEMA_VERSION
    assert set(m.required) == set(telemetrycontract.PINNED_REQUIRED)
    assert "device_peak_bytes" in m.counter_lines
    assert "host_peak_rss_bytes" in m.counter_lines
    assert set(m.counter_lines) <= set(m.directions)
    assert all(v in telemetrycontract.VALID_DIRECTIONS
               for v in m.directions.values())


#: (rule, mutation appended to a copy of telemetry/events.py)
_EVENTS_MUTATIONS = [
    ("undeclared-event-kind", (
        "\n\n"
        "def _mut_typo_kind(log):\n"
        '    log.emit("runmanifest", trainer="x")  # MUT-HAZARD\n')),
    ("undeclared-event-extra", (
        "\n\n"
        "def _mut_undeclared_extra(log):\n"
        '    log.emit("round", round=1, ms_per_round=1.0,\n'
        "             vibes=3)  # MUT-HAZARD\n")),
]


@pytest.mark.parametrize("rule,appendix", _EVENTS_MUTATIONS,
                         ids=[m[0] for m in _EVENTS_MUTATIONS])
def test_mutated_events_hazards_detected(rule, appendix):
    """Mutation-style acceptance: each schema hazard seeded into a copy
    of the real telemetry/events.py fires the expected rule at the
    injected line (and only there — the real emit sites are clean)."""
    src = _read_repo("ddt_tpu/telemetry/events.py") + appendix
    want = _mut_lines(src, "# MUT-HAZARD")
    assert want
    findings = _lint_src("ddt_tpu/telemetry/events.py", src, rule)
    got = {f.line for f in findings}
    assert got == want, (rule, sorted(got), sorted(want),
                         [f.render() for f in findings])


def test_mutated_counter_registry_detected():
    """A counter added to the `_c` registry without declaring it on the
    `counters` event or in COUNTER_DIRECTIONS trips BOTH rules at the
    injected registry line (cross-file: catalogs live in events.py and
    diffing.py)."""
    def add_counter(src):
        out = src.replace(
            "_c = {", '_c = {\n    "mut_counter": 0,  # MUT-HAZARD', 1)
        assert out != src
        return out

    trees = _telemetry_trees(
        {"ddt_tpu/telemetry/counters.py": add_counter})
    m = telemetrycontract.build(trees)
    src = trees["ddt_tpu/telemetry/counters.py"]._mut_src
    want = _mut_lines(src, "# MUT-HAZARD")
    by_rule = {}
    for f in m.findings:
        by_rule.setdefault(f.rule, set()).add((f.path, f.line))
    expect = {("ddt_tpu/telemetry/counters.py", ln) for ln in want}
    assert by_rule.get("undeclared-event-extra") == expect, by_rule
    assert by_rule.get("counter-direction-missing") == expect, by_rule


def test_schema_version_bump_retires_additivity_pin():
    """Growing a required set IS legal once SCHEMA_VERSION moves past
    the pin (the rule skips until re-pinned in the same PR)."""
    grown = ('SCHEMA_VERSION = 6\n'
             'EVENT_FIELDS = {\n'
             '    "round": ("round", "ms_per_round", "loss_now"),\n'
             '}\n')
    fs = _lint_src("ddt_tpu/telemetry/fixture_mod.py", grown,
                   "event-schema-additivity")
    assert fs == [], [f.render() for f in fs]


def test_observability_doc_telemetry_contract_in_sync():
    """docs/OBSERVABILITY.md embeds the analyzer's derived contract
    between ddtlint:telemetry-contract markers; a telemetry change that
    moves the contract must regenerate the doc block — that diff is the
    review artifact ISSUE 16 asks for (the SERVING.md pattern)."""
    import re as re_mod

    block = telemetrycontract.explain(
        telemetrycontract.build(_telemetry_trees())).strip()
    doc = _read_repo("docs/OBSERVABILITY.md")
    mm = re_mod.search(
        r"<!-- ddtlint:telemetry-contract:begin -->\s*```\n(.*?)```\s*"
        r"<!-- ddtlint:telemetry-contract:end -->", doc, re_mod.DOTALL)
    assert mm, "OBSERVABILITY.md lost its telemetry-contract markers"
    assert mm.group(1).strip() == block, (
        "docs/OBSERVABILITY.md telemetry-contract block is out of date "
        "— regenerate with `python -m tools.ddtlint --explain-telemetry`")


def test_explain_telemetry_cli():
    proc = subprocess.run(
        [sys.executable, "-m", "tools.ddtlint", "--explain-telemetry"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr
    assert "schema: v5" in proc.stdout
    assert "fault kinds:" in proc.stdout
    assert "grad_quant_rounds: neutral" in proc.stdout


# --------------------------------------------------------------------- #
# speed satellites: single-parse sharing, wall-time budget, changed-only
# --------------------------------------------------------------------- #
def test_lint_wall_time_budget():
    """The full-repo run must stay fast enough to live in tier-1 and in
    pre-push habits. Budget is ~6x the measured wall time at
    introduction (~2.3 s with shared ASTs) — headroom for CI noise, a
    tripwire for an accidentally quadratic checker."""
    t0 = time.perf_counter()
    findings = runner.lint_paths(GATE_PATHS, root=REPO)
    dt = time.perf_counter() - t0
    assert findings is not None
    assert dt < 15.0, f"full-repo ddtlint took {dt:.1f}s (budget 15s)"


def test_shared_ast_parse_once(monkeypatch):
    """lint_paths parses each file exactly once and shares the tree
    across checkers, the call graph, and the thread model: total
    ast.parse calls == number of scanned .py files (the analysis floor
    is the default scope, so that walk counts too; several distinct
    files legitimately share identical content — empty __init__.py —
    hence the total-count form)."""
    import ast as ast_mod

    calls = [0]
    real_parse = ast_mod.parse

    def counting_parse(src, *a, **k):
        calls[0] += 1
        return real_parse(src, *a, **k)

    monkeypatch.setattr(ast_mod, "parse", counting_parse)
    runner.lint_paths(["ddt_tpu/serve/"], root=REPO)
    scanned = set(runner._walk_py(["ddt_tpu/serve/"], REPO)) \
        | set(runner._walk_py(runner.DEFAULT_SCOPE, REPO))
    n_py = sum(1 for f in scanned if f.endswith(".py"))
    assert calls[0] == n_py, (calls[0], n_py)


def test_changed_files_vs_merge_base(tmp_path):
    """--changed-only's git plumbing: committed changes since the
    branch point + worktree edits + untracked files; None (full-scan
    fallback) without a merge base."""
    def git(*args):
        return subprocess.run(
            ["git", "-c", "user.email=t@t", "-c", "user.name=t", *args],
            cwd=tmp_path, capture_output=True, text=True, timeout=30)

    if git("init", "-b", "main").returncode != 0:
        pytest.skip("git unavailable")
    (tmp_path / "a.py").write_text("A = 1\n")
    (tmp_path / "b.py").write_text("B = 1\n")
    git("add", "-A")
    assert git("commit", "-m", "seed").returncode == 0
    git("checkout", "-b", "feature")
    (tmp_path / "a.py").write_text("A = 2\n")
    git("add", "a.py")
    assert git("commit", "-m", "change a").returncode == 0
    (tmp_path / "b.py").write_text("B = 2\n")        # worktree edit
    (tmp_path / "c.py").write_text("C = 1\n")        # untracked
    (tmp_path / "d.py").write_text("D = 1\n")        # staged-only (the
    git("add", "c.py", "d.py")                       # pre-commit state)
    changed = runner.changed_files(str(tmp_path))
    assert changed == {"a.py", "b.py", "c.py", "d.py"}


def test_changed_only_keeps_cross_file_analysis(tmp_path):
    """--changed-only narrows finding EMISSION, never the analysis
    inputs (review finding): a cross-role hazard in engine-only edits
    is detectable only because the thread model still sees batcher.py's
    Thread target + injected-callable binding."""
    serve = tmp_path / "ddt_tpu" / "serve"
    serve.mkdir(parents=True)
    (serve / "batcher.py").write_text(
        "import threading\n\n\n"
        "class Batcher:\n"
        "    def __init__(self, dispatch):\n"
        "        self._dispatch = dispatch\n"
        "        self._thread = threading.Thread(target=self._loop)\n"
        "        self._thread.start()\n\n"
        "    def _loop(self):\n"
        "        while True:\n"
        "            self._dispatch()\n")
    (serve / "engine.py").write_text(
        "from ddt_tpu.serve.batcher import Batcher\n\n\n"
        "class Engine:\n"
        "    def __init__(self):\n"
        "        self.model = object()\n"
        "        self._batcher = Batcher(self._dispatch)\n\n"
        "    def _dispatch(self):\n"
        "        return self.model\n\n"
        "    def swap(self, new):\n"
        "        self.model = new\n")
    want = [("ddt_tpu/serve/engine.py", "cross-role-state")]
    findings = runner.lint_paths(
        ["ddt_tpu/"], root=str(tmp_path),
        rules={"cross-role-state"},
        only_files={"ddt_tpu/serve/engine.py"})
    assert [(f.path, f.rule) for f in findings] == want, \
        [f.render() for f in findings]
    # Same contract for an EXPLICIT single-file path argument (review
    # finding): the analysis floor is the default scope, so
    # `ddtlint engine.py` sees batcher.py's thread roots too.
    findings = runner.lint_paths(
        ["ddt_tpu/serve/engine.py"], root=str(tmp_path),
        rules={"cross-role-state"})
    assert [(f.path, f.rule) for f in findings] == want, \
        [f.render() for f in findings]


def test_write_baseline_refuses_changed_only(tmp_path):
    """--write-baseline under a partial scan would truncate the ratchet
    to the changed files' findings (review finding) — refused."""
    proc = subprocess.run(
        [sys.executable, "-m", "tools.ddtlint", "--changed-only",
         "--write-baseline", "--baseline", str(tmp_path / "bl.json")],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 2
    assert "full scan" in proc.stderr
    assert not (tmp_path / "bl.json").exists()


def test_changed_only_scopes_stale_baseline():
    """A --changed-only run must not declare untouched files' baseline
    entries stale (split_vs_baseline's `scanned` contract)."""
    findings = []                       # nothing scanned found anything
    baseline = {"f1": {"fingerprint": "f1", "path": "ddt_tpu/api.py"},
                "f2": {"fingerprint": "f2", "path": "ddt_tpu/cli.py"}}
    new, known, stale = runner.split_vs_baseline(
        findings, baseline, scanned={"ddt_tpu/api.py"})
    assert (new, known) == ([], [])
    assert [e["path"] for e in stale] == ["ddt_tpu/api.py"]


def test_cli_json_format():
    """--format json: the stable machine-readable contract
    scripts/lint_smoke.py consumes."""
    proc = subprocess.run(
        [sys.executable, "-m", "tools.ddtlint", "ddt_tpu/serve/",
         "--no-baseline", "--format", "json"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    out = json.loads(proc.stdout)
    assert set(out) == {"findings", "new", "stale_baseline", "summary"}
    assert out["summary"]["total"] == len(out["findings"])
    for f in out["findings"]:
        assert set(f) == {"rule", "path", "line", "col", "message",
                          "line_text", "fingerprint"}
    # the serve tier is clean under every rule -> rc 0, no new findings
    assert proc.returncode == 0, proc.stdout
    assert out["new"] == []


# --------------------------------------------------------------------- #
# cross-module jit reachability (the traced-branch backbone)
# --------------------------------------------------------------------- #
def test_callgraph_cross_module_reachability():
    sources = {
        "pkg/ops/kern.py": (
            "import jax.numpy as jnp\n"
            "def traced_fn(x):\n"
            "    return helper(x)\n"
            "def helper(x):\n"
            "    return x\n"
            "def cold_fn(x):\n"
            "    return x\n"
        ),
        "pkg/backends/dev.py": (
            "import jax\n"
            "from pkg.ops.kern import traced_fn\n"
            "def make(cfg):\n"
            "    def grow(x):\n"
            "        return traced_fn(x)\n"
            "    return jax.jit(grow)\n"
        ),
    }
    reach = callgraph.build(sources)
    assert "grow" in {q.split(".")[-1] for q in reach["pkg/backends/dev.py"]}
    assert "traced_fn" in reach["pkg/ops/kern.py"]
    assert "helper" in reach["pkg/ops/kern.py"]       # transitive
    assert "cold_fn" not in reach["pkg/ops/kern.py"]  # no jit reaches it


def test_repo_ops_are_jit_reachable():
    """The backbone invariant on the real tree: the backend's jit roots
    reach the ops kernels (if this breaks, traced-branch goes blind)."""
    sources = {}
    for dirpath, dirnames, fns in os.walk(os.path.join(REPO, "ddt_tpu")):
        dirnames[:] = [d for d in dirnames
                       if d not in runner.SKIP_DIRS]
        for fn in fns:
            if fn.endswith(".py"):
                full = os.path.join(dirpath, fn)
                rel = os.path.relpath(full, REPO).replace(os.sep, "/")
                with open(full, encoding="utf-8") as f:
                    sources[rel] = f.read()
    reach = callgraph.build(sources)
    assert "grow_tree" in reach["ddt_tpu/ops/grow.py"]
    assert "build_histograms" in reach["ddt_tpu/ops/histogram.py"]
    # best_splits is an assignment wrapping the traced body since the
    # fused-round refactor; the BODY is what must stay jit-reachable.
    assert "best_splits_impl" in reach["ddt_tpu/ops/split.py"]
    # Pallas kernels are traced roots (pallas_call is a tracing
    # combinator, including partial()-wrapped kernels) — if this breaks,
    # traced-branch goes blind inside every kernel body.
    assert "_hist_kernel" in reach["ddt_tpu/ops/hist_pallas.py"]
    assert "_hist_kernel_t" in reach["ddt_tpu/ops/hist_pallas.py"]
    assert "_traverse_kernel" in reach["ddt_tpu/ops/predict_pallas.py"]


# --------------------------------------------------------------------- #
# baseline mechanics
# --------------------------------------------------------------------- #
def test_fingerprints_survive_line_shifts():
    src = "try:\n    import os\nexcept Exception:\n    pass\n"
    shifted = "# a new comment line\n# another\n" + src
    f1 = assign_fingerprints(runner.run_on_source("ddt_tpu/x.py", src))
    f2 = assign_fingerprints(runner.run_on_source("ddt_tpu/x.py", shifted))
    assert [f.fingerprint for f in f1] == [f.fingerprint for f in f2]
    assert f1[0].line != f2[0].line


def test_identical_lines_get_distinct_fingerprints():
    body = "    try:\n        pass\n    except Exception:\n        pass\n"
    src = "def a():\n" + body + "def b():\n" + body
    fs = assign_fingerprints(runner.run_on_source("ddt_tpu/x.py", src))
    assert len(fs) == 2
    assert fs[0].fingerprint != fs[1].fingerprint


def test_baseline_round_trip(tmp_path):
    src = "try:\n    import os\nexcept Exception:\n    pass\n"
    fs = assign_fingerprints(runner.run_on_source("ddt_tpu/x.py", src))
    p = str(tmp_path / "bl.json")
    runner.save_baseline(p, fs)
    loaded = runner.load_baseline(p)
    new, known, stale = runner.split_vs_baseline(fs, loaded)
    assert (new, len(known), stale) == ([], 1, [])


# --------------------------------------------------------------------- #
# the repo-wide gate
# --------------------------------------------------------------------- #
def test_ddtlint_gate():
    findings = runner.lint_paths(GATE_PATHS, root=REPO)
    baseline = runner.load_baseline(
        os.path.join(REPO, runner.DEFAULT_BASELINE))
    new, _known, stale = runner.split_vs_baseline(findings, baseline)
    assert not new, (
        "new ddtlint findings (fix them, add a documented "
        "`# ddtlint: disable=<rule>` pragma, or — only for a deliberate, "
        "documented exception — regenerate the baseline via "
        "`make lint-baseline`):\n  "
        + "\n  ".join(f.render() for f in new))
    assert not stale, (
        "stale ddtlint baseline entries — the finding was fixed, ratchet "
        "it out with `make lint-baseline`:\n  "
        + "\n  ".join(f"{e['path']} [{e['rule']}] {e.get('line_text', '')}"
                      for e in stale))


def test_cli_entry_point():
    proc = subprocess.run(
        [sys.executable, "-m", "tools.ddtlint", *GATE_PATHS],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "0 new" in proc.stdout


def test_cli_fails_on_stale_baseline_entries(tmp_path):
    """The CLI must agree with the pytest gate: a stale entry (fixed
    finding still in the baseline) is a failure until ratcheted out."""
    bl = tmp_path / "bl.json"
    bl.write_text(json.dumps({"findings": [{
        "fingerprint": "feedfeedfeedfeed", "rule": "broad-except",
        "path": "tools/ddtlint/findings.py", "line": 1,
        "line_text": "long gone", "message": "fixed ages ago"}]}))
    proc = subprocess.run(
        [sys.executable, "-m", "tools.ddtlint", "tools/ddtlint/findings.py",
         "--baseline", str(bl)],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "stale" in proc.stdout


# --------------------------------------------------------------------- #
# tsan audit classifier
# --------------------------------------------------------------------- #
def test_tsan_classifier_accepts_join_edge_shape():
    with open(os.path.join(FIXTURES, "tsan_join_edge.log"),
              encoding="utf-8") as f:
        summary = tsan_audit.classify_log(f.read())
    assert summary["ok"], summary
    assert summary["total_reports"] == 2
    assert summary["join_edge"] == 2


def test_tsan_classifier_rejects_real_race_shape():
    with open(os.path.join(FIXTURES, "tsan_real_race.log"),
              encoding="utf-8") as f:
        summary = tsan_audit.classify_log(f.read())
    assert not summary["ok"]
    reasons = json.dumps(summary["findings"])
    assert "ddt_" in reasons                 # kernel frame was visible
    assert "failed to restore" in reasons    # both stacks restored


def test_tsan_classifier_rejects_report_floods():
    with open(os.path.join(FIXTURES, "tsan_join_edge.log"),
              encoding="utf-8") as f:
        text = f.read()
    summary = tsan_audit.classify_log(text, max_reports=1)
    assert not summary["ok"]
    assert any(c["what"] == "report-count" for c in summary["findings"])


def test_audit_supp_drops_only_process_wide_entries(tmp_path):
    dst = str(tmp_path / "audit.supp")
    dropped = tsan_audit.write_audit_supp(
        os.path.join(REPO, "ddt_tpu/native/tsan.supp"), dst)
    assert dropped == 2
    with open(dst, encoding="utf-8") as f:
        lines = [ln.strip() for ln in f
                 if ln.strip() and not ln.strip().startswith("#")]
    # every ddt_-scoped entry still active, no process-wide ones left
    assert lines and all(
        ln.partition(":")[2].startswith("ddt_") for ln in lines), lines
