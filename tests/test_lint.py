"""ddtlint: repo-wide gate + per-checker fixture tests (tier-1,
marker-free so `pytest -m 'not slow'` always runs it).

Two layers, deliberately independent:
* fixture tests — each rule against minimal positive/negative snippets
  (tests/lint_fixtures/), so a checker that goes blind or noisy fails
  even while the repo gate stays green;
* the gate — the real tree against the ratchet baseline
  (tools/ddtlint/baseline.json): any NEW finding fails, and any STALE
  baseline entry fails too (fixed findings must be ratcheted out, the
  baseline only ever shrinks).
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from tools.ddtlint import callgraph, checkers, runner, tsan_audit  # noqa: E402
from tools.ddtlint.findings import assign_fingerprints  # noqa: E402

FIXTURES = os.path.join(REPO, "tests", "lint_fixtures")
GATE_PATHS = ["ddt_tpu/", "tests/"]


def _fixture_src(fname: str) -> str:
    with open(os.path.join(FIXTURES, fname), encoding="utf-8") as f:
        return f.read()


def _marker_lines(src: str, rule: str) -> set:
    return {i for i, line in enumerate(src.splitlines(), start=1)
            if f"# LINT: {rule}" in line}


def _flagged_lines(fname: str, synthetic_path: str, rule: str) -> set:
    src = _fixture_src(fname)
    findings = runner.run_on_source(
        synthetic_path, src, mesh_axes=runner.mesh_axis_names(REPO),
        rules={rule})
    assert all(f.rule == rule for f in findings), findings
    return {f.line for f in findings}


# (rule, positive fixture, negative fixture, synthetic path for scoping)
CASES = [
    ("traced-branch", "traced_branch_pos.py", "traced_branch_neg.py",
     "ddt_tpu/ops/fixture_mod.py"),
    ("host-sync", "host_sync_pos.py", "host_sync_neg.py",
     "ddt_tpu/ops/grow.py"),
    ("dtype-drift", "dtype_drift_pos.py", "dtype_drift_neg.py",
     "ddt_tpu/ops/fixture_mod.py"),
    ("collective-consistency", "collective_pos.py", "collective_neg.py",
     "ddt_tpu/ops/fixture_mod.py"),
    ("broad-except", "broad_except_pos.py", "broad_except_neg.py",
     "ddt_tpu/fixture_mod.py"),
    ("no-print", "no_print_pos.py", "no_print_neg.py",
     "ddt_tpu/fixture_mod.py"),
    ("pallas-interpret", "pallas_interpret_pos.py",
     "pallas_interpret_neg.py", "ddt_tpu/ops/fixture_mod.py"),
    ("pallas-vmem-guard", "pallas_vmem_pos.py",
     "pallas_vmem_neg.py", "ddt_tpu/ops/fixture_mod.py"),
    ("named-scope", "named_scope_pos.py", "named_scope_neg.py",
     "ddt_tpu/ops/fixture_mod.py"),
    ("atomic-artifact-write", "atomic_write_pos.py", "atomic_write_neg.py",
     "ddt_tpu/models/fixture_mod.py"),
    ("raw-phase-timing", "raw_timing_pos.py", "raw_timing_neg.py",
     "ddt_tpu/ops/fixture_mod.py"),
    ("serve-blocking-io", "serve_blocking_pos.py", "serve_blocking_neg.py",
     "ddt_tpu/serve/engine.py"),
    ("one-home-collective", "one_home_collective_pos.py",
     "one_home_collective_neg.py", "ddt_tpu/ops/fixture_mod.py"),
]


@pytest.mark.parametrize("rule,pos,_neg,path",
                         CASES, ids=[c[0] for c in CASES])
def test_checker_fires_on_seeded_violations(rule, pos, _neg, path):
    src = _fixture_src(pos)
    want = _marker_lines(src, rule)
    assert want, f"fixture {pos} has no LINT markers for {rule}"
    got = _flagged_lines(pos, path, rule)
    assert got == want, (
        f"{rule}: flagged lines {sorted(got)} != expected markers "
        f"{sorted(want)} in {pos}")


@pytest.mark.parametrize("rule,_pos,neg,path",
                         CASES, ids=[c[0] for c in CASES])
def test_checker_silent_on_clean_code(rule, _pos, neg, path):
    got = _flagged_lines(neg, path, rule)
    assert got == set(), f"{rule}: false positives at lines {sorted(got)} " \
                         f"in {neg}"


def test_one_home_collective_exempts_comms_module():
    """parallel/comms.py IS the one home: the same raw-collective source
    must not be flagged there (or outside ddt_tpu/ — tools and tests
    spell collectives freely)."""
    src = _fixture_src("one_home_collective_pos.py")
    for path in ("ddt_tpu/parallel/comms.py", "tests/test_comms.py",
                 "tools/ddtlint/fixture_mod.py"):
        findings = runner.run_on_source(path, src,
                                        rules={"one-home-collective"})
        assert findings == [], (path, [f.render() for f in findings])


def test_serve_blocking_io_exempts_transport_and_other_layers():
    """The rule is scoped to the serving HOT-LOOP modules only: the
    same blocking source must not be flagged in the HTTP transport
    layer (its blocking is the caller's thread), the cli, or non-serve
    library code (which other rules govern)."""
    src = _fixture_src("serve_blocking_pos.py")
    for path in ("ddt_tpu/serve/http.py", "ddt_tpu/cli.py",
                 "ddt_tpu/streaming.py", "scripts/serve_smoke.py"):
        findings = runner.run_on_source(path, src,
                                        rules={"serve-blocking-io"})
        assert findings == [], (path, [f.render() for f in findings])


def test_atomic_artifact_write_covers_registry():
    """ISSUE 9: the registry is an artifact-owning module — the SAME
    violating source that fires under models/ must fire under
    ddt_tpu/registry/, while the export staging layer stays exempt
    (its writes land in a staging dir published by one atomic dir
    rename — see the checker doc)."""
    src = _fixture_src("atomic_write_pos.py")
    want = _marker_lines(src, "atomic-artifact-write")
    got = _flagged_lines("atomic_write_pos.py",
                         "ddt_tpu/registry/store.py",
                         "atomic-artifact-write")
    assert got == want, (sorted(got), sorted(want))
    for exempt in ("ddt_tpu/export/aot.py", "scripts/registry_smoke.py"):
        findings = runner.run_on_source(
            exempt, src, rules={"atomic-artifact-write"})
        assert findings == [], (exempt,
                                [f.render() for f in findings])


def test_no_print_exempts_cli_and_non_library_paths():
    """The rule is scoped to LIBRARY code: the same print-bearing source
    must not be flagged when it lives in the CLI (stdout is its
    interface) or outside ddt_tpu/ (tools, tests)."""
    src = _fixture_src("no_print_pos.py")
    for path in ("ddt_tpu/cli.py", "tools/ddtlint/__main__.py",
                 "tests/test_cli.py", "scripts/telemetry_smoke.py"):
        findings = runner.run_on_source(path, src, rules={"no-print"})
        assert findings == [], (path, [f.render() for f in findings])


def test_suppression_hygiene_fires():
    src = _fixture_src("suppressions_pos.supp")
    findings = checkers.check_suppressions("ddt_tpu/native/fix.supp", src)
    assert {f.line_text for f in findings} == {
        "race:_contig_to_contig", "race:array_dealloc"}


def test_suppression_hygiene_silent_with_audit_tag():
    src = _fixture_src("suppressions_neg.supp")
    assert checkers.check_suppressions("ddt_tpu/native/fix.supp", src) == []


def test_repo_tsan_supp_passes_hygiene():
    with open(os.path.join(REPO, "ddt_tpu/native/tsan.supp"),
              encoding="utf-8") as f:
        src = f.read()
    findings = checkers.check_suppressions("ddt_tpu/native/tsan.supp", src)
    assert findings == [], [f.render() for f in findings]


# --------------------------------------------------------------------- #
# cross-module jit reachability (the traced-branch backbone)
# --------------------------------------------------------------------- #
def test_callgraph_cross_module_reachability():
    sources = {
        "pkg/ops/kern.py": (
            "import jax.numpy as jnp\n"
            "def traced_fn(x):\n"
            "    return helper(x)\n"
            "def helper(x):\n"
            "    return x\n"
            "def cold_fn(x):\n"
            "    return x\n"
        ),
        "pkg/backends/dev.py": (
            "import jax\n"
            "from pkg.ops.kern import traced_fn\n"
            "def make(cfg):\n"
            "    def grow(x):\n"
            "        return traced_fn(x)\n"
            "    return jax.jit(grow)\n"
        ),
    }
    reach = callgraph.build(sources)
    assert "grow" in {q.split(".")[-1] for q in reach["pkg/backends/dev.py"]}
    assert "traced_fn" in reach["pkg/ops/kern.py"]
    assert "helper" in reach["pkg/ops/kern.py"]       # transitive
    assert "cold_fn" not in reach["pkg/ops/kern.py"]  # no jit reaches it


def test_repo_ops_are_jit_reachable():
    """The backbone invariant on the real tree: the backend's jit roots
    reach the ops kernels (if this breaks, traced-branch goes blind)."""
    sources = {}
    for dirpath, dirnames, fns in os.walk(os.path.join(REPO, "ddt_tpu")):
        dirnames[:] = [d for d in dirnames
                       if d not in runner.SKIP_DIRS]
        for fn in fns:
            if fn.endswith(".py"):
                full = os.path.join(dirpath, fn)
                rel = os.path.relpath(full, REPO).replace(os.sep, "/")
                with open(full, encoding="utf-8") as f:
                    sources[rel] = f.read()
    reach = callgraph.build(sources)
    assert "grow_tree" in reach["ddt_tpu/ops/grow.py"]
    assert "build_histograms" in reach["ddt_tpu/ops/histogram.py"]
    # best_splits is an assignment wrapping the traced body since the
    # fused-round refactor; the BODY is what must stay jit-reachable.
    assert "best_splits_impl" in reach["ddt_tpu/ops/split.py"]
    # Pallas kernels are traced roots (pallas_call is a tracing
    # combinator, including partial()-wrapped kernels) — if this breaks,
    # traced-branch goes blind inside every kernel body.
    assert "_hist_kernel" in reach["ddt_tpu/ops/hist_pallas.py"]
    assert "_hist_kernel_t" in reach["ddt_tpu/ops/hist_pallas.py"]
    assert "_traverse_kernel" in reach["ddt_tpu/ops/predict_pallas.py"]


# --------------------------------------------------------------------- #
# baseline mechanics
# --------------------------------------------------------------------- #
def test_fingerprints_survive_line_shifts():
    src = "try:\n    import os\nexcept Exception:\n    pass\n"
    shifted = "# a new comment line\n# another\n" + src
    f1 = assign_fingerprints(runner.run_on_source("ddt_tpu/x.py", src))
    f2 = assign_fingerprints(runner.run_on_source("ddt_tpu/x.py", shifted))
    assert [f.fingerprint for f in f1] == [f.fingerprint for f in f2]
    assert f1[0].line != f2[0].line


def test_identical_lines_get_distinct_fingerprints():
    body = "    try:\n        pass\n    except Exception:\n        pass\n"
    src = "def a():\n" + body + "def b():\n" + body
    fs = assign_fingerprints(runner.run_on_source("ddt_tpu/x.py", src))
    assert len(fs) == 2
    assert fs[0].fingerprint != fs[1].fingerprint


def test_baseline_round_trip(tmp_path):
    src = "try:\n    import os\nexcept Exception:\n    pass\n"
    fs = assign_fingerprints(runner.run_on_source("ddt_tpu/x.py", src))
    p = str(tmp_path / "bl.json")
    runner.save_baseline(p, fs)
    loaded = runner.load_baseline(p)
    new, known, stale = runner.split_vs_baseline(fs, loaded)
    assert (new, len(known), stale) == ([], 1, [])


# --------------------------------------------------------------------- #
# the repo-wide gate
# --------------------------------------------------------------------- #
def test_ddtlint_gate():
    findings = runner.lint_paths(GATE_PATHS, root=REPO)
    baseline = runner.load_baseline(
        os.path.join(REPO, runner.DEFAULT_BASELINE))
    new, _known, stale = runner.split_vs_baseline(findings, baseline)
    assert not new, (
        "new ddtlint findings (fix them, add a documented "
        "`# ddtlint: disable=<rule>` pragma, or — only for a deliberate, "
        "documented exception — regenerate the baseline via "
        "`make lint-baseline`):\n  "
        + "\n  ".join(f.render() for f in new))
    assert not stale, (
        "stale ddtlint baseline entries — the finding was fixed, ratchet "
        "it out with `make lint-baseline`:\n  "
        + "\n  ".join(f"{e['path']} [{e['rule']}] {e.get('line_text', '')}"
                      for e in stale))


def test_cli_entry_point():
    proc = subprocess.run(
        [sys.executable, "-m", "tools.ddtlint", *GATE_PATHS],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "0 new" in proc.stdout


def test_cli_fails_on_stale_baseline_entries(tmp_path):
    """The CLI must agree with the pytest gate: a stale entry (fixed
    finding still in the baseline) is a failure until ratcheted out."""
    bl = tmp_path / "bl.json"
    bl.write_text(json.dumps({"findings": [{
        "fingerprint": "feedfeedfeedfeed", "rule": "broad-except",
        "path": "tools/ddtlint/findings.py", "line": 1,
        "line_text": "long gone", "message": "fixed ages ago"}]}))
    proc = subprocess.run(
        [sys.executable, "-m", "tools.ddtlint", "tools/ddtlint/findings.py",
         "--baseline", str(bl)],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "stale" in proc.stdout


# --------------------------------------------------------------------- #
# tsan audit classifier
# --------------------------------------------------------------------- #
def test_tsan_classifier_accepts_join_edge_shape():
    with open(os.path.join(FIXTURES, "tsan_join_edge.log"),
              encoding="utf-8") as f:
        summary = tsan_audit.classify_log(f.read())
    assert summary["ok"], summary
    assert summary["total_reports"] == 2
    assert summary["join_edge"] == 2


def test_tsan_classifier_rejects_real_race_shape():
    with open(os.path.join(FIXTURES, "tsan_real_race.log"),
              encoding="utf-8") as f:
        summary = tsan_audit.classify_log(f.read())
    assert not summary["ok"]
    reasons = json.dumps(summary["findings"])
    assert "ddt_" in reasons                 # kernel frame was visible
    assert "failed to restore" in reasons    # both stacks restored


def test_tsan_classifier_rejects_report_floods():
    with open(os.path.join(FIXTURES, "tsan_join_edge.log"),
              encoding="utf-8") as f:
        text = f.read()
    summary = tsan_audit.classify_log(text, max_reports=1)
    assert not summary["ok"]
    assert any(c["what"] == "report-count" for c in summary["findings"])


def test_audit_supp_drops_only_process_wide_entries(tmp_path):
    dst = str(tmp_path / "audit.supp")
    dropped = tsan_audit.write_audit_supp(
        os.path.join(REPO, "ddt_tpu/native/tsan.supp"), dst)
    assert dropped == 2
    with open(dst, encoding="utf-8") as f:
        lines = [ln.strip() for ln in f
                 if ln.strip() and not ln.strip().startswith("#")]
    # every ddt_-scoped entry still active, no process-wide ones left
    assert lines and all(
        ln.partition(":")[2].startswith("ddt_") for ln in lines), lines
