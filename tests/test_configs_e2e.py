"""Five-config integration matrix (round-3 verdict item 8).

One miniature of EACH `BASELINE.json` eval config, end to end through the
public surface (CLI where the config names one), asserting green plus the
config's key invariant. docs/CONFIGS.md links each config to its test
here, so "all five configs run" is witnessed by one file:

  1. Higgs-1M binary, depth-6, 100 trees, 255 bins -> test_config1_higgs
  2. Covertype 7-class, depth-8, 500 trees         -> test_config2_covertype
  3. Criteo CTR, sparse cat, 4-partition allreduce -> test_config3_criteo
  4. 1000-tree ensemble, 10M-row batch scoring     -> test_config4_scoring
  5. 10B-row / 1024-feature streamed stress        -> test_config5_stream

Shapes are cut to suite-friendly sizes; the full-size commands live in
docs/CONFIGS.md. The device ("tpu") backend here runs on the virtual
8-device CPU mesh (tests/conftest.py), exercising the same jitted
programs as the real chip.
"""

import json

import numpy as np

from ddt_tpu import api
from ddt_tpu.cli import main
from ddt_tpu.data import chunks as chunks_mod
from ddt_tpu.data import datasets
from ddt_tpu.models.tree import TreeEnsemble


def _run(capsys, argv):
    rc = main(argv)
    assert rc == 0
    return json.loads(capsys.readouterr().out.strip().splitlines()[-1])


def test_config1_higgs(tmp_path, capsys):
    """Config 1: Higgs-shape binary clf at the contract's depth-6 /
    255-bin settings through the device backend, CLI train -> predict."""
    model = str(tmp_path / "higgs.npz")
    rec = _run(capsys, [
        "train", "--backend=tpu", "--dataset=higgs", "--rows=20000",
        "--trees=10", "--depth=6", "--bins=255", f"--out={model}",
    ])
    assert rec["trees"] == 10 and rec["depth"] == 6
    assert rec["final_train_loss"] < 0.60       # learning, not memorizing pad

    scores = str(tmp_path / "s.npy")
    rec = _run(capsys, [
        "predict", "--backend=tpu", f"--model={model}",
        "--dataset=higgs", "--rows=4000", "--bins=255", f"--out={scores}",
    ])
    s = np.load(scores)
    assert s.shape == (4000,) and (0 <= s).all() and (s <= 1).all()
    # Depth-6 / 255-bin on the generator separates the classes (the CLI's
    # higgs dataset is synthetic_binary at --seed's default).
    _, y = datasets.synthetic_binary(4000, seed=0)
    auc = _auc(s, y)
    assert auc > 0.70, auc


def _auc(scores, y):
    order = np.argsort(scores)
    ranks = np.empty(len(y))
    ranks[order] = np.arange(1, len(y) + 1)
    pos = y > 0.5
    n1, n0 = pos.sum(), (~pos).sum()
    return (ranks[pos].sum() - n1 * (n1 + 1) / 2) / (n1 * n0)


def test_config2_covertype(tmp_path, capsys):
    """Config 2: 7-class one-vs-all softmax boosting (one tree per class
    per round) at depth 8."""
    model = str(tmp_path / "cov.npz")
    rec = _run(capsys, [
        "train", "--backend=tpu", "--dataset=covertype", "--rows=8000",
        "--trees=4", "--depth=8", "--bins=63", f"--out={model}",
    ])
    ens = TreeEnsemble.load(model)
    assert ens.loss == "softmax" and ens.n_classes == 7
    assert ens.n_trees == 4 * 7                 # rounds x classes
    assert rec["final_train_loss"] < np.log(7)  # below uniform chance

    scores = str(tmp_path / "cs.npy")
    _run(capsys, [
        "predict", "--backend=tpu", f"--model={model}",
        "--dataset=covertype", "--rows=2000", "--bins=63",
        f"--out={scores}",
    ])
    p = np.load(scores)
    assert p.shape == (2000, 7)
    np.testing.assert_allclose(p.sum(axis=1), 1.0, rtol=1e-5)


def test_config3_criteo(tmp_path, capsys):
    """Config 3: sparse-categorical CTR with the 4-partition histogram
    allreduce — 4-partition training must grow bit-identical tree
    structure to 1-partition (the allreduce is the only cross-device
    step, and it is additively exact)."""
    m4 = str(tmp_path / "c4.npz")
    m1 = str(tmp_path / "c1.npz")
    common = ["train", "--backend=tpu", "--dataset=criteo",
              "--rows=8000", "--trees=4", "--depth=5", "--bins=100",
              "--cat-splits=onehot"]
    _run(capsys, common + ["--partitions=4", f"--out={m4}"])
    _run(capsys, common + ["--partitions=1", f"--out={m1}"])
    e4, e1 = TreeEnsemble.load(m4), TreeEnsemble.load(m1)
    assert e4.has_cat_splits                    # cat one-vs-rest exercised
    np.testing.assert_array_equal(e4.feature, e1.feature)
    np.testing.assert_array_equal(e4.threshold_bin, e1.threshold_bin)
    np.testing.assert_array_equal(e4.is_leaf, e1.is_leaf)
    np.testing.assert_allclose(e4.leaf_value, e1.leaf_value,
                               rtol=2e-4, atol=2e-5)


def test_config4_scoring(tmp_path, capsys):
    """Config 4: big pretrained ensemble, large-batch inference-only
    scoring. A trained model is tiled to 1000 trees (the config's tree
    count) and scored over 100k rows through the device gather-free
    descent; the NumPy oracle must agree."""
    X, y = datasets.synthetic_binary(3000, n_features=12, seed=9)
    res = api.train(X, y, n_trees=10, max_depth=6, n_bins=63,
                    backend="cpu", log_every=10**9)
    big = TreeEnsemble.concat([res.ensemble] * 100)     # 1000 trees
    assert big.n_trees == 1000
    model = str(tmp_path / "big.npz")
    api.TrainResult(big, res.mapper, []).save(model)

    Xs, _ = datasets.synthetic_binary(100_000, n_features=12, seed=10)
    data = str(tmp_path / "batch.npz")
    np.savez(data, X=Xs, y=np.zeros(len(Xs), np.float32))  # y unused
    scores = str(tmp_path / "big_scores.npy")
    rec = _run(capsys, [
        "predict", "--backend=tpu", f"--model={model}",
        f"--data={data}", f"--out={scores}",
    ])
    assert rec["rows"] == 100_000
    got = np.load(scores)
    raw = big.predict_raw(res.mapper.transform(Xs), binned=True)
    with np.errstate(over="ignore"):    # exp overflow -> inf -> exactly 0.0
        want = 1.0 / (1.0 + np.exp(-raw))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_config5_stream(tmp_path, capsys):
    """Config 5: wide-feature out-of-core streamed training over on-disk
    shards on a row-sharded mesh, then out-of-core scoring — the pod
    config's shape at this box's scale. Streamed-from-disk training must
    match in-memory training on the same rows bit-identically."""
    F, rows_per, n_chunks = 256, 4000, 4
    parts = [datasets.stress_binned_chunk(c, rows_per, n_features=F,
                                          seed=77) for c in range(n_chunks)]
    Xb = np.concatenate([p[0] for p in parts])
    y = np.concatenate([p[1] for p in parts])
    d = str(tmp_path / "shards")
    chunks_mod.shard_arrays(Xb, y, d, n_chunks=n_chunks)

    model = str(tmp_path / "stream.npz")
    rec = _run(capsys, [
        "train", "--backend=tpu", "--partitions=2", "--trees=4",
        "--depth=5", "--bins=255", f"--stream-dir={d}", f"--out={model}",
    ])
    assert rec["trees"] == 4

    ens = TreeEnsemble.load(model)
    res = api.train(Xb, y, n_trees=4, max_depth=5, n_bins=255,
                    backend="tpu", n_partitions=2, binned=True,
                    log_every=10**9)
    np.testing.assert_array_equal(ens.feature, res.ensemble.feature)
    np.testing.assert_array_equal(ens.threshold_bin,
                                  res.ensemble.threshold_bin)
    np.testing.assert_allclose(ens.leaf_value, res.ensemble.leaf_value,
                               rtol=2e-4, atol=2e-5)

    # Out-of-core scoring over the same shards (per-shard .npy outputs).
    sdir = str(tmp_path / "scores")
    rec = _run(capsys, [
        "predict", "--backend=tpu", f"--model={model}",
        f"--stream-dir={d}", f"--out={sdir}",
    ])
    got = np.concatenate([
        np.load(f"{sdir}/scores_{c:05d}.npy") for c in range(n_chunks)])
    assert got.shape == (rows_per * n_chunks,)
    assert np.isfinite(got).all()
