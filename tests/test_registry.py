"""AOT export + digest-addressed model registry (ISSUE 9).

Covers the acceptance surface on the CPU tier: export→load→predict
bit-exactness against in-process `api.predict` (f32 and quantized),
zero retracing on the pre-traced bucket shapes (jit_compiles witness),
registry push atomicity under concurrent writers, corrupt/torn
artifact rejection, legacy manifest-less back-compat, reference-based
hot swap, and the schema-v5 `artifact` event → `report` registry
section round trip. The cold-PROCESS restore is scripts/
registry_smoke.py's job; everything here runs in-process.
"""

import json
import os
import shutil
import threading
import time

import numpy as np
import pytest

from ddt_tpu import api, cli
from ddt_tpu.config import TrainConfig
from ddt_tpu.data import datasets
from ddt_tpu.models.tree import TreeEnsemble
from ddt_tpu.registry import IntegrityError, Registry, RegistryError
from ddt_tpu.registry import manifest as manifest_mod
from ddt_tpu.registry.loader import (RestoredModel, load_servable,
                                     push_servable)
from ddt_tpu.serve.engine import ServeEngine
from ddt_tpu.serve.http import _swap
from ddt_tpu.telemetry import counters as tele_counters
from ddt_tpu.telemetry import report as tele_report
from ddt_tpu.telemetry.events import RunLog

MAX_BATCH = 16          # bucket ladder (1, 2, 4, 8, 16): small, fast AOT


@pytest.fixture(scope="module")
def trained():
    """One small model + reference config, shared module-wide (training
    and AOT export are the slow parts)."""
    X, y = datasets.synthetic_binary(2500, seed=7)
    res = api.train(X, y, n_trees=6, max_depth=3, n_bins=31,
                    backend="tpu", log_every=10**9)
    cfg = TrainConfig(backend="tpu", n_bins=31)
    cfg_lut = cfg.replace(predict_impl="lut")
    return dict(X=X, res=res, cfg=cfg, cfg_lut=cfg_lut)


@pytest.fixture(scope="module")
def pushed(trained, tmp_path_factory):
    """The model exported (f32 + quantized variants) and pushed once."""
    root = str(tmp_path_factory.mktemp("registry"))
    bundle = api.ModelBundle(ensemble=trained["res"].ensemble,
                             mapper=trained["res"].mapper)
    out = push_servable(root, bundle, name="higgs", max_batch=MAX_BATCH,
                        quantize=True)
    return dict(root=root, **out)


def _bundle(trained):
    return api.ModelBundle(ensemble=trained["res"].ensemble,
                           mapper=trained["res"].mapper)


# --------------------------------------------------------------------- #
# embedded npz manifests (satellite 1)
# --------------------------------------------------------------------- #
def test_save_model_embeds_verified_manifest(trained, tmp_path):
    p = str(tmp_path / "m.npz")
    api.save_model(p, trained["res"].ensemble, mapper=trained["res"].mapper,
                   run_id="deadbeef1234", cfg=trained["cfg"])
    b = api.load_model(p)
    man = b.manifest
    assert man is not None
    assert man["manifest_schema"] == manifest_mod.MANIFEST_SCHEMA
    assert man["kind"] == "model_bundle"
    assert man["run_id"] == "deadbeef1234"
    assert man["config_fingerprint"]
    assert len(man["digest"]) == 64
    # The digest covers the payload: same arrays -> same digest.
    with np.load(p) as z:
        d = dict(z)
    assert manifest_mod.arrays_digest(d) == man["digest"]


def test_save_model_bytes_are_deterministic(trained, tmp_path):
    """Content addressing rides on this: the same model saved twice
    produces IDENTICAL file bytes (zip member timestamps stripped —
    utils/atomic.atomic_savez deterministic mode), so re-pushing reuses
    the digest and version instead of minting a new artifact."""
    import hashlib
    import time

    a, b = str(tmp_path / "a.npz"), str(tmp_path / "b.npz")
    api.save_model(a, trained["res"].ensemble, mapper=trained["res"].mapper)
    time.sleep(0.01)
    api.save_model(b, trained["res"].ensemble, mapper=trained["res"].mapper)
    with open(a, "rb") as fa, open(b, "rb") as fb:
        assert hashlib.sha256(fa.read()).digest() \
            == hashlib.sha256(fb.read()).digest()


def test_load_model_rejects_tampered_payload(trained, tmp_path):
    p = str(tmp_path / "m.npz")
    api.save_model(p, trained["res"].ensemble, mapper=trained["res"].mapper)
    with np.load(p) as z:
        d = dict(z)
    d["leaf_value"] = np.array(d["leaf_value"])
    d["leaf_value"][0, 0] += 1.0          # one flipped leaf
    np.savez_compressed(str(tmp_path / "evil"), **d)
    with pytest.raises(IntegrityError, match="digest mismatch"):
        api.load_model(str(tmp_path / "evil.npz"))


def test_legacy_manifestless_npz_still_loads(trained, tmp_path):
    """Files written before manifests existed carry no manifest_json
    key and must keep loading (and serving) exactly as before."""
    p = str(tmp_path / "legacy.npz")
    d = trained["res"].ensemble.to_dict()
    d.update({f"mapper_{k}": v
              for k, v in trained["res"].mapper.save().items()})
    np.savez_compressed(p, **d)           # the pre-manifest writer
    b = api.load_model(p)
    assert b.manifest is None
    want = api.predict(trained["res"].ensemble, trained["X"][:8],
                       mapper=trained["res"].mapper, cfg=trained["cfg"])
    got = api.predict(b, trained["X"][:8], cfg=trained["cfg"])
    assert np.array_equal(want, got)


def test_tree_ensemble_save_carries_manifest(trained, tmp_path):
    p = str(tmp_path / "ens.npz")
    trained["res"].ensemble.save(p)
    # Plain load ignores the manifest key; api.load_model verifies it.
    ens = TreeEnsemble.load(p)
    assert ens.n_trees == trained["res"].ensemble.n_trees
    b = api.load_model(p)
    assert b.manifest["kind"] == "tree_ensemble"
    assert b.mapper is None


# --------------------------------------------------------------------- #
# store: push/resolve/list/tag, atomicity, corruption
# --------------------------------------------------------------------- #
def _fake_stage(reg: Registry, payload: bytes, kind: str = "servable"
                ) -> str:
    """A tiny hand-built artifact (no jax, no export) for store-level
    tests — content varies with `payload` so digests differ."""
    stage = reg.stage()
    with open(os.path.join(stage, "blob.bin"), "wb") as f:
        f.write(payload)
    manifest_mod.write_artifact_manifest(stage, {"kind": kind})
    return stage


def test_push_resolve_list_tag_roundtrip(tmp_path):
    reg = Registry(str(tmp_path / "reg"))
    d1 = reg.push(_fake_stage(reg, b"one"), "m")
    d2 = reg.push(_fake_stage(reg, b"two"), "m")
    assert (d1["version"], d2["version"]) == (1, 2)
    assert d1["digest"] != d2["digest"]
    # Every reference form resolves to the same object.
    for ref in (d1["digest"], d1["digest"][:10], "m@1"):
        assert reg.resolve(ref) == d1["digest"]
    for ref in ("m", "m@latest", "m@2"):
        assert reg.resolve(ref) == d2["digest"]
    tag = reg.tag("m@1", "prod")
    assert tag["version"] == 1
    assert reg.resolve("m@prod") == d1["digest"]
    inv = reg.list()
    assert [v["version"] for v in inv["names"]["m"]["versions"]] == [1, 2]
    assert inv["names"]["m"]["tags"] == {"prod": 1}
    assert inv["anonymous"] == []
    # Unknown refs fail loudly with the known inventory in hand.
    with pytest.raises(RegistryError):
        reg.resolve("m@3")
    with pytest.raises(RegistryError):
        reg.resolve("nosuch")
    with pytest.raises(RegistryError):
        reg.tag("m@1", "7")               # numeric tags are reserved


def test_push_same_content_is_idempotent(tmp_path):
    reg = Registry(str(tmp_path / "reg"))
    a = reg.push(_fake_stage(reg, b"same"), "m")
    b = reg.push(_fake_stage(reg, b"same"), "m")
    assert a == b                          # same digest, same version 1
    assert len(reg.list()["names"]["m"]["versions"]) == 1


def test_concurrent_pushers_get_dense_unique_versions(tmp_path):
    """The push-atomicity acceptance item: racing writers (distinct
    contents AND a duplicated content) never tear the store — versions
    come out dense and unique, every object integrity-checks."""
    reg = Registry(str(tmp_path / "reg"))
    n_distinct, errs, results = 12, [], []
    payloads = [f"model-{i}".encode() for i in range(n_distinct)]
    payloads += [b"model-0"] * 3          # same-content race too
    stages = [_fake_stage(reg, p) for p in payloads]
    barrier = threading.Barrier(len(stages))

    def push(stage):
        try:
            barrier.wait(timeout=30)
            results.append(reg.push(stage, "m"))
        # Worker-thread boundary: every failure must surface in errs,
        # never die silently on the thread.
        except Exception as e:  # ddtlint: disable=broad-except
            errs.append(e)

    threads = [threading.Thread(target=push, args=(s,)) for s in stages]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errs, errs
    versions = sorted(v["version"]
                      for v in reg.list()["names"]["m"]["versions"])
    assert versions == list(range(1, n_distinct + 1))
    dup = [r for r in results if r["digest"] == reg.resolve("m@1")]
    for r in results:                      # every pusher got a version
        assert r["version"] in versions
    assert len({r["digest"] for r in results}) == n_distinct
    assert len(dup) >= 1
    for v in versions:                     # every object verifies
        reg.get(f"m@{v}")
    # No staging litter became visible as an object.
    assert len(reg.list()["anonymous"]) == 0


def test_corrupt_and_torn_artifacts_are_rejected(tmp_path):
    reg = Registry(str(tmp_path / "reg"))
    d = reg.push(_fake_stage(reg, b"payload"), "m")
    obj = reg.object_dir(d["digest"])
    # 1. flipped byte in a listed file
    with open(os.path.join(obj, "blob.bin"), "r+b") as f:
        f.write(b"X")
    with pytest.raises(IntegrityError, match="sha256 mismatch"):
        reg.get("m@1")
    with open(os.path.join(obj, "blob.bin"), "wb") as f:
        f.write(b"payload")
    reg.get("m@1")                         # restored -> verifies again
    # 2. unlisted foreign file hiding in the object
    with open(os.path.join(obj, "extra.bin"), "wb") as f:
        f.write(b"sneaky")
    with pytest.raises(IntegrityError, match="drifted"):
        reg.get("m@1")
    os.remove(os.path.join(obj, "extra.bin"))
    # 3. manifest rewritten in place (digest no longer matches address)
    man_path = os.path.join(obj, manifest_mod.MANIFEST_FILE)
    with open(man_path, encoding="utf-8") as f:
        man = json.load(f)
    man["kind"] = "tampered"
    with open(man_path + ".t", "w", encoding="utf-8") as f:
        json.dump(man, f, sort_keys=True)
    os.replace(man_path + ".t", man_path)
    with pytest.raises(IntegrityError, match="addressed"):
        reg.get("m@1")
    # 4. truncated manifest = unreadable artifact
    with open(man_path, "w", encoding="utf-8") as f:
        f.write('{"artifact_schema": 1, "files"')
    with pytest.raises(IntegrityError, match="not valid JSON"):
        reg.get("m@1")


def test_staging_litter_is_invisible(tmp_path):
    reg = Registry(str(tmp_path / "reg"))
    reg.push(_fake_stage(reg, b"x"), "m")
    # A crashed pusher's leftover staging dir must never surface.
    dead = reg.stage()
    with open(os.path.join(dead, "half-written"), "wb") as f:
        f.write(b"torn")
    inv = reg.list()
    assert set(inv["names"]) == {"m"}
    assert inv["anonymous"] == []


def test_bad_names_rejected(tmp_path):
    reg = Registry(str(tmp_path / "reg"))
    for bad in ("", "a@b", "a/b", ".hidden"):
        with pytest.raises(RegistryError):
            reg.push(_fake_stage(reg, b"y"), bad)


# --------------------------------------------------------------------- #
# export -> load -> predict bit-exactness (acceptance)
# --------------------------------------------------------------------- #
def test_f32_restore_bitexact_vs_api_predict(trained, pushed):
    rep = load_servable(pushed["root"], "higgs@1", quantize=False)
    assert rep.mode == "aot-f32"
    m = rep.model
    assert isinstance(m, RestoredModel) and m.aot
    assert m.artifact_digest == pushed["digest"]
    m.warmup()
    X = trained["X"]
    # Sweep request sizes across buckets INCLUDING an over-sized one
    # (beyond the exported cap -> largest-bucket chunking).
    for n in (1, 3, 8, MAX_BATCH, 5 * MAX_BATCH + 3):
        want = api.predict(trained["res"].ensemble, X[:n],
                           mapper=trained["res"].mapper,
                           cfg=trained["cfg"])
        got = m.score_binned(trained["res"].mapper.transform(X[:n]))
        assert np.array_equal(np.asarray(want), np.asarray(got)), n


def test_lut_restore_bitexact_and_bounded(trained, pushed):
    rep = load_servable(pushed["root"], pushed["digest"])  # follows artifact
    assert rep.mode == "aot-lut"
    m = rep.model
    assert m.quantized and m.max_abs_err > 0
    m.warmup()
    X = trained["X"]
    for n in (1, 7, MAX_BATCH):
        want = api.predict(trained["res"].ensemble, X[:n],
                           mapper=trained["res"].mapper,
                           cfg=trained["cfg_lut"])
        got = m.score_binned(trained["res"].mapper.transform(X[:n]))
        assert np.array_equal(np.asarray(want), np.asarray(got)), n


def test_lut4_push_restore_bitexact_and_tier_pinned(trained, tmp_path):
    """ISSUE 12: --quantize=int4 exports lut4 AOT blobs + int4 tables;
    the cold restore is aot-lut4, bit-exact vs the in-process lut4
    path, stamps predict_impl='lut4', and tier mismatches refuse."""
    root = str(tmp_path / "reg4")
    out = push_servable(root, _bundle(trained), name="m4",
                        max_batch=8, quantize="int4")
    rep = load_servable(root, "m4")               # follows the artifact
    assert rep.mode == "aot-lut4"
    m = rep.model
    assert m.quantized and m.quantize_tier == "int4"
    assert m.predict_impl == "lut4"
    assert rep.manifest["quantized"]["tier"] == "int4"
    assert rep.manifest["quantized"]["leaf_dtype"] == "int4"
    assert m.max_abs_err == rep.manifest["quantized"]["max_abs_err"]
    m.warmup()
    X = trained["X"]
    cfg4 = trained["cfg"].replace(predict_impl="lut4")
    for n in (1, 7, 8, 19):
        want = api.predict(trained["res"].ensemble, X[:n],
                           mapper=trained["res"].mapper, cfg=cfg4)
        got = m.score_binned(trained["res"].mapper.transform(X[:n]))
        assert np.array_equal(np.asarray(want), np.asarray(got)), n
    # Tier pinning: an int4 artifact refuses an int8 request (and vice
    # versa via the `pushed` fixture) — the carried tables ARE the
    # representation, so a different grid would falsify the manifest's
    # error bound.
    with pytest.raises(RegistryError, match="int4.*tier|tier"):
        load_servable(root, "m4", quantize="int8")
    # f32 restore from the same artifact still works (mode wins).
    rep32 = load_servable(root, "m4", quantize=False)
    assert rep32.mode == "aot-f32"
    assert rep32.model.predict_impl == "f32"
    assert out["digest"] != ""


def test_lut4_tables_fallback_serves_carried_representation(
        trained, tmp_path, monkeypatch):
    """An int4 artifact on a platform its lut4 blobs don't cover still
    serves the CARRIED int4 tables through the backend ladder
    (tables-fallback), not a re-quantization."""
    root = str(tmp_path / "reg4f")
    push_servable(root, _bundle(trained), name="m4",
                  max_batch=8, quantize="int4")
    art_dir, man, _ = Registry(root).get("m4")
    man2 = dict(man, lut_platforms=[])            # simulate foreign platform
    monkeypatch.setattr(Registry, "get",
                        lambda self, ref: (art_dir, man2, "f" * 16))
    rep = load_servable(root, "m4", quantize="int4", backend="tpu")
    assert rep.mode == "tables-fallback"
    m = rep.model
    assert m.quantize_tier == "int4"
    assert m.tables.leaf_dtype == "int4"
    # The seeded memo IS the dispatch source.
    assert m.compiled.quantize(leaf_dtype="int4") is m.tables
    m.warmup()
    assert m.predict_impl == "lut4"               # backend ladder resolved
    X = trained["X"]
    got = m.score_binned(trained["res"].mapper.transform(X[:8]))
    want = api.predict(trained["res"].ensemble, X[:8],
                       mapper=trained["res"].mapper,
                       cfg=trained["cfg"].replace(predict_impl="lut4"))
    assert np.allclose(np.asarray(got), np.asarray(want), atol=1e-6)


def test_restore_rejects_model_blob_swap(trained, pushed, tmp_path):
    """model.npz and the AOT programs must agree: an object whose model
    file was swapped for a DIFFERENT (valid, digest-consistent at the
    npz level) model fails the manifest token pin, not silently serves
    the wrong trees with the old programs' shapes."""
    root2 = str(tmp_path / "reg2")
    src = Registry(pushed["root"]).object_dir(pushed["digest"])
    dst = Registry(root2).object_dir(pushed["digest"])
    os.makedirs(os.path.dirname(dst), exist_ok=True)
    shutil.copytree(src, dst)
    other = api.train(trained["X"][:, :],
                      (trained["X"][:, 0] < 0).astype(np.float32),
                      n_trees=6, max_depth=3, n_bins=31, backend="tpu",
                      log_every=10**9)
    api.save_model(os.path.join(dst, "model.npz"), other.ensemble,
                   mapper=other.mapper)
    # File-level integrity catches it first (sha256 of model.npz).
    with pytest.raises(IntegrityError):
        load_servable(root2, pushed["digest"])


def test_zero_retrace_on_pretraced_buckets(trained, pushed):
    """The acceptance witness, in-process form: after warmup, scoring
    every exported bucket shape (and oversize chunked requests) causes
    ZERO further XLA compiles — the jit_compiles counter the smoke
    asserts from a genuinely cold process."""
    rep = load_servable(pushed["root"], "higgs", quantize=False)
    m = rep.model
    m.warmup()
    Xb = trained["res"].mapper.transform(trained["X"])
    tele_counters.install_jax_listener()
    before = tele_counters.snapshot()["jit_compiles"]
    for n in (1, 2, 3, 4, 8, 15, MAX_BATCH, 3 * MAX_BATCH):
        m.score_binned(Xb[:n])
    assert tele_counters.snapshot()["jit_compiles"] - before == 0


# --------------------------------------------------------------------- #
# engine integration: publish, digest stamping, swap by reference
# --------------------------------------------------------------------- #
def test_engine_serves_restored_model_and_stamps_digest(trained, pushed):
    rep = load_servable(pushed["root"], "higgs@1", quantize=False)
    rl = RunLog()
    eng = ServeEngine(rep.model, trained["cfg"], max_wait_ms=5.0,
                      max_batch=MAX_BATCH, run_log=rl)
    try:
        X = trained["X"]
        got = eng.predict(X[:5])
        want = api.predict(trained["res"].ensemble, X[:5],
                           mapper=trained["res"].mapper,
                           cfg=trained["cfg"])
        assert np.array_equal(np.asarray(want), np.asarray(got))
        out = eng.emit_latency(reset=True)
        assert out["artifact_digest"] == pushed["digest"]
        ev = rl.events("serve_latency")[-1]
        assert ev["artifact_digest"] == pushed["digest"]
        assert eng.health()["artifact_digest"] == pushed["digest"]
        assert eng.health()["aot"] is True
    finally:
        eng.close()


def test_swap_by_registry_reference(trained, pushed):
    """The HTTP /swap body path: a file path still works, and with a
    registry root a name@version reference restores + swaps — the
    hot_swap fault event carries both artifact digests."""
    rl = RunLog()
    eng = ServeEngine(_bundle(trained), trained["cfg"], max_wait_ms=5.0,
                      max_batch=MAX_BATCH, run_log=rl)
    try:
        with pytest.raises(ValueError, match="without --registry"):
            _swap(eng, "higgs@1")
        eng.registry_root = pushed["root"]
        out = _swap(eng, "higgs@1")
        assert out["artifact_digest"] == pushed["digest"]
        assert out["mode"] == "aot-f32"
        assert eng.model_token == trained["res"].ensemble.cache_token()
        ev = [e for e in rl.events("fault") if e["kind"] == "hot_swap"][-1]
        assert ev["new_artifact"] == pushed["digest"]
        assert ev["old_artifact"] is None
        # Scores after the swap come from the restored AOT model.
        got = eng.predict(trained["X"][:3])
        want = api.predict(trained["res"].ensemble, trained["X"][:3],
                           mapper=trained["res"].mapper,
                           cfg=trained["cfg"])
        assert np.array_equal(np.asarray(want), np.asarray(got))
        with pytest.raises(RegistryError):
            _swap(eng, "higgs@99")
    finally:
        eng.close()


def test_fallback_rebuild_on_foreign_platform(trained, pushed,
                                              monkeypatch):
    """The CPU-fallback ladder: when no AOT blob covers the serving
    platform, the loader rebuilds in-process from model.npz — same
    artifact, same answers, honestly reported as a rebuild."""
    import jax

    monkeypatch.setattr(jax, "default_backend", lambda: "neverland")
    rep = load_servable(pushed["root"], "higgs@1", quantize=False,
                        cfg=trained["cfg"])
    assert rep.mode == "rebuild"
    assert not rep.model.aot
    assert rep.model.artifact_digest == pushed["digest"]
    monkeypatch.undo()
    rep.model.warmup()
    got = rep.model.score_binned(
        trained["res"].mapper.transform(trained["X"][:6]))
    want = api.predict(trained["res"].ensemble, trained["X"][:6],
                       mapper=trained["res"].mapper, cfg=trained["cfg"])
    assert np.array_equal(np.asarray(want), np.asarray(got))


def test_tables_fallback_serves_carried_tables(trained, pushed,
                                               monkeypatch):
    """quantize=True on a platform no LUT blob covers: the loader still
    serves the CARRIED lut_tables.npz (token-pinned, memo-seeded into
    the compiled model so the backend's dispatch consumes it), never a
    re-quantization — the manifest's error bound keeps describing what
    actually serves."""
    import jax

    monkeypatch.setattr(jax, "default_backend", lambda: "neverland")
    rep = load_servable(pushed["root"], "higgs@1", quantize=True,
                        cfg=trained["cfg_lut"])
    assert rep.mode == "tables-fallback"
    assert not rep.model.aot
    monkeypatch.undo()
    assert rep.model.tables.token == rep.manifest["model_token"]
    assert rep.model.max_abs_err == \
        rep.manifest["quantized"]["max_abs_err"]
    # The seeded memo IS the dispatch source: quantize() returns the
    # carried object itself, so the backend cannot re-derive.
    assert rep.model.compiled.quantize() is rep.model.tables
    rep.model.warmup()
    got = rep.model.score_binned(
        trained["res"].mapper.transform(trained["X"][:6]))
    want = api.predict(trained["res"].ensemble, trained["X"][:6],
                       mapper=trained["res"].mapper,
                       cfg=trained["cfg_lut"])
    assert np.array_equal(np.asarray(want), np.asarray(got))


def test_stage_sweeps_stale_crash_litter(tmp_path):
    """A SIGKILLed pusher's stage never runs its cleanup; the next
    stage() reclaims it once it ages past the sweep threshold — without
    touching a fresh (possibly live) concurrent stage."""
    from ddt_tpu.registry import store as store_mod

    reg = Registry(str(tmp_path / "reg"))
    stale = reg.stage()
    old = time.time() - 2 * store_mod._STAGE_SWEEP_AGE_S
    os.utime(stale, (old, old))
    fresh = reg.stage()
    reg.stage()
    assert not os.path.isdir(stale)
    assert os.path.isdir(fresh)


def test_quantized_restore_without_lut_export_refused(trained, tmp_path):
    root = str(tmp_path / "reg")
    push_servable(root, _bundle(trained), name="f32only",
                  max_batch=8, quantize=False)
    with pytest.raises(ValueError, match="without the quantized"):
        load_servable(root, "f32only", quantize=True)


# --------------------------------------------------------------------- #
# telemetry: artifact events, report registry section, back-compat
# --------------------------------------------------------------------- #
def test_artifact_events_flow_into_report(trained, tmp_path):
    root = str(tmp_path / "reg")
    log_path = str(tmp_path / "run.jsonl")
    with RunLog(log_path) as rl:
        rl.emit("run_manifest", trainer="driver", backend="tpu",
                loss="logloss", n_trees=6, max_depth=3, rows=100,
                features=8, run_id="feedface0001")
        out = push_servable(root, _bundle(trained), name="m",
                            max_batch=8, run_id="feedface0001",
                            run_log=rl)
        load_servable(root, "m@1", quantize=False, run_log=rl)
        rl.emit("run_end", completed_rounds=6, wallclock_s=0.1)
    events = tele_report.read_events(log_path)
    summary = tele_report.summarize(events)
    r = summary["registry"]
    assert r["pushes"] == 1 and r["loads"] == 1
    assert r["digests"] == [out["digest"]]
    push_ev = next(e for e in r["events"] if e["action"] == "push")
    assert push_ev["name"] == "m" and push_ev["version"] == 1
    assert push_ev["same_run"] is True     # run_id joins to the manifest
    load_ev = next(e for e in r["events"] if e["action"] == "load")
    assert load_ev["mode"] == "aot-f32"
    text = tele_report.render(summary)
    assert "registry: 1 push(es), 1 load(s)" in text
    assert out["digest"] in text
    assert "(this run)" in text


def test_v4_logs_still_parse(tmp_path):
    """Back-compat: a pre-registry (schema v4) log reads through report
    with registry=None — no required field changed."""
    p = str(tmp_path / "v4.jsonl")
    recs = [
        {"event": "run_manifest", "schema": 4, "t": 1.0, "seq": 0,
         "trainer": "driver", "backend": "tpu", "loss": "logloss",
         "n_trees": 2, "max_depth": 3, "rows": 10, "features": 4},
        {"event": "serve_latency", "schema": 4, "t": 2.0, "seq": 1,
         "requests": 5, "p50_ms": 1.0, "p99_ms": 2.0,
         "model_token": "abc123"},
        {"event": "run_end", "schema": 4, "t": 3.0, "seq": 2,
         "completed_rounds": 2, "wallclock_s": 0.5},
    ]
    with open(p, "w", encoding="utf-8") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
    summary = tele_report.summarize(tele_report.read_events(p))
    assert summary["registry"] is None
    assert summary["serving"]["requests"] == 5
    tele_report.render(summary)


# --------------------------------------------------------------------- #
# CLI round trip
# --------------------------------------------------------------------- #
def test_cli_registry_workflow(trained, tmp_path, capsys):
    model = str(tmp_path / "model.npz")
    root = str(tmp_path / "reg")
    api.save_model(model, trained["res"].ensemble,
                   mapper=trained["res"].mapper, cfg=trained["cfg"])
    assert cli.main(["registry", "--registry", root, "push",
                     "--model", model, "--name", "cli-model",
                     "--max-batch", "8"]) == 0
    push = json.loads(capsys.readouterr().out)
    assert push["version"] == 1 and len(push["digest"]) == 16
    assert cli.main(["registry", "--registry", root, "tag",
                     "cli-model@1", "prod"]) == 0
    capsys.readouterr()
    assert cli.main(["registry", "--registry", root, "list",
                     "--json"]) == 0
    inv = json.loads(capsys.readouterr().out)
    assert inv["names"]["cli-model"]["tags"] == {"prod": 1}
    assert cli.main(["registry", "--registry", root, "get",
                     "cli-model@prod"]) == 0
    got = json.loads(capsys.readouterr().out)
    assert got["digest"] == push["digest"]
    assert got["manifest"]["kind"] == "servable"
    assert got["manifest"]["buckets"] == [1, 2, 4, 8]
    # Idempotent re-push: same content, same version.
    assert cli.main(["registry", "--registry", root, "push",
                     "--model", model, "--name", "cli-model",
                     "--max-batch", "8"]) == 0
    assert json.loads(capsys.readouterr().out)["version"] == 1
    # Unknown reference exits cleanly with the CLI's message, not a
    # traceback.
    with pytest.raises(SystemExit, match="registry get"):
        cli.main(["registry", "--registry", root, "get", "ghost@9"])
