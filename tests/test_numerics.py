"""bf16-vs-f32 histogram-input training quality (round-1 verdict, Weak #4).

On real TPU the default path rounds per-row gradients to bfloat16 before MXU
accumulation (cfg.matmul_input_dtype="bfloat16"); CI runs on CPU where
hist_impl="auto" resolves to the exact segment path, so round 1 never
compared bf16-input training against f32 on the SAME backend. These tests
force the matmul implementation (which honors matmul_input_dtype on every
platform) and pin the end-model quality delta.
"""

import numpy as np

from ddt_tpu import api
from ddt_tpu.config import TrainConfig
from ddt_tpu.data.datasets import synthetic_binary
from ddt_tpu.data.quantizer import quantize
from ddt_tpu.utils.metrics import evaluate


def _train_auc(input_dtype: str, Xb, Xv, y, yv):
    cfg = TrainConfig(
        n_trees=20, max_depth=5, n_bins=63, backend="tpu",
        hist_impl="matmul", matmul_input_dtype=input_dtype, seed=0,
    )
    res = api.train(Xb, y, cfg, binned=True, log_every=10**9)
    raw = res.ensemble.predict_raw(Xv, binned=True)
    return evaluate("auc", yv, raw), res.ensemble


def test_bf16_histogram_inputs_match_f32_auc():
    """Held-out AUC with bf16 matmul inputs must sit within a tight band of
    the f32-exact run: bf16 rounding perturbs bin sums by ~2^-8 relative,
    far below split-decision margins on real signal, and the bf16-rounded
    deterministic tie-break absorbs selection noise. Pin the delta so a
    future kernel change that degrades accumulation shows up here."""
    X, y = synthetic_binary(12000, n_features=10, seed=5)
    Xb, mapper = quantize(X, n_bins=63, seed=5)
    tr, va = Xb[:9000], Xb[9000:]
    ytr, yva = y[:9000], y[9000:]

    auc16, ens16 = _train_auc("bfloat16", tr, va, ytr, yva)
    auc32, ens32 = _train_auc("float32", tr, va, ytr, yva)

    assert auc32 > 0.75          # the task is learnable at all
    # Measured delta on this config: < 0.003 absolute AUC. Band of 0.01
    # allows seed-level wiggle while catching real accumulation damage.
    assert abs(auc16 - auc32) < 0.01, (auc16, auc32)

    # Tree STRUCTURE legitimately diverges below any node where bf16
    # rounding flips a near-tie (and the whole subtree then differs), so
    # whole-tree agreement is not a meaningful invariant — measured ~72%
    # here. Root splits see the largest margins and must agree.
    root_agree = (ens16.feature[:, 0] == ens32.feature[:, 0]).mean()
    assert root_agree == 1.0, root_agree


def test_f32_matmul_inputs_match_segment_exactly():
    """matmul_input_dtype=float32 (Precision.HIGHEST) is EXACT on the
    compare path: identical trees to the segment-sum implementation."""
    X, y = synthetic_binary(4000, n_features=8, seed=9)
    Xb, _ = quantize(X, n_bins=63, seed=9)
    kw = dict(n_trees=6, max_depth=4, n_bins=63, backend="tpu", seed=9)
    e_mm = api.train(
        Xb, y, TrainConfig(hist_impl="matmul",
                           matmul_input_dtype="float32", **kw),
        binned=True, log_every=10**9).ensemble
    e_seg = api.train(
        Xb, y, TrainConfig(hist_impl="segment", **kw),
        binned=True, log_every=10**9).ensemble
    np.testing.assert_array_equal(e_mm.feature, e_seg.feature)
    np.testing.assert_array_equal(e_mm.threshold_bin, e_seg.threshold_bin)
    np.testing.assert_allclose(e_mm.leaf_value, e_seg.leaf_value,
                               rtol=2e-4, atol=2e-5)


def test_64bin_contract_quality():
    """The 64-bin opt-in speed contract (transposed kernel, docs/PERF.md
    round-3): coarser quantiles must cost little accuracy — held-out AUC
    within 0.01 of the 255-bin model on the synthetic Higgs config."""
    from ddt_tpu import api
    from ddt_tpu.data import datasets
    from ddt_tpu.utils.metrics import auc

    X, y = datasets.synthetic_binary(12000, seed=4)
    Xt, yt, Xv, yv = X[:9000], y[:9000], X[9000:], y[9000:]

    def fit_auc(bins):
        res = api.train(Xt, yt, n_trees=20, max_depth=5, n_bins=bins,
                        backend="cpu", log_every=10**9)
        return auc(yv, api.predict(res.ensemble, Xv, mapper=res.mapper,
                                   raw=True))

    a255 = fit_auc(255)
    a64 = fit_auc(64)
    assert a255 > 0.75                      # the config separates at all
    assert a64 > a255 - 0.01, (a64, a255)   # knob costs < 1 AUC point here
