"""Distributed flight recorder (docs/OBSERVABILITY.md): per-partition
attribution on a CPU mesh, the cross-host run-log merge, the Perfetto
trace-event export, and the benchwatch regression sentinel. CPU
platform, tier-1; the 8-virtual-device mesh comes from conftest."""

import copy
import json
import os

import numpy as np
import pytest

from ddt_tpu import api
from ddt_tpu.config import TrainConfig
from ddt_tpu.telemetry import merge, perfetto, report
from ddt_tpu.telemetry.events import (
    PartitionRecorder, RunLog, partition_skew_summary)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _binary(rows, features=7, bins=29, seed=0):
    rng = np.random.default_rng(seed)
    Xb = rng.integers(0, bins, size=(rows, features), dtype=np.uint8)
    y = (Xb[:, 0] > bins // 2).astype(np.float32)
    return Xb, y


def _recompute_skew(events):
    """Offline recompute of the skew reduction from the raw
    partition_phases stream — the acceptance contract: the emitted
    partition_skew must equal this."""
    totals = {}
    for e in events:
        if e["event"] != "partition_phases":
            continue
        for part in e["partitions"]:
            d = totals.setdefault(part["device"], {})
            for ph, ms in part["phases"].items():
                d[ph] = d.get(ph, 0.0) + ms
    return partition_skew_summary(totals)


# --------------------------------------------------------------------- #
# per-partition attribution (tentpole part 1)
# --------------------------------------------------------------------- #
def test_mesh_dryrun_partition_skew_matches_offline_recompute(tmp_path):
    """The acceptance criterion: a 4-partition CPU-mesh run produces a
    log whose partition_skew matches per-partition timings recomputed
    offline from the partition_phases events."""
    Xb, y = _binary(2048)
    path = str(tmp_path / "mesh.jsonl")
    with RunLog(path) as rl:
        api.train(Xb, y, binned=True, n_trees=4, max_depth=3, n_bins=29,
                  backend="tpu", n_partitions=4, run_log=rl)
    events = report.read_events(path)
    pp = [e for e in events if e["event"] == "partition_phases"]
    assert pp, "mesh run with a run log must emit partition_phases"
    for e in pp:
        devs = [p["device"] for p in e["partitions"]]
        assert devs == sorted(devs) and len(devs) == 4
        for p in e["partitions"]:
            assert p["hist_allreduce_bytes"] > 0
            assert all(ms >= 0 for ms in p["phases"].values())
    skew = [e for e in events if e["event"] == "partition_skew"]
    assert len(skew) == 1
    assert skew[-1]["n_partitions"] == 4
    recomputed = _recompute_skew(events)
    emitted = skew[-1]["phases"]
    assert [p["phase"] for p in emitted] == [p["phase"]
                                             for p in recomputed]
    for a, b in zip(emitted, recomputed):
        assert a["ms_max"] == pytest.approx(b["ms_max"], abs=0.01)
        assert a["ms_median"] == pytest.approx(b["ms_median"], abs=0.01)
        assert a["max_device"] == b["max_device"]
    # the manifest carries the v2 merge keys
    man = events[0]
    assert man["event"] == "run_manifest"
    assert len(man["run_id"]) == 12 and man["host"] == 0
    # ...and the report renders a straggler table from it
    summary = report.summarize(events)
    assert summary["n_partitions"] == 4
    assert summary["partition_skew"] == emitted
    assert "partitions (4 lanes" in report.render(summary)


def test_streaming_mesh_run_emits_partition_lanes(tmp_path):
    """The streaming device trainer's chunk passes carry per-partition
    lanes too (hist/leaf/roundstart phases)."""
    from ddt_tpu.streaming import fit_streaming

    Xb, y = _binary(960, seed=3)
    bounds = [0, 480, 960]

    def chunk_fn(c):
        return Xb[bounds[c]:bounds[c + 1]], y[bounds[c]:bounds[c + 1]]

    cfg = TrainConfig(n_trees=2, max_depth=3, n_bins=29, backend="tpu",
                      n_partitions=2)
    path = str(tmp_path / "stream.jsonl")
    with RunLog(path) as rl:
        fit_streaming(chunk_fn, 2, cfg, run_log=rl)
    events = report.read_events(path)
    pp = [e for e in events if e["event"] == "partition_phases"]
    assert len(pp) == 2                       # one per round
    phases = {ph for e in pp for p in e["partitions"]
              for ph in p["phases"]}
    assert "hist" in phases and "leaf" in phases
    assert "roundstart" in phases             # round 2's fused start pass
    skew = [e for e in events if e["event"] == "partition_skew"]
    assert skew and skew[-1]["n_partitions"] == 2
    assert _recompute_skew(events)[0]["phase"] == \
        skew[-1]["phases"][0]["phase"]


def test_disabled_telemetry_never_probes_shards(monkeypatch):
    """PR-2 invariant extended to the new collectors: with no run log, a
    DISTRIBUTED fit must never touch the shard probe (the probe is a
    device barrier) nor construct partition events."""
    from ddt_tpu.parallel import mesh as mesh_lib

    def _boom(*a, **k):
        raise AssertionError("shard probe touched with telemetry off")

    monkeypatch.setattr(mesh_lib, "shard_ready_times", _boom)
    Xb, y = _binary(1024, seed=5)
    res = api.train(Xb, y, binned=True, n_trees=2, max_depth=3,
                    n_bins=29, backend="tpu", n_partitions=2)
    assert res.ensemble.n_trees == 2


def test_partition_recorder_inert_without_mesh_or_log():
    class Backend:
        distributed = True

        def partition_ready_ms(self, h):      # pragma: no cover
            raise AssertionError("probed")

    # no run log -> inactive even on a distributed backend
    rec = PartitionRecorder(None, Backend())
    assert not rec.active
    rec.observe("grow", object(), 0.0)        # no probe, no error
    rec.flush_round(0)
    rec.emit_skew()
    # run log but single-device backend -> inactive
    class Single:
        distributed = False

        def partition_ready_ms(self, h):      # pragma: no cover
            raise AssertionError("probed")

    rec = PartitionRecorder(RunLog(), Single())
    assert not rec.active


def test_partition_skew_summary_reduction():
    totals = {0: {"grow": 10.0, "eval": 1.0},
              1: {"grow": 30.0, "eval": 1.0},
              2: {"grow": 20.0, "eval": 4.0}}
    out = partition_skew_summary(totals)
    assert [p["phase"] for p in out] == ["grow", "eval"]   # by ms_max
    grow = out[0]
    assert grow["ms_max"] == 30.0 and grow["max_device"] == 1
    assert grow["ms_median"] == 20.0
    assert grow["skew"] == pytest.approx(1.5)
    ev = out[1]
    assert ev["ms_max"] == 4.0 and ev["max_device"] == 2
    assert ev["ms_median"] == 1.0 and ev["skew"] == 4.0


# --------------------------------------------------------------------- #
# cross-host merge (tentpole part 3)
# --------------------------------------------------------------------- #
def _fabricate_two_hosts(tmp_path, offset_s=5.25):
    """One real single-host run log + a fabricated host-1 twin whose
    clock runs `offset_s` ahead and whose rounds interleave."""
    Xb, y = _binary(1200, seed=7)
    p0 = str(tmp_path / "host0.jsonl")
    with RunLog(p0) as rl:
        api.train(Xb, y, binned=True, n_trees=3, max_depth=3, n_bins=29,
                  backend="cpu", run_log=rl)
    ev0 = report.read_events(p0)
    p1 = str(tmp_path / "host1.jsonl")
    with open(p1, "w", encoding="utf-8") as f:
        for e in ev0:
            e2 = copy.deepcopy(e)
            e2["t"] += offset_s                # skewed wall clock
            e2["host"] = 1
            if e2["event"] == "round":         # a straggling host
                e2["ms_per_round"] += 1.0
            f.write(json.dumps(e2) + "\n")
    return p0, p1, ev0


def test_two_host_merge_offset_and_deterministic_order(tmp_path):
    p0, p1, ev0 = _fabricate_two_hosts(tmp_path)
    merged = merge.merge_paths([p0, p1])
    assert len(merged) == 2 * len(ev0)
    # clock offset estimated away: both manifests land at (near) the
    # same adjusted time, far closer than the fabricated 5.25 s skew
    mans = [e for e in merged if e["event"] == "run_manifest"]
    assert len(mans) == 2
    assert abs(mans[0]["t"] - mans[1]["t"]) < 1e-6
    # deterministic: argument order cannot change the merged stream
    key = [(e["event"], e["host"], round(e["t"], 6), e["seq"])
           for e in merged]
    swapped = merge.merge_paths([p1, p0])
    assert key == [(e["event"], e["host"], round(e["t"], 6), e["seq"])
                   for e in swapped]
    # times are monotone and rounds interleave host 0/1 adjacently
    ts = [e["t"] for e in merged]
    assert ts == sorted(ts)
    rounds = [(e["round"], e["host"]) for e in merged
              if e["event"] == "round"]
    assert rounds == [(r, h) for r in (1, 2, 3) for h in (0, 1)]


def test_merge_refuses_mismatched_run_ids(tmp_path):
    p0, p1, _ = _fabricate_two_hosts(tmp_path)
    other = str(tmp_path / "other.jsonl")
    evs = report.read_events(p1)
    with open(other, "w", encoding="utf-8") as f:
        for e in evs:
            e2 = dict(e)
            if e2["event"] == "run_manifest":
                e2["run_id"] = "feedfeedfeed"
            f.write(json.dumps(e2) + "\n")
    with pytest.raises(ValueError, match="different runs"):
        merge.merge_paths([p0, other])


def test_merged_report_single_segment_and_one_curve(tmp_path, capsys):
    from ddt_tpu.cli import main

    p0, p1, _ = _fabricate_two_hosts(tmp_path)
    rc = main(["report", "--log", p0, "--log", p1, "--json"])
    assert rc == 0
    summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert summary["n_runs_in_log"] == 1      # two hosts, ONE run
    assert summary["hosts"] == [0, 1]
    assert summary["n_round_records"] == 3    # one lane's curve, not 6
    rc = main(["report", "--log", p0, "--log", p1])
    assert rc == 0
    assert "hosts: 2 merged" in capsys.readouterr().out


def test_merged_straggler_table_covers_every_host(tmp_path):
    """On a merged pod log each host's partition_skew covers only its
    own devices — the report must recompute the table over EVERY host's
    partition_phases lanes, so a straggler on host 0 stays visible (and
    the fused `rounds` extra counts rounds, not events)."""
    def host_log(path, host, t0, grow_ms):
        with open(path, "w", encoding="utf-8") as f:
            recs = [
                {"event": "run_manifest", "trainer": "driver",
                 "backend": "tpu", "loss": "logloss", "n_trees": 3,
                 "max_depth": 3, "rows": 64, "features": 4,
                 "run_id": "aaaabbbbcccc", "host": host},
                {"event": "partition_phases", "round": 1, "rounds": 3,
                 "partitions": [
                     {"device": host * 2 + d,
                      "phases": {"grow_block": grow_ms[d]},
                      "hist_allreduce_bytes": 128} for d in (0, 1)]},
                {"event": "partition_skew", "n_partitions": 2,
                 "phases": [{"phase": "grow_block",
                             "ms_max": max(grow_ms),
                             "ms_median": sum(grow_ms) / 2,
                             "skew": 1.0,
                             "max_device": host * 2}]},
                {"event": "run_end", "completed_rounds": 3,
                 "wallclock_s": 1.0},
            ]
            for i, r in enumerate(recs):
                f.write(json.dumps({"schema": 2, "t": t0 + i * 0.1,
                                    "seq": i, **r}) + "\n")

    p0 = str(tmp_path / "h0.jsonl")
    p1 = str(tmp_path / "h1.jsonl")
    host_log(p0, 0, 100.0, [50.0, 90.0])      # host 0 holds the straggler
    host_log(p1, 1, 104.5, [10.0, 20.0])
    summary = report.summarize(merge.merge_paths([p0, p1]))
    assert summary["n_partitions"] == 4       # all lanes, both hosts
    assert summary["partition_rounds_observed"] == 3   # rounds, not events
    row = summary["partition_skew"][0]
    assert row["phase"] == "grow_block"
    assert row["ms_max"] == 90.0
    assert (row["max_host"], row["max_device"]) == (0, 1)
    assert row["ms_median"] == pytest.approx(35.0)     # median of 4 lanes
    text = report.render(summary)
    assert "@h0/dev1" in text


def test_single_log_from_nonzero_host_keeps_partition_rounds(tmp_path):
    """A lone pod host's UN-merged log (manifest host=N, events carry no
    host field) must still count its partition rounds and use its own
    skew event verbatim."""
    p = str(tmp_path / "h2.jsonl")
    with open(p, "w", encoding="utf-8") as f:
        recs = [
            {"event": "run_manifest", "trainer": "driver",
             "backend": "tpu", "loss": "logloss", "n_trees": 2,
             "max_depth": 3, "rows": 64, "features": 4,
             "run_id": "aaaabbbbcccc", "host": 2},
            {"event": "partition_phases", "round": 1, "rounds": 2,
             "partitions": [{"device": 4, "phases": {"grow_block": 5.0},
                             "hist_allreduce_bytes": 64},
                            {"device": 5, "phases": {"grow_block": 7.0},
                             "hist_allreduce_bytes": 64}]},
            {"event": "partition_skew", "n_partitions": 2,
             "phases": [{"phase": "grow_block", "ms_max": 7.0,
                         "ms_median": 6.0, "skew": 1.167,
                         "max_device": 5}]},
            {"event": "run_end", "completed_rounds": 2,
             "wallclock_s": 1.0},
        ]
        for i, r in enumerate(recs):
            f.write(json.dumps({"schema": 2, "t": 10.0 + i, "seq": i,
                                **r}) + "\n")
    summary = report.summarize(report.read_events(p))
    assert summary["hosts"] == [2]
    assert summary["partition_rounds_observed"] == 2
    assert summary["n_partitions"] == 2
    assert summary["partition_skew"][0]["max_device"] == 5


def test_merge_hostless_v1_logs_stays_deterministic(tmp_path):
    """Pre-v2 logs (no host/run_id stamps): host labels come from
    manifest-time rank, so swapping the file arguments cannot change
    the merged stream."""
    def v1_log(path, t0):
        with open(path, "w", encoding="utf-8") as f:
            recs = [
                {"event": "run_manifest", "trainer": "driver",
                 "backend": "cpu", "loss": "logloss", "n_trees": 1,
                 "max_depth": 3, "rows": 8, "features": 2},
                {"event": "round", "round": 1, "ms_per_round": 2.0,
                 "train_loss": None},
                {"event": "run_end", "completed_rounds": 1,
                 "wallclock_s": 0.1},
            ]
            for i, r in enumerate(recs):
                f.write(json.dumps({"schema": 1, "t": t0 + i * 0.1,
                                    "seq": i, **r}) + "\n")
    pa = str(tmp_path / "a.jsonl")
    pb = str(tmp_path / "b.jsonl")
    v1_log(pa, 50.0)
    v1_log(pb, 57.0)
    key = [(e["event"], e["host"], round(e["t"], 6), e["seq"])
           for e in merge.merge_paths([pa, pb])]
    assert key == [(e["event"], e["host"], round(e["t"], 6), e["seq"])
                   for e in merge.merge_paths([pb, pa])]
    # the earlier-manifest log is host 0 either way
    assert key[0][1] == 0


def test_benchwatch_unknown_current_fails_loudly(tmp_path, capsys):
    paths = [_bench_artifact(tmp_path, i + 1, value=50.0)
             for i in range(4)]
    junk = tmp_path / "torn.json"
    junk.write_text(json.dumps({"something": "else"}))
    rep = benchwatch.run(paths, current_path=str(junk))
    assert not rep["ok"] and "unrecognized" in rep["error"]
    assert bw_main([*paths, "--current", str(junk)]) == 1
    assert "ERROR" in capsys.readouterr().out


def test_same_host_restart_still_two_segments(tmp_path):
    """A preemptible restart appends a second segment with the SAME
    config-deterministic run_id on the SAME host — that must stay two
    segments, not collapse into a pod merge."""
    Xb, y = _binary(800, seed=11)
    path = str(tmp_path / "restart.jsonl")
    for _ in range(2):
        with RunLog(path) as rl:
            api.train(Xb, y, binned=True, n_trees=2, max_depth=3,
                      n_bins=29, backend="cpu", run_log=rl)
    summary = report.summarize(report.read_events(path))
    assert summary["n_runs_in_log"] == 2
    assert summary["n_round_records"] == 2    # last segment only


# --------------------------------------------------------------------- #
# perfetto export (tentpole part 2)
# --------------------------------------------------------------------- #
_PH_KNOWN = {"X", "i", "M"}


def _validate_trace(trace):
    """The trace-event field contract ui.perfetto.dev's importer needs:
    JSON object form, every record fully typed."""
    assert set(trace) == {"traceEvents", "displayTimeUnit"}
    assert trace["displayTimeUnit"] == "ms"
    assert trace["traceEvents"], "empty trace"
    for rec in trace["traceEvents"]:
        assert isinstance(rec["name"], str) and rec["name"]
        assert rec["ph"] in _PH_KNOWN
        assert isinstance(rec["ts"], (int, float)) and rec["ts"] >= 0
        assert isinstance(rec["pid"], int)
        assert isinstance(rec["tid"], int)
        if rec["ph"] == "X":
            assert isinstance(rec["dur"], (int, float)) and rec["dur"] >= 0
        if rec["ph"] == "M":
            assert rec["name"] in ("process_name", "thread_name")
            assert isinstance(rec["args"]["name"], str)


def test_trace_export_mesh_run_has_partition_lanes(tmp_path):
    Xb, y = _binary(2048, seed=13)
    path = str(tmp_path / "mesh.jsonl")
    with RunLog(path) as rl:
        api.train(Xb, y, binned=True, n_trees=3, max_depth=3, n_bins=29,
                  backend="tpu", n_partitions=4, run_log=rl)
    events = report.read_events(path)
    trace = perfetto.to_trace_events(events)
    _validate_trace(trace)
    recs = trace["traceEvents"]
    # round slices on tid 0, partition lanes on tids 1..4
    assert any(r["ph"] == "X" and r["tid"] == 0
               and r["name"].startswith("round ") for r in recs)
    lane_tids = {r["tid"] for r in recs
                 if r["ph"] == "X" and r["name"].startswith("ddt:")}
    assert lane_tids == {1, 2, 3, 4}
    lanes = [r for r in recs if r["ph"] == "X"
             and r["name"].startswith("ddt:")]
    assert all(r["args"]["hist_allreduce_bytes"] > 0 for r in lanes)
    # durations in the lanes equal the logged per-phase ms (µs scale)
    pp = [e for e in events if e["event"] == "partition_phases"][0]
    dev0 = pp["partitions"][0]
    got = [r for r in lanes if r["args"]["device"] == 0
           and r["args"]["round"] == pp["round"]]
    assert sorted(r["dur"] for r in got) == pytest.approx(
        sorted(ms * 1e3 for ms in dev0["phases"].values()))


def test_trace_cli_merged_two_hosts_parses(tmp_path, capsys):
    from ddt_tpu.cli import main

    p0, p1, _ = _fabricate_two_hosts(tmp_path)
    out = str(tmp_path / "trace.json")
    rc = main(["trace", "--log", p0, "--log", p1, "--out", out])
    assert rc == 0
    line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert line["out"] == out and line["trace_events"] > 0
    with open(out, encoding="utf-8") as f:
        trace = json.load(f)                  # asserts it parses
    _validate_trace(trace)
    pids = {r["pid"] for r in trace["traceEvents"]}
    assert pids == {0, 1}                     # one process per host
    names = {r["args"]["name"] for r in trace["traceEvents"]
             if r["ph"] == "M" and r["name"] == "process_name"}
    assert len(names) == 2


def test_trace_cli_fails_loudly_on_garbage(tmp_path):
    from ddt_tpu.cli import main

    bad = tmp_path / "bad.jsonl"
    bad.write_text('{"event": "nonsense", "schema": 1, "t": 0, "seq": 0}\n')
    with pytest.raises(SystemExit, match="trace:"):
        main(["trace", "--log", str(bad), "--out",
              str(tmp_path / "t.json")])


# --------------------------------------------------------------------- #
# benchwatch (tentpole part 4)
# --------------------------------------------------------------------- #
from tools import benchwatch  # noqa: E402
from tools.benchwatch.__main__ import main as bw_main  # noqa: E402


def _bench_artifact(tmp_path, n, **metrics):
    rec = {"metric": "higgs1m_histogram_throughput", **metrics}
    p = tmp_path / f"BENCH_r{n:02d}.json"
    p.write_text(json.dumps({"n": n, "rc": 0, "parsed": rec}))
    return str(p)


def test_benchwatch_flags_30pct_histogram_regression(tmp_path):
    vals = [55.0, 57.0, 56.3, 45.0, 47.9]
    paths = [_bench_artifact(tmp_path, i + 1, value=v,
                             e2e_train_s=12.0 + 0.1 * i)
             for i, v in enumerate(vals)]
    med = sorted(vals)[2]
    bad = _bench_artifact(tmp_path, 6, value=round(med * 0.7, 2),
                          e2e_train_s=12.2)
    rep = benchwatch.run(paths, current_path=bad)
    assert not rep["ok"]
    names = [r["metric"] for r in rep["bench"]["regressions"]]
    assert names == ["value"]
    # the same history with an in-band current passes
    good = _bench_artifact(tmp_path, 7, value=med, e2e_train_s=12.1)
    assert benchwatch.run(paths, current_path=good)["ok"]


def test_benchwatch_one_sided_and_direction_aware(tmp_path):
    paths = [_bench_artifact(tmp_path, i + 1, value=50.0 + i,
                             e2e_train_s=12.0)
             for i in range(4)]
    # pleasantly fast run (value up, time down) never fails
    fast = _bench_artifact(tmp_path, 5, value=200.0, e2e_train_s=3.0)
    assert benchwatch.run(paths, current_path=fast)["ok"]
    # a LOWER-is-better metric regresses upward
    slow = _bench_artifact(tmp_path, 6, value=51.0, e2e_train_s=30.0)
    rep = benchwatch.run(paths, current_path=slow)
    assert [r["metric"] for r in rep["bench"]["regressions"]] \
        == ["e2e_train_s"]


def test_benchwatch_schema_gates_redefined_metrics(tmp_path):
    """A metric whose MEANING changed at a schema bump
    (METRIC_MIN_SCHEMA) must not band against pre-bump history: the v2
    e2e_implied_hist_mrows counts effective levels (~0.58x the v1
    number at depth 6 with subtraction on), so a faster run would
    otherwise flag as a regression. Same-schema banding still works."""
    paths = [_bench_artifact(tmp_path, i + 1,
                             e2e_implied_hist_mrows=50.0 + i)
             for i in range(4)]                          # schema-1 history
    # v2 current: ~0.6x the v1 median — semantics, not a regression.
    cur = _bench_artifact(tmp_path, 5, bench_schema=2,
                          e2e_implied_hist_mrows=30.0)
    rep = benchwatch.run(paths, current_path=cur)
    assert rep["ok"]
    assert {"metric": "e2e_implied_hist_mrows", "history": 0} \
        in rep["bench"]["skipped"]
    # once schema-2 history accumulates, the band re-arms at the new
    # meaning and a real regression inside it still trips.
    paths2 = [_bench_artifact(tmp_path, 10 + i, bench_schema=2,
                              e2e_implied_hist_mrows=30.0 + i)
              for i in range(4)]
    bad = _bench_artifact(tmp_path, 15, bench_schema=2,
                          e2e_implied_hist_mrows=18.0)
    rep = benchwatch.run(paths2, current_path=bad)
    assert [r["metric"] for r in rep["bench"]["regressions"]] \
        == ["e2e_implied_hist_mrows"]


def test_benchwatch_skips_thin_history_never_guesses(tmp_path):
    paths = [_bench_artifact(tmp_path, 1, value=50.0,
                             predict_mrows_per_sec=2.7)]
    cur = _bench_artifact(tmp_path, 2, value=49.0,
                          predict_mrows_per_sec=0.1)
    rep = benchwatch.run(paths, current_path=cur)
    assert rep["ok"]
    skipped = {s["metric"] for s in rep["bench"]["skipped"]}
    assert {"value", "predict_mrows_per_sec"} <= skipped


def test_benchwatch_multichip_failure_flags(tmp_path):
    p = tmp_path / "MULTICHIP_r01.json"
    p.write_text(json.dumps({"n_devices": 8, "rc": 1, "ok": False,
                             "skipped": False, "tail": "boom"}))
    rep = benchwatch.run([str(p)])
    assert not rep["ok"]
    assert rep["multichip"][0]["regressions"]
    # a skipped run (no chips on this host) is not a regression
    p.write_text(json.dumps({"n_devices": 0, "rc": 0, "ok": False,
                             "skipped": True, "tail": ""}))
    assert benchwatch.run([str(p)])["ok"]


def test_benchwatch_passes_on_real_repo_history():
    """The acceptance criterion's other half: the shipped BENCH_r01-r05
    + MULTICHIP_r01-r05 artifacts pass the sentinel as-is."""
    paths = benchwatch.collect_default_paths(REPO)
    assert len(paths) >= 10
    rep = benchwatch.run(paths)
    assert rep["ok"], rep
    assert rep["bench"]["checked"], "no metric had banding history"


def test_benchwatch_cli_exit_codes(tmp_path, capsys, monkeypatch):
    paths = [_bench_artifact(tmp_path, i + 1, value=50.0)
             for i in range(4)]
    bad = _bench_artifact(tmp_path, 9, value=10.0)
    assert bw_main([*paths, "--current", bad]) == 1
    assert "REGRESSION value" in capsys.readouterr().out
    assert bw_main(paths) == 0
    empty = tmp_path / "empty"
    empty.mkdir()
    monkeypatch.chdir(empty)
    assert bw_main([]) == 2                   # nothing to check


def test_trace_smoke_script():
    """`make trace-smoke` run in-process: mesh train -> merge -> export
    -> parse (tier-1-safe; conftest's 8-device mesh covers the 2 the
    script asks for)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "trace_smoke", os.path.join(REPO, "scripts", "trace_smoke.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.main() == 0


# --------------------------------------------------------------------- #
# bench stamping (satellite) + host RSS (satellite)
# --------------------------------------------------------------------- #
def test_bench_artifact_stamping_fields():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "root_bench", os.path.join(REPO, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    rev = mod._git_rev()
    assert rev is None or (isinstance(rev, str) and len(rev) >= 7)
    assert isinstance(mod.BENCH_SCHEMA, int)
    src = open(os.path.join(REPO, "bench.py"), encoding="utf-8").read()
    for field in ('"run_id"', '"bench_schema"', '"git_rev"'):
        assert field in src


def test_host_rss_counter_recorded_and_rendered(tmp_path):
    from ddt_tpu.telemetry import counters as tele_counters

    rss = tele_counters.host_peak_rss_bytes()
    assert rss is None or rss > 1 << 20       # a python process is >1 MiB
    Xb, y = _binary(700, seed=17)
    path = str(tmp_path / "rss.jsonl")
    with RunLog(path) as rl:
        api.train(Xb, y, binned=True, n_trees=2, max_depth=3, n_bins=29,
                  backend="cpu", run_log=rl)
    events = report.read_events(path)
    c = [e for e in events if e["event"] == "counters"][-1]
    assert "host_peak_rss_bytes" in c
    assert c["host_peak_rss_bytes"] is None \
        or c["host_peak_rss_bytes"] > 1 << 20
    text = report.render(report.summarize(events))
    assert "host_rss_peak=" in text
