"""True multi-process distributed execution (round-2 verdict, item 1).

Everything else multi-device in this suite runs in ONE process over
virtual devices; these tests spawn real OS processes (two, and four for
the wider DCN-axis case), wire them with jax.distributed.initialize
(coordinator bootstrap over localhost, gloo CPU collectives), train over
(hosts=N, rows=2) pod meshes built from the GLOBAL device list, and
assert the fetched ensembles are bit-identical across processes AND to a
single-process run of the identical mesh shape.
This is the process-level failure surface a virtual mesh cannot reach:
per-process device visibility, cross-process psum, non-addressable-shard
placement (TPUDevice._put), replicated-output fetch (fetch_tree /
eval_round's all_gather path), and fit_streaming's per-(chunk, level)
device placement over on-disk shards (round-3 verdict item 4).

Contract: SURVEY.md §5 "Distributed communication backend"
("jax.distributed.initialize for the v5e-64 pod config"), BASELINE
config 5.
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "mp_worker.py")

# Capability gate (ISSUE 11 satellite): some images' XLA CPU builds
# cannot run true multi-process programs at all — every collective
# compile fails with this exact runtime error. That is an environment
# capability, not a regression in this repo, so the tests SKIP with the
# error quoted (tier-1 stays green-or-meaningful) instead of carrying a
# known failure into every PR's triage; where the runtime supports
# multi-process CPU (gloo) or a real pod, they run fully.
_MP_CPU_ERR = "Multiprocess computations aren't implemented on the CPU backend"


def _skip_if_multiprocess_unsupported(logs) -> None:
    joined = "\n".join(logs)
    if _MP_CPU_ERR in joined:
        pytest.skip(
            "XLA capability gate: this image's CPU backend refuses "
            f"multi-process programs (worker failed with: {_MP_CPU_ERR!r})")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _spawn(coord, nproc, pid, dev_per_proc, out, tmp_path,
           host_partitions=2):
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)     # worker pins cpu itself
    # Isolate XLA compile caches per worker: two processes racing one
    # cache directory is a real hazard but not what this test is for.
    env["DDT_COMPILATION_CACHE"] = str(tmp_path / f"cache{pid}")
    return subprocess.Popen(
        [sys.executable, _WORKER, coord, str(nproc), str(pid),
         str(dev_per_proc), out,
         str(tmp_path / f"shards_{nproc}_{pid}"), str(host_partitions)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True,
    )


@pytest.mark.parametrize("nproc,host_partitions", [(2, 2), (4, 4)],
                         ids=["2proc", "4proc"])
def test_multiprocess_bringup_bit_identical(nproc, host_partitions,
                                            tmp_path):
    """N OS processes over a (hosts=N, rows=2) pod mesh (2*N global
    devices). Ensembles must be bitwise identical ACROSS processes for
    EVERY path (fused, granular/eval, streamed-from-shards: replicas of
    one global computation) and match a single-process run of the
    identical mesh shape bitwise in structure, float-close in leaves
    (gloo may sum the allreduce in a different order than the single-
    controller collective — ops/split.py "Determinism boundary")."""
    port = _free_port()
    coord = f"localhost:{port}"
    outs = [str(tmp_path / f"p{i}.npz") for i in range(nproc)]
    single = str(tmp_path / "single.npz")

    procs = [_spawn(coord, nproc, i, 2, outs[i], tmp_path,
                    host_partitions=host_partitions)
             for i in range(nproc)]
    logs = []
    for p in procs:
        try:
            stdout, _ = p.communicate(timeout=900)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        logs.append(stdout)
    _skip_if_multiprocess_unsupported(logs)
    assert all(p.returncode == 0 for p in procs), (
        "worker failed:\n" + "\n----\n".join(logs))

    # Single-process comparator: the same (hosts=N, rows=2) mesh over
    # 2*N virtual devices in one controller — identical program, so
    # identical trees prove the multi-process run computed the same
    # thing.
    ps = _spawn("unused", 1, 0, 2 * nproc, single, tmp_path,
                host_partitions=host_partitions)
    stdout, _ = ps.communicate(timeout=900)
    assert ps.returncode == 0, stdout

    ds = np.load(single)
    data = [np.load(o) for o in outs]
    for i, d in enumerate(data):
        assert int(d["process_index"]) == i
    keys = ("feature", "threshold_bin", "is_leaf", "leaf_value")
    for prefix in ("", "g_", "s_"):
        for i in range(1, nproc):
            for k in keys:
                np.testing.assert_array_equal(
                    data[0][prefix + k], data[i][prefix + k],
                    err_msg=f"proc {i} {prefix}{k}")
        for k in ("feature", "threshold_bin", "is_leaf"):
            np.testing.assert_array_equal(data[0][prefix + k],
                                          ds[prefix + k],
                                          err_msg=prefix + k)
        np.testing.assert_allclose(data[0][prefix + "leaf_value"],
                                   ds[prefix + "leaf_value"],
                                   rtol=2e-4, atol=2e-5)


def test_initialize_multihost_guard():
    """The idempotence guard itself, in-process (no coordinator needed:
    the guard trips before jax.distributed is touched)."""
    from ddt_tpu.parallel import mesh

    orig = mesh._init_args
    try:
        mesh._init_args = {"coordinator_address": "localhost:1",
                           "num_processes": 2, "process_id": 0}
        # same args: no-op
        mesh.initialize_multihost("localhost:1", 2, 0)
        # different args: loud
        with pytest.raises(RuntimeError, match="cannot\n?\\s*re-initialise"):
            mesh.initialize_multihost("localhost:1", 2, 1)
    finally:
        mesh._init_args = orig


def test_cli_multihost_train(tmp_path):
    """The CLI's own multihost bring-up (--multihost-*): two OS processes
    run the SAME train command, each fetches a replicated ensemble,
    bit-identical across processes. (sitecustomize pins the axon
    platform even in fresh subprocesses, so the wrapper flips the jax
    config to cpu before invoking the CLI — exactly what a multihost
    launcher script does on a non-TPU host.)"""
    port = _free_port()
    outs = [str(tmp_path / f"cli{i}.npz") for i in range(2)]
    procs = []
    for i in range(2):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
        env["DDT_COMPILATION_CACHE"] = str(tmp_path / f"cc{i}")
        wrapper = ("import jax, sys; "
                   "jax.config.update('jax_platforms', 'cpu'); "
                   "from ddt_tpu.cli import main; "
                   "sys.exit(main(sys.argv[1:]))")
        procs.append(subprocess.Popen(
            [sys.executable, "-c", wrapper, "train",
             "--backend=tpu", "--rows=2048", "--trees=3", "--depth=3",
             "--bins=31", "--host-partitions=2", "--partitions=2",
             f"--multihost-coordinator=localhost:{port}",
             "--multihost-processes=2", f"--multihost-id={i}",
             f"--out={outs[i]}"],
            env=env, cwd=os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        ))
    logs = []
    for p in procs:
        try:
            stdout, _ = p.communicate(timeout=900)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        logs.append(stdout)
    _skip_if_multiprocess_unsupported(logs)
    assert all(p.returncode == 0 for p in procs), (
        "cli multihost worker failed:\n" + "\n----\n".join(logs))
    d0 = np.load(outs[0])
    d1 = np.load(outs[1])
    for k in ("feature", "threshold_bin", "is_leaf", "leaf_value"):
        np.testing.assert_array_equal(d0[k], d1[k], err_msg=k)
