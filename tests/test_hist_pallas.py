"""Pallas HistogramBuilder parity vs the NumPy oracle (interpret mode on CPU).

SURVEY.md §4 unit tests for the hot kernel: the same kernel logic that runs
compiled on TPU runs here through the Pallas interpreter, checked against
reference/numpy_trainer.build_histograms. bf16 one-hot/weight inputs mean
tolerances are bf16-level relative on the sums.
"""

import numpy as np
import pytest

from ddt_tpu.ops.hist_pallas import build_histograms_pallas
from ddt_tpu.reference import numpy_trainer as ref


def _case(R, F, B, N, seed=0, frozen_frac=0.2):
    rng = np.random.default_rng(seed)
    Xb = rng.integers(0, B, size=(R, F), dtype=np.uint8)
    g = rng.standard_normal(R).astype(np.float32)
    h = rng.random(R).astype(np.float32)
    ni = rng.integers(0, N, size=R).astype(np.int32)
    ni[rng.random(R) < frozen_frac] = -1
    return Xb, g, h, ni


@pytest.mark.parametrize("R,F,B,N", [
    (700, 4, 31, 1),       # unaligned rows, single node (root level)
    (1024, 3, 255, 8),     # full 255-bin width (row-major kernel)
    (2000, 5, 16, 32),     # deep level, small bins (transposed kernel)
])
def test_pallas_matches_oracle(R, F, B, N):
    Xb, g, h, ni = _case(R, F, B, N)
    want = ref.build_histograms(Xb, g, h, ni, N, B)
    got = np.asarray(build_histograms_pallas(
        Xb, g, h, ni, N, B, tile_r=256, interpret=True
    ))
    assert got.shape == want.shape
    # bf16 inputs: per-element products round to ~3 decimal digits; sums of
    # ~R/N/B terms keep relative error at the bf16 level.
    np.testing.assert_allclose(got, want, rtol=2e-2, atol=2e-2)
    # Mass conservation is exact in f32 accumulation up to bf16 input
    # rounding: total g per node must match the masked sums.
    for n in range(N):
        mask = ni == n
        np.testing.assert_allclose(
            got[n, 0, :, 0].sum(), g[mask].sum(), rtol=2e-2, atol=1e-2
        )


def test_pallas_all_frozen_rows_zero():
    Xb, g, h, ni = _case(300, 3, 16, 4)
    ni[:] = -1
    got = np.asarray(build_histograms_pallas(
        Xb, g, h, ni, 4, 16, tile_r=256, interpret=True
    ))
    assert np.all(got == 0.0)


def test_pallas_feature_chunked_deep_level():
    """n_nodes=128 x 255 bins overflows the one-call VMEM budget; the
    kernel must feature-chunk and still match the oracle exactly-ish."""
    import numpy as np
    from ddt_tpu.ops.hist_pallas import (
        build_histograms_pallas, feature_chunks_for, pallas_fits)
    from ddt_tpu.reference import numpy_trainer as ref

    R, F, B, N = 3000, 54, 255, 128
    assert not pallas_fits(N, F, B)
    assert (feature_chunks_for(N, F, B) or 0) > 1
    rng = np.random.default_rng(0)
    Xb = rng.integers(0, B, size=(R, F), dtype=np.uint8)
    g = rng.standard_normal(R).astype(np.float32)
    h = rng.random(R).astype(np.float32)
    ni = rng.integers(-1, N, size=R).astype(np.int32)
    got = np.asarray(build_histograms_pallas(Xb, g, h, ni, N, B))
    want = ref.build_histograms(Xb, g, h, ni, N, B)
    np.testing.assert_allclose(got, want, rtol=2e-2, atol=2e-2)


@pytest.mark.parametrize("R,F,B,N", [
    (1600, 6, 64, 8),      # the 64-bin opt-in contract (transposed)
    (900, 6, 64, 32),      # transposed at the widest depth-6 level
    (800, 3, 128, 4),      # transposed/row-major boundary: Bp exactly 128
    (800, 3, 129, 4),      # first width ABOVE the boundary (row-major)
])
def test_transposed_kernel_exact_f32(R, F, B, N):
    """The round-3 transposed kernel (n_bins <= 128 -> one lane tile,
    sublane-broadcast one-hot) vs the oracle with float32 inputs — exact
    accumulation isolates kernel STRUCTURE from bf16 input rounding."""
    import jax.numpy as jnp

    Xb, g, h, ni = _case(R, F, B, N)
    want = ref.build_histograms(Xb, g, h, ni, N, B)
    got = np.asarray(build_histograms_pallas(
        Xb, g, h, ni, N, B, tile_r=256, interpret=True,
        input_dtype=jnp.float32,
    ))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
