"""Stochastic training (subsample / colsample_bytree), feature importance,
and the sklearn-style estimator facade."""

import numpy as np
import pytest

from ddt_tpu import api
from ddt_tpu.config import TrainConfig
from ddt_tpu.data.datasets import synthetic_binary, synthetic_multiclass
from ddt_tpu.data.quantizer import quantize


def _data(n=4000, f=8, seed=3):
    X, y = synthetic_binary(n, n_features=f, seed=seed)
    Xb, m = quantize(X, n_bins=63, seed=seed)
    return X, Xb, y, m


def test_config_validates_sampling_fractions():
    for bad in (dict(subsample=0.0), dict(subsample=1.5),
                dict(colsample_bytree=0.0), dict(colsample_bytree=-1)):
        with pytest.raises(ValueError):
            TrainConfig(**bad)


def test_colsample_masks_features_in_split_selection():
    from ddt_tpu.reference import numpy_trainer as ref

    rng = np.random.default_rng(0)
    hist = np.abs(rng.standard_normal((4, 6, 31, 2)).astype(np.float32))
    mask = np.array([True, False, True, False, False, False])
    _, feats, _, _ = ref.best_splits(hist, 1.0, 1e-3, feature_mask=mask)
    assert set(np.unique(feats)) <= {0, 2}

    import jax.numpy as jnp
    from ddt_tpu.ops import split as S

    _, jfeats, _, _ = S.best_splits(jnp.asarray(hist), 1.0, 1e-3,
                                 jnp.asarray(mask))
    np.testing.assert_array_equal(np.asarray(jfeats), feats)


@pytest.mark.parametrize("backend", ["cpu", "tpu"])
def test_sampling_trains_and_is_deterministic(backend):
    _, Xb, y, _ = _data()
    cfg = TrainConfig(n_trees=6, max_depth=4, n_bins=63, backend=backend,
                      subsample=0.7, colsample_bytree=0.6, seed=5)
    a = api.train(Xb, y, cfg, binned=True, log_every=10 ** 9).ensemble
    b = api.train(Xb, y, cfg, binned=True, log_every=10 ** 9).ensemble
    np.testing.assert_array_equal(a.feature, b.feature)
    np.testing.assert_array_equal(a.leaf_value, b.leaf_value)
    # And it actually changed the model vs no sampling.
    full = api.train(Xb, y, cfg.replace(subsample=1.0, colsample_bytree=1.0),
                     binned=True, log_every=10 ** 9).ensemble
    assert not np.array_equal(a.feature, full.feature)


def test_sampling_backend_parity():
    """CPU and TPU grow identical ensembles under bagging + colsampling
    (masks are host-side and backend-independent)."""
    _, Xb, y, _ = _data(n=2500, f=6)
    kw = dict(n_trees=5, max_depth=4, n_bins=63,
              subsample=0.8, colsample_bytree=0.5, seed=11)
    ec = api.train(Xb, y, TrainConfig(backend="cpu", **kw),
                   binned=True, log_every=10 ** 9).ensemble
    et = api.train(Xb, y, TrainConfig(backend="tpu", **kw),
                   binned=True, log_every=10 ** 9).ensemble
    np.testing.assert_array_equal(ec.feature, et.feature)
    np.testing.assert_array_equal(ec.threshold_bin, et.threshold_bin)
    np.testing.assert_array_equal(ec.is_leaf, et.is_leaf)


def test_feature_importances_split_counts():
    _, Xb, y, _ = _data()
    ens = api.train(Xb, y, TrainConfig(n_trees=8, max_depth=4, n_bins=63,
                                       backend="cpu"),
                    binned=True, log_every=10 ** 9).ensemble
    imp = ens.feature_importances()
    assert imp.shape == (Xb.shape[1],)
    assert imp.min() >= 0 and abs(imp.sum() - 1.0) < 1e-6
    # Hand-count parity.
    used = ens.feature[(~ens.is_leaf) & (ens.feature >= 0)]
    want = np.bincount(used, minlength=Xb.shape[1]) / len(used)
    np.testing.assert_allclose(imp, want, rtol=1e-6)


def test_sklearn_classifier_binary():
    from ddt_tpu.sklearn import DDTClassifier

    X, _, y, _ = _data()
    y_lab = np.where(y > 0, "pos", "neg")        # non-integer labels
    clf = DDTClassifier(n_trees=15, max_depth=4, n_bins=63, backend="cpu")
    clf.fit(X, y_lab)
    assert set(clf.classes_) == {"neg", "pos"}
    proba = clf.predict_proba(X)
    assert proba.shape == (len(X), 2)
    np.testing.assert_allclose(proba.sum(1), 1.0, rtol=1e-5)
    assert clf.score(X, y_lab) > 0.72
    assert clf.feature_importances_.shape == (X.shape[1],)


def test_sklearn_classifier_multiclass_and_regressor():
    from ddt_tpu.sklearn import DDTClassifier, DDTRegressor

    X, y = synthetic_multiclass(3000, n_features=6, n_classes=3, seed=2)
    clf = DDTClassifier(n_trees=8, max_depth=4, n_bins=63, backend="cpu")
    clf.fit(X, y + 10)                            # offset labels map back
    assert set(clf.classes_) == {10, 11, 12}
    assert clf.score(X, y + 10) > 0.7

    rng = np.random.default_rng(0)
    Xr = rng.standard_normal((3000, 5)).astype(np.float32)
    yr = Xr[:, 0] * 2 - Xr[:, 1] + 0.1 * rng.standard_normal(3000)
    reg = DDTRegressor(n_trees=30, max_depth=4, n_bins=63, backend="cpu")
    reg.fit(Xr, yr)
    assert reg.score(Xr, yr) > 0.8


def test_gain_importance_and_backend_gain_parity(tmp_path):
    _, Xb, y, _ = _data(n=2500, f=6)
    kw = dict(n_trees=5, max_depth=4, n_bins=63, seed=3)
    ec = api.train(Xb, y, TrainConfig(backend="cpu", **kw),
                   binned=True, log_every=10 ** 9).ensemble
    et = api.train(Xb, y, TrainConfig(backend="tpu", **kw),
                   binned=True, log_every=10 ** 9).ensemble
    # Gains are bf16-rounded best gains -> identical across backends.
    np.testing.assert_array_equal(ec.split_gain, et.split_gain)
    assert (ec.split_gain[~ec.is_leaf & (ec.feature >= 0)] > 0).all()
    assert (ec.split_gain[ec.is_leaf] == 0).all()
    gi = ec.feature_importances(kind="gain")
    assert gi.shape == (6,) and abs(gi.sum() - 1.0) < 1e-6
    # save/load round-trips the gains; pre-gain archives load as zeros.
    path = str(tmp_path / "gain_ens.npz")
    ec.save(path)
    from ddt_tpu.models.tree import TreeEnsemble
    np.testing.assert_array_equal(
        TreeEnsemble.load(path).split_gain, ec.split_gain)
    d = ec.to_dict()
    del d["split_gain"]
    old = TreeEnsemble.from_dict(d)
    assert (old.split_gain == 0).all()


def test_sklearn_params_protocol():
    from ddt_tpu.sklearn import DDTClassifier

    clf = DDTClassifier(n_trees=7, max_depth=3, backend="cpu")
    p = clf.get_params()
    assert p["n_trees"] == 7 and p["max_depth"] == 3
    clone = DDTClassifier(**p)              # sklearn.clone() equivalent
    assert clone.get_params() == p
    clf.set_params(n_trees=9)
    assert clf.n_trees == 9
    with pytest.raises(ValueError):
        clf.set_params(nope=1)
    # Real sklearn interop when available.
    try:
        from sklearn.base import clone as skclone
    except ImportError:
        return
    c2 = skclone(clf)
    assert c2.get_params() == clf.get_params()


def test_classifier_single_class_raises_at_fit():
    """ADVICE r1: single-class y must fail at fit with a clear error, not
    an IndexError at predict."""
    import pytest

    from ddt_tpu.sklearn import DDTClassifier

    X = np.random.default_rng(0).standard_normal((50, 4)).astype(np.float32)
    y = np.ones(50, dtype=np.int64)
    with pytest.raises(ValueError, match="only one class"):
        DDTClassifier(n_trees=2, max_depth=2, backend="cpu").fit(X, y)


def test_train_config_is_frozen():
    """ADVICE r1: backend-cache keys assume configs never mutate; the
    dataclass enforces it."""
    import dataclasses

    import pytest

    from ddt_tpu.config import TrainConfig

    cfg = TrainConfig(n_bins=31)
    with pytest.raises(dataclasses.FrozenInstanceError):
        cfg.n_bins = 63
    assert cfg.replace(n_bins=63).n_bins == 63  # derivation still works


def test_sklearn_facade_eval_attributes():
    """LightGBM/sklearn-convention fitted eval attributes: best_iteration_,
    best_score_, evals_result_ (per-round metric series), populated on both
    backends (device eval on tpu, host eval on cpu)."""
    from ddt_tpu.data.datasets import synthetic_binary
    from ddt_tpu.sklearn import DDTClassifier

    X, y = synthetic_binary(3000, n_features=8, seed=3)
    for backend in ("cpu", "tpu"):
        clf = DDTClassifier(n_trees=12, max_depth=4, n_bins=63,
                            backend=backend)
        clf.fit(X[:2400], y[:2400], eval_set=(X[2400:], y[2400:]),
                eval_metric="auc", early_stopping_rounds=8)
        assert clf.best_iteration_ is not None
        assert clf.best_score_ == max(clf.evals_result_["auc"])
        assert len(clf.evals_result_["auc"]) >= clf.best_iteration_ + 1
    # no eval_set: attributes exist but are empty
    clf = DDTClassifier(n_trees=3, max_depth=3, n_bins=63, backend="cpu")
    clf.fit(X[:500], y[:500])
    assert clf.best_iteration_ is None and clf.evals_result_ == {}


def test_row_keep_twins_bit_identical():
    """The NumPy and JAX counter-hash twins (ops/sampling) produce the
    SAME keep bits — the whole cross-path bagging identity contract rests
    on this — including 64-bit global row bases past 2^32 (the 10B-row
    config's range) and shard-local offsets."""
    import jax.numpy as jnp

    from ddt_tpu.ops import sampling as S

    for seed, rnd, n, frac in [(0, 0, 1000, 0.8), (7, 13, 4096, 0.3),
                               (2**31, 999, 257, 0.5)]:
        want = S.row_keep_np(seed, rnd, 0, n, frac).astype(np.float32)
        got = np.asarray(S.row_keep_jax(
            jnp.int32(rnd), jnp.int32(0), n, seed=seed, subsample=frac))
        np.testing.assert_array_equal(want, got, err_msg=str((seed, rnd)))
    # offset equivalence: shard 1 of 2 equals the tail of the full draw
    full = S.row_keep_np(3, 2, 0, 2048, 0.6).astype(np.float32)
    tail = np.asarray(S.row_keep_jax(
        jnp.int32(2), jnp.int32(1024), 1024, seed=3, subsample=0.6))
    np.testing.assert_array_equal(full[1024:], tail)
    # 64-bit base crossing a 2^32 boundary
    base = (1 << 32) - 500
    want = S.row_keep_np(3, 5, base + 256, 1000, 0.5).astype(np.float32)
    got = np.asarray(S.row_keep_jax(
        jnp.int32(5), jnp.int32(256), 1000, seed=3, subsample=0.5,
        row_start_lo=jnp.uint32(base & 0xFFFFFFFF),
        row_start_hi=jnp.uint32(base >> 32)))
    np.testing.assert_array_equal(want, got)
    # statistics: keep rate ~ subsample, rounds roughly independent
    m0 = S.row_keep_np(0, 0, 0, 1_000_000, 0.8)
    m1 = S.row_keep_np(0, 1, 0, 1_000_000, 0.8)
    assert abs(m0.mean() - 0.8) < 2e-3
    assert abs((m0 & m1).mean() - 0.64) < 2e-3


def test_bagging_rides_fused_path():
    """Round-5: bagging row masks are recomputed IN-SCAN (counter-based,
    ops/sampling) — grow_rounds must engage (no granular fallback) and
    grow the granular CPU path's exact trees."""
    from ddt_tpu.backends import get_backend
    from ddt_tpu.driver import Driver

    X, y = synthetic_binary(2048, n_features=10, seed=3)
    Xb, _ = quantize(X, n_bins=31, seed=3)
    cfg = TrainConfig(n_trees=5, max_depth=3, n_bins=31, backend="tpu",
                      subsample=0.7, seed=7)
    be = get_backend(cfg)
    calls = {"fused": 0}
    orig = be.grow_rounds

    def spy(*a, **k):
        calls["fused"] += 1
        return orig(*a, **k)

    be.grow_rounds = spy
    try:
        fused = Driver(be, cfg, log_every=10**9).fit(Xb, y)
    finally:
        be.grow_rounds = orig
    assert calls["fused"] >= 1

    cfg_c = cfg.replace(backend="cpu")
    gran = Driver(get_backend(cfg_c), cfg_c, log_every=10**9).fit(Xb, y)
    np.testing.assert_array_equal(gran.feature, fused.feature)
    np.testing.assert_array_equal(gran.threshold_bin, fused.threshold_bin)
    np.testing.assert_allclose(gran.leaf_value, fused.leaf_value,
                               rtol=2e-4, atol=2e-5)


def test_bagging_fused_pod_mesh_identity():
    """In-scan bagging over a (hosts x rows) pod mesh: each shard derives
    its rows' global ids from the flattened shard index, so the sharded
    fused run must equal the single-device fused run exactly."""
    from ddt_tpu.backends import get_backend
    from ddt_tpu.driver import Driver

    X, y = synthetic_binary(1536, n_features=8, seed=5)
    Xb, _ = quantize(X, n_bins=31, seed=5)
    cfg = TrainConfig(n_trees=4, max_depth=3, n_bins=31, backend="tpu",
                      subsample=0.6, seed=13)
    single = Driver(get_backend(cfg), cfg, log_every=10**9).fit(Xb, y)
    cfg_p = cfg.replace(host_partitions=2, n_partitions=2)
    pod = Driver(get_backend(cfg_p), cfg_p, log_every=10**9).fit(Xb, y)
    np.testing.assert_array_equal(single.feature, pod.feature)
    np.testing.assert_array_equal(single.threshold_bin, pod.threshold_bin)
    np.testing.assert_array_equal(single.is_leaf, pod.is_leaf)


def test_bagged_eval_set_rides_fused_and_matches_cpu():
    """bagging + eval_set rides the FUSED path (round ids ride the eval
    scan as xs; grow_rounds_eval must engage): histories must match the
    CPU host-eval path and the models must be identical."""
    from ddt_tpu.backends import get_backend
    from ddt_tpu.config import TrainConfig as TC

    X, y = synthetic_binary(3000, n_features=8, seed=3)
    kw = dict(n_trees=12, max_depth=4, n_bins=63, subsample=0.8, seed=5,
              log_every=1, eval_set=(X[2400:], y[2400:]),
              eval_metric="logloss")
    rc = api.train(X[:2400], y[:2400], backend="cpu", **kw)
    be = get_backend(TC(backend="tpu", n_trees=12, max_depth=4, n_bins=63,
                        subsample=0.8, seed=5))
    calls = {"fused_eval": 0}
    orig = be.grow_rounds_eval

    def spy(*a, **k):
        calls["fused_eval"] += 1
        return orig(*a, **k)

    be.grow_rounds_eval = spy
    try:
        rt = api.train(X[:2400], y[:2400], backend="tpu", **kw)
    finally:
        be.grow_rounds_eval = orig
    assert calls["fused_eval"] >= 1
    hc = [r["valid_logloss"] for r in rc.history if "valid_logloss" in r]
    ht = [r["valid_logloss"] for r in rt.history if "valid_logloss" in r]
    assert len(ht) == 12
    np.testing.assert_allclose(hc, ht, rtol=2e-5)
    np.testing.assert_array_equal(rc.ensemble.feature, rt.ensemble.feature)


def test_full_stochastic_eval_combo_fused_matches_cpu():
    """The whole stochastic matrix at once — colsample + bagging +
    eval_set + early stopping — rides ONE fused scan (round 5 closes
    the matrix; only profiling still runs granular): grow_rounds_eval
    must engage with masks and round ids as xs, and the device run must
    grow the CPU host-eval path's exact trees with matching histories
    and stopping decision."""
    from ddt_tpu.backends import get_backend
    from ddt_tpu.config import TrainConfig as TC

    X, y = synthetic_binary(3000, n_features=10, seed=13)
    kw = dict(n_trees=20, max_depth=4, n_bins=63, subsample=0.8,
              colsample_bytree=0.6, seed=21, log_every=1,
              eval_set=(X[2400:], y[2400:]), eval_metric="logloss",
              early_stopping_rounds=5)
    rc = api.train(X[:2400], y[:2400], backend="cpu", **kw)
    be = get_backend(TC(backend="tpu", max_depth=4, n_bins=63,
                        subsample=0.8, colsample_bytree=0.6, seed=21))
    calls = {"n": 0}
    orig = be.grow_rounds_eval

    def spy(*a, **k):
        calls["n"] += 1
        assert k.get("fmasks") is not None     # masks rode the eval scan
        return orig(*a, **k)

    be.grow_rounds_eval = spy
    try:
        rt = api.train(X[:2400], y[:2400], backend="tpu", **kw)
    finally:
        be.grow_rounds_eval = orig
    assert calls["n"] >= 1
    assert rc.best_round == rt.best_round
    hc = [r["valid_logloss"] for r in rc.history if "valid_logloss" in r]
    ht = [r["valid_logloss"] for r in rt.history if "valid_logloss" in r]
    np.testing.assert_allclose(hc, ht, rtol=2e-5)
    np.testing.assert_array_equal(rc.ensemble.feature, rt.ensemble.feature)
    np.testing.assert_array_equal(rc.ensemble.threshold_bin,
                                  rt.ensemble.threshold_bin)


def test_bagged_auc_early_stop_fused_matches_granular():
    """The full combination — bagging + auc (binned device twin) + early
    stopping — on the fused path equals the granular device path (forced
    by profile=True) round for round."""
    X, y = synthetic_binary(4000, n_features=10, seed=3)
    kw = dict(n_trees=25, max_depth=4, n_bins=63, subsample=0.75, seed=9,
              log_every=10**9, eval_set=(X[3200:], y[3200:]),
              eval_metric="auc", early_stopping_rounds=4, backend="tpu")
    fused = api.train(X[:3200], y[:3200], **kw)
    gran = api.train(X[:3200], y[:3200], profile=True, **kw)
    assert fused.best_round == gran.best_round
    hf = [r["valid_auc"] for r in fused.history if "valid_auc" in r]
    hg = [r["valid_auc"] for r in gran.history if "valid_auc" in r]
    # The two paths compile DIFFERENT programs around the same ops, so
    # FMA contraction can move a validation score by f32 ULPs — which
    # shifts a score across a bin edge and the binned auc by ~1 pair
    # (the f32 score-boundary seam, driver.py docstring). The MODEL is
    # bitwise identical; scores agree to that seam.
    np.testing.assert_allclose(hf, hg, atol=1e-5)
    np.testing.assert_array_equal(fused.ensemble.feature,
                                  gran.ensemble.feature)


def test_colsample_rides_fused_path():
    """Round-3: colsample's [K, C, F] masks ride the fused scan as xs —
    grow_rounds_masked must engage and grow the same ensemble as the
    granular CPU path (same host-drawn masks)."""
    from ddt_tpu.backends import get_backend
    from ddt_tpu.driver import Driver

    X, y = synthetic_binary(2048, n_features=10, seed=3)
    Xb, _ = quantize(X, n_bins=31, seed=3)
    cfg = TrainConfig(n_trees=5, max_depth=3, n_bins=31, backend="tpu",
                      colsample_bytree=0.5, seed=7)
    be = get_backend(cfg)
    calls = {"masked": 0}
    orig = be.grow_rounds_masked

    def spy(*a, **k):
        calls["masked"] += 1
        return orig(*a, **k)

    be.grow_rounds_masked = spy
    try:
        fused = Driver(be, cfg, log_every=10**9).fit(Xb, y)
    finally:
        be.grow_rounds_masked = orig
    assert calls["masked"] >= 1

    cfg_c = cfg.replace(backend="cpu")
    gran = Driver(get_backend(cfg_c), cfg_c, log_every=10**9).fit(Xb, y)
    np.testing.assert_array_equal(gran.feature, fused.feature)
    np.testing.assert_array_equal(gran.threshold_bin, fused.threshold_bin)
    np.testing.assert_allclose(gran.leaf_value, fused.leaf_value,
                               rtol=2e-4, atol=2e-5)


def test_colsample_fused_softmax_and_partitions():
    """Masked fused blocks compose with softmax (per-class masks) and the
    row mesh."""
    from ddt_tpu.backends import get_backend
    from ddt_tpu.driver import Driver

    X, y = synthetic_multiclass(1500, n_features=12, seed=5)
    Xb, _ = quantize(X, n_bins=31, seed=5)
    cfg = TrainConfig(n_trees=3, max_depth=3, n_bins=31, backend="cpu",
                      loss="softmax", n_classes=7, colsample_bytree=0.6,
                      seed=9)
    gran = Driver(get_backend(cfg), cfg, log_every=10**9).fit(Xb, y)
    cfg_t = cfg.replace(backend="tpu", n_partitions=2)
    be = get_backend(cfg_t)
    calls = {"masked": 0}
    orig = be.grow_rounds_masked

    def spy(*a, **k):
        calls["masked"] += 1
        return orig(*a, **k)

    be.grow_rounds_masked = spy
    try:
        fused = Driver(be, cfg_t, log_every=10**9).fit(Xb, y)
    finally:
        be.grow_rounds_masked = orig
    assert calls["masked"] >= 1        # the masked fused path engaged
    np.testing.assert_array_equal(gran.feature, fused.feature)
    np.testing.assert_array_equal(gran.threshold_bin, fused.threshold_bin)
    np.testing.assert_allclose(gran.leaf_value, fused.leaf_value,
                               rtol=2e-4, atol=2e-5)
