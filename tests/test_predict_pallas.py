"""Pallas traversal kernel exactness sweep + compiled-ensemble cache tests.

The kernel contract (ops/predict_pallas.py): BIT-EXACT agreement with the
one-hot predict path at the same tree_chunk — missing-value routing,
categorical one-vs-rest, softmax round-major classes, uneven tree/row
remainders, R=0 — and oracle-grade agreement with the NumPy scorer. Runs
through Pallas interpret mode on CPU (the identical kernel logic the chip
compiles; same pattern as tests/test_hist_pallas.py).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from ddt_tpu import api
from ddt_tpu.backends import get_backend
from ddt_tpu.config import TrainConfig
from ddt_tpu.data.datasets import synthetic_binary
from ddt_tpu.data.quantizer import quantize
from ddt_tpu.models.tree import CompiledEnsemble, TreeEnsemble
from ddt_tpu.ops import predict as jpred
from ddt_tpu.ops import predict_pallas as jpp
from ddt_tpu.reference import numpy_trainer as oracle


def _rand_ensemble(T=9, depth=3, F=6, bins=31, n_classes=1, seed=0,
                   missing=False, cat=()):
    """Random full-ish trees wrapped in a TreeEnsemble (the NumPy oracle
    needs the object; the device paths take its arrays)."""
    rng = np.random.default_rng(seed)
    N = 2 ** (depth + 1) - 1
    ens = TreeEnsemble(
        feature=rng.integers(0, F, size=(T, N)).astype(np.int32),
        threshold_bin=rng.integers(0, bins - 1, (T, N)).astype(np.int32),
        threshold_raw=np.zeros((T, N), np.float32),
        is_leaf=rng.random((T, N)) < 0.25,
        leaf_value=rng.standard_normal((T, N)).astype(np.float32),
        split_gain=np.zeros((T, N), np.float32),
        max_depth=depth, n_features=F, learning_rate=0.1, base_score=0.3,
        loss="softmax" if n_classes > 1 else "logloss",
        n_classes=max(n_classes, 2),
        default_left=(rng.random((T, N)) < 0.5) if missing else None,
        missing_bin=missing, n_bins=bins,
        cat_features=np.asarray(cat, np.int32) if cat else None,
    )
    return ens


def _dev_args(ens):
    use_missing = ens.missing_bin and ens.default_left is not None
    kw = dict(
        max_depth=ens.max_depth, learning_rate=ens.learning_rate,
        base=ens.base_score,
        n_classes=ens.n_classes if ens.loss == "softmax" else 1,
        missing_bin_value=ens.n_bins - 1 if use_missing else -1,
    )
    opt = {}
    if use_missing:
        opt["default_left"] = jnp.asarray(ens.default_left)
    if ens.has_cat_splits:
        opt["cat_node"] = jnp.asarray(
            np.isin(ens.feature, ens.cat_features))
    args = (jnp.asarray(ens.feature), jnp.asarray(ens.threshold_bin),
            jnp.asarray(ens.is_leaf), jnp.asarray(ens.leaf_value))
    return args, kw, opt


@pytest.mark.parametrize("n_classes,tree_chunk,rows", [
    (1, 64, 500),      # T=9 < tree_chunk: one ragged tree chunk
    (1, 4, 511),       # uneven tree remainder (9 % 4) + odd row count
    (3, 2, 257),       # softmax round-major, rows not a tile multiple
    (1, 3, 0),         # R = 0
])
@pytest.mark.parametrize("missing,cat", [
    (False, ()), (True, ()), (False, (1, 4)), (True, (2,)),
])
def test_pallas_exact_vs_onehot_sweep(n_classes, tree_chunk, rows,
                                      missing, cat):
    """The kernel's headline contract: bit-exact vs the one-hot path over
    the full routing matrix x chunk-remainder x class sweep."""
    ens = _rand_ensemble(n_classes=n_classes, missing=missing, cat=cat,
                         seed=n_classes * 7 + tree_chunk)
    args, kw, opt = _dev_args(ens)
    Xb = np.random.default_rng(rows + 1).integers(
        0, ens.n_bins, size=(rows, ens.n_features)).astype(np.int32)
    want = np.asarray(jpred.predict_raw(
        *args, jnp.asarray(Xb), tree_chunk=tree_chunk, use_pallas=False,
        **kw, **opt))
    got = np.asarray(jpp.predict_raw_pallas(
        *args, jnp.asarray(Xb), tree_chunk=tree_chunk, **kw, **opt))
    np.testing.assert_array_equal(want, got)
    # and the dispatch flag reaches the same kernel
    via_flag = np.asarray(jpred.predict_raw(
        *args, jnp.asarray(Xb), tree_chunk=tree_chunk, use_pallas=True,
        **kw, **opt))
    np.testing.assert_array_equal(want, via_flag)


@pytest.mark.parametrize("missing,cat", [
    (False, ()), (True, ()), (False, (0, 3)),
])
def test_pallas_matches_numpy_oracle(missing, cat):
    """Three-way agreement: pallas == one-hot (exact) and both match the
    NumPy reference scorer to float tolerance (accumulation order is the
    only seam — selection is integer-exact everywhere)."""
    ens = _rand_ensemble(T=11, depth=4, missing=missing, cat=cat, seed=5)
    args, kw, opt = _dev_args(ens)
    rng = np.random.default_rng(9)
    Xb = rng.integers(0, ens.n_bins, size=(800, ens.n_features))
    want_np = ens.predict_raw(Xb.astype(np.uint8), binned=True)
    onehot = np.asarray(jpred.predict_raw(
        *args, jnp.asarray(Xb.astype(np.int32)), tree_chunk=4,
        use_pallas=False, **kw, **opt))
    pallas = np.asarray(jpp.predict_raw_pallas(
        *args, jnp.asarray(Xb.astype(np.int32)), tree_chunk=4, **kw,
        **opt))
    np.testing.assert_array_equal(onehot, pallas)
    np.testing.assert_allclose(pallas, want_np, rtol=2e-4, atol=2e-5)


def test_pallas_trained_model_softmax_and_binary():
    """Oracle-trained ensembles (not random trees) through the kernel:
    the reference trainer's exact leaf layout, both losses."""
    X, y = synthetic_binary(600, n_features=5, seed=7)
    Xb, mapper = quantize(X, n_bins=32)
    for loss_kw, C in [({}, 1),
                       ({"loss": "softmax", "n_classes": 3}, 3)]:
        yy = (y + (X[:, 0] > 0)).astype(np.int32) if C == 3 else y
        cfg = TrainConfig(n_trees=5, max_depth=3, n_bins=32,
                          backend="cpu", **loss_kw)
        ens = oracle.fit(Xb, yy, cfg, mapper=mapper)
        args, kw, opt = _dev_args(ens)
        want = ens.predict_raw(Xb, binned=True)
        onehot = np.asarray(jpred.predict_raw(
            *args, jnp.asarray(Xb.astype(np.int32)), tree_chunk=4,
            use_pallas=False, **kw, **opt))
        pallas = np.asarray(jpp.predict_raw_pallas(
            *args, jnp.asarray(Xb.astype(np.int32)), tree_chunk=4, **kw,
            **opt))
        np.testing.assert_array_equal(onehot, pallas)
        np.testing.assert_allclose(pallas, want, rtol=1e-4, atol=1e-5)


def test_pallas_rejects_float_data():
    ens = _rand_ensemble()
    args, kw, _ = _dev_args(ens)
    X = np.random.default_rng(0).standard_normal(
        (10, ens.n_features)).astype(np.float32)
    with pytest.raises(ValueError, match="binned"):
        jpred.predict_raw(*args, jnp.asarray(X), use_pallas=True, **kw)


def test_pallas_fits_guard():
    from ddt_tpu.ops.predict_pallas import predict_pallas_fits

    assert predict_pallas_fits(1024, 64, 6, 28, 1)       # the bench shape
    assert not predict_pallas_fits(1000, 64, 6, 28, 1)   # not a multiple
    # monster shape blows the VMEM/trace budget
    assert not predict_pallas_fits(1 << 20, 64, 10, 512, 1)


# --------------------------------------------------------------------- #
# CompiledEnsemble: host layout + device-resident cache
# --------------------------------------------------------------------- #

def test_compiled_ensemble_effective_arrays_match_traced():
    """The host pushdown twin is bitwise-identical to the traced one —
    the compiled path may never drift from predict_raw's prologue."""
    ens = _rand_ensemble(T=6, depth=4, seed=3)
    ce = CompiledEnsemble.build(ens, tree_chunk=4)
    tpad = ce.n_trees_padded - ens.n_trees

    def pad(a, fill=0):
        return jnp.pad(jnp.asarray(a), ((0, tpad), (0, 0)),
                       constant_values=fill)

    ef, et, ev, _ = jpred._effective_arrays(
        pad(ens.feature, -1), pad(ens.threshold_bin),
        pad(ens.is_leaf, True), pad(ens.leaf_value), ens.max_depth)
    np.testing.assert_array_equal(ce.eff_feat, np.asarray(ef))
    np.testing.assert_array_equal(ce.eff_thr, np.asarray(et))
    lo = (1 << ens.max_depth) - 1
    np.testing.assert_array_equal(ce.bot_val, np.asarray(ev)[:, lo:])


def test_backend_compiled_ensemble_cache_hits_and_invalidation():
    """Repeat scoring hits the device-resident cache (counter moves);
    mutating the model in place changes the token and serves fresh
    trees — a cached compiled ensemble may never go stale."""
    from ddt_tpu.telemetry import counters as tele_counters

    Xb = np.random.default_rng(0).integers(
        0, 31, size=(400, 6), dtype=np.uint8)
    ens = _rand_ensemble(T=5, depth=3, F=6, bins=31, seed=11)
    be = get_backend(TrainConfig(backend="tpu", n_bins=31))
    c0 = tele_counters.snapshot()
    a = be.predict_raw(ens, Xb)
    b = be.predict_raw(ens, Xb)
    np.testing.assert_array_equal(a, b)
    assert tele_counters.delta(c0)["compiled_ensemble_cache_hits"] == 1
    tok0 = ens.cache_token()
    ens.leaf_value[:] += 1.0                      # in-place mutation
    assert ens.cache_token() != tok0
    c = be.predict_raw(ens, Xb)
    assert not np.allclose(a, c)                  # fresh trees served
    np.testing.assert_allclose(
        c, ens.predict_raw(Xb, binned=True), rtol=2e-4, atol=2e-5)


def test_backend_predict_impl_pallas_matches_onehot():
    """cfg.predict_impl='pallas' forces the kernel through the whole
    backend path (compiled cache + chunking) — same scores, bit-exact."""
    Xb = np.random.default_rng(2).integers(
        0, 31, size=(300, 5), dtype=np.uint8)
    ens = _rand_ensemble(T=7, depth=3, F=5, bins=31, seed=2)
    be_1h = get_backend(TrainConfig(backend="tpu", n_bins=31,
                                    predict_impl="onehot"))
    be_pl = get_backend(TrainConfig(backend="tpu", n_bins=31,
                                    predict_impl="pallas"))
    np.testing.assert_array_equal(be_1h.predict_raw(ens, Xb),
                                  be_pl.predict_raw(ens, Xb))


def test_predict_impl_flag_validation():
    with pytest.raises(ValueError, match="predict_impl"):
        TrainConfig(predict_impl="cuda")


# --------------------------------------------------------------------- #
# overlapped streaming + the multi-chip flag
# --------------------------------------------------------------------- #

def test_predict_streaming_matches_in_memory():
    from ddt_tpu.streaming import predict_streaming

    X, y = synthetic_binary(2000, n_features=6, seed=4)
    Xb, _ = quantize(X, n_bins=31)
    cfg = TrainConfig(n_trees=6, max_depth=3, n_bins=31, backend="tpu")
    ens = api.train(Xb, y, cfg, binned=True, log_every=10**9).ensemble
    be = get_backend(cfg)
    want = be.predict_raw(ens, Xb)

    def cf(c):                    # ragged last chunk: 600*3 + 200
        return Xb[c * 600:(c + 1) * 600], None

    got = predict_streaming(cf, 4, ens, backend=be)
    np.testing.assert_array_equal(want, got)
    # sink form streams per-chunk scores and returns the row count
    parts = {}
    rows = predict_streaming(cf, 4, ens, backend=be,
                             sink=lambda c, s: parts.__setitem__(c, s))
    assert rows == 2000
    np.testing.assert_array_equal(
        np.concatenate([parts[i] for i in range(4)]), want)
    # host fallback (backend=None) agrees to scorer tolerance
    host = predict_streaming(cf, 4, ens, backend=None)
    np.testing.assert_allclose(host, want, rtol=2e-4, atol=2e-5)
    # oversized chunks (past the backend's per-dispatch row bound) must
    # route through the backend's own chunked path, not one big dispatch
    # (the 10M x 1000 single-dispatch OOM class), and stay in order
    from ddt_tpu.backends.tpu import TPUDevice

    old = TPUDevice.PREDICT_ROW_CHUNK
    TPUDevice.PREDICT_ROW_CHUNK = 256
    try:
        big = predict_streaming(cf, 4, ens, backend=be)
    finally:
        TPUDevice.PREDICT_ROW_CHUNK = old
    np.testing.assert_array_equal(big, want)


def test_api_predict_n_partitions_flag():
    """Multi-chip scoring is a flag: api.predict(n_partitions=4) row-
    shards over a parallel.mesh row mesh and matches the single-chip
    path exactly (8 virtual CPU devices, conftest)."""
    X, y = synthetic_binary(1500, n_features=6, seed=6)
    Xb, _ = quantize(X, n_bins=31)
    cfg = TrainConfig(n_trees=4, max_depth=3, n_bins=31, backend="tpu")
    ens = api.train(Xb, y, cfg, binned=True, log_every=10**9).ensemble
    want = api.predict(ens, Xb, binned=True, backend=get_backend(cfg),
                       raw=True)
    got = api.predict(ens, Xb, binned=True, n_partitions=4, raw=True)
    np.testing.assert_array_equal(want, got)
