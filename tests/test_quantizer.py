import numpy as np

from ddt_tpu.data.quantizer import BinMapper, fit_bin_mapper, quantize


def test_bins_in_range_and_dtype():
    rng = np.random.default_rng(0)
    X = rng.standard_normal((1000, 5)).astype(np.float32)
    Xb, mapper = quantize(X, n_bins=255)
    assert Xb.dtype == np.uint8
    assert Xb.min() >= 0 and Xb.max() <= 254
    assert mapper.edges.shape == (5, 254)


def test_bins_monotone_in_value():
    # Larger raw value never gets a smaller bin.
    rng = np.random.default_rng(1)
    X = rng.standard_normal((5000, 1)).astype(np.float32)
    Xb, _ = quantize(X, n_bins=64)
    order = np.argsort(X[:, 0])
    bins_sorted = Xb[order, 0].astype(int)
    assert (np.diff(bins_sorted) >= 0).all()


def test_quantile_balance():
    # Roughly equal mass per bin for continuous data.
    rng = np.random.default_rng(2)
    X = rng.standard_normal((100_000, 1)).astype(np.float32)
    Xb, _ = quantize(X, n_bins=16)
    counts = np.bincount(Xb[:, 0], minlength=16)
    assert counts.min() > 100_000 / 16 * 0.8
    assert counts.max() < 100_000 / 16 * 1.2


def test_threshold_value_consistency():
    # Split semantics: bin <= t  <=>  value <= threshold_value(f, t).
    rng = np.random.default_rng(3)
    X = rng.standard_normal((20_000, 3)).astype(np.float32)
    Xb, mapper = quantize(X, n_bins=32)
    for f in range(3):
        for t in (0, 7, 15, 30):
            left_by_bin = Xb[:, f] <= t
            left_by_val = X[:, f] <= mapper.threshold_value(f, t)
            assert (left_by_bin == left_by_val).all(), (f, t)


def test_constant_feature():
    X = np.ones((100, 2), dtype=np.float32)
    Xb, _ = quantize(X, n_bins=255)
    assert (Xb == Xb[0, 0]).all()  # single bin used


def test_nan_policy():
    X = np.array([[np.nan], [0.0], [1.0]], dtype=np.float32)
    mapper = fit_bin_mapper(np.array([[0.0], [0.5], [1.0]], np.float32), 8)
    Xb = mapper.transform(X)
    assert Xb[0, 0] == 0  # NaN -> bin 0 (documented v1 policy)


def test_save_load_roundtrip():
    rng = np.random.default_rng(4)
    X = rng.standard_normal((500, 4)).astype(np.float32)
    _, mapper = quantize(X, n_bins=100)
    m2 = BinMapper.load(mapper.save())
    assert np.array_equal(m2.edges, mapper.edges)
    assert m2.n_bins == mapper.n_bins
    assert np.array_equal(m2.transform(X), mapper.transform(X))


# ---------------------------------------------------------------------- #
# round-2 L7 additions: streamed quantile fit + device-side transform
# ---------------------------------------------------------------------- #

def test_streaming_fit_equals_inmemory_with_full_sample():
    """With max_sample >= total rows the reservoir keeps every row, so the
    streamed fit's edges must EQUAL the in-memory fit's (np.quantile is
    order-invariant)."""
    from ddt_tpu.data.quantizer import (
        fit_bin_mapper, fit_bin_mapper_streaming)

    rng = np.random.default_rng(0)
    X = rng.standard_normal((4000, 6)).astype(np.float32)

    def chunk_fn(c):
        return X[c * 1000:(c + 1) * 1000], None

    m_full = fit_bin_mapper(X, n_bins=31, max_sample=4000)
    m_str = fit_bin_mapper_streaming(chunk_fn, 4, n_bins=31,
                                     max_sample=4000)
    np.testing.assert_array_equal(m_full.edges, m_str.edges)


def test_streaming_fit_subsampled_deterministic_and_close():
    from ddt_tpu.data.quantizer import (
        fit_bin_mapper, fit_bin_mapper_streaming)

    rng = np.random.default_rng(1)
    X = rng.standard_normal((8000, 4)).astype(np.float32)

    def chunk_fn(c):
        return X[c * 1000:(c + 1) * 1000], None

    m1 = fit_bin_mapper_streaming(chunk_fn, 8, n_bins=31, max_sample=2000,
                                  seed=7)
    m2 = fit_bin_mapper_streaming(chunk_fn, 8, n_bins=31, max_sample=2000,
                                  seed=7)
    np.testing.assert_array_equal(m1.edges, m2.edges)   # deterministic
    m_full = fit_bin_mapper(X, n_bins=31, max_sample=8000)
    # a 25% uniform sample tracks the true quantiles closely on N(0,1)
    fin = np.isfinite(m_full.edges)
    assert np.abs(m1.edges[fin] - m_full.edges[fin]).max() < 0.25


def test_streaming_fit_trains_end_to_end():
    """Raw-float chunks -> streamed mapper fit -> binned_chunks adapter ->
    fit_streaming: equals in-memory training on the same mapper's bins."""
    from ddt_tpu.backends import get_backend
    from ddt_tpu.config import TrainConfig
    from ddt_tpu.data.datasets import synthetic_binary
    from ddt_tpu.data.quantizer import fit_bin_mapper_streaming
    from ddt_tpu.driver import Driver
    from ddt_tpu.streaming import binned_chunks, fit_streaming

    X, y = synthetic_binary(4096, n_features=8, seed=3)

    def raw_fn(c):
        s = c * 1024
        return X[s:s + 1024], y[s:s + 1024]

    m = fit_bin_mapper_streaming(raw_fn, 4, n_bins=31, max_sample=10_000)
    cfg = TrainConfig(n_trees=3, max_depth=4, n_bins=31, backend="cpu")
    streamed = fit_streaming(binned_chunks(raw_fn, m, cfg), 4, cfg)
    full = Driver(get_backend(cfg), cfg, log_every=10**9).fit(
        m.transform(X), y)
    np.testing.assert_array_equal(full.feature, streamed.feature)
    np.testing.assert_array_equal(full.threshold_bin,
                                  streamed.threshold_bin)


def test_binned_chunks_validates_mapper_against_cfg():
    """The raw-chunk adapter enforces the same mapper-consistency guards
    as api.train: n_bins, missing policy, and identity-binned cat columns
    (a mismatched mapper silently corrupts training otherwise)."""
    import pytest

    from ddt_tpu.config import TrainConfig
    from ddt_tpu.data.quantizer import fit_bin_mapper
    from ddt_tpu.streaming import binned_chunks

    rng = np.random.default_rng(0)
    X = rng.standard_normal((500, 5)).astype(np.float32)
    m = fit_bin_mapper(X, n_bins=31)
    raw_fn = lambda c: (X, np.zeros(500))  # noqa: E731
    with pytest.raises(ValueError, match="n_bins"):
        binned_chunks(raw_fn, m, TrainConfig(n_bins=63))
    with pytest.raises(ValueError, match="missing"):
        binned_chunks(raw_fn, m, TrainConfig(n_bins=31,
                                             missing_policy="learn"))
    with pytest.raises(ValueError, match="identity-binned"):
        binned_chunks(raw_fn, m, TrainConfig(n_bins=31, cat_features=(1,)))
    f = binned_chunks(raw_fn, m, TrainConfig(n_bins=31))
    assert f.n_features == 5
    np.testing.assert_array_equal(f.labels(0), np.zeros(500))


def test_device_transform_bit_identical():
    """ops/quantize.transform_binned == BinMapper.transform on every edge
    case: NaN, +/-inf, exact edge hits, duplicate-edge runs, identity
    (categorical) columns, reserved NaN bin, and the row-block seam."""
    from ddt_tpu.data.quantizer import fit_bin_mapper

    rng = np.random.default_rng(2)
    for policy in ("zero", "learn"):
        X = rng.standard_normal((3000, 5)).astype(np.float32)
        X[:, 2] = np.round(np.abs(X[:, 2]) * 3)     # few distinct values
        X[:, 4] = rng.integers(0, 20, 3000)         # identity column
        X[rng.random(X.shape) < 0.05] = np.nan
        X[0, 0] = np.inf
        X[1, 0] = -np.inf
        m = fit_bin_mapper(X, n_bins=31, missing_policy=policy,
                           cat_features=(4,))
        X[5, 1] = m.edges[1, 3]                     # exact edge hit
        want = m.transform(X)
        got = m.transform_device(X)
        np.testing.assert_array_equal(want, got)
    # row-block seam: R not a multiple of the block
    from ddt_tpu.ops.quantize import transform_binned
    import jax.numpy as jnp

    Xb = rng.standard_normal((700, 3)).astype(np.float32)
    m = fit_bin_mapper(Xb, n_bins=15)
    got = np.asarray(transform_binned(
        jnp.asarray(Xb), jnp.asarray(m.edges), n_bins=15, row_block=256))
    np.testing.assert_array_equal(m.transform(Xb), got)
