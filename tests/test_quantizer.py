import numpy as np

from ddt_tpu.data.quantizer import BinMapper, fit_bin_mapper, quantize


def test_bins_in_range_and_dtype():
    rng = np.random.default_rng(0)
    X = rng.standard_normal((1000, 5)).astype(np.float32)
    Xb, mapper = quantize(X, n_bins=255)
    assert Xb.dtype == np.uint8
    assert Xb.min() >= 0 and Xb.max() <= 254
    assert mapper.edges.shape == (5, 254)


def test_bins_monotone_in_value():
    # Larger raw value never gets a smaller bin.
    rng = np.random.default_rng(1)
    X = rng.standard_normal((5000, 1)).astype(np.float32)
    Xb, _ = quantize(X, n_bins=64)
    order = np.argsort(X[:, 0])
    bins_sorted = Xb[order, 0].astype(int)
    assert (np.diff(bins_sorted) >= 0).all()


def test_quantile_balance():
    # Roughly equal mass per bin for continuous data.
    rng = np.random.default_rng(2)
    X = rng.standard_normal((100_000, 1)).astype(np.float32)
    Xb, _ = quantize(X, n_bins=16)
    counts = np.bincount(Xb[:, 0], minlength=16)
    assert counts.min() > 100_000 / 16 * 0.8
    assert counts.max() < 100_000 / 16 * 1.2


def test_threshold_value_consistency():
    # Split semantics: bin <= t  <=>  value <= threshold_value(f, t).
    rng = np.random.default_rng(3)
    X = rng.standard_normal((20_000, 3)).astype(np.float32)
    Xb, mapper = quantize(X, n_bins=32)
    for f in range(3):
        for t in (0, 7, 15, 30):
            left_by_bin = Xb[:, f] <= t
            left_by_val = X[:, f] <= mapper.threshold_value(f, t)
            assert (left_by_bin == left_by_val).all(), (f, t)


def test_constant_feature():
    X = np.ones((100, 2), dtype=np.float32)
    Xb, _ = quantize(X, n_bins=255)
    assert (Xb == Xb[0, 0]).all()  # single bin used


def test_nan_policy():
    X = np.array([[np.nan], [0.0], [1.0]], dtype=np.float32)
    mapper = fit_bin_mapper(np.array([[0.0], [0.5], [1.0]], np.float32), 8)
    Xb = mapper.transform(X)
    assert Xb[0, 0] == 0  # NaN -> bin 0 (documented v1 policy)


def test_save_load_roundtrip():
    rng = np.random.default_rng(4)
    X = rng.standard_normal((500, 4)).astype(np.float32)
    _, mapper = quantize(X, n_bins=100)
    m2 = BinMapper.load(mapper.save())
    assert np.array_equal(m2.edges, mapper.edges)
    assert m2.n_bins == mapper.n_bins
    assert np.array_equal(m2.transform(X), mapper.transform(X))
