"""Training-megakernel parity: the VMEM-streaming Pallas histogram kernel
vs the matmul and segment-sum paths, the sibling-subtraction trick, and
the fused hist->gain->route level round.

Bit-exactness methodology: histogram values are f32 sums whose BITS
depend on accumulation order, so cross-implementation equality is only
testable bitwise when every partial sum is exactly representable — the
sweep therefore draws g/h from SMALL INTEGERS (sums stay << 2^24) and
forces float32 kernel inputs, making pallas == matmul == segment a
bit-for-bit assertion across tile orders (the same trick makes the
subtraction assembly provably exact). Float-valued tolerance parity
stays in tests/test_hist_pallas.py. Mirrors the sweep structure of
tests/test_predict_pallas.py: bin widths x class counts x ragged
row/feature remainders x reserved-missing-bin mass.
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ddt_tpu import api
from ddt_tpu.config import TrainConfig
from ddt_tpu.data.datasets import synthetic_binary
from ddt_tpu.data.quantizer import quantize
from ddt_tpu.ops import grow as grow_ops
from ddt_tpu.ops.hist_pallas import (
    _bins_pad, build_histograms_pallas, pallas_fits)
from ddt_tpu.ops.histogram import (
    build_histograms_matmul, build_histograms_segment)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _int_case(R, F, B, N, C=1, seed=0, frozen_frac=0.2, missing_frac=0.0):
    """Integer-valued g/h (exact in f32 under ANY summation order) +
    binned data, with optional mass parked in the reserved top bin."""
    rng = np.random.default_rng(seed)
    Xb = rng.integers(0, B, size=(R, F), dtype=np.uint8)
    if missing_frac:
        Xb[rng.random((R, F)) < missing_frac] = B - 1   # reserved NaN bin
    g = rng.integers(-8, 9, size=(R, C)).astype(np.float32)
    h = rng.integers(0, 9, size=(R, C)).astype(np.float32)
    ni = rng.integers(0, N, size=R).astype(np.int32)
    ni[rng.random(R) < frozen_frac] = -1
    if C == 1:
        g, h = g[:, 0], h[:, 0]
    return Xb, g, h, ni


@pytest.mark.parametrize("B", [16, 64, 255])
@pytest.mark.parametrize("C", [1, 3])
@pytest.mark.parametrize("R,F,N,missing", [
    (515, 5, 4, 0.0),       # ragged row remainder vs the 256 tile, odd F
    (1024, 7, 32, 0.0),     # tile-aligned rows, widest depth-6 level
    (700, 3, 8, 0.15),      # reserved-bin (missing) mass + row remainder
])
def test_kernel_bitexact_parity_sweep(B, C, R, F, N, missing):
    """THE parity contract: with f32 inputs and integer-valued g/h the
    VMEM-streaming kernel, the one-hot matmul path, and the segment-sum
    path agree BIT-FOR-BIT — per class, at every bin width, through
    ragged remainders and reserved-bin mass."""
    Xb, g, h, ni = _int_case(R, F, B, N, C=C, seed=B + C,
                             missing_frac=missing)
    for c in range(C):
        gc = g[:, c] if C > 1 else g
        hc = h[:, c] if C > 1 else h
        want = np.asarray(build_histograms_segment(Xb, gc, hc, ni, N, B))
        mat = np.asarray(build_histograms_matmul(
            Xb, gc, hc, ni, N, B, input_dtype=jnp.float32))
        pal = np.asarray(build_histograms_pallas(
            Xb, gc, hc, ni, N, B, tile_r=256, interpret=True,
            input_dtype=jnp.float32))
        np.testing.assert_array_equal(want, mat)
        np.testing.assert_array_equal(want, pal)


def test_subtraction_assembly_bitexact():
    """level_histograms' sibling subtraction vs a direct full-level
    build, bitwise (integer g/h): left children are the same sums, and
    right = parent - left is exact when every sum is an integer."""
    R, F, B = 2000, 4, 31
    rng = np.random.default_rng(7)
    Xb, g, h, _ = _int_case(R, F, B, 1, seed=7, frozen_frac=0.0)
    # Parent level: 2 nodes, a few rows frozen before it.
    ni_parent = rng.integers(0, 2, size=R).astype(np.int32)
    ni_parent[rng.random(R) < 0.1] = -1
    parent = grow_ops.level_histograms(
        jnp.asarray(Xb), jnp.asarray(g), jnp.asarray(h),
        jnp.asarray(ni_parent), 2, B, hist_impl="segment")
    # Child level: parent 0 split (children 0/1), parent 1 froze.
    go_right = Xb[:, 0] > 10
    ni_child = np.where(ni_parent == 0, go_right.astype(np.int32), -1)
    ni_child = ni_child.astype(np.int32)
    parent_split = jnp.asarray([True, False])
    got = np.asarray(grow_ops.level_histograms(
        jnp.asarray(Xb), jnp.asarray(g), jnp.asarray(h),
        jnp.asarray(ni_child), 4, B, hist_impl="segment",
        parent_hist=parent, parent_split=parent_split))
    want = np.asarray(grow_ops.level_histograms(
        jnp.asarray(Xb), jnp.asarray(g), jnp.asarray(h),
        jnp.asarray(ni_child), 4, B, hist_impl="segment"))
    np.testing.assert_array_equal(got, want)
    # The frozen parent's phantom children carry EXACTLY zero mass.
    assert np.all(got[2:] == 0.0)


def test_grow_subtraction_identical_structure():
    """grow_tree with the trick on vs off: identical split decisions
    (feature/threshold/leaf-ness/default-direction bitwise), leaf values
    to f32 tolerance — the split-agreement-unchanged contract."""
    import functools

    rng = np.random.default_rng(0)
    R = 4000
    Xb = jnp.asarray(rng.integers(0, 31, size=(R, 8), dtype=np.uint8))
    g = jnp.asarray(rng.standard_normal(R).astype(np.float32))
    h = jnp.asarray((rng.random(R) * 0.25 + 0.01).astype(np.float32))
    kw = dict(max_depth=4, n_bins=31, reg_lambda=1.0,
              min_child_weight=1e-3, min_split_gain=0.0)
    off = jax.jit(functools.partial(grow_ops.grow_tree,
                                    hist_subtraction=False, **kw))(Xb, g, h)
    on = jax.jit(functools.partial(grow_ops.grow_tree,
                                   hist_subtraction=True, **kw))(Xb, g, h)
    np.testing.assert_array_equal(np.asarray(off.feature),
                                  np.asarray(on.feature))
    np.testing.assert_array_equal(np.asarray(off.threshold_bin),
                                  np.asarray(on.threshold_bin))
    np.testing.assert_array_equal(np.asarray(off.is_leaf),
                                  np.asarray(on.is_leaf))
    np.testing.assert_array_equal(np.asarray(off.default_left),
                                  np.asarray(on.default_left))
    np.testing.assert_array_equal(np.asarray(off.leaf_of_row),
                                  np.asarray(on.leaf_of_row))
    np.testing.assert_allclose(np.asarray(off.leaf_value),
                               np.asarray(on.leaf_value),
                               rtol=1e-4, atol=1e-6)


@pytest.mark.parametrize("loss_kw", [
    {},                                       # binary
    {"loss": "softmax", "n_classes": 3},      # C = 3 trees per round
    {"missing_policy": "learn"},              # reserved-bin routing
])
def test_fused_vs_granular_bitexact_with_subtraction(loss_kw):
    """The Driver's fused multi-round path vs the granular per-tree path
    with subtraction forced ON: both trace the same grow_tree program,
    so tree STRUCTURE must match bitwise and leaf values to the same
    FMA-contraction tolerance the two paths already had (the
    fused == granular contract the subtraction trick must not widen)."""
    from ddt_tpu.backends import get_backend
    from ddt_tpu.driver import Driver

    X, y = synthetic_binary(2500, n_features=6, seed=11)
    if loss_kw.get("loss") == "softmax":
        y = (y + (X[:, 0] > 0)).astype(np.int32)
    Xb, _ = quantize(X, n_bins=31, seed=11)
    cfg = TrainConfig(n_trees=4, max_depth=3, n_bins=31, backend="tpu",
                      hist_subtraction="on", **loss_kw)
    fused = api.train(Xb, y, cfg, binned=True, log_every=10**9).ensemble
    be = get_backend(cfg)
    gran = Driver(be, cfg, log_every=10**9, profile=True).fit(Xb, y)
    np.testing.assert_array_equal(fused.feature, gran.feature)
    np.testing.assert_array_equal(fused.threshold_bin, gran.threshold_bin)
    np.testing.assert_array_equal(fused.is_leaf, gran.is_leaf)
    np.testing.assert_allclose(fused.leaf_value, gran.leaf_value,
                               rtol=1e-5, atol=1e-6)


def test_subtraction_distributed_matches_single_device():
    """4-partition row-sharded growth with subtraction ON vs single
    device: the trick halves the allreduce payload (hist_left only), and
    within one controller the psum'd left builds + replicated subtraction
    must reproduce the single-device decisions bitwise (the ops/split.py
    single-controller contract), leaf values to float tolerance."""
    X, y = synthetic_binary(2400, n_features=5, seed=23)
    Xb, _ = quantize(X, n_bins=31, seed=23)
    kw = dict(n_trees=3, max_depth=3, n_bins=31, backend="tpu",
              hist_subtraction="on")
    one = api.train(Xb, y, TrainConfig(**kw), binned=True,
                    log_every=10**9).ensemble
    four = api.train(Xb, y, TrainConfig(n_partitions=4, **kw),
                     binned=True, log_every=10**9).ensemble
    np.testing.assert_array_equal(one.feature, four.feature)
    np.testing.assert_array_equal(one.threshold_bin, four.threshold_bin)
    np.testing.assert_array_equal(one.is_leaf, four.is_leaf)
    np.testing.assert_allclose(one.leaf_value, four.leaf_value,
                               rtol=1e-5, atol=1e-6)
    # Column-sharded histogramming composes too: each feature shard
    # subtracts within its own columns (the per-shard node totals come
    # from row vectors, unchanged by the trick).
    fp = api.train(Xb, y, TrainConfig(n_partitions=2,
                                      feature_partitions=2, **kw),
                   binned=True, log_every=10**9).ensemble
    np.testing.assert_array_equal(one.feature, fp.feature)
    np.testing.assert_array_equal(one.threshold_bin, fp.threshold_bin)
    np.testing.assert_allclose(one.leaf_value, fp.leaf_value,
                               rtol=1e-5, atol=1e-6)


def test_resolve_hist_subtraction():
    assert grow_ops.resolve_hist_subtraction("on") is True
    assert grow_ops.resolve_hist_subtraction("off") is False
    # auto follows the platform: off everywhere but a real TPU chip.
    assert grow_ops.resolve_hist_subtraction("auto", platform="cpu") is False
    assert grow_ops.resolve_hist_subtraction("auto", platform="tpu") is True
    with pytest.raises(ValueError, match="hist_subtraction"):
        grow_ops.resolve_hist_subtraction("maybe")
    with pytest.raises(ValueError, match="hist_subtraction"):
        TrainConfig(hist_subtraction="sometimes")


def test_bins_pad_64_promotion():
    """The 64-bin layout is automatic dispatch now: n_bins <= 64 pads to
    64 SUBLANES (transposed kernel), not the old 128-lane tile — half
    the one-hot footprint, and the VMEM budget math must agree."""
    assert _bins_pad(16) == 64
    assert _bins_pad(64) == 64
    assert _bins_pad(65) == 128
    assert _bins_pad(128) == 128
    assert _bins_pad(129) == 256
    assert _bins_pad(255) == 256
    # The halved padding admits shapes the 128-lane layout would have
    # chunked: budget scales linearly in bins_pad.
    assert pallas_fits(64, 28, 64)
    # and the headline 255-bin shape still fits single-slab at N=32.
    assert pallas_fits(32, 28, 255)


def test_fused_round_scopes_in_compiled_program():
    """The new sub-spans are HLO metadata on the compiled grow program:
    ddt:fused_round wraps each level, ddt:hist:subtract the sibling
    assembly, ddt:hist:{stream,flush} the Pallas kernel's accumulation
    and its one HBM flush (named scopes survive into the compiled
    executable's op metadata, not the StableHLO text)."""
    import functools

    rng = np.random.default_rng(0)
    Xb = jnp.asarray(rng.integers(0, 15, size=(300, 3), dtype=np.uint8))
    g = jnp.asarray(rng.standard_normal(300).astype(np.float32))
    h = jnp.asarray((rng.random(300) * 0.25 + 0.01).astype(np.float32))
    kw = dict(max_depth=2, n_bins=15, reg_lambda=1.0,
              min_child_weight=1e-3, min_split_gain=0.0)
    txt = jax.jit(functools.partial(
        grow_ops.grow_tree, hist_subtraction=True, hist_impl="segment",
        **kw)).lower(Xb, g, h).compile().as_text()
    for scope in ("ddt:fused_round", "ddt:hist", "ddt:hist:subtract",
                  "ddt:gain", "ddt:route", "ddt:leaf"):
        assert scope in txt, scope
    # The kernel sub-spans ride the pallas dispatcher.
    fn = jax.jit(functools.partial(
        build_histograms_pallas, n_nodes=2, n_bins=15, tile_r=256,
        interpret=True))
    ktxt = fn.lower(Xb, g, h, jnp.zeros(300, jnp.int32)).compile().as_text()
    assert "ddt:hist:stream" in ktxt
    assert "ddt:hist:flush" in ktxt


def test_kernel_smoke_script():
    """scripts/kernel_smoke.py (make kernel-smoke) stays green — the
    2-round interpret-mode smoke is tier-1-reachable through here, the
    same pattern as the telemetry/trace/profile smokes."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "kernel_smoke", os.path.join(REPO, "scripts", "kernel_smoke.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.main() == 0
