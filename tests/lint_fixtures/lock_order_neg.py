"""Fixture: clean lock usage — nesting always in one global order, a
trylock under another lock (cannot deadlock), and sequential (never
nested) acquisition."""
import threading


class Engine:
    def __init__(self):
        self._cv = threading.Condition()
        self._gate = threading.Lock()

    def nested_one_order(self):
        with self._cv:
            with self._gate:
                pass

    def also_that_order(self):
        with self._cv:
            with self._gate:
                pass

    def trylock_under_lock(self):
        # Opposite order, but non-blocking: a trylock returns instead of
        # waiting, so it cannot complete a deadlock cycle.
        with self._gate:
            got = self._cv.acquire(blocking=False)
            if got:
                try:
                    pass
                finally:
                    self._cv.release()

    def sequential(self):
        with self._gate:
            pass
        with self._cv:
            pass
