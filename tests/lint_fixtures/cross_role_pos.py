"""Fixture: cross-role unguarded attribute — written on the handler
role (public method), read on the dispatcher role (thread-target loop),
no common lock, no atomic-publish annotation."""
import threading


class Engine:
    def __init__(self):
        self._lock = threading.Lock()
        self.model = object()
        self.limit = 4
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        while True:
            use(self.model)
            use(self.limit)

    def swap(self, new):
        self.model = new        # LINT: cross-role-state

    def resize(self, n):
        with self._lock:
            self.limit = n      # LINT: cross-role-state (reader unlocked)


def use(x):
    return x
