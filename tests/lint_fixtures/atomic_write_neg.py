"""atomic-artifact-write negative fixture: the compliant patterns —
tmp-then-os.replace, reads, appends, tempfile-derived targets."""

import json
import os
import tempfile

import numpy as np


def save_model(path, arrays):
    tmp = path + ".tmp.npz"
    np.savez_compressed(tmp, **arrays)
    os.replace(tmp, path)


def write_cursor(path, cur):
    tmp_c = path + ".tmp"
    with open(tmp_c, "w") as f:
        json.dump(cur, f)
    os.replace(tmp_c, path)


def read_cursor(path):
    with open(path) as f:
        return json.load(f)


def read_bytes(path):
    with open(path, "rb") as f:
        return f.read()


def append_log(path, line):
    # Append-only run logs are line-granular by design, not artifact
    # overwrites — the crash story is a torn final line, tolerated at
    # read time.
    with open(path, "a") as f:
        f.write(line)


def scratch_dump(arrays):
    with tempfile.NamedTemporaryFile(suffix=".npz") as tmp_f:
        np.savez(tmp_f.name, **arrays)
        return tmp_f.name
