# Fixture: host-sync MUST fire (linted under ddt_tpu/ops/grow.py path).
import numpy as np


def hot_loop(arrs, dev):
    total = 0.0
    for a in arrs:
        total += float(a)  # LINT: host-sync
        v = a.item()  # LINT: host-sync
        host = np.asarray(a)  # LINT: host-sync
        dev.consume(v, host)
    while total > 0:
        total -= int(dev.step())  # LINT: host-sync
    fetched = [np.asarray(o) for o in arrs]  # LINT: host-sync
    return total, fetched
