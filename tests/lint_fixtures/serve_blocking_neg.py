"""serve-blocking-io negative fixture: the compliant hot-loop idioms —
Condition/Event parking with timeouts, in-memory numpy work, and model
objects handed in ready (no file I/O)."""
import collections
import threading
import time

import numpy as np


class Batcher:
    def __init__(self, dispatch):
        self._q = collections.deque()
        self._cv = threading.Condition()
        self._dispatch = dispatch

    def loop(self, max_wait_s):
        with self._cv:
            while not self._q:
                self._cv.wait()                 # park, don't poll
            deadline = time.perf_counter() + max_wait_s
            remaining = deadline - time.perf_counter()
            if remaining > 0:
                self._cv.wait(remaining)        # admission window
            batch = list(self._q)
            self._q.clear()
        rows = np.concatenate([b.rows for b in batch])
        self._dispatch(rows)


def wait_result(event: threading.Event, timeout):
    return event.wait(timeout)
