"""pallas-vmem-guard negatives: the hist_pallas dispatch idiom — a
VMEM-fits predicate in the dispatching function itself, in a direct
caller, or two levels up the module-local call chain."""
import functools

import jax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_BUDGET = 12 * 1024 * 1024


def _kernel(x_ref, o_ref, *, scale):
    o_ref[:] = x_ref[:] * scale


def my_shape_fits(rows, cols):
    return rows * cols * 4 <= _BUDGET


def guarded_inline(x, interpret=None):
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if not my_shape_fits(*x.shape):
        raise ValueError("shape exceeds the VMEM budget")
    return pl.pallas_call(
        functools.partial(_kernel, scale=2),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=interpret,
    )(x)


def _inner_kernel_call(x, interpret):
    return pl.pallas_call(
        functools.partial(_kernel, scale=3),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        interpret=interpret,
    )(x)


def _mid_dispatch(x, interpret):
    return _inner_kernel_call(x, interpret)


def guarded_top_dispatcher(x, interpret=None):
    # the guard sits two module-local call levels above the pallas_call
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if not my_shape_fits(*x.shape):
        raise ValueError("shape exceeds the VMEM budget")
    return _mid_dispatch(x, interpret)


def feature_chunks_for(rows, cols):
    # chunk-count predicates count as guards too (the hist_pallas form)
    for k in range(1, cols + 1):
        if my_shape_fits(rows, -(-cols // k)):
            return k
    return None


def guarded_by_chunking(x, interpret=None):
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if feature_chunks_for(*x.shape) is None:
        raise ValueError("no chunking fits the VMEM budget")
    return _inner_kernel_call(x, interpret)


class GuardedBackend:
    """Method units: a guard in the method (or a caller) satisfies the
    rule the same way it does for module-level functions."""

    def dispatch(self, x, interpret=True):
        if not my_shape_fits(*x.shape):
            raise ValueError("shape exceeds the VMEM budget")
        return pl.pallas_call(
            functools.partial(_kernel, scale=6),
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            interpret=interpret,
        )(x)


def quant_shape_fits(rows, cols, input_bytes=2, grad_bytes=4,
                     acc_bytes=4):
    """The ISSUE 14 re-budgeted predicate form: itemsizes come from the
    ACTUAL operand dtypes (quantized int8/int16 gradients, int32
    scratch) instead of hard-coded f32 assumptions."""
    return (rows * cols * input_bytes + rows * (2 * grad_bytes + 4)
            + cols * acc_bytes) <= _BUDGET


def guarded_quantized(x, qg, interpret=None):
    # Quantized dispatch (int32 VMEM scratch): the dtype-parameterized
    # fits predicate on the dispatch chain satisfies the rule exactly
    # like the f32 form — the re-budget cannot shake the guard off.
    import jax.numpy as jnp

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if not quant_shape_fits(*x.shape, input_bytes=qg.dtype.itemsize,
                            grad_bytes=qg.dtype.itemsize):
        raise ValueError("shape exceeds the VMEM budget")
    return pl.pallas_call(
        functools.partial(_kernel, scale=4),
        out_shape=jax.ShapeDtypeStruct(x.shape, jnp.int32),
        scratch_shapes=[pltpu.VMEM(x.shape, jnp.int32)],
        interpret=interpret,
    )(x)
