# Fixture: traced-branch MUST fire (linted under a ddt_tpu/ops/ path).
import jax
import jax.numpy as jnp


@jax.jit
def bad_branch(x):
    m = jnp.sum(x)
    if m > 0:  # LINT: traced-branch
        return x
    y = x if jnp.any(x) else -x  # LINT: traced-branch
    return y


def traced_body(x):
    s = jnp.max(x)
    while s > 1.0:  # LINT: traced-branch
        s = s / 2.0
    return s


halver = jax.jit(traced_body)


def helper(x):
    # not decorated itself, but called from a jit root below
    t = jnp.min(x)
    if t < 0:  # LINT: traced-branch
        return -x
    return x


@jax.jit
def root(x):
    return helper(x)
