"""atomic-artifact-write positive fixture: persistent artifacts written
directly to their final paths (torn on a mid-write kill)."""

import json

import numpy as np


def save_model(path, arrays):
    np.savez_compressed(path, **arrays)  # LINT: atomic-artifact-write


def save_scores(path, scores):
    np.save(path, scores)  # LINT: atomic-artifact-write


def write_cursor(path, cur):
    with open(path, "w") as f:  # LINT: atomic-artifact-write
        json.dump(cur, f)


def write_manifest(path, text):
    with open(path, mode="w") as f:  # LINT: atomic-artifact-write
        f.write(text)
