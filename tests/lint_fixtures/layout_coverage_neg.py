"""Fixture: every operand name matches the rule table; non-layout
receivers with spec() methods are someone else's API."""


def build(lay):
    a = lay.specs("data", "grad", "hess", "node_index")
    b = lay.spec("pred1d")
    c = lay.specs("tree", "winners", "scalar", "fmasks")
    d = lay.specs(*(["replicated"] * 5))     # non-literal star: skipped
    return a, b, c, d


def other_api(catalog):
    return catalog.spec("anything_goes_here")
