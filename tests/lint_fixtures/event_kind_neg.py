"""undeclared-event-kind negative: every literal kind is catalogued;
variable kinds and splatted payloads are deliberately skipped (missed
findings over false positives)."""

EVENT_FIELDS = {
    "round": ("round", "ms_per_round"),
    "fault": ("kind",),
}
EVENT_EXTRAS = {
    "round": ("train_loss",),
    "fault": ("round", "error"),
}
FAULT_KINDS = ("retry", "injected")
SCHEMA_VERSION = 5


class Log:
    def emit(self, kind, **fields):
        pass

    def emit_fault(self, kind, **fields):
        self.emit("fault", kind=kind, **fields)


def run(log, dynamic_kind):
    log.emit("round", round=1, ms_per_round=3.5, train_loss=0.4)
    log.emit("fault", kind="retry", round=2)
    log.emit_fault("injected", round=3)
    log.emit(dynamic_kind, round=4)          # non-literal kind: skipped
