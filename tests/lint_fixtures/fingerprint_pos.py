"""fingerprint-field-coverage positive: the exclude list names
`log_every`, which is no current TrainConfig field — a renamed field
left a stale exclusion behind, and whatever replaced it is being
fingerprinted (or excluded) by accident."""
import dataclasses
import hashlib
import json


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    max_depth: int = 6
    n_bins: int = 255
    verbose: bool = False


def _cfg_fingerprint(cfg):
    d = dataclasses.asdict(cfg)
    for k in (
        "verbose",
        "log_every",  # LINT: fingerprint-field-coverage
    ):
        d.pop(k, None)
    blob = json.dumps(d, sort_keys=True)
    return hashlib.sha256(blob.encode()).hexdigest()
