"""Fixture: disciplined acquisition — `with`, and acquire immediately
guarded by try/finally (the express-lane shape)."""
import threading


class Batcher:
    def __init__(self):
        self._gate = threading.Lock()
        self._cv = threading.Condition()

    def with_block(self):
        with self._gate:
            do_work()

    def guarded(self, rows):
        with self._cv:
            if not self._gate.acquire(blocking=False):
                return None
        try:
            req = make_request(rows)
            return dispatch(req)
        finally:
            self._gate.release()


def do_work():
    pass


def make_request(rows):
    return rows


def dispatch(req):
    return req
