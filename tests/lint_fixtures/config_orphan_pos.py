"""config-field-orphan positive, both arms: `checkpoint_every` is in no
contract (not in _JIT_FIELDS, popped out of the fingerprint, not
annotated), and a derive_run_id call enumerates kwargs explicitly but
leaves fields out. `log_every` shows the legal escape hatch."""
import dataclasses
import hashlib
import json


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    max_depth: int = 6
    n_bins: int = 255
    checkpoint_every: int = 0  # LINT: config-field-orphan
    log_every: int = 50  # ddtlint: trace-inert — logging cadence only: shapes neither the compiled program nor the trained model, deliberately contract-less


_JIT_FIELDS = ("max_depth", "n_bins")


def _cache_key(cfg):
    return tuple(getattr(cfg, f) for f in _JIT_FIELDS)


def _cfg_fingerprint(cfg):
    d = dataclasses.asdict(cfg)
    for k in ("checkpoint_every", "log_every"):
        d.pop(k, None)
    return hashlib.sha256(
        json.dumps(d, sort_keys=True).encode()).hexdigest()


def derive_run_id(**fields):
    return hashlib.sha256(repr(sorted(fields.items())).encode()).hexdigest()


def start_run(cfg):
    return derive_run_id(  # LINT: config-field-orphan
        max_depth=cfg.max_depth, n_bins=cfg.n_bins)
