"""Fixture: blocking I/O OUTSIDE any lock (the file-scope
serve-blocking-io rule may still have opinions; lock scope does not),
and Condition.wait parking (the sanctioned time-based wait)."""
import json
import threading
import time


class Batcher:
    def __init__(self):
        self._gate = threading.Lock()
        self._cv = threading.Condition()

    def load_then_lock(self, path):
        with open(path) as f:
            data = json.load(f)
        with self._gate:
            return data

    def park(self):
        with self._cv:
            self._cv.wait(0.01)     # parking on the Condition is the idiom

    def unlocked_sleep(self):
        time.sleep(0)               # file-scope rule's business, not ours
