"""no-print positive fixture: bare builtin print() in library code."""


def dump_progress(rnd, loss):
    print("round", rnd, "loss", loss)             # LINT: no-print
    if loss > 1.0:
        print(f"diverging: {loss}")               # LINT: no-print


def nested():
    def inner(x):
        print(x)                                  # LINT: no-print
    return inner
