"""jit-cache-key-coverage positive: `cfg.row_tile` is read inside the
jit-traced grow body but `row_tile` is in neither _JIT_FIELDS nor the
return expression of _cache_key — a cached backend compiled under one
tile size would be silently reused for another. The mini-contract
anchors (TrainConfig, _JIT_FIELDS, _cache_key) are embedded so the
single-file fixture model resolves."""
import dataclasses

import jax


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    max_depth: int = 6
    n_bins: int = 255
    subsample: float = 1.0
    seed: int = 0
    row_tile: int = 128


_JIT_FIELDS = ("max_depth", "n_bins", "subsample")


def _cache_key(cfg):
    seed_live = cfg.subsample < 1.0
    return tuple(getattr(cfg, f) for f in _JIT_FIELDS) + (
        cfg.seed if seed_live else 0,
    )


def make_grow(cfg):
    def grow(x):
        depth = x * cfg.max_depth
        return depth + cfg.row_tile  # LINT: jit-cache-key-coverage
    return jax.jit(grow)
