# Fixture: collective-consistency must stay SILENT.
import jax


def reduce_ok(x):
    return jax.lax.psum(x, "rows")


def pod_ok(x):
    return jax.lax.psum(x, ("hosts", "rows"))


def feature_ok(x):
    return jax.lax.all_gather(x, axis_name="features")


def plumbed_ok(x, axis_name):
    # variable axis names are the safe pattern (resolved from the mesh)
    return jax.lax.psum(x, axis_name)
