# Fixture: collective-consistency MUST fire (axes: rows/hosts/features).
import jax


def reduce_bad(x):
    return jax.lax.psum(x, "cols")  # LINT: collective-consistency


def gather_bad(x):
    return jax.lax.all_gather(x, axis_name="replica")  # LINT: collective-consistency


def index_bad():
    return jax.lax.axis_index("batch")  # LINT: collective-consistency


def tuple_bad(x):
    return jax.lax.psum(x, ("hosts", "shards"))  # LINT: collective-consistency
