"""raw-phase-timing negative fixture: the sanctioned timing paths —
PhaseTimer phases, phase_ctx spans, named scopes — plus time-module
uses that are not clock reads."""

import time

from ddt_tpu.telemetry.annotations import phase_ctx, traced_scope


def grow_level(timer, dispatch, hist):
    ph = phase_ctx(timer)
    with ph("hist"):                              # the trainer-layer home
        out = dispatch(hist)
    return out


def traced(x):
    with traced_scope("hist"):                    # device-side attribution
        return x + 1


def backoff(retries):
    time.sleep(0.01 * retries)                    # a sleep, not a clock


def injected(clock):
    return clock()                                # parameter, not time.*


def strftime_label():
    return time.strftime("%Y%m%d")                # formatting, not timing
