"""pallas-interpret negatives: the hist_pallas dispatch idiom — an
`interpret` parameter auto-selected off-TPU and threaded to every
pallas_call as a live variable (True constants are fine too: tests may
force the interpreter)."""
import functools

import jax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _kernel(x_ref, o_ref, *, scale):
    o_ref[:] = x_ref[:] * scale


def dispatch(x, interpret=None):
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return pl.pallas_call(
        functools.partial(_kernel, scale=2),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=interpret,
    )(x)


def forced_interpreter(x):
    return pl.pallas_call(
        functools.partial(_kernel, scale=3),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        interpret=True,
    )(x)
