"""Fixture: axis names threaded correctly — imported constants,
parameters, and strings that merely COINCIDE with axis names in
non-axis positions (dict keys, bench metadata)."""
import jax.numpy as jnp

from ddt_tpu.parallel import comms
from ddt_tpu.parallel import mesh as mesh_lib

AXIS = mesh_lib.ROWS_AXIS              # alias the constant, not the string
ROW_AXES = (mesh_lib.HOSTS_AXIS, mesh_lib.ROWS_AXIS)


def reduce_it(x, axis_name):
    return comms.psum(x, axis_name)    # threaded parameter: the pattern


def kwarg_form(x, axis):
    return comms.hist_reduce(x, axis_name=axis)


def metadata(rows, features):
    # bench/metrics dicts spell dimension NAMES, not mesh axes.
    return {"rows": rows, "features": features, "hosts": 1}


def unrelated_literal():
    label = "rows"                     # not axis-named, not axis-passed
    return label
