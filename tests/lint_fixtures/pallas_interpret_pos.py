"""pallas-interpret positives: pallas_call sites with no live interpret
operand — no interpret kwarg at all, and hard-coded False/None."""
import functools

import jax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _kernel(x_ref, o_ref, *, scale):
    o_ref[:] = x_ref[:] * scale


def missing_interpret(x):
    return pl.pallas_call(  # LINT: pallas-interpret
        functools.partial(_kernel, scale=2),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
    )(x)


def hard_false(x):
    return pl.pallas_call(  # LINT: pallas-interpret
        functools.partial(_kernel, scale=3),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        interpret=False,
    )(x)


def hard_none(x):
    return pl.pallas_call(  # LINT: pallas-interpret
        functools.partial(_kernel, scale=4),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        interpret=None,
    )(x)
