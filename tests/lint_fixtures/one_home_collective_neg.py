"""one-home-collective negative fixture: the sanctioned spellings —
the comms module's wrappers, topology reads, and names that merely
resemble collectives."""

import jax

from ddt_tpu.parallel import comms


def merge_hist(hist, axis):
    return comms.psum(hist, axis)                 # the one-home wrapper


def scatter_hist(hist, axis):
    return comms.reduce_scatter(hist, axis, dim=1)


def gather_winners(gains, feats, bins, dls, axis):
    return comms.combine_shard_winners(
        gains, feats, bins, dls, axis, n_features=8, n_bins=16)


def shard_offset(axis):
    # Topology reads are not traffic.
    return jax.lax.axis_index(axis) * jax.lax.axis_size(axis)


def local_reduce(psum, x, axis):
    return psum(x, axis)                          # injected callable


class Reducer:
    def psum(self, x, axis):                      # method named psum
        return x

    def run(self, x, axis):
        return self.psum(x, axis)
