"""counter-direction-missing positive: a `_c` counter with no
COUNTER_DIRECTIONS entry, one with an invalid direction value, and an
epilogue counter (subscript-assigned then splatted into
`emit("counters", **d)`) the directions table never learned."""

EVENT_FIELDS = {
    "counters": ("jit_compiles",),
}
EVENT_EXTRAS = {
    "counters": ("h2d_bytes", "serve_requests", "sideways_counter",
                 "device_peak_bytes"),
}
SCHEMA_VERSION = 5

_c = {
    "jit_compiles": 0,
    "h2d_bytes": 0,
    "serve_requests": 0,  # LINT: counter-direction-missing
    "sideways_counter": 0,  # LINT: counter-direction-missing
}

COUNTER_DIRECTIONS = {
    "jit_compiles": "lower",
    "h2d_bytes": "lower",
    "sideways_counter": "diagonal",
}


class Log:
    def emit(self, kind, **fields):
        pass


def finish(log):
    d = dict(_c)
    d["device_peak_bytes"] = 1  # LINT: counter-direction-missing
    log.emit("counters", **d)
