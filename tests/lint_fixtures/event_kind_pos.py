"""undeclared-event-kind positive: a typo'd event kind at an emit site,
a fault `kind=` literal outside FAULT_KINDS, and an emit_fault() call
with an uncatalogued kind. The mini-catalogs are embedded so the
single-file fixture model resolves."""

EVENT_FIELDS = {
    "round": ("round", "ms_per_round"),
    "fault": ("kind",),
}
EVENT_EXTRAS = {
    "round": ("train_loss",),
    "fault": ("round", "error"),
}
FAULT_KINDS = ("retry", "injected")
SCHEMA_VERSION = 5


class Log:
    def emit(self, kind, **fields):
        pass

    def emit_fault(self, kind, **fields):
        self.emit("fault", kind=kind, **fields)


def run(log):
    log.emit("round", round=1, ms_per_round=3.5)
    log.emit("rond", round=2)  # LINT: undeclared-event-kind
    log.emit("fault", kind="retyr")  # LINT: undeclared-event-kind
    log.emit_fault("cosmic_ray", round=3)  # LINT: undeclared-event-kind
