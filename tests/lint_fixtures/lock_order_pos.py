"""Fixture: lock-order inversion — two methods take the same two locks
in opposite orders (the classic AB/BA deadlock)."""
import threading


class Engine:
    def __init__(self):
        self._cv = threading.Condition()
        self._gate = threading.Lock()

    def path_a(self):
        with self._cv:
            with self._gate:    # LINT: lock-order
                pass

    def path_b(self):
        with self._gate:
            with self._cv:      # LINT: lock-order
                pass
