"""Fixture: operand names no SpecLayout rule matches — a trace-time
ValueError today, a lint finding now."""


def build(lay, mesh):
    in_specs = lay.specs("data", "bogus_operand")      # LINT: layout-rule-coverage
    out = lay.spec("another_unknown")                  # LINT: layout-rule-coverage
    return in_specs, out


def starred(layout):
    return layout.specs(*["data", "mystery_name"])     # LINT: layout-rule-coverage
