"""counter-direction-missing negative: every published counter —
registry keys and the epilogue's subscript-added key alike — carries a
valid COUNTER_DIRECTIONS entry ("neutral" is the declared-but-unbanded
direction for workload-shape counters)."""

EVENT_FIELDS = {
    "counters": ("jit_compiles",),
}
EVENT_EXTRAS = {
    "counters": ("h2d_bytes", "serve_requests", "device_peak_bytes"),
}
SCHEMA_VERSION = 5

_c = {
    "jit_compiles": 0,
    "h2d_bytes": 0,
    "serve_requests": 0,
}

COUNTER_DIRECTIONS = {
    "jit_compiles": "lower",
    "h2d_bytes": "lower",
    "serve_requests": "neutral",
    "device_peak_bytes": "lower",
}


class Log:
    def emit(self, kind, **fields):
        pass


def finish(log):
    d = dict(_c)
    d["device_peak_bytes"] = 1
    log.emit("counters", **d)
