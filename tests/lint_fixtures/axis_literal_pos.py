"""Fixture: mesh axis names as literals in axis-bearing positions
outside parallel/mesh.py."""
import jax.numpy as jnp

from ddt_tpu.parallel import comms

AXIS = "rows"                          # LINT: axis-name-literal
ROW_AXES = ("hosts", "rows")           # LINT: axis-name-literal


def reduce_it(x):
    return comms.psum(x, "rows")       # LINT: axis-name-literal


def gather_it(x, lax):
    return lax.all_gather(x, "features", axis=0)  # LINT: axis-name-literal


def kwarg_form(x):
    return comms.hist_reduce(x, axis_name="rows")  # LINT: axis-name-literal


def shard_index():
    return comms.flat_axis_index(("hosts", "rows"))  # LINT: axis-name-literal


def spec_form(P):
    return P("rows", None)             # LINT: axis-name-literal
