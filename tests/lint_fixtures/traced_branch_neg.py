# Fixture: traced-branch must stay SILENT.
import jax
import jax.numpy as jnp


@jax.jit
def static_branches(x, axis_name=None, flag=True):
    if axis_name is not None:          # `is` test: static python
        x = jax.lax.psum(x, axis_name)
    if flag:                           # parameter: treated as static arg
        x = x + 1
    backend = jax.default_backend()    # host value, not a tracer
    if backend == "cpu":
        x = x * 2
    if jnp.issubdtype(x.dtype, jnp.floating):   # host bool
        x = x + 0
    return x


def unreachable(x):
    # identical shape to a violation, but no jit root reaches it
    m = jnp.sum(x)
    if m > 0:
        return x
    return -x


@jax.jit
def no_nested_taint_leak(n):
    # inner's traced `y` is a separate scope: the OUTER `y` is a plain
    # python int and branching on it is fine.
    def inner(x):
        y = jnp.zeros(3, jnp.float32)
        return x + y
    y = 1
    if y:
        n = n + 1
    return inner(n)
