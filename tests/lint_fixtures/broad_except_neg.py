# Fixture: broad-except must stay SILENT.


def narrow():
    try:
        risky()
    except (ImportError, OSError):
        pass


def translate():
    try:
        risky()
    except Exception as e:           # re-raise pattern: exempt
        raise RuntimeError("context") from e
