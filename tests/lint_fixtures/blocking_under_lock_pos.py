"""Fixture: blocking calls while a lock / the dispatch gate is held —
directly, and through a resolved module-local call."""
import json
import threading
import time


class Batcher:
    def __init__(self):
        self._gate = threading.Lock()
        self._cv = threading.Condition()

    def dispatch(self):
        with self._gate:
            time.sleep(0.01)            # LINT: blocking-under-lock

    def load_model(self, path):
        with self._cv:
            f = open(path)              # LINT: blocking-under-lock
            return json.load(f)         # LINT: blocking-under-lock

    def indirect(self):
        with self._gate:
            self._read()                # LINT: blocking-under-lock

    def _read(self):
        with open("x") as f:
            return f.read()
