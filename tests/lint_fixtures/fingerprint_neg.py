"""fingerprint-field-coverage negative: the asdict + exclude-list idiom
with every exclusion naming a live TrainConfig field."""
import dataclasses
import hashlib
import json


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    max_depth: int = 6
    n_bins: int = 255
    verbose: bool = False
    log_every: int = 50


def _cfg_fingerprint(cfg):
    d = dataclasses.asdict(cfg)
    for k in (
        "verbose",
        "log_every",
    ):
        d.pop(k, None)
    blob = json.dumps(d, sort_keys=True)
    return hashlib.sha256(blob.encode()).hexdigest()
