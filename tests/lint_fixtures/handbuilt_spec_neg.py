"""Fixture: specs resolved through the declarative layout — the
sanctioned pattern (and a bare `P(...)` call where P is NOT the
PartitionSpec alias)."""
import jax

from ddt_tpu.parallel import mesh as mesh_lib


def sharded_fn(f, mesh, lay):
    return mesh_lib.shard_map(
        f, mesh=mesh,
        in_specs=lay.specs("data", "grad"),
        out_specs=lay.replicated(),
    )


def named(mesh, lay):
    return jax.sharding.NamedSharding(mesh, lay.row_vector())


def P(x):
    """A local helper that merely shares the short name."""
    return x


def not_a_spec():
    return P(3)
