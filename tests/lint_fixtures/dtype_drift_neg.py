# Fixture: dtype-drift must stay SILENT.
import jax.numpy as jnp


def make(n):
    a = jnp.zeros(n, jnp.float32)            # positional dtype
    b = jnp.ones((n, 2), dtype=jnp.int32)    # keyword dtype
    c = jnp.array([1, 2, 3], jnp.uint8)
    d = jnp.zeros_like(a)                    # inherits dtype; not a ctor
    return a, b, c, d


def accumulate(hist, acc, x, ni, n):
    hist = hist + jnp.float32(0.5)           # pinned literal
    acc = acc * jnp.float32(2.0)
    out = build_histograms(x, jnp.float32(1.0), ni, n)
    scale = 2.0 * n                          # plain python math: fine
    return hist, acc, out, scale
