# Fixture: host-sync must stay SILENT.
import numpy as np


def setup(a, arrs):
    x = float(a)                # outside any loop: a one-off sync is fine
    y = np.asarray(a)
    lim = float("inf")          # literal coercions never flagged
    for i in range(3):
        lim = min(lim, i)
        n = int(7)
    # documented exception via pragma on the flagged line
    out = [
        np.asarray(o)  # ddtlint: disable=host-sync
        for o in arrs
    ]
    return x, y, lim, n, out
