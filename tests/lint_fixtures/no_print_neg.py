"""no-print negative fixture: idiomatic library output paths."""

import logging

log = logging.getLogger("ddt_tpu.fixture")


def dump_progress(rnd, loss):
    log.info("round %d loss %.6f", rnd, loss)     # the logger, not stdout


def with_injected_printer(printer):
    printer("ok")                                 # a parameter, not builtin


class Reporter:
    def print(self):                              # a METHOD named print
        return "rendered"


def use(reporter):
    return reporter.print()                       # attribute call is fine


def mentions():
    return "print( in a string literal is not a call"
