"""serve-blocking-io positive fixture: blocking host I/O on the serving
tier's shared dispatcher thread (scanned as ddt_tpu/serve/engine.py)."""
import json
import time

import numpy as np


def dispatcher_loop(queue, path):
    while queue:
        time.sleep(0.001)                      # LINT: serve-blocking-io
        batch = queue.pop()
        with open(path) as f:                  # LINT: serve-blocking-io
            cfg = json.load(f)                 # LINT: serve-blocking-io
        tables = np.load(path + ".npz")        # LINT: serve-blocking-io
        batch.score(cfg, tables)


def reload_model(model_path):
    blob = model_path.read_bytes()             # LINT: serve-blocking-io
    return blob
