"""pallas-vmem-guard positives: pallas_call dispatch chains with no
VMEM-fits predicate anywhere module-local — a direct dispatch, and a
kernel wrapper whose only caller is also unguarded."""
import functools

import jax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _kernel(x_ref, o_ref, *, scale):
    o_ref[:] = x_ref[:] * scale


def unguarded_direct(x, interpret=None):
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return pl.pallas_call(  # LINT: pallas-vmem-guard
        functools.partial(_kernel, scale=2),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=interpret,
    )(x)


def _unguarded_inner(x, interpret):
    return pl.pallas_call(  # LINT: pallas-vmem-guard
        functools.partial(_kernel, scale=3),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        interpret=interpret,
    )(x)


def unguarded_dispatcher(x, interpret=None):
    # calls the kernel wrapper but never consults a fits predicate
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _unguarded_inner(x, interpret)


class UnguardedBackend:
    """Methods are dispatch units too — a class cannot hide a site."""

    def dispatch(self, x, interpret=True):
        return pl.pallas_call(  # LINT: pallas-vmem-guard
            functools.partial(_kernel, scale=5),
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            interpret=interpret,
        )(x)


def unguarded_quantized(x, interpret=True):
    # The ISSUE 14 integer path (int32 VMEM scratch, quantized
    # operands) is a dispatch like any other — it cannot dodge the
    # rule by changing accumulator dtype.
    import jax.numpy as jnp

    return pl.pallas_call(  # LINT: pallas-vmem-guard
        functools.partial(_kernel, scale=9),
        out_shape=jax.ShapeDtypeStruct(x.shape, jnp.int32),
        scratch_shapes=[pltpu.VMEM(x.shape, jnp.int32)],
        interpret=interpret,
    )(x)


def other_shape_fits(rows, cols):
    return rows * cols <= 1024


class GuardedSibling:
    """A SAME-NAMED guarded method in another class must not launder the
    unguarded one above (units are class-qualified)."""

    def dispatch(self, x, interpret=True):
        if not other_shape_fits(*x.shape):
            raise ValueError("over budget")
        return pl.pallas_call(
            functools.partial(_kernel, scale=7),
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            interpret=interpret,
        )(x)
