"""jit-cache-key-coverage negative: every cfg read inside the traced
body is either a _JIT_FIELDS member, a _cache_key return-expression
term, or annotated trace-inert with a reason; reads in host-side
builder code (outside the jit closure) are not traced reads at all."""
import dataclasses

import jax


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    max_depth: int = 6
    n_bins: int = 255
    subsample: float = 1.0
    seed: int = 0
    row_tile: int = 128
    verbose: bool = False


_JIT_FIELDS = ("max_depth", "n_bins", "subsample", "row_tile")


def _cache_key(cfg):
    seed_live = cfg.subsample < 1.0
    return tuple(getattr(cfg, f) for f in _JIT_FIELDS) + (
        cfg.seed if seed_live else 0,
    )


def make_grow(cfg):
    verbose = cfg.verbose          # host-side read: not in the trace
    def grow(x):
        depth = x * cfg.max_depth
        if verbose and cfg.verbose:  # ddtlint: trace-inert — constant-folded at trace time: gates a host-only debug callback, never shapes the compiled program
            depth = depth + 0
        return depth + cfg.row_tile
    return jax.jit(grow)
