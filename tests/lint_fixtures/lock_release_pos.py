"""Fixture: acquire() without a dominating try/finally release — and an
acquire whose guard starts too late (a call between them can raise and
leak the lock)."""
import threading


class Batcher:
    def __init__(self):
        self._gate = threading.Lock()

    def leaky(self):
        self._gate.acquire()        # LINT: lock-release
        do_work()
        self._gate.release()

    def late_guard(self, rows):
        if not self._gate.acquire(blocking=False):  # LINT: lock-release
            return None
        req = make_request(rows)    # a raise here leaks the gate
        try:
            return dispatch(req)
        finally:
            self._gate.release()


def do_work():
    pass


def make_request(rows):
    return rows


def dispatch(req):
    return req
