"""raw-phase-timing positive fixture: host clocks in the device-op
layer — every one measures dispatch, not the device."""

import time


def grow_level(dispatch, hist):
    t0 = time.perf_counter()                      # LINT: raw-phase-timing
    out = dispatch(hist)
    return out, time.perf_counter() - t0          # LINT: raw-phase-timing


def stamp_round(run):
    run["t"] = time.time()                        # LINT: raw-phase-timing
    return run


def poll(handle):
    deadline = time.monotonic() + 5.0             # LINT: raw-phase-timing
    return deadline


def precise(dispatch):
    t = time.perf_counter_ns()                    # LINT: raw-phase-timing
    dispatch()
    return time.perf_counter_ns() - t             # LINT: raw-phase-timing
