"""undeclared-event-extra negative: every emit keyword is required or a
declared extra (including a `valid_*` glob match), and every `_c`
counter is declared on the `counters` event."""

EVENT_FIELDS = {
    "round": ("round", "ms_per_round"),
    "counters": ("jit_compiles",),
}
EVENT_EXTRAS = {
    "round": ("train_loss", "valid_*"),
    "counters": ("h2d_bytes", "stray_counter"),
}
SCHEMA_VERSION = 5

_c = {
    "jit_compiles": 0,
    "h2d_bytes": 0,
    "stray_counter": 0,
}


class Log:
    def emit(self, kind, **fields):
        pass


def run(log, payload):
    log.emit("round", round=1, ms_per_round=2.0, train_loss=0.5,
             valid_auc=0.93)
    log.emit("round", round=2, ms_per_round=2.0, **payload)  # splat: skipped
