"""named-scope negatives: scoped entry points, host-only helpers,
private functions, and unreachable code — none may be flagged."""
import functools

import jax
import jax.numpy as jnp

from ddt_tpu.telemetry.annotations import op_scope, traced_scope


@jax.jit
def scoped_with_block(x):
    with traced_scope("hist"):
        return jnp.sum(x)


@functools.partial(jax.jit, static_argnames=("k",))
@op_scope("gain")
def scoped_by_decorator(x, k):
    return jnp.argmax(x) + k


@jax.jit
def scoped_named_scope_literal(x):
    with jax.named_scope("ddt:route"):
        return jnp.cumsum(x)


def host_only_resolver(impl, n_nodes):
    # no traced calls: shape math never lowers HLO, nothing to name
    if impl == "auto":
        return "matmul" if n_nodes > 8 else "segment"
    return impl


def _private_entry(x):
    return jnp.sum(x)


@jax.jit
def caller(x):
    return _private_entry(x) + scoped_with_block(x)


def cold_public_fn(x):
    # public and device-lowering but NOT jit-reachable: never traced
    return jnp.sum(x)
