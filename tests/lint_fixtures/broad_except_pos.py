# Fixture: broad-except MUST fire.


def swallow_all():
    try:
        risky()
    except Exception:  # LINT: broad-except
        pass


def bare():
    try:
        risky()
    except:  # LINT: broad-except
        return None
