"""named-scope positives: jit-reachable public op entry points that
lower device work without opening a ddt: scope."""
import jax
import jax.numpy as jnp


@jax.jit
def bare_entry(x):  # LINT: named-scope
    return jnp.sum(x * 2.0)


def _helper_reached(x):     # private: traces under its caller's scope
    return jnp.tanh(x)


@jax.jit
def entry_via_helper(x):  # LINT: named-scope
    return _helper_reached(x) + jnp.float32(1.0)


@jax.jit
def scoped_wrong_prefix(x):  # LINT: named-scope
    with jax.named_scope("hist"):   # missing the ddt: prefix
        return jnp.cumsum(x)
