"""one-home-collective positive fixture: raw jax.lax collectives in
library code outside parallel/comms.py — each bypasses the comms
module's mode/dtype seams and its payload accounting."""

import jax
from jax import lax


def merge_hist(hist, axis):
    return jax.lax.psum(hist, axis)               # LINT: one-home-collective


def scatter_hist(hist, axis):
    return jax.lax.psum_scatter(                  # LINT: one-home-collective
        hist, axis, scatter_dimension=1, tiled=True)


def gather_winners(gains, axis):
    return lax.all_gather(gains, axis)            # LINT: one-home-collective


def global_max(x, axis):
    return jax.lax.pmax(x, axis)                  # LINT: one-home-collective
