"""Fixture: hand-built PartitionSpec in the backend layer — specs must
resolve through backend.layout (SpecLayout) by operand name."""
import jax

from ddt_tpu.parallel import mesh as mesh_lib

P = jax.sharding.PartitionSpec


def sharded_fn(f, mesh):
    return mesh_lib.shard_map(
        f, mesh=mesh,
        in_specs=P(None),                        # LINT: handbuilt-partition-spec
        out_specs=jax.sharding.PartitionSpec(),  # LINT: handbuilt-partition-spec
    )


def named(mesh, row_axes):
    return jax.sharding.NamedSharding(mesh, P(row_axes, None))  # LINT: handbuilt-partition-spec


# Alias bypasses must not be bypasses (review finding): import aliases
# and assigned aliases of any name count as PartitionSpec.
from jax.sharding import PartitionSpec as PS  # noqa: E402

Spec = jax.sharding.PartitionSpec
Chained = Spec


def alias_forms(mesh, row_axes):
    a = PS(None)                 # LINT: handbuilt-partition-spec
    b = Spec(row_axes)           # LINT: handbuilt-partition-spec
    c = Chained()                # LINT: handbuilt-partition-spec
    return a, b, c
