# Fixture: dtype-drift MUST fire (linted under a ddt_tpu/ops/ path).
import jax.numpy as jnp


def make(n):
    a = jnp.zeros(n)  # LINT: dtype-drift
    b = jnp.ones((n, 2))  # LINT: dtype-drift
    c = jnp.array([1, 2, 3])  # LINT: dtype-drift
    return a, b, c


def accumulate(hist, acc, x, ni, n):
    hist = hist + 0.5  # LINT: dtype-drift
    acc *= 2.0  # LINT: dtype-drift
    out = build_histograms(x, 1.0, ni, n)  # LINT: dtype-drift
    return hist, acc, out
