"""Fixture: cross-role state handled correctly — common lock on every
access, an annotated atomic publish, init-only publication, and
single-role mutation."""
import threading


class Engine:
    def __init__(self):
        self._lock = threading.Lock()
        self.model = object()
        self.count = 0
        self.config = {}        # written only here, read everywhere: fine
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        while True:
            with self._lock:
                use(self.count)
            use(self.model)
            use(self.config)

    def swap(self, new):
        self.model = new  # ddtlint: atomic-publish

    def bump(self):
        with self._lock:
            self.count += 1


class SingleRole:
    """No thread target: every method runs on caller threads only —
    one role, nothing for the cross-role rule to say."""

    def __init__(self):
        self.state = 0

    def set(self, v):
        self.state = v

    def get(self):
        return self.state


def use(x):
    return x
