"""event-schema-additivity positive: a required field added to an
existing event kind while SCHEMA_VERSION still says 5 — old logs lack
`loss_now` and read-side validation now rejects them. A brand-new kind
is additive and free."""

SCHEMA_VERSION = 5

EVENT_FIELDS = {
    "round": ("round", "ms_per_round", "loss_now"),  # LINT: event-schema-additivity
    "run_end": ("completed_rounds", "wallclock_s"),
    "trace_replay": ("path",),
}
