"""undeclared-event-extra positive: an emit-site keyword that is
neither a required field nor a declared extra, and a `_c` registry
counter the `counters` event never declares."""

EVENT_FIELDS = {
    "round": ("round", "ms_per_round"),
    "counters": ("jit_compiles",),
}
EVENT_EXTRAS = {
    "round": ("train_loss", "valid_*"),
    "counters": ("h2d_bytes",),
}
SCHEMA_VERSION = 5

_c = {
    "jit_compiles": 0,
    "h2d_bytes": 0,
    "stray_counter": 0,  # LINT: undeclared-event-extra
}


class Log:
    def emit(self, kind, **fields):
        pass


def run(log):
    log.emit("round", round=1, ms_per_round=2.0, train_loss=0.5,
             valid_auc=0.93)
    log.emit("round", round=2, ms_per_round=2.0,
             tree_bytes=1024)  # LINT: undeclared-event-extra
