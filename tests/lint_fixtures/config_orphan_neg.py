"""config-field-orphan negative: every field is in the cache key, in
the fingerprint (asdict minus excludes), a _cache_key return-expression
term, or annotated trace-inert with a reason; the derive_run_id site
uses the `**dataclasses.asdict(cfg)` idiom (full coverage by
construction)."""
import dataclasses
import hashlib
import json


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    max_depth: int = 6
    n_bins: int = 255
    checkpoint_every: int = 0  # ddtlint: trace-inert — host-side checkpoint cadence: resume replays to the recorded round whatever the cadence was, deliberately contract-less
    seed: int = 0


_JIT_FIELDS = ("max_depth", "n_bins")


def _cache_key(cfg):
    return tuple(getattr(cfg, f) for f in _JIT_FIELDS) + (cfg.seed,)


def _cfg_fingerprint(cfg):
    d = dataclasses.asdict(cfg)
    for k in ("checkpoint_every",):
        d.pop(k, None)
    return hashlib.sha256(
        json.dumps(d, sort_keys=True).encode()).hexdigest()


def derive_run_id(**fields):
    return hashlib.sha256(repr(sorted(fields.items())).encode()).hexdigest()


def start_run(cfg):
    return derive_run_id(**dataclasses.asdict(cfg))
