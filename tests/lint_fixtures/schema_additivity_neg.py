"""event-schema-additivity negative: the same `loss_now` growth done
the additive way — as an EVENT_EXTRAS entry — under the pinned version;
required sets match the v5 snapshot exactly."""

SCHEMA_VERSION = 5

EVENT_FIELDS = {
    "round": ("round", "ms_per_round"),
    "run_end": ("completed_rounds", "wallclock_s"),
    "trace_replay": ("path",),
}

EVENT_EXTRAS = {
    "round": ("loss_now",),
}
