"""Adversarial suite for the tie-proving comparator itself (round-4
verdict item 6): `assert_trees_match_mod_ties` guards the streamed and
cross-platform bit-identity contracts, so a false NEGATIVE in it — a
comparator that accepts a real divergence as a "boundary tie" — would
silently void the repo's strongest correctness claims. Every injected
real divergence here must be REJECTED; the accept-side cases pin the
documented contract boundary (gains within 2 bf16 ULPs, split/leaf flips
at the min_split_gain floor, one rare root cause)."""

import copy

import numpy as np
import pytest

from ddt_tpu.models.tree import empty_ensemble
from tree_compare import assert_trees_match_mod_ties

MSG = 1e-3          # min_split_gain used throughout
TIE = 2 ** -6       # the comparator's 2-bf16-ULP relative tie window


def _make_ens():
    """Two hand-built depth-2 trees in a depth-3 heap (full control over
    every gain, no training noise): root (f0, bin 5, gain 0.5), children
    (f1, bin 3, gain 0.3) / (f2, bin 7, gain 0.2), leaf grandchildren."""
    ens = empty_ensemble(2, 3, 4, 0.1, 0.0, "logloss")
    for t, scale in ((0, 1.0), (1, 0.7)):
        ens.feature[t, :3] = [0, 1, 2]
        ens.threshold_bin[t, :3] = [5, 3, 7]
        ens.split_gain[t, :3] = np.float32([0.5, 0.3, 0.2]) * scale
        ens.is_leaf[t, 3:7] = True
        ens.leaf_value[t, 3:7] = np.float32([1.0, 2.0, 3.0, 4.0]) * scale
    return ens


def _reject(full, mut):
    with pytest.raises(AssertionError):
        assert_trees_match_mod_ties(full, mut, MSG)


# --------------------------------------------------------------------- #
# reject side: every real divergence must fail
# --------------------------------------------------------------------- #

def test_rejects_flipped_split_at_non_boundary_gain():
    """A different (feature, bin) whose recorded gain differs beyond the
    tie window is a real divergence, not a tie."""
    full = _make_ens()
    mut = copy.deepcopy(full)
    mut.feature[0, 1] = 3
    mut.threshold_bin[0, 1] = 9
    mut.split_gain[0, 1] = full.split_gain[0, 1] * (1 + 4 * TIE)
    _reject(full, mut)


def test_rejects_perturbed_leaf_value():
    full = _make_ens()
    mut = copy.deepcopy(full)
    mut.leaf_value[1, 4] += 0.1
    _reject(full, mut)


def test_rejects_split_to_leaf_flip_away_from_floor():
    """Turning a strong split (gain 0.3 >> min_split_gain) into a leaf is
    never a floor tie."""
    full = _make_ens()
    mut = copy.deepcopy(full)
    mut.is_leaf[0, 1] = True
    mut.feature[0, 1] = -1
    mut.split_gain[0, 1] = 0.0
    _reject(full, mut)


def test_rejects_leaf_to_split_flip_away_from_floor():
    """The flip direction the STREAMED side could take: growing a strong
    split where the reference has a leaf."""
    full = _make_ens()
    mut = copy.deepcopy(full)
    full.is_leaf[0, 2] = True
    full.feature[0, 2] = -1
    full.split_gain[0, 2] = 0.0        # full: leaf; mut keeps gain 0.2
    _reject(full, mut)


def test_rejects_swapped_children():
    """Swapping a node's subtrees preserves the parent decision but the
    children's gains (0.3 vs 0.2) differ beyond the tie window."""
    full = _make_ens()
    mut = copy.deepcopy(full)
    for arr in (mut.feature, mut.threshold_bin, mut.split_gain):
        arr[0, 1], arr[0, 2] = arr[0, 2].copy(), arr[0, 1].copy()
    mut.leaf_value[0, 3:5], mut.leaf_value[0, 5:7] = (
        mut.leaf_value[0, 5:7].copy(), mut.leaf_value[0, 3:5].copy())
    _reject(full, mut)


def test_rejects_root_cause_flood():
    """Individually-tie-shaped flips (identical gains, different feature)
    in EVERY tree exceed the rarity cap: ties are measured rare (~1 per
    160k nodes) and a comparator without the cap would bless a
    systematically divergent trainer one 'tie' at a time."""
    full = _make_ens()
    mut = copy.deepcopy(full)
    for t in range(2):                 # equal gain -> each passes as tie
        mut.feature[t, 1] = 3
        mut.threshold_bin[t, 1] = 9
    _reject(full, mut)


def test_rejects_gain_drift_on_matching_decision():
    """Same (feature, bin, leaf) but a gain that moved beyond the bf16
    window: the decision agrees yet the histogram sums cannot have —
    a numerically broken accumulator must not slide through."""
    full = _make_ens()
    mut = copy.deepcopy(full)
    mut.split_gain[1, 0] *= 1.10
    _reject(full, mut)


# --------------------------------------------------------------------- #
# accept side: the documented contract boundary
# --------------------------------------------------------------------- #

def test_accepts_identical_trees():
    full = _make_ens()
    assert_trees_match_mod_ties(full, copy.deepcopy(full), MSG)


def test_accepts_one_provable_candidate_tie():
    """One cross-feature flip whose gains sit within 1 bf16 ULP is the
    legitimate chunked-accumulation seam (ops/split.py 'Determinism
    boundary') — with legitimately divergent descendants below it."""
    full = _make_ens()
    mut = copy.deepcopy(full)
    mut.feature[0, 1] = 3
    mut.threshold_bin[0, 1] = 9
    mut.split_gain[0, 1] = full.split_gain[0, 1] * (1 + TIE / 2)
    mut.leaf_value[0, 3:5] = [-9.0, 9.0]     # subtree excluded from checks
    assert_trees_match_mod_ties(full, mut, MSG)


def test_accepts_split_leaf_flip_at_the_floor():
    """A split whose gain sits within the tie window of min_split_gain
    can legitimately round to a leaf on the other side."""
    full = _make_ens()
    mut = copy.deepcopy(full)
    full.split_gain[0, 2] = MSG * (1 + TIE / 2)
    mut.is_leaf[0, 2] = True
    mut.feature[0, 2] = -1
    mut.split_gain[0, 2] = 0.0
    assert_trees_match_mod_ties(full, mut, MSG)


def test_accepts_cascade_gain_drift_after_root_cause():
    """After an accepted tie root cause in tree 0, later rounds train on
    legitimately-diverged predictions, so matched decisions there may
    carry small ABSOLUTE gain drift beyond the relative bf16 window
    (round-5 campaign case 10030: |dg|=1.5e-4 on a 0.004 gain). The
    cascade allowance accepts it — in LATER rounds only."""
    full = _make_ens()
    mut = copy.deepcopy(full)
    # Tree 0: accepted candidate tie (gains within the window).
    mut.feature[0, 1] = 3
    mut.threshold_bin[0, 1] = 9
    mut.split_gain[0, 1] = full.split_gain[0, 1] * (1 + TIE / 2)
    mut.leaf_value[0, 3:5] = [-9.0, 9.0]
    # Tree 1 (later round, logloss => 1 tree/round): matched decision
    # with a small-gain node drifted 1.5e-4 absolute (beyond TIE rel).
    full.split_gain[1, 2] = np.float32(0.004)
    mut.split_gain[1, 2] = np.float32(0.004 + 1.5e-4)
    assert_trees_match_mod_ties(full, mut, MSG)


def test_rejects_gain_corruption_even_after_root_cause():
    """The cascade allowance must NOT open the door to real corruption:
    with the same accepted tie in tree 0, a 10% drift on a LARGE gain
    (0.035 absolute > cascade_gain_atol) in a later round still fails."""
    full = _make_ens()
    mut = copy.deepcopy(full)
    mut.feature[0, 1] = 3
    mut.threshold_bin[0, 1] = 9
    mut.split_gain[0, 1] = full.split_gain[0, 1] * (1 + TIE / 2)
    mut.leaf_value[0, 3:5] = [-9.0, 9.0]
    mut.split_gain[1, 0] *= 1.10          # 0.35 -> 0.385: 0.035 absolute
    _reject(full, mut)


def test_rejects_cascade_scale_drift_in_same_round_as_root_cause():
    """The allowance is scoped to rounds AFTER the first root cause:
    the same 1.5e-4 absolute small-gain drift inside the root cause's
    own round (tree 0 here) must still fail the strict window — nodes
    there trained on identical predictions."""
    full = _make_ens()
    mut = copy.deepcopy(full)
    mut.feature[0, 1] = 3
    mut.threshold_bin[0, 1] = 9
    mut.split_gain[0, 1] = full.split_gain[0, 1] * (1 + TIE / 2)
    mut.leaf_value[0, 3:5] = [-9.0, 9.0]
    full.split_gain[0, 2] = np.float32(0.004)
    mut.split_gain[0, 2] = np.float32(0.004 + 1.5e-4)
    _reject(full, mut)


def test_accepts_cascade_leaf_drift_after_root_cause():
    """Case 10030's leaf face: post-root-cause leaves drift ~1.5x past
    both tight bounds (measured relative 1.47e-3, contribution 1.69e-3).
    This drift is sized to REQUIRE the 5x cascade scale with this
    fixture's lr=0.1: dv=0.03 -> contribution 3e-3, between the 1x
    (1e-3) and 5x (5e-3) contribution bounds, and beyond both relative
    bounds — so the test fails if cascade_leaf_scale is lost."""
    full = _make_ens()
    mut = copy.deepcopy(full)
    mut.feature[0, 1] = 3
    mut.threshold_bin[0, 1] = 9
    mut.split_gain[0, 1] = full.split_gain[0, 1] * (1 + TIE / 2)
    mut.leaf_value[0, 3:5] = [-9.0, 9.0]
    mut.leaf_value[1, 4] = full.leaf_value[1, 4] + np.float32(0.03)
    assert_trees_match_mod_ties(full, mut, MSG)


def test_rejects_leaf_corruption_even_after_root_cause():
    """The 5x leaf scale must not admit the adversarial perturbation:
    +0.1 on a later-round leaf (relative 5e-2, contribution 1e-2) still
    fails with the tree-0 tie accepted."""
    full = _make_ens()
    mut = copy.deepcopy(full)
    mut.feature[0, 1] = 3
    mut.threshold_bin[0, 1] = 9
    mut.split_gain[0, 1] = full.split_gain[0, 1] * (1 + TIE / 2)
    mut.leaf_value[0, 3:5] = [-9.0, 9.0]
    mut.leaf_value[1, 4] = full.leaf_value[1, 4] + np.float32(0.1)
    _reject(full, mut)


def test_rejects_non_tie_candidate_flip_after_root_cause():
    """The cascade atol widens the candidate-tie window in later rounds;
    a cross-feature flip whose gains differ beyond BOTH the bf16 window
    and cascade_gain_atol (0.21 vs 0.19: dg=0.02 > 2e-3) must still
    reject with the tree-0 tie accepted."""
    full = _make_ens()
    mut = copy.deepcopy(full)
    mut.feature[0, 1] = 3
    mut.threshold_bin[0, 1] = 9
    mut.split_gain[0, 1] = full.split_gain[0, 1] * (1 + TIE / 2)
    mut.leaf_value[0, 3:5] = [-9.0, 9.0]
    mut.feature[1, 2] = 3
    mut.threshold_bin[1, 2] = 9
    mut.split_gain[1, 2] = full.split_gain[1, 2] - np.float32(0.02)
    _reject(full, mut)


def test_rejects_off_floor_leaf_flip_after_root_cause():
    """Same for split-vs-leaf flips: post-root-cause, turning a strong
    split (gain 0.21 >> min_split_gain + cascade_gain_atol) into a leaf
    must still reject."""
    full = _make_ens()
    mut = copy.deepcopy(full)
    mut.feature[0, 1] = 3
    mut.threshold_bin[0, 1] = 9
    mut.split_gain[0, 1] = full.split_gain[0, 1] * (1 + TIE / 2)
    mut.leaf_value[0, 3:5] = [-9.0, 9.0]
    mut.is_leaf[1, 2] = True
    mut.feature[1, 2] = -1
    mut.split_gain[1, 2] = 0.0
    _reject(full, mut)
