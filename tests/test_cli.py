"""CLI round-trips (layer L8) on the CPU platform."""

import json
import os

import numpy as np
import pytest

from ddt_tpu.cli import main


def _run(capsys, argv):
    rc = main(argv)
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()
    return json.loads(out[-1])


def test_cli_train_predict_roundtrip(tmp_path, capsys):
    model = str(tmp_path / "ens.npz")
    rec = _run(capsys, [
        "train", "--backend=cpu", "--dataset=higgs", "--rows=2000",
        "--trees=4", "--depth=3", "--bins=31", f"--out={model}",
    ])
    assert rec["trees"] == 4 and rec["backend"] == "cpu"
    assert rec["final_train_loss"] < 0.693  # below chance for logloss

    scores = str(tmp_path / "scores.npy")
    rec = _run(capsys, [
        "predict", "--backend=cpu", f"--model={model}",
        "--dataset=higgs", "--rows=500", "--bins=31", f"--out={scores}",
    ])
    assert rec["rows"] == 500
    s = np.load(scores)
    assert s.shape == (500,) and (0 <= s).all() and (s <= 1).all()


def test_cli_train_tpu_backend_with_partitions(tmp_path, capsys):
    """The [BASELINE] flag surface: same command, different --backend, and
    a 4-partition run on the virtual device mesh."""
    model = str(tmp_path / "ens.npz")
    rec = _run(capsys, [
        "train", "--backend=tpu", "--dataset=higgs", "--rows=2000",
        "--trees=3", "--depth=3", "--bins=31", "--partitions=4",
        f"--out={model}",
    ])
    assert rec["backend"] == "tpu"


def test_cli_train_feature_partitions_and_early_stop(tmp_path, capsys):
    out = str(tmp_path / "m.npz")
    rc = main([
        "train", "--backend=tpu", "--dataset=higgs", "--rows=2000",
        "--bins=31", "--trees=12", "--depth=3", "--partitions=2",
        "--feature-partitions=2", "--out", out,
        "--valid-frac=0.2", "--metric=auc", "--early-stop=8",
    ])
    assert rc == 0
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["best_round"] >= 1
    assert 0.5 < rec["best_score"] <= 1.0
    assert rec["trees"] <= 12


def test_cli_covertype_softmax(tmp_path, capsys):
    model = str(tmp_path / "cov.npz")
    rec = _run(capsys, [
        "train", "--backend=cpu", "--dataset=covertype", "--rows=1500",
        "--trees=2", "--depth=3", "--bins=31", f"--out={model}",
    ])
    from ddt_tpu.models.tree import TreeEnsemble

    ens = TreeEnsemble.load(model)
    assert ens.loss == "softmax" and ens.n_classes == 7
    assert ens.n_trees == 2 * 7  # rounds x classes


def test_cli_criteo_categoricals(tmp_path, capsys):
    rec = _run(capsys, [
        "train", "--backend=cpu", "--dataset=criteo", "--rows=2000",
        "--trees=2", "--depth=3", "--bins=100",
        f"--out={tmp_path / 'c.npz'}",
    ])
    assert rec["final_train_loss"] < 0.60  # ~25% CTR base rate entropy

def test_cli_bench_histogram_cpu(capsys):
    rec = _run(capsys, [
        "bench", "--kernel=histogram", "--backend=cpu", "--rows=20000",
        "--features=6", "--bins=31", "--iters=1",
    ])
    assert rec["kernel"] == "histogram"
    assert rec["mrows_per_sec_per_chip"] > 0
    assert rec["impl"] in ("native-c++", "numpy")


def test_cli_fpga_backend_fails_loudly(tmp_path):
    with pytest.raises(NotImplementedError, match="FPGA"):
        main([
            "train", "--backend=fpga", "--dataset=higgs", "--rows=100",
            "--trees=1", "--depth=2", "--bins=15",
            f"--out={tmp_path / 'x.npz'}",
        ])


def test_cli_inspect(tmp_path, capsys):
    model = str(tmp_path / "ens.npz")
    _run(capsys, [
        "train", "--backend=cpu", "--dataset=higgs", "--rows=2000",
        "--trees=4", "--depth=3", "--bins=31", f"--out={model}",
    ])
    rc = main(["inspect", f"--model={model}", "--tree=0",
               "--importance=gain"])
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()
    rec = json.loads(out[0])
    assert rec["n_trees"] == 4 and rec["n_splits"] > 0
    assert rec["top_features_by_gain"]
    # The tree dump follows: root line mentions a feature split or a leaf.
    assert out[1].startswith(("f", "leaf="))


def test_cli_train_streaming(tmp_path, capsys):
    """--stream-chunks trains via the streaming path (BASELINE config 5
    from the CLI): streamed quantizer fit + per-chunk accumulation, model
    artifact complete (mapper included), trees identical to an in-memory
    run on the same mapper's bins."""
    from ddt_tpu import api
    from ddt_tpu.backends import get_backend
    from ddt_tpu.config import TrainConfig
    from ddt_tpu.driver import Driver

    model = str(tmp_path / "s.npz")
    rec = _run(capsys, [
        "train", "--backend=cpu", "--rows=4000", "--trees=4", "--depth=3",
        "--bins=31", "--stream-chunks=4", f"--out={model}",
    ])
    assert rec["streamed_chunks"] == 4 and rec["trees"] == 4
    b = api.load_model(model)
    assert b.mapper is not None

    # identical to in-memory training on the streamed mapper's bins
    from ddt_tpu.data.datasets import synthetic_binary

    X, y = synthetic_binary(4000, seed=0)
    cfg = TrainConfig(n_trees=4, max_depth=3, n_bins=31, backend="cpu")
    full = Driver(get_backend(cfg), cfg, log_every=10**9).fit(
        b.mapper.transform(X), y)
    np.testing.assert_array_equal(full.feature, b.ensemble.feature)

    # guard: early stopping still needs a validation split
    with pytest.raises(SystemExit, match="valid-frac"):
        main(["train", "--backend=cpu", "--rows=1000", "--trees=2",
              "--stream-chunks=2", "--early-stop=2"])


def test_cli_train_streaming_validation(tmp_path, capsys):
    """--stream-chunks composes with --valid-frac/--early-stop (round-2
    verdict item 3): held-out rows streamed as validation chunks, metric
    per round, best_round/best_score in the summary."""
    model = str(tmp_path / "s.npz")
    rec = _run(capsys, [
        "train", "--backend=cpu", "--rows=3000", "--trees=25", "--depth=3",
        "--bins=31", "--stream-chunks=3", "--valid-frac=0.25",
        "--metric=auc", "--early-stop=3", "--lr=0.9", f"--out={model}",
    ])
    assert rec["best_round"] >= 1
    assert 0.5 < rec["best_score"] <= 1.0
    # early stop truncated: trees == best_round (binary: 1 tree/round)
    assert rec["trees"] == rec["best_round"]
    assert rec["trees"] < 25


def test_cli_config_file(tmp_path, capsys):
    """--config overlays TrainConfig fields from YAML/JSON onto the flag-
    built config (file wins for fields it names; unknown keys fail)."""
    from ddt_tpu.config import TrainConfig

    yml = tmp_path / "c.yaml"
    yml.write_text("n_trees: 5\nmax_depth: 3\nreg_lambda: 2.5\n")
    model = str(tmp_path / "m.npz")
    rec = _run(capsys, [
        "train", "--backend=cpu", "--rows=1000", "--trees=99", "--bins=31",
        f"--config={yml}", f"--out={model}",
    ])
    assert rec["trees"] == 5 and rec["depth"] == 3   # file beat --trees=99

    js = tmp_path / "c.json"
    js.write_text('{"n_trees": 4, "learning_rate": 0.2}')
    rec = _run(capsys, [
        "train", "--backend=cpu", "--rows=1000", "--bins=31",
        f"--config={js}", f"--out={model}",
    ])
    assert rec["trees"] == 4

    bad = tmp_path / "bad.json"
    bad.write_text('{"n_treez": 4}')
    with pytest.raises(SystemExit, match="n_treez"):
        main(["train", "--backend=cpu", "--rows=500", f"--config={bad}"])
    with pytest.raises(SystemExit, match="config"):
        main(["train", "--backend=cpu", "--rows=500",
              "--config=/nonexistent.yaml"])

    # the library surface
    c = TrainConfig.from_file(str(yml))
    assert (c.n_trees, c.max_depth, c.reg_lambda) == (5, 3, 2.5)


def test_cli_config_file_syncs_pipeline_fields(tmp_path, capsys):
    """File-set fields that feed dataset loading / guards apply BEFORE the
    load: backend is reported truthfully, and file-set bagging streams
    (round 5 — the old streaming-vs-sampling rejection is gone; the
    counter-based masks made the combination exact)."""
    js = tmp_path / "c.json"
    js.write_text('{"backend": "cpu", "n_trees": 3, "seed": 7}')
    model = str(tmp_path / "m.npz")
    rec = _run(capsys, [
        "train", "--backend=tpu", "--rows=800", "--bins=31",
        f"--config={js}", f"--out={model}",
    ])
    assert rec["backend"] == "cpu"      # the file's backend, not the flag

    bag = tmp_path / "bag.yaml"
    bag.write_text("subsample: 0.5\nn_trees: 3\n")
    model2 = str(tmp_path / "bagged.npz")
    rec = _run(capsys, [
        "train", "--backend=cpu", "--rows=800", "--bins=31",
        "--stream-chunks=2", f"--config={bag}", f"--out={model2}",
    ])
    assert rec["streamed_chunks"] == 2
    assert os.path.exists(model2)
    # --profile composes with streaming since the telemetry PR
    # (fit_streaming wires its own PhaseTimer); the XLA trace capture
    # remains in-memory-only.
    with pytest.raises(SystemExit, match="trace-dir"):
        main(["train", "--backend=cpu", "--rows=800", "--bins=31",
              "--stream-chunks=2", "--trace-dir", str(tmp_path / "tr")])
    model3 = str(tmp_path / "profiled.npz")
    rec = _run(capsys, [
        "train", "--backend=cpu", "--rows=800", "--bins=31",
        "--stream-chunks=2", "--profile", f"--config={bag}",
        f"--out={model3}",
    ])
    assert rec["streamed_chunks"] == 2
    assert os.path.exists(model3)
