"""Worker for the config-5 O(chunk) memory witness (round-3 verdict
item 3). NOT a pytest module (no test_ prefix): RSS high-water marks are
process-wide, so the measurement needs a process that has never touched
the dataset — the parent test spawns this and asserts on the JSON it
prints.

Run: python tests/stream_rss_worker.py <rows> <features> <n_chunks> \
         <bins> <work_dir>

Phases, each RSS-stamped (ru_maxrss):
  1. import + jax init            (baseline)
  2. shard writing, chunk by chunk (never materialises the dataset)
  3. streamed training over the shards through the CLI --stream-dir path
The printed deltas let the parent assert the whole pipeline stayed
O(chunk): peak_after_train - baseline must be far below the binned
dataset size (let alone the float32 in-memory size)."""

import json
import os
import resource
import sys


def _rss_mb() -> float:
    # linux ru_maxrss is KiB.
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0


def main() -> int:
    rows, features, n_chunks, bins, work_dir = (
        int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3]),
        int(sys.argv[4]), sys.argv[5],
    )
    import jax

    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import numpy as np

    from ddt_tpu.data import chunks as chunks_mod

    jax.devices()                       # force platform init into baseline
    rss_baseline = _rss_mb()

    # Cut shards one chunk at a time — the writer itself must be O(chunk).
    shard_dir = os.path.join(work_dir, "shards")
    chunk_rows = chunks_mod.shard_stress_chunks(
        shard_dir, rows, n_chunks, n_features=features, seed=5,
        n_bins=bins)
    rss_sharded = _rss_mb()

    from ddt_tpu.cli import main as cli_main

    # Device cache OFF: on this CPU platform the "device" is host RAM, so
    # a cached run would legitimately hold the dataset and mask exactly
    # the O(chunk) property this worker exists to witness.
    rc = cli_main([
        "train", "--backend=tpu", f"--stream-dir={shard_dir}",
        f"--bins={bins}", "--trees=1", "--depth=2",
        "--stream-device-cache=off",
        f"--out={os.path.join(work_dir, 'm.npz')}",
    ])
    rss_trained = _rss_mb()

    src = chunks_mod.directory_chunks(shard_dir)
    print(json.dumps({
        "rc": rc,
        "rows": rows,
        "chunk_mb": chunk_rows * features / 1e6,
        "dataset_binned_mb": rows * features / 1e6,
        "dataset_float_mb": rows * features * 4 / 1e6,
        "n_chunks": src.n_chunks,
        "rss_baseline_mb": round(rss_baseline, 1),
        "rss_sharded_mb": round(rss_sharded, 1),
        "rss_trained_mb": round(rss_trained, 1),
    }))
    return rc


if __name__ == "__main__":
    sys.exit(main())
