"""The chaos matrix (docs/ROBUSTNESS.md): fault injection, retry seams,
checkpoint hardening, degrade ladder, straggler watchdog — plus the
zero-overhead guard that proves a plan-less run never touches any of it.

The recovery bar everywhere is BIT-IDENTITY: training is deterministic
given binned data, so a fault that the robustness layer absorbs must
leave the final ensemble exactly equal to an undisturbed run's."""

import json
import os

import numpy as np
import pytest

from ddt_tpu import api
from ddt_tpu.config import TrainConfig
from ddt_tpu.models.tree import TreeEnsemble, empty_ensemble
from ddt_tpu.robustness import faultplan, set_fault_sink
from ddt_tpu.robustness.watchdog import StragglerWatchdog
from ddt_tpu.streaming import fit_streaming
from ddt_tpu.telemetry.events import RunLog
from ddt_tpu.utils import checkpoint, retry


@pytest.fixture(autouse=True)
def _no_leaked_plan():
    """Every test starts and ends with no active plan and no sink — a
    leaked activation would silently fault unrelated tests."""
    faultplan.deactivate(None)
    set_fault_sink(None)
    yield
    faultplan.deactivate(None)
    set_fault_sink(None)


def _binary(rows=2000, n_bins=29, features=7, seed=5):
    rng = np.random.default_rng(seed)
    Xb = rng.integers(0, n_bins, size=(rows, features), dtype=np.uint8)
    y = (Xb[:, 0] + rng.integers(0, 6, size=rows) > 18).astype(np.float32)
    return Xb, y


def _chunks(Xb, y, n):
    bounds = np.linspace(0, len(y), n + 1).astype(np.int64)

    def f(c):
        return Xb[bounds[c]:bounds[c + 1]], y[bounds[c]:bounds[c + 1]]

    return f


def _assert_ens_equal(a, b):
    np.testing.assert_array_equal(a.feature, b.feature)
    np.testing.assert_array_equal(a.threshold_bin, b.threshold_bin)
    np.testing.assert_array_equal(a.is_leaf, b.is_leaf)
    np.testing.assert_array_equal(a.leaf_value, b.leaf_value)
    np.testing.assert_array_equal(a.split_gain, b.split_gain)


# ------------------------------------------------------------------ #
# retry engine (fake clock: deadline, jitter bounds, event emission)
# ------------------------------------------------------------------ #
class FakeClock:
    def __init__(self):
        self.t = 0.0
        self.sleeps = []

    def clock(self):
        return self.t

    def sleep(self, s):
        self.sleeps.append(s)
        self.t += s


def test_retry_succeeds_after_transient_failures_and_emits_events():
    rl = RunLog()
    set_fault_sink(rl)
    clk = FakeClock()
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise IOError(f"blip {calls['n']}")
        return "ok"

    out = retry.retry_call(flaky, seam="test.seam",
                           policy=retry.RetryPolicy(attempts=4, base_s=1.0,
                                                    multiplier=2.0,
                                                    jitter=0.5,
                                                    deadline_s=100.0),
                           clock=clk.clock, sleep=clk.sleep)
    assert out == "ok" and calls["n"] == 3
    faults = rl.events("fault")
    assert [e["kind"] for e in faults] == ["retry", "retry"]
    assert faults[0]["seam"] == "test.seam"
    assert faults[0]["attempt"] == 1 and faults[1]["attempt"] == 2
    assert faults[0]["error"] == "OSError"   # IOError is OSError


def test_retry_jitter_bounds_and_backoff_growth():
    pol = retry.RetryPolicy(attempts=6, base_s=1.0, multiplier=2.0,
                            jitter=0.5, deadline_s=1e9)
    for seed in range(10):
        clk = FakeClock()
        n = {"v": 0}

        def always_fail():
            n["v"] += 1
            raise IOError("x")

        with pytest.raises(IOError):
            retry.retry_call(always_fail, seam="jitter.test", policy=pol,
                             clock=clk.clock, sleep=clk.sleep,
                             rng=__import__("random").Random(seed))
        assert n["v"] == 6
        assert len(clk.sleeps) == 5
        for k, s in enumerate(clk.sleeps):
            full = pol.base_s * pol.multiplier ** k
            assert full * (1 - pol.jitter) <= s <= full, (k, s)


def test_retry_deadline_stops_before_overrunning():
    rl = RunLog()
    set_fault_sink(rl)
    clk = FakeClock()

    def always_fail():
        raise IOError("x")

    pol = retry.RetryPolicy(attempts=100, base_s=1.0, multiplier=2.0,
                            jitter=0.0, deadline_s=10.0)
    with pytest.raises(IOError):
        retry.retry_call(always_fail, seam="deadline.test", policy=pol,
                         clock=clk.clock, sleep=clk.sleep)
    # 1 + 2 + 4 = 7 slept; the next 8s sleep would pass 10s — refused.
    assert clk.t <= pol.deadline_s
    kinds = [e["kind"] for e in rl.events("fault")]
    assert kinds[-1] == "retry_deadline"


def test_retry_never_absorbs_non_transient():
    calls = {"n": 0}

    def boom():
        calls["n"] += 1
        raise ValueError("logic bug")

    with pytest.raises(ValueError):
        retry.retry_call(boom, seam="typed.test")
    assert calls["n"] == 1          # no second attempt


def test_retry_exhausted_emits_and_raises():
    rl = RunLog()
    set_fault_sink(rl)
    clk = FakeClock()
    with pytest.raises(IOError):
        retry.retry_call(
            lambda: (_ for _ in ()).throw(IOError("down")),
            seam="exhaust.test",
            policy=retry.RetryPolicy(attempts=3, base_s=0.01,
                                     deadline_s=100.0),
            clock=clk.clock, sleep=clk.sleep)
    kinds = [e["kind"] for e in rl.events("fault")]
    assert kinds == ["retry", "retry", "retry", "retry_exhausted"]


def test_is_transient_classification():
    assert retry.is_transient(IOError("x"))
    assert retry.is_transient(TimeoutError("x"))
    assert retry.is_transient(RuntimeError("UNAVAILABLE: tunnel reset"))
    assert retry.is_transient(faultplan.InjectedTransient("d2h"))
    assert not retry.is_transient(ValueError("x"))
    assert not retry.is_transient(faultplan.InjectedCrash("kill"))
    assert not retry.is_transient(
        faultplan.InjectedResourceExhausted("hist"))
    # Permanent filesystem errors fail identically on attempt 2 — a
    # mis-named chunk file must surface immediately, not after a full
    # backoff budget dressed up as transient-fault recovery.
    for exc in (FileNotFoundError(2, "no such file"),
                PermissionError(13, "denied"),
                IsADirectoryError(21, "is a dir"),
                NotADirectoryError(20, "not a dir")):
        assert not retry.is_transient(exc), exc
    # ...but an OSError with no errno (or a transient one) still retries.
    assert retry.is_transient(OSError("nfs blip"))


# ------------------------------------------------------------------ #
# fault plan mechanics
# ------------------------------------------------------------------ #
def test_fault_plan_parse_validation():
    with pytest.raises(ValueError, match="unknown site"):
        faultplan.load_plan({"faults": [{"site": "nope"}]})
    with pytest.raises(ValueError, match="unknown keys"):
        faultplan.load_plan(
            {"faults": [{"site": "hist.build", "wat": 1}]})
    with pytest.raises(ValueError, match="unknown error kind"):
        faultplan.load_plan(
            {"faults": [{"site": "hist.build", "error": "nope"}]})
    with pytest.raises(ValueError, match="'faults'"):
        faultplan.load_plan({"seed": 1})


def test_fault_plan_times_and_criteria(tmp_path):
    p = tmp_path / "plan.json"
    p.write_text(json.dumps({"faults": [
        {"site": "stream.chunk_read", "chunk": 2, "times": 2},
    ]}))
    plan = faultplan.load_plan(str(p))
    prev = faultplan.activate(plan)
    try:
        faultplan.inject("stream.chunk_read", chunk=1)   # no match
        with pytest.raises(faultplan.InjectedIOError):
            faultplan.inject("stream.chunk_read", chunk=2)
        with pytest.raises(faultplan.InjectedIOError):
            faultplan.inject("stream.chunk_read", chunk=2)
        faultplan.inject("stream.chunk_read", chunk=2)   # budget spent
    finally:
        faultplan.deactivate(prev)
    assert len(plan.fired_log) == 2


def test_fault_plan_injected_events_reach_sink():
    rl = RunLog()
    set_fault_sink(rl)
    prev = faultplan.activate(faultplan.load_plan(
        {"faults": [{"site": "fetch_tree"}]}))
    try:
        with pytest.raises(faultplan.InjectedTransient):
            faultplan.inject("fetch_tree")
    finally:
        faultplan.deactivate(prev)
    ev = rl.events("fault")
    assert len(ev) == 1 and ev[0]["kind"] == "injected"
    assert ev[0]["site"] == "fetch_tree"


def test_straggler_perturbation_is_query_not_raise():
    prev = faultplan.activate(faultplan.load_plan({"faults": [
        {"site": "straggler", "device": 1, "delay_ms": 250.0,
         "rounds": [2, 3], "times": 10},
    ]}))
    try:
        assert faultplan.perturb_ms("straggler", device=1, round=1) == 0.0
        assert faultplan.perturb_ms("straggler", device=0, round=2) == 0.0
        assert faultplan.perturb_ms("straggler", device=1, round=2) == 250.0
    finally:
        faultplan.deactivate(prev)
    assert faultplan.perturb_ms("straggler", device=1, round=2) == 0.0


# ------------------------------------------------------------------ #
# checkpoint hardening
# ------------------------------------------------------------------ #
def _mk_ens(cfg, F=7, rounds_filled=0, seed=0):
    ens = empty_ensemble(cfg.n_trees, cfg.max_depth, F, cfg.learning_rate,
                         0.0, cfg.loss, cfg.n_classes, n_bins=cfg.n_bins)
    rng = np.random.default_rng(seed)
    k = rounds_filled
    if k:
        ens.feature[:k] = rng.integers(0, F, ens.feature[:k].shape)
        ens.leaf_value[:k] = rng.random(ens.leaf_value[:k].shape,
                                        dtype=np.float32)
    return ens


def test_torn_pair_falls_back_to_last_good_history(tmp_path):
    cfg = TrainConfig(n_trees=10, max_depth=3, n_bins=29, backend="cpu")
    ck = str(tmp_path / "ck")
    e2 = _mk_ens(cfg, rounds_filled=2, seed=1)
    checkpoint.save_checkpoint(ck, e2, cfg, 2)
    # Simulate the crash-between-replaces: a NEWER ensemble lands but the
    # cursor never follows (the exact torn state ckpt.save.between
    # injects end-to-end in scripts/chaos_smoke.py).
    e4 = _mk_ens(cfg, rounds_filled=4, seed=2)
    prev = faultplan.activate(faultplan.load_plan(
        {"faults": [{"site": "ckpt.save.between", "round": 4}]}))
    try:
        with pytest.raises(faultplan.InjectedCrash):
            checkpoint.save_checkpoint(ck, e4, cfg, 4)
    finally:
        faultplan.deactivate(prev)
    rl = RunLog()
    fresh = _mk_ens(cfg)
    rounds = checkpoint.try_resume(ck, fresh, cfg, run_log=rl)
    assert rounds == 2
    np.testing.assert_array_equal(fresh.feature[:2], e2.feature[:2])
    kinds = [e["kind"] for e in rl.events("fault")]
    assert "checkpoint_corrupt" in kinds
    assert "checkpoint_fallback" in kinds


def test_corrupt_cursor_json_is_no_checkpoint_not_a_crash(tmp_path):
    cfg = TrainConfig(n_trees=10, max_depth=3, n_bins=29, backend="cpu")
    ck = str(tmp_path / "ck")
    os.makedirs(ck)
    # A torn/truncated cursor next to no ensemble and no history.
    with open(os.path.join(ck, checkpoint.CURSOR_FILE), "w") as f:
        f.write('{"completed_rounds": 2, "conf')     # truncated JSON
    with open(os.path.join(ck, checkpoint.CKPT_FILE), "wb") as f:
        f.write(b"PK\x03\x04 garbage npz")
    rl = RunLog()
    fresh = _mk_ens(cfg)
    assert checkpoint.try_resume(ck, fresh, cfg, run_log=rl) == 0
    kinds = [e["kind"] for e in rl.events("fault")]
    assert "checkpoint_corrupt" in kinds
    assert "checkpoint_unrecoverable" in kinds


def test_unreadable_npz_with_valid_cursor_falls_back(tmp_path):
    cfg = TrainConfig(n_trees=10, max_depth=3, n_bins=29, backend="cpu")
    ck = str(tmp_path / "ck")
    e2 = _mk_ens(cfg, rounds_filled=2, seed=3)
    checkpoint.save_checkpoint(ck, e2, cfg, 2)
    e4 = _mk_ens(cfg, rounds_filled=4, seed=4)
    checkpoint.save_checkpoint(ck, e4, cfg, 4)
    # Replace the TOP-LEVEL ensemble with garbage (a torn rewrite is a
    # NEW file, so the history hard links keep the good inode; in-place
    # bit rot would corrupt the shared inode too and fall back one more
    # round — still recovered, one save older).
    garbage = os.path.join(ck, "garbage.bin")
    with open(garbage, "wb") as f:
        f.write(b"PK\x03\x04 torn npz")
    os.replace(garbage, os.path.join(ck, checkpoint.CKPT_FILE))
    fresh = _mk_ens(cfg)
    rl = RunLog()
    # History ckpt-000004 links the PRE-corruption inode, so the newest
    # history pair still validates and resume loses nothing.
    assert checkpoint.try_resume(ck, fresh, cfg, run_log=rl) == 4
    np.testing.assert_array_equal(fresh.feature[:4], e4.feature[:4])
    assert "checkpoint_fallback" in [
        e["kind"] for e in rl.events("fault")]


def test_history_keeps_last_k(tmp_path):
    cfg = TrainConfig(n_trees=20, max_depth=3, n_bins=29, backend="cpu")
    ck = str(tmp_path / "ck")
    for r in (2, 4, 6, 8, 10):
        checkpoint.save_checkpoint(ck, _mk_ens(cfg, rounds_filled=r),
                                   cfg, r)
    hist = sorted(d for d in os.listdir(ck)
                  if d.startswith(checkpoint.HISTORY_PREFIX))
    assert hist == ["ckpt-000006", "ckpt-000008", "ckpt-000010"]


def test_old_format_cursor_without_digest_still_resumes(tmp_path):
    """Pre-hardening checkpoints (no digest, no history) stay resumable."""
    cfg = TrainConfig(n_trees=10, max_depth=3, n_bins=29, backend="cpu")
    ck = str(tmp_path / "ck")
    os.makedirs(ck)
    e3 = _mk_ens(cfg, rounds_filled=3, seed=5)
    np.savez_compressed(os.path.join(ck, checkpoint.CKPT_FILE + ".tmp"),
                        **e3.to_dict())
    os.replace(os.path.join(ck, checkpoint.CKPT_FILE + ".tmp.npz")
               if os.path.exists(
                   os.path.join(ck, checkpoint.CKPT_FILE + ".tmp.npz"))
               else os.path.join(ck, checkpoint.CKPT_FILE + ".tmp"),
               os.path.join(ck, checkpoint.CKPT_FILE))
    with open(os.path.join(ck, checkpoint.CURSOR_FILE), "w") as f:
        json.dump({"completed_rounds": 3,
                   "config": checkpoint._cfg_fingerprint(cfg)}, f)
    fresh = _mk_ens(cfg)
    assert checkpoint.try_resume(ck, fresh, cfg) == 3
    np.testing.assert_array_equal(fresh.feature[:3], e3.feature[:3])


def test_incompatible_config_still_raises(tmp_path):
    cfg = TrainConfig(n_trees=10, max_depth=3, n_bins=29, backend="cpu")
    ck = str(tmp_path / "ck")
    checkpoint.save_checkpoint(ck, _mk_ens(cfg, rounds_filled=2), cfg, 2)
    other = cfg.replace(learning_rate=0.5)
    with pytest.raises(ValueError, match="incompatible config"):
        checkpoint.try_resume(ck, _mk_ens(other), other)


def test_robustness_knobs_are_resume_compatible(tmp_path):
    """A run that crashed UNDER a fault plan resumes WITHOUT one — the
    robustness fields are system knobs outside the fingerprint."""
    cfg = TrainConfig(n_trees=10, max_depth=3, n_bins=29, backend="cpu",
                      fault_plan="/tmp/plan.json",
                      straggler_repartition=True)
    ck = str(tmp_path / "ck")
    checkpoint.save_checkpoint(ck, _mk_ens(cfg, rounds_filled=2), cfg, 2)
    clean = TrainConfig(n_trees=10, max_depth=3, n_bins=29, backend="cpu")
    assert checkpoint.try_resume(ck, _mk_ens(clean), clean) == 2


# ------------------------------------------------------------------ #
# end-to-end chaos: injected faults -> bit-identical ensembles
# ------------------------------------------------------------------ #
def test_injected_stream_read_fault_is_bit_exact():
    Xb, y = _binary()
    n_chunks = 4
    cfg = TrainConfig(n_trees=5, max_depth=3, n_bins=29, backend="tpu",
                      seed=2)
    clean = fit_streaming(_chunks(Xb, y, n_chunks), n_chunks, cfg)
    rl = RunLog()
    prev = faultplan.activate(faultplan.load_plan({"faults": [
        {"site": "stream.chunk_read", "chunk": 1, "times": 1},
        {"site": "stream.chunk_read", "chunk": 3, "times": 1},
    ]}))
    try:
        chaotic = fit_streaming(_chunks(Xb, y, n_chunks), n_chunks, cfg,
                                run_log=rl)
    finally:
        faultplan.deactivate(prev)
    _assert_ens_equal(clean, chaotic)
    kinds = [e["kind"] for e in rl.events("fault")]
    assert kinds.count("injected") == 2
    assert "retry" in kinds
    counters = rl.events("counters")[0]
    assert counters["fault_retries"] >= 2


def test_injected_fetch_tree_fault_is_bit_exact():
    Xb, y = _binary(1200)
    cfg = TrainConfig(n_trees=4, max_depth=3, n_bins=29, backend="tpu",
                      seed=2)
    # profile=True forces the granular path, whose fetch_tree seam the
    # plan targets (the fused path fetches whole blocks).
    ref = api.train(Xb, y, cfg, binned=True, profile=True)
    prev = faultplan.activate(faultplan.load_plan(
        {"faults": [{"site": "fetch_tree", "times": 2}]}))
    try:
        chaotic = api.train(Xb, y, cfg, binned=True, profile=True)
    finally:
        faultplan.deactivate(prev)
    _assert_ens_equal(ref.ensemble, chaotic.ensemble)


def test_granular_fit_without_checkpointing_accepts_every_0():
    """checkpoint_every=0 with no checkpoint_dir was valid before the
    watchdog's cadence check landed on the granular loop — the modulo
    must not resurrect it as a ZeroDivisionError."""
    from ddt_tpu.backends import get_backend
    from ddt_tpu.driver import Driver

    Xb, y = _binary(600)
    cfg = TrainConfig(n_trees=2, max_depth=3, n_bins=29, backend="tpu")
    be = get_backend(cfg)
    ens = Driver(be, cfg, log_every=10**9, checkpoint_dir=None,
                 checkpoint_every=0, profile=True).fit(Xb, y)
    assert ens.feature.shape[0] == cfg.n_trees


def test_cfg_fault_plan_is_activated_by_the_trainer(tmp_path):
    Xb, y = _binary(900)
    p = tmp_path / "plan.json"
    p.write_text(json.dumps(
        {"faults": [{"site": "fetch_tree", "times": 1}]}))
    rl = RunLog()
    cfg = TrainConfig(n_trees=3, max_depth=3, n_bins=29, backend="tpu",
                      fault_plan=str(p))
    res = api.train(Xb, y, cfg, binned=True, profile=True, run_log=rl)
    assert res.ensemble.n_trees == 3
    kinds = [e["kind"] for e in rl.events("fault")]
    assert "injected" in kinds and "retry" in kinds
    assert faultplan.active_plan() is None     # deactivated on exit


def test_multihost_init_timeout_retries(monkeypatch):
    from ddt_tpu.parallel import mesh as mesh_lib

    calls = {"n": 0}

    class FakeDistributed:
        @staticmethod
        def initialize(**kw):
            calls["n"] += 1

    monkeypatch.setattr(mesh_lib.jax, "distributed", FakeDistributed())
    monkeypatch.setattr(mesh_lib, "_init_args", None)
    monkeypatch.setattr(retry.time, "sleep", lambda s: None)
    prev = faultplan.activate(faultplan.load_plan(
        {"faults": [{"site": "multihost.init", "times": 1}]}))
    try:
        mesh_lib.initialize_multihost("127.0.0.1:9999", 1, 0)
    finally:
        faultplan.deactivate(prev)
    assert calls["n"] == 1      # attempt 2 reached the real initialize
    monkeypatch.setattr(mesh_lib, "_init_args", None)


def test_hist_oom_degrade_ladder_is_value_identical():
    from ddt_tpu.backends.tpu import TPUDevice

    cfg = TrainConfig(n_trees=2, max_depth=3, n_bins=29, backend="tpu",
                      hist_impl="segment")
    be = TPUDevice(cfg)
    rng = np.random.default_rng(0)
    Xb = rng.integers(0, 29, size=(512, 5), dtype=np.uint8)
    g = rng.random(512, dtype=np.float32)
    h = rng.random(512, dtype=np.float32)
    ni = np.zeros(512, np.int32)
    data = be.upload(Xb)
    ref = np.asarray(be.build_histograms(data, g, h, ni, 1))
    be2 = TPUDevice(cfg)
    rl = RunLog()
    set_fault_sink(rl)
    prev = faultplan.activate(faultplan.load_plan(
        {"faults": [{"site": "hist.build", "times": 1}]}))
    try:
        out = np.asarray(be2.build_histograms(
            be2.upload(Xb), g, h, ni, 1))
    finally:
        faultplan.deactivate(prev)
    # segment -> (ladder) -> matmul: value-identical here (integer-free
    # f32 sums at this scale agree bitwise on CPU XLA is NOT guaranteed,
    # so compare to the MATMUL reference instead of bitwise-to-segment).
    from ddt_tpu.ops import histogram as hist_ops
    import jax.numpy as jnp

    want = np.asarray(hist_ops.build_histograms_matmul(
        jnp.asarray(Xb), jnp.asarray(g), jnp.asarray(h),
        jnp.asarray(ni), 1, 29))
    np.testing.assert_allclose(out, want, rtol=0, atol=0)
    assert be2._hist_degrade == 1            # sticky
    ev = rl.events("fault")
    assert [e["kind"] for e in ev if e["kind"] == "hist_oom_degrade"]
    assert ref.shape == out.shape


# ------------------------------------------------------------------ #
# straggler watchdog + repartition
# ------------------------------------------------------------------ #
def test_watchdog_unit_detection_and_latch():
    wd = StragglerWatchdog(threshold=1.5, patience=2)
    balanced = {0: {"grow": 100.0}, 1: {"grow": 110.0}, 2: {"grow": 95.0}}
    skewed = {0: {"grow": 100.0}, 1: {"grow": 400.0}, 2: {"grow": 95.0}}
    assert wd.observe_round(0, balanced) is None
    obs = wd.observe_round(1, skewed)
    assert obs is not None and obs.device == 1 and obs.streak == 1
    assert not wd.pending_repartition
    obs2 = wd.observe_round(2, skewed)
    assert obs2.streak == 2 and wd.pending_repartition
    wd.repartition_done()
    assert not wd.pending_repartition
    # A DIFFERENT straggler resets the streak.
    other = {0: {"grow": 500.0}, 1: {"grow": 100.0}, 2: {"grow": 95.0}}
    assert wd.observe_round(3, skewed).streak == 1
    assert wd.observe_round(4, other).streak == 1


def test_injected_straggler_detection_and_repartition_bit_exact(tmp_path):
    """2-partition mesh run: injected straggler trips the watchdog, the
    repartition flag rotates shards at the checkpoint cadence, and the
    final ensemble is bit-identical to the undisturbed run (shard
    contents never move — only their device assignment)."""
    Xb, y = _binary(1600)
    # Default skew threshold: the watchdog's median excludes the
    # candidate lane, so 2.0 is reachable even with two lanes.
    base = TrainConfig(n_trees=6, max_depth=3, n_bins=29, backend="tpu",
                       n_partitions=2, seed=4,
                       straggler_repartition=True)
    # The flag forces the granular path, so the undisturbed reference
    # runs granular too (the fused path differs by documented
    # FMA-contraction ULPs — driver.py's resume-score seam).
    ref = api.train(Xb, y, base, binned=True)
    rl = RunLog()
    cfg = base
    prev = faultplan.activate(faultplan.load_plan({"faults": [
        {"site": "straggler", "device": 1, "delay_ms": 600000.0,
         "rounds": [1, 6], "times": 6},
    ]}))
    try:
        # checkpoint_every=2 -> the repartition boundary arrives fast.
        chaotic = api.train(Xb, y, cfg, binned=True, run_log=rl,
                            checkpoint_dir=str(tmp_path / "ck"),
                            checkpoint_every=2)
    finally:
        faultplan.deactivate(prev)
    _assert_ens_equal(ref.ensemble, chaotic.ensemble)
    kinds = [e["kind"] for e in rl.events("fault")]
    assert "straggler_detected" in kinds
    assert "repartition" in kinds


def test_partition_phases_carry_injected_straggler_lane():
    Xb, y = _binary(1600)
    cfg = TrainConfig(n_trees=3, max_depth=3, n_bins=29, backend="tpu",
                      n_partitions=2, seed=4)
    rl = RunLog()
    prev = faultplan.activate(faultplan.load_plan({"faults": [
        {"site": "straggler", "device": 0, "delay_ms": 123.0,
         "times": 1},
    ]}))
    try:
        api.train(Xb, y, cfg, binned=True, run_log=rl)
    finally:
        faultplan.deactivate(prev)
    pp = rl.events("partition_phases")
    assert pp
    lanes = {p["device"]: p["phases"] for p in pp[0]["partitions"]}
    assert lanes[0].get("straggler_injected") == 123.0


# ------------------------------------------------------------------ #
# zero-overhead guard (the telemetry disabled-path bar)
# ------------------------------------------------------------------ #
def test_no_plan_no_overhead_guard(monkeypatch, tmp_path):
    """With no fault plan active, the injection/retry layer must be a
    module-global read: firing, backoff, and straggler perturbation all
    explode if touched — training (checkpointed, so every seam runs)
    must complete anyway."""
    from ddt_tpu.utils import retry as retry_mod

    def _boom(*a, **k):
        raise AssertionError("robustness slow path touched with no plan")

    monkeypatch.setattr(faultplan.FaultPlan, "fire", _boom)
    monkeypatch.setattr(faultplan.FaultPlan, "delay_ms", _boom)
    monkeypatch.setattr(retry_mod, "_backoff_loop", _boom)
    monkeypatch.setattr(retry_mod.time, "sleep", _boom)
    Xb, y = _binary(900)
    cfg = TrainConfig(n_trees=4, max_depth=3, n_bins=29, backend="tpu")
    res = api.train(Xb, y, cfg, binned=True,
                    checkpoint_dir=str(tmp_path / "ck"),
                    checkpoint_every=2)
    assert res.ensemble.n_trees == 4
    # The streaming path's wrapped chunk reads hold the same bar.
    ens = fit_streaming(_chunks(Xb, y, 3), 3,
                        TrainConfig(n_trees=2, max_depth=3, n_bins=29,
                                    backend="tpu"))
    assert ens.n_trees == 2


def test_benchwatch_excludes_injected_fault_artifacts(tmp_path):
    """Chaos artifacts never band: not as history, not as current."""
    from tools import benchwatch

    hist_vals = [50.0, 52.0, 48.0, 51.0]
    paths = []
    for i, v in enumerate(hist_vals):
        p = tmp_path / f"BENCH_r{i:02d}.json"
        p.write_text(json.dumps({"n": i, "parsed": {
            "metric": "m", "value": v, "bench_schema": 2}}))
        paths.append(str(p))
    # A chaos run with an absurd number in history must not poison bands.
    pc = tmp_path / "BENCH_r04.json"
    pc.write_text(json.dumps({"n": 4, "parsed": {
        "metric": "m", "value": 5.0, "bench_schema": 2,
        "injected_faults": True}}))
    paths.append(str(pc))
    cur = tmp_path / "fresh.json"
    cur.write_text(json.dumps({"metric": "m", "value": 49.0,
                               "bench_schema": 2}))
    rep = benchwatch.run(paths, current_path=str(cur))
    assert rep["ok"], rep
    assert str(pc) in rep["excluded_injected"]
    banded = {c["metric"]: c for c in rep["bench"]["checked"]}
    assert banded["value"]["n_history"] == 4     # chaos run not counted
    # And a chaos CURRENT is excluded, not banded.
    rep2 = benchwatch.run(paths[:-1], current_path=str(pc))
    assert rep2["ok"]
    assert rep2["bench"].get("skipped_injected")


def test_atomic_save_model_and_ensemble(tmp_path):
    """api.save_model / TreeEnsemble.save leave no torn artifact and
    keep numpy's .npz suffixing semantics."""
    cfg = TrainConfig(n_trees=4, max_depth=3, n_bins=29, backend="cpu")
    ens = _mk_ens(cfg, rounds_filled=2, seed=7)
    p = str(tmp_path / "model.npz")
    api.save_model(p, ens)
    assert os.path.exists(p) and not os.path.exists(p + ".tmp.npz")
    loaded = api.load_model(p)
    np.testing.assert_array_equal(loaded.ensemble.feature, ens.feature)
    bare = str(tmp_path / "bare")
    ens.save(bare)
    assert os.path.exists(bare + ".npz")
    TreeEnsemble.load(bare + ".npz")
