"""int8 TreeLUT quantized traversal (ops/predict_lut.py): the rounding
contract, pinned.

Three properties, across n_classes {1, 3} x missing-value routing x
categorical one-vs-rest splits (the full feature matrix of the scoring
path):

1. ERROR CONTRACT: |lut - f32| <= QuantizedTables.max_abs_err for both
   leaf dtypes (fp16 and int8+scale) — the bound is COMPUTED per model
   at quantize time, so this asserts the documented contract, not a
   tolerance pulled from the air.
2. PARITY: jitted LUT == jitted f32 one-hot path fed the DEQUANTIZED
   tables, BITWISE — descent is exact (int8 thresholds lose nothing on
   integer bins) and the kernel mirrors the one-hot accumulation
   term-for-term, so the only difference between LUT and f32 is the
   single leaf-rounding step. (Both sides run under jit: the production
   dispatch always does, and XLA's fusion choices — e.g. an FMA in the
   base + lr*acc epilogue — differ between eager and jitted programs.)
3. DISPATCH: cfg.predict_impl="lut" routes the backend's predict cache
   through the quantized tables (within the bound of the f32 backend),
   and shapes past the kernel's VMEM budget refuse/fall back per the
   pallas-vmem-guard contract.

All kernels run in Pallas interpret mode on the CPU suite (the same
fallback pattern as tests/test_predict_pallas.py); shapes stay tiny.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddt_tpu.config import TrainConfig
from ddt_tpu.models.tree import empty_ensemble
from ddt_tpu.ops import predict as predict_ops
from ddt_tpu.ops import predict_lut


def _rand_ens(seed=0, trees=12, depth=3, features=7, bins=31,
              loss="logloss", n_classes=2, missing=False, cat=()):
    rng = np.random.default_rng(seed)
    n_nodes = 2 ** (depth + 1) - 1
    ens = empty_ensemble(
        trees, depth, features, 0.1, 0.25, loss, n_classes=n_classes,
        missing_bin=missing, n_bins=bins, cat_features=tuple(cat))
    ens.feature[:] = rng.integers(0, features, size=(trees, n_nodes))
    # Missing models reserve the top bin; thresholds stay in value bins.
    ens.threshold_bin[:] = rng.integers(
        0, bins - (2 if missing else 1), size=(trees, n_nodes))
    ens.is_leaf[:] = rng.random((trees, n_nodes)) < 0.25
    ens.leaf_value[:] = rng.standard_normal(
        (trees, n_nodes)).astype(np.float32)
    if missing:
        ens.default_left[:] = rng.random((trees, n_nodes)) < 0.5
    return ens


def _rows(ens, rows=50, bins=31, missing=False, seed=1):
    rng = np.random.default_rng(seed)
    Xb = rng.integers(0, bins - (1 if missing else 0),
                      size=(rows, ens.n_features)).astype(np.uint8)
    if missing:
        # A healthy share of rows carry the reserved NaN bin.
        mask = rng.random(Xb.shape) < 0.2
        Xb[mask] = bins - 1
    return Xb


VARIANTS = [
    pytest.param(dict(), id="binary"),
    pytest.param(dict(loss="softmax", n_classes=3, trees=12),
                 id="softmax3"),
    pytest.param(dict(missing=True), id="missing"),
    pytest.param(dict(cat=(1, 4)), id="categorical"),
    pytest.param(dict(loss="softmax", n_classes=3, cat=(0, 2),
                      trees=9), id="softmax3-categorical"),
]


def _f32_reference(ce, Xb, use_dequantized=None):
    """Jitted one-hot scores, on the original or dequantized tables."""
    if use_dequantized is None:
        arrays = [jnp.asarray(a) for a in ce.arrays()]
        eff_feat, eff_thr, bot_val, cls_oh, *rest = arrays
    else:
        thr_d, val_d = use_dequantized.dequantized()
        eff_feat = jnp.asarray(use_dequantized.eff_feat)
        eff_thr = jnp.asarray(thr_d)
        bot_val = jnp.asarray(val_d)
        cls_oh = jnp.asarray(use_dequantized.cls_oh)
        rest = []
        if use_dequantized.eff_dl is not None:
            rest.append(jnp.asarray(use_dequantized.eff_dl))
        if use_dequantized.eff_cat is not None:
            rest.append(jnp.asarray(use_dequantized.eff_cat))
    kw = {}
    opt = list(rest)
    if ce.eff_dl is not None:
        kw["eff_dl"] = opt.pop(0)
    if ce.eff_cat is not None:
        kw["eff_cat"] = opt.pop(0)
    return np.asarray(predict_ops.predict_raw_effective(
        eff_feat, eff_thr, bot_val, cls_oh, jnp.asarray(Xb),
        max_depth=ce.max_depth, learning_rate=ce.learning_rate,
        base=ce.base_score, n_classes=ce.n_classes_out,
        tree_chunk=ce.tree_chunk,
        missing_bin_value=ce.missing_bin_value, use_pallas=False, **kw))


def _lut_scores(tables, Xb):
    fn = jax.jit(lambda X: predict_lut.predict_effective_lut(tables, X))
    return np.asarray(fn(jnp.asarray(Xb)))


@pytest.mark.parametrize("variant", VARIANTS)
@pytest.mark.parametrize("leaf_dtype", ["float16", "int8"])
def test_error_contract_within_computed_bound(variant, leaf_dtype):
    """Property 1: the documented max-abs-error bound holds for every
    variant and both leaf dtypes (plus f32-accumulation slack)."""
    missing = variant.get("missing", False)
    ens = _rand_ens(**variant)
    Xb = _rows(ens, bins=31, missing=missing)
    ce = ens.compile(tree_chunk=8)
    tables = ce.quantize(leaf_dtype=leaf_dtype)
    got = _lut_scores(tables, Xb)
    want = _f32_reference(ce, Xb)
    err = float(np.abs(got - want).max())
    assert err <= tables.max_abs_err * (1 + 1e-5) + 1e-6, \
        (err, tables.max_abs_err)
    # The bound is meaningful, not vacuous: int8 leaves genuinely
    # round, so SOME error exists at these random leaf values.
    if leaf_dtype == "int8":
        assert tables.max_abs_err > 0


@pytest.mark.parametrize("variant", VARIANTS)
def test_lut_bitexact_to_dequantized_reference(variant):
    """Property 2: the LUT kernel is bit-exact to the f32 one-hot path
    fed the dequantized tables — descent identical, accumulation
    mirrored (both jitted; see module doc)."""
    missing = variant.get("missing", False)
    ens = _rand_ens(**variant)
    Xb = _rows(ens, bins=31, missing=missing)
    ce = ens.compile(tree_chunk=8)
    tables = ce.quantize()
    got = _lut_scores(tables, Xb)
    ref = _f32_reference(ce, Xb, use_dequantized=tables)
    np.testing.assert_array_equal(got, ref)


def test_threshold_quantization_is_exact():
    """Contract 1 in ops/predict_lut.py: int8 recentring loses nothing
    on integer bins — with exactly-representable leaf values the whole
    LUT output equals f32 BITWISE (leaf CHOICE must be identical, and
    values in 1/256 steps are exact in fp16)."""
    ens = _rand_ens(seed=7)
    rng = np.random.default_rng(7)
    ens.leaf_value[:] = (rng.integers(-256, 257, ens.leaf_value.shape)
                         / 256.0).astype(np.float32)
    Xb = _rows(ens)
    ce = ens.compile(tree_chunk=8)
    tables = ce.quantize()
    assert tables.max_abs_err == 0.0
    np.testing.assert_array_equal(_lut_scores(tables, Xb),
                                  _f32_reference(ce, Xb))


def test_quantize_rejects_unknown_leaf_dtype():
    # "int4" became a real tier in ISSUE 12 (tests/test_predict_lut4.py)
    # — the refusal contract now guards genuinely unknown dtypes.
    ens = _rand_ens()
    with pytest.raises(ValueError, match="leaf_dtype"):
        ens.compile().quantize(leaf_dtype="int2")


def test_fits_guard_refuses_monster_shapes():
    """predict_lut_fits is the vmem-guard: a shape whose trace/VMEM
    budget explodes must return False, and a forced COMPILED dispatch
    at it must raise at the cause (interpret mode has no VMEM to
    protect and stays callable for tests)."""
    assert predict_lut.predict_lut_fits(64, 64, 3, 7, 1)
    assert not predict_lut.predict_lut_fits(131072, 64, 10, 4096, 1)
    ens = _rand_ens()
    tables = ens.compile(tree_chunk=8).quantize()
    with pytest.raises(ValueError, match="VMEM"):
        predict_lut.predict_effective_lut(
            tables, _rows(ens), tile_r=10**6, interpret=False)


def test_backend_lut_dispatch_and_cache():
    """Property 3: a predict_impl='lut' backend scores through the
    quantized tables (within the bound of the f32 backend's answer),
    hits its compiled cache on repeat calls, and predict_raw(compiled=)
    accepts a prebuilt CompiledEnsemble (the serving request path)."""
    from ddt_tpu.backends import get_backend
    from ddt_tpu.telemetry import counters as tele_counters

    ens = _rand_ens(trees=8)
    Xb = _rows(ens, rows=33)
    be_f32 = get_backend(TrainConfig(backend="tpu", n_bins=31))
    be_lut = get_backend(TrainConfig(backend="tpu", n_bins=31,
                                     predict_impl="lut"))
    want = be_f32.predict_raw(ens, Xb)
    got = be_lut.predict_raw(ens, Xb)
    bound = ens.compile().quantize().max_abs_err
    assert float(np.abs(got - want).max()) <= bound * (1 + 1e-5) + 1e-6

    c0 = tele_counters.snapshot()
    ce = ens.compile(tree_chunk=64)
    got2 = be_lut.predict_raw(ens, Xb, compiled=ce)
    np.testing.assert_array_equal(got, got2)
    assert tele_counters.delta(c0)["compiled_ensemble_cache_hits"] >= 1


def test_lut_empty_batch():
    ens = _rand_ens()
    tables = ens.compile(tree_chunk=8).quantize()
    out = predict_lut.predict_effective_lut(
        tables, np.zeros((0, ens.n_features), np.uint8))
    assert np.asarray(out).shape == (0,)
