"""Digest-addressed model registry (docs/REGISTRY.md).

Submodules: `manifest` (provenance + integrity schemas, stdlib-only),
`store` (the on-disk object/version store), `loader` (the zero-retrace
servable restore — imports jax; keep it lazy from transport/CLI code).
Only the jax-free pieces are re-exported here so `registry list`-style
metadata work never pays a jax import."""

from ddt_tpu.registry.manifest import IntegrityError  # noqa: F401
from ddt_tpu.registry.store import Registry, RegistryError  # noqa: F401
