"""Artifact manifests: provenance + integrity for every persisted model.

Two manifest forms, one module (docs/REGISTRY.md "Manifest schema"):

- **npz-embedded** (`api.save_model` / `TreeEnsemble.save`): a single
  JSON blob stored under the `manifest_json` key INSIDE the artifact —
  schema version, content digest of the payload arrays, training
  `run_id`, config fingerprint, git rev. `read_npz_manifest` recomputes
  the digest at load and raises `IntegrityError` on mismatch; files
  written before manifests existed simply lack the key and load as
  before (the legacy contract tests/test_registry.py pins).
- **artifact-directory** (the registry's `objects/<digest>/`): a
  `manifest.json` beside the files it describes, carrying a per-file
  sha256 map plus the export metadata (bucket ladder, platforms,
  quantization error bound, model token). The ARTIFACT DIGEST is the
  sha256 of the canonical manifest bytes — a Merkle root: any flipped
  byte in any file changes its entry, which changes the manifest,
  which changes the digest the object directory is addressed by.

Pure stdlib+numpy — no jax, no model imports — so the models layer and
the registry store can both use it without cycles.
"""

from __future__ import annotations

import functools
import hashlib
import json
import os
import subprocess

import numpy as np

#: npz key holding the embedded manifest blob (api.save_model et al).
NPZ_MANIFEST_KEY = "manifest_json"
#: embedded-manifest schema (bump when a required field changes meaning).
MANIFEST_SCHEMA = 1
#: artifact-directory schema (the registry object layout).
ARTIFACT_SCHEMA = 1
MANIFEST_FILE = "manifest.json"


class IntegrityError(ValueError):
    """A persisted artifact does not match its recorded digests (torn
    write, bit rot, tampering). ValueError subclass so pre-registry
    callers guarding loads with `except ValueError` keep their
    behavior."""


@functools.lru_cache(maxsize=1)
def git_rev() -> str | None:
    """Current repo HEAD (short), or None outside a git checkout — the
    same best-effort stamp bench artifacts carry. Memoized: HEAD cannot
    change meaningfully mid-process, and every model save stamps it."""
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=os.path.dirname(os.path.abspath(__file__)),
            capture_output=True, text=True, timeout=10)
    except (OSError, subprocess.SubprocessError):
        return None
    return out.stdout.strip() if out.returncode == 0 else None


def arrays_digest(arrays: dict) -> str:
    """Content digest of an npz payload: sha256 over every (key, dtype,
    shape, bytes) in sorted key order, the manifest key itself excluded
    (the manifest cannot cover its own bytes). Deterministic across
    processes — the exporting and loading hosts must agree bit-for-bit."""
    h = hashlib.sha256()
    for k in sorted(arrays):
        if k == NPZ_MANIFEST_KEY:
            continue
        a = np.asarray(arrays[k])
        h.update(k.encode())
        h.update(str(a.dtype).encode())
        h.update(repr(a.shape).encode())
        h.update(np.ascontiguousarray(a).tobytes())
    return h.hexdigest()


def build_npz_manifest(arrays: dict, *, kind: str, run_id: str | None = None,
                       config_fingerprint: str | None = None,
                       **extras) -> dict:
    """The embedded-manifest dict for one npz payload (digest computed
    here; caller embeds via `embed_npz_manifest`)."""
    # NO timestamps in here: the manifest is part of the file bytes the
    # REGISTRY digest covers, and content addressing demands that the
    # same model saved twice produce the same bytes (re-push is
    # idempotent — tests pin it). Wall-clock provenance lives in the
    # name index's pushed_at, which is never hashed.
    man = {
        "manifest_schema": MANIFEST_SCHEMA,
        "kind": kind,
        "digest": arrays_digest(arrays),
        "run_id": run_id,
        "config_fingerprint": config_fingerprint,
        "git_rev": git_rev(),
    }
    man.update(extras)
    return man


def embed_npz_manifest(arrays: dict, *, kind: str,
                       run_id: str | None = None,
                       config_fingerprint: str | None = None,
                       **extras) -> dict:
    """Add the manifest blob to `arrays` IN PLACE (under
    NPZ_MANIFEST_KEY); returns the manifest dict."""
    man = build_npz_manifest(arrays, kind=kind, run_id=run_id,
                             config_fingerprint=config_fingerprint, **extras)
    arrays[NPZ_MANIFEST_KEY] = np.bytes_(
        json.dumps(man, sort_keys=True).encode())
    return man


def read_npz_manifest(arrays: dict, *, verify: bool = True,
                      source: str = "artifact") -> dict | None:
    """Parse (and by default digest-verify) the embedded manifest of a
    loaded npz dict. Returns None for legacy manifest-less files —
    they predate the schema and stay loadable; raises IntegrityError
    when a manifest IS present but its digest no longer matches the
    payload (torn write / bit rot / tampering)."""
    blob = arrays.get(NPZ_MANIFEST_KEY)
    if blob is None:
        return None
    try:
        man = json.loads(bytes(np.asarray(blob).item()))
    except (ValueError, TypeError) as e:
        raise IntegrityError(
            f"{source}: embedded manifest is not valid JSON ({e})"
        ) from e
    if verify:
        actual = arrays_digest(arrays)
        if man.get("digest") != actual:
            raise IntegrityError(
                f"{source}: content digest mismatch — manifest says "
                f"{str(man.get('digest'))[:16]}…, payload hashes to "
                f"{actual[:16]}… (torn write or corrupted file); "
                "re-export the artifact")
    return man


def config_fingerprint_digest(cfg) -> str:
    """Short stable digest of the resumability config fingerprint
    (utils.checkpoint._cfg_fingerprint) — the manifest field linking a
    model artifact to the exact training configuration without
    embedding the whole config."""
    from ddt_tpu.utils.checkpoint import _cfg_fingerprint

    blob = json.dumps(_cfg_fingerprint(cfg), sort_keys=True, default=str)
    return hashlib.sha1(blob.encode()).hexdigest()[:12]


# --------------------------------------------------------------------- #
# artifact-directory manifests (the registry object layout)
# --------------------------------------------------------------------- #

def file_sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(1 << 20), b""):
            h.update(block)
    return h.hexdigest()


def _walk_files(art_dir: str) -> list[str]:
    """Every file under `art_dir` except the manifest itself, as sorted
    /-separated relpaths (the canonical file set the digest covers)."""
    out = []
    for dirpath, _dirnames, fns in os.walk(art_dir):
        for fn in fns:
            rel = os.path.relpath(os.path.join(dirpath, fn),
                                  art_dir).replace(os.sep, "/")
            if rel != MANIFEST_FILE:
                out.append(rel)
    return sorted(out)


def write_artifact_manifest(art_dir: str, meta: dict) -> str:
    """Finalize a staged artifact directory: hash every file into a
    `files` map, write manifest.json (tmp-then-os.replace — the
    atomic-artifact-write contract), and return the ARTIFACT DIGEST
    (sha256 of the canonical manifest bytes)."""
    files = {rel: {"sha256": file_sha256(os.path.join(art_dir, rel)),
                   "bytes": os.path.getsize(os.path.join(art_dir, rel))}
             for rel in _walk_files(art_dir)}
    man = {"artifact_schema": ARTIFACT_SCHEMA, **meta, "files": files}
    blob = json.dumps(man, sort_keys=True).encode()
    final = os.path.join(art_dir, MANIFEST_FILE)
    tmp = final + ".tmp"
    try:
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, final)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise
    return hashlib.sha256(blob).hexdigest()


def read_artifact_manifest(art_dir: str, *, verify_files: bool = True
                           ) -> tuple[dict, str]:
    """(manifest, artifact digest) for one object directory, integrity-
    checked: the manifest must parse, every listed file must exist with
    the recorded sha256, and no unlisted file may hide in the directory
    (an unlisted file is a torn/foreign write — the digest would not
    cover it). Raises IntegrityError on any violation."""
    path = os.path.join(art_dir, MANIFEST_FILE)
    try:
        with open(path, "rb") as f:
            blob = f.read()
        man = json.loads(blob)
    except OSError as e:
        raise IntegrityError(f"{art_dir}: unreadable manifest: {e}") from e
    except ValueError as e:
        raise IntegrityError(
            f"{art_dir}: manifest is not valid JSON ({e})") from e
    if not isinstance(man, dict) or "files" not in man:
        raise IntegrityError(f"{art_dir}: manifest missing the files map")
    digest = hashlib.sha256(blob).hexdigest()
    if verify_files:
        listed = set(man["files"])
        present = set(_walk_files(art_dir))
        if present != listed:
            raise IntegrityError(
                f"{art_dir}: file set drifted from the manifest "
                f"(missing: {sorted(listed - present)}, "
                f"unlisted: {sorted(present - listed)})")
        for rel, rec in sorted(man["files"].items()):
            actual = file_sha256(os.path.join(art_dir, rel))
            if actual != rec["sha256"]:
                raise IntegrityError(
                    f"{art_dir}/{rel}: sha256 mismatch — manifest says "
                    f"{rec['sha256'][:16]}…, file hashes to "
                    f"{actual[:16]}… (torn or corrupted artifact)")
    return man, digest
