"""Zero-retrace servable restore from a registry artifact.

`load_servable(root, ref)` turns a digest-addressed artifact back into
a model the serve engine can publish directly (`ServeEngine(model)` /
`engine.swap(model)`): the integrity-checked read (registry/store.py)
hands over the object directory, model.npz restores the ensemble +
mapper + encoder (its embedded manifest digest-verified on the way in),
and the per-bucket StableHLO blobs deserialize into the scoring
callables — the model is never re-TRACED in this process; each bucket
pays exactly one XLA compile of the shipped program, at load time,
which `make registry-smoke`'s jit_compiles witness pins at zero during
serving.

Fallback ladder (the "same artifact serves on chip or host" contract):

1. requested variant's AOT blobs cover this platform -> RestoredModel
   (zero retrace);
2. quantized serving requested but the lut blobs were lowered for a
   different platform -> rebuild the LUT path from the CARRIED
   quantized tables (lut_tables.npz) through the normal backend — a
   retrace, but the int8 representation and its error bound are the
   exported ones, bit-for-bit;
3. no usable blobs at all (foreign platform, pre-AOT artifact) ->
   plain ServableModel build from model.npz — full prologue, correct
   everywhere.

Every restore emits an `artifact` run-log event (schema v5) carrying
the digest, the mode the ladder chose, and the training run_id — the
provenance join `cli report`'s registry section renders.
"""

from __future__ import annotations

import dataclasses
import logging
import os

import numpy as np

from ddt_tpu.registry.manifest import IntegrityError
from ddt_tpu.registry.store import DIGEST_LEN, Registry, RegistryError
from ddt_tpu.serve.engine import (TIER_IMPL, ServableModel,
                                  default_buckets)

log = logging.getLogger("ddt_tpu.registry.loader")


class RestoredModel(ServableModel):
    """A ServableModel whose dispatch seam is a deserialized AOT
    program per bucket shape — everything above `_invoke` (bucket
    padding, oversize chunking, per-request binning, probability
    transform) is inherited, so restored and freshly-built models obey
    identical shape/semantics contracts."""

    aot = True

    def __init__(self, bundle, manifest: dict, digest: str,
                 fns: dict, operands: tuple, *, quantized: bool,
                 raw: bool, tier: "str | None" = None):
        # Deliberately NOT calling ServableModel.__init__: this model
        # must never touch a backend or re-trace — its build cost was
        # paid in the exporting process.
        self.ens = bundle.ensemble
        self.mapper = bundle.mapper
        self.backend = None
        self.buckets = tuple(sorted(int(b) for b in manifest["buckets"]))
        self.raw = bool(raw)
        self.quantized = bool(quantized)
        self.quantize_tier = tier
        # The tier is PINNED by what was deserialized — there is no
        # backend ladder to consult (ServableModel.predict_impl), and a
        # restored program cannot silently fall anywhere.
        self._impl_override = TIER_IMPL.get(tier, "f32")
        self.compiled = None
        self.tables = None
        self.token = manifest["model_token"]
        self.artifact_digest = digest
        self.max_abs_err = float(
            (manifest.get("quantized") or {}).get("max_abs_err", 0.0)
            if quantized else 0.0)
        self._fns = dict(fns)           # bucket -> jitted Exported.call
        self._ops = tuple(operands)     # device-resident operand arrays

    def _invoke(self, Xb: np.ndarray) -> np.ndarray:
        # score_binned already padded to a manifest bucket, so the
        # lookup cannot miss; each callable is jax.jit(exported.call) —
        # compiled once at warmup, a cache hit forever after.
        return np.asarray(self._fns[Xb.shape[0]](*self._ops, Xb))


@dataclasses.dataclass
class LoadReport:
    """What the restore ladder actually did (surfaced by the CLI and
    asserted by the smoke: 'it worked' is not enough — the smoke needs
    to know it worked WITHOUT retracing)."""

    digest: str
    mode: str            # aot-f32 | aot-lut | tables-fallback | rebuild
    model: ServableModel
    manifest: dict


def _emit_artifact_event(run_log, action: str, digest: str, man: dict,
                         mode: str | None = None) -> None:
    if run_log is None:
        return
    from ddt_tpu.telemetry.events import RunLog

    rl = RunLog.coerce(run_log)
    rl.emit("artifact", action=action, digest=digest,
            kind=man.get("kind"), run_id=man.get("run_id"),
            model_token=(man.get("model_token") or "")[:12] or None,
            mode=mode)


def load_servable(root, ref: str, *, quantize=None,
                  raw: bool = False, backend=None, cfg=None,
                  run_log=None) -> LoadReport:
    """Restore a servable model from registry reference `ref` (digest,
    `name`, `name@version`, or `name@tag`). `quantize=None` follows the
    artifact (quantized exports serve quantized, at the TIER they were
    exported with — int8 or int4); True serves the artifact's exported
    tier; "int8"/"int4" demand that specific tier and refuse a
    mismatched artifact (the carried tables ARE the representation — a
    different grid would make the manifest's error bound a lie).
    `backend`/`cfg` are only consulted when the ladder has to fall back
    to an in-process build — `backend` is a DeviceBackend, or a backend
    NAME (the CLI's --backend) to combine with the model-derived config
    here. File I/O and deserialization all happen HERE, on the caller's
    thread — never inside the engine's dispatch loop (the
    serve-blocking-io contract)."""
    import jax

    from ddt_tpu import api
    from ddt_tpu.export import aot
    from ddt_tpu.serve.engine import normalize_quantize
    from ddt_tpu.telemetry.events import RunLog

    # Coerce ONCE: per-event coercion would restart seq at 0 for every
    # emit and leak a file handle per restore. A log we opened here from
    # a path closes with the restore (`_done`); a caller's RunLog
    # instance stays the caller's to close.
    own_log = isinstance(run_log, str)
    run_log = RunLog.coerce(run_log)

    def _done(report: LoadReport) -> LoadReport:
        if own_log:
            run_log.close()
        return report

    reg = root if isinstance(root, Registry) else Registry(root)
    art_dir, man, digest = reg.get(ref)
    if man.get("kind") != "servable":
        raise RegistryError(
            f"{ref!r} ({digest}) is a {man.get('kind')!r} artifact, not "
            "a servable export")
    # reg.get's verifying read already sha256'd model.npz against the
    # artifact manifest — skip the embedded digest's second full pass.
    bundle = api.load_model(os.path.join(art_dir, aot.MODEL_FILE),
                            verify=False)
    ce = bundle.ensemble.compile(tree_chunk=int(man["tree_chunk"]))
    if ce.token != man["model_token"]:
        raise IntegrityError(
            f"{digest}: model.npz rebuilds to token {ce.token[:12]} but "
            f"the manifest pins {str(man['model_token'])[:12]} — the "
            "model file and the exported programs disagree")
    qmeta = man.get("quantized")
    # Pre-int4 artifacts carry no "tier" key — they are the int8 tier.
    art_tier = (qmeta.get("tier", "int8") if qmeta else None)
    if quantize is None:
        tier = art_tier                  # follow the artifact
    elif quantize is True:
        # "serve quantized, whatever tier was exported" — an
        # unquantized artifact still fails loudly below.
        tier = art_tier or "int8"
    else:
        tier = normalize_quantize(quantize)
    if tier and qmeta is None:
        raise ValueError(
            f"{ref!r} was exported without the quantized variant; "
            f"re-push with --quantize={tier} to serve the LUT path")
    if tier and tier != art_tier:
        raise RegistryError(
            f"{ref!r} carries the {art_tier!r} quantized tier but "
            f"{tier!r} was requested — the carried tables are the "
            f"representation that serves; re-push with "
            f"--quantize={tier}")

    platform = jax.default_backend()
    buckets = tuple(sorted(int(b) for b in man["buckets"]))
    variant, blob_tpl = {
        None: ("aot-f32", aot.F32_BLOB),
        "int8": ("aot-lut", aot.LUT_BLOB),
        "int4": ("aot-lut4", aot.LUT4_BLOB),
    }[tier]
    covered = man.get("lut_platforms" if tier else "platforms") or []

    if platform in covered:
        if tier == "int4":
            tables = _load_tables(art_dir, man)
            host_ops = tables.pack_int4().ops
        elif tier:
            tables = _load_tables(art_dir, man)
            from ddt_tpu.ops.predict_lut import lut_device_operands

            host_ops = lut_device_operands(tables)
        else:
            host_ops = ce.arrays()
        import jax.numpy as jnp

        operands = tuple(jnp.asarray(a) for a in host_ops)
        fns = {}
        for b in buckets:
            path = os.path.join(art_dir, aot.AOT_DIR,
                                blob_tpl.format(bucket=b))
            with open(path, "rb") as f:
                exp = aot.deserialize_blob(f.read())
            fns[b] = jax.jit(exp.call)
        model = RestoredModel(bundle, man, digest, fns, operands,
                              quantized=tier is not None, raw=raw,
                              tier=tier)
        _emit_artifact_event(run_log, "load", digest, man, mode=variant)
        log.info("restored %s from %s (%s, buckets %s, zero retrace)",
                 man["model_token"][:12], digest, variant, list(buckets))
        return _done(LoadReport(digest=digest, mode=variant, model=model,
                                manifest=man))

    # ---- fallback: the artifact is still fully servable, just not
    # zero-retrace on this platform ------------------------------------
    mode = "tables-fallback" if tier else "rebuild"
    log.warning(
        "artifact %s carries no %s AOT program for platform %r "
        "(covered: %s); rebuilding the scoring path in-process", digest,
        variant, platform, covered or "none")
    be = None if isinstance(backend, str) else backend
    if be is None:
        from ddt_tpu.backends import get_backend
        from ddt_tpu.config import TrainConfig

        if cfg is None:
            cfg = TrainConfig(
                backend=backend if isinstance(backend, str) else "tpu",
                loss=bundle.ensemble.loss,
                n_classes=max(bundle.ensemble.n_classes, 2),
                predict_impl=TIER_IMPL.get(tier, "auto"))
        be = get_backend(cfg)
    # tables-fallback serves the CARRIED quantized representation
    # (token-pinned), not a re-quantization — the manifest's error
    # bound keeps describing what actually serves even across version
    # skew.
    model = ServableModel(bundle, be, quantize=tier,
                          buckets=buckets, raw=raw,
                          tables=_load_tables(art_dir, man)
                          if tier else None)
    model.artifact_digest = digest
    _emit_artifact_event(run_log, "load", digest, man, mode=mode)
    return _done(LoadReport(digest=digest, mode=mode, model=model,
                            manifest=man))


def _load_tables(art_dir: str, man: dict):
    """The carried quantized tables (lut_tables.npz), token-checked
    against the manifest. Registry.get's verifying read has already
    proven the file exists and matches its manifest hash — a pruned or
    torn file raises IntegrityError upstream, never reaches here."""
    from ddt_tpu.export import aot

    path = os.path.join(art_dir, aot.LUT_TABLES_FILE)
    with np.load(path) as z:
        tables = aot.tables_from_arrays(dict(z))
    if tables.token != man["model_token"]:
        raise IntegrityError(
            f"{path}: quantized tables carry token "
            f"{tables.token[:12]} but the manifest pins "
            f"{str(man['model_token'])[:12]}")
    return tables


def push_servable(root, bundle, *, name: str | None = None,
                  max_batch: int = 256, quantize=False,
                  raw: bool = False, tree_chunk: int = 64,
                  run_id: str | None = None, tag: str | None = None,
                  run_log=None) -> dict:
    """Export + publish in one call (the `cli registry push` body and
    the test/bench entry): stage a servable artifact for the engine's
    power-of-two bucket ladder up to `max_batch`, then push it.
    `quantize` is the tier (False | True/"int8" | "int4" — see
    aot.stage_servable). Returns the store's {digest, name, version}."""
    from ddt_tpu.export import aot
    from ddt_tpu.telemetry.events import RunLog

    if tag is not None and name is None:
        raise RegistryError(
            "a tag needs a name to live under (tags are rows of the "
            "name index); pass name= alongside tag=")
    reg = root if isinstance(root, Registry) else Registry(root)
    stage = reg.stage()
    try:
        aot.stage_servable(
            stage, bundle, buckets=default_buckets(max_batch),
            quantize=quantize, raw=raw, tree_chunk=tree_chunk,
            run_id=run_id)
        # stage_servable hashed every file into the manifest moments
        # ago in this process — skip the verifying re-read's second
        # full sha256 pass.
        return reg.push(stage, name, tag=tag,
                        run_log=RunLog.coerce(run_log),
                        verify_files=False)
    except BaseException:
        import shutil

        shutil.rmtree(stage, ignore_errors=True)
        raise


def short_digest(digest: str) -> str:
    return digest[:DIGEST_LEN]
