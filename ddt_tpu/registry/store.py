"""Digest-addressed on-disk model registry (docs/REGISTRY.md).

Layout under one root directory:

    <root>/
      objects/<digest16>/        # one immutable artifact per content digest
        manifest.json            # per-file sha256 map + export metadata
        model.npz                # the full api.save_model artifact
        aot/predict_*.bin        # serialized StableHLO per bucket shape
        lut_tables.npz           # quantized tables (quantized exports)
      names/<name>.json          # version index: [{version, digest, …}]
      names/<name>.lock          # O_EXCL read-modify-write lock
      staging/…                  # in-flight pushes (same filesystem)

Write discipline (the checkpoint-hardening patterns, PR 7, applied to a
new artifact class):

- **Objects land atomically.** A push stages its files under
  `staging/`, finalizes the manifest, and `os.rename`s the WHOLE
  directory to `objects/<digest>` — readers see a complete artifact or
  nothing; a killed push leaves only staging litter the next push
  sweeps. Content addressing makes concurrent same-content pushes
  idempotent: whoever renames first wins, the loser observes the
  object already present and succeeds without a second copy.
- **The name index is small JSON, locked then replaced.** Version
  assignment is a read-modify-write under `names/<name>.lock`
  (O_CREAT|O_EXCL with bounded retry), and the index itself lands via
  tmp-then-`os.replace` — concurrent pushers get dense, unique
  versions (tests/test_registry.py races them).
- **Reads verify.** `get()` re-hashes every file against the manifest
  and the manifest against the addressed digest; a torn or tampered
  object raises `IntegrityError`, never serves.
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import tempfile
import time
import uuid

from ddt_tpu.registry.manifest import (
    IntegrityError, read_artifact_manifest)

log = logging.getLogger("ddt_tpu.registry")

#: hex chars of the artifact sha256 used as the object directory name
#: (and the canonical short form printed everywhere).
DIGEST_LEN = 16
_LOCK_TIMEOUT_S = 10.0
_LOCK_POLL_S = 0.02
#: staged pushes older than this are crash litter (a live export runs
#: seconds, not hours) — swept by the next stage() call.
_STAGE_SWEEP_AGE_S = 3600.0


class RegistryError(ValueError):
    """Bad reference / missing object / misused registry — user-facing,
    distinct from IntegrityError (which means the BYTES are wrong)."""


class Registry:
    """One on-disk registry root. Thread- and process-safe for pushes
    (object renames are atomic; the name index is lock-serialized);
    reads need no locking at all — objects are immutable once visible."""

    def __init__(self, root: str):
        self.root = str(root)

    # ------------------------------------------------------------------ #
    # paths
    # ------------------------------------------------------------------ #

    @property
    def objects_dir(self) -> str:
        return os.path.join(self.root, "objects")

    @property
    def names_dir(self) -> str:
        return os.path.join(self.root, "names")

    def object_dir(self, digest: str) -> str:
        return os.path.join(self.objects_dir, digest[:DIGEST_LEN])

    def stage(self) -> str:
        """A fresh staging directory ON THE REGISTRY FILESYSTEM (the
        final `os.rename` into objects/ must never cross devices).
        Sweeps crash litter first: a SIGKILLed pusher's stage never got
        its cleanup, so stale push_* dirs (mtime older than the sweep
        age — far beyond any live export) are reclaimed here, best
        effort, without ever touching a concurrent pusher's fresh
        stage."""
        staging = os.path.join(self.root, "staging")
        os.makedirs(staging, exist_ok=True)
        cutoff = time.time() - _STAGE_SWEEP_AGE_S
        for entry in os.listdir(staging):
            if not entry.startswith("push_"):
                continue
            path = os.path.join(staging, entry)
            try:
                if os.path.getmtime(path) < cutoff:
                    log.info("sweeping stale stage %s", path)
                    shutil.rmtree(path, ignore_errors=True)
            except OSError:
                pass                    # raced with its owner: leave it
        return tempfile.mkdtemp(prefix="push_", dir=staging)

    # ------------------------------------------------------------------ #
    # push
    # ------------------------------------------------------------------ #

    def push(self, stage_dir: str, name: str | None = None, *,
             tag: str | None = None, run_log=None,
             verify_files: bool = True) -> dict:
        """Publish a finalized staged artifact (export.aot.stage_servable
        wrote it, manifest.json included). Returns {digest, name,
        version} (version None for anonymous pushes). Emits an
        `artifact` run-log event when `run_log` is given.
        `verify_files=False` skips re-hashing every staged file — for
        callers that just built the stage in-process (the manifest
        writer already hashed them); externally staged dirs keep the
        verifying default."""
        if tag is not None and name is None:
            raise RegistryError(
                "a tag needs a name to live under (tags are rows of "
                "the name index); pass name= alongside tag=")
        man, digest = read_artifact_manifest(stage_dir,
                                             verify_files=verify_files)
        os.makedirs(self.objects_dir, exist_ok=True)
        dst = self.object_dir(digest)
        if os.path.isdir(dst):
            # Content-addressed idempotence: the object is already
            # published (same bytes by construction) — drop the stage.
            shutil.rmtree(stage_dir, ignore_errors=True)
        else:
            try:
                os.rename(stage_dir, dst)       # the atomic publish
            except OSError:
                if not os.path.isdir(dst):      # not a lost same-digest
                    raise                       # race — a real failure
                shutil.rmtree(stage_dir, ignore_errors=True)
        version = None
        if name is not None:
            version = self._record_version(name, digest, man, tag=tag)
        if run_log is not None:
            run_log.emit(
                "artifact", action="push", digest=digest[:DIGEST_LEN],
                name=name, version=version, kind=man.get("kind"),
                run_id=man.get("run_id"), model_token=man.get(
                    "model_token", "")[:12] or None)
        log.info("registry push %s%s -> %s", name or "(anonymous)",
                 f"@{version}" if version else "", digest[:DIGEST_LEN])
        return {"digest": digest[:DIGEST_LEN], "name": name,
                "version": version}

    def _record_version(self, name: str, digest: str, man: dict, *,
                        tag: str | None = None) -> int:
        _check_name(name)
        os.makedirs(self.names_dir, exist_ok=True)
        with self._name_lock(name):
            idx = self._read_index(name)
            for v in idx["versions"]:
                if v["digest"] == digest[:DIGEST_LEN]:
                    # Same content re-pushed under the same name: reuse
                    # the version (push is idempotent end to end).
                    if tag is not None:
                        idx["tags"][tag] = v["version"]
                        self._write_index(name, idx)
                    return v["version"]
            version = 1 + max((v["version"] for v in idx["versions"]),
                              default=0)
            idx["versions"].append({
                "version": version, "digest": digest[:DIGEST_LEN],
                "pushed_at": time.time(),
                "run_id": man.get("run_id"),
                "model_token": (man.get("model_token") or "")[:12] or None,
                "quantized": bool(man.get("quantized")),
            })
            if tag is not None:
                idx["tags"][tag] = version
            self._write_index(name, idx)
        return version

    # ------------------------------------------------------------------ #
    # resolve / get / list / tag
    # ------------------------------------------------------------------ #

    def resolve(self, ref: str) -> str:
        """Reference -> full object-dir digest. Forms: `<digest>` (full
        or unique prefix, >= 8 hex chars), `name` (latest version),
        `name@<version>`, `name@<tag>`, `name@latest`."""
        ref = str(ref).strip()
        if not ref:
            raise RegistryError("empty registry reference")
        if "@" in ref:
            name, _, sel = ref.partition("@")
            return self._resolve_named(name, sel)
        # A bare hex string long enough to be unambiguous is a digest;
        # anything else is a name.
        if len(ref) >= 8 and all(c in "0123456789abcdef" for c in ref):
            cands = [d for d in self._object_digests()
                     if d.startswith(ref[:DIGEST_LEN])]
            if len(cands) == 1:
                return cands[0]
            if len(cands) > 1:
                raise RegistryError(
                    f"digest prefix {ref!r} is ambiguous ({len(cands)} "
                    "objects match); use more characters")
            # fall through: maybe it IS a model name that looks hexy
        return self._resolve_named(ref, "latest")

    def _resolve_named(self, name: str, sel: str) -> str:
        idx = self._read_index(name)
        if not idx["versions"]:
            raise RegistryError(
                f"no model named {name!r} in registry {self.root}")
        if sel in ("", "latest"):
            return idx["versions"][-1]["digest"]
        if sel.isdigit():
            for v in idx["versions"]:
                if v["version"] == int(sel):
                    return v["digest"]
            raise RegistryError(
                f"{name}@{sel}: no such version (have 1.."
                f"{idx['versions'][-1]['version']})")
        if sel in idx["tags"]:
            return self._resolve_named(name, str(idx["tags"][sel]))
        raise RegistryError(
            f"{name}@{sel}: no such version or tag "
            f"(tags: {sorted(idx['tags']) or 'none'})")

    def get(self, ref: str, *, verify: bool = True
            ) -> tuple[str, dict, str]:
        """(object dir, manifest, short digest) for a reference, with a
        full integrity check by default (every file re-hashed against
        the manifest, the manifest re-hashed against the address)."""
        digest = self.resolve(ref)
        d = self.object_dir(digest)
        if not os.path.isdir(d):
            raise RegistryError(
                f"{ref!r} resolves to {digest} but the object is missing "
                f"from {self.objects_dir} (pruned externally?)")
        man, full = read_artifact_manifest(d, verify_files=verify)
        if not full.startswith(digest[:DIGEST_LEN]):
            raise IntegrityError(
                f"{d}: manifest hashes to {full[:DIGEST_LEN]} but the "
                f"object is addressed as {digest[:DIGEST_LEN]} — the "
                "manifest was rewritten in place")
        return d, man, digest[:DIGEST_LEN]

    def list(self, name: str | None = None) -> dict:
        """Registry inventory: {name: {versions: […], tags: {…}}} (one
        entry when `name` is given), plus anonymous object digests not
        referenced by any name."""
        names = {}
        if name is not None:
            names[name] = self._read_index(name)
        else:
            try:
                files = sorted(os.listdir(self.names_dir))
            except OSError:
                files = []
            for fn in files:
                if fn.endswith(".json"):
                    n = fn[:-len(".json")]
                    names[n] = self._read_index(n)
        referenced = {v["digest"] for idx in names.values()
                      for v in idx["versions"]}
        anonymous = ([d for d in self._object_digests()
                      if d not in referenced] if name is None else [])
        return {"root": self.root, "names": names, "anonymous": anonymous}

    def tag(self, ref: str, tag: str) -> dict:
        """Point `name`'s tag at the version `ref` resolves to; ref must
        be name-qualified (tags live in the name index)."""
        if "@" not in ref:
            ref = ref + "@latest"
        name, _, sel = ref.partition("@")
        _check_name(name)
        if not tag or tag == "latest" or tag.isdigit():
            raise RegistryError(
                f"tag {tag!r} is reserved (versions and 'latest' resolve "
                "first); pick a non-numeric tag name")
        digest = self._resolve_named(name, sel)
        with self._name_lock(name):
            idx = self._read_index(name)
            version = next(v["version"] for v in idx["versions"]
                           if v["digest"] == digest)
            idx["tags"][tag] = version
            self._write_index(name, idx)
        return {"name": name, "tag": tag, "version": version,
                "digest": digest}

    # ------------------------------------------------------------------ #
    # name-index plumbing
    # ------------------------------------------------------------------ #

    def _index_path(self, name: str) -> str:
        return os.path.join(self.names_dir, f"{name}.json")

    def _read_index(self, name: str) -> dict:
        _check_name(name)
        try:
            with open(self._index_path(name), encoding="utf-8") as f:
                idx = json.load(f)
        except OSError:
            return {"versions": [], "tags": {}}
        except ValueError as e:
            # The index lands via os.replace, so a torn one means bit
            # rot, not a crashed writer — surface it.
            raise IntegrityError(
                f"{self._index_path(name)}: corrupt name index ({e})"
            ) from e
        idx.setdefault("versions", [])
        idx.setdefault("tags", {})
        return idx

    def _write_index(self, name: str, idx: dict) -> None:
        final = self._index_path(name)
        tmp = f"{final}.{uuid.uuid4().hex[:8]}.tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(idx, f, sort_keys=True)
            os.replace(tmp, final)
        except BaseException:
            try:
                os.remove(tmp)
            except OSError:
                pass
            raise

    def _name_lock(self, name: str):
        return _PathLock(os.path.join(self.names_dir, f"{name}.lock"))

    def _object_digests(self) -> list[str]:
        try:
            return sorted(os.listdir(self.objects_dir))
        except OSError:
            return []


class _PathLock:
    """O_CREAT|O_EXCL lockfile with bounded retry — the smallest
    mutual-exclusion primitive that works across processes on any
    filesystem. Held only around the tiny name-index read-modify-write,
    never around artifact hashing or renames."""

    def __init__(self, path: str,
                 timeout_s: float = _LOCK_TIMEOUT_S):
        self.path = path
        self.timeout_s = timeout_s

    def __enter__(self) -> "_PathLock":
        deadline = time.monotonic() + self.timeout_s
        while True:
            try:
                fd = os.open(self.path,
                             os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                os.write(fd, str(os.getpid()).encode())
                os.close(fd)
                return self
            except FileExistsError:
                if time.monotonic() >= deadline:
                    raise RegistryError(
                        f"timed out after {self.timeout_s:.0f}s waiting "
                        f"for {self.path} (a crashed pusher may have "
                        "left a stale lock; remove it to recover)"
                    ) from None
                time.sleep(_LOCK_POLL_S)

    def __exit__(self, *exc) -> None:
        try:
            os.remove(self.path)
        except OSError:
            pass


def _check_name(name: str) -> None:
    """Names become filenames: keep them path-safe and unambiguous with
    digests/refs (no '@', no separators, not pure hex-ish enforcement —
    resolve() prefers digests only at >= 8 hex chars)."""
    if not name or any(c in name for c in "@/\\") or name.startswith("."):
        raise RegistryError(
            f"invalid model name {name!r}: names must be non-empty, "
            "contain no '@' or path separators, and not start with '.'")
