"""Sparse categorical features → ≤255-bin path (the Criteo config).

[BASELINE]: "sparse categorical features (Criteo config)". The reference
handles high-cardinality categoricals; this build folds them into the same
uint8 binned representation every kernel already consumes (SURVEY.md §2
"Sparse categorical handling": "Hash/frequency-bin categoricals into the same
≤255-bin path"):

- **frequency binning** (default): per column, the (n_bins − 1) most frequent
  category ids each get a dedicated bin, ranked by frequency (rank 0 = most
  frequent → bin 1); everything else — the sparse tail — shares bin 0. CTR
  logs are Zipf-distributed, so the head bins cover most rows while the tail
  collapses to one bin, exactly the LightGBM-style treatment.
- **hash binning**: stateless `id % n_bins` for streaming settings where a
  frequency pass is impossible (the 10B-row config); collisions trade accuracy
  for O(0) state.

Note the tree split semantics stay ordinal (bin <= t goes left). Frequency
binning makes that ordering meaningful (split = "head categories vs tail");
true categorical one-hot-gain splits are a documented extension
(SURVEY.md §2: "one-hot-gain variant later").
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass
class CategoricalEncoder:
    """Per-column frequency-rank vocabularies, serializable."""

    vocab_ids: list[np.ndarray]    # per column: int64 ids, rank order
    n_bins: int

    def transform(self, X_cat: np.ndarray) -> np.ndarray:
        """int64 category ids [R, C] → uint8 bins [R, C] (0 = tail/unknown)."""
        X_cat = np.asarray(X_cat)
        out = np.zeros(X_cat.shape, np.uint8)
        for c, vocab in enumerate(self.vocab_ids):
            # rank+1 for known ids, 0 for tail. searchsorted over the sorted
            # vocab gives the position; map back to frequency rank.
            order = np.argsort(vocab, kind="stable")
            sorted_ids = vocab[order]
            pos = np.searchsorted(sorted_ids, X_cat[:, c])
            pos = np.clip(pos, 0, len(sorted_ids) - 1)
            hit = sorted_ids[pos] == X_cat[:, c]
            rank = order[pos]
            out[:, c] = np.where(hit, rank + 1, 0).astype(np.uint8)
        return out

    def save(self) -> dict:
        d = {"n_bins": np.int64(self.n_bins),
             "n_cols": np.int64(len(self.vocab_ids))}
        for c, v in enumerate(self.vocab_ids):
            d[f"vocab_{c}"] = v
        return d

    @staticmethod
    def load(d: dict) -> "CategoricalEncoder":
        n_cols = int(d["n_cols"])
        return CategoricalEncoder(
            vocab_ids=[np.asarray(d[f"vocab_{c}"], np.int64)
                       for c in range(n_cols)],
            n_bins=int(d["n_bins"]),
        )


def fit_categorical_encoder(
    X_cat: np.ndarray, n_bins: int = 255
) -> CategoricalEncoder:
    """Build per-column frequency vocabularies of size ≤ n_bins − 1."""
    X_cat = np.asarray(X_cat)
    vocabs = []
    for c in range(X_cat.shape[1]):
        ids, counts = np.unique(X_cat[:, c], return_counts=True)
        # Stable frequency order: by (-count, id) so ties are deterministic.
        order = np.lexsort((ids, -counts))
        vocabs.append(ids[order][: n_bins - 1].astype(np.int64))
    return CategoricalEncoder(vocab_ids=vocabs, n_bins=n_bins)


def bin_categoricals(X_cat: np.ndarray, n_bins: int = 255) -> np.ndarray:
    """fit + transform convenience (single-pass frequency binning)."""
    return fit_categorical_encoder(X_cat, n_bins=n_bins).transform(X_cat)


def hash_bin_categoricals(X_cat: np.ndarray, n_bins: int = 255) -> np.ndarray:
    """Stateless hash binning for streaming: (id * φ-mix) % n_bins.

    Fibonacci-hash style mixing so adjacent ids don't collide into adjacent
    bins; pure function of the id, usable chunk-by-chunk at 10B-row scale.
    """
    X_cat = np.asarray(X_cat).astype(np.uint64)
    mixed = (X_cat * np.uint64(11400714819323198485)) >> np.uint64(40)
    return (mixed % np.uint64(n_bins)).astype(np.uint8)
