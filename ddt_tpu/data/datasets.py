"""Deterministic synthetic dataset generators for the BASELINE.json configs.

SURVEY.md §2 "Datasets": the reference's eval configs are Higgs-1M (binary),
Covertype (7-class), Criteo (sparse categorical CTR) and a synthetic 10B-row
stress config. This environment has no network, so each config gets a seeded
synthetic generator with the same schema/statistics shape; real-data loaders
can be dropped in later behind the same functions.

All generators return float32 features + integer labels and are chunk-streamable
for the 10B-row config (generate(chunk_start, chunk_rows) is pure in the seed).
"""

from __future__ import annotations

import gzip
import os

import numpy as np


def _rng(seed: int, *stream: int) -> np.random.Generator:
    return np.random.default_rng(np.random.SeedSequence([seed, *stream]))


# --------------------------------------------------------------------- #
# Real-data file loaders (BASELINE configs 1-3 name Higgs/Covertype/
# Criteo files; no network here, but the moment a file exists these read
# it). Formats: .npz (arrays X, y), .csv[.gz] (UCI Higgs: label first
# column; UCI Covertype: label last), .libsvm/.svm/.txt[.gz] (sparse
# "label idx:val ..." lines, 1-based indices).
# --------------------------------------------------------------------- #

def _open_maybe_gzip(path: str):
    if path.endswith(".gz"):
        return gzip.open(path, "rt")
    return open(path, "r")


def _looks_integer_labels(col: np.ndarray) -> bool:
    """Small-cardinality integer-valued column => plausible label column."""
    if not np.all(np.isfinite(col)):
        return False
    r = np.round(col)
    return bool(np.all(np.abs(col - r) < 1e-6) and np.unique(r).size <= 64)

def _split_label(M: np.ndarray, label_col: str) -> tuple[np.ndarray, np.ndarray]:
    if M.ndim != 2 or M.shape[1] < 2:
        raise ValueError(f"tabular file must be 2-D with >=2 columns, "
                         f"got shape {M.shape}")
    if label_col == "first":
        y, X = M[:, 0], M[:, 1:]
    elif label_col == "last":
        y, X = M[:, -1], M[:, :-1]
    elif label_col == "auto":
        # Pick the side that looks like a small-cardinality integer label;
        # ties go to FIRST (the UCI Higgs convention this repo's primary
        # config uses). When NEITHER side qualifies (e.g. a float
        # regression target), auto refuses rather than silently training
        # on a feature column — the caller must say first/last.
        first_ok = _looks_integer_labels(M[:, 0])
        last_ok = _looks_integer_labels(M[:, -1])
        if not first_ok and not last_ok:
            raise ValueError(
                "label_col='auto' could not identify a label column "
                "(neither the first nor the last column is a small-"
                "cardinality integer column — float regression targets "
                "are indistinguishable from features); pass "
                "label_col='first' or 'last' (--label-col in the CLI)"
            )
        if first_ok:
            y, X = M[:, 0], M[:, 1:]
        else:
            y, X = M[:, -1], M[:, :-1]
    else:
        raise ValueError(f"label_col must be first|last|auto, got {label_col!r}")
    return X, y


def _finalize_xy(
    X: np.ndarray, y: np.ndarray, normalize_labels: bool
) -> tuple[np.ndarray, np.ndarray]:
    X = np.ascontiguousarray(X, dtype=np.float32)
    y = np.asarray(y)
    if y.ndim != 1 or len(y) != len(X):
        raise ValueError(f"y must be 1-D with len(X)={len(X)}, got {y.shape}")
    if not np.all(np.isfinite(y.astype(np.float64))):
        raise ValueError("labels contain NaN/inf")
    r = np.round(y.astype(np.float64))
    if np.all(np.abs(y.astype(np.float64) - r) < 1e-9):
        yi = r.astype(np.int64)
        if normalize_labels:
            u = np.unique(yi)
            # External classification conventions -> 0-based class ids:
            # libsvm's binary -1/+1, and EXACTLY-1..k sets (Covertype's
            # 1..7). The contiguity + size>=2 requirement keeps a slice
            # that merely lacks some class (e.g. all-positive {1}) from
            # being silently relabeled. Inherently ambiguous cases (a
            # 0-based file where class 0 never occurs looks like 1..k)
            # have the normalize_labels=False escape hatch.
            if u.size == 2 and u[0] == -1 and u[1] == 1:
                yi = (yi > 0).astype(np.int64)
            elif (2 <= u.size <= 64 and u[0] == 1
                  and u[-1] == u.size
                  and np.array_equal(u, np.arange(1, u.size + 1))):
                yi = yi - 1
        if np.abs(yi).max() < 2 ** 31:
            return X, yi.astype(np.int32)
        return X, y.astype(np.float32)
    return X, y.astype(np.float32)


# Densified-libsvm guardrail: refuse rows x max_index allocations past this
# many float32s (~1 GiB) — hash-indexed CTR files (max index ~2^20+) must go
# through a sparse/streaming pipeline, not this dense loader.
_LIBSVM_DENSE_MAX_ELEMS = 1 << 28


def _is_libsvm_data_line(data: str) -> bool:
    """Structurally a libsvm data line: float label + idx:val tokens. A CSV
    header merely CONTAINING ':' (e.g. 'ts:utc,label,f1') fails this."""
    parts = data.split()
    if len(parts) < 2:
        return False
    try:
        float(parts[0])
        for tok in parts[1:]:
            i, v = tok.split(":", 1)
            int(i)
            float(v)
        return True
    except ValueError:
        return False


def _load_libsvm(
    path: str, n_features: int | None = None, max_rows: int | None = None
) -> tuple[np.ndarray, np.ndarray]:
    labels: list[float] = []
    rows: list[list[tuple[int, float]]] = []
    max_idx = 0
    with _open_maybe_gzip(path) as f:
        for ln, line in enumerate(f, 1):
            if max_rows is not None and len(rows) >= max_rows:
                break
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            parts = line.split()
            try:
                labels.append(float(parts[0]))
                feats = []
                for tok in parts[1:]:
                    i, v = tok.split(":", 1)
                    i = int(i)
                    if i < 1:
                        raise ValueError("libsvm indices are 1-based")
                    feats.append((i, float(v)))
                    max_idx = max(max_idx, i)
                rows.append(feats)
            except (ValueError, IndexError) as e:
                raise ValueError(f"{path}:{ln}: bad libsvm line: {e}") from e
    if n_features is not None:
        if max_idx > n_features:
            raise ValueError(
                f"{path}: feature index {max_idx} exceeds n_features="
                f"{n_features}"
            )
        max_idx = n_features   # pin width: sparse tails must not shrink X
    if len(rows) * max_idx > _LIBSVM_DENSE_MAX_ELEMS:
        raise ValueError(
            f"{path}: densifying {len(rows)} x {max_idx} would allocate "
            f">{_LIBSVM_DENSE_MAX_ELEMS * 4 >> 30} GiB; this loader is "
            "dense-only — pass max_rows to trim, or preprocess hash-indexed "
            "sparse data (e.g. via data.categorical.hash_bin_categoricals) "
            "instead of widening it"
        )
    X = np.zeros((len(rows), max_idx), dtype=np.float32)
    for r, feats in enumerate(rows):
        for i, v in feats:
            X[r, i - 1] = v
    return X, np.asarray(labels)


def load_file(
    path: str,
    label_col: str = "auto",
    max_rows: int | None = None,
    normalize_labels: bool | None = None,
    n_features: int | None = None,
) -> tuple[np.ndarray, np.ndarray]:
    """Load (X float32 [R,F], y [R]) from an on-disk dataset file.

    Supported: .npz with arrays X and y; .csv[.gz] numeric tables (label
    column picked by `label_col`: first|last|auto — pass label_col="last"
    for regression CSVs whose float target auto cannot detect); libsvm
    sparse text (sniffed by ':' tokens regardless of extension).

    `normalize_labels` maps external CLASSIFICATION label conventions to
    0-based class ids ({-1,+1} -> {0,1}; 1-based sets like Covertype's 1..7
    shifted down). Default: True for text formats (which carry those
    conventions), False for .npz (our own format — y is taken verbatim).
    Pass False explicitly when loading integer regression targets from
    text.

    `n_features` pins the expected column count (pass the model's
    n_features when loading a scoring set): libsvm files are padded to it
    (a sparse scoring file whose rows never touch the last features must
    not shrink X), and any wider/mismatched file raises. Raises ValueError
    on schema problems instead of training on garbage.
    """
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    base = path[:-3] if path.endswith(".gz") else path
    ext = os.path.splitext(base)[1].lower()
    if ext == ".npz":
        with np.load(path) as d:
            if "X" not in d or "y" not in d:
                raise ValueError(
                    f"{path}: .npz must contain arrays 'X' and 'y' "
                    f"(has {sorted(d.files)})"
                )
            X, y = d["X"], d["y"]
        if max_rows:
            X, y = X[:max_rows], y[:max_rows]
        if n_features is not None and X.shape[1] != n_features:
            raise ValueError(
                f"{path}: expected {n_features} feature columns, "
                f"got {X.shape[1]}"
            )
        return _finalize_xy(X, y, normalize_labels or False)
    if normalize_labels is None:
        normalize_labels = True
    # Text: find the first line that is DATA (a non-parsing first line is a
    # CSV header — skipped, and never used for format sniffing, so header
    # names containing ':' can't misroute a CSV to the libsvm parser).
    # `skip` counts PHYSICAL lines consumed before the first data line —
    # np.loadtxt's skiprows is physical, so blank/comment-only lines ahead
    # of a header must be counted too, not just the header itself.
    with _open_maybe_gzip(path) as f:
        first = ""
        skip = 0
        n_headers = 0
        for line in f:
            data = line.split("#", 1)[0]
            if not data.strip():
                skip += 1              # blank or comment-only line
                continue
            try:
                [float(t) for t in data.replace(",", " ").split()]
                first = data
                break
            except ValueError:
                if _is_libsvm_data_line(data):
                    first = data
                    break
                skip += 1              # header line
                n_headers += 1
                if n_headers > 1:
                    raise ValueError(
                        f"{path}: not a numeric CSV (two non-parsing "
                        "leading lines) and not libsvm format"
                    ) from None
    if _is_libsvm_data_line(first) or ext in (".libsvm", ".svm"):
        return _finalize_xy(
            *_load_libsvm(path, n_features=n_features, max_rows=max_rows),
            normalize_labels,
        )
    # CSV: `skip` header rows were detected above. The native C++ parser
    # (native/csv_loader.cpp) is 1.5x np.loadtxt single-core and
    # OpenMP-parallel over rows for real ingest hosts; semantics are the
    # same np.loadtxt subset (parity-tested, tests/test_native.py) and the
    # NumPy path remains the no-toolchain fallback.
    M = None
    try:
        from ddt_tpu.native import csv_parse_native
    except (ImportError, OSError):   # OSError: unloadable .so via
        csv_parse_native = None      # ctypes.CDLL (e.g. sanitizer build
                                     # without its runtime preloaded)
    if csv_parse_native is not None:
        # File I/O errors (missing file, permissions, bad gzip) are NOT
        # guarded — they must surface here, not after a loadtxt re-read.
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rb") as f:
            M = csv_parse_native(f.read(), skip_rows=skip,
                                 max_rows=max_rows)
    if M is None:
        with _open_maybe_gzip(path) as f:
            M = np.loadtxt(f, delimiter=",", skiprows=skip,
                           max_rows=max_rows, dtype=np.float64)
    if M.ndim == 1:
        M = M[None, :]
    X, y = _split_label(M, label_col)
    if n_features is not None and X.shape[1] != n_features:
        raise ValueError(
            f"{path}: expected {n_features} feature columns, got {X.shape[1]}"
        )
    return _finalize_xy(X, y, normalize_labels)


def synthetic_binary(
    n_rows: int, n_features: int = 28, seed: int = 0, noise: float = 1.0
) -> tuple[np.ndarray, np.ndarray]:
    """Higgs-like binary task: 28 continuous features, nonlinear signal.

    Label depends on a few nonlinear feature interactions so trees of depth>=3
    have real signal to find; AUC of a good GBDT lands ~0.8-0.9 (sanity band
    used by tests, not a physics claim).
    """
    rng = _rng(seed, 1)
    X = rng.standard_normal((n_rows, n_features), dtype=np.float32)
    score = (
        np.sin(X[:, 0] * 2.0)
        + X[:, 1] * X[:, 2]
        + 0.5 * np.square(X[:, 3])
        - 1.0 * (X[:, 4] > 0.5)
        + noise * rng.standard_normal(n_rows, dtype=np.float32) * 0.5
    )
    y = (score > np.median(score)).astype(np.int32)
    return X, y


def synthetic_multiclass(
    n_rows: int,
    n_features: int = 54,
    n_classes: int = 7,
    seed: int = 0,
) -> tuple[np.ndarray, np.ndarray]:
    """Covertype-like 7-class task: class-dependent cluster centers + noise."""
    rng = _rng(seed, 2)
    centers = rng.standard_normal((n_classes, n_features), dtype=np.float32) * 2.0
    y = rng.integers(0, n_classes, size=n_rows).astype(np.int32)
    X = centers[y] + rng.standard_normal((n_rows, n_features), dtype=np.float32)
    return X, y


def synthetic_ctr(
    n_rows: int,
    n_numeric: int = 13,
    n_categorical: int = 26,
    cardinality: int = 100_000,
    seed: int = 0,
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Criteo-like CTR task: 13 numeric + 26 high-cardinality categorical cols.

    Returns (X_num float32 [R, 13], X_cat int64 [R, 26], y int32 [R]).
    Categorical ids are Zipf-distributed (few heavy hitters), like real CTR
    logs; a subset of categories carries label signal.
    """
    rng = _rng(seed, 3)
    X_num = rng.standard_normal((n_rows, n_numeric), dtype=np.float32)
    # Zipf-ish: sample from a power-law over [0, cardinality)
    u = rng.random((n_rows, n_categorical))
    X_cat = np.floor(cardinality * np.power(u, 3.0)).astype(np.int64)
    signal = (
        0.8 * np.sin((X_cat[:, 0] % 17).astype(np.float32))
        + 0.6 * ((X_cat[:, 1] % 5) == 0)
        + 0.5 * X_num[:, 0]
        + rng.standard_normal(n_rows, dtype=np.float32) * 0.7
    )
    y = (signal > np.quantile(signal, 0.75)).astype(np.int32)  # ~25% CTR
    return X_num, X_cat, y


def synthetic_regression(
    n_rows: int, n_features: int = 16, seed: int = 0
) -> tuple[np.ndarray, np.ndarray]:
    rng = _rng(seed, 4)
    X = rng.standard_normal((n_rows, n_features), dtype=np.float32)
    y = (
        2.0 * X[:, 0]
        + np.square(X[:, 1])
        + X[:, 2] * (X[:, 3] > 0)
        + 0.1 * rng.standard_normal(n_rows, dtype=np.float32)
    ).astype(np.float32)
    return X, y


def stress_binned_chunk(
    chunk_start: int,
    chunk_rows: int,
    n_features: int = 1024,
    n_bins: int = 255,
    seed: int = 0,
) -> tuple[np.ndarray, np.ndarray]:
    """Streaming generator for the 10B-row/1024-feature stress config.

    Emits already-binned uint8 chunks (no quantizer pass needed at this scale)
    plus binary labels; pure function of (seed, chunk_start), so any chunk can
    be regenerated independently on any host — this is how the pod-scale config
    streams without a shared filesystem.
    """
    rng = _rng(seed, 5, chunk_start)
    Xb = rng.integers(0, n_bins, size=(chunk_rows, n_features), dtype=np.uint8)
    y = (
        (Xb[:, 0].astype(np.int32) + Xb[:, 1].astype(np.int32))
        > n_bins
    ).astype(np.int32)
    return Xb, y
