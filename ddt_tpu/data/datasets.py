"""Deterministic synthetic dataset generators for the BASELINE.json configs.

SURVEY.md §2 "Datasets": the reference's eval configs are Higgs-1M (binary),
Covertype (7-class), Criteo (sparse categorical CTR) and a synthetic 10B-row
stress config. This environment has no network, so each config gets a seeded
synthetic generator with the same schema/statistics shape; real-data loaders
can be dropped in later behind the same functions.

All generators return float32 features + integer labels and are chunk-streamable
for the 10B-row config (generate(chunk_start, chunk_rows) is pure in the seed).
"""

from __future__ import annotations

import numpy as np


def _rng(seed: int, *stream: int) -> np.random.Generator:
    return np.random.default_rng(np.random.SeedSequence([seed, *stream]))


def synthetic_binary(
    n_rows: int, n_features: int = 28, seed: int = 0, noise: float = 1.0
) -> tuple[np.ndarray, np.ndarray]:
    """Higgs-like binary task: 28 continuous features, nonlinear signal.

    Label depends on a few nonlinear feature interactions so trees of depth>=3
    have real signal to find; AUC of a good GBDT lands ~0.8-0.9 (sanity band
    used by tests, not a physics claim).
    """
    rng = _rng(seed, 1)
    X = rng.standard_normal((n_rows, n_features), dtype=np.float32)
    score = (
        np.sin(X[:, 0] * 2.0)
        + X[:, 1] * X[:, 2]
        + 0.5 * np.square(X[:, 3])
        - 1.0 * (X[:, 4] > 0.5)
        + noise * rng.standard_normal(n_rows, dtype=np.float32) * 0.5
    )
    y = (score > np.median(score)).astype(np.int32)
    return X, y


def synthetic_multiclass(
    n_rows: int,
    n_features: int = 54,
    n_classes: int = 7,
    seed: int = 0,
) -> tuple[np.ndarray, np.ndarray]:
    """Covertype-like 7-class task: class-dependent cluster centers + noise."""
    rng = _rng(seed, 2)
    centers = rng.standard_normal((n_classes, n_features), dtype=np.float32) * 2.0
    y = rng.integers(0, n_classes, size=n_rows).astype(np.int32)
    X = centers[y] + rng.standard_normal((n_rows, n_features), dtype=np.float32)
    return X, y


def synthetic_ctr(
    n_rows: int,
    n_numeric: int = 13,
    n_categorical: int = 26,
    cardinality: int = 100_000,
    seed: int = 0,
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Criteo-like CTR task: 13 numeric + 26 high-cardinality categorical cols.

    Returns (X_num float32 [R, 13], X_cat int64 [R, 26], y int32 [R]).
    Categorical ids are Zipf-distributed (few heavy hitters), like real CTR
    logs; a subset of categories carries label signal.
    """
    rng = _rng(seed, 3)
    X_num = rng.standard_normal((n_rows, n_numeric), dtype=np.float32)
    # Zipf-ish: sample from a power-law over [0, cardinality)
    u = rng.random((n_rows, n_categorical))
    X_cat = np.floor(cardinality * np.power(u, 3.0)).astype(np.int64)
    signal = (
        0.8 * np.sin((X_cat[:, 0] % 17).astype(np.float32))
        + 0.6 * ((X_cat[:, 1] % 5) == 0)
        + 0.5 * X_num[:, 0]
        + rng.standard_normal(n_rows, dtype=np.float32) * 0.7
    )
    y = (signal > np.quantile(signal, 0.75)).astype(np.int32)  # ~25% CTR
    return X_num, X_cat, y


def synthetic_regression(
    n_rows: int, n_features: int = 16, seed: int = 0
) -> tuple[np.ndarray, np.ndarray]:
    rng = _rng(seed, 4)
    X = rng.standard_normal((n_rows, n_features), dtype=np.float32)
    y = (
        2.0 * X[:, 0]
        + np.square(X[:, 1])
        + X[:, 2] * (X[:, 3] > 0)
        + 0.1 * rng.standard_normal(n_rows, dtype=np.float32)
    ).astype(np.float32)
    return X, y


def stress_binned_chunk(
    chunk_start: int,
    chunk_rows: int,
    n_features: int = 1024,
    n_bins: int = 255,
    seed: int = 0,
) -> tuple[np.ndarray, np.ndarray]:
    """Streaming generator for the 10B-row/1024-feature stress config.

    Emits already-binned uint8 chunks (no quantizer pass needed at this scale)
    plus binary labels; pure function of (seed, chunk_start), so any chunk can
    be regenerated independently on any host — this is how the pod-scale config
    streams without a shared filesystem.
    """
    rng = _rng(seed, 5, chunk_start)
    Xb = rng.integers(0, n_bins, size=(chunk_rows, n_features), dtype=np.uint8)
    y = (
        (Xb[:, 0].astype(np.int32) + Xb[:, 1].astype(np.int32))
        > n_bins
    ).astype(np.int32)
    return Xb, y
