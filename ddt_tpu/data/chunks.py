"""File-backed chunk sources for the streaming trainer (layer L7).

The 10B-row config (BASELINE config 5) cannot hold a dataset in host
memory; streaming.fit_streaming already trains from any pure
``chunk_fn(c) -> (X_chunk, y_chunk)``. This module provides the on-disk
realization: a directory of npz shards, a writer that cuts one, and a
binned-cache writer so every re-read of a chunk streams uint8 straight
from disk instead of re-binning floats (fit_streaming re-reads every
chunk (max_depth+1) times per tree).

Shard layout: ``<dir>/chunk_00000.npz`` ... each holding arrays ``X``
([rows, F] — float32 raw features, or uint8 when pre-binned) and ``y``
([rows] labels). Shards stream in filename order; sizes may differ (each
distinct size jit-compiles its own device program — the writers cut
near-equal sizes so at most two programs compile).

O(chunk) guarantee: nothing here holds more than one shard in memory at
a time; the label-only accessor decompresses just the ``y`` member (npz
members are read lazily), so fit_streaming's pass 0 never touches X.
"""

from __future__ import annotations

import glob
import os
import re

import numpy as np

from ddt_tpu.utils.atomic import atomic_savez

CHUNK_PREFIX = "chunk_"
_CHUNK_RE = re.compile(re.escape(CHUNK_PREFIX) + r"(\d+)\.npz$")


def _chunk_path(out_dir: str, c: int) -> str:
    return os.path.join(out_dir, f"{CHUNK_PREFIX}{c:05d}.npz")


def _atomic_savez(path: str, **arrays) -> None:
    """Shard writes are tmp-then-os.replace (utils/atomic.py): a writer
    killed mid-shard leaves no torn chunk_*.npz for a later training run
    to choke on — the reader's canonical-name regex (_CHUNK_RE,
    $-anchored) never matches the .tmp.npz name, so a partial write is
    invisible to chunk_files."""
    atomic_savez(path, **arrays)


def _purge_stale(out_dir: str, n_chunks: int) -> None:
    """Drop shards with index >= n_chunks that a prior (larger) run left
    behind — otherwise directory_chunks would report the stale count and
    serve the old run's data. Called AFTER writing so re-sharding in
    place never deletes data before reading it; non-canonical filenames
    that merely match the glob (chunk_backup.npz) are left alone."""
    for path in glob.glob(os.path.join(out_dir, CHUNK_PREFIX + "*.npz")):
        m = _CHUNK_RE.search(os.path.basename(path))
        if m and int(m.group(1)) >= n_chunks:
            os.remove(path)


def chunk_files(src_dir: str) -> list[str]:
    files = sorted(
        f for f in glob.glob(os.path.join(src_dir, CHUNK_PREFIX + "*.npz"))
        if _CHUNK_RE.search(os.path.basename(f))
    )
    if not files:
        raise ValueError(
            f"no {CHUNK_PREFIX}*.npz shards in {src_dir!r} — write them "
            "with data.chunks.shard_arrays / shard_file"
        )
    return files


def shard_arrays(
    X: np.ndarray,
    y: np.ndarray,
    out_dir: str,
    n_chunks: int | None = None,
    chunk_rows: int | None = None,
) -> list[str]:
    """Writer utility: cut an in-memory (X, y) into npz shards (linspace
    bounds — every row covered, sizes differ by at most one). Exactly one
    of n_chunks / chunk_rows. Returns the written paths."""
    if (n_chunks is None) == (chunk_rows is None):
        raise ValueError("pass exactly one of n_chunks / chunk_rows")
    rows = len(y)
    if rows == 0:
        raise ValueError("cannot shard an empty dataset")
    if n_chunks is None:
        n_chunks = max(1, -(-rows // chunk_rows))
    if n_chunks > rows:
        raise ValueError(
            f"n_chunks={n_chunks} exceeds the row count ({rows}); empty "
            "chunks are not allowed"
        )
    os.makedirs(out_dir, exist_ok=True)
    bounds = np.linspace(0, rows, n_chunks + 1).astype(np.int64)
    paths = []
    for c in range(n_chunks):
        p = _chunk_path(out_dir, c)
        _atomic_savez(p, X=X[bounds[c]:bounds[c + 1]],
                      y=y[bounds[c]:bounds[c + 1]])
        paths.append(p)
    _purge_stale(out_dir, n_chunks)
    return paths


def shard_file(
    src: str,
    out_dir: str,
    chunk_rows: int,
    label_col: str = "auto",
    normalize_labels: bool | None = None,
) -> list[str]:
    """Shard a dataset file (.npz/.csv[.gz]/libsvm — data.datasets.load_file
    formats) into npz chunk shards. The source file is materialised once
    to split it (these formats aren't seekable by row); from then on
    training streams the shards in O(chunk_rows) memory — run this once on
    a big-memory box, train anywhere."""
    from ddt_tpu.data.datasets import load_file

    X, y = load_file(src, label_col=label_col,
                     normalize_labels=normalize_labels)
    return shard_arrays(X, y, out_dir, chunk_rows=chunk_rows)


def shard_stress_chunks(
    out_dir: str,
    rows: int,
    n_chunks: int,
    n_features: int = 64,
    seed: int = 7,
    n_bins: int = 63,
) -> int:
    """Cut `rows` of the deterministic stress generator
    (data.datasets.stress_binned_chunk) into npz shards, ONE chunk in
    memory at a time (the writer itself is O(chunk) — the scale
    harnesses assert that). The single home of the stress-shard naming
    contract the scale experiments and RSS tests share; returns the
    per-chunk row count."""
    from ddt_tpu.data.datasets import stress_binned_chunk

    os.makedirs(out_dir, exist_ok=True)
    chunk_rows = rows // n_chunks
    for c in range(n_chunks):
        Xc, yc = stress_binned_chunk(
            c, chunk_rows, n_features=n_features, seed=seed,
            n_bins=n_bins)
        _atomic_savez(_chunk_path(out_dir, c), X=Xc, y=yc)
        del Xc, yc
    _purge_stale(out_dir, n_chunks)
    return chunk_rows


def directory_chunks(src_dir: str):
    """ChunkFn over a shard directory. Exposes the side-channel accessors
    fit_streaming/binned_chunks use: ``.labels(c)`` (reads only the y
    member), ``.n_features``, ``.n_chunks``, ``.binned`` (True when the
    shards hold uint8 pre-binned data)."""
    files = chunk_files(src_dir)

    def f(c: int):
        with np.load(files[c]) as d:
            return d["X"], d["y"]

    def labels(c: int):
        with np.load(files[c]) as d:
            return d["y"]

    with np.load(files[0]) as d0:
        X0 = d0["X"]
        f.n_features = int(X0.shape[1])
        f.binned = X0.dtype == np.uint8

    f.labels = labels
    f.n_chunks = len(files)
    return f


class HostShardedChunks:
    """Per-host-addressable chunk source (ROADMAP item 2's ingest half).

    Every group of `shards_per_chunk` consecutive ``chunk_*.npz`` files
    forms one LOGICAL training chunk: logical chunk ``c`` is the row
    concatenation of sub-shards ``c*spc .. (c+1)*spc - 1`` in file
    order. A view for process ``p`` reads the feature matrix of ONLY
    the sub-shards the chunk-shard→host ``assignment`` maps to ``p`` —
    fit_streaming assembles the global device array from those local
    blocks (TPUDevice.upload_row_shards, the
    jax.make_array_from_process_local_data path), so ingest bandwidth
    scales with the host count instead of bottlenecking one controller.

    Labels deliberately stay a GLOBAL side channel (``labels(c)`` reads
    every sub-shard's ``y`` member): the base score, chunk lengths, and
    validity masks are global metadata, and at 4 bytes/row labels are
    noise next to the F bytes/row feature matrix the ownership contract
    protects. npz members load lazily, so the label read never touches
    an unowned shard's ``X``.

    The ownership CONTRACT: with ``process_count > 1`` a full-chunk
    call (``source(c)``) raises — nothing on the host-sharded path may
    materialize another host's feature rows. Single-process views own
    every slot, so the callable form keeps working (the in-memory
    comparators and the host loop ride it).

    ``rotate_assignment()`` is the skew response's ingest half (the
    straggler watchdog's streamed re-partition): the slot→host map
    rotates by one host, so after the paired mesh rotation each host
    reads the sub-shards that now land on its devices. The GLOBAL row
    order never changes — re-partitioning is bit-identical by
    construction, exactly like ``rotate_row_partitions`` on the
    in-memory path."""

    host_sharded = True

    def __init__(self, src_dir: str, shards_per_chunk: int,
                 process_index: int | None = None,
                 process_count: int | None = None,
                 assignment: "tuple | None" = None):
        if process_index is None or process_count is None:
            import jax

            process_index = jax.process_index()
            process_count = jax.process_count()
        files = chunk_files(src_dir)
        if shards_per_chunk < 1:
            raise ValueError(
                f"shards_per_chunk must be >= 1, got {shards_per_chunk}")
        if len(files) % shards_per_chunk:
            raise ValueError(
                f"{len(files)} shard files do not group into logical "
                f"chunks of {shards_per_chunk} sub-shards; re-cut the "
                "shards (data.chunks.shard_arrays with a multiple)")
        if shards_per_chunk % process_count:
            raise ValueError(
                f"shards_per_chunk={shards_per_chunk} must be a multiple "
                f"of process_count={process_count} so every host owns an "
                "equal contiguous block")
        self._files = files
        self.n_shards_per_chunk = shards_per_chunk
        self.process_index = int(process_index)
        self.process_count = int(process_count)
        self.n_chunks = len(files) // shards_per_chunk
        if assignment is None:
            # Contiguous blocks: slot s -> host s*P//spc, so each host's
            # sub-shards are adjacent rows (matching the hosts-outermost
            # mesh's contiguous addressable row range).
            assignment = tuple(
                s * process_count // shards_per_chunk
                for s in range(shards_per_chunk))
        self.assignment = tuple(int(a) for a in assignment)
        if sorted(set(self.assignment)) != list(range(process_count)):
            raise ValueError(
                f"assignment {self.assignment} must cover every process "
                f"in [0, {process_count})")
        with np.load(files[0]) as d0:
            X0 = d0["X"]
            self.n_features = int(X0.shape[1])
            self.binned = X0.dtype == np.uint8
        self._lens: dict = {}

    # -- ownership ----------------------------------------------------- #

    def owned_slots(self, c: int) -> list[int]:
        """Sub-shard slots of logical chunk `c` this process reads (the
        assignment is chunk-independent: skew is a host property)."""
        return [s for s in range(self.n_shards_per_chunk)
                if self.assignment[s] == self.process_index]

    def rotate_assignment(self) -> None:
        """Rotate the slot→host map by one host (the watchdog's streamed
        re-partition, ingest half). Callers pair this with the backend's
        mesh rotation; the global row order is untouched."""
        P = self.process_count
        self.assignment = tuple((a + 1) % P for a in self.assignment)

    # -- reads --------------------------------------------------------- #

    def _file(self, c: int, s: int) -> str:
        return self._files[c * self.n_shards_per_chunk + s]

    def read_part(self, c: int, s: int) -> np.ndarray:
        """Feature matrix of sub-shard `s` of logical chunk `c` — the
        ONLY sanctioned X read on a multi-process view, and only for
        owned slots."""
        if self.process_count > 1 and self.assignment[s] != \
                self.process_index:
            raise PermissionError(
                f"process {self.process_index} asked for sub-shard "
                f"(chunk {c}, slot {s}) owned by process "
                f"{self.assignment[s]} — the host-sharded ownership "
                "contract forbids cross-host chunk reads")
        with np.load(self._file(c, s)) as d:
            return d["X"]

    def part_rows(self, c: int) -> list[int]:
        """Per-slot row counts of logical chunk `c` (y-member reads
        only — cached)."""
        lens = self._lens.get(c)
        if lens is None:
            lens = []
            for s in range(self.n_shards_per_chunk):
                with np.load(self._file(c, s)) as d:
                    lens.append(int(d["y"].shape[0]))
            self._lens[c] = lens
        return lens

    def chunk_rows(self, c: int) -> int:
        return sum(self.part_rows(c))

    def labels(self, c: int) -> np.ndarray:
        """Logical chunk c's GLOBAL labels (y members only, every slot)."""
        ys = []
        for s in range(self.n_shards_per_chunk):
            with np.load(self._file(c, s)) as d:
                ys.append(d["y"])
        return np.concatenate(ys)

    def __call__(self, c: int):
        """Full logical chunk — single-process only (comparators, the
        host loop); a multi-process call is an ownership violation."""
        if self.process_count > 1:
            raise PermissionError(
                "full-chunk reads are forbidden on a multi-process "
                "host-sharded source (ownership contract); use "
                "read_part(c, slot) for owned slots")
        X = np.concatenate([self.read_part(c, s)
                            for s in range(self.n_shards_per_chunk)])
        return X, self.labels(c)


def host_sharded_chunks(src_dir: str, shards_per_chunk: int,
                        process_index: int | None = None,
                        process_count: int | None = None) -> \
        HostShardedChunks:
    """This process's view of a host-sharded shard directory (see
    HostShardedChunks). The fit_streaming-facing constructor."""
    return HostShardedChunks(src_dir, shards_per_chunk,
                             process_index=process_index,
                             process_count=process_count)


def write_binned_cache(
    raw_chunk_fn,
    n_chunks: int,
    mapper,
    cache_dir: str,
):
    """Transform each raw chunk ONCE through a fitted BinMapper and persist
    the uint8 result; returns a directory_chunks source over the cache.
    This is the optional binned-chunk cache: fit_streaming re-reads every
    chunk (max_depth+1) times per tree, and uint8-from-disk beats
    re-binning floats on every pass (and is 4x smaller on disk than the
    float32 it replaces). O(chunk) memory throughout."""
    os.makedirs(cache_dir, exist_ok=True)
    for c in range(n_chunks):
        X, y = raw_chunk_fn(c)
        _atomic_savez(_chunk_path(cache_dir, c),
                      X=mapper.transform(np.asarray(X, np.float32)), y=y)
    _purge_stale(cache_dir, n_chunks)
    return directory_chunks(cache_dir)
