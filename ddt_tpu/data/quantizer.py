"""Quantile binning: float features -> uint8 bin indices (<=255 bins).

Layer L7 of SURVEY.md §1: the reference runs an offline quantizer producing
<=255-bin binned matrices before training ([BASELINE] "features are quantized
into bins (255 bins named explicitly)"). TPU realisation: a NumPy/JAX quantile
sketch on a row sample, then `searchsorted` to produce a uint8 matrix that is
the only large tensor ever shipped to the device.

Bin semantics (shared by every kernel in this repo — oracle, XLA, Pallas, C++):
  bin b covers values v with  edges[b-1] < v <= edges[b]   (edges ascending)
  i.e. bin = searchsorted(edges, v, side='left') clipped to [0, n_bins-1].
A split "(feature f, threshold bin t)" routes rows with bin <= t LEFT.
The raw-value threshold equivalent is edges[t] (go left iff v <= edges[t]).

NaN policy (cfg.missing_policy): "zero" maps NaN to bin 0 (the v1 policy);
"learn" reserves the TOP bin (n_bins-1) for NaN and every split learns a
default direction for it (ops/split.py, reference/numpy_trainer.py) — the
standard histogram-GBDT missing-value treatment.
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass
class BinMapper:
    """Per-feature bin edges + the binned-matrix transform.

    With `missing_bin=True` (cfg.missing_policy="learn") the TOP bin
    (n_bins-1) is reserved for NaN: real values occupy bins 0..n_bins-2 and
    every split learns a default direction for bin n_bins-1 downstream."""

    edges: np.ndarray       # [n_features, n_bins-1] float32, ascending per row
    n_bins: int
    missing_bin: bool = False
    # Columns fitted with IDENTITY edges (values are category/bin ids, never
    # quantile-merged). Recorded so train/predict can verify that a model's
    # cat_features were identity-binned by THIS mapper — a mapper fitted
    # without them would silently merge/permute category ids (failing loudly
    # beats silently, same as the missing_bin guard).
    cat_features: tuple = ()
    # Per-feature REFERENCE bin histogram of the training matrix (ISSUE 19,
    # the drift observatory's baseline): int64 [n_features, n_bins] raw
    # counts, attached by api.train after binning (None when never
    # captured — binned=True training has no mapper-visible matrix, and
    # every pre-drift artifact loads with None). Raw counts, not
    # normalized: the sample size stays visible and the divergence
    # scorer (serve/drift.py) owns the epsilon smoothing. The mapper
    # owns the bin space, so it owns the reference distribution too —
    # save()/load() round-trip it through the same `mapper_*` npz
    # channel as every other field.
    ref_counts: "np.ndarray | None" = None

    @property
    def n_features(self) -> int:
        return self.edges.shape[0]

    @property
    def n_value_bins(self) -> int:
        """Bins available to real values (excludes the reserved NaN bin)."""
        return self.n_bins - 1 if self.missing_bin else self.n_bins

    def non_identity_columns(self, features) -> list[int]:
        """Subset of `features` whose edges do NOT identity-map integer bin
        ids (i.e. were quantile-fitted, so category ids would be merged or
        permuted by transform). Checks the edges themselves rather than the
        recorded `cat_features` metadata, so mappers saved before that field
        existed — or hand-built ones — are judged by the invariant that
        actually matters.

        Memoized per feature tuple: api.predict runs this check on EVERY
        call (scoring correctness must not depend on call history), but
        the edge scan is O(cat_features x bins) against edges that never
        mutate after fit — paying it once per (mapper, feature-set) keeps
        the serving request path's prologue flat (ISSUE 8 satellite).
        Mutating `edges` in place after fit voids the memo (and every
        other consistency property of a fitted mapper)."""
        key = tuple(sorted(int(f) for f in features))
        cache = self.__dict__.setdefault("_non_identity_memo", {})
        if key in cache:
            return list(cache[key])
        bad = sorted(f for f in key if not 0 <= f < self.n_features)
        if bad:
            raise ValueError(
                f"cat_features indices {bad} out of range for "
                f"{self.n_features} features"
            )
        nv = self.n_value_bins
        want = np.arange(nv - 1, dtype=np.float32)
        out = sorted(
            f for f in key
            if not np.array_equal(self.edges[f, : nv - 1], want)
        )
        cache[key] = tuple(out)
        return out

    def transform(self, X: np.ndarray) -> np.ndarray:
        """Bin a float matrix [rows, n_features] -> uint8 [rows, n_features]."""
        X = np.asarray(X, dtype=np.float32)
        if X.ndim != 2 or X.shape[1] != self.n_features:
            raise ValueError(
                f"X must be [rows, {self.n_features}], got {X.shape}"
            )
        out = np.empty(X.shape, dtype=np.uint8)
        nv = self.n_value_bins
        for f in range(self.n_features):
            col = X[:, f]
            binned = np.searchsorted(self.edges[f, : nv - 1], col,
                                     side="left")
            np.clip(binned, 0, nv - 1, out=binned)
            # NaN policy: reserved top bin under missing_bin, else bin 0
            # (v1 policy, module doc). +/-inf fall naturally into the
            # top/bottom VALUE bin via searchsorted.
            binned[np.isnan(col)] = self.n_bins - 1 if self.missing_bin else 0
            out[:, f] = binned.astype(np.uint8)
        return out

    def transform_device(self, X: np.ndarray) -> np.ndarray:
        """transform() on the default JAX device (ops/quantize.py) —
        bit-identical output. Worth it when the float matrix is already
        on (or headed to) the device, or behind a real PCIe/DMA link;
        through a slow host link the f32 upload dominates (measured
        4x slower than host NumPy through this image's remote tunnel —
        the device COMPUTE is sub-second at 2M x 28)."""
        from ddt_tpu.ops.quantize import transform_device

        return transform_device(self, X)

    def threshold_value(self, feature: int, threshold_bin: int) -> float:
        """Raw-value threshold for a (feature, bin) split: go left iff v <= it."""
        t = int(threshold_bin)
        if t >= self.n_value_bins - 1:
            return float("inf")  # rightmost value bin: every value goes left
        return float(self.edges[feature, t])

    def save(self) -> dict:
        d = {"edges": self.edges, "n_bins": np.int64(self.n_bins),
             "missing_bin": np.bool_(self.missing_bin),
             "cat_features": np.asarray(self.cat_features, np.int32)}
        if self.ref_counts is not None:
            d["ref_counts"] = np.asarray(self.ref_counts, np.int64)
        return d

    @staticmethod
    def load(d: dict) -> "BinMapper":
        ref = d.get("ref_counts")
        return BinMapper(edges=np.asarray(d["edges"], np.float32),
                         n_bins=int(d["n_bins"]),
                         missing_bin=bool(d.get("missing_bin", False)),
                         cat_features=tuple(
                             int(f) for f in d.get("cat_features", ())),
                         ref_counts=(None if ref is None
                                     else np.asarray(ref, np.int64)))


def feature_bincounts(Xb: np.ndarray, n_bins: int) -> np.ndarray:
    """Per-feature bin histogram of a binned uint8 matrix: [rows, F] ->
    int64 [F, n_bins] counts. The ONE bincount home shared by the
    training-time reference capture (api.train -> mapper.ref_counts) and
    the serve-side online accumulator (serve/drift.py), so the two sides
    of a PSI comparison count bins identically. Vectorized: one flat
    bincount over feature-offset codes, no per-feature Python loop."""
    Xb = np.asarray(Xb)
    if Xb.ndim != 2:
        raise ValueError(f"Xb must be [rows, features], got {Xb.shape}")
    n_f = Xb.shape[1]
    flat = (np.arange(n_f, dtype=np.intp)[None, :] * n_bins
            + Xb.astype(np.intp, copy=False)).ravel()
    return np.bincount(flat, minlength=n_f * n_bins).reshape(n_f, n_bins)


def fit_bin_mapper(
    X: np.ndarray,
    n_bins: int = 255,
    max_sample: int = 200_000,
    seed: int = 0,
    missing_policy: str = "zero",
    cat_features: tuple = (),
) -> BinMapper:
    """Fit per-feature quantile bin edges on (a sample of) X.

    Edges are non-decreasing per feature (np.maximum.accumulate). Duplicate
    edge values form runs that searchsorted(side='left') always resolves to
    the first edge of the run, so the corresponding higher bins are simply
    never assigned — constant / low-cardinality features occupy few distinct
    bins, matching histogram-GBDT convention. Backends must not assume
    strictly increasing edges.
    """
    X = np.asarray(X, dtype=np.float32)
    rows, n_features = X.shape
    if rows > max_sample:
        rng = np.random.default_rng(seed)
        idx = rng.choice(rows, size=max_sample, replace=False)
        Xs = X[idx]
    else:
        Xs = X

    missing = missing_policy == "learn"
    if missing and n_bins < 3:
        raise ValueError("missing_policy='learn' needs n_bins >= 3")
    # Under the reserved-NaN-bin policy real values get n_bins-1 bins, so
    # they need n_bins-2 interior edges; the edges array keeps its
    # [n_features, n_bins-1] width (trailing column unused = +inf) so the
    # serialized layout is policy-independent.
    n_val = n_bins - 1 if missing else n_bins
    qs = np.linspace(0.0, 1.0, n_val + 1)[1:-1]   # n_val-1 interior quantiles
    edges = np.full((n_features, n_bins - 1), np.float32(np.inf))
    cat = set(int(f) for f in cat_features)
    for f in range(n_features):
        if f in cat:
            # Categorical column: values ARE bin ids (CategoricalEncoder
            # output) — identity edges so quantile re-binning cannot merge
            # or permute categories. Bin b covers (edges[b-1], edges[b]]
            # under searchsorted(side='left'), so edges [0, 1, ..] map
            # integer v to bin v exactly.
            edges[f, : n_val - 1] = np.arange(n_val - 1, dtype=np.float32)
            continue
        col = Xs[:, f]
        col = col[np.isfinite(col)]
        if col.size == 0:
            edges[f, : n_val - 1] = np.arange(n_val - 1, dtype=np.float32)
            continue
        e = np.quantile(col, qs).astype(np.float32)
        # Force strict monotonicity: collapse duplicates upward by epsilon-free
        # padding — duplicates become a run that searchsorted('left') resolves
        # to the first edge, so dup bins are simply never assigned.
        e = np.maximum.accumulate(e)
        edges[f, : n_val - 1] = e
    return BinMapper(edges=edges, n_bins=n_bins, missing_bin=missing,
                     cat_features=tuple(sorted(cat)))


def quantize(
    X: np.ndarray, n_bins: int = 255, max_sample: int = 200_000,
    seed: int = 0, missing_policy: str = "zero",
) -> tuple[np.ndarray, BinMapper]:
    """fit + transform convenience: returns (binned uint8 matrix, mapper)."""
    mapper = fit_bin_mapper(X, n_bins=n_bins, max_sample=max_sample,
                            seed=seed, missing_policy=missing_policy)
    return mapper.transform(X), mapper


def fit_bin_mapper_streaming(
    chunk_fn,
    n_chunks: int,
    n_bins: int = 255,
    max_sample: int = 200_000,
    seed: int = 0,
    missing_policy: str = "zero",
    cat_features: tuple = (),
) -> BinMapper:
    """Fit bin edges from STREAMED raw-float chunks (the 10B-row config's
    L7 story: no full matrix ever materialises). A priority-based
    reservoir keeps a uniform `max_sample`-row subsample across chunks —
    each row draws a U(0,1) priority from a per-(seed, chunk) generator
    and the globally smallest `max_sample` priorities survive — then the
    edges are fitted exactly like `fit_bin_mapper` on that sample.
    Deterministic given (seed, chunk order); with
    max_sample >= total rows the sample IS the dataset, so the edges
    equal the in-memory fit's (np.quantile is order-invariant).

    `chunk_fn(c) -> (X_chunk float [rows_c, F], y_chunk)` — the same
    signature `streaming.fit_streaming` consumes (y is ignored here)."""
    buf = None          # [k, F] sampled rows
    pri = None          # [k] their priorities
    for c in range(n_chunks):
        Xc = np.asarray(chunk_fn(c)[0], np.float32)
        pc = np.random.default_rng((seed, 15485863, c)).random(len(Xc))
        if buf is None:
            buf, pri = Xc, pc
        else:
            if len(pri) >= max_sample:
                # Saturated: a newcomer survives only by beating the
                # current worst kept priority — pre-filter so the append
                # shrinks as 1/chunks_seen instead of copying the whole
                # reservoir + chunk every time (identical output: the
                # filtered-out rows could never be among the k smallest).
                sel = pc < pri.max()
                Xc, pc = Xc[sel], pc[sel]
                if not len(pc):
                    continue
            buf = np.concatenate([buf, Xc])
            pri = np.concatenate([pri, pc])
        if len(pri) > max_sample:
            keep = np.argpartition(pri, max_sample)[:max_sample]
            buf, pri = buf[keep], pri[keep]
    if buf is None:
        raise ValueError("no chunks")
    return fit_bin_mapper(buf, n_bins=n_bins, max_sample=len(buf),
                          seed=seed, missing_policy=missing_policy,
                          cat_features=cat_features)
