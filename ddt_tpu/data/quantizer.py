"""Quantile binning: float features -> uint8 bin indices (<=255 bins).

Layer L7 of SURVEY.md §1: the reference runs an offline quantizer producing
<=255-bin binned matrices before training ([BASELINE] "features are quantized
into bins (255 bins named explicitly)"). TPU realisation: a NumPy/JAX quantile
sketch on a row sample, then `searchsorted` to produce a uint8 matrix that is
the only large tensor ever shipped to the device.

Bin semantics (shared by every kernel in this repo — oracle, XLA, Pallas, C++):
  bin b covers values v with  edges[b-1] < v <= edges[b]   (edges ascending)
  i.e. bin = searchsorted(edges, v, side='left') clipped to [0, n_bins-1].
A split "(feature f, threshold bin t)" routes rows with bin <= t LEFT.
The raw-value threshold equivalent is edges[t] (go left iff v <= edges[t]).
NaNs are mapped to bin 0 (documented v1 policy; dedicated missing-bin is a
later extension).
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass
class BinMapper:
    """Per-feature bin edges + the binned-matrix transform."""

    edges: np.ndarray       # [n_features, n_bins-1] float32, ascending per row
    n_bins: int

    @property
    def n_features(self) -> int:
        return self.edges.shape[0]

    def transform(self, X: np.ndarray) -> np.ndarray:
        """Bin a float matrix [rows, n_features] -> uint8 [rows, n_features]."""
        X = np.asarray(X, dtype=np.float32)
        if X.ndim != 2 or X.shape[1] != self.n_features:
            raise ValueError(
                f"X must be [rows, {self.n_features}], got {X.shape}"
            )
        out = np.empty(X.shape, dtype=np.uint8)
        for f in range(self.n_features):
            col = X[:, f]
            binned = np.searchsorted(self.edges[f], col, side="left")
            np.clip(binned, 0, self.n_bins - 1, out=binned)
            binned[np.isnan(col)] = 0  # v1 NaN policy (see module doc);
            # +/-inf fall naturally into the top/bottom bin via searchsorted.
            out[:, f] = binned.astype(np.uint8)
        return out

    def threshold_value(self, feature: int, threshold_bin: int) -> float:
        """Raw-value threshold for a (feature, bin) split: go left iff v <= it."""
        t = int(threshold_bin)
        if t >= self.edges.shape[1]:
            return float("inf")  # rightmost bin: everything goes left
        return float(self.edges[feature, t])

    def save(self) -> dict:
        return {"edges": self.edges, "n_bins": np.int64(self.n_bins)}

    @staticmethod
    def load(d: dict) -> "BinMapper":
        return BinMapper(edges=np.asarray(d["edges"], np.float32),
                         n_bins=int(d["n_bins"]))


def fit_bin_mapper(
    X: np.ndarray,
    n_bins: int = 255,
    max_sample: int = 200_000,
    seed: int = 0,
) -> BinMapper:
    """Fit per-feature quantile bin edges on (a sample of) X.

    Edges are non-decreasing per feature (np.maximum.accumulate). Duplicate
    edge values form runs that searchsorted(side='left') always resolves to
    the first edge of the run, so the corresponding higher bins are simply
    never assigned — constant / low-cardinality features occupy few distinct
    bins, matching histogram-GBDT convention. Backends must not assume
    strictly increasing edges.
    """
    X = np.asarray(X, dtype=np.float32)
    rows, n_features = X.shape
    if rows > max_sample:
        rng = np.random.default_rng(seed)
        idx = rng.choice(rows, size=max_sample, replace=False)
        Xs = X[idx]
    else:
        Xs = X

    qs = np.linspace(0.0, 1.0, n_bins + 1)[1:-1]  # n_bins-1 interior quantiles
    edges = np.empty((n_features, n_bins - 1), dtype=np.float32)
    for f in range(n_features):
        col = Xs[:, f]
        col = col[np.isfinite(col)]
        if col.size == 0:
            edges[f] = np.arange(n_bins - 1, dtype=np.float32)
            continue
        e = np.quantile(col, qs).astype(np.float32)
        # Force strict monotonicity: collapse duplicates upward by epsilon-free
        # padding — duplicates become a run that searchsorted('left') resolves
        # to the first edge, so dup bins are simply never assigned.
        e = np.maximum.accumulate(e)
        edges[f] = e
    return BinMapper(edges=edges, n_bins=n_bins)


def quantize(
    X: np.ndarray, n_bins: int = 255, max_sample: int = 200_000, seed: int = 0
) -> tuple[np.ndarray, BinMapper]:
    """fit + transform convenience: returns (binned uint8 matrix, mapper)."""
    mapper = fit_bin_mapper(X, n_bins=n_bins, max_sample=max_sample, seed=seed)
    return mapper.transform(X), mapper
