"""Whole-tree growth as one traced XLA program (the L5 level loop, on device).

SURVEY.md §3's per-level stack — build_histograms -> [psum] -> best_splits ->
apply splits -> partition_rows — realised TPU-first: the depth loop is
UNROLLED inside one jitted function (static shapes per level: level d has
2^d nodes), so growing a tree is a single device dispatch with zero host
round-trips. The reference crosses the host<->device boundary per kernel call;
on TPU that would serialise ~6 dispatches x 100 trees of latency, so we fuse.

Each level is one FUSED ROUND (`ddt:fused_round`): the VMEM-streaming
histogram kernel, the optional sibling-SUBTRACTION assembly
(level_histograms — levels >= 1 build only left children and recover
right children as parent - left, halving kernel work and allreduce
payload; arXiv:1812.08295's pipelined on-chip hist->gain architecture is
the blueprint), the gain epilogue (split.best_splits_impl inlined into
the same program — no nested pjit boundary), and row routing — with no
intermediate state landing in HBM between stages beyond the level's own
[2^d, F, B, 2] histogram.

Distribution (SURVEY.md §1 L2): pass `axis_name` when tracing under
jax.shard_map over a row-sharded mesh — the histogram (and final-leaf
aggregate) get a `jax.lax.psum` over ICI, which is the TPU-native realisation
of the reference's "cross-partition histogram allreduce over the FPGA network
fabric" [BASELINE]. Everything else is replicated math on tiny arrays, so all
shards deterministically grow identical trees.

Row routing keeps a dense per-row heap node-id vector ("partition_rows" as a
jnp.where update — SURVEY.md §2 "Node partitioner": no data movement, static
shapes; rows frozen at early leaves are masked out of histograms by the
node_index = -1 sentinel).

Feature parallelism (SURVEY.md §2 "Parallelism strategies": the optional
`features` mesh axis, the TP-analog for histogram GBDT): pass
`feature_axis_name` when Xb is COLUMN-sharded over a second mesh axis. Each
shard histograms only its own features (splitting the hot loop's F dimension
across chips), local per-node best splits are combined with an `all_gather`
of the (gain, feature, bin) triples — tiny: [n_shards, n_level] — and row
routing recovers the winning feature's values via a masked `psum` over the
feature axis (exactly one shard owns each winning column, all others
contribute zero). Tie-break stays bit-identical to single-device: within a
shard argmax picks the first flattened (feature, bin); across shards the
first shard wins ties, which IS global first-feature order.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ddt_tpu.ops import grad as grad_ops
from ddt_tpu.ops import histogram as H
from ddt_tpu.ops import split as S
from ddt_tpu.parallel import comms
from ddt_tpu.parallel import mesh as mesh_lib
from ddt_tpu.telemetry.annotations import traced_scope

# Perfetto alignment (docs/OBSERVABILITY.md): the traced_scope blocks
# below name the lowered XLA ops `ddt:fused_round` (one whole level's
# hist -> subtract -> gain -> route group) with `ddt:hist` /
# `ddt:allreduce` / `ddt:hist:subtract` / `ddt:gain` / `ddt:route` /
# `ddt:leaf` nested inside, so a profiler capture's device timeline
# carries the same phase names as the host PhaseTimer spans. Zero
# runtime cost — named scopes are HLO metadata, not ops.


def resolve_hist_subtraction(flag: str, platform: str | None = None,
                             integer_hists: bool = False) -> bool:
    """cfg.hist_subtraction ('auto'|'on'|'off') -> bool for this platform.

    'auto' enables the sibling-subtraction trick only on a real TPU chip:
    it changes right-child bin sums by float-rounding ULPs (parent - left
    vs a direct sum), which is invisible to model quality and absorbed by
    the bf16 gain rounding in almost every decision, but would break the
    streamed == in-memory BITWISE contracts the CPU fixed-seed suites
    assert (ops/split.py's determinism-boundary notes). Off-chip runs and
    oracles therefore default off; tests opt in with 'on'.

    `integer_hists=True` (the quantized-gradient path, cfg.grad_dtype):
    parent - left is EXACT in the int32 domain — the f32-ULP caveat that
    forced the platform gate does not exist there — so 'auto' resolves
    ON everywhere: half the kernel work and half the collective payload
    per level >= 1, with the streamed == in-memory contracts intact
    ('off' still forces it off)."""
    if flag == "on":
        return True
    if flag == "off":
        return False
    if flag != "auto":
        raise ValueError(
            f"hist_subtraction must be auto|on|off, got {flag!r}")
    if integer_hists:
        return True
    if platform is None:
        platform = jax.default_backend()
    return platform == "tpu"


def _slab_widths(F: int, slabs: int, row_shards: int) -> list[int]:
    """Feature-slab widths for the slab-pipelined build+reduce loop.

    Slab boundaries align to the row-shard count (each non-final slab's
    width is a multiple of `row_shards`) so that under reduce_scatter
    every padded local column id lands >= F — the one-line validity test
    the gain mask relies on (`col < F`). Returns [F] when pipelining is
    off or the shape is too narrow to split."""
    if slabs <= 1:
        return [F]
    fc = -(-F // (slabs * row_shards)) * row_shards
    if fc <= 0 or fc >= F:
        return [F]
    return [min(fc, F - i) for i in range(0, F, fc)]


def level_histograms(
    Xb: jax.Array,
    g: jax.Array,
    h: jax.Array,
    node_index: jax.Array,      # int32 [R] level-local, -1 = frozen
    n_level: int,
    n_bins: int,
    *,
    hist_impl: str = "auto",
    row_chunk: int = 32_768,
    input_dtype=jnp.bfloat16,
    allreduce=lambda x: x,
    comms_slabs: int = 1,
    row_shards: int = 1,
    parent_hist: jax.Array | None = None,   # [n_level//2, F(_loc), B, 2],
    #   the PREVIOUS level's post-collective histograms (the local slab
    #   under reduce_scatter — the carry and the reduce share a layout)
    parent_split: jax.Array | None = None,  # bool [n_level//2]: which
    #   parents actually split (children of leaves must read zero mass)
) -> jax.Array:
    """One level's [n_level, F, B, 2] histograms (post-collective; the
    merged F/row_shards slab under reduce_scatter), with the classic GBDT
    sibling-SUBTRACTION trick when parent state is given: only LEFT
    children are built from rows (half the kernel work AND half the
    collective payload), and each right child is recovered as
    parent - left. Children of non-split parents are gated to exactly
    zero — without the gate a frozen parent's phantom right child would
    inherit the full parent mass and could "win" a split no training row
    can reach (a predict-time divergence, since predict-time rows CAN
    reach it).

    `allreduce` is the histogram collective (comms.hist_reduce bound by
    the caller: psum or reduce-scatter, optionally compressed). With
    `comms_slabs` > 1 the build+collective is SLAB-PIPELINED: the
    feature axis splits into row-shard-aligned slabs (_slab_widths), and
    slab k+1's histogram kernels are dispatched before slab k's
    collective completes — inside one traced program, XLA's async
    collectives then hide the wire latency behind VPU work. f32/bf16
    collectives are elementwise reductions, so the phasing is
    bit-identical to the monolithic form by construction; int32_fixed
    derives its fixed-point scale per collective, so each slab
    quantizes on its own (tighter) grid — deterministic and inside the
    same error bound, but not bitwise vs slabs=1.

    Exactness: left-child sums are BITWISE identical to a direct full
    build (a node's rows accumulate in the same tile order; absent rows
    contribute exact +0.0 terms either way). Right-child sums differ
    from a direct build by f32 rounding ULPs — the documented seam
    behind cfg.hist_subtraction's platform gating."""
    F = Xb.shape[1]
    widths = _slab_widths(F, comms_slabs, row_shards)

    def build_reduced(ni, n_nodes):
        """Per-slab histogram build, each slab's collective issued as
        soon as its build is traced (the overlap phasing)."""
        outs = []
        lo = 0
        for w in widths:
            with traced_scope("hist"):
                hs = H.build_histograms(
                    Xb[:, lo:lo + w] if len(widths) > 1 else Xb,
                    g, h, ni, n_nodes, n_bins,
                    impl=hist_impl, row_chunk=row_chunk,
                    input_dtype=input_dtype,
                )
            with traced_scope("allreduce"):
                outs.append(allreduce(hs))
            lo += w
        if len(outs) == 1:
            return outs[0]
        return jnp.concatenate(outs, axis=1)

    if parent_hist is None or n_level < 2:
        return build_reduced(node_index, n_level)
    half = n_level // 2
    # Rows sitting in LEFT children (even level-local index) keyed by
    # parent slot; everyone else (right children, frozen) masks out.
    # HALF a full level's collective payload.
    is_left = (node_index >= 0) & (node_index % 2 == 0)
    li = jnp.where(is_left, node_index // 2, -1).astype(jnp.int32)
    hist_left = build_reduced(li, half)
    with traced_scope("hist:subtract"):
        gate = parent_split.reshape(half, 1, 1, 1)
        # Dtype-generic zero: on the quantized path the carry and the
        # left build are int32 and the subtraction is EXACT (integer
        # adds commute) — the f32-ULP right-child caveat vanishes.
        hist_right = jnp.where(gate, parent_hist - hist_left,
                               jnp.zeros((), hist_left.dtype))
        # Interleave [half, {left,right}, F, B, 2] -> level order
        # (left child = 2p, right child = 2p + 1).
        hist = jnp.stack([hist_left, hist_right], axis=1)
        return hist.reshape((n_level,) + hist_left.shape[1:])


class TreeArrays(NamedTuple):
    """One grown tree in SoA heap layout + per-row leaf assignment."""

    feature: jax.Array        # int32 [n_nodes_total], -1 on leaves
    threshold_bin: jax.Array  # int32 [n_nodes_total]
    is_leaf: jax.Array        # bool  [n_nodes_total]
    leaf_value: jax.Array     # float32 [n_nodes_total]
    split_gain: jax.Array     # float32 [n_nodes_total], 0 on leaves
    default_left: jax.Array   # bool  [n_nodes_total] NaN-row direction
    leaf_of_row: jax.Array    # int32 [R] heap slot where each row landed


def grow_tree(
    Xb: jax.Array,            # uint8 [R, F] (the local shard when distributed)
    g: jax.Array,             # float32 [R]
    h: jax.Array,             # float32 [R]
    *,
    max_depth: int,
    n_bins: int,
    reg_lambda: float,
    min_child_weight: float,
    min_split_gain: float,
    hist_impl: str = "auto",
    row_chunk: int = 32_768,
    input_dtype=jnp.bfloat16,
    axis_name: "str | tuple[str, ...] | None" = None,   # row-shard axes;
    #   a ("hosts", "rows") tuple for pod meshes — psum reduces over all of
    #   them (XLA phases ICI before DCN for a (hosts, rows, ...) mesh).
    feature_axis_name: str | None = None,
    feature_mask: jax.Array | None = None,   # bool [F global]; colsample
    missing_bin: bool = False,   # cfg.missing_policy="learn": bin n_bins-1
    #   holds NaN rows; splits learn a default direction for them.
    cat_features: tuple = (),    # GLOBAL feature indices with one-vs-rest
    #   ("bin == k goes left") categorical splits (cfg.cat_features).
    hist_subtraction: bool = False,  # sibling-subtraction trick: levels
    #   >= 1 build only LEFT-child histograms and derive right children as
    #   parent - left (see level_histograms / resolve_hist_subtraction —
    #   backends resolve cfg.hist_subtraction before tracing).
    split_comms: str = "allreduce",  # RESOLVED collective for split
    #   finding ("allreduce" | "reduce_scatter" — backends resolve
    #   cfg.split_comms via comms.resolve_split_comms): reduce_scatter
    #   hands each row shard one merged F/P feature slab, split finding
    #   runs on the slab, and the tiny per-shard winner tuples are
    #   combined by GLOBAL flattened candidate index
    #   (comms.combine_shard_winners) — same trees, O(F·B/P) payload.
    #   COMPOSES with feature_axis_name (the 2D rows x features mesh):
    #   the scatter runs over the row axes WITHIN this shard's F/Pf
    #   column slab (per-device slab F/(Pr·Pf)) and ONE winner combine
    #   gathers over both axes — trees stay structure-identical to
    #   single-device at any (Pr, Pf).
    hist_comms_dtype: str = "f32",   # wire dtype of the histogram
    #   collective (comms.hist_reduce): f32 | bf16 | int32_fixed.
    comms_slabs: int = 1,            # RESOLVED slab-pipelining factor
    #   (comms.resolve_comms_slabs): the level's build+collective splits
    #   into this many feature slabs so slab k+1's kernels overlap slab
    #   k's wire time. 1 = monolithic; f32/bf16 phasing is bit-identical
    #   either way (int32_fixed: see level_histograms).
    grad_dtype: str = "f32",         # cfg.grad_dtype: "int8"/"int16"
    #   quantizes g/h ONCE per tree onto a shared power-of-two grid
    #   (ops/grad.quantize_gradients — per-output-dim scale from psum'd
    #   |g|,|h| stats, seeded stochastic rounding) and runs the whole
    #   level loop in the integer domain: int32 histograms, exact
    #   sibling subtraction, bit-stable integer merges, ONE dequantize
    #   per level just before the gain epilogue.
    quant_tree_id=None,              # traced int32 ABSOLUTE tree index
    #   (round * n_classes + class) — the stochastic-rounding key's
    #   per-tree component; None = 0 (single-shot callers/benches).
    quant_seed: int = 0,             # cfg.seed (static rounding key part)
) -> TreeArrays:
    """Grow one complete-heap tree. Trace under jit (and shard_map if
    axis_name is set). Matches reference/numpy_trainer.grow_tree decisions.

    With feature_axis_name, Xb is the [R_loc, F_loc] column shard and the
    returned tree's feature indices are GLOBAL (shard offset applied);
    feature_mask is indexed globally and sliced to the local columns."""
    R, F = Xb.shape
    # Routing packs (feat << 12 | bin << 3 | cat << 2 | default_left << 1
    # | split) into int32 — enforce the field bounds at trace time so a
    # future wider-bin or huge-F config fails loudly instead of silently
    # corrupting row routing.
    assert n_bins <= 512, f"routing pack needs n_bins <= 512, got {n_bins}"
    # The packed feats are GLOBAL indices under feature sharding (shard
    # offset applied below), so the bound must cover shards x local width,
    # not just the local F. axis_size is static at trace time.
    F_global = F if feature_axis_name is None else (
        F * mesh_lib.static_axis_size(feature_axis_name))
    assert F_global < 2 ** 19, \
        f"routing pack needs global F < 2^19, got {F_global}"
    N = 2 ** (max_depth + 1) - 1

    feature = jnp.full((N,), -1, jnp.int32)
    threshold_bin = jnp.zeros((N,), jnp.int32)
    is_leaf = jnp.zeros((N,), bool)
    leaf_value = jnp.zeros((N,), jnp.float32)
    split_gain = jnp.zeros((N,), jnp.float32)
    default_left = jnp.zeros((N,), bool)

    node_id = jnp.zeros((R,), jnp.int32)   # heap slot per row
    frozen = jnp.zeros((R,), bool)

    # Split-finding comms (parallel/comms.py; docs/PERF.md "Histogram
    # comms"): `allreduce` is the exact psum for the small aggregates
    # (node totals, leaf sums, routing values); the HISTOGRAM collective
    # is hist_collective — psum or reduce_scatter over the row axes,
    # optionally compressed on the wire.
    rs = split_comms == "reduce_scatter" and axis_name is not None
    P_row = comms.axis_size(axis_name)

    def allreduce(x):
        return comms.psum(x, axis_name)

    def hist_collective(hs):
        if rs:
            hs = comms.pad_to_multiple(hs, 1, P_row)
        return comms.hist_reduce(
            hs, axis_name,
            mode="reduce_scatter" if rs else "allreduce",
            comms_dtype=hist_comms_dtype, scatter_dim=1)

    # Quantized gradients (cfg.grad_dtype; docs/PERF.md "Quantized
    # gradients"): ONE in-trace quantization per tree — per-output-dim
    # scales from psum'd/pmax'd |g|,|h| stats (ops/grad.quant_scale),
    # then seeded stochastic rounding keyed by (seed, tree, GLOBAL row
    # id) so chaos retries, resharding and resumes replay identical
    # bits. Every consumer below (histograms, node totals, leaf sums)
    # accumulates the INTEGER q's and dequantizes exactly once after
    # its merge.
    quant = grad_dtype != "f32"
    gscale = hscale = scale2 = None
    if quant:
        tid = quant_tree_id if quant_tree_id is not None else jnp.int32(0)
        g, h, gscale, hscale = grad_ops.quantize_gradients(
            g, h, grad_dtype=grad_dtype, tree_id=tid, seed=quant_seed,
            local_offset=comms.flat_axis_index(axis_name) * R,
            allreduce=allreduce,
            allmax=lambda x: comms.pmax(x, axis_name),
            n_rows_global=R * comms.axis_size(axis_name))
        scale2 = jnp.stack([gscale, hscale])      # [..., 2] dequant vector

    # Local->global column map of this shard's reduce-scattered slab:
    # slab s of width w contributes wp/P_row contiguous columns per
    # shard (wp = w padded to the shard count); slab boundaries align to
    # P_row (_slab_widths), so every padded local column id lands >= F
    # and `col < F` is the validity test. None when not scattering.
    col_ids = None
    if rs:
        idx = comms.flat_axis_index(axis_name)
        parts, lo = [], 0
        for w in _slab_widths(F, comms_slabs, P_row):
            b = (-(-w // P_row) * P_row) // P_row
            parts.append(lo + idx * b + jnp.arange(b, dtype=jnp.int32))
            lo += w
        col_ids = (jnp.concatenate(parts) if len(parts) > 1
                   else parts[0]).astype(jnp.int32)

    cat_vec_g = S.cat_feature_vec(cat_features, F_global)  # bool [F_global]
    cat_vec = cat_vec_g                    # this shard's columns

    if feature_axis_name is not None:
        f_shard = jax.lax.axis_index(feature_axis_name)
        f_lo = f_shard * F                 # global index of local column 0
        if feature_mask is not None:
            feature_mask = jax.lax.dynamic_slice_in_dim(
                feature_mask, f_lo, F)     # this shard's columns
        if cat_vec_g is not None:
            cat_vec = jax.lax.dynamic_slice_in_dim(cat_vec_g, f_lo, F)

    # Sibling-subtraction carry: the previous level's post-allreduce
    # histograms + its split decisions (level_histograms gates phantom
    # children of frozen parents on these). None keeps every level a
    # direct build — the bit-exact baseline path.
    prev_hist = None
    prev_split = None

    for depth in range(max_depth):         # unrolled: static 2^d nodes/level
        offset = (1 << depth) - 1
        n_level = 1 << depth
        node_index = jnp.where(frozen, -1, node_id - offset).astype(jnp.int32)
        # One FUSED level round: hist -> [psum] -> (subtract) -> gain ->
        # route, a single traced group with no host boundary and no HBM
        # round-trip of intermediate state between stages (the gain
        # epilogue consumes best_splits_impl directly — no nested pjit).
        with traced_scope("fused_round"):
            hist = level_histograms(
                Xb, g, h, node_index, n_level, n_bins,
                hist_impl=hist_impl, row_chunk=row_chunk,
                input_dtype=input_dtype, allreduce=hist_collective,
                comms_slabs=comms_slabs, row_shards=P_row,
                parent_hist=prev_hist, parent_split=prev_split,
            )
            if feature_axis_name is None and not rs:
                G, Hh = S.node_totals(hist)
                if quant:
                    # Integer bin sums, dequantized ONCE — exact.
                    G = G.astype(jnp.float32) * gscale
                    Hh = Hh.astype(jnp.float32) * hscale
            else:
                # Node totals from the row vectors, not the histogram:
                # local histograms hold different COLUMNS per shard, so
                # their bin sums agree only up to float add order — this
                # form is bit-identical (and provably feature-axis-
                # invariant) on every shard. On the quantized path the
                # segment sums run int32 (exact under ANY order, so the
                # histogram form would agree too — this one stays for
                # symmetry with the f32 path).
                act = node_index >= 0
                seg = jnp.clip(node_index, 0, n_level - 1)
                if quant:
                    zq = jnp.zeros((), g.dtype)
                    G = allreduce(jax.ops.segment_sum(
                        jnp.where(act, g, zq).astype(jnp.int32), seg,
                        num_segments=n_level)).astype(jnp.float32) * gscale
                    Hh = allreduce(jax.ops.segment_sum(
                        jnp.where(act, h, zq).astype(jnp.int32), seg,
                        num_segments=n_level)).astype(jnp.float32) * hscale
                else:
                    G = allreduce(jax.ops.segment_sum(
                        jnp.where(act, g, 0.0), seg, num_segments=n_level))
                    Hh = allreduce(jax.ops.segment_sum(
                        jnp.where(act, h, 0.0), seg, num_segments=n_level))
            with traced_scope("gain"):
                # The ONE dequantize per level (quantized path): the
                # int32 histogram — post-collective, post-subtraction —
                # becomes f32 only here, feeding the gain epilogue; the
                # sibling-subtraction carry below keeps the INTEGER
                # form so next level's parent - left stays exact.
                hist_q = hist
                if quant:
                    hist = hist.astype(jnp.float32) * scale2
                if rs:
                    # Slab-local split finding: masks gather down to this
                    # shard's columns (padded ids >= F are invalid), the
                    # slab argmax runs locally, winners map back to
                    # GLOBAL feature ids via col_ids (+ the feature-shard
                    # offset on a 2D mesh), and the tiny per-shard tuples
                    # combine by global flattened candidate index —
                    # exactly the single-device argmax's pick
                    # (comms.combine_shard_winners). With a feature axis
                    # the combine gathers over BOTH axes in one pass:
                    # every (row, feature) shard owns a disjoint global
                    # column set, so the layout-independent tie-break key
                    # needs no per-axis staging.
                    valid_loc = col_ids < F
                    cid = jnp.minimum(col_ids, F - 1)
                    fm_loc = valid_loc if feature_mask is None else (
                        jnp.take(feature_mask, cid) & valid_loc)
                    cm_loc = None if cat_vec is None else (
                        jnp.take(cat_vec, cid) & valid_loc)
                    gains, feats, bins, dls = S.best_splits_impl(
                        hist, reg_lambda, min_child_weight, fm_loc,
                        missing_bin=missing_bin, cat_mask=cm_loc)
                    feats = jnp.take(col_ids, feats)
                    if feature_axis_name is None:
                        combine_axes, nf = axis_name, F
                    else:
                        feats = feats + f_lo
                        row_t = (axis_name if isinstance(axis_name, tuple)
                                 else (axis_name,))
                        combine_axes = row_t + (feature_axis_name,)
                        nf = F_global
                    gains, feats, bins, dls = comms.combine_shard_winners(
                        gains, feats, bins, dls, combine_axes,
                        n_features=nf, n_bins=n_bins,
                        missing_bin=missing_bin)
                else:
                    gains, feats, bins, dls = S.best_splits_impl(
                        hist, reg_lambda, min_child_weight, feature_mask,
                        missing_bin=missing_bin, cat_mask=cat_vec)
                    if feature_axis_name is not None:
                        # Combine per-shard winners: all_gather the
                        # (gain, feat, bin, direction) tuples (tiny) and
                        # pick by global flattened candidate index — the
                        # global first-(direction, feature, bin)
                        # tie-break rule (comms.combine_shard_winners).
                        feats = feats + f_lo
                        gains, feats, bins, dls = \
                            comms.combine_shard_winners(
                                gains, feats, bins, dls, feature_axis_name,
                                n_features=F_global, n_bins=n_bins,
                                missing_bin=missing_bin)
            # Guarded like the final level and the streamed twin: an EMPTY
            # node at reg_lambda=0 would otherwise store -0/0 = NaN as its
            # leaf value, which a predict-time row (different data) can
            # reach.
            value = jnp.where(Hh > 0, -G / (Hh + reg_lambda), 0.0)

            do_split = (
                (gains > min_split_gain) & jnp.isfinite(gains) & (Hh > 0)
            )
            sl = slice(offset, offset + n_level)
            feature = feature.at[sl].set(jnp.where(do_split, feats, -1))
            threshold_bin = threshold_bin.at[sl].set(
                jnp.where(do_split, bins, 0))
            is_leaf = is_leaf.at[sl].set(~do_split)
            leaf_value = leaf_value.at[sl].set(
                jnp.where(do_split, 0.0, value))
            split_gain = split_gain.at[sl].set(
                jnp.where(do_split, gains.astype(jnp.float32), 0.0))
            default_left = default_left.at[sl].set(do_split & dls)

            # Route rows through the new splits (dense node-id update).
            # All per-row lookups are one-hot compare+reduce instead of
            # gathers: TPU gathers (even from a 32-entry table) each cost
            # ~10-20 ms at 1M rows, while the [R, n_level] masked
            # reductions are a few ms total — and integer one-hot sums
            # are EXACT, so routing is bit-identical to the gather
            # formulation. The five per-node tables (feature, bin,
            # cat-ness, direction, do_split) are packed into ONE int32 so
            # a single masked reduction covers them:
            # feat<<12 | bin<<3 | cat<<2 | default_left<<1 | split.
            with traced_scope("route"):
                idx_c = jnp.clip(node_id - offset, 0, n_level - 1)
                noh = (idx_c[:, None]
                       == jnp.arange(n_level, dtype=jnp.int32)[None, :])
                if cat_vec_g is not None:
                    # Per-NODE cat-ness of the winning (global) feature.
                    # An n_level-sized gather from the replicated
                    # [F_global] table is fine — the gathers this file
                    # avoids are [R]-sized ones.
                    cat_n = jnp.take(cat_vec_g, feats, axis=0)
                else:
                    cat_n = jnp.zeros(n_level, bool)
                table = ((feats << 12) | (bins << 3)
                         | (cat_n.astype(jnp.int32) << 2)
                         | (dls.astype(jnp.int32) << 1)
                         | do_split.astype(jnp.int32))
                packed_r = jnp.sum(jnp.where(noh, table[None, :], 0),
                                   axis=1)
                split_here = (packed_r & 1).astype(bool) & ~frozen
                dl_r = ((packed_r >> 1) & 1).astype(bool)
                cat_r = ((packed_r >> 2) & 1).astype(bool)
                feat_r = packed_r >> 12
                bin_r = (packed_r >> 3) & 0x1FF
                if feature_axis_name is None:
                    foh = (
                        jax.lax.broadcasted_iota(jnp.int32, (1, F), 1)
                        == feat_r[:, None]
                    )
                    fv = jnp.sum(jnp.where(foh, Xb.astype(jnp.int32), 0),
                                 axis=1)
                else:
                    # Winning columns live on exactly one feature shard:
                    # lanes only match on the owner (out-of-range local
                    # index matches nothing), everyone else contributes
                    # zero; psum broadcasts.
                    loc = feat_r - f_lo
                    foh = (
                        jax.lax.broadcasted_iota(jnp.int32, (1, F), 1)
                        == loc[:, None]
                    )
                    fv = comms.psum(
                        jnp.sum(jnp.where(foh, Xb.astype(jnp.int32), 0),
                                axis=1),
                        feature_axis_name,
                    )
                go_right = fv > bin_r
                if cat_features:
                    # Categorical one-vs-rest: the matched category goes
                    # LEFT.
                    go_right = jnp.where(cat_r, fv != bin_r, go_right)
                if missing_bin:
                    # NaN rows occupy the reserved top bin and follow the
                    # node's learned default direction.
                    go_right = jnp.where(fv == n_bins - 1, ~dl_r, go_right)
                go_right = go_right.astype(jnp.int32)
                node_id = jnp.where(split_here,
                                    2 * node_id + 1 + go_right, node_id)
                frozen = frozen | ~split_here

        # Carry for the next level's sibling subtraction (the integer
        # form on the quantized path — subtraction must stay exact).
        if hist_subtraction:
            prev_hist = hist_q if quant else hist
            prev_split = do_split

    # Final level: leaf values from per-terminal-node (G, H) aggregates
    # via the shared one-hot contraction (grad_ops.leaf_gh_sums — the
    # one home; rationale and numerics notes live on it). On the
    # quantized path the contraction is an exact int32 sum, the psum an
    # exact integer merge, and the dequantize happens once after it —
    # leaf (G, H) are bitwise shard- and order-invariant where the f32
    # form differed from the CPU twin by ULPs.
    with traced_scope("leaf"):
        offset = (1 << max_depth) - 1
        n_last = 1 << max_depth
        active = ~frozen
        idx = jnp.clip(node_id - offset, 0, n_last - 1)
        GH = grad_ops.leaf_gh_sums(idx, active, g, h, n_last)
        if quant:
            Gl = allreduce(GH[:, 0]).astype(jnp.float32) * gscale
            Hl = allreduce(GH[:, 1]).astype(jnp.float32) * hscale
        else:
            Gl = allreduce(GH[:, 0])
            Hl = allreduce(GH[:, 1])
        vals = jnp.where(Hl > 0, -Gl / (Hl + reg_lambda), 0.0)
        sl = slice(offset, offset + n_last)
        is_leaf = is_leaf.at[sl].set(True)
        leaf_value = leaf_value.at[sl].set(vals.astype(jnp.float32))

    return TreeArrays(feature, threshold_bin, is_leaf, leaf_value,
                      split_gain, default_left, node_id)


def tree_predict_delta(tree: TreeArrays, learning_rate: float) -> jax.Array:
    """Per-row raw-score increment from a freshly grown tree: lr * leaf value
    at the slot each row landed in (leaf_of_row). Keeps residuals fresh
    without re-traversing (SURVEY.md §3 hot loop #2 avoided during training).
    """
    return learning_rate * tree.leaf_value[tree.leaf_of_row]
