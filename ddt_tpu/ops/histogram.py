"""HistogramBuilder: per-(node, feature, bin) gradient/hessian histograms.

THE hot kernel (SURVEY.md §2/§3 "HOT LOOP #1", the benchmark metric:
M-rows/sec/chip). Contract (identical to the NumPy oracle
reference/numpy_trainer.build_histograms): given binned uint8 features
Xb [R, F], gradients g/h [R] float32 and a per-row level-local node index
(int32, -1 for rows frozen at an earlier leaf), return float32
[n_nodes, F, n_bins, 2] with (g, h) sums per (node, feature, bin).

TPU realisation — XLA hates random-access scatter, so three interchangeable
implementations (SURVEY.md §7 "hard parts (a)"):

- "pallas": VMEM-accumulating tiled kernel (ops/hist_pallas.py): raw
  g/h/node-index rows stream in tiles, the weighted node one-hot AND the
  bin one-hot are synthesised on-chip, per-(feature-slab, node) bin
  accumulators live in VMEM scratch across the row-tile grid, and each
  slab performs exactly ONE HBM write — nothing but the uint8 Xb, 12
  bytes/row of g/h/ni, and the output ever touches HBM. The TPU default
  for shapes whose working set fits VMEM (hist_pallas.pallas_fits).
- "matmul": one-hot outer-product accumulation on the MXU. Per feature f the
  histogram is A^T @ Bf where A [R, 2N] stacks node-one-hot weighted by g and
  by h, and Bf [R, B] is the bin one-hot. Chunked over rows with lax.scan so
  the one-hot never materialises more than `row_chunk` rows at once — but XLA
  still round-trips it through HBM, which bounds throughput (~29 GB/build at
  the Higgs-1M shape). The TPU fallback for shapes too large for the Pallas
  kernel's VMEM accumulator, and the non-TPU accelerator default.
- "segment": `jax.ops.segment_sum` over combined (node*B + bin) keys, vmapped
  over features. Lowers to scatter-add; the fast path on CPU, slow on TPU.

All return bit-identical shapes and (up to float addition order) the same
values; parity vs the NumPy oracle is tests/test_ops.py.

QUANTIZED INTEGER PATH (cfg.grad_dtype, docs/PERF.md "Quantized
gradients"): int8/int16 g/h (ops/grad.quantize_gradients) dispatch the
same three implementations in the INTEGER domain — int32 accumulators,
s8/s16 operands on the MXU path — and return the RAW int32 histogram.
Integer adds commute, so all three impls are bitwise IDENTICAL to each
other (not merely up to addition order) and to any chunked/sharded
merge of themselves; the caller dequantizes exactly once (hist * scale)
after its last merge. Overflow is impossible by the quantizer's
sum-cap construction plus its enforced row ceiling
(ops/grad.GRAD_SUM_CAP / GRAD_ROW_LIMIT).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ddt_tpu.telemetry.annotations import op_scope
from ddt_tpu.telemetry.costmodel import costed


def _mask_inactive(
    g: jax.Array, h: jax.Array, node_index: jax.Array
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Zero out frozen rows (node_index < 0) and clamp their index to 0.
    Dtype-preserving on the quantized integer path (int8/int16 g/h stay
    narrow — the whole point of the stream); floats normalize to f32."""
    active = node_index >= 0
    idx = jnp.where(active, node_index, 0).astype(jnp.int32)
    if jnp.issubdtype(g.dtype, jnp.integer):
        zero = jnp.zeros((), g.dtype)
        return jnp.where(active, g, zero), jnp.where(active, h, zero), idx
    gz = jnp.where(active, g, 0.0).astype(jnp.float32)
    hz = jnp.where(active, h, 0.0).astype(jnp.float32)
    return gz, hz, idx


# --------------------------------------------------------------------------- #
# segment_sum implementation (scatter path; CPU fast path / TPU fallback)
# --------------------------------------------------------------------------- #

@costed("hist", phase="hist")
@functools.partial(jax.jit, static_argnames=("n_nodes", "n_bins"))
@op_scope("hist")
def build_histograms_segment(
    Xb: jax.Array,          # uint8 [R, F]
    g: jax.Array,           # float32 [R]
    h: jax.Array,           # float32 [R]
    node_index: jax.Array,  # int32 [R], -1 = frozen
    n_nodes: int,
    n_bins: int,
) -> jax.Array:
    gz, hz, idx = _mask_inactive(g, h, node_index)
    if jnp.issubdtype(gz.dtype, jnp.integer):
        # Quantized path: widen to the int32 accumulator FIRST (a
        # segment_sum in int8/int16 would wrap) — the scatter-adds are
        # then exact and order-independent; output is the RAW int32
        # histogram the caller dequantizes after its last merge.
        gz = gz.astype(jnp.int32)
        hz = hz.astype(jnp.int32)
    keys = idx[:, None] * n_bins + Xb.astype(jnp.int32)       # [R, F]
    num = n_nodes * n_bins

    def per_feature(k):
        gs = jax.ops.segment_sum(gz, k, num_segments=num)
        hs = jax.ops.segment_sum(hz, k, num_segments=num)
        return jnp.stack([gs, hs], axis=-1)                   # [N*B, 2]

    out = jax.vmap(per_feature, in_axes=1)(keys)              # [F, N*B, 2]
    F = Xb.shape[1]
    return out.reshape(F, n_nodes, n_bins, 2).transpose(1, 0, 2, 3)


# --------------------------------------------------------------------------- #
# one-hot matmul implementation (MXU path; TPU default)
# --------------------------------------------------------------------------- #

def _hist_chunk_matmul(
    Xb_c: jax.Array,    # [r, F] uint8
    gz: jax.Array,      # [r] float32 (already masked)
    hz: jax.Array,
    idx: jax.Array,     # [r] int32 in [0, n_nodes)
    n_nodes: int,
    n_bins: int,
    input_dtype: jnp.dtype,
) -> jax.Array:
    """One row-chunk's histogram via outer-product matmuls: [F, 2N, B]
    f32 — int32 on the quantized integer path (exact adds; the caller
    dequantizes after its last merge)."""
    if jnp.issubdtype(gz.dtype, jnp.integer):
        # Quantized path: A and the bin one-hot in the gradient dtype
        # (|q| <= qmax fits), dot with an int32 accumulator — exact and
        # order-independent where the f32 form was ULP-tolerant. The
        # input_dtype/bf16-emulation knobs are float-path concerns.
        qdt = gz.dtype
        noh = (idx[:, None]
               == jnp.arange(n_nodes, dtype=jnp.int32)[None, :])
        zero = jnp.zeros((), qdt)
        A = jnp.concatenate(
            [jnp.where(noh, gz[:, None], zero),
             jnp.where(noh, hz[:, None], zero)], axis=1)      # [r, 2N]

        def per_feature_q(xcol):                              # [r] uint8
            bins_oh = (
                xcol[:, None]
                == jnp.arange(n_bins, dtype=jnp.uint8)[None, :]
            ).astype(qdt)                                     # [r, B]
            return jax.lax.dot_general(
                A, bins_oh,
                (((0,), (0,)), ((), ())),                     # contract rows
                preferred_element_type=jnp.int32,
            )                                                 # [2N, B] i32

        return jax.vmap(per_feature_q, in_axes=1)(Xb_c)       # [F, 2N, B]
    node_oh = jax.nn.one_hot(idx, n_nodes, dtype=jnp.float32)     # [r, N]
    # A stacks g-weighted and h-weighted node one-hots: [r, 2N].
    A = jnp.concatenate(
        [node_oh * gz[:, None], node_oh * hz[:, None]], axis=1
    ).astype(input_dtype)
    # CPU XLA has no BF16 x BF16 = F32 dot thunk; emulate EXACTLY by
    # rounding the inputs to bf16 and contracting in f32 — bf16 values are
    # exact in f32 and their products fit f32, and the MXU accumulates in
    # f32 anyway, so this reproduces the TPU path's numerics (used by the
    # bf16-vs-f32 training-quality tests, tests/test_numerics.py).
    emulate_bf16 = (
        input_dtype == jnp.bfloat16 and jax.default_backend() == "cpu"
    )
    if emulate_bf16:
        A = A.astype(jnp.float32)
    # TPU default matmul precision is bf16 passes even for f32 operands;
    # when the caller asked for f32 inputs they want exact accumulation.
    prec = (
        jax.lax.Precision.HIGHEST
        if input_dtype == jnp.float32 or emulate_bf16
        else jax.lax.Precision.DEFAULT
    )

    def per_feature(xcol):                                        # [r] uint8
        bins_oh = (
            xcol[:, None] == jnp.arange(n_bins, dtype=jnp.uint8)[None, :]
        ).astype(input_dtype)                                     # [r, B]
        if emulate_bf16:
            bins_oh = bins_oh.astype(jnp.float32)
        return jax.lax.dot_general(
            A, bins_oh,
            (((0,), (0,)), ((), ())),                             # contract rows
            preferred_element_type=jnp.float32,
            precision=prec,
        )                                                         # [2N, B]

    return jax.vmap(per_feature, in_axes=1)(Xb_c)                 # [F, 2N, B]


@costed("hist", phase="hist")
@functools.partial(
    jax.jit,
    static_argnames=("n_nodes", "n_bins", "row_chunk", "input_dtype"),
)
@op_scope("hist")
def build_histograms_matmul(
    Xb: jax.Array,          # uint8 [R, F]
    g: jax.Array,
    h: jax.Array,
    node_index: jax.Array,
    n_nodes: int,
    n_bins: int,
    row_chunk: int = 32_768,
    input_dtype: jnp.dtype = jnp.bfloat16,
) -> jax.Array:
    R, F = Xb.shape
    gz, hz, idx = _mask_inactive(g, h, node_index)
    acc_dtype = (jnp.int32 if jnp.issubdtype(gz.dtype, jnp.integer)
                 else jnp.float32)

    if R <= row_chunk:
        out = _hist_chunk_matmul(Xb, gz, hz, idx, n_nodes, n_bins, input_dtype)
    else:
        # Pad R to a chunk multiple; padded rows carry g=h=0 so they add 0.
        n_chunks = -(-R // row_chunk)
        pad = n_chunks * row_chunk - R
        Xb_p = jnp.pad(Xb, ((0, pad), (0, 0)))
        gz_p = jnp.pad(gz, (0, pad))
        hz_p = jnp.pad(hz, (0, pad))
        idx_p = jnp.pad(idx, (0, pad))

        def body(acc, args):
            xc, gc, hc, ic = args
            return acc + _hist_chunk_matmul(
                xc, gc, hc, ic, n_nodes, n_bins, input_dtype
            ), None

        acc0 = jnp.zeros((F, 2 * n_nodes, n_bins), acc_dtype)
        out, _ = jax.lax.scan(
            body,
            acc0,
            (
                Xb_p.reshape(n_chunks, row_chunk, F),
                gz_p.reshape(n_chunks, row_chunk),
                hz_p.reshape(n_chunks, row_chunk),
                idx_p.reshape(n_chunks, row_chunk),
            ),
        )

    # [F, 2N, B] -> [N, F, B, 2]
    out = out.reshape(F, 2, n_nodes, n_bins)
    return out.transpose(2, 0, 3, 1)


# --------------------------------------------------------------------------- #
# dispatch
# --------------------------------------------------------------------------- #

def resolve_hist_impl(
    hist_impl: str,
    platform: str | None = None,
    n_nodes: int | None = None,
    n_features: int | None = None,
    n_bins: int | None = None,
    input_bytes: int = 2,
    grad_bytes: int = 4,
) -> str:
    """'auto' -> the right implementation for the platform (and shape).

    CPU: segment (scatter is fine there). TPU: the Pallas VMEM kernel when
    the shape fits its accumulator budget (hist_pallas.pallas_fits), else the
    chunked matmul. Other accelerators: matmul (the Pallas kernel is
    TPU-only; off-TPU it would silently run interpreted, orders of magnitude
    slower). Shape args omitted -> optimistic TPU answer ("pallas").
    `input_bytes`/`grad_bytes` are the one-hot operand and g/h row
    itemsizes (pallas_fits' budget terms): build_histograms passes the
    ACTUAL gradient dtype's sizes, so quantized int8/int16 shapes chunk
    against their own — smaller — working set instead of the f32
    defaults silently forcing the matmul fallback at deep levels.
    """
    if hist_impl != "auto":
        return hist_impl
    if platform is None:
        platform = jax.default_backend()
    if platform == "cpu":
        return "segment"
    if platform != "tpu":
        return "matmul"
    if n_nodes is not None and n_features is not None and n_bins is not None:
        from ddt_tpu.ops.hist_pallas import feature_chunks_for

        # The kernel feature-chunks itself for deep levels. Since the
        # VMEM-streaming rewrite a slab re-reads only its own uint8
        # columns plus 2 * grad-itemsize + 4 bytes/row of g/h/ni — 12
        # for f32 gradients, 8/6 for quantized int16/int8 (the old form
        # re-streamed the [R, 2N] weighted one-hot per slab, which
        # capped k at 4) — so chunking stays ahead of the matmul
        # fallback until the slab count itself is pathological.
        k = feature_chunks_for(n_nodes, n_features, n_bins,
                               input_bytes=input_bytes,
                               grad_bytes=grad_bytes)
        if k is None or k > 8:
            return "matmul"
    return "pallas"


def build_histograms(
    Xb: jax.Array,
    g: jax.Array,
    h: jax.Array,
    node_index: jax.Array,
    n_nodes: int,
    n_bins: int,
    impl: str = "auto",
    row_chunk: int = 32_768,
    input_dtype: jnp.dtype = jnp.bfloat16,
) -> jax.Array:
    """Dispatching HistogramBuilder; see module docstring for impls."""
    quant = jnp.issubdtype(jnp.dtype(g.dtype), jnp.integer)
    gb = jnp.dtype(g.dtype).itemsize if quant else 4
    impl = resolve_hist_impl(
        impl, n_nodes=n_nodes, n_features=Xb.shape[1], n_bins=n_bins,
        # Quantized one-hot operands are built in the gradient dtype
        # (1/2 B); the f32 path's one-hot rides cfg.matmul_input_dtype
        # (bf16 = 2 B, the historical resolver assumption).
        input_bytes=gb if quant else 2, grad_bytes=gb,
    )
    if impl == "segment":
        return build_histograms_segment(Xb, g, h, node_index, n_nodes, n_bins)
    if impl == "matmul":
        return build_histograms_matmul(
            Xb, g, h, node_index, n_nodes, n_bins,
            row_chunk=row_chunk, input_dtype=input_dtype,
        )
    if impl == "pallas":
        from ddt_tpu.ops.hist_pallas import build_histograms_pallas
        return build_histograms_pallas(
            Xb, g, h, node_index, n_nodes, n_bins, input_dtype=input_dtype
        )
    raise ValueError(f"unknown hist impl {impl!r}")
