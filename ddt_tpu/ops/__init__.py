"""L3 operator/kernel layer (SURVEY.md §1): HistogramBuilder, SplitGain,
Predict, gradients, and the fused whole-tree growth step. Pure JAX/XLA (+
Pallas for the histogram hot loop); every op has a NumPy twin in
ddt_tpu/reference/numpy_trainer.py that serves as its correctness oracle."""

from ddt_tpu.ops.grad import base_score, grad_hess
from ddt_tpu.ops.grow import TreeArrays, grow_tree, tree_predict_delta
from ddt_tpu.ops.histogram import (
    build_histograms,
    build_histograms_matmul,
    build_histograms_segment,
    resolve_hist_impl,
)
from ddt_tpu.ops.predict import (
    predict_proba,
    predict_raw,
    predict_raw_effective,
    resolve_use_pallas,
    traverse,
)
from ddt_tpu.ops.split import best_splits, node_totals

__all__ = [
    "TreeArrays",
    "base_score",
    "best_splits",
    "build_histograms",
    "build_histograms_matmul",
    "build_histograms_segment",
    "grad_hess",
    "grow_tree",
    "node_totals",
    "predict_proba",
    "predict_raw",
    "predict_raw_effective",
    "resolve_hist_impl",
    "resolve_use_pallas",
    "traverse",
    "tree_predict_delta",
]
