"""TreeLUT-style int8 quantized traversal — the low-latency scoring path.

TreeLUT (arXiv:2501.01511) shows that latency-critical GBDT inference
wants the model as small fixed-point lookup tables, not f32 node arrays:
int8 thresholds, low-precision leaf tables, and a traversal shaped like
table lookups. This module is that representation for the serving tier
(docs/SERVING.md): `quantize_compiled` turns a CompiledEnsemble's
pushed-down arrays into `QuantizedTables`, and `predict_effective_lut`
scores binned rows against them with a Pallas kernel (interpret-mode CPU
fallback, the hist_pallas/predict_pallas pattern).

Why it is faster per request than the f32 Pallas path (docs/PERF.md
"Serving latency"):

- the binned rows stream from HBM as RAW uint8 — the f32 kernel streams
  an int32-widened copy, so the row traffic (the only O(rows) HBM term)
  drops 4x;
- thresholds live as int8 (4x smaller than the int32 effective table)
  and leaves as fp16 or int8+scale (2-4x smaller than f32) — at
  single-row micro-batches the tree tables ARE the working set, so the
  resident footprint shrinks by the same factor;
- the descent itself is unchanged in SHAPE (one-hot colval matmul +
  indexed selects, all in VMEM) — the quantization changes what crosses
  HBM, not what the VPU does.

Bitwise rounding contract (tests/test_predict_lut.py pins all three):

1. THRESHOLDS ARE EXACT. Bin ids occupy [0, 255]; `thr_i8 = clip(
   eff_thr, 0, 255) - 128` (round-to-nearest is vacuous — the values
   are integers) loses nothing: a pushed-down leaf's +BIG threshold
   clips to 255, and "fv > 255" is false for every uint8 bin value —
   exactly the always-left routing +BIG encoded. Descent (and therefore
   leaf CHOICE) is bit-identical to the f32 path.
2. LEAVES ROUND ONCE, DOCUMENTED. fp16 mode: leaf tables are
   np.float16(bot_val) (IEEE round-to-nearest-even); int8 mode:
   `q = round(bot_val / scale_t)` with one f32 scale per tree row,
   scale_t = max|bot_val[t]| / 127. Dequantization (f16 -> f32 cast,
   q * scale in f32) is exact, so the ONLY error source is that single
   rounding step.
3. MAX-ABS-ERROR BOUND, COMPUTED NOT HOPED. `QuantizedTables.
   max_abs_err` = learning_rate * sum over trees of the tree's worst
   node rounding error — an exact, per-model bound on |lut - f32| for
   any input (each tree contributes exactly one leaf per row; softmax
   classes see a subset of trees, so the scalar bound is conservative).
   The tests drive random inputs across n_classes x missing x
   categorical and assert the bound holds with only f32-accumulation
   slack on top.

Parity contract: the kernel mirrors the one-hot path's accumulation
term-for-term, so `predict_effective_lut(tables, X)` is BIT-EXACT to
`predict_raw_effective(..., use_pallas=False)` fed the DEQUANTIZED
tables — the interpret-mode reference the tests pin. Dispatch:
cfg.predict_impl="lut" / `cli predict --quantized` / ServeEngine
(quantize=True), auto-guarded by `predict_lut_fits` (the ddtlint
pallas-vmem-guard contract) with the f32 path as fallback.

int4 TIER (ISSUE 12, the microsecond single-row bar of arXiv:2501.01511
/ arXiv:2409.16075): `quantize_compiled(ce, leaf_dtype="int4")` rounds
leaves onto a 4-bit grid (`q = round(bot_val / scale_t)`, scale_t =
max|bot_val[t]| / 7, clipped to [-7, 7]) — the SAME single documented
rounding step as int8, just a coarser grid, so the max_abs_err bound
formula extends unchanged (lr * sum of per-tree worst node error).
`QuantizedTables.pack_int4()` then bit-packs the device layout
two-nibbles-per-byte: leaf planes pair (j, j + n_leaves/2) into one
byte block, and thresholds ride the nibble pack too WHEN every real
threshold fits (value <= 14; nibble 15 is the always-left sentinel,
decoded in-VPU to 256 > any uint8 bin — models trained with <= 15 bins,
the TreeLUT regime). Descent stays EXACT either way: unpackable
thresholds keep the lossless int8 form. `_lut4_kernel` unpacks in-VPU
(shift/mask on int32 lanes) and keeps the whole walk in VMEM — at
single-row micro-batches the tables ARE the working set, and the int4
pack halves the int8 tier's resident bytes again. Dispatch:
cfg.predict_impl="lut4" / `--quantized int4` / ServeEngine
(quantize="int4"), guarded by `predict_lut4_fits` with the int8 LUT
tier, then f32, as the fallback ladder (backends/tpu.py).

int4 exactness contract (tests/test_predict_lut4.py): DESCENT — and
therefore leaf CHOICE — is bit-identical to the f32 path (thresholds
dequantize exactly at either width), and each selected leaf dequantizes
to exactly `leaf_q * scale` in f32 (the kernel performs that very
multiply, pre-select, on the unpacked table). The one remaining float
degree of freedom is f32 SUMMATION ORDER across trees, which XLA's
fusion choices own, not this kernel (the same slack every kernel-parity
contract in this repo carries — tests/test_hist_fused.py pins its
bitwise claims on integer-valued inputs for exactly this reason). The
tests therefore pin BITWISE equality to the one-hot reference on
order-free exact-grid leaf values (power-of-two scale, integer leaf_q)
across the full variant matrix, and hold random-value models to the
computed max_abs_err bound with f32-accumulation slack only.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ddt_tpu.telemetry.annotations import op_scope, traced_scope
from ddt_tpu.telemetry.costmodel import costed

# Same ceiling discipline as predict_pallas: the per-tile colval/comp
# working set + the (now int8/fp16) resident tables + Mosaic's
# double-buffered windows must fit ~16 MB/core with headroom.
_VMEM_BUDGET_BYTES = 12 * 1024 * 1024
_DEFAULT_TILE_R = 256
_MAX_TRACE_SELECTS = 32_768

#: int8 bin recentering offset: uint8 bins [0, 255] -> [-128, 127].
_I8_OFFSET = 128

#: largest REAL threshold a nibble can carry (15 is the always-left
#: sentinel — pack_int4's threshold-packability condition).
_NIB_THR_MAX = 14
#: what the sentinel nibble decodes to in-kernel: 256 > every uint8 bin
#: value, so "fv > 256" is always False — the +BIG always-left contract
#: in 4-bit clothing (exact in bf16: 2^8).
_NIB_BIG = 256


@dataclasses.dataclass(frozen=True)
class QuantizedTables:
    """int8/fp16 LUT scoring tables for one model version (host arrays;
    device backends key their resident copies on `token`, exactly like
    the f32 CompiledEnsemble path)."""

    token: str                  # source CompiledEnsemble.token
    tree_chunk: int
    max_depth: int
    n_classes_out: int
    learning_rate: float
    base_score: float
    loss: str
    missing_bin_value: int      # raw (unrecentred) reserved-NaN bin, -1=off
    leaf_dtype: str             # "float16" | "int8"
    max_abs_err: float          # documented |lut - f32| bound (module doc)
    eff_feat: np.ndarray        # int32 [Tpad, N] pushed-down features
    thr_i8: np.ndarray          # int8  [Tpad, N] recentred thresholds
    leaf_q: np.ndarray          # f16 [Tpad, 2^D] or int8 [Tpad, 2^D]
    leaf_scale: np.ndarray | None   # f32 [Tpad] per-tree scale (int8 mode)
    cls_oh: np.ndarray          # f32 [Tpad, C] round-major class one-hot
    eff_dl: np.ndarray | None   # bool [Tpad, N] or None
    eff_cat: np.ndarray | None  # bool [Tpad, N] or None

    @property
    def n_trees_padded(self) -> int:
        return int(self.eff_feat.shape[0])

    def arrays(self) -> tuple:
        """Device-uploadable operand tuple in predict_effective_lut's
        argument order (optional masks appended when present)."""
        out = [self.eff_feat, self.thr_i8, self.leaf_q]
        if self.leaf_scale is not None:
            out.append(self.leaf_scale)
        out.append(self.cls_oh)
        if self.eff_dl is not None:
            out.append(self.eff_dl)
        if self.eff_cat is not None:
            out.append(self.eff_cat)
        return tuple(out)

    def dequantized(self) -> tuple[np.ndarray, np.ndarray]:
        """(eff_thr int32, bot_val f32) EXACTLY as the kernel sees them —
        the reference arrays the parity tests feed the f32 one-hot path
        (dequantization is exact; module doc, contract 2)."""
        thr = self.thr_i8.astype(np.int32) + _I8_OFFSET
        if self.leaf_scale is not None:
            val = (self.leaf_q.astype(np.float32)
                   * self.leaf_scale[:, None].astype(np.float32))
        else:
            val = self.leaf_q.astype(np.float32)
        return thr, val

    def pack_int4(self) -> "PackedTables":
        """Bit-pack the int4 tier's DEVICE layout two-nibbles-per-byte
        (module doc "int4 TIER"): leaf planes (j, j + half) share a
        byte block; thresholds join the pack when every real threshold
        fits a nibble (value <= 14 — nibble 15 decodes to the 256
        always-left sentinel in-VPU), else they keep the lossless int8
        node-major form. Built ONCE per model version; the serving
        backend uploads `ops` device-resident."""
        if self.leaf_dtype != "int4":
            raise ValueError(
                f"pack_int4 needs leaf_dtype='int4' tables, got "
                f"{self.leaf_dtype!r}; quantize with leaf_dtype='int4'")
        q = self
        tc = q.tree_chunk
        n_tc = q.n_trees_padded // tc
        n_int = (1 << q.max_depth) - 1
        n_leaves = 1 << q.max_depth
        # Thresholds: raw (unrecentred) values in [0, 255]; +BIG clipped
        # to 255 at quantize time. Packable iff every REAL threshold is
        # <= 14 — 255 (the clipped +BIG) maps to the sentinel, and for
        # NUMERIC ">" splits a genuine 255 would be always-left for
        # uint8 bins anyway. Categorical nodes get NO 255 exemption:
        # their comparison is equality, and remapping a category id to
        # the 256 sentinel would flip "bin == 255 goes left" into
        # always-right — cat-active nodes must fit the nibble verbatim.
        thr_raw = q.thr_i8[:, :n_int].astype(np.int32) + _I8_OFFSET
        ok = (thr_raw <= _NIB_THR_MAX) | (thr_raw >= 255)
        if q.eff_cat is not None:
            cat_nodes = (q.eff_cat[:, :n_int].astype(bool)
                         & (q.eff_feat[:, :n_int] >= 0))
            ok &= ~cat_nodes | (thr_raw <= _NIB_THR_MAX)
        thr_packed = bool(np.all(ok))
        if thr_packed:
            nib = np.where(thr_raw >= 255, 15, thr_raw).astype(np.uint8)
            h_n = (n_int + 1) // 2          # n_int = 2^D - 1 is odd
            # Pad the node axis with the always-left sentinel so low/high
            # halves pair up; the kernel's lane slice drops the pad.
            nib = np.pad(nib, ((0, 0), (0, 2 * h_n - n_int)),
                         constant_values=15)
            thr_op = _pack_nibbles(
                _node_major(nib[:, :h_n], n_tc, tc, h_n, np.uint8),
                _node_major(nib[:, h_n:], n_tc, tc, h_n, np.uint8))
        else:
            thr_op = _node_major(q.thr_i8[:, :n_int], n_tc, tc, n_int,
                                 np.int8)
        # Leaves: int4 values in [-7, 7]; plane j pairs with j + h_l
        # (low/high nibble), two's-complement low nibble per value.
        h_l = (n_leaves + 1) // 2
        leaf = np.pad(q.leaf_q.astype(np.int16),
                      ((0, 0), (0, 2 * h_l - n_leaves)))
        leaf_op = _pack_nibbles(
            _node_major(leaf[:, :h_l] & 0xF, n_tc, tc, h_l, np.uint8),
            _node_major(leaf[:, h_l:] & 0xF, n_tc, tc, h_l, np.uint8))
        ops = [
            _node_major(q.eff_feat[:, :n_int], n_tc, tc, n_int, np.int32),
            thr_op,
            leaf_op,
            q.leaf_scale.reshape(n_tc, tc).astype(np.float32),
            np.asarray(q.cls_oh, np.float32),
        ]
        if q.eff_dl is not None:
            ops.append(_node_major(q.eff_dl[:, :n_int], n_tc, tc, n_int,
                                   np.int8))
        if q.eff_cat is not None:
            # Pre-gate on eff_feat >= 0 so pushed-down leaves stay
            # always-left, exactly like the int8/f32 paths.
            cat_eff = (q.eff_cat[:, :n_int].astype(bool)
                       & (q.eff_feat[:, :n_int] >= 0))
            ops.append(_node_major(cat_eff, n_tc, tc, n_int, np.int8))
        return PackedTables(tables=q, thr_packed=thr_packed,
                            ops=tuple(ops))


@dataclasses.dataclass(frozen=True)
class PackedTables:
    """The int4 tier's bit-packed device operand layout for one model
    version (QuantizedTables.pack_int4): node-major arrays in kernel
    argument order, leaf nibbles (and threshold nibbles when
    `thr_packed`) two-per-byte. `tables` keeps the logical int4 tier —
    token, error bound, and the npz round trip all ride on it."""

    tables: QuantizedTables
    thr_packed: bool            # thresholds rode the nibble pack
    ops: tuple                  # node-major operand arrays

    @property
    def token(self) -> str:
        return self.tables.token

    @property
    def max_abs_err(self) -> float:
        return self.tables.max_abs_err

    def arrays(self) -> tuple:
        """Device-uploadable operand tuple in predict_effective_lut4_ops
        argument order."""
        return self.ops

    def static_kwargs(self) -> dict:
        """The kernel's static argument set — one home shared by the
        backend dispatch, the AOT export closure, and the bench."""
        t = self.tables
        return dict(
            max_depth=t.max_depth, learning_rate=t.learning_rate,
            base=t.base_score, n_classes=t.n_classes_out,
            tree_chunk=t.tree_chunk, n_trees_padded=t.n_trees_padded,
            missing_bin_value=t.missing_bin_value,
            use_missing=t.eff_dl is not None,
            use_cat=t.eff_cat is not None,
            thr_packed=self.thr_packed,
        )


def _pack_nibbles(lo: np.ndarray, hi: np.ndarray) -> np.ndarray:
    """Two uint8 nibble arrays -> one byte array (lo | hi << 4)."""
    return ((lo.astype(np.uint8) & 0xF)
            | ((hi.astype(np.uint8) & 0xF) << 4)).astype(np.uint8)


def quantize_compiled(ce, leaf_dtype: str = "float16") -> QuantizedTables:
    """CompiledEnsemble -> QuantizedTables (the rounding contract in the
    module doc; pure NumPy — models/tree.CompiledEnsemble.quantize calls
    this lazily so the models layer stays jax-free). leaf_dtype "int4"
    is the bit-packed tier's logical form: leaf_q holds the 4-bit
    integers [-7, 7] in an int8 array (the npz round trip and
    `dequantized()` stay dtype-generic); `pack_int4()` makes the
    two-nibbles-per-byte device layout."""
    if leaf_dtype not in ("float16", "int8", "int4"):
        raise ValueError(
            f"leaf_dtype must be float16|int8|int4, got {leaf_dtype!r}")
    # Contract 1: integer bin thresholds survive the int8 recentring
    # exactly; +BIG (pushed-down leaves) clips to 255 = always-left.
    thr_i8 = (np.clip(ce.eff_thr, 0, 255) - _I8_OFFSET).astype(np.int8)
    bot = np.asarray(ce.bot_val, np.float32)
    if leaf_dtype == "float16":
        leaf_q = bot.astype(np.float16)
        leaf_scale = None
        deq = leaf_q.astype(np.float32)
    else:
        # Same single documented rounding step at both integer widths —
        # only the grid changes (contract 2; the int4 step is the
        # "extended to the int4 rounding step" of the bound, contract 3).
        qmax = 7.0 if leaf_dtype == "int4" else 127.0
        max_abs = np.abs(bot).max(axis=1)                   # [Tpad]
        leaf_scale = np.where(max_abs > 0, max_abs / qmax,
                              1.0).astype(np.float32)
        leaf_q = np.clip(np.rint(bot / leaf_scale[:, None]),
                         -qmax, qmax).astype(np.int8)
        deq = leaf_q.astype(np.float32) * leaf_scale[:, None]
    # Contract 3: exact per-model bound — each tree contributes one leaf
    # per row, so worst-node error per tree sums across trees.
    per_tree = np.abs(bot - deq).max(axis=1)                # [Tpad]
    max_abs_err = float(ce.learning_rate * per_tree.sum())
    return QuantizedTables(
        token=ce.token, tree_chunk=ce.tree_chunk, max_depth=ce.max_depth,
        n_classes_out=ce.n_classes_out, learning_rate=ce.learning_rate,
        base_score=ce.base_score, loss=ce.loss,
        missing_bin_value=ce.missing_bin_value, leaf_dtype=leaf_dtype,
        max_abs_err=max_abs_err,
        eff_feat=np.asarray(ce.eff_feat, np.int32), thr_i8=thr_i8,
        leaf_q=leaf_q, leaf_scale=leaf_scale,
        cls_oh=np.asarray(ce.cls_oh, np.float32),
        eff_dl=ce.eff_dl, eff_cat=ce.eff_cat,
    )


def predict_lut_fits(
    n_trees_padded: int,
    tree_chunk: int,
    max_depth: int,
    n_features: int,
    n_classes: int,
    tile_r: int | None = None,
) -> bool:
    """Whether the LUT kernel's VMEM working set (and trace size) fits at
    this shape — the guard behind the "lut" dispatch (backends/tpu.py
    falls back to the f32 path when it fails; the ddtlint
    pallas-vmem-guard contract)."""
    if tile_r is None:
        tile_r = _DEFAULT_TILE_R
    if n_trees_padded % tree_chunk != 0:
        return False
    n_int = (1 << max_depth) - 1
    n_leaves = 1 << max_depth
    n_tc = n_trees_padded // tree_chunk
    if n_tc * (n_int + n_leaves) > _MAX_TRACE_SELECTS:
        return False
    lanes = n_int * tree_chunk
    work = tile_r * lanes * 3                 # colval bf16 + comp bytes
    # Resident tables: feat int32 + thr int8 + leaves (2B f16 / 1B int8
    # + 4B scale) + class one-hot — the quantized footprint.
    trees = n_tc * (lanes * 5 + n_leaves * tree_chunk * 2)
    trees += n_trees_padded * (n_classes * 4 + 4)
    x_tile = tile_r * n_features              # raw uint8 rows
    out = tile_r * max(n_classes, 8) * 4
    return work + trees + x_tile + out <= _VMEM_BUDGET_BYTES


def _lut_kernel(x_ref, feat_ref, thr_ref, val_ref, *rest,
                n_tc: int, tc: int, n_int: int, n_leaves: int,
                n_feat: int, max_depth: int, missing_bin_value: int,
                use_missing: bool, use_cat: bool, use_scale: bool):
    """One row tile against the int8/fp16 tables, fully in VMEM.

    x_ref [TILE_R, F] RAW uint8 bins (the 4x HBM saving — no widened
    copy); feat [n_tc, Nint*Tc] int32 node-major; thr [n_tc, Nint*Tc]
    int8 recentred; val [n_tc, W*Tc] f16 or int8; optional scale
    [n_tc, Tc] f32; coh [Tpad, C] f32; optional dl/cat [n_tc, Nint*Tc]
    int8; out [TILE_R, C] f32. Descent logic mirrors predict_pallas.
    _traverse_kernel plane for plane; only the table dtypes differ."""
    rest = list(rest)
    out_ref = rest.pop()
    scale_ref = rest.pop(0) if use_scale else None
    coh_ref = rest.pop(0)
    dl_ref = rest.pop(0) if use_missing else None
    cat_ref = rest.pop(0) if use_cat else None
    tile_r = x_ref.shape[0]
    lanes = n_int * tc
    xb = x_ref[:].astype(jnp.bfloat16)                    # bins: exact
    f_iota = jax.lax.broadcasted_iota(jnp.int32, (n_feat, lanes), 0)
    acc = jnp.zeros((tile_r, out_ref.shape[1]), jnp.float32)
    for c in range(n_tc):
        # Feature one-hot (sublane broadcast vs lane iota — the
        # hist_pallas trick); feat = -1 matches no sublane -> colval 0.
        feat = jnp.broadcast_to(feat_ref[c:c + 1, :], (n_feat, lanes))
        fohT = (feat == f_iota).astype(jnp.bfloat16)      # [F, Nint*Tc]
        colval = jax.lax.dot_general(
            xb, fohT, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.bfloat16,   # bins <= 255: exact
        )                                                 # [T, Nint*Tc]
        # Undo the int8 recentring in VMEM: int8 -> bf16 is exact, and
        # +128 keeps every value an exact bf16 integer <= 255. A clipped
        # +BIG threshold decodes to 255 -> "fv > 255" is always False,
        # the always-left contract (module doc, contract 1).
        thr = jnp.broadcast_to(
            thr_ref[c:c + 1, :], (tile_r, lanes)
        ).astype(jnp.bfloat16) + jnp.bfloat16(_I8_OFFSET)
        comp = colval > thr
        if use_cat:
            cat = jnp.broadcast_to(
                cat_ref[c:c + 1, :], (tile_r, lanes)) != 0
            comp = jnp.where(cat, colval != thr, comp)
        if use_missing:
            # Reserved-NaN-bin rows (raw bin space — x streams
            # unrecentred) follow the learned direction; pushed-down
            # leaves have colval 0, never the reserved bin.
            miss = colval == jnp.bfloat16(missing_bin_value)
            dl = jnp.broadcast_to(
                dl_ref[c:c + 1, :], (tile_r, lanes)) != 0
            comp = jnp.where(miss, ~dl, comp)
        # Indexed descent: k-select the path node's bit per level (every
        # node plane a static lane slice of the node-major comp).
        k = jnp.zeros((tile_r, tc), jnp.int32)
        for d in range(max_depth):
            lo = (1 << d) - 1
            go = jnp.zeros((tile_r, tc), jnp.bool_)
            for i in range(1 << d):
                n = lo + i
                go = jnp.where(k == i, comp[:, n * tc:(n + 1) * tc], go)
            k = 2 * k + go.astype(jnp.int32)
        # Bottom-level leaf select, dequantizing in VMEM: f16 -> f32 cast
        # is exact; int8 * f32 scale is exact in f32 (contract 2).
        vals = jnp.zeros((tile_r, tc), jnp.float32)
        for j in range(n_leaves):
            plane = jnp.broadcast_to(
                val_ref[c:c + 1, j * tc:(j + 1) * tc], (tile_r, tc)
            ).astype(jnp.float32)
            vals = jnp.where(k == j, plane, vals)
        if use_scale:
            vals = vals * jnp.broadcast_to(
                scale_ref[c:c + 1, :], (tile_r, tc)).astype(jnp.float32)
        # Same dot, precision, and per-chunk add order as the one-hot
        # path's scan body — the bit-stable mirror the parity test pins.
        acc = acc + jax.lax.dot_general(
            vals, coh_ref[c * tc:(c + 1) * tc, :],
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
    out_ref[:] = acc


def _node_major(a: np.ndarray, n_tc: int, tree_chunk: int, width: int,
                dtype) -> np.ndarray:
    """[Tpad, width] -> [n_tc, width*Tc], lane block n = node n of every
    tree in the chunk (host-side, once per model version)."""
    return (np.ascontiguousarray(
        np.asarray(a, dtype).reshape(n_tc, tree_chunk, width)
        .transpose(0, 2, 1)).reshape(n_tc, width * tree_chunk))


def lut_device_operands(tables: QuantizedTables) -> tuple:
    """Host-side kernel operand layout for one model version — node-major
    tables in their quantized dtypes, built ONCE (the serving tier and
    the backend cache upload these; per-request work is rows only)."""
    q = tables
    n_tc = q.n_trees_padded // q.tree_chunk
    n_int = (1 << q.max_depth) - 1
    n_leaves = 1 << q.max_depth
    ops = [
        _node_major(q.eff_feat[:, :n_int], n_tc, q.tree_chunk, n_int,
                    np.int32),
        _node_major(q.thr_i8[:, :n_int], n_tc, q.tree_chunk, n_int,
                    np.int8),
        _node_major(q.leaf_q, n_tc, q.tree_chunk, n_leaves,
                    np.float16 if q.leaf_scale is None else np.int8),
    ]
    if q.leaf_scale is not None:
        ops.append(q.leaf_scale.reshape(n_tc, q.tree_chunk)
                   .astype(np.float32))
    ops.append(np.asarray(q.cls_oh, np.float32))
    if q.eff_dl is not None:
        ops.append(_node_major(q.eff_dl[:, :n_int], n_tc, q.tree_chunk,
                               n_int, np.int8))
    if q.eff_cat is not None:
        # Pre-gate on eff_feat >= 0 so pushed-down leaves stay
        # always-left, exactly like the f32 paths.
        cat_eff = (q.eff_cat[:, :n_int].astype(bool)
                   & (q.eff_feat[:, :n_int] >= 0))
        ops.append(_node_major(cat_eff, n_tc, q.tree_chunk, n_int,
                               np.int8))
    return tuple(ops)


def predict_effective_lut_ops(
    ops: tuple,                # lut_device_operands(tables) (host or device)
    Xc: jax.Array,             # [R, F] uint8 bins
    *,
    max_depth: int,
    learning_rate,
    base,
    n_classes: int,
    tree_chunk: int,
    n_trees_padded: int,
    missing_bin_value: int,
    use_missing: bool,
    use_cat: bool,
    use_scale: bool,
    tile_r: int | None = None,
    interpret: bool | None = None,
) -> jax.Array:
    """LUT scoring core on prebuilt node-major operands (jit-safe; the
    backend caches the device copies of `ops` per model token)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if tile_r is None:
        tile_r = _DEFAULT_TILE_R
    if not jnp.issubdtype(Xc.dtype, jnp.integer):
        raise ValueError(
            "the LUT kernel requires binned integer data; raw-threshold "
            "scoring has no quantized form")
    R, F = Xc.shape
    C = n_classes
    if R == 0:
        out = jnp.full((0, C), base, jnp.float32)
        return out[:, 0] if C == 1 else out
    if not interpret and not predict_lut_fits(
            n_trees_padded, tree_chunk, max_depth, F, C, tile_r):
        raise ValueError(
            f"LUT shape (trees_padded={n_trees_padded}, "
            f"tree_chunk={tree_chunk}, depth={max_depth}, F={F}, C={C}) "
            "exceeds the Pallas VMEM/trace budget; use the f32 path")
    n_tc = n_trees_padded // tree_chunk
    n_int = (1 << max_depth) - 1
    n_leaves = 1 << max_depth
    lanes = n_int * tree_chunk

    Xu = Xc.astype(jnp.uint8)        # raw bins stream as 1 B/feature
    n_tiles = -(-R // tile_r)
    rpad = n_tiles * tile_r - R
    if rpad:
        Xu = jnp.pad(Xu, ((0, rpad), (0, 0)))

    kernel = functools.partial(
        _lut_kernel, n_tc=n_tc, tc=tree_chunk, n_int=n_int,
        n_leaves=n_leaves, n_feat=F, max_depth=max_depth,
        missing_bin_value=missing_bin_value, use_missing=use_missing,
        use_cat=use_cat, use_scale=use_scale,
    )
    pinned = pl.BlockSpec((n_tc, lanes), lambda i: (0, 0),
                          memory_space=pltpu.VMEM)
    in_specs = [
        pl.BlockSpec((tile_r, F), lambda i: (i, 0),
                     memory_space=pltpu.VMEM),             # rows (uint8)
        pinned,                                            # feat
        pinned,                                            # thr (int8)
        pl.BlockSpec((n_tc, n_leaves * tree_chunk), lambda i: (0, 0),
                     memory_space=pltpu.VMEM),             # leaf table
    ]
    if use_scale:
        in_specs.append(pl.BlockSpec((n_tc, tree_chunk), lambda i: (0, 0),
                                     memory_space=pltpu.VMEM))
    in_specs.append(pl.BlockSpec((n_trees_padded, C), lambda i: (0, 0),
                                 memory_space=pltpu.VMEM))  # coh
    in_specs += [pinned] * (int(use_missing) + int(use_cat))
    cost = pl.CostEstimate(
        flops=2 * n_tiles * tile_r * (F * n_tc * lanes
                                      + n_trees_padded * C),
        # The honest HBM story: rows cross at 1 B/feature, tables at
        # their quantized widths (vs 4 B/feature + f32 tables on the
        # f32 kernel).
        bytes_accessed=n_tiles * tile_r * (F + C * 4)
        + n_tc * lanes * 5 + n_trees_padded * C * 4,
        transcendentals=0,
    )
    with traced_scope("predict"):
        with traced_scope("predict:traverse"):
            acc = pl.pallas_call(
                kernel,
                grid=(n_tiles,),
                in_specs=in_specs,
                out_specs=pl.BlockSpec((tile_r, C), lambda i: (i, 0),
                                       memory_space=pltpu.VMEM),
                out_shape=jax.ShapeDtypeStruct((n_tiles * tile_r, C),
                                               jnp.float32),
                cost_estimate=cost,
                interpret=interpret,
            )(Xu, *ops)
        with traced_scope("predict:accumulate"):
            out = base + learning_rate * acc[:R]
    return out[:, 0] if C == 1 else out


@costed("predict_lut", phase="predict")
@op_scope("predict")
def predict_effective_lut(
    tables: QuantizedTables,
    Xc,                         # [R, F] uint8 bins (host or device)
    tile_r: int | None = None,
    interpret: bool | None = None,
) -> jax.Array:
    """Standalone host entry (tests/bench/serve fallback): builds the
    node-major operands from the tables and runs the kernel. The backend
    path (TPUDevice._predict_fn with cfg.predict_impl="lut") caches the
    operands device-resident instead — this entry rebuilds them per call
    and exists for correctness work, not the hot loop."""
    ops = lut_device_operands(tables)
    return predict_effective_lut_ops(
        tuple(jnp.asarray(a) for a in ops), jnp.asarray(Xc),
        max_depth=tables.max_depth, learning_rate=tables.learning_rate,
        base=tables.base_score, n_classes=tables.n_classes_out,
        tree_chunk=tables.tree_chunk,
        n_trees_padded=tables.n_trees_padded,
        missing_bin_value=tables.missing_bin_value,
        use_missing=tables.eff_dl is not None,
        use_cat=tables.eff_cat is not None,
        use_scale=tables.leaf_scale is not None,
        tile_r=tile_r, interpret=interpret,
    )


# --------------------------------------------------------------------- #
# int4 bit-packed tier (module doc "int4 TIER")
# --------------------------------------------------------------------- #

def predict_lut4_fits(
    n_trees_padded: int,
    tree_chunk: int,
    max_depth: int,
    n_features: int,
    n_classes: int,
    tile_r: int | None = None,
    thr_packed: bool = False,
) -> bool:
    """Whether the int4 kernel's VMEM working set (and trace size) fits
    at this shape — the guard behind the "lut4" dispatch (backends/
    tpu.py degrades to the int8 LUT tier, then f32, when it fails; the
    ddtlint pallas-vmem-guard contract)."""
    if tile_r is None:
        tile_r = _DEFAULT_TILE_R
    if n_trees_padded % tree_chunk != 0:
        return False
    n_int = (1 << max_depth) - 1
    n_leaves = 1 << max_depth
    n_tc = n_trees_padded // tree_chunk
    if n_tc * (n_int + n_leaves) > _MAX_TRACE_SELECTS:
        return False
    lanes = n_int * tree_chunk
    work = tile_r * lanes * 3                 # colval bf16 + comp bytes
    # Resident tables at the PACKED widths: feat int32 + thr (half a
    # byte/node when nibble-packed, else int8) + leaf nibbles (half a
    # byte per leaf) + f32 scale + class one-hot — half the int8 tier's
    # threshold/leaf bytes again.
    h_l = (n_leaves + 1) // 2
    thr_bytes = ((n_int + 1) // 2 if thr_packed else n_int) * tree_chunk
    trees = n_tc * (lanes * 4 + thr_bytes + h_l * tree_chunk
                    + tree_chunk * 4)
    trees += n_trees_padded * n_classes * 4
    # In-VPU unpack temporaries: the per-chunk int32 nibble planes the
    # shift/mask decode materialises before the descent consumes them.
    unpack = (lanes + h_l * 2 * tree_chunk) * 4
    x_tile = tile_r * n_features              # raw uint8 rows
    out = tile_r * max(n_classes, 8) * 4
    return work + trees + unpack + x_tile + out <= _VMEM_BUDGET_BYTES


def _lut4_kernel(x_ref, feat_ref, thr_ref, val_ref, scale_ref, coh_ref,
                 *rest, n_tc: int, tc: int, n_int: int, n_leaves: int,
                 n_feat: int, max_depth: int, missing_bin_value: int,
                 use_missing: bool, use_cat: bool, thr_packed: bool):
    """One row tile against the bit-packed int4 tables, fully in VMEM.

    x_ref [TILE_R, F] RAW uint8 bins; feat [n_tc, Nint*Tc] int32
    node-major; thr packed uint8 [n_tc, ((Nint+1)/2)*Tc] (nibble pairs
    (n, n+h); 15 = always-left sentinel -> 256) or lossless int8
    [n_tc, Nint*Tc]; val packed uint8 [n_tc, ((W+1)/2)*Tc] (leaf pairs
    (j, j+h), two's-complement nibbles); scale [n_tc, Tc] f32; coh
    [Tpad, C] f32; optional dl/cat [n_tc, Nint*Tc] int8; out [TILE_R, C]
    f32. Unpacking is shift/mask on int32 lanes + a lane-axis concat —
    the nibble planes land exactly node-major, so the descent below is
    _lut_kernel's, plane for plane."""
    rest = list(rest)
    out_ref = rest.pop()
    dl_ref = rest.pop(0) if use_missing else None
    cat_ref = rest.pop(0) if use_cat else None
    tile_r = x_ref.shape[0]
    lanes = n_int * tc
    h_l = (n_leaves + 1) // 2
    xb = x_ref[:].astype(jnp.bfloat16)                    # bins: exact
    f_iota = jax.lax.broadcasted_iota(jnp.int32, (n_feat, lanes), 0)
    acc = jnp.zeros((tile_r, out_ref.shape[1]), jnp.float32)
    for c in range(n_tc):
        feat = jnp.broadcast_to(feat_ref[c:c + 1, :], (n_feat, lanes))
        fohT = (feat == f_iota).astype(jnp.bfloat16)      # [F, Nint*Tc]
        colval = jax.lax.dot_general(
            xb, fohT, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.bfloat16,   # bins <= 255: exact
        )                                                 # [T, Nint*Tc]
        if thr_packed:
            # In-VPU nibble decode: low/high nibbles are node blocks
            # [0, h) and [h, 2h) — the lane concat rebuilds node-major
            # order; sentinel 15 -> 256 = always-left for any uint8 bin.
            tp = thr_ref[c:c + 1, :].astype(jnp.int32)
            nib = jnp.concatenate(
                [jnp.bitwise_and(tp, 15),
                 jnp.right_shift(tp, 4)], axis=1)[:, :lanes]
            thr_row = jnp.where(nib >= 15, jnp.int32(_NIB_BIG),
                                nib).astype(jnp.bfloat16)
        else:
            # Lossless int8 form (a model whose thresholds exceed the
            # nibble): undo the recentring exactly like _lut_kernel.
            thr_row = (thr_ref[c:c + 1, :].astype(jnp.bfloat16)
                       + jnp.bfloat16(_I8_OFFSET))
        thr = jnp.broadcast_to(thr_row, (tile_r, lanes))
        comp = colval > thr
        if use_cat:
            cat = jnp.broadcast_to(
                cat_ref[c:c + 1, :], (tile_r, lanes)) != 0
            comp = jnp.where(cat, colval != thr, comp)
        if use_missing:
            miss = colval == jnp.bfloat16(missing_bin_value)
            dl = jnp.broadcast_to(
                dl_ref[c:c + 1, :], (tile_r, lanes)) != 0
            comp = jnp.where(miss, ~dl, comp)
        k = jnp.zeros((tile_r, tc), jnp.int32)
        for d in range(max_depth):
            lo = (1 << d) - 1
            go = jnp.zeros((tile_r, tc), jnp.bool_)
            for i in range(1 << d):
                n = lo + i
                go = jnp.where(k == i, comp[:, n * tc:(n + 1) * tc], go)
            k = 2 * k + go.astype(jnp.int32)
        # Unpack + dequantize the WHOLE leaf table once per chunk:
        # two's-complement sign extension of each nibble, then the one
        # f32 multiply by the per-tree scale — the very multiply the
        # host-side dequantized() reference performs, BEFORE the
        # k-select, so selected values are bit-identical to the
        # reference table (a post-select multiply invites XLA to fuse
        # it into the class dot and costs the last ULP — measured).
        vp = val_ref[c:c + 1, :].astype(jnp.int32)
        vnib = jnp.concatenate(
            [jnp.bitwise_and(vp, 15),
             jnp.bitwise_and(jnp.right_shift(vp, 4), 15)], axis=1)
        sext = jnp.where(vnib >= 8, vnib - 16,
                         vnib).astype(jnp.float32)        # [1, 2h*Tc]
        scale_row = scale_ref[c:c + 1, :].astype(jnp.float32)  # [1, Tc]
        deq = sext * jnp.concatenate([scale_row] * (2 * h_l), axis=1)
        vals = jnp.zeros((tile_r, tc), jnp.float32)
        for j in range(n_leaves):
            plane = jnp.broadcast_to(
                deq[:, j * tc:(j + 1) * tc], (tile_r, tc))
            vals = jnp.where(k == j, plane, vals)
        acc = acc + jax.lax.dot_general(
            vals, coh_ref[c * tc:(c + 1) * tc, :],
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
    out_ref[:] = acc


def predict_effective_lut4_ops(
    ops: tuple,                # PackedTables.ops (host or device)
    Xc: jax.Array,             # [R, F] uint8 bins
    *,
    max_depth: int,
    learning_rate,
    base,
    n_classes: int,
    tree_chunk: int,
    n_trees_padded: int,
    missing_bin_value: int,
    use_missing: bool,
    use_cat: bool,
    thr_packed: bool,
    tile_r: int | None = None,
    interpret: bool | None = None,
) -> jax.Array:
    """int4 scoring core on prebuilt bit-packed operands (jit-safe; the
    backend caches the device copies of `ops` per model token, the AOT
    export lowers exactly this computation per bucket shape)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if tile_r is None:
        tile_r = _DEFAULT_TILE_R
    if not jnp.issubdtype(Xc.dtype, jnp.integer):
        raise ValueError(
            "the LUT kernel requires binned integer data; raw-threshold "
            "scoring has no quantized form")
    R, F = Xc.shape
    C = n_classes
    if R == 0:
        out = jnp.full((0, C), base, jnp.float32)
        return out[:, 0] if C == 1 else out
    if not interpret and not predict_lut4_fits(
            n_trees_padded, tree_chunk, max_depth, F, C, tile_r,
            thr_packed=thr_packed):
        raise ValueError(
            f"int4 LUT shape (trees_padded={n_trees_padded}, "
            f"tree_chunk={tree_chunk}, depth={max_depth}, F={F}, C={C}) "
            "exceeds the Pallas VMEM/trace budget; use the int8/f32 "
            "ladder")
    n_tc = n_trees_padded // tree_chunk
    n_int = (1 << max_depth) - 1
    n_leaves = 1 << max_depth
    lanes = n_int * tree_chunk
    h_n = (n_int + 1) // 2
    h_l = (n_leaves + 1) // 2

    Xu = Xc.astype(jnp.uint8)        # raw bins stream as 1 B/feature
    n_tiles = -(-R // tile_r)
    rpad = n_tiles * tile_r - R
    if rpad:
        Xu = jnp.pad(Xu, ((0, rpad), (0, 0)))

    kernel = functools.partial(
        _lut4_kernel, n_tc=n_tc, tc=tree_chunk, n_int=n_int,
        n_leaves=n_leaves, n_feat=F, max_depth=max_depth,
        missing_bin_value=missing_bin_value, use_missing=use_missing,
        use_cat=use_cat, thr_packed=thr_packed,
    )
    pinned = pl.BlockSpec((n_tc, lanes), lambda i: (0, 0),
                          memory_space=pltpu.VMEM)
    in_specs = [
        pl.BlockSpec((tile_r, F), lambda i: (i, 0),
                     memory_space=pltpu.VMEM),             # rows (uint8)
        pinned,                                            # feat
        pl.BlockSpec((n_tc, (h_n if thr_packed else n_int) * tree_chunk),
                     lambda i: (0, 0),
                     memory_space=pltpu.VMEM),             # thr (packed)
        pl.BlockSpec((n_tc, h_l * tree_chunk), lambda i: (0, 0),
                     memory_space=pltpu.VMEM),             # leaf nibbles
        pl.BlockSpec((n_tc, tree_chunk), lambda i: (0, 0),
                     memory_space=pltpu.VMEM),             # scale
        pl.BlockSpec((n_trees_padded, C), lambda i: (0, 0),
                     memory_space=pltpu.VMEM),             # coh
    ]
    in_specs += [pinned] * (int(use_missing) + int(use_cat))
    cost = pl.CostEstimate(
        flops=2 * n_tiles * tile_r * (F * n_tc * lanes
                                      + n_trees_padded * C),
        # The honest HBM story: rows at 1 B/feature, thresholds/leaves
        # at HALF a byte each when packed — the int4 pack's whole point.
        bytes_accessed=n_tiles * tile_r * (F + C * 4)
        + n_tc * (lanes * 4
                  + (h_n if thr_packed else n_int) * tree_chunk
                  + h_l * tree_chunk + tree_chunk * 4)
        + n_trees_padded * C * 4,
        transcendentals=0,
    )
    with traced_scope("predict"):
        with traced_scope("predict:traverse"):
            acc = pl.pallas_call(
                kernel,
                grid=(n_tiles,),
                in_specs=in_specs,
                out_specs=pl.BlockSpec((tile_r, C), lambda i: (i, 0),
                                       memory_space=pltpu.VMEM),
                out_shape=jax.ShapeDtypeStruct((n_tiles * tile_r, C),
                                               jnp.float32),
                cost_estimate=cost,
                interpret=interpret,
            )(Xu, *ops)
        with traced_scope("predict:accumulate"):
            out = base + learning_rate * acc[:R]
    return out[:, 0] if C == 1 else out


@costed("predict_lut4", phase="predict")
@op_scope("predict")
def predict_effective_lut4(
    packed,                     # PackedTables (or int4 QuantizedTables)
    Xc,                         # [R, F] uint8 bins (host or device)
    tile_r: int | None = None,
    interpret: bool | None = None,
) -> jax.Array:
    """Standalone host entry for the int4 tier (tests/bench): packs on
    demand and runs the kernel. The backend path (TPUDevice._predict_fn
    with cfg.predict_impl="lut4") caches the packed operands
    device-resident instead — this entry exists for correctness work,
    not the hot loop."""
    if isinstance(packed, QuantizedTables):
        packed = packed.pack_int4()
    return predict_effective_lut4_ops(
        tuple(jnp.asarray(a) for a in packed.ops), jnp.asarray(Xc),
        **packed.static_kwargs(), tile_r=tile_r, interpret=interpret,
    )
