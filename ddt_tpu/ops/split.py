"""SplitGain: scan histogram bins, score splits, argmax per node.

Layer L3 kernel #2 (SURVEY.md §2 "SplitGain"): cumulative-sum scan over the
bin axis, XGBoost-style gain formula, argmax over the flattened (feature, bin)
axis. NumPy twin: reference/numpy_trainer.best_splits — tie-break semantics
(first occurrence in flattened order) deliberately match jnp.argmax so every
backend picks identical splits.

This is tiny (histograms are [N, F, B, 2] ~ KBs-MBs) — pure XLA vector code,
fused by the compiler; never a bottleneck next to the histogram build.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ddt_tpu.telemetry.annotations import op_scope
from ddt_tpu.telemetry.costmodel import costed


@op_scope("cat_vec")
def cat_feature_vec(cat_features, n_features: int) -> "jax.Array | None":
    """bool [n_features] mask of one-vs-rest (categorical) columns, or
    None when there are none — the single home of the cat_features →
    vector convention (grow routing, streamed traversal, device eval all
    read this)."""
    if not cat_features:
        return None
    return jnp.zeros(n_features, bool).at[
        jnp.asarray(cat_features, jnp.int32)].set(True)


def node_totals(hist: jax.Array) -> tuple[jax.Array, jax.Array]:
    """(G, H) per node: sums over bins of feature 0 (any feature sums the
    same rows). float32 [n_nodes] each."""
    return hist[:, 0, :, 0].sum(axis=1), hist[:, 0, :, 1].sum(axis=1)


@op_scope("gain")
def best_splits_impl(
    hist: jax.Array,            # float32 [n_nodes, F, B, 2]
    reg_lambda: float,
    min_child_weight: float,
    feature_mask: jax.Array | None = None,   # bool [F]; False = excluded
    missing_bin: bool = False,
    cat_mask: jax.Array | None = None,       # bool [F]; True = categorical
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Per-node best split: (gain [n], feature [n] i32, bin [n] i32,
    default_left [n] bool).

    gain = 0.5 * (GL^2/(HL+l) + GR^2/(HR+l) - G^2/(H+l)); split at bin b
    sends bins <= b left; last bin invalid (empty right child); children must
    carry >= min_child_weight hessian mass. Invalid positions score -inf.
    feature_mask implements colsample_bytree: masked features never win.

    missing_bin=True (cfg.missing_policy="learn"): bin B-1 holds NaN rows;
    both default directions are scored per (feature, bin) and the argmax
    runs over the flattened (direction, feature, bin) axis with the RIGHT
    block first — zero-missing nodes tie exactly and deterministically pick
    default_left=False.

    cat_mask marks categorical features (cfg.cat_features): one-vs-rest
    candidates ("bin == k goes left", every bin valid, one-hot gain)
    replace the ordinal cumsum gains on those features; under missing_bin
    they compete in the RIGHT block only. Semantics identical to the NumPy
    twin (reference/numpy_trainer.best_splits); keep in sync.
    """
    n_nodes, F, B, _ = hist.shape
    GL = jnp.cumsum(hist[..., 0], axis=2)           # [n, F, B]
    HL = jnp.cumsum(hist[..., 1], axis=2)
    # PER-FEATURE totals: feature f's own cumsum tail, so degenerate
    # candidates (all mass on one side) get an EXACTLY-zero complement
    # rather than cross-feature f32 noise near min_child_weight. Keep in
    # sync with numpy_trainer.best_splits and native/split_gain.cpp.
    G = GL[:, :, B - 1:B]                           # [n, F, 1]
    H = HL[:, :, B - 1:B]

    def gain_of(GLd, HLd):
        GR = G - GLd
        HR = H - HLd
        parent = jnp.square(G) / (H + reg_lambda)
        gain = 0.5 * (
            jnp.square(GLd) / (HLd + reg_lambda)
            + jnp.square(GR) / (HR + reg_lambda)
            - parent
        )
        valid = (HLd >= min_child_weight) & (HR >= min_child_weight)
        valid = valid & ~jnp.isnan(gain)            # 0/0 when reg_lambda == 0
        if feature_mask is not None:
            valid = valid & feature_mask[None, :, None]
        return gain, valid

    # Deterministic split selection: round gains to bfloat16 before argmax.
    # Gains within float noise of each other (different cumsum algorithms,
    # psum accumulation order across partitions, NumPy-vs-XLA rounding)
    # collapse to EXACT ties, broken by the shared first-flattened-index rule
    # — so every backend and every partition count picks identical splits.
    # Selecting among candidates within bf16 resolution (~0.4%) of the max is
    # immaterial to model quality; decision stability across devices is not.
    #
    # Determinism boundary: bf16 rounding absorbs noise RELATIVE to the
    # gain's magnitude — it collapses near-ties AMONG candidates, but it
    # cannot protect the split/no-split DECISION when a signal-free
    # node's best gain is itself f32 cancellation noise (~1e-8): with
    # min_split_gain=0 that noise's sign decides leaf-vs-split and
    # legitimately differs across summation orders (any reg_lambda).
    # reg_lambda=0 with min_child_weight=0 additionally lets near-empty
    # children amplify the noise unboundedly (0/0 vs x/0 can even differ
    # NaN-vs-inf across backends). Cross-backend bit-identity therefore
    # holds when decisions sit above the noise floor: min_split_gain >=
    # ~1e-3 (and min_child_weight >= ~1e-3 when reg_lambda = 0) — the
    # domain tests/test_config_fuzz.py randomizes over. Well-separated
    # real-signal configs (the default-parameter test suites) satisfy
    # this without any explicit floor.
    #
    # Cross-PLATFORM boundary (round 3, measured — experiments/
    # chip_parity.py): all of the above holds WITHIN a platform. Real-v5e
    # vs CPU training additionally differs by f32 summation ORDER (MXU
    # systolic accumulation vs sequential loops), which flips decisions
    # on EXACT near-ties that straddle a bf16 quantization boundary —
    # ~2-4 nodes per 155 at depth 4, unaffected by min_split_gain or
    # f32 matmul inputs (ordering is not a dtype). Model quality is
    # equivalent (held-out AUC within 0.004 both directions over 20
    # trees); reproducibility ACROSS platforms is per-platform, not
    # bitwise.
    #
    # Cross-PROCESS boundary (round 3, tests/test_multiprocess.py): a
    # multi-process mesh (gloo/real-pod collectives) may sum the
    # histogram allreduce in a different order than the single-
    # controller compilation of the same mesh shape. Measured effect:
    # tree STRUCTURE stays bit-identical (bf16 gain rounding absorbs
    # the ULPs), leaf VALUES agree to float tolerance (rtol ~2e-4)
    # rather than bitwise. The bit-identity contract is therefore:
    # bitwise within one controller at any partition count; structure-
    # identical + leaf-tolerant across controllers/processes.
    #
    # Chunked-ACCUMULATION boundary (round 4, fuzz campaign 2: seed
    # 197, the one divergence in 210 random streaming cases): streamed training sums per-chunk
    # histogram partials on host, a different f32 summation tree than
    # the in-memory single device sum. When a node's two best candidate
    # gains land within ~1 bf16 ULP of each other (measured: 0.00102997
    # vs 0.00102234 at the min_split_gain floor, reg_lambda=0), the
    # rounded argmax can legitimately pick either — ~1 root-cause node
    # per 160k across the campaigns. Streamed == in-memory is therefore
    # bitwise EXCEPT provable bf16-boundary candidate ties (the fuzz's
    # _assert_trees_match_mod_ties states the checkable contract); the
    # many fixed-seed streaming suites remain bitwise in practice.
    def overlay_cat(gain, valid):
        """Replace cat features' ordinal gains with one-vs-rest gains
        (left child = exactly bin k => GL_k is the per-bin sum itself)."""
        if cat_mask is None:
            return gain, valid
        gc, vc = gain_of(hist[..., 0], hist[..., 1])
        m = cat_mask[None, :, None]
        return jnp.where(m, gc, gain), jnp.where(m, vc, valid)

    if not missing_bin:
        gain, valid = gain_of(GL, HL)
        valid = valid & (jnp.arange(B) < B - 1)[None, None, :]
        gain, valid = overlay_cat(gain, valid)
        gain = jnp.where(valid, gain, -jnp.inf).astype(jnp.bfloat16)
        flat = gain.reshape(n_nodes, F * B)
        best = jnp.argmax(flat, axis=1)
        best_gain = jnp.take_along_axis(
            flat, best[:, None], axis=1)[:, 0].astype(jnp.float32)
        return (
            best_gain,
            (best // B).astype(jnp.int32),
            (best % B).astype(jnp.int32),
            jnp.zeros(n_nodes, bool),
        )

    miss_g = hist[:, :, B - 1:B, 0]                 # [n, F, 1]
    miss_h = hist[:, :, B - 1:B, 1]
    gain_r, valid_r = gain_of(GL, HL)               # missing stays RIGHT
    gain_l, valid_l = gain_of(GL + miss_g, HL + miss_h)   # missing LEFT
    not_nan_bin = (jnp.arange(B) < B - 1)[None, None, :]
    valid_r = valid_r & not_nan_bin
    # t = B-2 under LEFT puts every row left (empty right child): invalid
    # regardless of the min_child_weight knob.
    valid_l = valid_l & (jnp.arange(B) < B - 2)[None, None, :]
    gain_r, valid_r = overlay_cat(gain_r, valid_r)
    if cat_mask is not None:
        valid_l = valid_l & ~cat_mask[None, :, None]   # cat: RIGHT only
    g16 = jnp.concatenate(
        [jnp.where(valid_r, gain_r, -jnp.inf),
         jnp.where(valid_l, gain_l, -jnp.inf)], axis=1,
    ).astype(jnp.bfloat16)                          # [n, 2F, B]: RIGHT first
    flat = g16.reshape(n_nodes, 2 * F * B)
    best = jnp.argmax(flat, axis=1)
    best_gain = jnp.take_along_axis(
        flat, best[:, None], axis=1)[:, 0].astype(jnp.float32)
    fb = best % (F * B)
    return (
        best_gain,
        (fb // B).astype(jnp.int32),
        (fb % B).astype(jnp.int32),
        best >= F * B,
    )


#: The standalone jit entry (granular backend surface + host callers).
#: `best_splits_impl` above is the raw traced body: the fused level round
#: (ops/grow.py) calls it DIRECTLY so gain scoring inlines into the same
#: XLA program as the histogram build and row routing — no nested pjit
#: boundary between hist output and the gain epilogue.
best_splits = costed("gain", phase="gain")(
    functools.partial(
        jax.jit,
        static_argnames=("reg_lambda", "min_child_weight", "missing_bin"),
    )(best_splits_impl))
