"""SplitGain: scan histogram bins, score splits, argmax per node.

Layer L3 kernel #2 (SURVEY.md §2 "SplitGain"): cumulative-sum scan over the
bin axis, XGBoost-style gain formula, argmax over the flattened (feature, bin)
axis. NumPy twin: reference/numpy_trainer.best_splits — tie-break semantics
(first occurrence in flattened order) deliberately match jnp.argmax so every
backend picks identical splits.

This is tiny (histograms are [N, F, B, 2] ~ KBs-MBs) — pure XLA vector code,
fused by the compiler; never a bottleneck next to the histogram build.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=())
def node_totals(hist: jax.Array) -> tuple[jax.Array, jax.Array]:
    """(G, H) per node: sums over bins of feature 0 (any feature sums the
    same rows). float32 [n_nodes] each."""
    return hist[:, 0, :, 0].sum(axis=1), hist[:, 0, :, 1].sum(axis=1)


@functools.partial(
    jax.jit, static_argnames=("reg_lambda", "min_child_weight")
)
def best_splits(
    hist: jax.Array,            # float32 [n_nodes, F, B, 2]
    reg_lambda: float,
    min_child_weight: float,
    feature_mask: jax.Array | None = None,   # bool [F]; False = excluded
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Per-node best split: (gain [n], feature [n] int32, bin [n] int32).

    gain = 0.5 * (GL^2/(HL+l) + GR^2/(HR+l) - G^2/(H+l)); split at bin b
    sends bins <= b left; last bin invalid (empty right child); children must
    carry >= min_child_weight hessian mass. Invalid positions score -inf.
    feature_mask implements colsample_bytree: masked features never win.
    """
    n_nodes, F, B, _ = hist.shape
    GL = jnp.cumsum(hist[..., 0], axis=2)           # [n, F, B]
    HL = jnp.cumsum(hist[..., 1], axis=2)
    G = GL[:, 0:1, B - 1:B]                         # [n, 1, 1] totals
    H = HL[:, 0:1, B - 1:B]
    GR = G - GL
    HR = H - HL
    parent = jnp.square(G) / (H + reg_lambda)
    gain = 0.5 * (
        jnp.square(GL) / (HL + reg_lambda)
        + jnp.square(GR) / (HR + reg_lambda)
        - parent
    )
    valid = (HL >= min_child_weight) & (HR >= min_child_weight)
    valid = valid & (jnp.arange(B) < B - 1)[None, None, :]
    valid = valid & ~jnp.isnan(gain)                # 0/0 when reg_lambda == 0
    if feature_mask is not None:
        valid = valid & feature_mask[None, :, None]
    # Deterministic split selection: round gains to bfloat16 before argmax.
    # Gains within float noise of each other (different cumsum algorithms,
    # psum accumulation order across partitions, NumPy-vs-XLA rounding)
    # collapse to EXACT ties, broken by the shared first-flattened-index rule
    # — so every backend and every partition count picks identical splits.
    # Selecting among candidates within bf16 resolution (~0.4%) of the max is
    # immaterial to model quality; decision stability across devices is not.
    gain = jnp.where(valid, gain, -jnp.inf).astype(jnp.bfloat16)

    flat = gain.reshape(n_nodes, F * B)
    best = jnp.argmax(flat, axis=1)
    best_gain = jnp.take_along_axis(flat, best[:, None], axis=1)[:, 0].astype(
        jnp.float32
    )
    return (
        best_gain,
        (best // B).astype(jnp.int32),
        (best % B).astype(jnp.int32),
    )
