"""Stateless counter-based sampling masks (round-4 verdict item 2).

Bagging (cfg.subsample) keeps a row in a boosting round by a pure
function of (seed, round, GLOBAL row id) — a counter-based hash, no RNG
stream to carry or fast-forward and no O(R) mask to ship. Every trainer
computes the identical bit for a row wherever that row lives:

- the granular Driver draws the mask host-side (`row_keep_np`) and
  applies it via backend.apply_row_mask (any backend);
- the fused TPU path computes it IN-SCAN on device (`row_keep_jax`) —
  the [K, R] mask-shipping exclusion that kept bagging off the fused
  dispatch path is gone, the mask is (re)computed where it is used;
- the streaming trainers compute it per chunk from the chunk's global
  row offset, O(chunk) — which is what lets fit_streaming support the
  bagging configs it used to reject (10B-row runs are exactly where
  bagging is standard practice).

The two twins produce bit-identical uint32 streams (tested in
tests/test_sampling.py), so bagged training keeps the same
cross-backend / cross-path ensemble-identity contract as deterministic
training. Row ids are 64-bit (the 10B-row config overflows uint32);
devices without x64 carry them as (hi, lo) uint32 pairs.

Hash: the 'lowbias32' integer finalizer (a public-domain, statistically
tested 16-bit-shift/multiply permutation of uint32) applied to the row
id words, keyed per (seed, round). The top 24 bits form the uniform —
exactly representable in f32, so the `< subsample` compare is exact and
platform-invariant.

colsample_bytree stays host-drawn (`colsample_mask` below): its [F]
masks are KBs, every path already ships them, and this module is their
single home (including the degenerate-draw rescue) so fused == granular
== streamed draws stay bit-identical.
"""

from __future__ import annotations

import numpy as np

from ddt_tpu.telemetry.annotations import op_scope

_M1 = 0x7FEB352D
_M2 = 0x846CA68B
_GOLD = 0x9E3779B9
_KEY2 = 0x85EBCA6B


def _mix32_host(x: int) -> int:
    """lowbias32 on a python int (mod 2^32) — the scalar key path."""
    x &= 0xFFFFFFFF
    x ^= x >> 16
    x = (x * _M1) & 0xFFFFFFFF
    x ^= x >> 15
    x = (x * _M2) & 0xFFFFFFFF
    x ^= x >> 16
    return x


def round_key(seed: int, rnd: int) -> int:
    """Per-(seed, round) 32-bit key, computed with python ints so both
    twins (and any future one) can reproduce it exactly."""
    k = _mix32_host((seed & 0xFFFFFFFF) ^ _GOLD)
    return _mix32_host(k ^ ((rnd * _KEY2) & 0xFFFFFFFF))


def _mix32_np(x: np.ndarray) -> np.ndarray:
    x = x.astype(np.uint32)
    x ^= x >> np.uint32(16)
    x = x * np.uint32(_M1)
    x ^= x >> np.uint32(15)
    x = x * np.uint32(_M2)
    x ^= x >> np.uint32(16)
    return x


def uniform_np(seed: int, rnd: int, row_start: int, n: int) -> np.ndarray:
    """f32 [n] uniforms in [0, 1) for global rows [row_start, row_start
    + n) — the generic counter-hash draw behind row_keep_np, exposed so
    other per-(seed, round, row) randomness (the grad-quant stochastic
    rounding, ops/grad.py — which salts the seed per channel) shares the
    one hash and its 24-bit-exact-in-f32 property. Strictly < 1 (the top
    24 bits over 2^-24), so floor(x + u) of an on-grid x never rounds."""
    ids = np.arange(row_start, row_start + n, dtype=np.uint64)
    lo = (ids & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    hi = (ids >> np.uint64(32)).astype(np.uint32)
    key = np.uint32(round_key(seed, rnd))
    bits = _mix32_np(lo ^ _mix32_np(hi ^ key))
    return (bits >> np.uint32(8)).astype(np.float32) * np.float32(2.0 ** -24)


def row_keep_np(seed: int, rnd: int, row_start: int, n: int,
                subsample: float) -> np.ndarray:
    """bool [n]: keep bits for global rows [row_start, row_start + n)."""
    return uniform_np(seed, rnd, row_start, n) < np.float32(subsample)


@op_scope("sample")
def uniform_jax(rnd, local_offset, n: int, *, seed: int,
                row_start_lo=None, row_start_hi=None):
    """f32 [n] uniforms in [0, 1), traceable under jit/shard_map — the
    device twin of uniform_np (bit-identical by construction; the shared
    draw behind row_keep_jax and the grad-quant stochastic rounding).

    `rnd` and `local_offset` are traced int32 scalars (`local_offset` =
    this shard's first row within the padded global batch, typically
    flat_shard_index * local_rows — pad rows get ids too, but their
    valid-weight is 0 so the wasted bits are inert). `row_start_lo/hi`
    (traced uint32 scalars) carry a 64-bit chunk base for the streaming
    trainer; None means base 0. Key derivation mirrors round_key()
    exactly, in uint32 ops."""
    import jax.numpy as jnp

    rnd32 = rnd.astype(jnp.uint32) if hasattr(rnd, "astype") else \
        jnp.uint32(rnd)

    def mix(x):
        x ^= x >> 16
        x = x * jnp.uint32(_M1)
        x ^= x >> 15
        x = x * jnp.uint32(_M2)
        x ^= x >> 16
        return x

    key = mix(jnp.uint32((seed & 0xFFFFFFFF) ^ _GOLD))
    key = mix(key ^ (rnd32 * jnp.uint32(_KEY2)))
    loc = (jnp.arange(n, dtype=jnp.uint32)
           + jnp.uint32(local_offset))          # < 2^31: never wraps
    if row_start_lo is None:
        lo = loc
        hi = jnp.zeros((), jnp.uint32)
    else:
        base_lo = jnp.uint32(row_start_lo)
        lo = base_lo + loc
        carry = (lo < base_lo).astype(jnp.uint32)   # loc < 2^31 => exact
        hi = jnp.uint32(row_start_hi) + carry
    bits = mix(lo ^ mix(hi ^ key))
    return (bits >> 8).astype(jnp.float32) * jnp.float32(2.0 ** -24)


@op_scope("sample")
def row_keep_jax(rnd, local_offset, n: int, *, seed: int,
                 subsample: float, row_start_lo=None, row_start_hi=None):
    """f32 [n] 0/1 keep mask, traceable under jit/shard_map — the device
    twin of row_keep_np (bit-identical by construction; see uniform_jax
    for the id/key conventions)."""
    import jax.numpy as jnp

    u = uniform_jax(rnd, local_offset, n, seed=seed,
                    row_start_lo=row_start_lo, row_start_hi=row_start_hi)
    return (u < jnp.float32(subsample)).astype(jnp.float32)


def colsample_mask(seed: int, rnd: int, c: int, F: int,
                   colsample_bytree: float) -> np.ndarray:
    """The per-(seed, round, class) colsample feature mask — ONE home for
    the rng tuple and the degenerate-draw rescue, because the fused ==
    granular == streamed ensemble-parity guarantee depends on every path
    drawing bit-identical masks."""
    m = (np.random.default_rng(
        (seed, 104729, rnd, c)).random(F) < colsample_bytree)
    if not m.any():                 # degenerate draw: keep 1 feature
        m[rnd % F] = True
    return m
