"""Batch ensemble prediction: depth-unrolled compare+select on XLA.

Layer L3/L6 (SURVEY.md §3 "predict"): the reference's `TreeEnsemble.predict`
batch-scoring path. The north star calls this "gather+compare" [BASELINE] —
but on TPU a literal per-(tree,row) `take_along_axis` traversal lowers to
scalar-loop gathers (measured ~10 M lookups/s on a v5e: 28 s for 200k rows x
100 trees, and the 10M x 1000 config killed the chip). So the gathers are
re-expressed as one-hot compare+reduce, which vectorises on the VPU and is
EXACT (integer sums select a single matching lane):

1. Leaf-chain pushdown (`_effective_arrays`): descendants of a leaf inherit
   its value/slot; leaves themselves get feature=-1, thr=+inf so every row
   walks all the way to the bottom level (always-left below a leaf). This
   removes the frozen-node case, so at level d a row's node is exactly its
   d-bit relative index — all lookups stay inside the level's 2^d-wide slice.
2. Per level: node-relative one-hot [T, R, 2^d] selects (feature, thr) from
   the level slice; a feature one-hot [T, R, F] selects the row's bin value
   (feature=-1 matches no lane -> fv=0 < thr=+inf -> go left). All
   compare+select+reduce chains fuse — nothing [T, R, *]-shaped reaches HBM.
3. Bottom level: one-hot select of the (pushed-down) leaf value per row.

Doubly chunked via lax.scan — trees in chunks of `tree_chunk`, rows in chunks
of `row_chunk` — so the working set stays bounded for the 10M-row x
1000-tree inference config [BASELINE] (a flat [1000, 10M] int32 node state
alone would be 40 GB).

Since the inference-overhaul PR the module exposes THREE related entries:

- `predict_raw` — the original raw-arrays contract (pushdown computed
  in-trace); kept for tests/experiments and host callers.
- `predict_raw_effective` — the same scoring core fed PRE-pushed-down,
  pre-padded arrays (models/tree.CompiledEnsemble builds them ONCE per
  model on host; backends keep them device-resident across calls).
- the Pallas fast path (`ops/predict_pallas.py`) — dispatched from either
  entry via `use_pallas` (None = auto: binned data on a real TPU whose
  shape fits the kernel's VMEM budget; the one-hot path is the fallback).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ddt_tpu.telemetry.annotations import op_scope, traced_scope
from ddt_tpu.telemetry.costmodel import costed

_DEFAULT_ROW_CHUNK = 65_536


def _effective_arrays(feature, thr, is_leaf, leaf_value, max_depth):
    """Push leaves down the heap: returns (eff_feat, eff_thr, eff_val,
    eff_slot) where every node below a leaf inherits the leaf's value and
    original slot, leaf/inherited nodes carry feature=-1 and thr=+BIG.

    All ops are on tiny [T, N] arrays (N = 2^(D+1)-1); the per-level parent
    indexing uses STATIC index vectors, which XLA lowers to cheap slices.

    `leaf_value=None` skips the value chain entirely (eff_val comes back
    None) — `traverse` only needs slots, and the old throwaway
    `jnp.zeros`-shaped value array bought nothing but flops.
    """
    T, N = feature.shape
    big = (
        jnp.asarray(jnp.inf, thr.dtype)
        if jnp.issubdtype(thr.dtype, jnp.floating)
        else jnp.asarray(2 ** 30, thr.dtype)
    )
    dead = is_leaf
    eff_feat = jnp.where(dead, -1, feature)
    eff_thr = jnp.where(dead, big, thr)
    eff_val = leaf_value
    eff_slot = jnp.broadcast_to(jnp.arange(N, dtype=jnp.int32), (T, N))
    chained = is_leaf
    for d in range(1, max_depth + 1):
        lo, hi = (1 << d) - 1, (1 << (d + 1)) - 1
        par = (jnp.arange(lo, hi) - 1) // 2            # static indices
        pch = chained[:, par]                          # parent leaf/chained
        eff_feat = eff_feat.at[:, lo:hi].set(
            jnp.where(pch, -1, eff_feat[:, lo:hi]))
        eff_thr = eff_thr.at[:, lo:hi].set(
            jnp.where(pch, big, eff_thr[:, lo:hi]))
        if eff_val is not None:
            eff_val = eff_val.at[:, lo:hi].set(
                jnp.where(pch, eff_val[:, par], eff_val[:, lo:hi]))
        eff_slot = eff_slot.at[:, lo:hi].set(
            jnp.where(pch, eff_slot[:, par], eff_slot[:, lo:hi]))
        chained = chained.at[:, lo:hi].set(pch | is_leaf[:, lo:hi])
    return eff_feat, eff_thr, eff_val, eff_slot


def _select_level(k, table):
    """table[t, k[t, r]] for a level-local table [T, w] — one-hot
    compare+reduce (exact: k matches exactly one lane)."""
    w = table.shape[1]
    noh = k[:, :, None] == jnp.arange(w, dtype=jnp.int32)[None, None, :]
    zero = jnp.zeros((), table.dtype)
    return jnp.sum(jnp.where(noh, table[:, None, :], zero), axis=-1)


def _descend(eff_feat, eff_thr, Xc, max_depth, dl=None,
             missing_bin_value=-1, cat_node=None):
    """Relative node index at the bottom level: int32 [T, R].

    Per-level formulation: one-hot select of the row's (feature, thr) from
    the level slice, then a feature one-hot select of the bin value. Used
    for float (raw-threshold) data; the binned fast path is _descend_comp.

    `dl` ([T, N] bool) enables missing-value routing: rows whose selected
    value is missing — bin == missing_bin_value for integer data, NaN for
    float data — follow the node's learned default direction. Pushed-down
    leaf nodes select fv = 0 (feature=-1 matches no lane), which is neither
    the reserved bin nor NaN, so they stay on the always-left path.

    `cat_node` ([T, N] bool) marks categorical one-vs-rest nodes: the
    matched bin goes LEFT (fv != thr goes right). Gated on eff_feat >= 0
    so pushed-down leaf nodes (thr = +BIG, fv = 0) stay always-left.
    """
    Tc = eff_feat.shape[0]
    R, F = Xc.shape
    binned = jnp.issubdtype(Xc.dtype, jnp.integer)
    k = jnp.zeros((Tc, R), jnp.int32)
    f_iota = jnp.arange(F, dtype=jnp.int32)[None, None, :]
    for d in range(max_depth):
        lo, w = (1 << d) - 1, 1 << d
        feat_r = _select_level(k, eff_feat[:, lo:lo + w])         # [T, R]
        thr_r = _select_level(k, eff_thr[:, lo:lo + w])
        foh = feat_r[:, :, None] == f_iota                        # [T, R, F]
        fv = jnp.sum(
            jnp.where(foh, Xc[None, :, :], jnp.zeros((), Xc.dtype)), axis=-1
        )
        go = fv > thr_r
        if cat_node is not None:
            cat_r = _select_level(
                k, cat_node[:, lo:lo + w].astype(jnp.int32)).astype(bool)
            go = jnp.where(cat_r & (feat_r >= 0), fv != thr_r, go)
        if dl is not None:
            miss = (fv == missing_bin_value) if binned else jnp.isnan(fv)
            dl_r = _select_level(
                k, dl[:, lo:lo + w].astype(jnp.int32)).astype(bool)
            go = jnp.where(miss, ~dl_r, go)
        k = 2 * k + go.astype(jnp.int32)
    return k


def _descend_comp(eff_feat, eff_thr, Xc, max_depth, dl=None,
                  missing_bin_value=-1, cat_node=None):
    """Binned fast path: relative node index at the bottom level, [R, T].

    Precomputes the comparison bit of EVERY internal node for every row in
    one MXU matmul — colval[(t,n), r] = Xc[r, feat[t,n]] via the feature
    one-hot (exact: bin values <= 255 are exact in bf16, and the one-hot
    contraction selects a single element) — then descends by selecting the
    path node's bit per level (2 VPU ops/level vs ~3+(F/2^d)·3 for the
    per-level selects). Returns k ROW-MAJOR [R, T] (the caller's vals/class
    accumulation contracts over T)."""
    Tc, N = eff_feat.shape
    R, F = Xc.shape
    n_int = (1 << max_depth) - 1          # internal nodes
    foh = (
        eff_feat[:, :n_int, None]
        == jnp.arange(F, dtype=jnp.int32)[None, None, :]
    ).astype(jnp.bfloat16)                # [T, Nint, F]; feat=-1 -> zero row
    colval = jax.lax.dot_general(
        Xc.astype(jnp.bfloat16), foh.reshape(Tc * n_int, F),
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.bfloat16,   # bins <= 255: exact in bf16
    ).reshape(R, Tc, n_int)               # [R, T, Nint] exact bin values
    comp = colval > eff_thr[None, :, :n_int].astype(jnp.bfloat16)
    if cat_node is not None:
        # One-vs-rest nodes: the matched bin (exact in bf16) goes left.
        # Gate on eff_feat >= 0 so pushed-down leaves stay always-left.
        cat_eff = cat_node[:, :n_int] & (eff_feat[:, :n_int] >= 0)
        comp = jnp.where(
            cat_eff[None, :, :],
            colval != eff_thr[None, :, :n_int].astype(jnp.bfloat16), comp)
    if dl is not None:
        # Missing rows (the reserved bin, exact in bf16) follow the node's
        # learned direction; pushed-down leaves have colval=0, never the
        # reserved bin.
        miss = colval == jnp.bfloat16(missing_bin_value)
        comp = jnp.where(miss, ~dl[None, :, :n_int], comp)
    k = jnp.zeros((R, Tc), jnp.int32)
    for d in range(max_depth):
        lo, w = (1 << d) - 1, 1 << d
        noh = k[:, :, None] == jnp.arange(w, dtype=jnp.int32)[None, None, :]
        go = jnp.any(noh & comp[:, :, lo:lo + w], axis=-1)
        k = 2 * k + go.astype(jnp.int32)
    return k


@functools.partial(jax.jit, static_argnames=("max_depth",))
def traverse(
    feature: jax.Array,        # int32 [T, N]
    thr: jax.Array,            # [T, N] int32 bins or float32 raw thresholds
    is_leaf: jax.Array,        # bool  [T, N]
    Xc: jax.Array,             # [R, F] int32 (binned) or float32 (raw)
    max_depth: int,
) -> jax.Array:
    """Leaf slot per (tree, row): int32 [T, R] (the ORIGINAL heap slot the
    row lands in, as with explicit frozen-node traversal).

    Routed through the shared effective-arrays helper with leaf_value=None
    — no throwaway value array is allocated or pushed down; persistent
    cross-call reuse of the pushdown lives one level up
    (models/tree.CompiledEnsemble + the backend cache)."""
    eff_feat, eff_thr, _, eff_slot = _effective_arrays(
        feature, thr, is_leaf, None, max_depth)
    k = _descend(eff_feat, eff_thr, Xc, max_depth)
    lo = (1 << max_depth) - 1
    return _select_level(k, eff_slot[:, lo:])


def resolve_use_pallas(use_pallas, binned: bool, n_trees_padded: int,
                       tree_chunk: int, max_depth: int, n_features: int,
                       n_classes: int) -> bool:
    """The ONE home of the pallas-vs-one-hot predict dispatch rule.

    None = auto: the Pallas traversal kernel is taken when the data is
    binned, a real TPU backs the computation, and the kernel's VMEM
    working set fits (predict_pallas.predict_pallas_fits). Explicit True
    demands the kernel (binned data required — raises otherwise; off-TPU
    it runs in interpret mode, the test contract); explicit False always
    takes the one-hot path."""
    if use_pallas is False:
        return False
    from ddt_tpu.ops import predict_pallas

    if use_pallas is None:
        return (binned and jax.default_backend() == "tpu"
                and predict_pallas.predict_pallas_fits(
                    n_trees_padded, tree_chunk, max_depth, n_features,
                    n_classes))
    if not binned:
        raise ValueError(
            "use_pallas=True requires binned (integer) data; the Pallas "
            "traversal kernel has no raw-threshold form — use the one-hot "
            "path for float features")
    return True


def _predict_effective(
    eff_feat, eff_thr, bot_val, cls_oh, Xc, *,
    max_depth: int, learning_rate, base, n_classes: int,
    tree_chunk: int, row_chunk: int | None,
    missing_bin_value: int, eff_dl=None, eff_cat=None,
    use_pallas=None,
):
    """Scoring core on PRE-pushed-down, tree-padded arrays.

    eff_feat/eff_thr [Tpad, N], bot_val [Tpad, 2^D] (bottom level of the
    pushed-down values), cls_oh [Tpad, C] (round-major class one-hot;
    padded trees carry value 0 so their class column gains exactly 0.0).
    eff_dl/eff_cat are the pushdown-aligned routing masks or None. The
    doubly chunked scan is unchanged from the original predict_raw body —
    the pushdown just moved out (models/tree.CompiledEnsemble computes it
    once per model on host; predict_raw still computes it in-trace)."""
    binned = bool(jnp.issubdtype(Xc.dtype, jnp.integer))
    if binned:
        Xc = Xc.astype(jnp.int32)      # uint8 uploads are 4x cheaper; widen
    R, F = Xc.shape
    C = n_classes
    if R == 0:
        out = jnp.full((0, C), base, jnp.float32)
        return out[:, 0] if C == 1 else out
    Tpad = eff_feat.shape[0]
    if resolve_use_pallas(use_pallas, binned, Tpad, tree_chunk, max_depth,
                          F, C):
        from ddt_tpu.ops import predict_pallas

        return predict_pallas.predict_effective_pallas(
            eff_feat, eff_thr, bot_val, cls_oh, Xc,
            max_depth=max_depth, learning_rate=learning_rate, base=base,
            n_classes=C, tree_chunk=tree_chunk,
            missing_bin_value=missing_bin_value,
            eff_dl=eff_dl, eff_cat=eff_cat,
        )
    if row_chunk is None:
        # The binned comparison-matrix descent materialises
        # [Rc, chunk, Nint] bits; default to a smaller row chunk there to
        # bound it. Round-5 interleaved sweep (docs/PERF.md): the
        # row_chunk axis is flat within ~4% over 4k-16k while
        # tree_chunk=64 dominates — (64, 8192) sits on the plateau.
        # None is the only "use default" value — an explicit row_chunk,
        # including 65536, is always honored.
        row_chunk = 8_192 if binned else _DEFAULT_ROW_CHUNK
    n_tc = Tpad // tree_chunk
    featp = eff_feat.reshape(n_tc, tree_chunk, -1)
    thrp = eff_thr.reshape(n_tc, tree_chunk, -1)
    use_missing = eff_dl is not None
    if use_missing:
        dlp = eff_dl.reshape(n_tc, tree_chunk, -1)
    use_cat = eff_cat is not None
    if use_cat:
        catp = eff_cat.reshape(n_tc, tree_chunk, -1)
    valp = bot_val.reshape(n_tc, tree_chunk, -1)      # bottom level only
    cls_ohp = cls_oh.reshape(n_tc, tree_chunk, C)

    row_chunk = min(row_chunk, R)
    n_rc = -(-R // row_chunk)
    rpad = n_rc * row_chunk - R
    Xp = jnp.pad(Xc, ((0, rpad), (0, 0))).reshape(n_rc, row_chunk, F)

    def row_body(_, xrc):
        def tree_body(acc, args):
            f, t, v, coh = args[:4]
            rest = list(args[4:])
            dlc = rest.pop(0) if use_missing else None
            catc = rest.pop(0) if use_cat else None
            with traced_scope("predict:traverse"):
                if binned:
                    k = _descend_comp(f, t, xrc, max_depth, dl=dlc,
                                      missing_bin_value=missing_bin_value,
                                      cat_node=catc)
                else:
                    k = _descend(f, t, xrc, max_depth, dl=dlc,
                                 missing_bin_value=missing_bin_value,
                                 cat_node=catc)
            with traced_scope("predict:accumulate"):
                if binned:
                    W = v.shape[1]                               # [Rc, chunk]
                    noh = (
                        k[:, :, None]
                        == jnp.arange(W, dtype=jnp.int32)[None, None, :]
                    )
                    vals = jnp.sum(
                        jnp.where(noh, v[None, :, :], 0.0), axis=-1
                    )                                            # [Rc, chunk]
                    contract = (((1,), (0,)), ((), ()))
                else:
                    vals = _select_level(k, v)                   # [chunk, Rc]
                    contract = (((0,), (0,)), ((), ()))
                # Scatter chunk sums into classes: one_hot [chunk, C]
                # matmul.
                acc = acc + jax.lax.dot_general(
                    vals, coh, contract,
                    preferred_element_type=jnp.float32,
                    # Exact: one operand is a 0/1 one-hot, so HIGHEST costs
                    # little and keeps predictions bit-stable across
                    # platforms.
                    precision=jax.lax.Precision.HIGHEST,
                )                                                # [Rc, C]
            return acc, None

        acc0 = jnp.zeros((row_chunk, C), jnp.float32)
        xs = [featp, thrp, valp, cls_ohp]
        if use_missing:
            xs.append(dlp)
        if use_cat:
            xs.append(catp)
        acc, _ = jax.lax.scan(tree_body, acc0, tuple(xs))
        return None, acc

    # `ddt:predict` on the device timeline (telemetry.annotations): the
    # whole doubly-chunked descent shows as one named span in Perfetto,
    # matching the host-side scoring phase name; `ddt:predict:traverse` /
    # `ddt:predict:accumulate` sub-spans nest inside it.
    with traced_scope("predict"):
        _, accs = jax.lax.scan(row_body, None, Xp)           # [n_rc, Rc, C]
    out = base + learning_rate * accs.reshape(n_rc * row_chunk, C)[:R]
    return out[:, 0] if C == 1 else out


@costed("predict", phase="predict")
@functools.partial(
    jax.jit,
    static_argnames=("max_depth", "n_classes", "tree_chunk", "row_chunk",
                     "missing_bin_value", "use_pallas"),
)
def predict_raw_effective(
    eff_feat: jax.Array,       # [Tpad, N] pushed-down features
    eff_thr: jax.Array,        # [Tpad, N] pushed-down thresholds
    bot_val: jax.Array,        # float32 [Tpad, 2^D] bottom-level values
    cls_oh: jax.Array,         # float32 [Tpad, C] class one-hot
    Xc: jax.Array,             # [R, F]
    max_depth: int,
    learning_rate: float,
    base: float,
    n_classes: int = 1,
    tree_chunk: int = 64,
    row_chunk: int | None = None,
    eff_dl: jax.Array | None = None,
    missing_bin_value: int = -1,
    eff_cat: jax.Array | None = None,
    use_pallas: bool | None = None,
) -> jax.Array:
    """predict_raw on a CompiledEnsemble's precomputed arrays — no
    pushdown, no padding, no class-one-hot construction in-trace. The
    backend keeps these arrays device-resident across calls (the
    resident-vs-total bench gap showed ~27% of predict wall time was
    re-upload/setup). Tpad must be a multiple of tree_chunk
    (CompiledEnsemble.build guarantees it)."""
    return _predict_effective(
        eff_feat, eff_thr, bot_val, cls_oh, Xc,
        max_depth=max_depth, learning_rate=learning_rate, base=base,
        n_classes=n_classes, tree_chunk=tree_chunk, row_chunk=row_chunk,
        missing_bin_value=missing_bin_value, eff_dl=eff_dl,
        eff_cat=eff_cat, use_pallas=use_pallas,
    )


@costed("predict", phase="predict")
@functools.partial(
    jax.jit,
    static_argnames=("max_depth", "n_classes", "tree_chunk", "row_chunk",
                     "missing_bin_value", "use_pallas"),
)
@op_scope("predict")
def predict_raw(
    feature: jax.Array,        # int32 [T, N]
    thr: jax.Array,            # [T, N]
    is_leaf: jax.Array,        # bool [T, N]
    leaf_value: jax.Array,     # float32 [T, N]
    Xc: jax.Array,             # [R, F]
    max_depth: int,
    learning_rate: float,
    base: float,
    n_classes: int = 1,        # 1 = scalar output; C = softmax round-major
    tree_chunk: int = 64,
    row_chunk: int | None = None,
    default_left: jax.Array | None = None,   # bool [T, N]; None = no
    #   missing-value handling (models trained without the reserved bin)
    missing_bin_value: int = -1,             # reserved NaN bin id (binned
    #   data); raw float data detects NaN directly
    cat_node: jax.Array | None = None,       # bool [T, N]; one-vs-rest
    #   split nodes ("bin == thr goes left", cfg.cat_features). For raw
    #   float data the caller must put the BIN id in thr for these nodes
    #   (categorical columns carry bin ids in both representations).
    use_pallas: bool | None = None,          # None = auto (binned data on
    #   a real TPU at a VMEM-fitting shape); the one-hot path is the
    #   fallback. ops/predict_pallas.py documents the kernel.
) -> jax.Array:
    """Raw margin scores: [R] (n_classes==1) or [R, C].

    Doubly lax.scan-chunked (rows outer, trees inner); per-chunk leaf values
    are accumulated into the per-class output (round-major tree->class
    interleave for softmax, matching reference/numpy_trainer.fit).
    """
    T = feature.shape[0]               # on device where casts are free
    C = n_classes
    n_tc = -(-T // tree_chunk)
    tpad = n_tc * tree_chunk - T

    def pad_t(a, fill=0):
        return jnp.pad(a, ((0, tpad), (0, 0)), constant_values=fill)

    # Padded trees are all-leaf at the root with value 0 -> contribute 0.
    ef, et, ev, _ = _effective_arrays(
        pad_t(feature, -1), pad_t(thr), pad_t(is_leaf, True),
        pad_t(leaf_value), max_depth,
    )
    lo = (1 << max_depth) - 1
    # Class of tree t is t % C (round-major interleave).
    cls = jnp.arange(n_tc * tree_chunk, dtype=jnp.int32) % C
    cls_oh = jax.nn.one_hot(cls, C, dtype=jnp.float32)   # [Tpad, C]
    return _predict_effective(
        ef, et, ev[:, lo:], cls_oh, Xc,
        max_depth=max_depth, learning_rate=learning_rate, base=base,
        n_classes=C, tree_chunk=tree_chunk, row_chunk=row_chunk,
        missing_bin_value=missing_bin_value,
        eff_dl=pad_t(default_left) if default_left is not None else None,
        eff_cat=pad_t(cat_node) if cat_node is not None else None,
        use_pallas=use_pallas,
    )


def predict_proba(raw: jax.Array, loss: str) -> jax.Array:
    if loss == "logloss":
        return jax.nn.sigmoid(raw)
    if loss == "softmax":
        return jax.nn.softmax(raw, axis=1)
    return raw
