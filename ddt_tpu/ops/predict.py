"""Batch ensemble prediction: depth-unrolled gather+compare on XLA.

Layer L3/L6 (SURVEY.md §3 "predict"): the reference's `TreeEnsemble.predict`
batch-scoring path, lowered exactly as the north star prescribes — "Batch
ensemble inference (TreeEnsemble.predict) lowers to XLA gather+compare"
[BASELINE]. Complete-heap node layout makes traversal branch-free:

    node <- is_leaf[node] ? node : 2*node + 1 + (x[feat[node]] > thr[node])

unrolled max_depth times with fully static shapes, vmapped over trees via
take_along_axis gathers. The 10M-row / 1000-tree inference config shards the
row axis across the mesh (parallel/inference.py); no collectives needed —
row-sharded scoring is embarrassingly parallel.

Tree-chunked via lax.scan when n_trees is large so the [T, R] working set
stays bounded (1000 trees x 10M rows of int32 would be 40 GB).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _traverse_level(node, feature, thr, is_leaf, Xc):
    """One gather+compare step for all (tree, row) pairs. node: int32 [T, R]."""
    feat = jnp.take_along_axis(feature, node, axis=1)            # [T, R]
    t = jnp.take_along_axis(thr, node, axis=1)
    leaf = jnp.take_along_axis(is_leaf, node, axis=1)
    # Gather feature values: fv[k, r] = Xc[r, feat[k, r]] (clip handles the
    # -1 sentinel on leaves; the result is masked by `leaf` anyway).
    fv = Xc.T[feat.clip(0), jnp.arange(Xc.shape[0])[None, :]]    # [T, R]
    go_right = (fv > t).astype(node.dtype)
    nxt = 2 * node + 1 + go_right
    return jnp.where(leaf, node, nxt)


def _traverse(feature, thr, is_leaf, Xc, max_depth):
    node = jnp.zeros((feature.shape[0], Xc.shape[0]), jnp.int32)
    for _ in range(max_depth):
        node = _traverse_level(node, feature, thr, is_leaf, Xc)
    return node


@functools.partial(jax.jit, static_argnames=("max_depth",))
def traverse(
    feature: jax.Array,        # int32 [T, N]
    thr: jax.Array,            # [T, N] int32 bins or float32 raw thresholds
    is_leaf: jax.Array,        # bool  [T, N]
    Xc: jax.Array,             # [R, F] int32 (binned) or float32 (raw)
    max_depth: int,
) -> jax.Array:
    """Leaf slot per (tree, row): int32 [T, R]."""
    return _traverse(feature, thr, is_leaf, Xc, max_depth)


@functools.partial(
    jax.jit, static_argnames=("max_depth", "n_classes", "tree_chunk")
)
def predict_raw(
    feature: jax.Array,        # int32 [T, N]
    thr: jax.Array,            # [T, N]
    is_leaf: jax.Array,        # bool [T, N]
    leaf_value: jax.Array,     # float32 [T, N]
    Xc: jax.Array,             # [R, F]
    max_depth: int,
    learning_rate: float,
    base: float,
    n_classes: int = 1,        # 1 = scalar output; C = softmax round-major
    tree_chunk: int = 64,
) -> jax.Array:
    """Raw margin scores: [R] (n_classes==1) or [R, C].

    Trees are processed in chunks of `tree_chunk` via lax.scan to bound the
    [chunk, R] traversal working set; per-chunk leaf values are accumulated
    into the per-class output (round-major tree->class interleave for
    softmax, matching reference/numpy_trainer.fit).
    """
    T = feature.shape[0]
    R = Xc.shape[0]
    C = n_classes
    n_chunks = -(-T // tree_chunk)
    pad = n_chunks * tree_chunk - T

    def pad_t(a, fill=0):
        return jnp.pad(a, ((0, pad), (0, 0)), constant_values=fill)

    # Padded trees are all-leaf at the root with value 0 -> contribute nothing.
    featp = pad_t(feature, -1).reshape(n_chunks, tree_chunk, -1)
    thrp = pad_t(thr).reshape(n_chunks, tree_chunk, -1)
    leafp = pad_t(is_leaf, True).reshape(n_chunks, tree_chunk, -1)
    valp = pad_t(leaf_value).reshape(n_chunks, tree_chunk, -1)
    # Class of tree t is t % C (round-major interleave).
    cls = (jnp.arange(n_chunks * tree_chunk, dtype=jnp.int32) % C).reshape(
        n_chunks, tree_chunk
    )

    def body(acc, args):
        f, t, l, v, c = args
        node = _traverse(f, t, l, Xc, max_depth)
        vals = jnp.take_along_axis(v, node, axis=1)              # [chunk, R]
        # Scatter chunk sums into classes: one_hot [chunk, C] matmul.
        cls_oh = jax.nn.one_hot(c, C, dtype=vals.dtype)          # [chunk, C]
        acc = acc + jax.lax.dot_general(
            vals, cls_oh, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            # Exact: one operand is a 0/1 one-hot, so HIGHEST costs little
            # and keeps predictions bit-stable across platforms.
            precision=jax.lax.Precision.HIGHEST,
        )                                                        # [R, C]
        return acc, None

    acc0 = jnp.zeros((R, C), jnp.float32)
    acc, _ = jax.lax.scan(body, acc0, (featp, thrp, leafp, valp, cls))
    out = base + learning_rate * acc
    return out[:, 0] if C == 1 else out


def predict_proba(raw: jax.Array, loss: str) -> jax.Array:
    if loss == "logloss":
        return jax.nn.sigmoid(raw)
    if loss == "softmax":
        return jax.nn.softmax(raw, axis=1)
    return raw
