"""Device-side streaming kernels: per-chunk level work as ONE dispatch.

The 10B-row stress config (BASELINE.json config 5) streams the row axis:
per tree level, every chunk contributes a partial histogram. Round-1's
trainer recomputed node assignment and gradients on HOST per chunk per
level and uploaded g/h/ni alongside the data — O(levels x rows) host
compute plus ~9 extra bytes/row of H2D per pass. These kernels move the
whole per-(chunk, level) step on device:

    upload Xb chunk (uint8, the unavoidable stream) [+ pred/y if not
    device-resident] -> ONE dispatch: partial-tree traversal (gather-free
    one-hot routing, same formulation as ops/grow.py) -> grad/hess ->
    masked histogram [-> psum over row shards] -> small [n, F, B, 2]
    output fetched by the host accumulator.

Everything here traces under jit and under shard_map (axis_name set): a
pod streams chunks with each chunk row-sharded over the mesh, the partial
histogram psum riding ICI/DCN exactly like the in-memory trainer
(SURVEY.md §5 "Distributed communication backend", §7 M6). Since ISSUE
11 the chunks themselves can arrive HOST-SHARDED (each process reads
only its own sub-shards — data/chunks.HostShardedChunks assembled by
TPUDevice.upload_row_shards); these kernels are unchanged by that: the
assembled global array has the identical row-sharded layout. The
streamed ops stay row-parallel-only — the 2D (rows x features) mesh is
the in-memory trainer's layout (streaming a wide dataset shards its
LONG axis; ops/grow.py carries the feature-axis composition).

Bit-compatibility: traversal mirrors streaming._traverse_partial (the
host twin) and the histogram sum enters the same bf16-rounded split
selection, so streamed training stays bit-identical to in-memory training
(tests/test_streaming.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ddt_tpu.ops import grad as grad_ops
from ddt_tpu.ops import histogram as H
from ddt_tpu.parallel import comms
from ddt_tpu.telemetry.annotations import op_scope


def _hist_collective(out, axis_name, comms_mode: str, comms_dtype: str):
    """The streamed histogram collective (parallel/comms.py): psum or —
    under split_comms=reduce_scatter — an F-slab scatter (the caller's
    out_specs shard the feature axis; the host reassembles at D2H time,
    so only the WIRE pays the slab cost). F pads to the shard count;
    callers slice the zero pad columns off after fetch. Integer partials
    (quantized gradients) merge natively — hist_reduce refuses
    compression for them (they are already on one shared grid)."""
    if axis_name is None:
        return out
    if comms_mode == "reduce_scatter":
        out = comms.pad_to_multiple(out, 1, comms.axis_size(axis_name))
    return comms.hist_reduce(out, axis_name, mode=comms_mode,
                             comms_dtype=comms_dtype, scatter_dim=1)


def partial_node_index(
    Xb: jax.Array,            # int32/uint8 [R, F] binned rows
    feature: jax.Array,       # int32 [n_nodes_total] (-1 on leaves)
    threshold_bin: jax.Array,  # int32 [n_nodes_total]
    is_leaf: jax.Array,       # bool  [n_nodes_total]
    depth: int,
    default_left: jax.Array | None = None,   # bool [n_nodes_total]
    missing_bin_value: int = -1,
    cat_vec: jax.Array | None = None,        # bool [F]: one-vs-rest cols
) -> jax.Array:
    """Level-local node per row at `depth` (-1 = frozen at an earlier
    leaf). Gather-free: per unrolled level, the row's node's routing
    fields are one-hot selected from the level's heap slice (w = 2^d
    lanes) as ONE packed table, the winning column's value from the F
    lanes — exact integer masked reductions, no scalar-loop gathers
    (ops/grow.py's routing formulation incl. categorical one-vs-rest and
    reserved-NaN-bin default directions; twin of
    streaming._traverse_partial)."""
    R, F = Xb.shape
    Xi = Xb.astype(jnp.int32)
    node = jnp.zeros(R, jnp.int32)
    frozen = jnp.zeros(R, bool)
    for d in range(depth):
        offset = (1 << d) - 1
        w = 1 << d
        idx = node - offset
        noh = idx[:, None] == jnp.arange(w, dtype=jnp.int32)[None, :]
        sl = slice(offset, offset + w)
        leaf_r = jnp.any(noh & is_leaf[sl][None, :], axis=1)
        frozen = frozen | leaf_r
        # Packed (feat<<12 | thr<<3 | cat<<2 | dl<<1) select: one masked
        # reduction for every routing table (thr < 512 by the n_bins
        # contract; leaves carry feature -1, clamped — frozen rows never
        # route anyway).
        f_lvl = jnp.maximum(feature[sl], 0)
        cat_lvl = (
            jnp.take(cat_vec, f_lvl, axis=0) if cat_vec is not None
            else jnp.zeros(w, bool)
        )
        dl_lvl = (
            default_left[sl] if default_left is not None
            else jnp.zeros(w, bool)
        )
        packed = ((f_lvl << 12) | (threshold_bin[sl] << 3)
                  | (cat_lvl.astype(jnp.int32) << 2)
                  | (dl_lvl.astype(jnp.int32) << 1))
        pr = jnp.sum(jnp.where(noh, packed[None, :], 0), axis=1)
        feat_r = pr >> 12
        thr_r = (pr >> 3) & 0x1FF
        cat_r = ((pr >> 2) & 1).astype(bool)
        dl_r = ((pr >> 1) & 1).astype(bool)
        foh = jax.lax.broadcasted_iota(
            jnp.int32, (1, F), 1) == feat_r[:, None]
        fv = jnp.sum(jnp.where(foh, Xi, 0), axis=1)
        go_right = fv > thr_r
        if cat_vec is not None:
            go_right = jnp.where(cat_r, fv != thr_r, go_right)
        if missing_bin_value >= 0:
            go_right = jnp.where(fv == missing_bin_value, ~dl_r, go_right)
        node = jnp.where(
            frozen, node, 2 * node + 1 + go_right.astype(jnp.int32))
    offset = (1 << depth) - 1
    return jnp.where(frozen, -1, node - offset).astype(jnp.int32)


def chunk_grads(
    pred: jax.Array,          # f32 [R] or [R, C]
    y: jax.Array,
    valid: jax.Array,         # float32 [R] weights (0 on pad rows)
    loss: str,
    class_idx: int = 0,
) -> tuple[jax.Array, jax.Array]:
    """(g, h) for one class column, scaled by the per-row weight mask
    (pad rows carry 0; instance weights when the caller set them)."""
    g, h = grad_ops.grad_hess(pred, y, loss)
    if g.ndim == 2:
        g = g[:, class_idx]
        h = h[:, class_idx]
    v = valid.astype(jnp.float32)
    return g * v, h * v


@op_scope("hist")
def stream_level_hist(
    Xb: jax.Array,            # uint8 [R, F] chunk
    pred: jax.Array,
    y: jax.Array,
    valid: jax.Array,
    feature: jax.Array,
    threshold_bin: jax.Array,
    is_leaf: jax.Array,
    default_left: jax.Array | None = None,
    *,
    depth: int,
    n_bins: int,
    loss: str,
    class_idx: int = 0,
    hist_impl: str = "auto",
    input_dtype=jnp.bfloat16,
    axis_name=None,
    missing_bin_value: int = -1,
    cat_vec: jax.Array | None = None,
    row_keep: jax.Array | None = None,   # f32 [R] 0/1 bagging mask
    comms_mode: str = "allreduce",
    comms_dtype: str = "f32",
    build_left: bool = False,   # sibling-subtraction: build only LEFT
    #   children keyed by PARENT slot — [2^(depth-1), F, B, 2]; the host
    #   accumulator recovers right children as parent - left (streaming.
    #   _assemble_subtracted_level), halving the streamed collective
    #   payload exactly like the fused rounds' level_histograms.
    quantize=None,              # quantized-gradient seam (cfg.grad_dtype):
    #   a (g, h) -> (qg, qh) closure built by the backend around
    #   ops/grad.quantize_with_scales with this ROUND's host-reduced
    #   scales and this chunk's global-row-id base — the histogram then
    #   builds INTEGER (int32 partials, exact cross-chunk/shard merges).
) -> jax.Array:
    """One chunk's level-`depth` partial histogram [2^depth, F, B, 2]
    (collected over row shards when axis_name is set — psum, or the F/P
    reduce-scatter under split_comms=reduce_scatter). `row_keep` is the
    round's counter-based bagging mask (ops/sampling) — 0/1 f32, exact
    under multiplication, so masked grads match the in-memory trainers
    bitwise. With `quantize` the output is the RAW int32 partial — the
    host accumulator dequantizes once after the level's last chunk."""
    ni = partial_node_index(
        Xb, feature, threshold_bin, is_leaf, depth, default_left,
        missing_bin_value=missing_bin_value, cat_vec=cat_vec)
    n_nodes = 1 << depth
    if build_left:
        assert depth >= 1, "build_left needs a parent level"
        is_l = (ni >= 0) & (ni % 2 == 0)
        ni = jnp.where(is_l, ni // 2, -1).astype(jnp.int32)
        n_nodes //= 2
    if row_keep is not None:
        valid = valid * row_keep
    g, h = chunk_grads(pred, y, valid, loss, class_idx)
    if quantize is not None:
        g, h = quantize(g, h)
    out = H.build_histograms(
        Xb, g, h, ni, n_nodes, n_bins,
        impl=hist_impl, input_dtype=input_dtype,
    )
    return _hist_collective(out, axis_name, comms_mode, comms_dtype)


@op_scope("leaf")
def stream_leaf_gh(
    Xb: jax.Array,
    pred: jax.Array,
    y: jax.Array,
    valid: jax.Array,
    feature: jax.Array,
    threshold_bin: jax.Array,
    is_leaf: jax.Array,
    default_left: jax.Array | None = None,
    *,
    max_depth: int,
    loss: str,
    class_idx: int = 0,
    axis_name=None,
    missing_bin_value: int = -1,
    cat_vec: jax.Array | None = None,
    row_keep: jax.Array | None = None,   # f32 [R] 0/1 bagging mask
    quantize=None,                       # see stream_level_hist
) -> jax.Array:
    """Final-level (G, H) aggregates for one chunk: f32 [2^max_depth, 2]
    via the one-hot matmul formulation (ops/grow.py's final level) —
    int32 under `quantize` (the host dequantizes after the last chunk;
    leaf sums then merge bit-exactly across chunks AND shards)."""
    ni = partial_node_index(
        Xb, feature, threshold_bin, is_leaf, max_depth, default_left,
        missing_bin_value=missing_bin_value, cat_vec=cat_vec)
    if row_keep is not None:
        valid = valid * row_keep
    g, h = chunk_grads(pred, y, valid, loss, class_idx)
    if quantize is not None:
        g, h = quantize(g, h)
    n_last = 1 << max_depth
    act = ni >= 0
    idx = jnp.clip(ni, 0, n_last - 1)
    # The shared one-hot contraction (grad_ops.leaf_gh_sums — one home
    # with ops/grow's final level; int32-exact on the quantized path).
    GH = grad_ops.leaf_gh_sums(idx, act, g, h, n_last)
    # Tiny [2^d, 2] aggregate: always the exact psum (scattering or
    # compressing it would save nothing and cost exactness).
    return comms.psum(GH, axis_name)


@op_scope("grad_quant")
def stream_grad_stats(
    pred: jax.Array,
    y: jax.Array,
    valid: jax.Array,
    *,
    loss: str,
    n_classes: int,
    axis_name=None,
    row_keep: jax.Array | None = None,
) -> jax.Array:
    """Per-class quantization stats [n_classes, 4] = (max|g|, sum|g|,
    max|h|, sum|h|) for one chunk's ROUND-START gradients — the cheap
    scale-derivation pass of quantized-gradient streaming (cfg.
    grad_dtype): no Xb read, just resident pred/labels. The host maxes/
    sums the per-chunk values (exact for the maxes; the f32 sums'
    chunk order is absorbed by the power-of-two scale snap — ops/grad),
    derives the round's per-output-dim scales once, and every level/leaf
    pass of the round quantizes onto that ONE shared grid — which is
    what makes the cross-chunk and cross-shard integer merges exact.
    Maxes ride pmax and sums psum over the row mesh."""
    if row_keep is not None:
        valid = valid * row_keep
    rows = []
    for c in range(n_classes):
        g, h = chunk_grads(pred, y, valid, loss, c)
        ag = jnp.abs(g)
        ah = jnp.abs(h)
        rows.append(jnp.stack([jnp.max(ag), jnp.sum(ag),
                               jnp.max(ah), jnp.sum(ah)]))
    st = jnp.stack(rows)                                  # [C, 4]
    mx = comms.pmax(st[:, 0::2], axis_name)
    sm = comms.psum(st[:, 1::2], axis_name)
    return jnp.stack([mx[:, 0], sm[:, 0], mx[:, 1], sm[:, 1]], axis=1)


@op_scope("route")
def apply_tree_pred(
    Xb: jax.Array,
    pred: jax.Array,
    feature: jax.Array,
    threshold_bin: jax.Array,
    is_leaf: jax.Array,
    leaf_value: jax.Array,
    default_left: jax.Array | None = None,
    *,
    max_depth: int,
    learning_rate: float,
    class_idx: int = 0,
    missing_bin_value: int = -1,
    cat_vec: jax.Array | None = None,    # bool [F global]: one-vs-rest cols
    feature_axis_name: str | None = None,
) -> jax.Array:
    """pred += lr * leaf_value[leaf slot] for one finished tree — the full
    routing semantics of ops/grow.py (ordinal, categorical one-vs-rest,
    reserved-NaN-bin default directions), gather-free one-hot selects.

    Used for per-chunk boosting-state updates (streaming) and device-side
    eval_set scoring (the Driver keeps validation predictions resident on
    device and applies each freshly grown tree here — round-1 verdict,
    Weak #5). With `feature_axis_name`, Xb is the local column shard and
    winning-column values ride a psum like grow's routing."""
    R, F = Xb.shape
    Xi = Xb.astype(jnp.int32)
    node = jnp.zeros(R, jnp.int32)
    frozen = jnp.zeros(R, bool)
    f_lo = (
        jax.lax.axis_index(feature_axis_name) * F
        if feature_axis_name is not None else 0
    )
    for d in range(max_depth):
        offset = (1 << d) - 1
        w = 1 << d
        idx = node - offset
        noh = idx[:, None] == jnp.arange(w, dtype=jnp.int32)[None, :]
        sl = slice(offset, offset + w)
        # STICKY frozen flag (as in partial_node_index): once a row stops
        # at an early leaf its node index lags the level being matched, so
        # noh is all-False from then on and a non-sticky "live" test would
        # wrongly resume descending through a garbage 0/0 split.
        frozen = frozen | jnp.any(noh & is_leaf[sl][None, :], axis=1)
        f_lvl = jnp.maximum(feature[sl], 0)     # leaves carry -1: clamp so
        #                                         the packed field stays sane
        cat_lvl = (
            jnp.take(cat_vec, f_lvl, axis=0) if cat_vec is not None
            else jnp.zeros(w, bool)
        )
        dl_lvl = (
            default_left[sl] if default_left is not None
            else jnp.zeros(w, bool)
        )
        # One packed per-node table (grow.py's routing trick): a single
        # masked reduction recovers feature, threshold, cat-ness and the
        # NaN default direction per row.
        packed = ((f_lvl << 12) | (threshold_bin[sl] << 3)
                  | (cat_lvl.astype(jnp.int32) << 2)
                  | (dl_lvl.astype(jnp.int32) << 1))
        pr = jnp.sum(jnp.where(noh, packed[None, :], 0), axis=1)
        feat_r = pr >> 12
        thr_r = (pr >> 3) & 0x1FF
        cat_r = ((pr >> 2) & 1).astype(bool)
        dl_r = ((pr >> 1) & 1).astype(bool)
        foh = jax.lax.broadcasted_iota(
            jnp.int32, (1, F), 1) == (feat_r - f_lo)[:, None]
        fv = jnp.sum(jnp.where(foh, Xi, 0), axis=1)
        if feature_axis_name is not None:
            # Exactly one column shard owns the winning feature; psum
            # broadcasts its value (everyone else contributes zero).
            fv = comms.psum(fv, feature_axis_name)
        go_right = fv > thr_r
        if cat_vec is not None:
            go_right = jnp.where(cat_r, fv != thr_r, go_right)
        if missing_bin_value >= 0:
            go_right = jnp.where(fv == missing_bin_value, ~dl_r, go_right)
        node = jnp.where(
            frozen, node, 2 * node + 1 + go_right.astype(jnp.int32))
    N = leaf_value.shape[0]
    voh = node[:, None] == jnp.arange(N, dtype=jnp.int32)[None, :]
    dv = jnp.sum(jnp.where(voh, leaf_value[None, :], 0.0), axis=1)
    if pred.ndim == 2:
        return pred.at[:, class_idx].add(learning_rate * dv)
    return pred + learning_rate * dv


@op_scope("roundstart")
def stream_round_start(
    Xb: jax.Array,
    pred: jax.Array,
    y: jax.Array,
    valid: jax.Array,
    prev_trees: tuple,        # ((feat, thr, leaf, val, dl), ...) — the
    #                           previous round's finished class trees
    *,
    max_depth: int,
    learning_rate: float,
    n_bins: int,
    loss: str,
    hist_impl: str = "auto",
    input_dtype=jnp.bfloat16,
    axis_name=None,
    missing_bin_value: int = -1,
    cat_vec: jax.Array | None = None,
    row_keep: jax.Array | None = None,   # f32 [R] 0/1 bagging mask for
    #   the NEW round's histogram (the pred update is never masked)
    comms_mode: str = "allreduce",
    comms_dtype: str = "f32",
    grad_stats_classes: int = 0,   # quantized-gradient mode (> 0): the
    #   NEW round's scales are not derivable until the previous trees
    #   land in pred, so this pass returns per-class quantization STATS
    #   (stream_grad_stats, [C, 4]) instead of a depth-0 histogram — the
    #   depth-0 build then runs as a normal quantized hist pass. One
    #   extra dispatch per round, zero extra Xb reads (the stats read
    #   only resident state).
) -> tuple[jax.Array, jax.Array]:
    """Fused round-start pass (round-2 verdict item 6): apply the PREVIOUS
    round's finished trees to pred, then compute class-0 gradients and the
    next tree's depth-0 histogram — ONE data pass where the trainer used
    to spend two (a pred-update pass plus the next round's first hist
    pass). On the transfer-bound streaming path that deletes one full
    dataset re-read per round (~1/(max_depth+2) of total passes).

    Returns (updated pred, [1, F, B, 2] depth-0 histogram, psum'd over row
    shards when axis_name is set) — or (updated pred, [C, 4] quant
    stats) when `grad_stats_classes` > 0 (see the param note)."""
    for cls, (feat, thr, leaf, val, dl) in enumerate(prev_trees):
        pred = apply_tree_pred(
            Xb, pred, feat, thr, leaf, val, dl,
            max_depth=max_depth, learning_rate=learning_rate,
            class_idx=cls, missing_bin_value=missing_bin_value,
            cat_vec=cat_vec,
        )
    if grad_stats_classes > 0:
        return pred, stream_grad_stats(
            pred, y, valid, loss=loss, n_classes=grad_stats_classes,
            axis_name=axis_name, row_keep=row_keep)
    g, h = chunk_grads(
        pred, y, valid if row_keep is None else valid * row_keep, loss, 0)
    ni = jnp.zeros(Xb.shape[0], jnp.int32)     # depth 0: every row at root
    out = H.build_histograms(
        Xb, g, h, ni, 1, n_bins, impl=hist_impl, input_dtype=input_dtype,
    )
    return pred, _hist_collective(out, axis_name, comms_mode, comms_dtype)


@op_scope("route")
def stream_update_pred(
    Xb: jax.Array,
    pred: jax.Array,
    feature: jax.Array,
    threshold_bin: jax.Array,
    is_leaf: jax.Array,
    leaf_value: jax.Array,
    default_left: jax.Array | None = None,
    *,
    max_depth: int,
    learning_rate: float,
    class_idx: int = 0,
    missing_bin_value: int = -1,
    cat_vec: jax.Array | None = None,
) -> jax.Array:
    """pred += lr * leaf_value[leaf slot] for one finished tree (per-chunk
    boosting-state update, on device; full routing semantics)."""
    return apply_tree_pred(
        Xb, pred, feature, threshold_bin, is_leaf, leaf_value,
        default_left,
        max_depth=max_depth, learning_rate=learning_rate,
        class_idx=class_idx, missing_bin_value=missing_bin_value,
        cat_vec=cat_vec,
    )
