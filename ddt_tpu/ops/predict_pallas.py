"""Pallas TPU traversal kernel for batch ensemble scoring — binned data.

Why this kernel exists (round-5 phase breakdown, docs/PERF.md): the pure-XLA
one-hot predict path is bound by the comparison matrix's HBM traffic — the
[row_chunk, tree_chunk, Nint] compare bits are ~33 MB per chunk pair, ~644 GB
total for the 10M x 1000 config against the v5e's ~820 GB/s, while the MXU
part of the matmul is ~0.2 ms of the 1.13 s P1 phase. Same disease the
histogram kernel had (ops/hist_pallas.py), same cure: build the per-tile
working set IN VMEM and never let it touch HBM. The only HBM traffic is the
binned input itself (R x F int32) plus the tiny tree tables and the [R, C]
scores — the comparison matrix, feature one-hots, and descent state live and
die inside one row tile's VMEM residency.

Layout strategy (one grid step = one tile of TILE_R rows; ALL tree tables are
pinned in VMEM for the whole kernel via constant index maps — a 1000-tree
depth-6 ensemble is ~1 MB):

    X     [TILE_R, F]        int32 bins, cast bf16 in-VMEM.
    feat  [n_tc, Nint*Tc]    NODE-MAJOR flattened effective features per
                             tree chunk (lane block n holds node n of all
                             Tc trees) — so every descent select is a
                             STATIC lane slice, no gathers anywhere.
    thr/dl/cat               same node-major layout.
    val   [n_tc, W*Tc]       bottom-level pushed-down leaf values.
    coh   [Tpad, C]          round-major class one-hot.

Per tree chunk (static Python loop, traced once):
    fohT [F, Nint*Tc] bf16 one-hot built on the VPU by SUBLANE-broadcasting
        the feature row against a lane iota (the hist_pallas transposed-
        kernel trick), then ONE MXU matmul: colval = X @ fohT — the exact
        bin value at every (row, tree, node).
    comp = colval > thr (with categorical one-vs-rest and reserved-NaN-bin
        routing applied exactly as ops/predict._descend_comp).
    D-step indexed descent: k[r, t] starts 0; level d selects the path
        node's comparison bit by k-indexed predicated selects over the
        level's 2^d node planes (each plane a static lane slice) —
        sum(2^d) = Nint VPU selects per chunk, zero HBM traffic.
    Leaf select + class scatter: vals[r, t] by k-indexed select over the
        W bottom planes, then acc += vals @ class-one-hot (f32, HIGHEST —
        bit-stable, mirroring the one-hot path's accumulation order).

Contract: EXACT match with ops/predict.predict_raw at the same tree_chunk
(missing-value routing, categorical one-vs-rest, softmax round-major classes
all preserved; integer descent identical, float accumulation mirrored
term-for-term — tests/test_predict_pallas.py asserts array equality).
Interpret-mode CPU fallback auto-selects off-TPU, same pattern as
hist_pallas.py; dispatch lives in ops/predict.resolve_use_pallas (the
`use_pallas` flag on predict_raw / predict_raw_effective, one-hot fallback).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ddt_tpu.telemetry.annotations import op_scope, traced_scope
from ddt_tpu.telemetry.costmodel import costed

# VMEM ceiling for auto-dispatch: the per-chunk [TILE_R, Nint*Tc] colval
# (bf16) + comparison bits + the resident tree tables + Mosaic's
# double-buffered input windows must fit ~16 MB/core; 12 MB leaves the
# same headroom hist_pallas budgets.
_VMEM_BUDGET_BYTES = 12 * 1024 * 1024
_DEFAULT_TILE_R = 256
# Static-unroll ceiling: the kernel traces n_tc * (Nint + W + ~4) ops;
# past this the trace (and Mosaic compile) grows pathological — the
# one-hot path is the right tool for such shapes anyway.
_MAX_TRACE_SELECTS = 32_768


def predict_pallas_fits(
    n_trees_padded: int,
    tree_chunk: int,
    max_depth: int,
    n_features: int,
    n_classes: int,
    tile_r: int | None = None,
) -> bool:
    """Whether the traversal kernel's VMEM working set (and trace size)
    fits at this shape — the guard behind use_pallas=None auto-dispatch
    (ops/predict.resolve_use_pallas)."""
    if tile_r is None:
        tile_r = _DEFAULT_TILE_R
    if n_trees_padded % tree_chunk != 0:
        return False
    n_int = (1 << max_depth) - 1
    n_leaves = 1 << max_depth
    n_tc = n_trees_padded // tree_chunk
    if n_tc * (n_int + n_leaves) > _MAX_TRACE_SELECTS:
        return False
    lanes = n_int * tree_chunk
    work = tile_r * lanes * 3                 # colval bf16 + comp bytes
    trees = n_tc * (lanes * 8                 # feat i32 + thr f32
                    + n_leaves * tree_chunk * 4)
    trees += n_trees_padded * n_classes * 4   # class one-hot
    x_tile = tile_r * n_features * 4
    out = tile_r * max(n_classes, 8) * 4
    return work + trees + x_tile + out <= _VMEM_BUDGET_BYTES


def _traverse_kernel(x_ref, feat_ref, thr_ref, val_ref, coh_ref, *rest,
                     n_tc: int, tc: int, n_int: int, n_leaves: int,
                     n_feat: int, max_depth: int, missing_bin_value: int,
                     use_missing: bool, use_cat: bool):
    """One row tile: margins for every class, all trees, fully in VMEM.

    x_ref [TILE_R, F] int32; feat/thr (+ optional dl, cat) [n_tc, Nint*Tc]
    node-major; val [n_tc, W*Tc]; coh [Tpad, C]; out [TILE_R, C] f32."""
    rest = list(rest)
    out_ref = rest.pop()
    dl_ref = rest.pop(0) if use_missing else None
    cat_ref = rest.pop(0) if use_cat else None
    tile_r = x_ref.shape[0]
    lanes = n_int * tc
    xb = x_ref[:].astype(jnp.bfloat16)                    # [T, F]
    f_iota = jax.lax.broadcasted_iota(jnp.int32, (n_feat, lanes), 0)
    acc = jnp.zeros((tile_r, out_ref.shape[1]), jnp.float32)
    for c in range(n_tc):
        # Feature one-hot, TRANSPOSED: sublane-broadcast the feature row
        # (cheap row replication — the hist_pallas _hist_kernel_t trick)
        # against the per-feature iota. feat = -1 (pushed-down leaves)
        # matches no sublane -> colval 0 < thr(+BIG) -> always-left.
        feat = jnp.broadcast_to(feat_ref[c:c + 1, :], (n_feat, lanes))
        fohT = (feat == f_iota).astype(jnp.bfloat16)      # [F, Nint*Tc]
        colval = jax.lax.dot_general(
            xb, fohT, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.bfloat16,   # bins <= 255: exact
        )                                                 # [T, Nint*Tc]
        thr = jnp.broadcast_to(
            thr_ref[c:c + 1, :], (tile_r, lanes)).astype(jnp.bfloat16)
        comp = colval > thr
        if use_cat:
            # One-vs-rest nodes (pre-gated on eff_feat >= 0 in the
            # prologue): the matched bin goes left.
            cat = jnp.broadcast_to(
                cat_ref[c:c + 1, :], (tile_r, lanes)) != 0
            comp = jnp.where(cat, colval != thr, comp)
        if use_missing:
            # Reserved-NaN-bin rows follow the learned direction;
            # pushed-down leaves have colval 0, never the reserved bin.
            miss = colval == jnp.bfloat16(missing_bin_value)
            dl = jnp.broadcast_to(
                dl_ref[c:c + 1, :], (tile_r, lanes)) != 0
            comp = jnp.where(miss, ~dl, comp)
        # Indexed descent: k-select the path node's bit per level. Every
        # node plane is a STATIC lane slice of the node-major comp.
        k = jnp.zeros((tile_r, tc), jnp.int32)
        for d in range(max_depth):
            lo = (1 << d) - 1
            go = jnp.zeros((tile_r, tc), jnp.bool_)
            for i in range(1 << d):
                n = lo + i
                go = jnp.where(k == i, comp[:, n * tc:(n + 1) * tc], go)
            k = 2 * k + go.astype(jnp.int32)
        # Bottom-level leaf select (exact: k matches exactly one plane).
        vals = jnp.zeros((tile_r, tc), jnp.float32)
        for j in range(n_leaves):
            plane = jnp.broadcast_to(
                val_ref[c:c + 1, j * tc:(j + 1) * tc], (tile_r, tc))
            vals = jnp.where(k == j, plane, vals)
        # Class scatter — the same dot, precision, and per-chunk add order
        # as the one-hot path's scan body (bit-stable mirror).
        acc = acc + jax.lax.dot_general(
            vals, coh_ref[c * tc:(c + 1) * tc, :],
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
    out_ref[:] = acc


def predict_effective_pallas(
    eff_feat: jax.Array,       # [Tpad, N] pushed-down features (int32)
    eff_thr: jax.Array,        # [Tpad, N] pushed-down thresholds
    bot_val: jax.Array,        # f32 [Tpad, 2^D] bottom-level values
    cls_oh: jax.Array,         # f32 [Tpad, C] class one-hot
    Xc: jax.Array,             # [R, F] integer bins
    *,
    max_depth: int,
    learning_rate,
    base,
    n_classes: int = 1,
    tree_chunk: int = 64,
    missing_bin_value: int = -1,
    eff_dl: jax.Array | None = None,
    eff_cat: jax.Array | None = None,
    tile_r: int | None = None,
    interpret: bool | None = None,
) -> jax.Array:
    """Pallas twin of ops/predict._predict_effective (binned data only).

    interpret=None auto-selects Pallas interpreter mode off-TPU (the CPU
    test suite exercises the identical kernel logic; the compiled path
    needs a real chip) — the same fallback pattern as
    hist_pallas.build_histograms_pallas. Jit-safe: callable inside
    predict_raw / predict_raw_effective traces or standalone."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if tile_r is None:
        tile_r = _DEFAULT_TILE_R
    if not jnp.issubdtype(Xc.dtype, jnp.integer):
        raise ValueError(
            "the Pallas traversal kernel requires binned integer data; "
            "float (raw-threshold) scoring uses the one-hot path")
    R, F = Xc.shape
    C = n_classes
    if R == 0:
        out = jnp.full((0, C), base, jnp.float32)
        return out[:, 0] if C == 1 else out
    Tpad, N = eff_feat.shape
    if Tpad % tree_chunk != 0:
        raise ValueError(
            f"padded tree count {Tpad} is not a multiple of "
            f"tree_chunk={tree_chunk}")
    if not interpret and not predict_pallas_fits(
            Tpad, tree_chunk, max_depth, F, C, tile_r):
        # Compiled dispatch past the budget means a VMEM OOM or a
        # pathological Mosaic trace on the chip — fail at the cause. The
        # auto path (ops/predict.resolve_use_pallas) never gets here;
        # this guards a forced predict_impl='pallas' at a monster shape.
        # Interpret mode (CPU tests) has no VMEM to protect.
        raise ValueError(
            f"predict shape (trees_padded={Tpad}, tree_chunk={tree_chunk}, "
            f"depth={max_depth}, F={F}, C={C}) exceeds the Pallas "
            "VMEM/trace budget; use the one-hot path")
    n_tc = Tpad // tree_chunk
    n_int = (1 << max_depth) - 1
    n_leaves = 1 << max_depth

    def node_major(a, width, dtype):
        """[Tpad, width] -> [n_tc, width*Tc] with lane block n holding
        node n of every tree in the chunk (tiny arrays; the transpose is
        noise next to the row volume)."""
        return (a.astype(dtype)
                .reshape(n_tc, tree_chunk, width)
                .transpose(0, 2, 1)
                .reshape(n_tc, width * tree_chunk))

    feat_nm = node_major(eff_feat[:, :n_int], n_int, jnp.int32)
    thr_nm = node_major(eff_thr[:, :n_int], n_int, jnp.float32)
    val_nm = node_major(bot_val, n_leaves, jnp.float32)
    use_missing = eff_dl is not None
    use_cat = eff_cat is not None
    extras = []
    if use_missing:
        extras.append(node_major(eff_dl[:, :n_int], n_int, jnp.int32))
    if use_cat:
        # Pre-gate on eff_feat >= 0 so pushed-down leaves (colval 0,
        # thr +BIG) stay always-left, exactly like _descend_comp.
        cat_eff = eff_cat[:, :n_int].astype(bool) & (eff_feat[:, :n_int]
                                                     >= 0)
        extras.append(node_major(cat_eff, n_int, jnp.int32))

    Xi = Xc.astype(jnp.int32)
    n_tiles = -(-R // tile_r)
    rpad = n_tiles * tile_r - R
    if rpad:
        Xi = jnp.pad(Xi, ((0, rpad), (0, 0)))

    lanes = n_int * tree_chunk
    kernel = functools.partial(
        _traverse_kernel, n_tc=n_tc, tc=tree_chunk, n_int=n_int,
        n_leaves=n_leaves, n_feat=F, max_depth=max_depth,
        missing_bin_value=missing_bin_value, use_missing=use_missing,
        use_cat=use_cat,
    )
    pinned = pl.BlockSpec((n_tc, lanes), lambda i: (0, 0),
                          memory_space=pltpu.VMEM)
    in_specs = [
        pl.BlockSpec((tile_r, F), lambda i: (i, 0),
                     memory_space=pltpu.VMEM),
        pinned,                                           # feat
        pinned,                                           # thr
        pl.BlockSpec((n_tc, n_leaves * tree_chunk), lambda i: (0, 0),
                     memory_space=pltpu.VMEM),            # val
        pl.BlockSpec((Tpad, C), lambda i: (0, 0),
                     memory_space=pltpu.VMEM),            # coh
    ] + [pinned] * len(extras)
    cost = pl.CostEstimate(
        flops=2 * n_tiles * tile_r * (F * n_tc * lanes + Tpad * C),
        bytes_accessed=n_tiles * tile_r * (F + C) * 4
        + n_tc * lanes * 8 + Tpad * C * 4,
        transcendentals=0,
    )
    with traced_scope("predict"):
        with traced_scope("predict:traverse"):
            acc = pl.pallas_call(
                kernel,
                grid=(n_tiles,),
                in_specs=in_specs,
                out_specs=pl.BlockSpec((tile_r, C), lambda i: (i, 0),
                                       memory_space=pltpu.VMEM),
                out_shape=jax.ShapeDtypeStruct((n_tiles * tile_r, C),
                                               jnp.float32),
                cost_estimate=cost,
                interpret=interpret,
            )(Xi, feat_nm, thr_nm, val_nm,
              cls_oh.astype(jnp.float32), *extras)
        with traced_scope("predict:accumulate"):
            out = base + learning_rate * acc[:R]
    return out[:, 0] if C == 1 else out


@costed("predict_pallas", phase="predict")
@functools.partial(
    jax.jit,
    static_argnames=("max_depth", "n_classes", "tree_chunk",
                     "missing_bin_value", "tile_r", "interpret"),
)
@op_scope("predict")
def predict_raw_pallas(
    feature: jax.Array,        # int32 [T, N]
    thr: jax.Array,            # [T, N] int32 bins
    is_leaf: jax.Array,        # bool [T, N]
    leaf_value: jax.Array,     # float32 [T, N]
    Xc: jax.Array,             # [R, F] integer bins
    max_depth: int,
    learning_rate: float,
    base: float,
    n_classes: int = 1,
    tree_chunk: int = 64,
    default_left: jax.Array | None = None,
    missing_bin_value: int = -1,
    cat_node: jax.Array | None = None,
    tile_r: int | None = None,
    interpret: bool | None = None,
) -> jax.Array:
    """Standalone raw-arrays entry (tests/bench): pushdown in-trace, then
    the Pallas core — the predict_raw contract with use_pallas forced."""
    from ddt_tpu.ops import predict as predict_ops

    T = feature.shape[0]
    C = n_classes
    n_tc = -(-T // tree_chunk)
    tpad = n_tc * tree_chunk - T

    def pad_t(a, fill=0):
        return jnp.pad(a, ((0, tpad), (0, 0)), constant_values=fill)

    ef, et, ev, _ = predict_ops._effective_arrays(
        pad_t(feature, -1), pad_t(thr), pad_t(is_leaf, True),
        pad_t(leaf_value), max_depth,
    )
    lo = (1 << max_depth) - 1
    cls = jnp.arange(n_tc * tree_chunk, dtype=jnp.int32) % C
    cls_oh = jax.nn.one_hot(cls, C, dtype=jnp.float32)
    return predict_effective_pallas(
        ef, et, ev[:, lo:], cls_oh, Xc,
        max_depth=max_depth, learning_rate=learning_rate, base=base,
        n_classes=C, tree_chunk=tree_chunk,
        missing_bin_value=missing_bin_value,
        eff_dl=pad_t(default_left) if default_left is not None else None,
        eff_cat=pad_t(cat_node) if cat_node is not None else None,
        tile_r=tile_r, interpret=interpret,
    )
