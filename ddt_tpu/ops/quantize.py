"""Device-side binning: the quantizer's transform as an XLA op.

Host binning (`BinMapper.transform`, NumPy searchsorted) costs seconds at
the 10M-row configs and serialises on one core. On device the same
semantics are a compare+sum: `searchsorted(edges, v, side='left')` equals
the count of edges strictly below v, so the device compute is sub-second
— but the f32 upload is 4 bytes/cell, so this path wins only when the
raw matrix is already device-side or the host link is real PCIe/DMA
(through this image's remote tunnel the upload dominates; see the
BinMapper.transform_device docstring for the measurement). Formula:

    bin = clip( sum_e [edges[f, e] < v], 0, n_value_bins - 1 )

with NaN routed to the reserved bin (missing_policy="learn") or bin 0 —
BIT-IDENTICAL to the host transform, including +/-inf, duplicate-edge
runs (dup bins are simply never produced by either form) and identity
(categorical) columns. Rows are processed in blocks via lax.map so the
[block, F, n_edges] compare stays a fused VMEM-resident transient.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ddt_tpu.telemetry.annotations import op_scope


@functools.partial(
    jax.jit, static_argnames=("n_bins", "missing_bin", "row_block")
)
@op_scope("quantize")
def transform_binned(
    X: jax.Array,           # float32 [R, F] raw features (NaN allowed)
    edges: jax.Array,       # float32 [F, n_bins - 1] (trailing cols +inf)
    n_bins: int,
    missing_bin: bool = False,
    row_block: int = 8192,
) -> jax.Array:
    """uint8 [R, F] bin indices; device twin of BinMapper.transform."""
    R, F = X.shape
    nv = n_bins - 1 if missing_bin else n_bins
    e = edges[:, : nv - 1]                         # [F, nv-1]
    nan_bin = n_bins - 1 if missing_bin else 0

    def block(Xb):
        cmp = e[None, :, :] < Xb[:, :, None]       # [blk, F, nv-1]
        b = jnp.clip(cmp.sum(-1).astype(jnp.int32), 0, nv - 1)
        b = jnp.where(jnp.isnan(Xb), nan_bin, b)
        return b.astype(jnp.uint8)

    if R <= row_block:
        return block(X)
    pad = -R % row_block
    Xp = jnp.pad(X, ((0, pad), (0, 0))) if pad else X
    out = jax.lax.map(block, Xp.reshape(-1, row_block, F))
    return out.reshape(-1, F)[:R]


def transform_device(mapper, X: np.ndarray) -> np.ndarray:
    """Bin a float matrix on the default device; returns host uint8.
    Semantics identical to mapper.transform (tests assert bit-equality)."""
    X = np.asarray(X, np.float32)
    if X.ndim != 2 or X.shape[1] != mapper.n_features:
        raise ValueError(
            f"X must be [rows, {mapper.n_features}], got {X.shape}"
        )
    out = transform_binned(
        jnp.asarray(X), jnp.asarray(mapper.edges),
        n_bins=mapper.n_bins, missing_bin=mapper.missing_bin,
    )
    return np.asarray(out)
