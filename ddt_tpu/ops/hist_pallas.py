"""Pallas TPU kernel for HistogramBuilder — VMEM-accumulating, hand-tiled.

Why this kernel exists (measured on TPU v5e, 1M rows x 28 feat x 255 bins):
the pure-XLA one-hot-matmul path materialises the [rows, F*B] bin one-hot in
HBM — ~29 GB of write+read traffic per build — and runs HBM-bound at
~26 M-rows/s with the MXU nearly idle. The first Pallas form (rounds 1-5)
built the bin one-hot tile-by-tile in VMEM but still materialised the
WEIGHTED NODE ONE-HOT `A [R, 2N]` (plus an int32 copy of the binned input)
in an XLA prologue: ~250 MB of avoidable HBM write+read per build at the
headline shape, re-streamed per feature slab when chunked — the roofline
observatory's `ddt:hist` verdict stayed "hbm".

This rewrite streams only the RAW operands and synthesises everything else
on-chip:

    inputs per grid step (one tile of TILE_R rows):
      Xb  [TILE_R, F]  uint8  binned features (cast int32 in-VMEM — the
                       only row-sized HBM read, 1 byte/feature/row)
      g,h [1, TILE_R]  f32    gradient/hessian rows
      ni  [1, TILE_R]  i32    level-local node index, -1 = frozen
    on-chip per tile (VPU):
      A   [TILE_R, 2N]   node one-hot weighted by g (cols 0..N-1) and by
                         h (cols N..2N-1); ni = -1 matches no column, so
                         frozen rows vanish without a masking prologue.
      OH  [TILE_R, F*Bp] per-feature bin one-hot, Bp = padded lanes/bins.
    accumulate (MXU):
      acc [2N, F*Bp] f32 VMEM SCRATCH += A^T @ OH — ONE dot_general per
      tile; the scratch lives across the whole row-tile grid loop and is
      flushed to the output block (ONE HBM write per feature slab) at the
      final grid step.

HBM traffic per build: R x F uint8 + (2 * grad itemsize + 4) bytes/row
of g/h/ni + the [N, F, B, 2] output — nothing else. No prologue
materialisation, no per-slab re-stream of row-sized state (chunked
slabs re-read only g/h/ni). The g/h itemsize is DTYPE-PARAMETERIZED
(ISSUE 14): f32 gradients stream 12 B/row of g/h/ni; quantized int16
streams 8 B/row and int8 6 B/row — the pallas_fits budget and the
CostEstimate read the actual operand dtypes, never a hard-coded 12.

INTEGER ACCUMULATION (cfg.grad_dtype, docs/PERF.md "Quantized
gradients"): when g/h arrive QUANTIZED (int8/int16 from
ops/grad.quantize_gradients), the whole kernel runs in the integer
domain — A and the bin one-hot are built in the gradient dtype, the
dot_general accumulates with preferred_element_type=int32 into an int32
VMEM scratch (s8 x s8 -> s32 is MXU-native), and the flushed output is
the RAW int32 histogram. Integer adds commute, so the result is
bitwise independent of tile order, feature chunking, sibling
subtraction, and shard merge order; the caller dequantizes exactly once
(hist * scale) after the last merge. The scratch/output itemsize is
unchanged (int32 == f32 at 4 B), so the VMEM budget arithmetic is
shared with the f32 path.

Two kernel forms (dispatch on the padded bin width, sweep-9/10 measured):
row-major (`_hist_kernel`, bins_pad >= 256) builds OH [T, F*Bp] with bins
on LANES; the transposed form (`_hist_kernel_t`, bins_pad <= 128) builds
OH [F*Bp, T] with bins on SUBLANES — x broadcasts along sublanes as cheap
row replication, ~1.5x the row-major form at 64 bins. Since round 6 the
64-bin layout is promoted to automatic dispatch: n_bins <= 64 pads to Bp
= 64 sublanes (half the OH footprint and half the MXU columns of the old
128-lane padding), which is what the bench's `value_64bin_optin` arm
measures.

Contract identical to ops/histogram.py: returns [n_nodes, F, n_bins, 2]
f32. Tests run this kernel in Pallas interpret mode on CPU
(tests/test_hist_pallas.py, tests/test_hist_fused.py); the real-chip path
is exercised by bench.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ddt_tpu.telemetry.annotations import traced_scope
from ddt_tpu.telemetry.costmodel import costed

LANE = 128

# VMEM working-set ceiling for auto-selection: the one-hot tile
# [tile_r, F*Bp] + the scratch accumulator AND its HBM-flush output block
# (both [2N, F*Bp] f32) + pipeline buffers must fit ~16 MB/core. 12 MB
# leaves headroom for Mosaic's double-buffered input windows.
_VMEM_BUDGET_BYTES = 12 * 1024 * 1024
_DEFAULT_TILE_R = 512
# The transposed kernel's default row tile: tiles 1024-2048 measure
# identically (~73 Mrows/s at 64 bins, min-of-8; sweep 10 A/B) and 512
# was never faster — 1024 keeps the VMEM working set modest.
_DEFAULT_TILE_R_T = 1024


def _default_tile_r(n_bins: int) -> int:
    """The row tile the dispatcher will actually run with: the transposed
    kernel (n_bins <= 128) uses the larger tile (sweep-10 A/B). The ONE
    home of this rule — pallas_fits/feature_chunks_for must size VMEM for
    the same tile the kernel allocates."""
    return _DEFAULT_TILE_R_T if _bins_pad(n_bins) <= LANE \
        else _DEFAULT_TILE_R


def _bins_pad(n_bins: int) -> int:
    """Padded one-hot width per feature. n_bins <= 64 pads to 64 SUBLANES
    (the promoted 64-bin layout — bins ride the transposed kernel's
    sublane axis, where 64 is tile-aligned for both bf16 and f32);
    n_bins <= 128 pads to one 128 tile and still routes transposed; wider
    bin counts pad to 256 LANES for the row-major kernel."""
    if n_bins <= 64:
        return 64
    if n_bins <= LANE:
        return LANE
    return max(2 * LANE, ((n_bins + LANE - 1) // LANE) * LANE)


def pallas_fits(
    n_nodes: int,
    n_features: int,
    n_bins: int,
    tile_r: int | None = None,
    input_bytes: int = 2,
    grad_bytes: int = 4,
    acc_bytes: int = 4,
) -> bool:
    """Whether the kernel's VMEM working set fits at this shape (the shape
    guard behind hist_impl='auto' — ops/histogram.resolve_hist_impl).
    tile_r=None sizes for the tile the dispatcher will actually run.

    The budget is computed from the ACTUAL operand itemsizes, never
    hard-coded f32 (ISSUE 14): `input_bytes` is the one-hot/A operand
    itemsize (2 bf16, 4 f32; 1/2 on the quantized int8/int16 path),
    `grad_bytes` the streamed g/h row itemsize (4 f32, 2 int16, 1 int8),
    `acc_bytes` the scratch/output accumulator itemsize (4 for both f32
    and the quantized path's int32 — asserted, not assumed)."""
    assert acc_bytes == 4, (
        "the VMEM accumulator is f32 or int32 — both 4 B; a new "
        "accumulator dtype must re-derive this budget")
    if tile_r is None:
        tile_r = _default_tile_r(n_bins)
    fbp = n_features * _bins_pad(n_bins)
    oh_bytes = tile_r * fbp * input_bytes
    # Streamed per-tile row operands (g, h, ni blocks) — tiny next to
    # the one-hot, but dtype-parameterized like everything else.
    row_bytes = tile_r * (2 * grad_bytes + 4)
    # Scratch accumulator + the output block it flushes into: both live
    # in VMEM for the whole kernel.
    acc_total = 2 * (2 * n_nodes * fbp * acc_bytes)
    return oh_bytes + row_bytes + acc_total <= _VMEM_BUDGET_BYTES


def _weighted_node_onehot(ni, g, h, n_nodes: int, input_dtype):
    """A [T, 2N]: node one-hot weighted by g then h, built on the VPU.
    ni = -1 (frozen / pad rows) matches no column — the masking prologue
    the old kernel needed is free here. Dtype-generic: on the quantized
    path g/h are int8/int16 and A stays in that dtype (the weights fit
    by the |q| <= qmax construction), so the dot runs integer."""
    tile_r = ni.shape[0]
    noh = ni[:, None] == jax.lax.broadcasted_iota(
        jnp.int32, (tile_r, n_nodes), 1)
    zero = jnp.zeros((), g.dtype)
    return jnp.concatenate(
        [jnp.where(noh, g[:, None], zero), jnp.where(noh, h[:, None], zero)],
        axis=1,
    ).astype(input_dtype)                                 # [T, 2N]


def _acc_dtype(input_dtype):
    """Accumulator dtype for an operand dtype: int32 on the quantized
    integer path (exact adds), f32 otherwise (the MXU's native form)."""
    return (jnp.int32 if jnp.issubdtype(jnp.dtype(input_dtype), jnp.integer)
            else jnp.float32)


def _hist_kernel(xb_ref, g_ref, h_ref, ni_ref, out_ref, acc_ref, *,
                 n_nodes: int, n_feat: int, bins_pad: int, input_dtype):
    """One row tile, row-major form: acc += A^T @ OH, all built in VMEM.

    xb_ref [TILE_R, F] uint8; g/h [1, TILE_R] f32; ni [1, TILE_R] i32;
    acc_ref [2N, F*Bp] f32 VMEM scratch (lives across the grid);
    out_ref same shape — written ONCE at the final grid step."""
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    x = xb_ref[:].astype(jnp.int32)                       # [T, F]
    tile_r = x.shape[0]
    A = _weighted_node_onehot(ni_ref[0, :], g_ref[0, :], h_ref[0, :],
                              n_nodes, input_dtype)
    bin_iota = jax.lax.broadcasted_iota(
        jnp.int32, (tile_r, bins_pad), 1
    )
    # Per-feature one-hot slabs, concatenated to [T, F * Bp]. The Python
    # loop unrolls at trace time (F is static).
    slabs = [
        (x[:, f][:, None] == bin_iota).astype(input_dtype)
        for f in range(n_feat)
    ]
    oh = jnp.concatenate(slabs, axis=1)                   # [T, F*Bp]

    acc_ref[:] += jax.lax.dot_general(
        A, oh,
        (((0,), (0,)), ((), ())),                         # contract rows
        preferred_element_type=_acc_dtype(input_dtype),
    )

    @pl.when(step == pl.num_programs(0) - 1)
    def _():
        out_ref[:] = acc_ref[:]                           # ONE HBM write


def _hist_kernel_t(xt_ref, g_ref, h_ref, ni_ref, out_ref, acc_ref, *,
                   n_nodes: int, n_feat: int, bins_pad: int, input_dtype):
    """TRANSPOSED row tile (bins_pad <= 128, i.e. n_bins <= 128):
    acc[F*Bp, 2N] += OH[F*Bp, T] @ A[T, 2N].

    Why a second form exists (experiments/hist_sweep9/10, measured v5e):
    the row-major kernel is bound by per-feature [T, 1] -> [T, Bp] LANE
    broadcasts (cost flat in Bp — shrinking bins bought nothing), while
    this form broadcasts x rows along SUBLANES ((bin_iota[Bp, 1] ==
    x[1, T])), which Mosaic executes as cheap row replication. At 64 bins
    it measures ~72 Mrows/s vs ~48 row-major, and the promoted Bp = 64
    sublane layout (n_bins <= 64) halves the OH footprint again. At
    Bp = 256 the transposed form loses its edge (more sublane tiles per
    slab), so the row-major kernel keeps the 255-bin contract.
    """
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    xt = xt_ref[:].astype(jnp.int32)                      # [F, T]
    tile_r = xt.shape[1]
    A = _weighted_node_onehot(ni_ref[0, :], g_ref[0, :], h_ref[0, :],
                              n_nodes, input_dtype)
    bin_iota = jax.lax.broadcasted_iota(jnp.int32, (bins_pad, tile_r), 0)
    slabs = [
        (xt[f, :][None, :] == bin_iota).astype(input_dtype)   # [Bp, T]
        for f in range(n_feat)
    ]
    oh = jnp.concatenate(slabs, axis=0)                   # [F*Bp, T]
    acc_ref[:] += jax.lax.dot_general(
        oh, A,
        (((1,), (0,)), ((), ())),                         # contract rows
        preferred_element_type=_acc_dtype(input_dtype),
    )

    @pl.when(step == pl.num_programs(0) - 1)
    def _():
        out_ref[:] = acc_ref[:]                           # ONE HBM write


def feature_chunks_for(n_nodes: int, n_features: int, n_bins: int,
                       tile_r: int | None = None,
                       input_bytes: int = 2,
                       grad_bytes: int = 4) -> int | None:
    """Smallest number of feature chunks whose per-chunk working set fits
    the kernel's VMEM budget, or None if even one feature does not fit
    (then the caller must use the matmul path). input_bytes is the one-hot
    operand's itemsize (2 bfloat16, 4 float32, 1/2 quantized int8/int16);
    grad_bytes the g/h row itemsize (see pallas_fits)."""
    if tile_r is None:
        tile_r = _default_tile_r(n_bins)
    for k in range(1, n_features + 1):
        if pallas_fits(n_nodes, -(-n_features // k), n_bins, tile_r,
                       input_bytes, grad_bytes):
            return k
    return None


def build_histograms_pallas(
    Xb: jax.Array,
    g: jax.Array,
    h: jax.Array,
    node_index: jax.Array,
    n_nodes: int,
    n_bins: int,
    tile_r: int | None = None,
    interpret: bool | None = None,
    input_dtype=jnp.bfloat16,
) -> jax.Array:
    """Pallas HistogramBuilder: [n_nodes, F, n_bins, 2] float32 — or RAW
    int32 when g/h arrive quantized (int8/int16; the caller dequantizes
    once after the last merge — see the module docstring's integer
    section).

    interpret=None auto-selects Pallas interpreter mode off-TPU (CPU tests
    exercise the identical kernel logic; the compiled path needs a real
    chip). input_dtype is the A/one-hot operand dtype: bfloat16 rides the MXU
    at full rate; float32 buys exact accumulation at reduced rate (same knob
    as the matmul path — cfg.matmul_input_dtype). Quantized g/h OVERRIDE it
    with their own dtype (s8/s16 operands, s32 accumulation — exact).

    Shapes whose VMEM working set overflows the budget (deep levels:
    n_nodes >= 32 at 255 bins) are feature-CHUNKED: one pallas_call per
    column slab, outputs concatenated — exact (columns are independent),
    and since the rewrite a slab re-reads only its own Xb columns plus
    2 * grad-itemsize + 4 bytes/row of g/h/ni, so chunking stays far
    above the matmul fallback.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if tile_r is None:
        tile_r = _default_tile_r(n_bins)
    quant = jnp.issubdtype(jnp.dtype(g.dtype), jnp.integer)
    dt = jnp.dtype(g.dtype) if quant else jnp.dtype(input_dtype)
    F = Xb.shape[1]
    grad_bytes = dt.itemsize if quant else 4
    k = feature_chunks_for(n_nodes, F, n_bins, tile_r, dt.itemsize,
                           grad_bytes)
    if k is None:
        raise ValueError(
            f"histogram shape (n_nodes={n_nodes}, n_bins={n_bins}) exceeds "
            "the Pallas VMEM budget even at one feature per call; use the "
            "matmul implementation"
        )
    return _build_histograms_pallas(
        Xb, g, h, node_index, n_nodes, n_bins, tile_r, interpret, dt, k,
    )


@costed("hist_pallas", phase="hist")
@functools.partial(
    jax.jit,
    static_argnames=("n_nodes", "n_bins", "tile_r", "interpret",
                     "input_dtype", "n_chunks"),
)
def _build_histograms_pallas(
    Xb: jax.Array,          # uint8 [R, F]
    g: jax.Array,           # float32 [R]
    h: jax.Array,           # float32 [R]
    node_index: jax.Array,  # int32 [R], -1 = frozen
    n_nodes: int,
    n_bins: int,
    tile_r: int = _DEFAULT_TILE_R,
    interpret: bool = False,
    input_dtype=jnp.bfloat16,
    n_chunks: int = 1,      # feature slabs (one pallas_call each); slabs
                            # share the streamed g/h/ni rows
) -> jax.Array:
    R, F = Xb.shape
    bins_pad = _bins_pad(n_bins)
    quant = jnp.issubdtype(jnp.dtype(input_dtype), jnp.integer)
    acc_dtype = _acc_dtype(input_dtype)

    # Stream prologue (XLA, cheap): pad rows to a tile multiple and fold
    # the per-row vectors to [n_tiles, tile_r] blocks. Pad rows carry
    # ni = -1, so they match no node column in-kernel — no weighted
    # one-hot, no int32 input copy, nothing row-sized materialises.
    # Quantized g/h keep their narrow dtype on the stream (the whole
    # point: 1-2 bytes/row instead of 4 per channel).
    n_tiles = -(-R // tile_r)
    pad = n_tiles * tile_r - R
    Xp = Xb
    gz = g if quant else g.astype(jnp.float32)
    hz = h if quant else h.astype(jnp.float32)
    ni = node_index.astype(jnp.int32)
    if pad:
        Xp = jnp.pad(Xp, ((0, pad), (0, 0)))
        gz = jnp.pad(gz, (0, pad))
        hz = jnp.pad(hz, (0, pad))
        ni = jnp.pad(ni, (0, pad), constant_values=-1)
    g2 = gz.reshape(n_tiles, tile_r)
    h2 = hz.reshape(n_tiles, tile_r)
    ni2 = ni.reshape(n_tiles, tile_r)

    row_spec = pl.BlockSpec((1, tile_r), lambda i: (i, 0),
                            memory_space=pltpu.VMEM)

    def slab(Xs):
        Fs = Xs.shape[1]
        # bytes_accessed from the ACTUAL operand dtypes: uint8 Xb, g/h at
        # their streamed itemsize (4 f32, 2 int16, 1 int8), int32 ni, and
        # the 4 B/entry (f32 or int32) output — never a hard-coded 12.
        row_bytes = 2 * jnp.dtype(gz.dtype).itemsize + 4
        cost = pl.CostEstimate(
            flops=2 * 2 * n_nodes * Fs * bins_pad * n_tiles * tile_r,
            bytes_accessed=R * Fs + R * row_bytes
            + 2 * n_nodes * Fs * bins_pad * 4,
            transcendentals=0,
        )
        if bins_pad <= LANE:
            # Transposed kernel (n_bins <= 128): sublane-broadcast one-hot
            # build — ~1.5x the row-major form at 64 bins (sweep 10).
            with traced_scope("hist:stream"):
                out = pl.pallas_call(
                    functools.partial(_hist_kernel_t, n_nodes=n_nodes,
                                      n_feat=Fs, bins_pad=bins_pad,
                                      input_dtype=input_dtype),
                    grid=(n_tiles,),
                    in_specs=[
                        pl.BlockSpec((Fs, tile_r), lambda i: (0, i),
                                     memory_space=pltpu.VMEM),
                        row_spec, row_spec, row_spec,
                    ],
                    out_specs=pl.BlockSpec(
                        (Fs * bins_pad, 2 * n_nodes), lambda i: (0, 0),
                        memory_space=pltpu.VMEM,
                    ),
                    out_shape=jax.ShapeDtypeStruct(
                        (Fs * bins_pad, 2 * n_nodes), acc_dtype),
                    scratch_shapes=[
                        pltpu.VMEM((Fs * bins_pad, 2 * n_nodes),
                                   acc_dtype),
                    ],
                    cost_estimate=cost,
                    interpret=interpret,
                )(Xs.T, g2, h2, ni2)
            with traced_scope("hist:flush"):
                # [Fs*Bp, 2N] -> [N, Fs, B, 2]
                out = out.reshape(Fs, bins_pad, 2, n_nodes)[:, :n_bins]
                return out.transpose(3, 0, 1, 2)
        with traced_scope("hist:stream"):
            out = pl.pallas_call(
                functools.partial(_hist_kernel, n_nodes=n_nodes, n_feat=Fs,
                                  bins_pad=bins_pad,
                                  input_dtype=input_dtype),
                grid=(n_tiles,),
                in_specs=[
                    pl.BlockSpec(
                        (tile_r, Fs), lambda i: (i, 0),
                        memory_space=pltpu.VMEM,
                    ),
                    row_spec, row_spec, row_spec,
                ],
                out_specs=pl.BlockSpec(
                    (2 * n_nodes, Fs * bins_pad), lambda i: (0, 0),
                    memory_space=pltpu.VMEM,
                ),
                out_shape=jax.ShapeDtypeStruct((2 * n_nodes, Fs * bins_pad),
                                               acc_dtype),
                scratch_shapes=[
                    pltpu.VMEM((2 * n_nodes, Fs * bins_pad), acc_dtype),
                ],
                cost_estimate=cost,
                interpret=interpret,
            )(Xs, g2, h2, ni2)
        with traced_scope("hist:flush"):
            # [2N, Fs*Bp] -> [N, Fs, B, 2]
            out = out.reshape(2, n_nodes, Fs, bins_pad)[..., :n_bins]
            return out.transpose(1, 2, 3, 0)

    if n_chunks == 1:
        return slab(Xp)
    fc = -(-F // n_chunks)
    return jnp.concatenate(
        [slab(Xp[:, i:i + fc]) for i in range(0, F, fc)], axis=1)
