"""Pallas TPU kernel for HistogramBuilder — the hot loop, hand-tiled.

Why this kernel exists (measured on TPU v5e, 1M rows x 28 feat x 255 bins):
the pure-XLA one-hot-matmul path materialises the [rows, F*B] bin one-hot in
HBM — ~29 GB of write+read traffic per build — and runs HBM-bound at
~26 M-rows/s with the MXU nearly idle (time is independent of node count).
This kernel builds the one-hot TILE-BY-TILE IN VMEM, feeds it straight to the
MXU, and never lets it touch HBM. The only HBM traffic is the binned input
itself (R x F uint8) plus tiny per-row vectors — about 500x less.

Shape strategy per grid step (one tile of TILE_R rows):
    A   [TILE_R, 2N]   bf16: node one-hot weighted by g (cols 0..N-1) and by
                       h (cols N..2N-1) — built on the VPU from ni/g/h.
    OH  [TILE_R, F*Bp] bf16: per-feature bin one-hot, Bp = 256-padded lanes
                       per feature (2 MXU lane tiles), built on the VPU.
    out [2N, F*Bp]     f32: += A^T @ OH — ONE dot_general per tile on the
                       MXU, f32 accumulation via preferred_element_type.
The output block is revisited by every grid step (index_map -> (0, 0)), so it
lives in VMEM for the whole kernel and is zero-initialised at step 0 — the
classic sequential-grid accumulation pattern.

VMEM budget at TILE_R=512, F=28, N<=32: OH 512x7168xbf16 = 7.3 MB,
acc 64x7168xf32 = 1.8 MB, inputs < 0.1 MB — comfortably inside 16 MB.

Contract identical to ops/histogram.py: returns [n_nodes, F, n_bins, 2] f32;
rows with node_index < 0 are masked out (done in the XLA prologue). Tests run
this kernel in Pallas interpret mode on CPU (tests/test_hist_pallas.py);
the real-chip path is exercised by bench.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ddt_tpu.telemetry.costmodel import costed

LANE = 128

# VMEM working-set ceiling for auto-selection: the one-hot tile
# [tile_r, F*Bp] + the revisited accumulator [2N, F*Bp] f32 + pipeline
# buffers must fit ~16 MB/core. 12 MB leaves headroom for Mosaic's
# double-buffered input windows.
_VMEM_BUDGET_BYTES = 12 * 1024 * 1024
_DEFAULT_TILE_R = 512
# The transposed kernel's default row tile: tiles 1024-2048 measure
# identically (~73 Mrows/s at 64 bins, min-of-8; sweep 10 A/B) and 512
# was never faster — 1024 keeps the VMEM working set modest.
_DEFAULT_TILE_R_T = 1024


def _default_tile_r(n_bins: int) -> int:
    """The row tile the dispatcher will actually run with: the transposed
    kernel (n_bins <= 128) uses the larger tile (sweep-10 A/B). The ONE
    home of this rule — pallas_fits/feature_chunks_for must size VMEM for
    the same tile the kernel allocates."""
    return _DEFAULT_TILE_R_T if _bins_pad(n_bins) <= LANE \
        else _DEFAULT_TILE_R


def _bins_pad(n_bins: int) -> int:
    """Padded one-hot lanes per feature. n_bins <= 128 pads to ONE lane
    tile and routes to the TRANSPOSED kernel (see _hist_kernel_t);
    wider bin counts pad to 256 for the row-major kernel."""
    if n_bins <= LANE:
        return LANE
    return max(2 * LANE, ((n_bins + LANE - 1) // LANE) * LANE)


def pallas_fits(
    n_nodes: int,
    n_features: int,
    n_bins: int,
    tile_r: int | None = None,
    input_bytes: int = 2,
) -> bool:
    """Whether the kernel's VMEM working set fits at this shape (the shape
    guard behind hist_impl='auto' — ops/histogram.resolve_hist_impl).
    tile_r=None sizes for the tile the dispatcher will actually run."""
    if tile_r is None:
        tile_r = _default_tile_r(n_bins)
    fbp = n_features * _bins_pad(n_bins)
    oh_bytes = tile_r * fbp * input_bytes
    acc_bytes = 2 * n_nodes * fbp * 4
    return oh_bytes + acc_bytes <= _VMEM_BUDGET_BYTES


def _hist_kernel(xb_ref, a_ref, out_ref, *, n_feat: int, bins_pad: int,
                 input_dtype):
    """One row tile: out += A^T @ OH with OH built in VMEM.

    xb_ref: [TILE_R, F] int32 (bin indices), a_ref: [TILE_R, 2N] bf16,
    out_ref: [2N, F * bins_pad] f32 (revisited accumulator block).
    """
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    x = xb_ref[:]                                         # [T, F] int32
    tile_r = x.shape[0]
    bin_iota = jax.lax.broadcasted_iota(
        jnp.int32, (tile_r, bins_pad), 1
    )
    # Per-feature one-hot slabs, concatenated to [T, F * Bp]. The Python
    # loop unrolls at trace time (F is static).
    slabs = [
        (x[:, f][:, None] == bin_iota).astype(input_dtype)
        for f in range(n_feat)
    ]
    oh = jnp.concatenate(slabs, axis=1)                   # [T, F*Bp]

    out_ref[:] += jax.lax.dot_general(
        a_ref[:], oh,
        (((0,), (0,)), ((), ())),                         # contract rows
        preferred_element_type=jnp.float32,
    )


def _hist_kernel_t(xt_ref, a_ref, out_ref, *, n_feat: int, bins_pad: int,
                   input_dtype):
    """TRANSPOSED row tile (used when bins_pad == 128, i.e. n_bins <= 128):
    out[F*Bp, 2N] += OH[F*Bp, T] @ A[T, 2N].

    Why a second form exists (experiments/hist_sweep9/10, measured v5e):
    the row-major kernel is bound by per-feature [T, 1] -> [T, Bp] LANE
    broadcasts (cost flat in Bp — shrinking bins bought nothing), while
    this form broadcasts x rows along SUBLANES ((bin_iota[Bp, 1] ==
    x[1, T])), which Mosaic executes as cheap row replication. At 64 bins
    it measures ~72 Mrows/s vs ~48 row-major. At Bp = 256 the transposed
    form loses its edge (more sublane tiles per slab), so the row-major
    kernel keeps the 255-bin contract.
    """
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    xt = xt_ref[:]                                        # [F, T]
    tile_r = xt.shape[1]
    bin_iota = jax.lax.broadcasted_iota(jnp.int32, (bins_pad, tile_r), 0)
    slabs = [
        (xt[f, :][None, :] == bin_iota).astype(input_dtype)   # [Bp, T]
        for f in range(n_feat)
    ]
    oh = jnp.concatenate(slabs, axis=0)                   # [F*Bp, T]
    out_ref[:] += jax.lax.dot_general(
        oh, a_ref[:],
        (((1,), (0,)), ((), ())),                         # contract rows
        preferred_element_type=jnp.float32,
    )


def feature_chunks_for(n_nodes: int, n_features: int, n_bins: int,
                       tile_r: int | None = None,
                       input_bytes: int = 2) -> int | None:
    """Smallest number of feature chunks whose per-chunk working set fits
    the kernel's VMEM budget, or None if even one feature does not fit
    (then the caller must use the matmul path). input_bytes is the one-hot
    operand's itemsize (2 for bfloat16, 4 for float32)."""
    if tile_r is None:
        tile_r = _default_tile_r(n_bins)
    for k in range(1, n_features + 1):
        if pallas_fits(n_nodes, -(-n_features // k), n_bins, tile_r,
                       input_bytes):
            return k
    return None


def build_histograms_pallas(
    Xb: jax.Array,
    g: jax.Array,
    h: jax.Array,
    node_index: jax.Array,
    n_nodes: int,
    n_bins: int,
    tile_r: int | None = None,
    interpret: bool | None = None,
    input_dtype=jnp.bfloat16,
) -> jax.Array:
    """Pallas HistogramBuilder: [n_nodes, F, n_bins, 2] float32.

    interpret=None auto-selects Pallas interpreter mode off-TPU (CPU tests
    exercise the identical kernel logic; the compiled path needs a real
    chip). input_dtype is the A/one-hot operand dtype: bfloat16 rides the MXU
    at full rate; float32 buys exact accumulation at reduced rate (same knob
    as the matmul path — cfg.matmul_input_dtype).

    Shapes whose [2N, F*Bp] accumulator overflows the VMEM budget (deep
    levels: n_nodes >= 64 at 255 bins) are feature-CHUNKED: one pallas_call
    per column slab, outputs concatenated — exact (columns are independent)
    and still ~2x the HBM-bound matmul fallback per slab.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if tile_r is None:
        tile_r = _default_tile_r(n_bins)
    dt = jnp.dtype(input_dtype)
    F = Xb.shape[1]
    k = feature_chunks_for(n_nodes, F, n_bins, tile_r, dt.itemsize)
    if k is None:
        raise ValueError(
            f"histogram shape (n_nodes={n_nodes}, n_bins={n_bins}) exceeds "
            "the Pallas VMEM budget even at one feature per call; use the "
            "matmul implementation"
        )
    return _build_histograms_pallas(
        Xb, g, h, node_index, n_nodes, n_bins, tile_r, interpret, dt, k,
    )


@costed("hist_pallas", phase="hist")
@functools.partial(
    jax.jit,
    static_argnames=("n_nodes", "n_bins", "tile_r", "interpret",
                     "input_dtype", "n_chunks"),
)
def _build_histograms_pallas(
    Xb: jax.Array,          # uint8 [R, F]
    g: jax.Array,           # float32 [R]
    h: jax.Array,           # float32 [R]
    node_index: jax.Array,  # int32 [R], -1 = frozen
    n_nodes: int,
    n_bins: int,
    tile_r: int = _DEFAULT_TILE_R,
    interpret: bool = False,
    input_dtype=jnp.bfloat16,
    n_chunks: int = 1,      # feature slabs (one pallas_call each); the
                            # prologue below is shared across slabs
) -> jax.Array:
    R, F = Xb.shape
    bins_pad = _bins_pad(n_bins)

    # Prologue (XLA, fused & cheap): mask frozen rows, build the weighted
    # node one-hot A, pad rows to a tile multiple (padded rows carry A=0).
    active = node_index >= 0
    idx = jnp.where(active, node_index, 0).astype(jnp.int32)
    gz = jnp.where(active, g, 0.0).astype(jnp.float32)
    hz = jnp.where(active, h, 0.0).astype(jnp.float32)
    node_oh = jax.nn.one_hot(idx, n_nodes, dtype=jnp.float32)   # [R, N]
    A = jnp.concatenate(
        [node_oh * gz[:, None], node_oh * hz[:, None]], axis=1
    ).astype(input_dtype)                                       # [R, 2N]
    Xi = Xb.astype(jnp.int32)

    n_tiles = -(-R // tile_r)
    pad = n_tiles * tile_r - R
    if pad:
        Xi = jnp.pad(Xi, ((0, pad), (0, 0)))
        A = jnp.pad(A, ((0, pad), (0, 0)))

    def slab(Xs):
        Fs = Xs.shape[1]
        cost = pl.CostEstimate(
            flops=2 * 2 * n_nodes * Fs * bins_pad * n_tiles * tile_r,
            bytes_accessed=R * Fs * 4 + R * 4 * n_nodes
            + 2 * n_nodes * Fs * bins_pad * 4,
            transcendentals=0,
        )
        if bins_pad <= LANE:
            # Transposed kernel (n_bins <= 128): sublane-broadcast one-hot
            # build — ~1.5x the row-major form at 64 bins (sweep 10).
            out = pl.pallas_call(
                functools.partial(_hist_kernel_t, n_feat=Fs,
                                  bins_pad=bins_pad,
                                  input_dtype=input_dtype),
                grid=(n_tiles,),
                in_specs=[
                    pl.BlockSpec((Fs, tile_r), lambda i: (0, i),
                                 memory_space=pltpu.VMEM),
                    pl.BlockSpec((tile_r, 2 * n_nodes), lambda i: (i, 0),
                                 memory_space=pltpu.VMEM),
                ],
                out_specs=pl.BlockSpec(
                    (Fs * bins_pad, 2 * n_nodes), lambda i: (0, 0),
                    memory_space=pltpu.VMEM,
                ),
                out_shape=jax.ShapeDtypeStruct(
                    (Fs * bins_pad, 2 * n_nodes), jnp.float32),
                cost_estimate=cost,
                interpret=interpret,
            )(Xs.T, A)
            # [Fs*Bp, 2N] -> [N, Fs, B, 2]
            out = out.reshape(Fs, bins_pad, 2, n_nodes)[:, :n_bins]
            return out.transpose(3, 0, 1, 2)
        out = pl.pallas_call(
            functools.partial(_hist_kernel, n_feat=Fs, bins_pad=bins_pad,
                              input_dtype=input_dtype),
            grid=(n_tiles,),
            in_specs=[
                pl.BlockSpec(
                    (tile_r, Fs), lambda i: (i, 0), memory_space=pltpu.VMEM
                ),
                pl.BlockSpec(
                    (tile_r, 2 * n_nodes), lambda i: (i, 0),
                    memory_space=pltpu.VMEM,
                ),
            ],
            out_specs=pl.BlockSpec(
                (2 * n_nodes, Fs * bins_pad), lambda i: (0, 0),
                memory_space=pltpu.VMEM,
            ),
            out_shape=jax.ShapeDtypeStruct((2 * n_nodes, Fs * bins_pad),
                                           jnp.float32),
            cost_estimate=cost,
            interpret=interpret,
        )(Xs, A)
        # [2N, Fs*Bp] -> [N, Fs, B, 2]
        out = out.reshape(2, n_nodes, Fs, bins_pad)[..., :n_bins]
        return out.transpose(1, 2, 3, 0)

    if n_chunks == 1:
        return slab(Xi)
    fc = -(-F // n_chunks)
    return jnp.concatenate(
        [slab(Xi[:, i:i + fc]) for i in range(0, F, fc)], axis=1)
