"""Loss gradients/hessians as jitted elementwise XLA ops.

Layer L3 of SURVEY.md §1 ("Gradient computer"): per-boosting-round grad/hess
from the loss — logloss (binary), mse (regression), softmax (one-vs-all
multiclass histograms, the Covertype config [BASELINE]). NumPy twin:
ddt_tpu/reference/numpy_trainer.grad_hess — keep formulas in sync; the parity
test is tests/test_ops.py::test_grad_hess_matches_oracle.

Elementwise, so XLA fuses these into whatever consumes them; no Pallas needed.
Internally computed in float32 (matching the NumPy oracle's effective
precision for these formula shapes) and returned as float32.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ddt_tpu.telemetry.annotations import op_scope


def base_score(y: jax.Array, loss: str) -> jax.Array:
    """Raw-score init: log-odds for logloss, mean for mse, 0 for softmax."""
    if loss == "logloss":
        p = jnp.clip(jnp.mean(y.astype(jnp.float32)), 1e-6, 1 - 1e-6)
        return jnp.log(p / (1 - p))
    if loss == "mse":
        return jnp.mean(y.astype(jnp.float32))
    return jnp.float32(0.0)


@op_scope("loss")
def mean_loss(
    pred_raw: jax.Array,
    y: jax.Array,
    valid: jax.Array,
    loss: str,
    allreduce=lambda x: x,
) -> jax.Array:
    """Mean training loss over valid rows — the single home of the loss
    formulas shared by TPUDevice._loss_fn and the fused grow_rounds path
    (their reported train_loss must stay numerically identical). `allreduce`
    is identity on one shard, psum over the row axes inside shard_map."""
    valid = valid.astype(jnp.float32)
    # `valid` carries instance WEIGHTS (1/0 without sample_weight), so the
    # denominator is a weight sum in (0, inf) — clamp only the exact-zero
    # case, not sums below 1 (a >=1 clamp silently halves the reported
    # loss for fractional-weight datasets).
    n = jnp.maximum(allreduce(valid.sum()), 1e-12)
    if loss == "logloss":
        yf = y.astype(jnp.float32)
        # Numerically stable logistic loss: log(1+e^-|x|)+max(x,0)-x*y
        per = jnp.logaddexp(0.0, pred_raw) - pred_raw * yf
        return allreduce(jnp.sum(per * valid)) / n
    if loss == "mse":
        return allreduce(jnp.sum(jnp.square(pred_raw - y) * valid)) / n
    if loss == "softmax":
        logp = jax.nn.log_softmax(pred_raw, axis=1)
        picked = jnp.take_along_axis(
            logp, y.astype(jnp.int32)[:, None], axis=1
        )[:, 0]
        return -allreduce(jnp.sum(picked * valid)) / n
    raise ValueError(loss)


@op_scope("grad")
def grad_hess(
    pred_raw: jax.Array, y: jax.Array, loss: str
) -> tuple[jax.Array, jax.Array]:
    """(g, h) of the loss wrt raw scores. float32, [R] or [R, C] for softmax."""
    if loss == "logloss":
        p = jax.nn.sigmoid(pred_raw.astype(jnp.float32))
        return p - y.astype(jnp.float32), p * (1.0 - p)
    if loss == "mse":
        return (
            pred_raw.astype(jnp.float32) - y.astype(jnp.float32),
            jnp.ones_like(pred_raw, jnp.float32),
        )
    if loss == "softmax":
        p = jax.nn.softmax(pred_raw.astype(jnp.float32), axis=1)
        onehot = jax.nn.one_hot(y, pred_raw.shape[1], dtype=jnp.float32)
        return p - onehot, p * (1.0 - p)
    raise ValueError(loss)
