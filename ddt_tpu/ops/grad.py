"""Loss gradients/hessians as jitted elementwise XLA ops.

Layer L3 of SURVEY.md §1 ("Gradient computer"): per-boosting-round grad/hess
from the loss — logloss (binary), mse (regression), softmax (one-vs-all
multiclass histograms, the Covertype config [BASELINE]). NumPy twin:
ddt_tpu/reference/numpy_trainer.grad_hess — keep formulas in sync; the parity
test is tests/test_ops.py::test_grad_hess_matches_oracle.

Elementwise, so XLA fuses these into whatever consumes them; no Pallas needed.
Internally computed in float32 (matching the NumPy oracle's effective
precision for these formula shapes) and returned as float32.

QUANTIZED GRADIENTS (cfg.grad_dtype, docs/PERF.md "Quantized gradients"):
this module is also the one home of the per-round g/h discretization the
fixed-point-training line (arXiv:1812.08295) and bandwidth-first GPU
boosting (arXiv:1706.08359) motivate. Once per (tree, output dim) the
f32 gradients round onto one shared grid:

    scale = max(max|g| / qmax, 2^ceil(log2(sum|g| / 2^30)))
    q     = clip(floor(g / scale + u), -qmax, qmax)     int8 / int16

with `u` a per-(seed, tree, GLOBAL row) counter-hash uniform in [0, 1)
(ops/sampling.uniform_jax/np — SEEDED stochastic rounding: the estimator
is unbiased, E[q * scale] = g, and the draw is a pure function of its
key, so chaos-harness retries and checkpoint resumes replay the exact
bits; it can never differ per attempt). The scale terms:

- max|g| / qmax keeps every row representable (|q| <= qmax by
  construction; the clip is a no-op belt). It is taken EXACTLY — not
  snapped to a power of two — so the full qmax range is always live
  (a snap-up would cost as much as one effective bit, measurably
  moving deep-node split agreement); the term is still bit-identical
  across every trainer path because the max reduces exactly and the
  f32 divide is IEEE-deterministic.
- sum|g| / 2^30 caps the TOTAL quantized mass so every int32 histogram
  accumulator, cross-chunk host accumulation, and cross-shard integer
  merge is overflow-free BY CONSTRUCTION: floor(x + u) can overshoot
  |x| by at most one grid step per row, so the hard worst case is
  sum|q| <= sum|g|/scale + n_rows <= 2^30 + n_rows, which stays under
  INT32_MAX (2^31 - 1) for any n_rows < 2^30 — and GRAD_ROW_LIMIT
  enforces exactly that bound at quantization time (trace-time static
  on the fused path, a loud host check on the streamed path), so no
  DATA-dependent runtime overflow checks are needed. THIS term snaps
  up to a power of two (frexp/ldexp, bit-identical between the jax
  and numpy twins): f32 sums can differ by chunk/shard order ULPs
  between paths, and the snap absorbs them (it engages only when the
  mass term dominates — huge-row regimes).

Exact-grid models (the structure-identity contract tests): pin the
channel's max to qmax * 2^k with every value an integer multiple of
2^k — the scale is then exactly 2^k, and quantize + dequantize are
both exact (u < 1 strictly, so floor(int + u) never rounds; the
power-of-two multiply is lossless for integer sums below 2^24).

Downstream, histograms/node totals/leaf sums accumulate the INTEGER q's
(ops/histogram.py int32 paths) and dequantize exactly once after the
merge — integer sums commute, so sibling subtraction and N-way shard or
chunk merges are bit-exact where the f32 path was ULP-tolerant.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ddt_tpu.telemetry.annotations import op_scope

#: cfg.grad_dtype values (config.py validates; "f32" = quantization off).
GRAD_DTYPES = ("f32", "int16", "int8")
#: Symmetric quantized range per dtype (the -qmax..qmax grid; the most
#: negative two's-complement value is deliberately unused).
GRAD_QMAX = {"int16": 32767, "int8": 127}
#: Bytes per quantized g (or h) value — the effective-bytes models in
#: telemetry/counters.py read this (one home).
GRAD_ITEMSIZE = {"f32": 4, "int16": 2, "int8": 1}
#: int32 headroom: scale is floored so the GLOBAL sum of |q| cannot
#: exceed this PLUS one stochastic-rounding step per row — every
#: integer accumulator/merge in the pipeline is overflow-free by
#: construction given GRAD_ROW_LIMIT (see module docstring).
GRAD_SUM_CAP = 1 << 30
#: Global-row ceiling for the overflow proof: sum|q| <= GRAD_SUM_CAP +
#: n_rows < 2^31 - 1 requires n_rows < 2^30 (~1.07B rows — above the
#: ISSUE 14 design envelope). quantize_gradients asserts it at trace
#: time; the streamed scale pass checks it loudly on host.
GRAD_ROW_LIMIT = 1 << 30
# Per-channel seed salts for the stochastic-rounding draw: g and h (and
# the bagging mask, which salts nothing) must not share rounding bits.
_G_SALT = 0x67AD5C01
_H_SALT = 0x48E55CA3


def grad_quant_dtype(grad_dtype: str):
    """jnp dtype for a quantized-gradient mode (validates the name)."""
    if grad_dtype not in GRAD_QMAX:
        raise ValueError(
            f"grad_dtype must be one of {GRAD_DTYPES[1:]} here, got "
            f"{grad_dtype!r}")
    return jnp.int8 if grad_dtype == "int8" else jnp.int16


def base_score(y: jax.Array, loss: str) -> jax.Array:
    """Raw-score init: log-odds for logloss, mean for mse, 0 for softmax."""
    if loss == "logloss":
        p = jnp.clip(jnp.mean(y.astype(jnp.float32)), 1e-6, 1 - 1e-6)
        return jnp.log(p / (1 - p))
    if loss == "mse":
        return jnp.mean(y.astype(jnp.float32))
    return jnp.float32(0.0)


@op_scope("loss")
def mean_loss(
    pred_raw: jax.Array,
    y: jax.Array,
    valid: jax.Array,
    loss: str,
    allreduce=lambda x: x,
) -> jax.Array:
    """Mean training loss over valid rows — the single home of the loss
    formulas shared by TPUDevice._loss_fn and the fused grow_rounds path
    (their reported train_loss must stay numerically identical). `allreduce`
    is identity on one shard, psum over the row axes inside shard_map."""
    valid = valid.astype(jnp.float32)
    # `valid` carries instance WEIGHTS (1/0 without sample_weight), so the
    # denominator is a weight sum in (0, inf) — clamp only the exact-zero
    # case, not sums below 1 (a >=1 clamp silently halves the reported
    # loss for fractional-weight datasets).
    n = jnp.maximum(allreduce(valid.sum()), 1e-12)
    if loss == "logloss":
        yf = y.astype(jnp.float32)
        # Numerically stable logistic loss: log(1+e^-|x|)+max(x,0)-x*y
        per = jnp.logaddexp(0.0, pred_raw) - pred_raw * yf
        return allreduce(jnp.sum(per * valid)) / n
    if loss == "mse":
        return allreduce(jnp.sum(jnp.square(pred_raw - y) * valid)) / n
    if loss == "softmax":
        logp = jax.nn.log_softmax(pred_raw, axis=1)
        picked = jnp.take_along_axis(
            logp, y.astype(jnp.int32)[:, None], axis=1
        )[:, 0]
        return -allreduce(jnp.sum(picked * valid)) / n
    raise ValueError(loss)


@op_scope("grad")
def grad_hess(
    pred_raw: jax.Array, y: jax.Array, loss: str
) -> tuple[jax.Array, jax.Array]:
    """(g, h) of the loss wrt raw scores. float32, [R] or [R, C] for softmax."""
    if loss == "logloss":
        p = jax.nn.sigmoid(pred_raw.astype(jnp.float32))
        return p - y.astype(jnp.float32), p * (1.0 - p)
    if loss == "mse":
        return (
            pred_raw.astype(jnp.float32) - y.astype(jnp.float32),
            jnp.ones_like(pred_raw, jnp.float32),
        )
    if loss == "softmax":
        p = jax.nn.softmax(pred_raw.astype(jnp.float32), axis=1)
        onehot = jax.nn.one_hot(y, pred_raw.shape[1], dtype=jnp.float32)
        return p - onehot, p * (1.0 - p)
    raise ValueError(loss)


@op_scope("leaf")
def leaf_gh_sums(idx, active, g, h, n_last: int) -> jax.Array:
    """[n_last, 2] per-leaf (G, H) sums via the one-hot contraction —
    the ONE home of ops/grow's final level and ops/stream's leaf pass
    (four call-site twins before this existed). One-hot matmul rather
    than segment_sum: the scatter path costs ~2x20 ms at 1M rows on
    TPU, the single [n, R]@[R, 2] matmul ~7 ms. Dtype-dispatched like
    the histogram impls: f32 operands contract on the MXU at HIGHEST
    precision (summation order differs from the CPU twin's row-order
    adds by ULPs only — leaf VALUES are tolerance-compared everywhere);
    integer (quantized-gradient) operands contract with an int32
    accumulator — exact, order-invariant, the caller dequantizes once
    after its collective."""
    if jnp.issubdtype(g.dtype, jnp.integer):
        zero = jnp.zeros((), g.dtype)
        ga = jnp.where(active, g, zero)
        ha = jnp.where(active, h, zero)
        leaf_oh = (
            idx[:, None] == jnp.arange(n_last, dtype=jnp.int32)[None, :]
        ).astype(g.dtype)                                   # [R, n_last]
        gh = jnp.stack([ga, ha], axis=1)                    # [R, 2]
        return jax.lax.dot_general(
            leaf_oh, gh, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )                                                   # [n_last, 2]
    ga = jnp.where(active, g, 0.0)
    ha = jnp.where(active, h, 0.0)
    leaf_oh = (
        idx[:, None] == jnp.arange(n_last, dtype=jnp.int32)[None, :]
    ).astype(jnp.float32)                                   # [R, n_last]
    gh = jnp.stack([ga, ha], axis=1)                        # [R, 2]
    return jax.lax.dot_general(
        leaf_oh, gh, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )                                                       # [n_last, 2]


# --------------------------------------------------------------------- #
# quantized gradients (cfg.grad_dtype — see module docstring)
# --------------------------------------------------------------------- #

@op_scope("grad_quant")
def quant_scale(max_abs, sum_abs, grad_dtype: str):
    """Quantization step (traced f32 scalar) for values bounded by
    `max_abs` with total mass `sum_abs` — the jax twin of quant_scale_np
    (bit-identical: exact max reduce, IEEE f32 divide, frexp/ldexp on
    the snapped overflow-cap term; see the module docstring for why the
    range term is exact and only the cap term snaps). All-zero channels
    (max_abs == 0 and sum_abs == 0) get scale 1.0 — every q is 0."""
    qmax = GRAD_QMAX[grad_dtype]
    base = jnp.asarray(max_abs, jnp.float32) / jnp.float32(qmax)
    raw_cap = jnp.asarray(sum_abs, jnp.float32) / jnp.float32(GRAD_SUM_CAP)
    m, e = jnp.frexp(raw_cap)
    # ceil(log2(x)): frexp gives x = m * 2^e with m in [0.5, 1);
    # m == 0.5 (x an exact power of two) snaps to e - 1 = log2(x).
    e = e - (m == jnp.float32(0.5))
    cap = jnp.where(raw_cap > 0, jnp.ldexp(jnp.float32(1.0), e),
                    jnp.float32(0.0))
    scale = jnp.maximum(base, cap)
    return jnp.where(scale > 0, scale, jnp.float32(1.0))


def quant_scale_np(max_abs: float, sum_abs: float,
                   grad_dtype: str) -> np.float32:
    """Host twin of quant_scale (the streaming trainers derive the
    round's scale from chunk-reduced stats here; tests cross-check)."""
    qmax = GRAD_QMAX[grad_dtype]
    base = np.float32(max_abs) / np.float32(qmax)
    raw_cap = np.float32(sum_abs) / np.float32(GRAD_SUM_CAP)
    cap = np.float32(0.0)
    if raw_cap > 0:
        m, e = np.frexp(raw_cap)
        cap = np.ldexp(np.float32(1.0), int(e) - int(m == np.float32(0.5)))
    scale = np.maximum(base, cap)
    return scale if scale > 0 else np.float32(1.0)


@op_scope("grad_quant")
def grad_abs_stats(g, h, allreduce=lambda x: x, allmax=lambda x: x):
    """(max|g|, sum|g|, max|h|, sum|h|) as traced f32 scalars, reduced
    over the row mesh by the caller-bound collectives (identity on one
    shard). max is exact under any reduction order; the f32 sum's
    shard/chunk order can differ between trainer paths by ULPs, which
    the power-of-two snap absorbs except at exact frexp boundaries
    (documented in docs/PERF.md "Quantized gradients")."""
    ag = jnp.abs(g.astype(jnp.float32))
    ah = jnp.abs(h.astype(jnp.float32))
    return (allmax(jnp.max(ag)), allreduce(jnp.sum(ag)),
            allmax(jnp.max(ah)), allreduce(jnp.sum(ah)))


@op_scope("grad_quant")
def quantize_with_scales(g, h, gscale, hscale, *, grad_dtype: str,
                         tree_id, seed: int, local_offset,
                         row_start_lo=None, row_start_hi=None):
    """(qg, qh) int8/int16 [R] from f32 gradients and a PRE-DERIVED pair
    of scales (quant_scale) — the streamed trainers' entry point (their
    scale is host-reduced across chunks; the fused path's
    quantize_gradients derives it in-trace and calls this).

    Stochastic rounding: q = floor(g / scale + u) with u the
    per-(seed ^ channel salt, tree_id, GLOBAL row) counter-hash uniform
    (ops/sampling.uniform_jax) — unbiased, replayable, shard-layout
    invariant (row ids are global, so resharding/rotation changes no
    bit). `tree_id` is the traced ABSOLUTE tree index (round * n_classes
    + class — the per-output-dim key); `local_offset`/`row_start_lo/hi`
    follow the sampling-hash conventions. On-grid values (g an exact
    integer multiple of scale) quantize exactly: u < 1 strictly, so
    floor(int + u) == int — the exact-grid contract's mechanism."""
    from ddt_tpu.ops import sampling

    qmax = GRAD_QMAX[grad_dtype]
    dt = grad_quant_dtype(grad_dtype)
    n = g.shape[0]
    ug = sampling.uniform_jax(tree_id, local_offset, n,
                              seed=seed ^ _G_SALT,
                              row_start_lo=row_start_lo,
                              row_start_hi=row_start_hi)
    uh = sampling.uniform_jax(tree_id, local_offset, n,
                              seed=seed ^ _H_SALT,
                              row_start_lo=row_start_lo,
                              row_start_hi=row_start_hi)
    fq = jnp.float32(qmax)
    qg = jnp.clip(jnp.floor(g.astype(jnp.float32) / gscale + ug), -fq, fq)
    qh = jnp.clip(jnp.floor(h.astype(jnp.float32) / hscale + uh), -fq, fq)
    return qg.astype(dt), qh.astype(dt)


def quantize_gradients(g, h, *, grad_dtype: str, tree_id, seed: int,
                       local_offset, row_start_lo=None, row_start_hi=None,
                       allreduce=lambda x: x, allmax=lambda x: x,
                       n_rows_global: "int | None" = None):
    """One tree's full quantization step, in-trace (the fused/granular
    grow path — ops/grow.grow_tree): per-output-dim scales from the
    psum'd/pmax'd |g|,|h| stats, then seeded stochastic rounding.
    Returns (qg, qh, gscale, hscale); dequantize any integer aggregate A
    of the q's as A * scale — exactly once, after every merge.
    `n_rows_global` (static; defaults to the local row count) feeds the
    overflow proof's row ceiling — past GRAD_ROW_LIMIT the sum-cap no
    longer guarantees int32 headroom, so we refuse at trace time."""
    if n_rows_global is None:
        n_rows_global = g.shape[0]
    if n_rows_global >= GRAD_ROW_LIMIT:
        raise ValueError(
            f"quantized gradients over {n_rows_global} rows exceed the "
            f"int32 overflow proof's row ceiling ({GRAD_ROW_LIMIT}): "
            "sum|q| <= 2^30 + n_rows must stay under INT32_MAX (see "
            "ops/grad.py); shard the rows or use grad_dtype='f32'")
    mg, sg, mh, sh = grad_abs_stats(g, h, allreduce=allreduce,
                                    allmax=allmax)
    gscale = quant_scale(mg, sg, grad_dtype)
    hscale = quant_scale(mh, sh, grad_dtype)
    qg, qh = quantize_with_scales(
        g, h, gscale, hscale, grad_dtype=grad_dtype, tree_id=tree_id,
        seed=seed, local_offset=local_offset,
        row_start_lo=row_start_lo, row_start_hi=row_start_hi)
    return qg, qh, gscale, hscale


def quantize_gradients_np(g: np.ndarray, h: np.ndarray, *,
                          grad_dtype: str, tree_id: int, seed: int,
                          row_start: int = 0,
                          gscale=None, hscale=None):
    """Host twin of quantize_gradients/quantize_with_scales (reference
    for the bit-identity tests; scales derived from this array's stats
    when not given). Returns (qg, qh, gscale, hscale)."""
    from ddt_tpu.ops import sampling

    qmax = GRAD_QMAX[grad_dtype]
    npdt = np.int8 if grad_dtype == "int8" else np.int16
    g = np.asarray(g, np.float32)
    h = np.asarray(h, np.float32)
    if gscale is None:
        gscale = quant_scale_np(np.max(np.abs(g), initial=0.0),
                                np.sum(np.abs(g)), grad_dtype)
    if hscale is None:
        hscale = quant_scale_np(np.max(np.abs(h), initial=0.0),
                                np.sum(np.abs(h)), grad_dtype)
    n = g.shape[0]
    ug = sampling.uniform_np(seed ^ _G_SALT, tree_id, row_start, n)
    uh = sampling.uniform_np(seed ^ _H_SALT, tree_id, row_start, n)
    fq = np.float32(qmax)
    qg = np.clip(np.floor(g / np.float32(gscale) + ug), -fq, fq)
    qh = np.clip(np.floor(h / np.float32(hscale) + uh), -fq, fq)
    return qg.astype(npdt), qh.astype(npdt), gscale, hscale


def grad_quant_error_bound(grad_dtype: str, max_abs: float,
                           sum_abs: float, n_rows: int) -> float:
    """Worst-case ABSOLUTE error any integer aggregate of quantized
    gradients (a histogram entry, node total, or leaf sum over up to
    `n_rows` rows) can carry vs the exact f32 sum — the predict_lut
    pattern: a COMPUTED bound the contract tests hold measured
    deviations under, not a hope.

    Each row's stochastic rounding lands within ONE grid step of its
    value (floor(x + u) in (x - 1, x + 1)), steps sum exactly in the
    integer domain, and the single dequantize multiply rounds once in
    f32 — so: n_rows * scale from the rounding, plus eps_f32 times the
    worst-case dequantized magnitude (sum_abs + n_rows * scale)."""
    scale = float(quant_scale_np(max_abs, sum_abs, grad_dtype))
    rounding = n_rows * scale
    return rounding + 2.0 ** -23 * (float(sum_abs) + rounding)
