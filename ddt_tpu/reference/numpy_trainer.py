"""M0: pure-NumPy reference GBDT trainer — the correctness oracle.

SURVEY.md §7 step 1: an exact histogram-algorithm GBDT on one host. Every other
backend (TPU XLA, Pallas, C++ CPU kernels) must reproduce this trainer's split
decisions on small data; SURVEY.md §4 names this "the real correctness anchor".
It doubles as the CPU-reference implementation whose histogram throughput
instantiates the >=5x BASELINE target (BASELINE.md).

Algorithm (classic histogram GBDT, level-wise, complete heap trees):
  for each boosting round:
    g, h = loss.grad_hess(pred, y)
    for depth d in 0..max_depth-1:
      hist[node, feature, bin] = sum of (g, h) via np.add.at   <- HOT LOOP
      cumsum over bins -> left/right aggregates -> gain; argmax (feature, bin)
      split or freeze each level node; reroute rows (node-id vector update)
    leaf values = -G/(H+lambda); pred += lr * leaf_value[leaf of row]

All accumulations are float32 to match device numerics (accumulation order may
still differ; tests use small data where argmax ties are improbable).
"""

from __future__ import annotations

import ml_dtypes  # ships with jax; used for the bf16 deterministic tie-break
import numpy as np

from ddt_tpu.config import TrainConfig
from ddt_tpu.data.quantizer import BinMapper
from ddt_tpu.models.tree import TreeEnsemble, empty_ensemble


# --------------------------------------------------------------------------- #
# Losses (NumPy twins of ops/grad.py — keep formulas in sync)
# --------------------------------------------------------------------------- #

def base_score(y: np.ndarray, loss: str, n_classes: int = 2,
               sample_weight: np.ndarray | None = None) -> float:
    """Raw-score init; the weighted mean when sample_weight is given
    (weights scale each row's contribution to the loss, so the optimal
    constant shifts with them)."""
    mean = (
        float(np.mean(y)) if sample_weight is None
        else float(np.average(y, weights=sample_weight))
    )
    if loss == "logloss":
        p = float(np.clip(mean, 1e-6, 1 - 1e-6))
        return float(np.log(p / (1 - p)))
    if loss == "mse":
        return mean
    return 0.0  # softmax: symmetric zero init per class


def grad_hess(
    pred_raw: np.ndarray, y: np.ndarray, loss: str
) -> tuple[np.ndarray, np.ndarray]:
    """Gradient/hessian of the loss wrt raw scores. float32 [R] or [R, C]."""
    if loss == "logloss":
        p = 1.0 / (1.0 + np.exp(-pred_raw.astype(np.float64)))
        g = (p - y).astype(np.float32)
        h = (p * (1.0 - p)).astype(np.float32)
        return g, h
    if loss == "mse":
        return (pred_raw - y).astype(np.float32), np.ones_like(y, np.float32)
    if loss == "softmax":
        z = pred_raw - pred_raw.max(axis=1, keepdims=True)
        e = np.exp(z.astype(np.float64))
        p = e / e.sum(axis=1, keepdims=True)
        onehot = np.zeros_like(p)
        onehot[np.arange(y.shape[0]), y.astype(np.int64)] = 1.0
        g = (p - onehot).astype(np.float32)
        h = (p * (1.0 - p)).astype(np.float32)
        return g, h
    raise ValueError(loss)


# --------------------------------------------------------------------------- #
# Kernels (NumPy reference of L3 in SURVEY.md §1)
# --------------------------------------------------------------------------- #

def build_histograms(
    Xb: np.ndarray,        # uint8 [R, F]
    g: np.ndarray,         # float32 [R]
    h: np.ndarray,         # float32 [R]
    node_index: np.ndarray,  # int32 [R]; level-local node in [0, n_nodes) or -1
    n_nodes: int,
    n_bins: int,
) -> np.ndarray:
    """Reference HistogramBuilder: float32 [n_nodes, F, n_bins, 2] (g, h sums).

    Rows with node_index < 0 (frozen at an earlier-level leaf) contribute
    nothing. This signature is the L4 kernel contract every backend implements.
    """
    R, F = Xb.shape
    hist = np.zeros((n_nodes, F, n_bins, 2), dtype=np.float32)
    active = node_index >= 0
    idx_n = node_index[active]
    ga = g[active]
    ha = h[active]
    Xa = Xb[active]
    for f in range(F):
        bins_f = Xa[:, f].astype(np.int64)
        np.add.at(hist, (idx_n, f, bins_f, 0), ga)
        np.add.at(hist, (idx_n, f, bins_f, 1), ha)
    return hist


def best_splits(
    hist: np.ndarray,          # [n_nodes, F, B, 2]
    reg_lambda: float,
    min_child_weight: float,
    feature_mask: np.ndarray | None = None,   # bool [F]; False = excluded
    missing_bin: bool = False,
    cat_mask: np.ndarray | None = None,       # bool [F]; True = categorical
) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Reference SplitGain: per-node best
    (gain, feature, threshold_bin, default_left).

    gain = 0.5*(GL^2/(HL+l) + GR^2/(HR+l) - G^2/(H+l)), maximised over the
    flattened (direction, feature, bin) axis; first-occurrence argmax
    (matches jnp.argmax) so all backends agree on tie-breaks. Splitting at
    bin b sends bins <= b left; the last bin is excluded (empty right
    child).

    With missing_bin=True the top bin B-1 holds NaN rows and both default
    directions are scored per (feature, bin): RIGHT keeps the missing mass
    with the right child (the plain cumsum), LEFT moves it left. Candidate
    bins are the VALUE bins 0..B-2 (t = B-2 under direction RIGHT is the
    "missing vs everything" split). Direction RIGHT occupies the first
    flattened block, so nodes with zero missing mass — where both
    directions tie exactly — deterministically report default_left=False,
    matching the missing_bin=False semantics.

    `cat_mask` marks CATEGORICAL features: their candidates are
    one-vs-rest splits ("bin == k goes LEFT", every bin a candidate,
    one-hot gain Gk^2/(Hk+l) + (G-Gk)^2/(H-Hk+l) - parent) replacing the
    ordinal cumsum gains in the same (feature, bin) argmax grid — the
    chosen bin is the matched category k. Under missing_bin they compete
    only in the direction-RIGHT block (categorical columns are
    integer-coded and never NaN).
    """
    n_nodes, F, B, _ = hist.shape
    GL = np.cumsum(hist[..., 0], axis=2)       # [n, F, B]
    HL = np.cumsum(hist[..., 1], axis=2)
    # PER-FEATURE totals (every feature sums the same rows, so these agree
    # up to f32 add order). Using feature f's own total makes the
    # complement side EXACTLY zero for degenerate candidates (e.g. all of a
    # node's rows missing on f: the all-left variant gets HR = 0, not
    # cross-feature float noise that can straddle min_child_weight
    # differently per partition count). Twins: ops/split.py, C++
    # split_gain.cpp — keep the same totals convention in all three.
    G = GL[:, :, -1][:, :, None]               # [n, F, 1]
    H = HL[:, :, -1][:, :, None]

    def gain_of(GLd, HLd):
        GR = G - GLd
        HR = H - HLd
        with np.errstate(divide="ignore", invalid="ignore"):
            parent = np.square(G) / (H + reg_lambda)
            gain = 0.5 * (
                np.square(GLd) / (HLd + reg_lambda)
                + np.square(GR) / (HR + reg_lambda)
                - parent
            )
        valid = (HLd >= min_child_weight) & (HR >= min_child_weight)
        valid &= ~np.isnan(gain)   # 0/0 when reg_lambda == 0
        if feature_mask is not None:
            valid = valid & feature_mask[None, :, None]
        return gain, valid

    def overlay_cat(gain, valid):
        """Replace cat features' ordinal gains with one-vs-rest gains
        (left child = exactly bin k, so GL_k is the per-bin sum itself;
        every bin including the last is a candidate)."""
        if cat_mask is None or not cat_mask.any():
            return gain, valid
        gc, vc = gain_of(hist[..., 0], hist[..., 1])
        m = cat_mask[None, :, None]
        return np.where(m, gc, gain), np.where(m, vc, valid)

    if not missing_bin:
        gain, valid = gain_of(GL, HL)
        valid[:, :, B - 1] = False             # cannot split on last bin
        gain, valid = overlay_cat(gain, valid)
        # Deterministic selection (see ops/split.py): bf16-rounded gains
        # turn float-noise near-ties into exact ties with a shared
        # first-index tie-break, so CPU/TPU/any-partition-count all pick
        # identical splits.
        g16 = np.where(valid, gain, -np.inf).astype(ml_dtypes.bfloat16)
        flat = g16.reshape(n_nodes, F * B)
        best = np.argmax(flat, axis=1)
        best_gain = flat[np.arange(n_nodes), best].astype(np.float32)
        return (best_gain, (best // B).astype(np.int32),
                (best % B).astype(np.int32), np.zeros(n_nodes, bool))

    miss_g = hist[:, :, B - 1, 0][..., None]   # [n, F, 1]
    miss_h = hist[:, :, B - 1, 1][..., None]
    gain_r, valid_r = gain_of(GL, HL)               # missing stays RIGHT
    gain_l, valid_l = gain_of(GL + miss_g, HL + miss_h)   # missing LEFT
    valid_r[:, :, B - 1] = False               # the NaN bin itself: no split
    valid_l[:, :, B - 1] = False
    # t = B-2 under LEFT puts every row left -> empty right child; the
    # HR >= min_child_weight guard already rejects it for mcw > 0, but the
    # rule must not depend on the knob:
    valid_l[:, :, B - 2] = False
    gain_r, valid_r = overlay_cat(gain_r, valid_r)
    if cat_mask is not None:
        valid_l &= ~cat_mask[None, :, None]    # cat: RIGHT block only
    g16 = np.concatenate(
        [np.where(valid_r, gain_r, -np.inf),
         np.where(valid_l, gain_l, -np.inf)], axis=1,
    ).astype(ml_dtypes.bfloat16)               # [n, 2F, B]: RIGHT block first
    flat = g16.reshape(n_nodes, 2 * F * B)
    best = np.argmax(flat, axis=1)
    best_gain = flat[np.arange(n_nodes), best].astype(np.float32)
    default_left = best >= F * B
    fb = best % (F * B)
    return (best_gain, (fb // B).astype(np.int32),
            (fb % B).astype(np.int32), default_left)


def node_totals(hist: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """(G, H) per node from a histogram (sums over bins of feature 0)."""
    return hist[:, 0, :, 0].sum(axis=1), hist[:, 0, :, 1].sum(axis=1)


# --------------------------------------------------------------------------- #
# Tree growth + boosting (L5 Driver loop, reference edition)
# --------------------------------------------------------------------------- #

def grow_tree(
    Xb: np.ndarray,
    g: np.ndarray,
    h: np.ndarray,
    cfg: TrainConfig,
    hist_fn=None,
    feature_mask: np.ndarray | None = None,
    split_full_fn=None,
) -> dict:
    """Grow one complete-heap tree. Returns dict of node arrays [n_nodes_total].

    hist_fn/split_full_fn inject alternate L3 kernels with the same
    contract (CPUDevice passes the native C++ ones — bit-parity
    guaranteed); defaults are the NumPy oracle kernels in this module.
    split_full_fn carries the full best_splits contract:
    (hist, feature_mask, missing_bin, cat_mask) -> 4-tuple.
    """
    R, F = Xb.shape
    N = cfg.n_nodes_total
    missing = cfg.missing_policy == "learn"
    cat_mask = None
    if cfg.cat_features:
        cat_mask = np.zeros(F, bool)
        cat_mask[list(cfg.cat_features)] = True
    feature = np.full(N, -1, np.int32)
    threshold_bin = np.zeros(N, np.int32)
    is_leaf = np.zeros(N, bool)
    leaf_value = np.zeros(N, np.float32)
    split_gain = np.zeros(N, np.float32)
    default_left = np.zeros(N, bool)

    node_id = np.zeros(R, np.int64)    # heap index per row
    frozen = np.zeros(R, bool)         # row reached an early leaf

    for depth in range(cfg.max_depth):
        offset = (1 << depth) - 1
        n_level = 1 << depth
        node_index = np.where(frozen, -1, node_id - offset).astype(np.int32)
        if hist_fn is not None:
            hist = hist_fn(Xb, g, h, node_index, n_level)
        else:
            hist = build_histograms(Xb, g, h, node_index, n_level, cfg.n_bins)
        G, H = node_totals(hist)
        if split_full_fn is not None:
            gains, feats, bins, dls = split_full_fn(
                hist, feature_mask, missing, cat_mask)
        else:
            gains, feats, bins, dls = best_splits(
                hist, cfg.reg_lambda, cfg.min_child_weight, feature_mask,
                missing_bin=missing, cat_mask=cat_mask,
            )
        # Guarded like the final level and the streamed twin: an EMPTY
        # node at reg_lambda=0 would otherwise store -0/0 = NaN as its
        # leaf value, which a predict-time row (different data) can reach.
        with np.errstate(divide="ignore", invalid="ignore"):
            value = np.where(H > 0, -G / (H + cfg.reg_lambda), 0.0)

        do_split = (gains > cfg.min_split_gain) & np.isfinite(gains) & (H > 0)
        for i in range(n_level):
            node = offset + i
            if do_split[i]:
                feature[node] = feats[i]
                threshold_bin[node] = bins[i]
                split_gain[node] = gains[i]
                default_left[node] = dls[i]
            else:
                is_leaf[node] = True
                leaf_value[node] = value[i]

        # Reroute active rows through new splits; freeze rows at new leaves.
        active = ~frozen
        idx = (node_id - offset)[active]
        split_here = do_split[idx]
        feat_r = feats[idx]
        bin_r = bins[idx]
        fv = Xb[active, feat_r].astype(np.int32)
        go_right = fv > bin_r
        if cat_mask is not None:
            # Categorical one-vs-rest: the matched category goes LEFT.
            go_right = np.where(cat_mask[feat_r], fv != bin_r, go_right)
        if missing:
            # NaN rows (top bin) follow the learned default direction.
            is_miss = fv == cfg.n_bins - 1
            go_right = np.where(is_miss, ~dls[idx], go_right)
        new_ids = np.where(
            split_here,
            2 * node_id[active] + 1 + go_right,
            node_id[active],
        )
        node_id[active] = new_ids
        newly_frozen = np.zeros(R, bool)
        newly_frozen[active] = ~split_here
        frozen |= newly_frozen

    # Final-level leaves: value from G/H aggregated per terminal node. All
    # last-level slots are marked leaves even when unreachable (no active
    # rows) — unreachable slots become inert zero-value leaves, identical to
    # ops/grow.py's device semantics (backend-parity contract).
    active = ~frozen
    offset = (1 << cfg.max_depth) - 1
    idx = node_id[active] - offset
    n_last = 1 << cfg.max_depth
    Gl = np.zeros(n_last, np.float32)
    Hl = np.zeros(n_last, np.float32)
    np.add.at(Gl, idx, g[active])
    np.add.at(Hl, idx, h[active])
    with np.errstate(divide="ignore", invalid="ignore"):
        vals = -Gl / (Hl + cfg.reg_lambda)
    leaf_ids = offset + np.arange(n_last)
    is_leaf[leaf_ids] = True
    leaf_value[leaf_ids] = np.where(Hl > 0, vals, 0.0)

    return {
        "feature": feature,
        "threshold_bin": threshold_bin,
        "is_leaf": is_leaf,
        "leaf_value": leaf_value,
        "split_gain": split_gain,
        "default_left": default_left,
        "leaf_of_row": node_id.astype(np.int64),
    }


def fit(
    Xb: np.ndarray,
    y: np.ndarray,
    cfg: TrainConfig,
    mapper: BinMapper | None = None,
) -> TreeEnsemble:
    """Train a GBDT on binned data. The oracle for all backends."""
    R, F = Xb.shape
    if Xb.dtype != np.uint8:
        raise TypeError(f"Xb must be uint8 binned data, got {Xb.dtype}")
    if R and int(Xb.max()) >= cfg.n_bins:
        raise ValueError(
            f"Xb contains bin {int(Xb.max())} but cfg.n_bins={cfg.n_bins}; "
            "quantize with the same n_bins as the TrainConfig."
        )
    y = np.asarray(y)
    if cfg.cat_features and cfg.cat_features[-1] >= F:
        raise ValueError(
            f"cat_features index {cfg.cat_features[-1]} out of range "
            f"for {F} features"
        )
    C = cfg.n_classes if cfg.loss == "softmax" else 1
    bs = base_score(y, cfg.loss, cfg.n_classes)
    n_trees_total = cfg.n_trees * C
    ens = empty_ensemble(
        n_trees_total, cfg.max_depth, F, cfg.learning_rate, bs,
        cfg.loss, cfg.n_classes,
        missing_bin=cfg.missing_policy == "learn", n_bins=cfg.n_bins,
        cat_features=cfg.cat_features,
    )

    if cfg.loss == "softmax":
        pred = np.zeros((R, C), np.float32)
    else:
        pred = np.full(R, bs, np.float32)

    t_out = 0
    for _round in range(cfg.n_trees):
        g, h = grad_hess(pred, y, cfg.loss)
        for c in range(C):
            gc = g[:, c] if C > 1 else g
            hc = h[:, c] if C > 1 else h
            tree = grow_tree(Xb, gc, hc, cfg)
            ens.feature[t_out] = tree["feature"]
            ens.threshold_bin[t_out] = tree["threshold_bin"]
            ens.is_leaf[t_out] = tree["is_leaf"]
            ens.leaf_value[t_out] = tree["leaf_value"]
            ens.split_gain[t_out] = tree["split_gain"]
            ens.default_left[t_out] = tree["default_left"]
            delta = cfg.learning_rate * tree["leaf_value"][tree["leaf_of_row"]]
            if C > 1:
                pred[:, c] += delta
            else:
                pred += delta
            t_out += 1

    if mapper is not None:
        _fill_raw_thresholds(ens, mapper)
    return ens


def _fill_raw_thresholds(ens: TreeEnsemble, mapper: BinMapper) -> None:
    T, N = ens.feature.shape
    for t in range(T):
        for n in range(N):
            f = ens.feature[t, n]
            if f >= 0:
                ens.threshold_raw[t, n] = mapper.threshold_value(
                    int(f), int(ens.threshold_bin[t, n])
                )
    ens.has_raw_thresholds = True
