"""Mesh construction + multi-host initialisation (layers L2/L0 plumbing).

SURVEY.md §5 "Distributed communication backend": the reference's on-FPGA
100G fabric allreduce maps to XLA collectives over mesh axes — psum rides ICI
within a slice; a second ("hosts") axis rides DCN across slices. A GBDT
histogram is KBs–MBs and additive, so the same single psum works over a 1-D
flattened mesh too; the 2-D constructor exists so multi-slice pods lay the
reduce-scatter/all-reduce phases out along the fast axis first (XLA does this
automatically for a 2-D mesh when axes are ordered (hosts, rows)).

Multi-host: standard single-controller JAX — every host runs the same
program, jax.distributed.initialize() wires the DCN bootstrap, and
jax.devices() becomes the global device list. Training code is unchanged:
TPUDevice row-shards over the global mesh and the Driver loop never knows.
"""

from __future__ import annotations

import dataclasses
import logging
import re

import jax

log = logging.getLogger("ddt_tpu.parallel")

ROWS_AXIS = "rows"
HOSTS_AXIS = "hosts"
FEATURES_AXIS = "features"

P = jax.sharding.PartitionSpec


@dataclasses.dataclass(frozen=True)
class SpecLayout:
    """Canonical PartitionSpecs for every trainer operand over the
    declarative 2D (rows x features) mesh — the SpecLayout idiom
    (SNIPPETS [3]) applied to histogram GBDT.

    `row_axes` is the row-shard axis name — a ("hosts", "rows") tuple on
    pod meshes, plain "rows" otherwise, or None on single-device
    backends (every spec degenerates to replicated, so single-device
    traces share the callers' code). `feature_axis` is the optional
    column axis ("features"), or None when the feature dimension is
    replicated.

    The layout is the ONE home of "which operand shards how": backends
    resolve in_specs/out_specs through the rule table below
    (match_partition_rules) by operand NAME, so adding a mesh axis is a
    table edit, not a hunt through every shard_map call site."""

    row_axes: "str | tuple[str, ...] | None" = ROWS_AXIS
    feature_axis: "str | None" = None

    # -- canonical per-operand specs ---------------------------------- #

    def binned_data(self) -> P:
        """uint8 [R, F]: rows sharded, columns sharded when the feature
        axis is live (the wide-dataset case ROADMAP item 2 exists for)."""
        if self.row_axes is None:
            return P()
        return P(self.row_axes, self.feature_axis)

    def row_vector(self) -> P:
        """float32/int32 [R]: gradients, hessians, node indices, labels,
        validity masks — row-sharded, feature-replicated."""
        return P() if self.row_axes is None else P(self.row_axes)

    def row_matrix(self) -> P:
        """[R, C] per-class state (softmax pred): rows sharded, classes
        replicated."""
        return P() if self.row_axes is None else P(self.row_axes, None)

    def level_hist_scattered(self) -> P:
        """[n_level, F, B, 2] POST-reduce-scatter level histogram: the
        feature dim sharded over the ROW axes (each row shard merged one
        F/Pr slab — parallel/comms.hist_reduce)."""
        if self.row_axes is None:
            return P()
        return P(None, self.row_axes)

    def replicated(self) -> P:
        """Tree node arrays, split winners, scalars, colsample masks —
        tiny, identical on every shard by construction."""
        return P()

    # -- the declarative rule table ----------------------------------- #

    def rules(self) -> list:
        """[(operand-name regex, PartitionSpec)] — first match wins
        (match_partition_rules). Names are the backends' operand
        vocabulary; `.*` (replicated) is the explicit fallback so a
        typo'd name fails the match audit in tests, not silently."""
        return [
            (r"^(data|binned|Xb)", self.binned_data()),
            (r"^(grad|hess|node_index|labels|valid|row_keep|pred1d|y)$",
             self.row_vector()),
            (r"^(pred|val_pred)$", self.row_matrix()),
            (r"^hist_scattered$", self.level_hist_scattered()),
            (r"^(tree|winners|mask|scalar|fmasks|replicated)",
             self.replicated()),
        ]

    def spec(self, name: str) -> P:
        return match_partition_rules(self.rules(), [name])[0]

    def specs(self, *names: str) -> tuple:
        return match_partition_rules(self.rules(), list(names))


def match_partition_rules(rules, names) -> tuple:
    """PartitionSpec per operand name from a [(regex, spec)] rule table
    — the match_partition_rules idiom (SNIPPETS [1]) on operand names
    instead of parameter-tree paths (a GBDT trainer has a dozen named
    operands, not a parameter pytree). Unmatched names fail loudly: a
    silently-replicated row matrix is a 10x memory bug, not a default."""
    out = []
    for name in names:
        for rule, spec in rules:
            if re.search(rule, name) is not None:
                out.append(spec)
                break
        else:
            raise ValueError(
                f"no partition rule matches operand {name!r}; add it to "
                "SpecLayout.rules()")
    return tuple(out)


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """Version-portable jax.shard_map — the ONE home of the API seam.

    jax promoted shard_map from jax.experimental to the top level (and
    renamed check_rep -> check_vma) across the versions this repo must
    run on; every shard_map site in the backend routes through here so
    the codebase tracks exactly one spelling. Older jax (<= 0.4.x,
    including this image's 0.4.37) takes the experimental import with
    the check_rep spelling; newer jax takes jax.shard_map verbatim."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map

    # The legacy rep-checker predates the VMA formulation and rejects
    # sound programs the new checker accepts (scan carries that start
    # replicated, gathered argmaxes — its own error message says to
    # disable it). Correctness on old jax is held by the suite's
    # bit-identity contracts (N-partition == 1-partition trees), not by
    # the static checker, so it is off unconditionally here.
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=False)


def static_axis_size(axis_name) -> int:
    """Static (trace-time python int) extent of a named mesh axis — the
    version-portable jax.lax.axis_size (absent before jax 0.5; there,
    jax.core.axis_frame(name) IS the size). Must be called inside a
    shard_map/collective trace over the axis, like the original."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    import jax.core as _core

    return int(_core.axis_frame(axis_name))


def make_row_mesh(
    n_partitions: int, devices: list | None = None
) -> jax.sharding.Mesh:
    """1-D mesh over the data-parallel "rows" axis (the GBDT's only
    parallelism dimension — SURVEY.md §2 "Parallelism strategies")."""
    devs = devices if devices is not None else jax.devices()
    if len(devs) < n_partitions:
        raise ValueError(
            f"n_partitions={n_partitions} but only {len(devs)} devices visible"
        )
    return jax.make_mesh((n_partitions,), (ROWS_AXIS,),
                         devices=devs[:n_partitions])


def make_pod_mesh(
    n_hosts: int | None = None,
    devices_per_host: int | None = None,
    feature_partitions: int = 1,
    devices: list | None = None,
) -> jax.sharding.Mesh:
    """(hosts, rows[, features]) mesh for multi-slice pods: "rows" is the
    intra-slice ICI axis, "hosts" the cross-slice DCN axis (outermost =
    slowest varying, so each host's devices stay ICI-contiguous). Histogram
    reduction becomes psum over (hosts, rows); XLA phases it as ICI-reduce
    then DCN-allreduce.

    Consumed by TPUDevice: pass the result as `TPUDevice(cfg, mesh=...)`
    (it reads the hosts/rows/features axis sizes off the mesh), or just set
    cfg.host_partitions and let TPUDevice build the identical mesh itself."""
    devs = devices if devices is not None else jax.devices()
    if n_hosts is None:
        n_hosts = max(1, jax.process_count())
    if devices_per_host is None:
        devices_per_host = len(devs) // (n_hosts * feature_partitions)
    n_dev = n_hosts * devices_per_host * feature_partitions
    if len(devs) < n_dev:
        raise ValueError(
            f"pod mesh {n_hosts} x {devices_per_host} x "
            f"{feature_partitions} needs {n_dev} devices, "
            f"have {len(devs)}"
        )
    if feature_partitions > 1:
        return jax.make_mesh(
            (n_hosts, devices_per_host, feature_partitions),
            (HOSTS_AXIS, ROWS_AXIS, "features"), devices=devs[:n_dev],
        )
    return jax.make_mesh(
        (n_hosts, devices_per_host), (HOSTS_AXIS, ROWS_AXIS),
        devices=devs[:n_dev],
    )


def make_mesh_2d(
    row_partitions: int,
    feature_partitions: int = 1,
    n_hosts: int = 1,
    devices: list | None = None,
) -> jax.sharding.Mesh:
    """Declarative 2D (rows x features) mesh — ROADMAP item 2's layout.

    Axis order is (hosts?, rows, features): hosts outermost (DCN,
    slowest-varying, so each host's devices stay ICI-contiguous), rows
    middle, features innermost (ICI-adjacent — the per-level winner
    gather over the feature axis is latency-sensitive; the hosts hop
    happens once per reduction). The features axis is always present on
    the 2-D form (size 1 when unsharded) so partition specs naming it
    resolve on every mesh; the pure pod form (make_pod_mesh) remains the
    (hosts, rows) spelling for row-only multi-slice runs.

    This is the ONE mesh constructor the TPUDevice backend uses; pass
    `cfg.mesh_shape=(Pr, Pf)` (or --mesh-shape Pr,Pf) and the backend
    calls this with those extents."""
    devs = devices if devices is not None else jax.devices()
    n_dev = n_hosts * row_partitions * feature_partitions
    if len(devs) < n_dev:
        raise ValueError(
            f"mesh ({n_hosts} hosts x {row_partitions} rows x "
            f"{feature_partitions} features) needs {n_dev} devices, "
            f"have {len(devs)}"
        )
    if n_hosts > 1:
        return jax.make_mesh(
            (n_hosts, row_partitions, feature_partitions),
            (HOSTS_AXIS, ROWS_AXIS, FEATURES_AXIS), devices=devs[:n_dev],
        )
    return jax.make_mesh(
        (row_partitions, feature_partitions), (ROWS_AXIS, FEATURES_AXIS),
        devices=devs[:n_dev],
    )


def shard_ready_times(arr, poll_interval_s: float = 5e-5,
                      timeout_s: float = 600.0) -> "list | None":
    """Per-device completion times of `arr`'s addressable shards:
    [(device_id, time.perf_counter() at readiness)], device-id sorted.

    The flight recorder's probe (telemetry.events.PartitionRecorder):
    polling each shard's is_ready() records every device's completion
    moment independently — the per-partition wall-time signal a single
    block_until_ready collapses into one number. Where the runtime
    exposes no is_ready (old jax array wrappers), falls back to blocking
    shard-by-shard in device order, which keeps the MAX (the straggler)
    exact while flattening earlier lanes onto the running prefix-max —
    documented bias, not silent error. Returns None for values with no
    shard view (host arrays). Only meaningful to call on a handle whose
    producer has been dispatched; the probe IS a barrier on the array."""
    import time as _time

    try:
        shards = arr.addressable_shards
    except AttributeError:
        return None
    pending = {int(s.device.id): s.data for s in shards}
    out: dict[int, float] = {}
    can_poll = all(hasattr(d, "is_ready") for d in pending.values())
    if can_poll:
        deadline = _time.perf_counter() + timeout_s
        while pending and _time.perf_counter() < deadline:
            for dev in list(pending):
                if pending[dev].is_ready():
                    out[dev] = _time.perf_counter()
                    del pending[dev]
            if pending:
                _time.sleep(poll_interval_s)
    for dev in sorted(pending):              # fallback / timeout residue
        pending[dev].block_until_ready()
        out[dev] = _time.perf_counter()
    return sorted(out.items())


# Args of the successful initialize_multihost call, for the idempotence
# guard below (None = never initialised in this process).
_init_args: dict | None = None


def initialize_multihost(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """jax.distributed.initialize with arguments optional (TPU pods
    auto-discover via the metadata service; explicit args for manual
    bring-up). Call once per process, BEFORE first device use.

    Idempotent-or-loud: a repeat call with the SAME arguments is a logged
    no-op (preemptible-restart loops re-run their whole entry point); a
    repeat call with DIFFERENT arguments raises — jax.distributed cannot
    re-wire a live coordinator, and silently keeping the old topology
    would train on the wrong mesh.

    Transient bootstrap faults (a coordinator that is still coming up, a
    DCN blip — the classic pod bring-up race) are RETRIED with backoff
    before the hard failure below: one slow peer must not abort an
    N-host launch (utils/retry.py, seam "multihost.init"; the chaos
    harness injects its timeout at the same seam)."""
    global _init_args
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    if _init_args is not None:
        if _init_args == kwargs:
            log.info("multihost already initialised (process %d/%d); no-op",
                     jax.process_index(), jax.process_count())
            return
        raise RuntimeError(
            f"initialize_multihost already ran with {_init_args}; cannot "
            f"re-initialise with {kwargs} — restart the process to change "
            "the distributed topology"
        )
    from ddt_tpu.robustness import faultplan
    from ddt_tpu.utils import retry

    def _attempt() -> None:
        faultplan.inject("multihost.init")
        jax.distributed.initialize(**kwargs)

    try:
        retry.retry_call(
            _attempt, seam="multihost.init",
            # Bootstrap waits are long: few, slow attempts with a pod-
            # bring-up-sized deadline (vs the default I/O policy's 30 s).
            policy=retry.RetryPolicy(attempts=3, base_s=2.0,
                                     multiplier=2.0, jitter=0.5,
                                     deadline_s=120.0))
    except Exception as e:
        raise RuntimeError(
            f"jax.distributed.initialize({kwargs}) failed — check that the "
            "coordinator address is reachable from every process, that "
            "process_id values are unique in [0, num_processes), and that "
            "no JAX device was touched before this call"
        ) from e
    _init_args = kwargs
    log.info(
        "multihost initialised: process %d/%d, %d global devices",
        jax.process_index(), jax.process_count(), len(jax.devices()),
    )
