"""One-home collectives for split finding: the single spelling of
psum/reduce_scatter/all_gather (+ compressed payloads), like
`mesh.shard_map` is for shard_map.

Every cross-device byte the trainer moves funnels through this module —
the ddtlint `one-home-collective` rule flags raw `jax.lax.psum`/
`reduce_scatter`/`all_gather` anywhere else in ddt_tpu/, so changing a
collective's algorithm, payload dtype, or instrumentation is a one-file
edit and the `hist_allreduce_bytes` counter's payload model
(telemetry/counters.py) cannot silently drift from the wire.

Three concerns live here (ISSUE 10, docs/PERF.md "Histogram comms"):

- **Version-portable collectives.** `psum`/`pmax`/`pmin`/`all_gather`
  are thin wrappers (identity when `axis_name` is None, so single-device
  traces share the callers' code path). `reduce_scatter` takes
  `jax.lax.psum_scatter(tiled=True)` where the runtime supports it
  (this image's 0.4.37 does, lowering to a true `reduce-scatter` HLO
  over tuple (hosts, rows) axes) and falls back to psum + a local
  dynamic slice — same VALUES and same memory contract for the caller,
  full allreduce wire cost (the fallback is for portability, not
  performance; `HAS_PSUM_SCATTER` says which spelling is live).

- **Reduce-scatter split finding** (`cfg.split_comms`): instead of
  psumming the full `[n, F, B, 2]` level histogram to every device and
  having every device run the same argmax, `hist_reduce(...,
  mode="reduce_scatter")` hands each of the P row shards one merged
  F/P-feature slab; the caller runs split finding on its slab and
  `combine_shard_winners` all_gathers the tiny per-shard (gain, feat,
  bin, direction) tuples — O(F·B/P) + O(P · n_level) per device where
  the allreduce moved O(F·B). The cross-shard tie-break is by GLOBAL
  flattened candidate index (direction block, then feature, then bin),
  so the combined winner is exactly the single-device argmax's pick —
  including the missing-bin RIGHT-block-first rule — regardless of
  which shard owns which slab. On a 2D (rows x features) mesh the
  scatter runs over the row axes WITHIN each feature slab (per-device
  slab F/(Pr·Pf)) and the winner combine gathers over BOTH axes — the
  tie-break key is layout-independent, so composition needs no new
  rule (ROADMAP item 2).

- **Compressed collective payloads** (`cfg.hist_comms_dtype`, opt-in):
  `bf16` halves the wire bytes at ~2^-9 relative rounding per partial;
  `int32_fixed` quantizes each partial onto a shared fixed-point grid
  (global scale from a pmax of the local max-abs) and reduces in int32
  — integer addition commutes EXACTLY, so an N-partition merge is
  bit-stable under any reduction order where f32 psum order was not.
  `comms_error_bound` computes the worst-case per-entry error either
  mode can introduce; the split-agreement contract tests
  (tests/test_comms.py) hold the trained trees to it.

Named scopes: every collective opens a `ddt:comms:<kind>` traced scope
(compress/decompress included) so profiler captures attribute the wire
time this module exists to shrink (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ddt_tpu.parallel import mesh as mesh_lib
from ddt_tpu.telemetry.annotations import traced_scope

#: cfg.split_comms values (config.py validates; backends resolve "auto").
SPLIT_COMMS = ("auto", "allreduce", "reduce_scatter")
#: cfg.hist_comms_dtype values — the histogram collective's wire dtype.
COMMS_DTYPES = ("f32", "bf16", "int32_fixed")

#: Wire bytes per histogram entry under each comms dtype (the
#: hist_allreduce_bytes payload model reads this — one home).
COMMS_DTYPE_BYTES = {"f32": 4, "bf16": 2, "int32_fixed": 4}

#: Whether this jax exposes the true reduce-scatter collective. Absent
#: (ancient jax), reduce_scatter() below emulates with psum + slice —
#: same values, allreduce wire cost.
HAS_PSUM_SCATTER = hasattr(jax.lax, "psum_scatter")

#: int32_fixed headroom: the per-partial quantized magnitude cap is
#: (2^30 - 1) // P so the P-way integer sum can never overflow int32
#: (sum bounded by P * cap < 2^30 << 2^31 - 1).
_FIXED_CAP = (1 << 30) - 1


# --------------------------------------------------------------------- #
# axis helpers (tuple row axes — the (hosts, rows) pod mesh — welcome)
# --------------------------------------------------------------------- #

def axis_size(axis_name) -> int:
    """Static total extent of `axis_name` (product over a tuple of
    axes) — trace-time python int."""
    if axis_name is None:
        return 1
    if isinstance(axis_name, tuple):
        n = 1
        for a in axis_name:
            n *= mesh_lib.static_axis_size(a)
        return n
    return mesh_lib.static_axis_size(axis_name)


def flat_axis_index(axis_name):
    """This shard's flattened index over `axis_name` (row-major over a
    tuple of axes, matching psum_scatter's slab ordering and the
    backends' global-row-offset convention)."""
    if axis_name is None:
        return jnp.int32(0)
    if isinstance(axis_name, tuple):
        idx = jax.lax.axis_index(axis_name[0])
        for a in axis_name[1:]:
            idx = idx * mesh_lib.static_axis_size(a) + jax.lax.axis_index(a)
        return idx.astype(jnp.int32)
    return jax.lax.axis_index(axis_name).astype(jnp.int32)


# --------------------------------------------------------------------- #
# the collectives (identity when axis_name is None)
# --------------------------------------------------------------------- #

def psum(x, axis_name):
    if axis_name is None:
        return x
    with traced_scope("comms:allreduce"):
        return jax.lax.psum(x, axis_name)


def pmax(x, axis_name):
    if axis_name is None:
        return x
    with traced_scope("comms:allreduce"):
        return jax.lax.pmax(x, axis_name)


def pmin(x, axis_name):
    if axis_name is None:
        return x
    with traced_scope("comms:allreduce"):
        return jax.lax.pmin(x, axis_name)


def all_gather(x, axis_name, axis: int = 0, tiled: bool = False):
    if axis_name is None:
        return x if tiled else x[None]
    with traced_scope("comms:allgather"):
        return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name, dim: int):
    """Sum `x` over `axis_name` and hand each shard its contiguous
    1/P block of dimension `dim` (shard i gets block i in flattened
    axis order). `x.shape[dim]` must be a multiple of the axis size —
    callers pad (see `pad_to_multiple`). Falls back to psum + local
    slice when the runtime lacks psum_scatter."""
    if axis_name is None:
        return x
    P = axis_size(axis_name)
    if x.shape[dim] % P:
        raise ValueError(
            f"reduce_scatter dim {dim} extent {x.shape[dim]} not a "
            f"multiple of the axis size {P}; pad first")
    if HAS_PSUM_SCATTER:
        with traced_scope("comms:reduce_scatter"):
            return jax.lax.psum_scatter(
                x, axis_name, scatter_dimension=dim, tiled=True)
    # Portability fallback: full allreduce then a local slice — same
    # values and caller contract, no wire saving.
    with traced_scope("comms:reduce_scatter"):
        full = jax.lax.psum(x, axis_name)
        block = x.shape[dim] // P
        return jax.lax.dynamic_slice_in_dim(
            full, flat_axis_index(axis_name) * block, block, axis=dim)


def pad_to_multiple(x, dim: int, multiple: int):
    """Zero-pad dimension `dim` of `x` up to a multiple (identity when
    already aligned) — the reduce_scatter callers' F-axis alignment."""
    extent = x.shape[dim]
    rem = extent % multiple
    if rem == 0:
        return x
    pad = [(0, 0)] * x.ndim
    pad[dim] = (0, multiple - rem)
    return jnp.pad(x, pad)


# --------------------------------------------------------------------- #
# compressed histogram reduction
# --------------------------------------------------------------------- #

def _reduce(x, axis_name, mode: str, scatter_dim: int):
    if mode == "reduce_scatter":
        return reduce_scatter(x, axis_name, scatter_dim)
    return psum(x, axis_name)


def hist_reduce(hist, axis_name, *, mode: str = "allreduce",
                comms_dtype: str = "f32", scatter_dim: int = 1):
    """The histogram collective: merge per-shard partial histograms over
    `axis_name`, replicated (`mode="allreduce"`) or slab-sharded along
    `scatter_dim` (`mode="reduce_scatter"`; callers pre-pad that dim to
    the axis size). `comms_dtype` down-converts the payload on the wire:

    - "f32": the exact baseline.
    - "bf16": 2 bytes/entry; each shard's partial rounds to bf16 before
      the reduce (accumulation stays f32 via an upcast — psum of bf16
      operands would also round every partial SUM).
    - "int32_fixed": 4 bytes/entry, but the reduction is an INTEGER sum
      on a shared fixed-point grid (scale = pmax of the local max-abs),
      so the merged histogram is bitwise independent of reduction order
      — N-partition merges become bit-stable where f32 psum order was
      not. An all-zero histogram short-circuits exactly (scale guard).
      The scale is derived from THIS call's tensor: slab-pipelined
      callers (ops/grow.level_histograms) therefore quantize each slab
      on its own — tighter — grid, so int32_fixed values depend on the
      slab count (deterministic, inside comms_error_bound, not bitwise
      vs the monolithic call; f32/bf16 are elementwise and slab-
      invariant).

    INTEGER partials (the quantized-gradient path, cfg.grad_dtype —
    ops/grad.py): int32 histograms already live on ONE shared
    fixed-point grid (the scale is derived from psum'd/pmax'd global
    stats before quantization), so the merge is a plain integer psum /
    reduce-scatter — order-independent bit-stable WITHOUT int32_fixed's
    per-collective scale carve-out, and overflow-free by the quantizer's
    sum-cap construction. Compression is REFUSED for them rather than
    silently double-quantizing (config.py raises at TrainConfig
    construction; this is the backstop for direct callers).

    Single-shard traces (axis_name None) skip compression entirely —
    there is no wire, so there must be no rounding."""
    if comms_dtype not in COMMS_DTYPES:
        raise ValueError(
            f"comms_dtype must be one of {COMMS_DTYPES}, got {comms_dtype!r}")
    if jnp.issubdtype(hist.dtype, jnp.integer):
        if comms_dtype != "f32":
            raise ValueError(
                f"hist_comms_dtype={comms_dtype!r} cannot compress integer "
                "(quantized-gradient) histogram partials: they already "
                "live on one shared fixed-point grid, so re-quantizing "
                "per collective would DOUBLE-quantize and void the "
                "grad_quant error bound; keep hist_comms_dtype='f32' "
                "(the integer merge is already bit-stable and needs no "
                "compression for order-independence)")
        return _reduce(hist, axis_name, mode, scatter_dim)
    if axis_name is None or comms_dtype == "f32":
        return _reduce(hist, axis_name, mode, scatter_dim)
    if comms_dtype == "bf16":
        with traced_scope("comms:compress"):
            x = hist.astype(jnp.bfloat16).astype(jnp.float32)
        return _reduce(x, axis_name, mode, scatter_dim)
    # int32_fixed: shared scale from the global max-abs; quantized
    # partials bounded by cap = _FIXED_CAP // P so the int32 sum cannot
    # overflow. round-half-away rounding matches the NumPy twin in
    # tests; dequantize AFTER the integer reduce.
    P = axis_size(axis_name)
    cap = _FIXED_CAP // P
    m = pmax(jnp.max(jnp.abs(hist)), axis_name)
    scale = jnp.where(m > 0, m / cap, jnp.float32(1.0))
    with traced_scope("comms:compress"):
        q = jnp.round(hist / scale).astype(jnp.int32)
    q = _reduce(q, axis_name, mode, scatter_dim)
    with traced_scope("comms:decompress"):
        return q.astype(jnp.float32) * scale


def comms_error_bound(comms_dtype: str, partitions: int,
                      max_abs: float) -> float:
    """Worst-case ABSOLUTE per-entry error the compressed merge can add
    to a histogram whose partials are bounded by `max_abs`, vs the exact
    f32 merge. The split-agreement contract tests hold measured
    deviations (and the gains derived from them) under this bound.

    - bf16: each of the P partials rounds once, relative error
      <= 2^-9 (8 mantissa bits + implicit) of that partial.
    - int32_fixed: each partial lands within half a grid step of its
      value (grid step = scale = max_abs / cap), plus the single f32
      rounding of the dequantized result (`int_sum * scale`), which is
      bounded by eps_f32 times the merged magnitude (<= P * max_abs)."""
    if comms_dtype == "f32":
        return 0.0
    if comms_dtype == "bf16":
        return partitions * max_abs * 2.0 ** -9
    if comms_dtype == "int32_fixed":
        cap = _FIXED_CAP // max(1, partitions)
        return (0.5 * partitions * max_abs / cap
                + partitions * max_abs * 2.0 ** -23)
    raise ValueError(f"unknown comms_dtype {comms_dtype!r}")


# --------------------------------------------------------------------- #
# split-winner combine (the reduce-scatter epilogue)
# --------------------------------------------------------------------- #

def combine_shard_winners(gains, feats, bins, dls, axis_name, *,
                          n_features: int, n_bins: int,
                          missing_bin: bool = False):
    """Combine per-shard best-split tuples into the global winner.

    Each shard ran the argmax over its own feature slab; `feats` are
    already GLOBAL indices. The payload is tiny — 4 x [n_level] per
    shard — and the tie-break is exact: maximum gain, ties broken by the
    smallest GLOBAL flattened candidate index (direction block first
    when missing_bin — RIGHT before LEFT — then feature, then bin),
    which is precisely jnp.argmax's first-occurrence rule on the
    single-device flattened gain table. Shard slab layout therefore
    cannot perturb split selection, interleaved slabs included."""
    if axis_name is None:
        return gains, feats, bins, dls
    with traced_scope("comms:winners"):
        ga = all_gather(gains, axis_name)          # [P, n_level]
        fa = all_gather(feats, axis_name)
        ba = all_gather(bins, axis_name)
        da = all_gather(dls, axis_name)
        # Global flattened candidate index (the single-device tie-break
        # key). int32 is safe: F < 2^19 and B <= 512 by the routing-pack
        # contract => 2*F*B < 2^29.
        flat = fa * n_bins + ba
        if missing_bin:
            flat = flat + da.astype(jnp.int32) * (n_features * n_bins)
        # Shards with a -inf slab winner (fully masked slab) must never
        # win; park their key past every real candidate.
        live = jnp.isfinite(ga)
        flat = jnp.where(live, flat, jnp.int32(2 ** 30))
        best_gain = jnp.max(ga, axis=0)
        tied = ga == best_gain[None, :]
        key = jnp.where(tied, flat, jnp.int32(2 ** 30))
        kmin = jnp.min(key, axis=0)
        # First axis-0 row matching the winning key (rows are distinct
        # per shard except exact candidate collisions, which cannot
        # happen: flat indices are globally unique per candidate).
        w = jnp.argmax(key == kmin[None, :], axis=0)
        take = lambda a: jnp.take_along_axis(a, w[None], axis=0)[0]  # noqa: E731
        return take(ga), take(fa), take(ba), take(da)


# --------------------------------------------------------------------- #
# resolution (the cfg.split_comms seam)
# --------------------------------------------------------------------- #

def resolve_split_comms(flag: str, *, distributed: bool,
                        feature_partitions: int = 1,
                        row_shards: "int | None" = None) -> str:
    """cfg.split_comms -> "allreduce" | "reduce_scatter" for this mesh.

    Since the 2D (rows x features) mesh landed (ROADMAP item 2),
    reduce-scatter split finding COMPOSES with a sharded feature axis:
    the scatter runs over the ROW axes *within* each feature slab (each
    of the Pr x Pf devices ends up with an F/(Pr*Pf) sub-slab) and the
    winner combine all_gathers over both axes — grow_tree wires it, so
    the old feature-sharded refusal is gone. `feature_partitions` is
    kept for signature compatibility; it no longer changes the answer.

    "auto" picks reduce_scatter exactly when a ROW wire exists —
    `row_shards` > 1 when the caller knows the row-axis extent (the
    hosts x rows product), else `distributed` as the legacy proxy. A
    pure feature mesh (Pr=1, Pf>1) has no row wire to scatter, so it
    resolves to allreduce (a size-1-axis scatter is an identity with
    extra ceremony). Forcing "reduce_scatter" without a row wire
    degrades to allreduce the same way."""
    if flag not in SPLIT_COMMS:
        raise ValueError(
            f"split_comms must be one of {SPLIT_COMMS}, got {flag!r}")
    if flag == "allreduce":
        return "allreduce"
    has_row_wire = (distributed if row_shards is None else row_shards > 1)
    return "reduce_scatter" if has_row_wire else "allreduce"


#: Auto slab count for the pipelined build+collective loop: enough
#: in-flight collectives to hide one DCN round-trip behind the next
#: slab's VPU work, few enough that per-slab kernels stay fat.
_AUTO_SLABS = 4


def resolve_comms_slabs(flag: int, *, distributed: bool,
                        platform: str | None = None) -> int:
    """cfg.hist_comms_slabs (0 = auto) -> the static slab count for the
    level loop's pipelined build+collective. Auto pipelines only on a
    real TPU mesh: that is where a wire exists to hide, and keeping the
    CPU suites on the monolithic path leaves their fixed-seed artifacts
    untouched (the phasing is bit-identical by construction — tested —
    but compile time isn't free). Explicit N >= 1 forces N everywhere
    (tests pipeline on the CPU mesh this way)."""
    if flag < 0:
        raise ValueError(f"hist_comms_slabs must be >= 0, got {flag}")
    if flag >= 1:
        return flag
    if not distributed:
        return 1
    if platform is None:
        platform = jax.default_backend()
    return _AUTO_SLABS if platform == "tpu" else 1
