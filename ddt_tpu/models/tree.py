"""TreeEnsemble: SoA tensor representation of a boosted-tree ensemble.

Layer L6 of SURVEY.md §1. The reference stores trees as arrays-of-nodes and
exposes `TreeEnsemble.predict` for batch scoring [BASELINE]. TPU realisation:
structure-of-arrays tensors in complete-heap layout so prediction lowers to
depth-unrolled gather+compare with fully static shapes (no pointers, no
recursion — XLA-friendly by construction).

Heap layout: a tree of `max_depth` split levels occupies 2^(max_depth+1)-1 node
slots; node i's children are 2i+1 (left) and 2i+2 (right). Early-stopped nodes
are marked `is_leaf` and traversal freezes there. Every node slot stores a
`leaf_value` (its value as-if-leaf), so traversal needs no special casing.

Split semantics (shared repo-wide, see data/quantizer.py): binned row goes LEFT
iff bin[feature] <= threshold_bin; raw row goes LEFT iff value <= threshold_raw.
"""

from __future__ import annotations

import dataclasses
import hashlib

import numpy as np

from ddt_tpu.utils.atomic import atomic_savez


@dataclasses.dataclass
class TreeEnsemble:
    """Boosted ensemble as stacked per-tree SoA arrays.

    Shapes: [n_trees, n_nodes_total] for all node arrays. For multiclass
    (softmax), trees are interleaved round-major: tree t scores class
    `t % n_classes` (n_trees = rounds * n_classes).
    """

    feature: np.ndarray        # int32  [T, N] split feature (-1 on leaves)
    threshold_bin: np.ndarray  # int32  [T, N] split bin (go left if <=)
    threshold_raw: np.ndarray  # float32 [T, N] raw-value threshold (same rule)
    is_leaf: np.ndarray        # bool   [T, N]
    leaf_value: np.ndarray     # float32 [T, N]
    split_gain: np.ndarray     # float32 [T, N] gain of the split (0 on leaves)
    max_depth: int
    n_features: int
    learning_rate: float
    base_score: float          # raw-score offset (per class for softmax)
    loss: str                  # logloss | mse | softmax
    n_classes: int = 2
    has_raw_thresholds: bool = False  # True once a BinMapper filled threshold_raw
    # Missing-value support (cfg.missing_policy="learn"): NaN rows occupy
    # the reserved top bin (n_bins-1) and route by the per-node learned
    # default direction. default_left is None for models trained without
    # the policy (and treated as all-False).
    default_left: np.ndarray | None = None   # bool [T, N]
    missing_bin: bool = False  # True: bin n_bins-1 is the NaN bin
    n_bins: int = 0            # binning width the model was trained with
    #   (0 = unknown/legacy; required when missing_bin is True)
    # Categorical one-vs-rest splits (cfg.cat_features): nodes splitting on
    # these FEATURE indices route "bin == threshold_bin goes left" instead
    # of "bin <= threshold_bin" — the split type derives from the feature,
    # no extra per-node storage. None/empty = all-ordinal model.
    cat_features: np.ndarray | None = None   # int32, sorted

    @property
    def n_trees(self) -> int:
        return int(self.feature.shape[0])

    @property
    def n_nodes_total(self) -> int:
        return int(self.feature.shape[1])

    @property
    def has_cat_splits(self) -> bool:
        """Whether any feature uses categorical one-vs-rest routing (the
        single home of the cat_features presence test)."""
        return self.cat_features is not None and len(self.cat_features) > 0

    # ------------------------------------------------------------------ #
    # compiled scoring layout (device predict fast path)
    # ------------------------------------------------------------------ #

    def cache_token(self) -> str:
        """Content digest of everything the device scoring program depends
        on — the CompiledEnsemble cache key. The node arrays are mutated
        in place by every trainer (ens.feature[t] = ...), so identity
        cannot key a cache; hashing the ~MBs of node arrays costs
        single-digit milliseconds against the seconds of re-upload/
        re-pushdown a miss would pay."""
        h = hashlib.sha1()
        for a in (self.feature, self.threshold_bin, self.is_leaf,
                  self.leaf_value):
            h.update(np.ascontiguousarray(a).tobytes())
        if self.default_left is not None:
            h.update(np.ascontiguousarray(self.default_left).tobytes())
        if self.has_cat_splits:
            h.update(np.ascontiguousarray(self.cat_features).tobytes())
        h.update(repr((self.max_depth, self.learning_rate, self.base_score,
                       self.loss, self.n_classes, self.missing_bin,
                       self.n_bins)).encode())
        return h.hexdigest()

    def compile(self, tree_chunk: int = 64) -> "CompiledEnsemble":
        """Host-side compiled scoring layout (see CompiledEnsemble)."""
        return CompiledEnsemble.build(self, tree_chunk=tree_chunk)

    # ------------------------------------------------------------------ #
    # NumPy prediction (oracle-grade; the fast path is ops/predict.py)
    # ------------------------------------------------------------------ #

    def _traverse_np(self, X: np.ndarray, binned: bool) -> np.ndarray:
        """Leaf index per (tree, row): int32 [T, R]."""
        if not binned and not self.has_raw_thresholds:
            raise ValueError(
                "Ensemble has no raw-value thresholds (trained without a "
                "BinMapper); predict on binned data with binned=True, or "
                "train/fill with a mapper first."
            )
        T = self.n_trees
        R = X.shape[0]
        node = np.zeros((T, R), dtype=np.int64)
        thr = self.threshold_bin if binned else self.threshold_raw
        Xc = X.astype(np.int32) if binned else X.astype(np.float32)
        use_missing = self.missing_bin and self.default_left is not None
        use_cat = self.has_cat_splits
        for _ in range(self.max_depth):
            feat = np.take_along_axis(self.feature, node, axis=1)
            t = np.take_along_axis(thr, node, axis=1)
            leaf = np.take_along_axis(self.is_leaf, node, axis=1)
            fv = np.stack([Xc[np.arange(R), np.maximum(feat[k], 0)]
                           for k in range(T)])
            go_right = fv > t
            if use_cat:
                # One-vs-rest: matched category goes left. Categorical
                # columns hold bin ids in BOTH representations (the
                # encoder output passes through identity edges), so the
                # comparison is against threshold_bin either way.
                tb = np.take_along_axis(self.threshold_bin, node, axis=1)
                go_right = np.where(np.isin(feat, self.cat_features),
                                    fv != tb, go_right)
            if use_missing:
                # NaN rows: binned = the reserved top bin; raw = NaN itself
                # (NaN > t is already False, but the learned direction may
                # be RIGHT). Route by per-node default_left.
                dl = np.take_along_axis(self.default_left, node, axis=1)
                miss = (fv == self.n_bins - 1) if binned else np.isnan(fv)
                go_right = np.where(miss, ~dl, go_right)
            nxt = 2 * node + 1 + go_right
            node = np.where(leaf, node, nxt)
        return node.astype(np.int32)

    def aggregate_leaves(self, leaf_idx: np.ndarray) -> np.ndarray:
        """Raw scores from precomputed leaf indices [T, R] — the single home
        of the leaf-value aggregation rule (lr scale, base score, softmax
        tree-to-class interleave: tree t scores class t % n_classes). Used
        by predict_raw here and by the native-traversal CPU backend path."""
        vals = np.take_along_axis(self.leaf_value, leaf_idx.astype(np.int64),
                                  axis=1)               # [T, R]
        vals = vals * self.learning_rate
        if self.loss == "softmax":
            C = self.n_classes
            R = leaf_idx.shape[1]
            out = np.full((R, C), self.base_score, dtype=np.float32)
            for t in range(self.n_trees):
                out[:, t % C] += vals[t]
            return out
        return (self.base_score + vals.sum(axis=0)).astype(np.float32)

    def predict_raw(self, X: np.ndarray, binned: bool = False) -> np.ndarray:
        """Raw (margin) scores. Binary/regression: [R]; softmax: [R, C]."""
        return self.aggregate_leaves(self._traverse_np(X, binned=binned))

    def _traverse_native(self, Xb: np.ndarray) -> "np.ndarray | None":
        """Leaf indices [T, R] via the native C++ kernel on BINNED data,
        or None when the library is unavailable — the ONE home of the
        native routing-flag derivation, shared by CPUDevice.predict_raw
        and predict_raw_roundwise. Missing-bin routing needs the learned
        directions; without default_left the reserved bin falls through
        to ordinary compares, exactly like _traverse_np's use_missing
        guard. Results are bitwise equal to _traverse_np (the
        predict-path fuzz asserts it)."""
        try:
            from ddt_tpu.native import traverse_native
        except Exception:   # no toolchain, or an unloadable .so (OSError
            return None     # from ctypes.CDLL) — NumPy path either way
        cat_node = (
            np.isin(self.feature, self.cat_features)
            if self.has_cat_splits else None
        )
        use_missing = self.missing_bin and self.default_left is not None
        return traverse_native(
            np.asarray(Xb), self.feature, self.threshold_bin,
            self.is_leaf, self.max_depth,
            default_left=self.default_left,
            missing_bin_value=self.n_bins - 1 if use_missing else -1,
            cat_node=cat_node,
        )

    def predict_raw_roundwise(self, X: np.ndarray,
                              binned: bool = False) -> np.ndarray:
        """predict_raw with the SAME float32 accumulation order as the
        Driver's fit loop (one sequential add per tree, in tree order) —
        aggregate_leaves' vals.sum(axis=0) uses NumPy pairwise summation,
        whose ULP-level differences would make checkpoint resume only
        approximately equal to an uninterrupted run. Used to reconstitute
        boosting state on resume so recovery is bit-exact.

        Traversal prefers the native C++ kernel on binned data: leaf
        indices are exact integers on every engine (the predict-path
        fuzz asserts native == NumPy bitwise — results are identical,
        measured), so only the accumulation below carries the ordering
        contract. On this 1-core build box the two traversals time the
        same (~21 s for 320 trees x 1M rows); the native path exists
        for many-core hosts, where the OpenMP parallel-for scales and
        NumPy stays single-threaded."""
        leaf_idx = self._traverse_native(X) if binned else None
        if leaf_idx is None:
            leaf_idx = self._traverse_np(X, binned=binned)      # [T, R]
        if self.loss == "softmax":
            # aggregate_leaves' softmax branch is already a sequential
            # per-tree loop in tree order — identical accumulation.
            return self.aggregate_leaves(leaf_idx)
        vals = np.take_along_axis(self.leaf_value,
                                  leaf_idx.astype(np.int64), axis=1)
        vals = (vals * self.learning_rate).astype(np.float32)
        out = np.full((leaf_idx.shape[1],), self.base_score, dtype=np.float32)
        for t in range(self.n_trees):
            out += vals[t]
        return out

    def predict(self, X: np.ndarray, binned: bool = False) -> np.ndarray:
        """Probability predictions (or raw values for mse)."""
        raw = self.predict_raw(X, binned=binned)
        if self.loss == "logloss":
            return 1.0 / (1.0 + np.exp(-raw))
        if self.loss == "softmax":
            z = raw - raw.max(axis=1, keepdims=True)
            e = np.exp(z)
            return e / e.sum(axis=1, keepdims=True)
        return raw

    # ------------------------------------------------------------------ #
    # Serialization (SURVEY.md §5 checkpoint/resume: ensembles are tiny)
    # ------------------------------------------------------------------ #

    def feature_importances(self, kind: str = "split") -> np.ndarray:
        """Normalized per-feature importance, float32 [n_features].

        kind="split": fraction of internal-node splits using the feature;
        kind="gain": fraction of total split gain attributed to the feature
        (LightGBM's importance_type="split"/"gain")."""
        mask = (~self.is_leaf) & (self.feature >= 0)
        used = self.feature[mask]
        if kind == "split":
            w = np.ones(used.shape[0])
        elif kind == "gain":
            w = self.split_gain[mask].astype(np.float64)
        else:
            raise ValueError(f"unknown importance kind {kind!r}")
        counts = np.bincount(used, weights=w, minlength=self.n_features)
        counts = counts[: self.n_features].astype(np.float64)
        tot = counts.sum()
        return (counts / tot if tot > 0 else counts).astype(np.float32)

    def dump(self, tree: int) -> dict:
        """One tree as a nested plain-Python dict (debugging / interop).

        Split nodes: {"split": {"feature", "bin", "threshold" (raw value or
        None), "gain"}, "left", "right"}; leaves: {"leaf": value}. The raw
        threshold is only present when the ensemble holds BinMapper-filled
        thresholds."""
        t = int(tree)

        def node(i: int) -> dict:
            if self.is_leaf[t, i] or self.feature[t, i] < 0:
                return {"leaf": float(self.leaf_value[t, i])}
            return {
                "split": {
                    "feature": int(self.feature[t, i]),
                    "bin": int(self.threshold_bin[t, i]),
                    "threshold": (
                        float(self.threshold_raw[t, i])
                        if self.has_raw_thresholds else None
                    ),
                    "gain": float(self.split_gain[t, i]),
                },
                "left": node(2 * i + 1),
                "right": node(2 * i + 2),
            }

        return node(0)

    def dump_text(self, tree: int) -> str:
        """Indented text rendering of one tree."""
        lines: list[str] = []

        def walk(d: dict, depth: int) -> None:
            pad = "  " * depth
            if "leaf" in d:
                lines.append(f"{pad}leaf={d['leaf']:+.6f}")
                return
            s = d["split"]
            thr = (f" (<= {s['threshold']:.6g})"
                   if s["threshold"] is not None else "")
            lines.append(
                f"{pad}f{s['feature']} <= bin {s['bin']}{thr}  "
                f"gain={s['gain']:.4g}"
            )
            walk(d["left"], depth + 1)
            walk(d["right"], depth + 1)

        walk(self.dump(tree), 0)
        return "\n".join(lines)

    def to_lightgbm_text(self, feature_names: list[str] | None = None
                         ) -> str:
        """LightGBM model.txt rendering (models/lightgbm_io.py): load with
        lightgbm.Booster(model_str=...) or diff against a LightGBM model
        tree-by-tree (docs/REAL_DATA.md)."""
        from ddt_tpu.models.lightgbm_io import to_lightgbm_text

        return to_lightgbm_text(self, feature_names=feature_names)

    @staticmethod
    def from_lightgbm_text(text: str) -> "TreeEnsemble":
        """Parse a LightGBM model.txt (models/lightgbm_io.py)."""
        from ddt_tpu.models.lightgbm_io import from_lightgbm_text

        return from_lightgbm_text(text)

    def to_dict(self) -> dict:
        return {
            "feature": self.feature,
            "threshold_bin": self.threshold_bin,
            "threshold_raw": self.threshold_raw,
            "is_leaf": self.is_leaf,
            "leaf_value": self.leaf_value,
            "split_gain": self.split_gain,
            "default_left": self._dl(),
            "max_depth": np.int64(self.max_depth),
            "n_features": np.int64(self.n_features),
            "learning_rate": np.float64(self.learning_rate),
            "base_score": np.float64(self.base_score),
            "loss": np.bytes_(self.loss.encode()),
            "n_classes": np.int64(self.n_classes),
            "has_raw_thresholds": np.bool_(self.has_raw_thresholds),
            "missing_bin": np.bool_(self.missing_bin),
            "n_bins": np.int64(self.n_bins),
            # NB: named so it does NOT collide with the model-artifact
            # encoder keys ("cat_"-prefixed, api.save_model).
            "categorical_features": (
                self.cat_features if self.cat_features is not None
                else np.zeros(0, np.int32)
            ),
        }

    @staticmethod
    def from_dict(d: dict) -> "TreeEnsemble":
        return TreeEnsemble(
            feature=np.asarray(d["feature"], np.int32),
            threshold_bin=np.asarray(d["threshold_bin"], np.int32),
            threshold_raw=np.asarray(d["threshold_raw"], np.float32),
            is_leaf=np.asarray(d["is_leaf"], bool),
            leaf_value=np.asarray(d["leaf_value"], np.float32),
            split_gain=np.asarray(
                d["split_gain"] if "split_gain" in d
                else np.zeros_like(d["leaf_value"]),
                np.float32),    # absent in pre-gain saves: zeros
            default_left=(
                np.asarray(d["default_left"], bool)
                if "default_left" in d
                else np.zeros(np.asarray(d["is_leaf"]).shape, bool)
            ),
            max_depth=int(d["max_depth"]),
            n_features=int(d["n_features"]),
            learning_rate=float(d["learning_rate"]),
            base_score=float(d["base_score"]),
            loss=bytes(d["loss"]).decode(),
            n_classes=int(d["n_classes"]),
            has_raw_thresholds=bool(d.get("has_raw_thresholds", False)),
            missing_bin=bool(d.get("missing_bin", False)),
            n_bins=int(d.get("n_bins", 0)),
            cat_features=(
                np.asarray(d["categorical_features"], np.int32)
                if "categorical_features" in d
                and np.asarray(d["categorical_features"]).size
                else None
            ),
        )

    def save(self, path: str) -> None:
        # tmp-then-replace (the atomic-artifact-write contract): a kill
        # mid-save never leaves a torn model file behind. The embedded
        # manifest (schema version, content digest, git rev —
        # registry/manifest.py) makes the bare-ensemble artifact
        # self-describing too; `load` ignores the extra key, and
        # api.load_model digest-verifies it (docs/REGISTRY.md).
        from ddt_tpu.registry import manifest as manifest_mod

        d = self.to_dict()
        manifest_mod.embed_npz_manifest(d, kind="tree_ensemble")
        atomic_savez(path, compressed=True, deterministic=True, **d)

    @staticmethod
    def load(path: str) -> "TreeEnsemble":
        with np.load(path) as d:
            return TreeEnsemble.from_dict(dict(d))

    def _dl(self) -> np.ndarray:
        return (self.default_left if self.default_left is not None
                else np.zeros_like(self.is_leaf))

    def truncate(self, n_trees: int) -> "TreeEnsemble":
        """First `n_trees` trees (early stopping keeps the best round)."""
        return dataclasses.replace(
            self,
            feature=self.feature[:n_trees],
            threshold_bin=self.threshold_bin[:n_trees],
            threshold_raw=self.threshold_raw[:n_trees],
            is_leaf=self.is_leaf[:n_trees],
            leaf_value=self.leaf_value[:n_trees],
            split_gain=self.split_gain[:n_trees],
            default_left=self._dl()[:n_trees],
        )

    @staticmethod
    def concat(ensembles: list["TreeEnsemble"]) -> "TreeEnsemble":
        """Stack ensembles trained sequentially (used by checkpoint resume)."""
        head = ensembles[0]
        return dataclasses.replace(
            head,
            feature=np.concatenate([e.feature for e in ensembles]),
            threshold_bin=np.concatenate([e.threshold_bin for e in ensembles]),
            threshold_raw=np.concatenate([e.threshold_raw for e in ensembles]),
            is_leaf=np.concatenate([e.is_leaf for e in ensembles]),
            leaf_value=np.concatenate([e.leaf_value for e in ensembles]),
            split_gain=np.concatenate([e.split_gain for e in ensembles]),
            default_left=np.concatenate([e._dl() for e in ensembles]),
        )


def _effective_arrays_np(feature, thr, is_leaf, leaf_value, max_depth):
    """Host twin of ops/predict._effective_arrays (leaf-chain pushdown):
    (eff_feat, eff_thr, eff_val) with every node below a leaf inheriting
    the leaf's value, leaf/inherited nodes carrying feature=-1 and
    thr=+BIG. Bitwise-identical to the traced version — both are pure
    integer/copy selects — so hoisting the pushdown to host (the
    CompiledEnsemble cache) changes no prediction."""
    big = (np.asarray(np.inf, thr.dtype)
           if np.issubdtype(thr.dtype, np.floating)
           else np.asarray(2 ** 30, thr.dtype))
    eff_feat = np.where(is_leaf, np.int32(-1), feature).astype(np.int32)
    eff_thr = np.where(is_leaf, big, thr).astype(thr.dtype)
    eff_val = np.array(leaf_value, np.float32)
    chained = np.array(is_leaf, bool)
    for d in range(1, max_depth + 1):
        lo, hi = (1 << d) - 1, (1 << (d + 1)) - 1
        par = (np.arange(lo, hi) - 1) // 2
        pch = chained[:, par]
        eff_feat[:, lo:hi] = np.where(pch, -1, eff_feat[:, lo:hi])
        eff_thr[:, lo:hi] = np.where(pch, big, eff_thr[:, lo:hi])
        eff_val[:, lo:hi] = np.where(pch, eff_val[:, par],
                                     eff_val[:, lo:hi])
        chained[:, lo:hi] = pch | is_leaf[:, lo:hi]
    return eff_feat, eff_thr, eff_val


@dataclasses.dataclass(frozen=True)
class CompiledEnsemble:
    """Precomputed BINNED scoring layout for one model: pushdown applied,
    trees padded to a tree_chunk multiple, class one-hot built — every
    per-call rebuild the old predict path paid (the resident-vs-total
    bench gap showed ~27% of predict wall time was re-upload/setup),
    hoisted to ONE host-side build per model version.

    Consumed by ops/predict.predict_raw_effective (one-hot or Pallas
    core); device backends key a small LRU of device-resident copies on
    `token` (TPUDevice._predict_fn), so repeated scoring calls against an
    unchanged model re-upload nothing and re-push nothing. Raw-threshold
    (float) scoring keeps the uncompiled predict_raw path — the device
    batch-scoring contract is binned."""

    token: str                 # TreeEnsemble.cache_token() at build time
    tree_chunk: int
    max_depth: int
    n_classes_out: int         # C: softmax n_classes, else 1
    learning_rate: float
    base_score: float
    loss: str
    missing_bin_value: int     # reserved NaN bin id, -1 = no missing
    eff_feat: np.ndarray       # int32 [Tpad, N] pushed-down
    eff_thr: np.ndarray        # int32 [Tpad, N] pushed-down (bins)
    bot_val: np.ndarray        # float32 [Tpad, 2^D] bottom-level values
    cls_oh: np.ndarray         # float32 [Tpad, C] round-major class 1-hot
    eff_dl: np.ndarray | None  # bool [Tpad, N] or None
    eff_cat: np.ndarray | None  # bool [Tpad, N] or None

    @property
    def n_trees_padded(self) -> int:
        return int(self.eff_feat.shape[0])

    def arrays(self) -> tuple:
        """Device-uploadable operand tuple in predict_raw_effective's
        argument order (optional masks appended when present)."""
        out = [self.eff_feat, self.eff_thr, self.bot_val, self.cls_oh]
        if self.eff_dl is not None:
            out.append(self.eff_dl)
        if self.eff_cat is not None:
            out.append(self.eff_cat)
        return tuple(out)

    def quantize(self, leaf_dtype: str = "float16"):
        """TreeLUT-style quantized scoring tables (ops/predict_lut.
        QuantizedTables): int8 recentred thresholds (EXACT — bin ids are
        integers in [0, 255]), fp16 / int8+scale / int4+scale leaf
        tables ("int4" is the bit-packed tier's logical form —
        `.pack_int4()` makes the two-nibbles-per-byte device layout),
        and a computed `max_abs_err` bound on |lut - f32| (the rounding
        contract documented in ops/predict_lut.py). The low-latency
        serving opt-in (cfg.predict_impl="lut"/"lut4" / `cli predict
        --quantized[=int4]` / ServeEngine(quantize=...)). Lazy import
        keeps this module jax-free for hosts that never score quantized.

        Memoized per leaf_dtype (this instance is immutable — frozen
        snapshot of one model version): the serving tier quantizes at
        publish for its error-bound reporting and the backend quantizes
        again on first LUT dispatch — one O(model) host pass, shared."""
        memo = self.__dict__.get("_quant_memo")
        if memo is None:
            memo = {}
            object.__setattr__(self, "_quant_memo", memo)
        if leaf_dtype not in memo:
            from ddt_tpu.ops.predict_lut import quantize_compiled

            memo[leaf_dtype] = quantize_compiled(
                self, leaf_dtype=leaf_dtype)
        return memo[leaf_dtype]

    def seed_quantized(self, tables) -> None:
        """Install pre-built tables as this instance's quantization:
        `quantize(leaf_dtype=tables.leaf_dtype)` — including the
        backend's first LUT dispatch — returns them verbatim instead of
        re-deriving. The registry loader seeds the artifact's CARRIED
        lut_tables.npz here so the exported int8 representation is what
        serves, even across version skew in the quantization routine."""
        memo = self.__dict__.get("_quant_memo")
        if memo is None:
            memo = {}
            object.__setattr__(self, "_quant_memo", memo)
        memo[tables.leaf_dtype] = tables

    @staticmethod
    def build(ens: TreeEnsemble, tree_chunk: int = 64
              ) -> "CompiledEnsemble":
        T, N = ens.feature.shape
        n_tc = -(-T // tree_chunk)
        tpad = n_tc * tree_chunk - T

        def pad_t(a, fill=0):
            return np.pad(a, ((0, tpad), (0, 0)), constant_values=fill)

        # Padded trees are all-leaf at the root with value 0 ->
        # contribute exactly 0.0 to their class column (the same padding
        # predict_raw applies in-trace).
        ef, et, ev = _effective_arrays_np(
            pad_t(ens.feature, -1).astype(np.int32),
            pad_t(ens.threshold_bin).astype(np.int32),
            pad_t(ens.is_leaf, True), pad_t(ens.leaf_value),
            ens.max_depth,
        )
        C = ens.n_classes if ens.loss == "softmax" else 1
        lo = (1 << ens.max_depth) - 1
        cls = np.arange(n_tc * tree_chunk, dtype=np.int64) % C
        cls_oh = np.zeros((n_tc * tree_chunk, C), np.float32)
        cls_oh[np.arange(len(cls)), cls] = 1.0
        use_missing = ens.missing_bin and ens.default_left is not None
        eff_dl = pad_t(ens.default_left) if use_missing else None
        eff_cat = (pad_t(np.isin(ens.feature, ens.cat_features))
                   if ens.has_cat_splits else None)
        return CompiledEnsemble(
            token=ens.cache_token(), tree_chunk=tree_chunk,
            max_depth=ens.max_depth, n_classes_out=C,
            learning_rate=float(ens.learning_rate),
            base_score=float(ens.base_score), loss=ens.loss,
            missing_bin_value=(ens.n_bins - 1 if use_missing else -1),
            eff_feat=ef, eff_thr=et,
            bot_val=np.ascontiguousarray(ev[:, lo:]),
            cls_oh=cls_oh, eff_dl=eff_dl, eff_cat=eff_cat,
        )


def empty_ensemble(
    n_trees: int,
    max_depth: int,
    n_features: int,
    learning_rate: float,
    base_score: float,
    loss: str,
    n_classes: int = 2,
    missing_bin: bool = False,
    n_bins: int = 0,
    cat_features: tuple = (),
) -> TreeEnsemble:
    n_nodes = 2 ** (max_depth + 1) - 1
    return TreeEnsemble(
        feature=np.full((n_trees, n_nodes), -1, np.int32),
        threshold_bin=np.zeros((n_trees, n_nodes), np.int32),
        threshold_raw=np.zeros((n_trees, n_nodes), np.float32),
        is_leaf=np.zeros((n_trees, n_nodes), bool),
        leaf_value=np.zeros((n_trees, n_nodes), np.float32),
        split_gain=np.zeros((n_trees, n_nodes), np.float32),
        default_left=np.zeros((n_trees, n_nodes), bool),
        max_depth=max_depth,
        n_features=n_features,
        learning_rate=learning_rate,
        base_score=base_score,
        loss=loss,
        n_classes=n_classes,
        missing_bin=missing_bin,
        n_bins=n_bins,
        cat_features=(np.asarray(cat_features, np.int32)
                      if cat_features else None),
    )
