"""LightGBM model.txt interop (round-2 verdict item 8).

`to_lightgbm_text` renders a TreeEnsemble in LightGBM's plain-text model
format so the eventual real-data validation (docs/REAL_DATA.md) can diff
models tree-by-tree against a LightGBM run — not just compare AUCs —
and so LightGBM tooling (its own Booster(model_str=...), SHAP, treelite)
can load models trained here. `from_lightgbm_text` is the repo's own
re-parser: the round-trip test (export -> parse -> identical predictions)
keeps the writer honest without LightGBM installed.

Format notes (LightGBM's text serialization, stable since v2):
- one `Tree=<i>` block per tree; arrays are space-separated lines
- internal nodes are numbered 0..num_leaves-2, leaves 0..num_leaves-1;
  child references encode leaves as ~leaf_idx (i.e. -(leaf_idx+1))
- routing: value <= threshold goes LEFT (same rule as this repo's
  threshold_raw semantics)
- decision_type bit 1 (value 2) = missing values default LEFT; bits 2-3
  = missing type (0 none, 1 zero, 2 NaN)
- leaf_value carries the FINAL additive contribution (shrinkage already
  applied); the ensemble's base score is folded into tree 0's leaves
  (LightGBM's boost_from_average does the same)

Exportable models: ordinal splits need raw thresholds (train through a
BinMapper). Categorical one-vs-rest splits export as LightGBM categorical
nodes (decision_type bit 0): the node's `threshold` is an index into
`cat_boundaries`, which offsets into the `cat_threshold` uint32 bitset
array; a value v routes LEFT when bit v is set. One-vs-rest means every
exported bitset has exactly ONE bit set (the matched category). NaN
handling on cat nodes mirrors ordinal nodes (missing type NaN + per-node
default direction) — that matches this repo's traversal, not LightGBM's
own NaN-in-categorical convention (to_lightgbm_text warns when a model
mixes the two, so users don't assume cross-tool NaN parity on cat
splits).

Import breadth (round-5): the re-parser ALSO accepts multi-bit bitsets —
the externally-trained-LightGBM case (a real LightGBM categorical split
sends a SET of categories left). A k-bit set is expanded into a chain of
k one-vs-rest nodes: each chain link tests one member category (matched
goes LEFT into a copy of the original left subtree); the last link's
right child is the original right subtree. Routing is exactly equivalent
— including NaN rows, which follow the node's default direction at every
link (default-left exits into the left subtree at link 0; default-right
falls through the whole chain into the right subtree). Costs: tree depth
grows by k-1 per multi-bit node (the heap overflows past depth 30 and
raises, naming the node), the left subtree is materialised k times, and
split_gain is recorded on the first link only (0 on the rest) so
gain-sum feature importances are preserved.
"""

from __future__ import annotations

import warnings

import numpy as np

from ddt_tpu.models.tree import TreeEnsemble

_MISSING_NAN = 2 << 2        # decision_type missing-type field: NaN
_DEFAULT_LEFT = 2            # decision_type default-left bit
_CATEGORICAL = 1             # decision_type categorical-split bit


def _objective(ens: TreeEnsemble) -> str:
    if ens.loss == "logloss":
        return "binary sigmoid:1"
    if ens.loss == "softmax":
        return f"multiclass num_class:{ens.n_classes}"
    return "regression"


def _fmt(values) -> str:
    return " ".join(f"{float(v):.17g}" for v in values)


def _fmt_int(values) -> str:
    return " ".join(str(int(v)) for v in values)


def to_lightgbm_text(ens: TreeEnsemble,
                     feature_names: list[str] | None = None) -> str:
    """Render the ensemble as a LightGBM model.txt string."""
    if not ens.has_raw_thresholds:
        raise ValueError(
            "LightGBM export needs raw-value thresholds; train through a "
            "BinMapper (api.train) or fill them with "
            "reference.numpy_trainer._fill_raw_thresholds first"
        )
    cat_set = (set(int(f) for f in ens.cat_features)
               if ens.has_cat_splits else set())
    if feature_names is None:
        feature_names = [f"Column_{i}" for i in range(ens.n_features)]
    C = ens.n_classes if ens.loss == "softmax" else 1
    lines = [
        "tree",
        "version=v3",
        f"num_class={C}",
        f"num_tree_per_iteration={C}",
        "label_index=0",
        f"max_feature_idx={ens.n_features - 1}",
        f"objective={_objective(ens)}",
        "feature_names=" + " ".join(feature_names),
        "feature_infos=" + " ".join(["[-inf:inf]"] * ens.n_features),
        "",
    ]
    use_missing = ens.missing_bin and ens.default_left is not None
    if use_missing and cat_set:
        warnings.warn(
            "exporting a model with BOTH learned NaN default directions "
            "and categorical splits: this repo routes NaN on categorical "
            "nodes by the per-node default direction, which differs from "
            "LightGBM's own NaN-in-categorical convention — the exported "
            "model scores NaN rows differently when loaded into real "
            "LightGBM (module docstring, 'NaN handling')",
            stacklevel=2,
        )
    for t in range(ens.n_trees):
        # Pre-order walk of the heap: internal nodes and leaves numbered
        # in encounter order (root = internal 0, LightGBM's convention).
        split_feature: list[int] = []
        split_gain: list[float] = []
        threshold: list[float] = []
        decision_type: list[int] = []
        left_child: list[int] = []
        right_child: list[int] = []
        leaf_value: list[float] = []
        cat_boundaries: list[int] = [0]    # prefix offsets into cat words
        cat_threshold: list[int] = []      # uint32 bitset words

        def walk(slot: int) -> int:
            """Returns the LightGBM child reference for heap `slot`:
            internal index, or ~leaf_idx for a leaf."""
            if ens.is_leaf[t, slot] or ens.feature[t, slot] < 0:
                v = float(ens.leaf_value[t, slot]) * ens.learning_rate
                if t < C:                      # fold base into round 0
                    v += ens.base_score
                leaf_value.append(v)
                return -len(leaf_value)        # ~(leaf_idx) == -(idx+1)
            i = len(split_feature)
            feat = int(ens.feature[t, slot])
            split_feature.append(feat)
            split_gain.append(float(ens.split_gain[t, slot]))
            dt = 0
            if feat in cat_set:
                # One-vs-rest: a single-bit bitset (matched category goes
                # LEFT); threshold holds the index into cat_boundaries.
                k = int(ens.threshold_bin[t, slot])
                words = [0] * (k // 32 + 1)
                words[k // 32] = 1 << (k % 32)
                threshold.append(float(len(cat_boundaries) - 1))
                cat_threshold.extend(words)
                cat_boundaries.append(len(cat_threshold))
                dt |= _CATEGORICAL
            else:
                threshold.append(float(ens.threshold_raw[t, slot]))
            if use_missing:
                dt |= _MISSING_NAN
                if ens.default_left[t, slot]:
                    dt |= _DEFAULT_LEFT
            decision_type.append(dt)
            left_child.append(0)               # patched after recursion
            right_child.append(0)
            left_child[i] = walk(2 * slot + 1)
            right_child[i] = walk(2 * slot + 2)
            return i

        walk(0)
        n_leaves = len(leaf_value)
        n_cat = len(cat_boundaries) - 1
        zeros = [0.0] * n_leaves
        izeros = [0] * max(1, n_leaves - 1)
        lines += [
            f"Tree={t}",
            f"num_leaves={n_leaves}",
            f"num_cat={n_cat}",
            "split_feature=" + _fmt_int(split_feature),
            "split_gain=" + _fmt(split_gain),
            "threshold=" + _fmt(threshold),
            "decision_type=" + _fmt_int(decision_type),
            "left_child=" + _fmt_int(left_child),
            "right_child=" + _fmt_int(right_child),
            "leaf_value=" + _fmt(leaf_value),
            "leaf_weight=" + _fmt(zeros),
            "leaf_count=" + _fmt_int([0] * n_leaves),
            "internal_value=" + _fmt([0.0] * max(1, n_leaves - 1)),
            "internal_weight=" + _fmt([0.0] * max(1, n_leaves - 1)),
            "internal_count=" + _fmt_int(izeros),
        ]
        if n_cat:
            lines += [
                "cat_boundaries=" + _fmt_int(cat_boundaries),
                "cat_threshold=" + _fmt_int(cat_threshold),
            ]
        lines += [
            "is_linear=0",
            f"shrinkage={ens.learning_rate:.17g}",
            "",
        ]
    lines += ["end of trees", "", "pandas_categorical:null", ""]
    return "\n".join(lines)


def _parse_block(lines: list[str], i: int) -> tuple[dict, int]:
    d: dict = {}
    while i < len(lines) and lines[i].strip():
        k, _, v = lines[i].partition("=")
        d[k] = v
        i += 1
    return d, i


def from_lightgbm_text(text: str) -> TreeEnsemble:
    """Parse a LightGBM model.txt back into a TreeEnsemble (heap layout).

    Supports what to_lightgbm_text writes (numerical splits, single-bit
    categorical nodes, optional NaN-missing default directions) PLUS
    externally-trained models with multi-category bitsets, which expand
    into equivalent one-vs-rest chains (module docstring, 'Import
    breadth'). Trees deeper than 30 levels after chain expansion overflow
    the heap and raise."""
    lines = text.splitlines()
    head, i = _parse_block(lines, 0)
    n_features = int(head["max_feature_idx"]) + 1
    C = int(head.get("num_class", 1))
    obj = head.get("objective", "regression")
    loss = ("logloss" if obj.startswith("binary")
            else "softmax" if obj.startswith("multiclass") else "mse")

    trees = []
    while i < len(lines):
        if not lines[i].startswith("Tree="):
            i += 1
            continue
        blk, i = _parse_block(lines, i)
        trees.append(blk)

    # Per-internal-node category-bit lists (None for numerical nodes),
    # parsed ONCE per tree: both the depth computation and the placement
    # need them — a k-bit categorical set expands into a k-link chain, so
    # it contributes k levels of depth where a numerical node adds 1.
    def bits_of(blk, t: int) -> list:
        if int(blk["num_leaves"]) == 1:
            return []
        sf = [int(v) for v in blk["split_feature"].split()]
        th = [float(v) for v in blk["threshold"].split()]
        dt = [int(float(v)) for v in blk["decision_type"].split()]
        cb = ct = None
        if int(blk.get("num_cat", "0")) != 0:
            cb = [int(v) for v in blk["cat_boundaries"].split()]
            ct = [int(v) for v in blk["cat_threshold"].split()]
        out: list = []
        for ref in range(len(sf)):
            if not (dt[ref] & _CATEGORICAL):
                out.append(None)
                continue
            if cb is None:
                # Malformed/foreign input: categorical decision_type bit
                # set but the tree block carries no bitset arrays. Fail
                # loudly like the other validation paths (a None subscript
                # would raise an opaque TypeError here otherwise).
                raise ValueError(
                    f"tree {t} node {ref}: categorical decision_type but "
                    "num_cat=0 (no cat_boundaries/cat_threshold arrays)"
                )
            cat_idx = int(th[ref])
            words = ct[cb[cat_idx]:cb[cat_idx + 1]]
            bits = [w * 32 + b for w, word in enumerate(words)
                    for b in range(32) if word >> b & 1]
            if not bits and (dt[ref] >> 2) == 2 and dt[ref] & _DEFAULT_LEFT:
                # Empty bitset + NaN-missing + default-LEFT: no category
                # matches, but NaN rows still exit into the LEFT subtree,
                # so the node cannot collapse away. Emit one match-nothing
                # link (sentinel category -1: LightGBM category values are
                # non-negative, so no real value ever equals it) whose
                # default_left carries the NaN route.
                bits = [-1]
            out.append(bits)
        return out

    tree_bits = [bits_of(b, t) for t, b in enumerate(trees)]

    # Depth of each parsed tree (longest root->leaf path), counting each
    # k-bit categorical node as the k levels its expansion chain occupies
    # (an all-rows-right empty bitset collapses to its RIGHT subtree:
    # 0 levels, and the dropped left subtree contributes no depth).
    def depth_of(blk, bits) -> int:
        if int(blk["num_leaves"]) == 1:
            return 0
        lc = [int(v) for v in blk["left_child"].split()]
        rc = [int(v) for v in blk["right_child"].split()]

        def d(ref: int) -> int:
            if ref < 0:
                return 0
            b = bits[ref]
            if b is None:                      # numerical node
                return 1 + max(d(lc[ref]), d(rc[ref]))
            if not b:                          # collapsed empty bitset
                return d(rc[ref])
            return len(b) + max(d(lc[ref]), d(rc[ref]))
        return d(0)

    max_depth = max(1, max(depth_of(b, bi)
                           for b, bi in zip(trees, tree_bits)))
    if max_depth > 30:
        raise ValueError(
            f"tree depth {max_depth} (after multi-category chain "
            "expansion) overflows the heap layout")
    # The heap is DENSE and its depth is GLOBAL: one k-category set deep
    # in one tree adds k-1 levels to EVERY tree's 2^(D+1)-1 node arrays.
    # Real LightGBM categorical splits routinely carry dozens of
    # categories, where the expansion allocates astronomically — fail
    # with the cause and the number, not a MemoryError from np.full.
    # 2^27 total nodes ~ 2.3 GB across the seven node arrays.
    total_nodes = len(trees) * (2 ** (max_depth + 1) - 1)
    if total_nodes > 2 ** 27:
        widest = max((len(b) for bi in tree_bits
                      for b in bi if b is not None), default=1)
        raise ValueError(
            f"multi-category chain expansion needs depth {max_depth} "
            f"across {len(trees)} trees = {total_nodes} heap nodes "
            f"(> 2^27): the dense heap layout cannot hold this model "
            f"(widest category set: {widest} bits). Models with large "
            "categorical sets are unrepresentable here; score them with "
            "LightGBM itself, or retrain with "
            "cat_features one-vs-rest splits"
        )
    n_nodes = 2 ** (max_depth + 1) - 1
    T = len(trees)
    feature = np.full((T, n_nodes), -1, np.int32)
    threshold_bin = np.zeros((T, n_nodes), np.int32)
    threshold_raw = np.zeros((T, n_nodes), np.float32)
    is_leaf = np.zeros((T, n_nodes), bool)
    leaf_value = np.zeros((T, n_nodes), np.float32)
    split_gain = np.zeros((T, n_nodes), np.float32)
    default_left = np.zeros((T, n_nodes), bool)
    any_missing = False
    cat_feats: set[int] = set()    # features with categorical nodes
    ord_feats: set[int] = set()    # features with numerical nodes

    for t, blk in enumerate(trees):
        bits_t = tree_bits[t]
        lv = [float(v) for v in blk["leaf_value"].split()]
        if int(blk["num_leaves"]) == 1:
            is_leaf[t, 0] = True
            leaf_value[t, 0] = lv[0]
            continue
        sf = [int(v) for v in blk["split_feature"].split()]
        sg = [float(v) for v in blk["split_gain"].split()]
        th = [float(v) for v in blk["threshold"].split()]
        dt = [int(float(v)) for v in blk["decision_type"].split()]
        lc = [int(v) for v in blk["left_child"].split()]
        rc = [int(v) for v in blk["right_child"].split()]

        def place(ref: int, slot: int, dup: bool = False) -> None:
            # `dup`: this subtree is a repeated COPY made by chain
            # expansion — its split gains are zeroed so gain-sum feature
            # importances count each original split exactly once.
            nonlocal any_missing
            if ref < 0:
                is_leaf[t, slot] = True
                leaf_value[t, slot] = lv[~ref]
                return
            bits = bits_t[ref]
            miss = (dt[ref] >> 2) == 2         # NaN missing type
            if miss:
                any_missing = True
            if bits is None:                   # numerical split
                ord_feats.add(sf[ref])
                feature[t, slot] = sf[ref]
                split_gain[t, slot] = 0.0 if dup else sg[ref]
                threshold_raw[t, slot] = th[ref]
                if miss:
                    default_left[t, slot] = bool(dt[ref] & _DEFAULT_LEFT)
                place(lc[ref], 2 * slot + 1, dup)
                place(rc[ref], 2 * slot + 2, dup)
                return
            if not bits:
                # Empty bitset reaching here means no category matches
                # AND NaN routes right too (default-right, or no missing
                # handling) — bits_of keeps a sentinel link otherwise —
                # so the node collapses to its right subtree; the no-op
                # split's gain vanishes with it.
                place(rc[ref], slot, dup)
                return
            # Categorical set -> a chain of one-vs-rest links: link j
            # tests bits[j] (matched goes LEFT into a copy of the left
            # subtree); the last link's right child is the right subtree.
            # NaN rows follow the node's default direction at EVERY link,
            # so default-left exits left at link 0 and default-right
            # falls through the chain — exactly the un-expanded routing.
            cat_feats.add(sf[ref])
            cur = slot
            for j, b in enumerate(bits):
                feature[t, cur] = sf[ref]
                # Gain on the first link only (same once-per-split rule).
                split_gain[t, cur] = 0.0 if dup or j > 0 else sg[ref]
                # Cat columns hold category ids in BOTH representations,
                # so bin and raw thresholds coincide.
                threshold_bin[t, cur] = b
                threshold_raw[t, cur] = float(b)
                if miss:
                    default_left[t, cur] = bool(dt[ref] & _DEFAULT_LEFT)
                place(lc[ref], 2 * cur + 1, dup or j > 0)
                if j < len(bits) - 1:
                    cur = 2 * cur + 2
            place(rc[ref], 2 * cur + 2, dup)

        place(0, 0)

    both = cat_feats & ord_feats
    if both:
        raise ValueError(
            f"features {sorted(both)} appear in both categorical and "
            "numerical nodes; TreeEnsemble derives split type from the "
            "feature, so mixed use is unrepresentable"
        )

    return TreeEnsemble(
        feature=feature,
        threshold_bin=threshold_bin,
        threshold_raw=threshold_raw,
        is_leaf=is_leaf,
        leaf_value=leaf_value,
        split_gain=split_gain,
        max_depth=max_depth,
        n_features=n_features,
        learning_rate=1.0,          # leaf values are final contributions
        base_score=0.0,             # folded into round 0's leaves
        loss=loss,
        n_classes=max(C, 2),
        has_raw_thresholds=True,
        cat_features=(np.asarray(sorted(cat_feats), np.int32)
                      if cat_feats else None),
        default_left=default_left if any_missing else None,
        # Raw-value traversal tests np.isnan directly; missing_bin=True
        # just switches the learned default_left directions on.
        missing_bin=any_missing,
    )
