"""Driver: the host-side tree-construction / boosting loop (layer L5).

The reference's `Driver` grows trees level-by-level against a `DeviceBackend`
and is explicitly "unchanged above the operator layer" when backends swap
[BASELINE]. This Driver is that loop, shaped for TPU dispatch economics
(SURVEY.md §3 call stack):

    for round in 1..n_trees:                      (sequential, host)
      g, h = backend.grad_hess(pred, y)           (device, fused elementwise)
      for c in classes:                           (1 for binary/mse)
        handle, delta = backend.grow_tree(data, g_c, h_c) (ONE device dispatch:
              histograms → [psum over mesh] → gains → splits → row routing,
              all levels)
        pred = backend.apply_delta(pred, delta, c)
      ensemble[t-1] = backend.fetch_tree(prev_handle)     (≈KBs to host, ONE
              transfer, pipelined one round behind so the device→host
              round-trip hides under the next tree's compute)

Boosting state (`pred`) is an opaque backend handle — on TPUDevice it lives
sharded on device for the whole run; the Driver never sees a float of it.

Observability (SURVEY.md §5): structured per-round records (train loss,
ms/tree) via `logging`, collected in `Driver.history`, and — when a
`run_log` is attached — emitted as schema-versioned JSONL telemetry events
(ddt_tpu/telemetry: run manifest, per-round records, per-phase timings,
early-stop decisions, resume/fault events, device counters; render with
`ddt_tpu.cli report`). With no run_log the hot loop pays nothing: no device
syncs, no file I/O. Checkpoint/resume
(SURVEY.md §5): pass `checkpoint_dir` — after every `checkpoint_every` rounds
the partial ensemble + cursor is written; `fit` resumes from the cursor if a
checkpoint exists (utils/checkpoint.py).

Validation tracking: `fit(..., eval_set=(Xb_val, y_val))` scores the held-out
set every round by incremental host-side traversal of each freshly grown tree
(O(rows·depth) NumPy — the val set never occupies device memory), records
`valid_<metric>` in history, and with `early_stopping_rounds=k` stops when
the metric hasn't improved in k rounds and truncates the ensemble to the best
round (utils/metrics.py).

Two documented exceptions to the cross-backend determinism story (the split
DECISIONS are bit-identical per ops/split.py; these are about reported
SCORES):

- f32 score boundary: device backends evaluate metrics with their f32 device
  twins (utils/metrics.device_metric) while host backends use the f64 host
  implementations, so per-round validation scores — and early-stopping
  choices on rounds tied within f32 resolution — can differ between TPU and
  CPU backends for the same data. Binary auc rides the binned-rank device
  twin (round-5: auc eval/early-stop now stays on the fused dispatch path),
  whose within-bin tie mass widens this seam to ~1/DEVICE_AUC_BINS (~2e-5)
  on the score values. (Softmax-auc is rejected at fit — the rank
  formulation is binary.)
- Resume score seam: on checkpoint resume with a device backend and an
  eval_set, val predictions are reconstituted by host roundwise rescoring,
  which differs from the uninterrupted device accumulation by FMA-contraction
  ULPs; near-tied best_round selection may shift across a resume. (The
  streaming trainer replays the device ops instead and is bit-exact — its
  runs are the week-long ones where this matters.)
"""

from __future__ import annotations

import dataclasses
import logging
import time

import numpy as np

from ddt_tpu.backends.base import DeviceBackend
from ddt_tpu.config import TrainConfig
from ddt_tpu.models.tree import TreeEnsemble, empty_ensemble
from ddt_tpu.reference.numpy_trainer import base_score
from ddt_tpu.robustness import faultplan, set_fault_sink
from ddt_tpu.telemetry import costmodel
from ddt_tpu.telemetry import counters as tele_counters
from ddt_tpu.telemetry.annotations import phase_ctx
from ddt_tpu.telemetry.events import (
    PartitionRecorder, RoundRecorder, RunLog, comms_manifest_fields,
    derive_run_id, emit_early_stop, emit_train_heartbeat, finish_run_log)
from ddt_tpu.utils import checkpoint
from ddt_tpu.utils.profiling import PhaseTimer

log = logging.getLogger("ddt_tpu.driver")

# The cap on rounds per fused dispatch is cfg.fused_block_rounds — a
# config field (not a constant) because it encodes a remote-runtime
# watchdog interaction that varies by deployment; rationale in
# TrainConfig's field docstring.


def _traverse_one(
    feature: np.ndarray,
    threshold_bin: np.ndarray,
    is_leaf: np.ndarray,
    Xb: np.ndarray,
    max_depth: int,
    default_left: np.ndarray | None = None,
    missing_bin_value: int = -1,
    cat_features: tuple = (),
) -> np.ndarray:
    """Leaf heap-slot per row for ONE tree (node arrays [n_nodes])."""
    R = Xb.shape[0]
    rows = np.arange(R)
    node = np.zeros(R, np.int64)
    for _ in range(max_depth):
        leaf = is_leaf[node]
        feat = feature[node]
        fv = Xb[rows, np.maximum(feat, 0)]
        go_right = fv > threshold_bin[node]
        if cat_features is not None and len(cat_features):
            go_right = np.where(np.isin(feat, cat_features),
                                fv != threshold_bin[node], go_right)
        if missing_bin_value >= 0:
            go_right = np.where(fv == missing_bin_value,
                                ~default_left[node], go_right)
        nxt = 2 * node + 1 + go_right
        node = np.where(leaf, node, nxt)
    return node


class Driver:
    """Backend-agnostic boosting driver (the L5→L4 contract consumer)."""

    def __init__(
        self,
        backend: DeviceBackend,
        cfg: TrainConfig,
        log_every: int = 10,
        checkpoint_dir: str | None = None,
        checkpoint_every: int = 25,
        profile: bool = False,
        run_log: "RunLog | str | None" = None,
        profiler_window=None,
        status=None,
    ):
        self.backend = backend
        self.cfg = cfg
        self.log_every = log_every
        self.checkpoint_dir = checkpoint_dir
        if checkpoint_dir is not None and checkpoint_every < 1:
            raise ValueError(
                f"checkpoint_every must be >= 1, got {checkpoint_every}")
        self.checkpoint_every = checkpoint_every
        self.history: list[dict] = []
        self.best_round: int | None = None
        self.best_score: float | None = None
        # profile=True records a per-phase wallclock breakdown (SURVEY.md §5
        # tracing): each phase ends with a device barrier, so rounds get
        # SLOWER (the fast path pipelines phases without syncs) but the
        # report shows where device time actually goes. A run_log alone
        # also times phases — WITHOUT the barriers (numbers then measure
        # host dispatch + whatever the async queue back-pressures, which
        # is honest for a pipeline) and WITHOUT forcing the granular path.
        self.profile = profile
        # A path string means the Driver OWNS the log (opens and closes
        # it); a RunLog instance stays the caller's to close.
        self._own_run_log = isinstance(run_log, str)
        self.run_log = RunLog.coerce(run_log)
        self.timer = (
            PhaseTimer() if (profile or self.run_log is not None) else None
        )
        self._recorder: RoundRecorder | None = None
        self._part_rec: PartitionRecorder | None = None
        # Device-truth cost capture (telemetry/costmodel.py): a collector
        # is installed only for telemetry runs (_fit prologue) and torn
        # down in fit's finally — runs without a log never lower/compile
        # anything extra (guard-tested).
        self._cost = None
        # Programmatic xprof capture window (telemetry/profiler.py), or
        # None — every hook below is behind an `is not None` check.
        self._window = profiler_window
        # Live-ops status aggregate (telemetry/statusd.TrainStatus), or
        # None — same gating contract as the window above: without
        # `--status-port` the trainer holds no statusd state and every
        # round-boundary hook is one `is not None` test (ISSUE 20).
        self._status = status

    def _draw_colsample_mask(self, rnd: int, c: int, F: int) -> np.ndarray:
        """The per-(seed, round, class) colsample feature mask; the draw
        itself lives in ops/sampling.colsample_mask (shared with the
        streaming trainers) because the fused == granular == streamed
        ensemble-parity guarantee depends on every path drawing
        bit-identical masks."""
        from ddt_tpu.ops.sampling import colsample_mask

        return colsample_mask(self.cfg.seed, rnd, c, F,
                              self.cfg.colsample_bytree)

    def _psync(self, x) -> None:
        """Backend barrier on x's producer chain — only when PROFILING
        (the fast path must stay sync-free to pipeline rounds; a run_log
        alone adds zero syncs); no-op on host-resident backends."""
        if self.profile:
            self.backend.sync(x)

    def _finish_run(self, t0: float, completed_rounds: int,
                    counters_start: dict | None) -> None:
        """Telemetry epilogue shared by the granular and fused paths:
        phase report at INFO (profiled runs), then the shared
        phase_timings / counters / run_end epilogue
        (telemetry.events.finish_run_log)."""
        if self.profile and self.timer is not None:
            self.timer.log_report(log)
        if self._status is not None:
            self._status.set_phase("done")
        finish_run_log(self.run_log, self.timer, counters_start,
                       completed_rounds,
                       round(time.perf_counter() - t0, 4),
                       partitions=self._part_rec, costs=self._cost)

    def fit(
        self,
        Xb: np.ndarray,
        y: np.ndarray,
        eval_set: tuple[np.ndarray, np.ndarray] | None = None,
        eval_metric: str | None = None,
        early_stopping_rounds: int | None = None,
        sample_weight: np.ndarray | None = None,
    ) -> TreeEnsemble:
        """Train on binned uint8 data. Returns the grown ensemble.

        `sample_weight` (float [R], >= 0, not all zero): per-row instance
        weights scaling each row's gradient/hessian contribution and the
        weighted-mean training loss; the base score becomes the weighted
        mean. Integer weights are exactly equivalent to duplicating rows
        (tested). Validation metrics stay unweighted; the streaming
        trainer does not take weights.

        (Ownership shim around _fit: a Driver-OWNED run log — one built
        from a path string — is closed on every exit, success or mid-run
        exception such as the NaN-eval ValueError, so repeated failing
        fits cannot leak file handles. fit_streaming carries the same
        shim. The same shim scopes the robustness state: the fault-event
        sink points at this run's log for the duration, and a
        cfg.fault_plan chaos plan is activated here — unless one is
        already active process-wide, e.g. the CLI armed it before
        multihost bootstrap — and deactivated on every exit.)"""
        # Load the plan BEFORE touching any process-global state: a bad
        # plan file must fail clean, not leak the sink or collectors.
        plan = None
        if self.cfg.fault_plan and faultplan.active_plan() is None:
            plan = faultplan.load_plan(self.cfg.fault_plan)
        prev_sink = set_fault_sink(self.run_log)
        plan_prev = None
        plan_armed = False
        if plan is not None:
            plan_prev = faultplan.activate(plan)
            plan_armed = True
        try:
            return self._fit(
                Xb, y, eval_set=eval_set, eval_metric=eval_metric,
                early_stopping_rounds=early_stopping_rounds,
                sample_weight=sample_weight)
        finally:
            # Cost capture must not outlive its run (a later telemetry-
            # less fit in the same process must pay zero capture work),
            # and a still-open xprof window (death inside the round
            # range) must be stopped so the trace flushes.
            costmodel.deactivate(self._cost)
            if self._window is not None:
                self._window.close()
            if plan_armed:
                faultplan.deactivate(plan_prev)
            set_fault_sink(prev_sink)
            if self._own_run_log and self.run_log is not None:
                self.run_log.close()

    def _fit(
        self,
        Xb: np.ndarray,
        y: np.ndarray,
        eval_set: tuple[np.ndarray, np.ndarray] | None = None,
        eval_metric: str | None = None,
        early_stopping_rounds: int | None = None,
        sample_weight: np.ndarray | None = None,
    ) -> TreeEnsemble:
        """fit's body (see fit for the full contract)."""
        cfg = self.cfg
        R, F = Xb.shape
        if Xb.dtype != np.uint8:
            raise TypeError(f"Xb must be uint8 binned data, got {Xb.dtype}")
        C = cfg.n_classes if cfg.loss == "softmax" else 1
        if cfg.cat_features and cfg.cat_features[-1] >= F:
            # Validate here, where F is known: the TPU path's scatter
            # would silently DROP out-of-bounds indices (JAX semantics)
            # while the NumPy twin raises — a backend-parity trap.
            raise ValueError(
                f"cat_features index {cfg.cat_features[-1]} out of range "
                f"for {F} features"
            )
        if sample_weight is not None:
            sample_weight = np.asarray(sample_weight, np.float32)
            if sample_weight.shape != (R,):
                raise ValueError(
                    f"sample_weight must be [R]={R}, got "
                    f"{sample_weight.shape}")
            if not np.all(np.isfinite(sample_weight)) \
                    or (sample_weight < 0).any():
                raise ValueError("sample_weight must be finite and >= 0")
            if not (sample_weight > 0).any():
                raise ValueError("sample_weight is all zero")
        bs = base_score(np.asarray(y), cfg.loss, cfg.n_classes,
                        sample_weight=sample_weight)

        # Telemetry prologue — BEFORE the first upload so the transfer
        # counters see the data plane; all of it is host-side bookkeeping
        # (zero device syncs) and absent entirely when run_log is None.
        t_fit0 = time.perf_counter()
        counters_start = None
        # The deterministic config digest serves two consumers: the v2
        # manifest merge key AND the xprof capture window's trace-dir
        # name (telemetry/profiler.py) — computed whenever either wants
        # it. The FULL config feeds the digest: two sweep points
        # differing only in, say, learning_rate must refuse to merge, so
        # no field may be left out.
        run_id = None
        if self.run_log is not None or self._window is not None \
                or self._status is not None:
            run_id = derive_run_id(
                trainer="driver", rows=int(R), features=int(F),
                **dataclasses.asdict(cfg))
        # Exposed for artifact provenance: api.train stamps it into the
        # TrainResult so saved models' embedded manifests (and registry
        # artifacts) cross-reference this run's log (docs/REGISTRY.md).
        self.run_id = run_id
        if self._window is not None:
            self._window.bind(run_id)
        if self._status is not None:
            self._status.begin_run(run_id=run_id,
                                   total_rounds=cfg.n_trees, rows=int(R))
        if self.run_log is not None:
            tele_counters.install_jax_listener()
            counters_start = tele_counters.snapshot()
            # Device-truth cost capture (telemetry/costmodel.py): active
            # for this run only; deactivated in fit's finally.
            self._cost = costmodel.activate()
            self.run_log.emit(
                "run_manifest", trainer="driver",
                backend=self.backend.name, loss=cfg.loss,
                n_trees=cfg.n_trees, max_depth=cfg.max_depth,
                n_bins=cfg.n_bins, rows=int(R), features=int(F),
                n_classes=C, seed=cfg.seed,
                distributed=bool(getattr(self.backend, "distributed",
                                         False)),
                # v2 extras: the cross-host merge key + lane label
                # (telemetry.merge) — identical on every pod host by SPMD
                # construction.
                run_id=run_id,
                host=int(getattr(self.backend, "host_index", 0)),
                # ISSUE-10 extras (schema extras, no version bump): the
                # RESOLVED split-finding comms config — report renders
                # the per-mode comms line from these.
                **comms_manifest_fields(self.backend),
                # v3 extras: the xprof cross-reference — a flight-recorder
                # lane and a profiler session join on run_id through
                # these (telemetry/profiler.py).
                **(self._window.manifest_fields()
                   if self._window is not None else {}))

        data = self.backend.upload(Xb)
        y_dev = self.backend.upload_labels(np.asarray(y),
                                           sample_weight=sample_weight)
        pred = self.backend.init_pred(y_dev, bs)

        ens = empty_ensemble(
            cfg.n_trees * C, cfg.max_depth, F, cfg.learning_rate, bs,
            cfg.loss, cfg.n_classes,
            missing_bin=cfg.missing_policy == "learn", n_bins=cfg.n_bins,
            cat_features=cfg.cat_features,
        )

        start_round = 0
        if self.checkpoint_dir is not None:
            from ddt_tpu.utils.checkpoint import try_resume

            start_round = try_resume(self.checkpoint_dir, ens, cfg,
                                     run_log=self.run_log)
            if start_round > 0:
                # Reconstitute boosting state by rescoring the partial
                # ensemble with fit's own per-round accumulation order, so
                # resumed training is BIT-identical to an uninterrupted run
                # (pairwise-summed predict_raw differs in ULPs, which could
                # flip a bf16-boundary gain downstream).
                part = ens.truncate(start_round * C)
                pred = self.backend.load_pred(
                    np.asarray(part.predict_raw_roundwise(Xb, binned=True))
                )
                log.info("resumed from checkpoint at round %d", start_round)
                if self.run_log is not None:
                    self.run_log.emit("fault", kind="checkpoint_resume",
                                      round=start_round)

        # --- validation-set state ---
        # Two realisations of per-round eval scoring:
        #   device (TPUDevice): validation predictions live ON DEVICE; each
        #   round's packed tree handles are applied there (eval_round), so
        #   the host never traverses the val set and the tree-fetch
        #   pipeline stays on. Only the metric crosses to host — a scalar
        #   when its f32 device twin exists (every shipped valid metric),
        #   else a raw-score vector (the twin-less-metric fallback).
        #   host (CPUDevice): incremental NumPy traversal per tree.
        metric_name = None
        val_raw = None
        use_dev_eval = False
        dev_metric = None
        val_data_dev = val_y_dev = val_pred_dev = None
        if eval_set is not None:
            from ddt_tpu.utils.metrics import (
                GREATER_IS_BETTER, default_metric, evaluate)

            Xb_val, y_val = eval_set
            Xb_val = np.asarray(Xb_val)
            y_val = np.asarray(y_val)
            if Xb_val.dtype != np.uint8:
                raise TypeError("eval_set features must be uint8 binned data")
            metric_name = eval_metric or default_metric(cfg.loss)
            if metric_name not in GREATER_IS_BETTER:
                raise ValueError(
                    f"unknown metric {metric_name!r}; "
                    f"have {sorted(GREATER_IS_BETTER)}"
                )
            if metric_name == "auc" and C > 1:
                # The rank formulation is binary; multiclass raw scores
                # would crash deep inside the host auc (shape mismatch on
                # ravel) — fail at the cause instead.
                raise ValueError(
                    "auc is a binary metric; softmax eval_set supports "
                    "logloss or accuracy"
                )
            sign = 1.0 if GREATER_IS_BETTER[metric_name] else -1.0
            if C > 1:
                val_raw = np.full((Xb_val.shape[0], C), bs, np.float32)
            else:
                val_raw = np.full(Xb_val.shape[0], bs, np.float32)
            if start_round > 0:
                k = start_round * C
                val_raw = ens.truncate(k).predict_raw_roundwise(
                    Xb_val, binned=True).astype(np.float32)
            best = -np.inf
            if getattr(self.backend, "eval_round", None) is not None:
                from ddt_tpu.utils.metrics import device_metric

                use_dev_eval = True
                dev_metric = (
                    metric_name
                    if device_metric(metric_name, n_classes=C) is not None
                    else None
                )
                val_data_dev = self.backend.upload(Xb_val)
                val_y_dev = self.backend.upload_labels(y_val)
                val_pred_dev = self.backend.load_pred(val_raw)
        elif early_stopping_rounds is not None:
            raise ValueError("early_stopping_rounds requires an eval_set")

        t_out = start_round * C
        completed_rounds = cfg.n_trees
        # One-deep fetch pipeline: a device backend's grow_tree returns an
        # unresolved handle; resolving it costs a device→host round-trip
        # (~tens of ms on a remote-attached chip), so we fetch tree k while
        # tree k+1 computes. HOST-side eval needs each tree immediately for
        # incremental scoring (pipeline bypassed); device-side eval applies
        # the handle on device, so the pipeline stays on.
        pending: tuple | None = None   # (handle, ensemble slot)

        # Phase context (telemetry.annotations.phase_ctx): host PhaseTimer
        # + a `ddt:<phase>` profiler span, so Perfetto host tracks carry
        # the same names as the run log's phase_timings; bare nullcontext
        # when neither profiling nor telemetry is on.
        ph = phase_ctx(self.timer)

        self._recorder = RoundRecorder(
            self.history, self.run_log, self.log_every, cfg.n_trees,
            metric_name, log)
        # Estimated allreduce payload per round (telemetry.counters): the
        # psum lives inside the fused device program, so the host records
        # the statically-known histogram shapes instead of observing the
        # wire. Zero on single-device runs.
        coll_bytes_round = 0
        if getattr(self.backend, "distributed", False):
            # EFFECTIVE payload for the resolved comms config (mode,
            # wire dtype, subtraction) — backends/tpu.py
            # collective_bytes_per_tree is the one home.
            coll_bytes_round = C * self.backend.collective_bytes_per_tree(F)
        # Effective per-round g/h HBM stream (telemetry.counters
        # grad_stream_bytes — the quantized-gradient byte win's witness:
        # f32 and int8/int16 runs record their own dtype's model, so two
        # run logs' counters carry the ratio).
        self._grad_bytes_round = C * tele_counters.grad_stream_bytes(
            R, cfg.max_depth, cfg.grad_dtype)
        # Per-partition attribution (the distributed flight recorder):
        # active only on mesh runs WITH a run log — it probes per-device
        # shard completion, which is a barrier on the observed handle.
        # Single-device runs and disabled telemetry get the inert
        # recorder (no probes, no syncs — the PR-2 invariant).
        self._part_rec = part_rec = PartitionRecorder(
            self.run_log, self.backend, bytes_per_round=coll_bytes_round)
        # Straggler watchdog (robustness/watchdog.py): consumes the
        # recorder's per-round lanes, so it exists exactly when the
        # recorder does — detection events always, the repartition
        # ACTION only behind cfg.straggler_repartition (which also
        # forces the granular path below: the rotation needs a round
        # boundary a fused block does not yield).
        self._watchdog = None
        if part_rec.active:
            from ddt_tpu.robustness.watchdog import StragglerWatchdog

            self._watchdog = StragglerWatchdog(
                threshold=cfg.straggler_skew_threshold)

        def _store(handle, slot):
            with ph("fetch_tree"):
                tree = self.backend.fetch_tree(handle)
            ens.feature[slot] = tree["feature"]
            ens.threshold_bin[slot] = tree["threshold_bin"]
            ens.is_leaf[slot] = tree["is_leaf"]
            ens.leaf_value[slot] = tree["leaf_value"]
            ens.split_gain[slot] = tree["split_gain"]
            ens.default_left[slot] = tree["default_left"]
            return tree

        # Stochastic training (cfg.subsample / cfg.colsample_bytree):
        # bagging row masks are STATELESS counter-based draws — a pure
        # hash of (seed, round, global row id), ops/sampling — so every
        # path (host-drawn here, device in-scan on the fused path,
        # per-chunk in the streaming trainers) computes the identical bit
        # on every backend/partition layout AND across checkpoint resume.
        # Colsample [F] feature masks stay host-drawn (KBs; same shared
        # home, ops/sampling.colsample_mask).
        bagging = cfg.subsample < 1.0
        colsample = cfg.colsample_bytree < 1.0

        # Fused block path: backends exposing grow_rounds run whole blocks
        # of rounds in one device dispatch + one tree fetch (per-round
        # dispatch latency dominates on a remote-attached chip). Validation
        # rides INSIDE the scan (grow_rounds_eval) when its metric has a
        # device twin; EARLY STOPPING rides too — the stopping rule is
        # replayed post-hoc over the block's per-round scores vector
        # (training past the stop point cannot change earlier trees, so
        # truncation gives the EXACT granular-path model; blocks are
        # capped at the patience so overrun work is bounded). Every
        # stochastic-training combination composes with the fused path
        # since round 5: colsample [K, C, F] masks (KBs) and bagging's
        # round ids both ride the scan as xs (the row masks themselves
        # are recomputed in-scan from the counter hash), with or without
        # in-scan eval. Only profiling always runs granular (per-phase
        # barriers), plus the host-eval fallbacks below.
        fused_eval = (
            eval_set is not None
            and use_dev_eval
            and dev_metric is not None
            and getattr(self.backend, "grow_rounds_eval", None) is not None
        )
        fused_masked = (
            colsample
            and getattr(self.backend, "grow_rounds_masked", None)
            is not None
        )
        if (
            getattr(self.backend, "grow_rounds", None) is not None
            and (eval_set is None or fused_eval)
            and not self.profile
            and not cfg.straggler_repartition
            and (not colsample or fused_masked)
        ):
            eval_state = None
            if fused_eval:
                eval_state = (val_data_dev, val_pred_dev, val_y_dev,
                              dev_metric, sign)
            ens = self._fit_fused(
                data, y_dev, pred, ens, start_round, C,
                eval_state=eval_state,
                early_stopping_rounds=early_stopping_rounds,
                colsample_features=F if colsample else None,
                coll_bytes_round=coll_bytes_round)
            self._finish_run(t_fit0, ens.n_trees // C, counters_start)
            return ens

        for rnd in range(start_round, cfg.n_trees):
            if self._window is not None:      # xprof window: start edge
                self._window.round_start(rnd)
            t0 = time.perf_counter()
            round_handles: list = []
            with ph("grad"):
                g, h = self.backend.grad_hess(pred, y_dev)
                self._psync(h)
            if bagging:
                from ddt_tpu.ops.sampling import row_keep_np

                rmask = row_keep_np(cfg.seed, rnd, 0, R, cfg.subsample)
                g, h = self.backend.apply_row_mask(g, h, rmask)
            for c in range(C):
                gc = g[:, c] if C > 1 else g
                hc = h[:, c] if C > 1 else h
                fmask = (
                    self._draw_colsample_mask(rnd, c, F) if colsample
                    else None
                )
                tg0 = time.perf_counter()
                with ph("grow"):
                    handle, delta = self.backend.grow_tree(
                        data, gc, hc, feature_mask=fmask,
                        tree_id=rnd * C + c)
                    self._psync(delta)
                # Flight recorder: per-device completion of this tree's
                # growth (hist + allreduce + gain + route). No-op unless
                # distributed AND a run log is attached.
                part_rec.observe("grow", handle, tg0)
                with ph("apply_delta"):
                    pred = self.backend.apply_delta(pred, delta, c)
                    self._psync(pred)
                if use_dev_eval:
                    round_handles.append(handle)
                    if pending is not None:
                        _store(*pending)
                    pending = (handle, t_out)
                elif val_raw is not None:
                    tree = _store(handle, t_out)
                    leaf = _traverse_one(
                        tree["feature"], tree["threshold_bin"],
                        tree["is_leaf"], Xb_val, cfg.max_depth,
                        default_left=tree["default_left"],
                        missing_bin_value=cfg.missing_bin_value,
                        cat_features=cfg.cat_features,
                    )
                    dv = cfg.learning_rate * tree["leaf_value"][leaf]
                    if C > 1:
                        val_raw[:, c] += dv
                    else:
                        val_raw += dv
                else:
                    if pending is not None:
                        _store(*pending)
                    pending = (handle, t_out)
                t_out += 1

            val_score = None
            if use_dev_eval:
                with ph("eval"):
                    val_pred_dev, sc = self.backend.eval_round(
                        val_data_dev, val_pred_dev, round_handles,
                        val_y_dev, dev_metric)
                if dev_metric is not None:
                    val_score = float(sc)
                else:           # metric has no f32 device twin (auc):
                    # sc is a replicated copy of the predictions (safe to
                    # resolve even on a multi-host mesh); pad rows dropped.
                    val_score = evaluate(
                        metric_name, y_val,
                        np.asarray(sc)[: Xb_val.shape[0]],
                    )
            elif val_raw is not None:
                val_score = evaluate(metric_name, y_val, val_raw)
            dt = time.perf_counter() - t0
            if coll_bytes_round:
                tele_counters.record_collective(coll_bytes_round)
            tele_counters.record_grad_stream(self._grad_bytes_round)
            if cfg.grad_dtype != "f32":
                tele_counters.record_grad_quant_round()

            if val_score is not None:
                if sign * val_score > best:
                    best = sign * val_score
                    self.best_round = rnd
                    self.best_score = val_score

            self._recorder.record(
                rnd, dt * 1e3, val_score,
                lambda: self.backend.loss_value(pred, y_dev))
            self._observe_straggler(rnd, part_rec.flush_round(rnd))
            if self._window is not None:      # xprof window: stop edge
                self._window.round_end(rnd)
            tele_counters.record_train_round()
            if self._status is not None:      # live-ops plane (ISSUE 20)
                # history only holds on-cadence records; off-cadence
                # rounds get a fresh bare record for the /debug ring.
                self._status.round_end(
                    rnd, dt * 1e3,
                    self.history[-1]
                    if (self.history
                        and self.history[-1].get("round") == rnd + 1)
                    else RoundRecorder.make_record(rnd, dt * 1e3, None))

            if early_stopping_rounds is not None and self.best_round is None:
                # NaN never compares greater, so a NaN-from-round-1 metric
                # leaves best_round unset; fail with the cause, not a
                # TypeError from the subtraction below.
                raise ValueError(
                    f"validation {metric_name} has been NaN since round 1 "
                    "(degenerate eval_set — e.g. constant scores or a "
                    "single-class slice); cannot early-stop on it"
                )
            if (
                early_stopping_rounds is not None
                and rnd - self.best_round >= early_stopping_rounds
            ):
                log.info(
                    "early stop at round %d (best %s=%.6f at round %d)",
                    rnd + 1, metric_name, self.best_score,
                    self.best_round + 1,
                )
                emit_early_stop(self.run_log, rnd + 1, metric_name,
                                self.best_round + 1, self.best_score)
                if pending is not None:   # flush BEFORE truncating: the
                    _store(*pending)      # pending slot indexes the full-
                    pending = None        # size arrays
                ens = ens.truncate((self.best_round + 1) * C)
                completed_rounds = self.best_round + 1
                break

            if (
                self.checkpoint_dir is not None
                and (rnd + 1) % self.checkpoint_every == 0
            ):
                if pending is not None:        # flush the fetch pipeline
                    _store(*pending)
                    pending = None
                checkpoint.maybe_save(self.checkpoint_dir, ens, cfg,
                                      rnd + 1)
                if self._status is not None:
                    self._status.checkpoint_saved(rnd + 1)
            # Liveness heartbeat at the checkpoint CADENCE, checkpoint
            # directory or not (ISSUE 20): a SIGKILLed run's log ends at
            # most one cadence past its last heartbeat, which is what
            # `report progress` rolls up. No-op without a run log.
            if self.run_log is not None and self.checkpoint_every >= 1 \
                    and (rnd + 1) % self.checkpoint_every == 0:
                emit_train_heartbeat(
                    self.run_log, rnd=rnd, total_rounds=cfg.n_trees,
                    checkpoint_round=(rnd + 1
                                      if self.checkpoint_dir is not None
                                      else None),
                    ms_per_round=dt * 1e3,
                    rows_per_s=(R / dt if dt > 0 else None))
            if self.checkpoint_every >= 1 \
                    and (rnd + 1) % self.checkpoint_every == 0 \
                    and self._wants_repartition():
                # The watchdog's action fires only on the checkpoint
                # CADENCE (with or without a directory): the rotation
                # recompiles every mesh-bound program, so it must be
                # paid at a boundary, never mid-stride. The pending
                # fetch is flushed first — its handle belongs to the
                # pre-rotation mesh.
                if pending is not None:
                    _store(*pending)
                    pending = None
                (data, y_dev, pred, val_data_dev, val_y_dev,
                 val_pred_dev) = self._repartition(
                    rnd, data, y_dev, pred, val_data_dev, val_y_dev,
                    val_pred_dev, C)

        if pending is not None:                # flush the fetch pipeline
            _store(*pending)
            pending = None

        checkpoint.maybe_save(self.checkpoint_dir, ens, cfg,
                              completed_rounds)
        self._finish_run(t_fit0, completed_rounds, counters_start)
        return ens

    def _observe_straggler(self, rnd: int, parts: "dict | None") -> None:
        """One round's flushed partition lanes -> the watchdog (shared
        feed: robustness.watchdog.feed_watchdog — warning + fault
        event). No-op when either side is absent."""
        if self._watchdog is None:
            return
        from ddt_tpu.robustness.watchdog import feed_watchdog

        feed_watchdog(self._watchdog, self.run_log, rnd, parts, log)

    def _wants_repartition(self) -> bool:
        # The 2D (rows x features) mesh repartitions too since ISSUE 11:
        # rotate_row_partitions rolls the ROW axis of the device grid
        # (feature columns preserved), so no feature_partitions guard.
        return (self._watchdog is not None
                and self._watchdog.pending_repartition
                and self.cfg.straggler_repartition
                and getattr(self.backend, "rotate_row_partitions", None)
                is not None)

    def _repartition(self, rnd: int, data, y_dev, pred,
                     val_data, val_y, val_pred, C: int) -> tuple:
        """The watchdog's action: rotate the row-shard -> device
        assignment (backend.rotate_row_partitions — shard contents and
        therefore the model are untouched) and move every live handle
        onto the new mesh. Runs at checkpoint boundaries only; emits a
        `repartition` fault event so the run log shows when lanes
        moved."""
        be = self.backend
        if not be.rotate_row_partitions():
            # Nothing to rotate (single device / multi-process mesh):
            # acknowledge so the watchdog does not re-request every
            # boundary.
            self._watchdog.repartition_done()
            return data, y_dev, pred, val_data, val_y, val_pred
        extra = 1 if C > 1 else 0
        data = be.reshard_data(data)
        y_dev = type(y_dev)(be.reshard_rows(y_dev.y),
                            be.reshard_rows(y_dev.valid))
        pred = be.reshard_rows(pred, extra_dims=extra)
        if val_data is not None:
            val_data = be.reshard_data(val_data)
        if val_y is not None:
            val_y = type(val_y)(be.reshard_rows(val_y.y),
                                be.reshard_rows(val_y.valid))
        if val_pred is not None:
            val_pred = be.reshard_rows(val_pred, extra_dims=extra)
        log.warning("repartitioned at round %d: rotated row shards off "
                    "the straggling device", rnd + 1)
        if self.run_log is not None:
            self.run_log.emit("fault", kind="repartition", round=rnd + 1,
                              rotation=1)
        self._watchdog.repartition_done()
        return data, y_dev, pred, val_data, val_y, val_pred

    def _fit_fused(self, data, y_dev, pred, ens: TreeEnsemble,
                   start_round: int, C: int,
                   eval_state: tuple | None = None,
                   early_stopping_rounds: int | None = None,
                   colsample_features: int | None = None,
                   coll_bytes_round: int = 0
                   ) -> TreeEnsemble:
        """Block loop over backend.grow_rounds: K rounds per dispatch,
        K x C trees per fetch. Blocks break at checkpoint_every boundaries
        so the checkpoint cadence (and resume bit-exactness) is identical
        to the granular path. With eval_state, validation scoring runs
        inside the scan (grow_rounds_eval) and a [K] scores vector rides
        the same fetch; early stopping replays the stopping rule over
        that vector after the fetch — identical models to the granular
        path (trees past the stop point are simply discarded), with
        blocks capped at the patience so at most one patience-worth of
        rounds is grown beyond the stop."""
        cfg = self.cfg
        metric_name = None
        if eval_state is not None:
            val_data, val_pred, val_y, metric_name, sign = eval_state
            best = -np.inf
        # Coarse phase breakdown for telemetry runs: the block dispatch is
        # async (enqueue returns immediately), so "grow_block" measures
        # dispatch + whatever back-pressures, and "fetch_tree" — the
        # np.asarray barrier — carries the block's device wallclock.
        ph = phase_ctx(self.timer)
        rnd = start_round
        while rnd < cfg.n_trees:
            K = min(cfg.n_trees - rnd, cfg.fused_block_rounds)
            if self.checkpoint_dir is not None:
                nxt = (rnd // self.checkpoint_every + 1) * \
                    self.checkpoint_every
                K = min(K, nxt - rnd)
            if early_stopping_rounds is not None:
                K = min(K, max(early_stopping_rounds, 1))
            if self._window is not None:
                # xprof window: break blocks at the capture edges (the
                # checkpoint-boundary treatment) so start/stop land on
                # true round boundaries, then open the window if this
                # block enters it.
                K = self._window.block_cap(rnd, K)
                self._window.round_start(rnd)
            t0 = time.perf_counter()
            fmasks = None
            if colsample_features is not None:
                F = colsample_features
                fmasks = np.zeros((K, C, F), bool)
                for k in range(K):
                    for c in range(C):
                        fmasks[k, c] = self._draw_colsample_mask(
                            rnd + k, c, F)
            with ph("grow_block"):
                if eval_state is not None:
                    trees_h, pred, losses_h, val_pred, scores_h = \
                        self.backend.grow_rounds_eval(
                            data, pred, y_dev, K,
                            val_data, val_pred, val_y, metric_name,
                            first_round=rnd, fmasks=fmasks)
                elif fmasks is not None:
                    trees_h, pred, losses_h = \
                        self.backend.grow_rounds_masked(
                            data, pred, y_dev, K, fmasks, first_round=rnd)
                else:
                    trees_h, pred, losses_h = self.backend.grow_rounds(
                        data, pred, y_dev, K, first_round=rnd)
            # Flight recorder: per-device completion of the whole block
            # (one lane sample per device per block; the probe is the
            # block barrier, so the fetch below materialises already-done
            # transfers). Inert unless distributed + run log.
            part_rec = self._part_rec
            if part_rec is not None:
                part_rec.observe("grow_block", trees_h, t0)
            with ph("fetch_tree"):
                if eval_state is not None:
                    scores = np.asarray(scores_h)  # [K] — same fetch wave
                trees = np.asarray(trees_h)     # [K, C, 5, N] — ONE fetch
                losses = np.asarray(losses_h)
            dt = time.perf_counter() - t0
            if self._window is not None:
                # The fetch above was the block's barrier: the captured
                # trace now holds every dispatch of rounds [rnd, rnd+K).
                self._window.round_end(rnd + K - 1)
            if part_rec is not None:
                # Watchdog feed on the fused path too — detection only
                # (the repartition action needs the granular loop, which
                # cfg.straggler_repartition forces).
                self._observe_straggler(
                    rnd, part_rec.flush_round(rnd, n_rounds=K))
            tele_counters.record_d2h(trees.nbytes + losses.nbytes)
            if coll_bytes_round:
                tele_counters.record_collective(coll_bytes_round * K)
            tele_counters.record_grad_stream(self._grad_bytes_round * K)
            if cfg.grad_dtype != "f32":
                tele_counters.record_grad_quant_round(K)
            for k in range(K):
                for c in range(C):
                    slot = (rnd + k) * C + c
                    p = trees[k, c]
                    ens.feature[slot] = p[0].astype(np.int32)
                    ens.threshold_bin[slot] = p[1].astype(np.int32)
                    ens.is_leaf[slot] = p[2].astype(bool)
                    ens.leaf_value[slot] = p[3]
                    ens.split_gain[slot] = p[4]
                    ens.default_left[slot] = p[5].astype(bool)
                r = rnd + k
                val_score = None
                if eval_state is not None:
                    val_score = float(scores[k])
                    if sign * val_score > best:
                        best = sign * val_score
                        self.best_round = r
                        self.best_score = val_score
                self._recorder.record(
                    r, dt * 1e3 / K, val_score,
                    lambda k=k: float(losses[k]))
                tele_counters.record_train_round()
                if self._status is not None:  # live-ops plane (ISSUE 20)
                    self._status.round_end(
                        r, dt * 1e3 / K,
                        self.history[-1]
                        if (self.history
                            and self.history[-1].get("round") == r + 1)
                        else RoundRecorder.make_record(
                            r, dt * 1e3 / K, None))
                if early_stopping_rounds is not None:
                    if self.best_round is None:
                        raise ValueError(
                            f"validation {metric_name} has been NaN since "
                            "round 1 (degenerate eval_set — e.g. constant "
                            "scores or a single-class slice); cannot "
                            "early-stop on it"
                        )
                    if r - self.best_round >= early_stopping_rounds:
                        log.info(
                            "early stop at round %d (best %s=%.6f at "
                            "round %d)", r + 1, metric_name,
                            self.best_score, self.best_round + 1,
                        )
                        emit_early_stop(self.run_log, r + 1, metric_name,
                                        self.best_round + 1,
                                        self.best_score)
                        ens = ens.truncate((self.best_round + 1) * C)
                        checkpoint.maybe_save(self.checkpoint_dir, ens,
                                              cfg, self.best_round + 1)
                        return ens
            rnd += K
            if rnd < cfg.n_trees:
                checkpoint.maybe_save(self.checkpoint_dir, ens, cfg, rnd,
                                      self.checkpoint_every)
                if self._status is not None \
                        and self.checkpoint_dir is not None \
                        and rnd % self.checkpoint_every == 0:
                    self._status.checkpoint_saved(rnd)
            # Heartbeat when this block CROSSED a cadence boundary: with
            # a checkpoint dir, blocks break exactly at checkpoint_every
            # boundaries (the K cap above) so these are the granular
            # path's heartbeat rounds; without one, block ends are the
            # only true round boundaries the fused dispatch has, so the
            # heartbeat lands on the first block end past the mark.
            if self.run_log is not None and self.checkpoint_every >= 1 \
                    and (rnd // self.checkpoint_every
                         > (rnd - K) // self.checkpoint_every):
                emit_train_heartbeat(
                    self.run_log, rnd=rnd - 1, total_rounds=cfg.n_trees,
                    checkpoint_round=(rnd
                                      if self.checkpoint_dir is not None
                                      and rnd < cfg.n_trees
                                      and rnd % self.checkpoint_every == 0
                                      else None),
                    ms_per_round=dt * 1e3 / K)
        checkpoint.maybe_save(self.checkpoint_dir, ens, cfg, cfg.n_trees)
        return ens
