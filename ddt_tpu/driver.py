"""Driver: the host-side tree-construction / boosting loop (layer L5).

The reference's `Driver` grows trees level-by-level against a `DeviceBackend`
and is explicitly "unchanged above the operator layer" when backends swap
[BASELINE]. This Driver is that loop, shaped for TPU dispatch economics
(SURVEY.md §3 call stack):

    for round in 1..n_trees:                      (sequential, host)
      g, h = backend.grad_hess(pred, y)           (device, fused elementwise)
      for c in classes:                           (1 for binary/mse)
        tree, delta = backend.grow_tree(data, g_c, h_c)   (ONE device dispatch:
              histograms → [psum over mesh] → gains → splits → row routing,
              all levels)
        pred = backend.apply_delta(pred, delta, c)
      ensemble[t] = tree                          (≈KBs to host)

Boosting state (`pred`) is an opaque backend handle — on TPUDevice it lives
sharded on device for the whole run; the Driver never sees a float of it.

Observability (SURVEY.md §5): structured per-round log records (train loss,
ms/tree) via `logging`, collected in `Driver.history`. Checkpoint/resume
(SURVEY.md §5): pass `checkpoint_dir` — after every `checkpoint_every` rounds
the partial ensemble + cursor is written; `fit` resumes from the cursor if a
checkpoint exists (utils/checkpoint.py).
"""

from __future__ import annotations

import logging
import time

import numpy as np

from ddt_tpu.backends.base import DeviceBackend
from ddt_tpu.config import TrainConfig
from ddt_tpu.models.tree import TreeEnsemble, empty_ensemble
from ddt_tpu.reference.numpy_trainer import base_score

log = logging.getLogger("ddt_tpu.driver")


class Driver:
    """Backend-agnostic boosting driver (the L5→L4 contract consumer)."""

    def __init__(
        self,
        backend: DeviceBackend,
        cfg: TrainConfig,
        log_every: int = 10,
        checkpoint_dir: str | None = None,
        checkpoint_every: int = 25,
    ):
        self.backend = backend
        self.cfg = cfg
        self.log_every = log_every
        self.checkpoint_dir = checkpoint_dir
        self.checkpoint_every = checkpoint_every
        self.history: list[dict] = []

    def fit(self, Xb: np.ndarray, y: np.ndarray) -> TreeEnsemble:
        """Train on binned uint8 data. Returns the grown ensemble."""
        cfg = self.cfg
        R, F = Xb.shape
        if Xb.dtype != np.uint8:
            raise TypeError(f"Xb must be uint8 binned data, got {Xb.dtype}")
        C = cfg.n_classes if cfg.loss == "softmax" else 1
        bs = base_score(np.asarray(y), cfg.loss, cfg.n_classes)

        data = self.backend.upload(Xb)
        y_dev = self.backend.upload_labels(np.asarray(y))
        pred = self.backend.init_pred(y_dev, bs)

        ens = empty_ensemble(
            cfg.n_trees * C, cfg.max_depth, F, cfg.learning_rate, bs,
            cfg.loss, cfg.n_classes,
        )

        start_round = 0
        if self.checkpoint_dir is not None:
            from ddt_tpu.utils.checkpoint import try_resume

            start_round = try_resume(self.checkpoint_dir, ens, cfg)
            if start_round > 0:
                # Reconstitute boosting state by rescoring the partial
                # ensemble (deterministic: trees fix the leaf of every row).
                import dataclasses

                k = start_round * C
                part = dataclasses.replace(
                    ens,
                    feature=ens.feature[:k],
                    threshold_bin=ens.threshold_bin[:k],
                    is_leaf=ens.is_leaf[:k],
                    leaf_value=ens.leaf_value[:k],
                )
                pred = self.backend.load_pred(
                    np.asarray(part.predict_raw(Xb, binned=True))
                )
                log.info("resumed from checkpoint at round %d", start_round)

        t_out = start_round * C
        for rnd in range(start_round, cfg.n_trees):
            t0 = time.perf_counter()
            g, h = self.backend.grad_hess(pred, y_dev)
            for c in range(C):
                gc = g[:, c] if C > 1 else g
                hc = h[:, c] if C > 1 else h
                tree, delta = self.backend.grow_tree(data, gc, hc)
                pred = self.backend.apply_delta(pred, delta, c)
                ens.feature[t_out] = tree["feature"]
                ens.threshold_bin[t_out] = tree["threshold_bin"]
                ens.is_leaf[t_out] = tree["is_leaf"]
                ens.leaf_value[t_out] = tree["leaf_value"]
                t_out += 1
            dt = time.perf_counter() - t0

            if (rnd + 1) % self.log_every == 0 or rnd == cfg.n_trees - 1:
                loss = self.backend.loss_value(pred, y_dev)
                rec = {
                    "round": rnd + 1,
                    "train_loss": loss,
                    "ms_per_round": dt * 1e3,
                }
                self.history.append(rec)
                log.info(
                    "round %4d/%d  loss=%.6f  %.1f ms/round",
                    rnd + 1, cfg.n_trees, loss, dt * 1e3,
                )

            if (
                self.checkpoint_dir is not None
                and (rnd + 1) % self.checkpoint_every == 0
            ):
                from ddt_tpu.utils.checkpoint import save_checkpoint

                save_checkpoint(self.checkpoint_dir, ens, cfg, rnd + 1)

        if self.checkpoint_dir is not None:
            from ddt_tpu.utils.checkpoint import save_checkpoint

            save_checkpoint(self.checkpoint_dir, ens, cfg, cfg.n_trees)
        return ens
