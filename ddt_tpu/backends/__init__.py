"""Backend registry + flag selection.

[BASELINE]: "FPGA vs TPU backend selectable by flag" — the reference picks its
DeviceBackend by a runtime flag. Here the registry maps flag values to
implementations: "cpu" (NumPy/native reference), "tpu" (JAX/XLA — the north
star), and "fpga" (present for flag-surface parity, unavailable in this
build: we have no FPGA shell to drive, and stubbing silently would be lying
about capability).
"""

from __future__ import annotations

from ddt_tpu.backends.base import DeviceBackend, HostTree
from ddt_tpu.config import TrainConfig


class FPGADevice(DeviceBackend):
    """Flag-parity stub for the reference's FPGA backend (not in this build)."""

    name = "fpga"

    def __init__(self, cfg: TrainConfig):
        raise NotImplementedError(
            "The FPGA backend exists in this framework's flag surface for "
            "parity with the reference, but this build targets TPU: no FPGA "
            "shell/runtime is present. Use --backend=tpu or --backend=cpu."
        )

    # Abstract methods are never reachable (init always raises); satisfy the
    # ABC so the class itself is constructible up to the NotImplementedError.
    upload = upload_labels = build_histograms = best_splits = None  # type: ignore
    init_pred = load_pred = grad_hess = grow_tree = apply_delta = None  # type: ignore
    loss_value = predict_raw = None  # type: ignore


def get_backend(cfg: TrainConfig, **kwargs) -> DeviceBackend:
    """Instantiate the backend named by cfg.backend (the flag)."""
    if cfg.backend == "cpu":
        from ddt_tpu.backends.cpu import CPUDevice

        return CPUDevice(cfg, **kwargs)
    if cfg.backend == "tpu":
        from ddt_tpu.backends.tpu import TPUDevice

        return TPUDevice(cfg, **kwargs)
    if cfg.backend == "fpga":
        return FPGADevice(cfg)
    raise ValueError(f"unknown backend {cfg.backend!r}")


__all__ = ["DeviceBackend", "HostTree", "FPGADevice", "get_backend"]
