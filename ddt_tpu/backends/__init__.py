"""Backend registry + flag selection.

[BASELINE]: "FPGA vs TPU backend selectable by flag" — the reference picks its
DeviceBackend by a runtime flag. Here the registry maps flag values to
implementations: "cpu" (NumPy/native reference), "tpu" (JAX/XLA — the north
star), and "fpga" (present for flag-surface parity, unavailable in this
build: we have no FPGA shell to drive, and stubbing silently would be lying
about capability).
"""

from __future__ import annotations

from ddt_tpu.backends.base import DeviceBackend, HostTree
from ddt_tpu.config import TrainConfig


class FPGADevice(DeviceBackend):
    """Flag-parity stub for the reference's FPGA backend (not in this build)."""

    name = "fpga"

    def __init__(self, cfg: TrainConfig):
        raise NotImplementedError(
            "The FPGA backend exists in this framework's flag surface for "
            "parity with the reference, but this build targets TPU: no FPGA "
            "shell/runtime is present. Use --backend=tpu or --backend=cpu."
        )

    # Abstract methods are never reachable (init always raises); satisfy the
    # ABC so the class itself is constructible up to the NotImplementedError.
    upload = upload_labels = build_histograms = best_splits = None  # type: ignore
    init_pred = load_pred = grad_hess = grow_tree = apply_delta = None  # type: ignore
    loss_value = predict_raw = None  # type: ignore


# Backend instances are cached on the config fields that shape their traced
# programs: a TPUDevice's jitted grow/grad/predict functions live on the
# instance, and recompiling them costs seconds (tens of seconds through a
# remote-attached chip) — far more than any training round. Fields like
# n_trees never enter a trace, so two train() calls differing only there
# share one compiled backend. subsample and seed DO enter the fused trace
# since round 5 (the in-scan counter-based bagging hash bakes both in);
# a cached instance reused across them would train with the wrong masks.
# seed is trace-relevant ONLY under bagging, so the key normalises it to
# 0 when subsample == 1.0 — a seed sweep over deterministic/colsample-only
# configs (whose masks are host data, not trace constants) keeps sharing
# one compiled backend instead of paying a recompile per seed.
_JIT_FIELDS = (
    "backend", "n_partitions", "feature_partitions", "host_partitions",
    "max_depth", "n_bins", "learning_rate", "loss", "n_classes",
    "reg_lambda", "min_child_weight", "min_split_gain",
    "hist_impl", "predict_impl", "matmul_input_dtype", "missing_policy",
    "cat_features", "subsample",
    # Trace-shaping comms + kernel-phasing knobs: the resolved collective
    # mode/dtype/slab count and the sibling-subtraction flag all bake
    # into the compiled grow/stream programs — a cached instance reused
    # across them would train with the wrong collectives (the A/B benches
    # and the comms parity tests flip exactly these).
    "hist_subtraction", "split_comms", "hist_comms_dtype",
    "hist_comms_slabs",
    # Quantized-gradient training (ISSUE 14): the integer histogram
    # programs differ from f32 at every level — a cached f32 instance
    # reused under grad_dtype='int8' would silently train unquantized.
    "grad_dtype",
)


def _cache_key(cfg: TrainConfig) -> tuple:
    # seed is trace-relevant under bagging (in-scan counter hash) AND
    # under quantized gradients (the stochastic-rounding key bakes it
    # into the grow programs) — normalise to 0 only when neither is on.
    seed_live = cfg.subsample < 1.0 or cfg.grad_dtype != "f32"
    return tuple(getattr(cfg, f) for f in _JIT_FIELDS) + (
        cfg.seed if seed_live else 0,
    )
# LRU-bounded: each cached TPUDevice pins its compiled executables (and any
# upload-derived device state) for its lifetime, so a hyperparameter sweep
# over many configs must evict old entries. TrainConfig is frozen, so a
# cached instance's cfg can never drift from the key it was cached under.
_CACHE_MAX = 8
_CACHE: "dict" = {}


def get_backend(cfg: TrainConfig, use_cache: bool = True,
                **kwargs) -> DeviceBackend:
    """Instantiate (or reuse) the backend named by cfg.backend (the flag)."""
    key = None
    if use_cache and not kwargs:
        key = _cache_key(cfg)
        hit = _CACHE.pop(key, None)
        if hit is not None:
            _CACHE[key] = hit      # re-insert: most-recently-used
            return hit
    if cfg.backend == "cpu":
        from ddt_tpu.backends.cpu import CPUDevice

        be: DeviceBackend = CPUDevice(cfg, **kwargs)
    elif cfg.backend == "tpu":
        from ddt_tpu.backends.tpu import TPUDevice

        be = TPUDevice(cfg, **kwargs)
    elif cfg.backend == "fpga":
        return FPGADevice(cfg)
    else:
        raise ValueError(f"unknown backend {cfg.backend!r}")
    if key is not None:
        _CACHE[key] = be
        while len(_CACHE) > _CACHE_MAX:
            _CACHE.pop(next(iter(_CACHE)))    # evict least-recently-used
    return be


__all__ = ["DeviceBackend", "HostTree", "FPGADevice", "get_backend"]
