"""DeviceBackend: the L5→L4 operator-boundary contract.

SURVEY.md §1: "The Driver sees only DeviceBackend.{upload, build_histograms,
best_splits, apply_split/partition, predict}. Everything below L4 is swappable
per backend; everything above is backend-agnostic." The reference pairs a host
`Driver` with an `FPGADevice` behind this interface [BASELINE]; the north star
is a `TPUDevice` slotting in beside it with the tree loop unchanged. This
module is that interface, TPU-first:

- The granular kernels (`build_histograms`, `best_splits`) stay on the
  interface as the parity/bench surface — tests drive each backend's kernels
  against the NumPy oracle through exactly these methods.
- The Driver's per-tree call is the *fused* `grow_tree`: on TPU a whole tree
  (all levels: histograms → allreduce → gains → split → row routing) is ONE
  device dispatch (ops/grow.py), because crossing the host boundary per kernel
  per level — the reference's FPGA calling convention — would serialise
  hundreds of dispatch latencies per tree. Backends that cannot fuse (the
  NumPy CPU reference) implement grow_tree as the plain level loop.
- Boosting state (raw predictions) lives where the backend wants it: opaque
  `pred` handles flow Driver → grad_hess → grow_tree → apply_delta without
  ever forcing a host round-trip. Only the grown tree's node arrays (a few KB)
  come back per tree.

Backend registry + flag selection lives in backends/__init__.py
([BASELINE] "backend selectable by flag").
"""

from __future__ import annotations

import abc
from typing import Any

import numpy as np

from ddt_tpu.config import TrainConfig
from ddt_tpu.models.tree import TreeEnsemble


class HostTree(dict):
    """One grown tree, host-side: np arrays feature/threshold_bin/is_leaf/
    leaf_value, each [n_nodes_total]. Plain dict subclass for clarity."""


class DeviceBackend(abc.ABC):
    """Uniform device API for histogram-GBDT training and inference."""

    name: str = "abstract"

    def __init__(self, cfg: TrainConfig):
        self.cfg = cfg

    # ------------------------------------------------------------------ #
    # data plane
    # ------------------------------------------------------------------ #

    @abc.abstractmethod
    def upload(self, Xb: np.ndarray) -> Any:
        """Ship the binned uint8 matrix [R, F] to the device (row-sharded when
        distributed). Returns an opaque handle accepted by the kernels."""

    @abc.abstractmethod
    def upload_labels(self, y: np.ndarray,
                      sample_weight: np.ndarray | None = None) -> Any:
        """Ship labels [R] (row-sharded alongside the data when
        distributed), with optional per-row instance weights — they scale
        gradients, hessians, and the training loss's numerator AND
        denominator (weighted means)."""

    # ------------------------------------------------------------------ #
    # L3 kernels (granular contract: parity tests + bench drive these)
    # ------------------------------------------------------------------ #

    @abc.abstractmethod
    def build_histograms(
        self,
        data: Any,
        g: Any,
        h: Any,
        node_index: Any,
        n_nodes: int,
    ) -> Any:
        """Per-(node, feature, bin) (g, h) sums: [n_nodes, F, n_bins, 2] f32.

        `node_index` is the level-local node per row (int32, -1 = frozen).
        When distributed this INCLUDES the cross-partition allreduce — the
        result is the global histogram, as the reference's fabric allreduce
        delivers it to split selection [BASELINE].
        """

    @abc.abstractmethod
    def best_splits(self, hist: Any) -> tuple[Any, Any, Any]:
        """SplitGain: per-node (gain f32, feature i32, threshold_bin i32)."""

    # ------------------------------------------------------------------ #
    # fused training ops (what the Driver actually calls per tree)
    # ------------------------------------------------------------------ #

    @abc.abstractmethod
    def init_pred(self, y: Any, base: float) -> Any:
        """Initial raw scores: [R] filled with `base` (or [R, C] zeros for
        softmax). Opaque device array."""

    @abc.abstractmethod
    def load_pred(self, raw: np.ndarray) -> Any:
        """Adopt host raw scores [R] / [R, C] as the boosting state (used by
        checkpoint resume). Opaque device array, padded/sharded as needed."""

    @abc.abstractmethod
    def grad_hess(self, pred: Any, y: Any) -> tuple[Any, Any]:
        """Loss gradients/hessians at `pred`: float32 [R] or [R, C]."""

    def sync(self, x: Any) -> None:
        """Barrier on x's producer chain, for phase profiling. No-op on
        host-resident backends (numpy arrays are already materialised);
        device backends block until x has actually been computed."""

    def apply_row_mask(self, g: Any, h: Any, mask: np.ndarray):
        """(g * mask, h * mask) — per-round row bagging (cfg.subsample).
        `mask` is a host bool [R]; device backends upload + fuse the
        multiply. Default: NumPy elementwise."""
        m = mask.astype(np.float32)
        if getattr(g, "ndim", 1) == 2:
            m = m[:, None]
        return g * m, h * m

    @abc.abstractmethod
    def grow_tree(self, data: Any, g: Any, h: Any,
                  feature_mask: np.ndarray | None = None,
                  tree_id: int = 0) -> tuple[Any, Any]:
        """Grow one complete-heap tree from (sharded) data + grads.
        feature_mask (host bool [F], or None) excludes features from split
        selection — cfg.colsample_bytree. `tree_id` is the absolute tree
        index (round * n_classes + class) — the quantized-gradient
        stochastic-rounding key on backends that honor cfg.grad_dtype
        (ignored elsewhere).

        Returns (tree_handle, delta): a backend-opaque handle to the tree's
        node arrays (resolve with fetch_tree), and the per-row raw-score
        increment lr * leaf_value[leaf_of_row] as an opaque device array
        aligned with `pred` (used by apply_delta). For softmax, g/h are the
        single class column being boosted.

        The handle lets device backends defer the device→host copy: the
        Driver resolves it one round later, hiding the transfer round-trip
        (~tens of ms on a remote-attached chip) under the next tree's
        compute. CPU-resident backends just return the HostTree itself.
        """

    def fetch_tree(self, handle: Any) -> HostTree:
        """Resolve a grow_tree handle to host node arrays. Default: the
        handle already is the HostTree (CPU-resident backends)."""
        return handle

    @abc.abstractmethod
    def apply_delta(self, pred: Any, delta: Any, class_idx: int) -> Any:
        """pred updated by delta (into column class_idx when pred is [R, C])."""

    @abc.abstractmethod
    def loss_value(self, pred: Any, y: Any) -> float:
        """Mean training loss at `pred` (host float; may sync). Logging only."""

    # ------------------------------------------------------------------ #
    # inference
    # ------------------------------------------------------------------ #

    @abc.abstractmethod
    def predict_raw(self, ens: TreeEnsemble, Xb: np.ndarray,
                    compiled=None) -> np.ndarray:
        """Batch ensemble scoring on binned data (TreeEnsemble.predict path,
        [BASELINE]): raw margins [R] or [R, C], on host. `compiled` is an
        optional models/tree.CompiledEnsemble already built for THIS ens
        (the serving tier holds one per model version); backends that
        keep device-resident scoring caches use it to skip the per-call
        content hash, others may ignore it."""

    # ------------------------------------------------------------------ #

    def __repr__(self) -> str:  # pragma: no cover
        return f"<{type(self).__name__} backend={self.name!r}>"
