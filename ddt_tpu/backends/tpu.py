"""TPUDevice: the JAX/XLA execution backend (the north-star deliverable).

Realises [BASELINE]: "the per-feature HistogramBuilder and SplitGain kernels
are re-expressed as jax.vmap'd XLA ops, and the cross-partition histogram
allreduce that today runs over the FPGA network fabric becomes jax.lax.psum
over TPU ICI. The host-side Driver/DeviceBackend abstraction gains a TPUDevice
implementation alongside FPGADevice."

Design, TPU-first (SURVEY.md §1 L2–L4):

- **One dispatch per tree.** `grow_tree` jit-compiles the whole level-unrolled
  growth program (ops/grow.py) once per (shape, config) and reuses it for all
  trees; only ~KBs of node arrays cross the host boundary per tree. The
  reference's per-kernel host↔device calling convention would serialise
  6 × depth × trees dispatch latencies — fused instead.
- **Distribution = mesh axis, not message passing.** With n_partitions > 1 the
  backend builds a 1-D `jax.sharding.Mesh` over axis "rows", row-shards the
  binned matrix/labels/boosting state with NamedSharding, and traces the same
  growth program under `jax.shard_map` with axis_name="rows" — the histogram
  allreduce appears as `jax.lax.psum` riding ICI. Tree arrays come out
  replicated (every shard deterministically grows the identical tree); the
  per-row state stays sharded and never moves.
- **Static shapes.** Rows are padded to a multiple of the partition count;
  padded rows are masked out of gradients (g = h = 0) so they contribute to
  no histogram, no leaf sum, and no loss.

This class runs unmodified on CPU XLA (tests use an 8-virtual-device CPU
mesh — SURVEY.md §4 "Distributed without a cluster") and on real TPU; "tpu"
names the design target, and the flag surface matches the reference's
fpga/tpu selection [BASELINE].
"""

from __future__ import annotations

import functools
import logging
import os
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ddt_tpu.backends.base import DeviceBackend, HostTree
from ddt_tpu.config import TrainConfig
from ddt_tpu.models.tree import TreeEnsemble
from ddt_tpu.ops import grad as grad_ops
from ddt_tpu.ops import grow as grow_ops
from ddt_tpu.ops import histogram as hist_ops
from ddt_tpu.ops import predict as predict_ops
from ddt_tpu.ops import split as split_ops
from ddt_tpu.parallel import comms as comms_lib
from ddt_tpu.parallel import mesh as mesh_lib
from ddt_tpu.robustness import emit_fault, faultplan
from ddt_tpu.telemetry import counters as tele_counters
from ddt_tpu.telemetry.annotations import phase_span
from ddt_tpu.telemetry.costmodel import costed
from ddt_tpu.utils import retry as retry_lib

log = logging.getLogger("ddt_tpu.backends.tpu")

# Mesh axis names are OWNED by parallel/mesh.py (the ddtlint
# axis-name-literal contract): the backend aliases the constants, never
# the strings, so a rename there cannot silently desynchronize here.
AXIS = mesh_lib.ROWS_AXIS       # data-parallel axis (SURVEY.md §2)
FAXIS = mesh_lib.FEATURES_AXIS  # optional TP-analog column axis
HAXIS = mesh_lib.HOSTS_AXIS  # cross-slice DCN axis (SURVEY.md §5
#   "Distributed comm backend"): row shards span (hosts, rows); the
#   histogram allreduce becomes psum over BOTH axes, which XLA phases as
#   an ICI-local reduce followed by a DCN allreduce.


def _axis_allreduce(axis):
    """Collective-or-identity reducer over `axis` (None = single shard):
    (x, op) with op in sum|min|max — the ONE home of the psum/pmin/pmax
    dispatch the metric twins and loss reductions share (collectives
    themselves spelled in parallel/comms.py, the one-home module)."""
    def allreduce(x, op="sum"):
        return {"sum": comms_lib.psum, "min": comms_lib.pmin,
                "max": comms_lib.pmax}[op](x, axis)

    return allreduce


def _local_row_offset(axis, rows_axis_size: int, n_local: int):
    """This shard's first row within the padded global batch — the
    flattened (hosts, rows) shard index times the local row count; the
    global-row-id base every in-trace bagging hash derives from (ONE
    home: fused grow_rounds and the streamed ops must agree bit-for-bit
    with the host twin's ids). `rows_axis_size` is the "rows" axis
    extent (needed to flatten the 2-axis case; ignored otherwise)."""
    if axis is None:
        return jnp.int32(0)
    if isinstance(axis, tuple):
        idx = (jax.lax.axis_index(axis[0]) * rows_axis_size
               + jax.lax.axis_index(axis[1]))
    else:
        idx = jax.lax.axis_index(axis)
    return (idx * n_local).astype(jnp.int32)


def _pack_tree(tree) -> "jax.Array":
    """Stack a grown tree's node arrays into one [6, N] f32 array (single
    device→host fetch; int32/bool values are exact in f32)."""
    return jnp.stack([
        tree.feature.astype(jnp.float32),
        tree.threshold_bin.astype(jnp.float32),
        tree.is_leaf.astype(jnp.float32),
        tree.leaf_value,
        tree.split_gain,
        tree.default_left.astype(jnp.float32),
    ])


class LabelHandle(NamedTuple):
    """Labels + per-row WEIGHT mask, row-sharded — the opaque `y` handle
    the Driver threads through grad_hess/loss_value. `valid` is float32:
    0 on pad rows, the instance weight elsewhere (1.0 without
    sample_weight) — one mask multiplication weights gradients, hessians,
    loss numerators AND the loss denominator (weighted means) everywhere,
    granular and fused paths alike. Per-dataset state lives here, NOT on
    the backend instance (instances are cached and shared)."""

    y: jax.Array
    valid: jax.Array


def enable_persistent_compile_cache() -> None:
    """Point XLA's persistent compilation cache at a local directory (unless
    the user already configured one). Compiling the fused grow program costs
    seconds — tens of seconds through a remote-attached chip — and the cache
    makes every process after the first skip it entirely.

    Mutates process-global JAX config, so the LIBRARY never calls it
    implicitly: our own entry points (cli, bench, __graft_entry__) do, and
    embedders opt in by calling it or setting $DDT_COMPILATION_CACHE
    (honored in TPUDevice.__init__)."""
    try:
        if jax.config.jax_compilation_cache_dir is None:
            jax.config.update(
                "jax_compilation_cache_dir",
                os.environ.get(
                    "DDT_COMPILATION_CACHE",
                    os.path.expanduser("~/.cache/ddt_tpu/xla"),
                ),
            )
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:    # unsupported jax version / read-only FS: non-fatal
        pass


class TPUDevice(DeviceBackend):
    """XLA backend; single-chip or row-sharded over a device mesh."""

    name = "tpu"

    def __init__(
        self,
        cfg: TrainConfig,
        devices: list | None = None,
        mesh: jax.sharding.Mesh | None = None,
    ):
        super().__init__(cfg)
        if "DDT_COMPILATION_CACHE" in os.environ:
            enable_persistent_compile_cache()
        self.n_partitions = max(1, cfg.n_partitions)
        self.feature_partitions = max(1, cfg.feature_partitions)
        self.host_partitions = max(1, cfg.host_partitions)
        if mesh is not None:
            self.mesh = mesh
            names = mesh.axis_names
            self.feature_partitions = (
                mesh.shape[FAXIS] if FAXIS in names else 1)
            self.host_partitions = mesh.shape[HAXIS] if HAXIS in names else 1
            self.n_partitions = mesh.devices.size // (
                self.feature_partitions * self.host_partitions)
        elif (self.n_partitions > 1 or self.feature_partitions > 1
              or self.host_partitions > 1):
            # Declarative 2D (rows x features) mesh — ONE constructor
            # (parallel/mesh.make_mesh_2d): hosts outermost (DCN,
            # slowest), rows middle, features innermost (ICI-adjacent) —
            # the feature winner gather per level is latency-sensitive;
            # the hosts hop happens once per reduction.
            self.mesh = mesh_lib.make_mesh_2d(
                self.n_partitions, self.feature_partitions,
                n_hosts=self.host_partitions, devices=devices)
        else:
            self.mesh = None
        self.distributed = self.mesh is not None
        # Row shards span (hosts x rows); every row-dimension sharding spec
        # and row-axis psum uses this (a tuple axis entry when the pod axis
        # exists, the plain "rows" name otherwise).
        self.row_shards = self.host_partitions * self.n_partitions
        self._row_axes = (
            (HAXIS, AXIS) if self.host_partitions > 1 else AXIS)
        # The declarative operand->PartitionSpec layout (parallel/mesh.
        # SpecLayout + match_partition_rules): every shard_map below
        # resolves its in/out specs through this table by operand name,
        # so the mesh's axis story lives in ONE rule table.
        self.layout = mesh_lib.SpecLayout(
            row_axes=self._row_axes if self.distributed else None,
            feature_axis=FAXIS if self.feature_partitions > 1 else None)
        self._input_dtype = jnp.dtype(cfg.matmul_input_dtype)
        # Split-finding comms, resolved ONCE at backend construction so
        # every program this backend builds — fused, granular, streamed —
        # and the telemetry payload model all read the same answer.
        # Reduce-scatter now COMPOSES with a sharded feature axis (the
        # scatter runs over the row axes within each feature slab), so
        # the resolver keys on whether a ROW wire exists.
        self.split_comms = comms_lib.resolve_split_comms(
            cfg.split_comms, distributed=self.distributed,
            feature_partitions=self.feature_partitions,
            row_shards=self.row_shards)
        # Host-FETCH histogram surfaces (the granular build_histograms
        # and the streamed hist ops) return the table to the host; under
        # reduce_scatter that output is row-sharded, which a
        # multi-process mesh cannot np.asarray (shards span other
        # processes' devices). Those surfaces therefore fall back to
        # allreduce on multi-process meshes — the fused in-trace path
        # keeps the scatter (its histograms never leave the program).
        self.stream_hist_comms = (
            self.split_comms if jax.process_count() == 1 else "allreduce")
        self.comms_slabs = comms_lib.resolve_comms_slabs(
            cfg.hist_comms_slabs, distributed=self.distributed)
        # Quantized-gradient training (cfg.grad_dtype; ops/grad.py): one
        # resolved bool every program builder below reads — the grow
        # programs quantize in-trace, the streamed ops take per-round
        # scales, and the byte models report the integer path.
        self._grad_quant = cfg.grad_dtype != "f32"
        # Sticky position on the histogram OOM-degradation ladder
        # (build_histograms below): 0 = the configured impl.
        self._hist_degrade = 0

    def collective_bytes_per_tree(self, n_features: int,
                                  streamed: bool = False) -> int:
        """Effective per-tree histogram-collective payload estimate for
        THIS backend's resolved comms configuration (mode, wire dtype,
        sibling subtraction) — the one home the Driver and the streaming
        trainers record into `hist_allreduce_bytes` (telemetry.counters
        documents the model). `streamed=True` reads the host-fetch
        surfaces' mode (stream_hist_comms — allreduce on multi-process
        meshes). Zero on single-device backends."""
        if not self.distributed:
            return 0
        from ddt_tpu.ops.grow import resolve_hist_subtraction

        return tele_counters.hist_allreduce_bytes(
            self.cfg.max_depth, n_features, self.cfg.n_bins,
            partitions=self.row_shards,
            feature_partitions=self.feature_partitions,
            mode=self.stream_hist_comms if streamed else self.split_comms,
            comms_dtype=self.cfg.hist_comms_dtype,
            subtraction=resolve_hist_subtraction(
                self.cfg.hist_subtraction, integer_hists=self._grad_quant),
            grad_dtype=self.cfg.grad_dtype,
        )

    # ------------------------------------------------------------------ #
    # sharding helpers
    # ------------------------------------------------------------------ #

    def _row_sharding(self, extra_dims: int = 0):
        """NamedSharding for a row-sharded [R, ...] operand, resolved
        through the declarative layout (row_vector / row_matrix — the
        ddtlint handbuilt-partition-spec contract: the backend never
        hand-builds a PartitionSpec). Trailing dims past the spec are
        replicated by PartitionSpec semantics."""
        lay = self.layout
        return self._named(
            lay.row_vector() if extra_dims == 0 else lay.row_matrix())

    def _named(self, spec):
        """NamedSharding from a SpecLayout-resolved PartitionSpec (None
        on single-device backends — device_put picks the default)."""
        if not self.distributed:
            return None
        return jax.sharding.NamedSharding(self.mesh, spec)

    @staticmethod
    def _put(a: np.ndarray, sh) -> jax.Array:
        """device_put that also works on a MULTI-PROCESS mesh: device_put
        cannot place shards on devices this process does not own, so when
        the sharding spans other processes' devices each process
        materialises its addressable shards from the (identical-everywhere)
        global host array via the sharding's index map. Single-process
        meshes keep the plain device_put fast path."""
        # Telemetry: every host->device transfer funnels through here —
        # ONE integer add per upload feeds the run log's h2d counter
        # (telemetry.counters; no device interaction, ~ns).
        tele_counters.record_h2d(a.nbytes)
        if sh is None:
            return jax.device_put(a)
        if not sh.is_fully_addressable:
            return jax.make_array_from_callback(
                a.shape, sh, lambda idx: a[idx])
        return jax.device_put(a, sh)

    def _pad_rows(self, a: np.ndarray) -> np.ndarray:
        """Pad axis 0 to a multiple of the (hosts x rows) shard count."""
        R = a.shape[0]
        Rp = -(-R // self.row_shards) * self.row_shards
        if Rp == R:
            return a
        pad = [(0, Rp - R)] + [(0, 0)] * (a.ndim - 1)
        return np.pad(a, pad)

    def _put_rows(self, a: np.ndarray, extra_dims: int = 0) -> jax.Array:
        a = self._pad_rows(np.ascontiguousarray(a))
        return self._put(a, self._row_sharding(extra_dims))

    # ------------------------------------------------------------------ #
    # data plane
    # ------------------------------------------------------------------ #

    def upload(self, Xb: np.ndarray) -> jax.Array:
        if Xb.dtype != np.uint8:
            raise TypeError(f"binned data must be uint8, got {Xb.dtype}")
        R = Xb.shape[0]
        if self.feature_partitions > 1:
            # Column-shard over the feature axis (pad F to a multiple; padded
            # columns are all-zeros => their best gain is exactly 0 with an
            # empty right child, so they are never chosen as splits).
            F = Xb.shape[1]
            Fp = -(-F // self.feature_partitions) * self.feature_partitions
            if Fp != F:
                Xb = np.pad(Xb, ((0, 0), (0, Fp - F)))
            Xp = self._pad_rows(np.ascontiguousarray(Xb))
            data = self._put(Xp, self._named(self.layout.binned_data()))
        else:
            data = self._put_rows(Xb, extra_dims=1)
        return data

    def upload_row_shards(self, parts: list, total_rows: int) -> jax.Array:
        """Host-sharded chunk upload (ROADMAP item 2's ingest half):
        assemble a row-sharded [R, F] uint8 device array from THIS
        process's contiguous row block — `parts` are the sub-shards this
        process owns (data.chunks.HostShardedChunks), in global order;
        other processes' rows are NEVER materialized on this host.

        Single-process meshes (where every sub-shard is local) simply
        concatenate and take the normal padded upload — identical device
        layout, so the two paths are interchangeable per process count.
        Multi-process meshes use jax.make_array_from_process_local_data:
        each process contributes exactly its addressable devices' rows,
        replacing the single-controller make_array_from_callback that
        forced every host to hold the full global chunk. Row padding (to
        the shard count) lands in the LAST process's block, matching
        _pad_rows' global layout; uneven blocks raise — the chunk writer
        cuts uniform sub-shards (shard_arrays / shard_stress_chunks)."""
        local = (np.ascontiguousarray(np.concatenate(parts))
                 if len(parts) > 1 else np.ascontiguousarray(parts[0]))
        if local.dtype != np.uint8:
            raise TypeError(
                f"binned data must be uint8, got {local.dtype}")
        if not self.distributed or jax.process_count() == 1:
            return self.upload(local)
        if self.feature_partitions > 1:
            # The streamed path is row-parallel only (the stream ops
            # raise too); saying so HERE keeps the multi-process branch
            # from silently skipping upload()'s feature-axis column
            # padding if that contract ever loosens.
            raise NotImplementedError(
                "host-sharded uploads are row-parallel only; "
                "feature_partitions > 1 does not stream")
        n_proc = jax.process_count()
        Rp = -(-total_rows // self.row_shards) * self.row_shards
        if Rp % n_proc:
            raise ValueError(
                f"padded rows {Rp} do not split over {n_proc} processes")
        block = Rp // n_proc
        pad = block - local.shape[0]
        if pad < 0 or (pad > 0 and jax.process_index() != n_proc - 1):
            raise ValueError(
                f"process {jax.process_index()} holds {local.shape[0]} "
                f"rows but its block is {block}; host-sharded chunks "
                "need uniform sub-shard sizes (re-cut the shards)")
        if pad:
            local = np.pad(local, ((0, pad), (0, 0)))
        tele_counters.record_h2d(local.nbytes)
        sh = self._named(self.layout.binned_data())
        return jax.make_array_from_process_local_data(
            sh, local, (Rp, local.shape[1]))

    def upload_labels(self, y: np.ndarray,
                      sample_weight: np.ndarray | None = None
                      ) -> "LabelHandle":
        # The pad-row weight mask travels WITH the labels (not on the
        # backend instance): backend instances are cached and shared across
        # fits, so per-dataset state must live in the opaque handles the
        # Driver threads through grad_hess/loss_value.
        y = np.asarray(y)
        valid = np.zeros(self._pad_rows(y).shape[0], np.float32)
        valid[: y.shape[0]] = (
            1.0 if sample_weight is None
            else np.asarray(sample_weight, np.float32))
        return LabelHandle(self._put_rows(y), self._put_rows(valid))

    # ------------------------------------------------------------------ #
    # granular L3 kernels (parity/bench surface)
    # ------------------------------------------------------------------ #

    @functools.cached_property
    def _hist_fns(self) -> dict:
        # (impl, row_chunk) -> dispatcher; one entry per degrade-ladder
        # step actually reached (almost always just the first).
        return {}

    def _hist_fn_for(self, impl: str, row_chunk: int):
        key = (impl, row_chunk)
        fn = self._hist_fns.get(key)
        if fn is not None:
            return fn
        cfg = self.cfg

        if self.feature_partitions > 1:
            def unsupported(*a, **k):
                raise NotImplementedError(
                    "the granular build_histograms surface is row-parallel "
                    "only; feature_partitions > 1 is handled inside "
                    "grow_tree (the Driver path)"
                )
            self._hist_fns[key] = unsupported
            return unsupported

        rax = self._row_axes
        rs = self.stream_hist_comms == "reduce_scatter"

        def hist(Xb, g, h, node_index, *, n_nodes):
            # impl resolution happens inside build_histograms with the full
            # shape (pallas only when its VMEM working set fits).
            out = hist_ops.build_histograms(
                Xb, g, h, node_index, n_nodes, cfg.n_bins,
                impl=impl, row_chunk=row_chunk,
                input_dtype=self._input_dtype,
            )
            if self.distributed:
                # The fabric-allreduce analog (parallel/comms.py); over
                # (hosts, rows) XLA phases it ICI-reduce first, then the
                # cross-slice DCN hop. Under split_comms=reduce_scatter
                # each shard keeps only its merged F/P slab on device —
                # the host reassembles the full table from the sharded
                # output at D2H time, so the WIRE pays the scatter cost
                # while the caller contract is unchanged.
                if rs:
                    out = comms_lib.pad_to_multiple(out, 1, self.row_shards)
                out = comms_lib.hist_reduce(
                    out, rax,
                    mode="reduce_scatter" if rs else "allreduce",
                    comms_dtype=cfg.hist_comms_dtype, scatter_dim=1)
            return out

        if self.distributed:
            lay = self.layout

            def sharded(Xb, g, h, node_index, *, n_nodes):
                out_specs = (lay.level_hist_scattered() if rs
                             else lay.replicated())
                f = mesh_lib.shard_map(
                    functools.partial(hist, n_nodes=n_nodes),
                    mesh=self.mesh,
                    in_specs=lay.specs("data", "grad", "hess",
                                       "node_index"),
                    out_specs=out_specs,
                )
                out = f(Xb, g, h, node_index)
                if rs and out.shape[1] != Xb.shape[1]:
                    out = out[:, :Xb.shape[1]]   # drop scatter pad columns
                return out
            self._hist_fns[key] = sharded
            return sharded
        self._hist_fns[key] = hist
        return hist

    # Graceful-degradation ladder for the granular/streamed histogram
    # surface (docs/ROBUSTNESS.md): a RESOURCE_EXHAUSTED from the
    # resolved impl (the Pallas VMEM kernel pins its working set; a
    # config past the budget predicate's model can still OOM on a busy
    # chip) steps DOWN — matmul at the default row chunk, matmul at a
    # small row chunk (a quarter of the one-hot working set), finally
    # the scatter path — instead of discarding the run. The step is
    # STICKY per backend instance (the same shape would OOM again) and
    # each step emits a fault event + the hist_oom_degrades counter.
    _HIST_DEGRADE_ROW_CHUNK = 8192

    @functools.cached_property
    def _hist_ladder(self) -> list:
        default_rc = 32_768
        ladder = [(self.cfg.hist_impl, default_rc)]
        for step in (("matmul", default_rc),
                     ("matmul", self._HIST_DEGRADE_ROW_CHUNK),
                     ("segment", default_rc)):
            # Membership (not just last-entry) dedup: hist_impl=
            # "segment" must yield [segment, matmul, matmul@8k], never
            # re-climb to a hungrier impl only to re-try the one that
            # just OOM'd. (segment IS the floor for scatter-friendly
            # platforms, but matmul's bounded row chunks are the only
            # lower-VMEM option left when scatter itself blew up.)
            if step not in ladder:
                ladder.append(step)
        return ladder

    def build_histograms(self, data, g, h, node_index, n_nodes):
        g = g if isinstance(g, jax.Array) else self._put_rows(np.asarray(g))
        h = h if isinstance(h, jax.Array) else self._put_rows(np.asarray(h))
        if not isinstance(node_index, jax.Array):
            node_index = self._put_rows(
                self._pad_rows_index(np.asarray(node_index))
            )
        while True:
            impl, row_chunk = self._hist_ladder[self._hist_degrade]
            try:
                faultplan.inject("hist.build")
                return self._hist_fn_for(impl, row_chunk)(
                    data, g, h, node_index, n_nodes=n_nodes)
            except Exception as e:
                if not faultplan.is_resource_exhausted(e) \
                        or self._hist_degrade + 1 >= len(self._hist_ladder):
                    raise
                self._hist_degrade += 1
                nxt, nxt_rc = self._hist_ladder[self._hist_degrade]
                tele_counters.record_hist_oom_degrade()
                emit_fault("hist_oom_degrade", from_impl=impl,
                           to_impl=nxt, row_chunk=nxt_rc)
                log.warning(
                    "histogram build RESOURCE_EXHAUSTED under impl=%s "
                    "(row_chunk=%d); degrading to impl=%s (row_chunk=%d) "
                    "for the rest of this process: %s",
                    impl, row_chunk, nxt, nxt_rc, str(e)[:200])

    def _pad_rows_index(self, idx: np.ndarray) -> np.ndarray:
        """Pad a node-index vector with -1 (frozen) so pad rows are inert."""
        R = idx.shape[0]
        Rp = -(-R // self.row_shards) * self.row_shards
        if Rp == R:
            return idx
        return np.concatenate(
            [idx, np.full(Rp - R, -1, idx.dtype)]
        )

    def best_splits(self, hist):
        # The granular L4 surface keeps the 3-tuple contract (no missing
        # handling — that lives in the fused grow path with the config flag).
        return split_ops.best_splits(
            jnp.asarray(hist), self.cfg.reg_lambda, self.cfg.min_child_weight
        )[:3]

    # ------------------------------------------------------------------ #
    # fused training ops
    # ------------------------------------------------------------------ #

    def init_pred(self, y, base: float):
        Rp = y.y.shape[0]
        if self.cfg.loss == "softmax":
            z = np.zeros((Rp, self.cfg.n_classes), np.float32)
            sh = self._row_sharding(extra_dims=1)
        else:
            z = np.full(Rp, base, np.float32)
            sh = self._row_sharding()
        return self._put(z, sh)

    def load_pred(self, raw: np.ndarray):
        extra = 1 if raw.ndim == 2 else 0
        return self._put_rows(raw.astype(np.float32), extra_dims=extra)

    @functools.cached_property
    def _grad_fn(self):
        loss = self.cfg.loss

        @jax.jit
        def f(pred, y, valid):
            g, h = grad_ops.grad_hess(pred, y, loss)
            if g.ndim == 2:
                v = valid[:, None]
            else:
                v = valid
            return g * v, h * v  # pad rows contribute nothing anywhere

        return costed("grad", phase="grad")(f)

    def grad_hess(self, pred, y):
        return self._grad_fn(pred, y.y, y.valid)

    @functools.cached_property
    def _grow_fn(self):
        return self._build_grow_fn(with_mask=False)

    @functools.cached_property
    def _grow_masked_fn(self):
        return self._build_grow_fn(with_mask=True)

    def _build_grow_fn(self, with_mask: bool):
        cfg = self.cfg
        axis = self._row_axes if self.distributed else None
        faxis = FAXIS if self.feature_partitions > 1 else None
        quant = self._grad_quant
        # Platform-resolved ONCE at program build (trace-time static) —
        # the fused and granular paths must agree or their bit-exactness
        # contract breaks. Integer hists (quantized grads) subtract
        # exactly, so 'auto' resolves ON regardless of platform there.
        subtract = grow_ops.resolve_hist_subtraction(
            cfg.hist_subtraction, integer_hists=quant)

        def grow_full(Xb, g, h, fmask=None, tid=None):
            tree = grow_ops.grow_tree(
                Xb, g, h,
                max_depth=cfg.max_depth,
                n_bins=cfg.n_bins,
                reg_lambda=cfg.reg_lambda,
                min_child_weight=cfg.min_child_weight,
                min_split_gain=cfg.min_split_gain,
                hist_impl=cfg.hist_impl,   # per-level shape-aware resolution
                input_dtype=self._input_dtype,
                axis_name=axis,
                feature_axis_name=faxis,
                feature_mask=fmask,
                missing_bin=cfg.missing_policy == "learn",
                cat_features=cfg.cat_features,
                hist_subtraction=subtract,
                split_comms=self.split_comms,
                hist_comms_dtype=cfg.hist_comms_dtype,
                comms_slabs=self.comms_slabs,
                grad_dtype=cfg.grad_dtype,
                quant_tree_id=tid,
                quant_seed=cfg.seed,
            )
            delta = grow_ops.tree_predict_delta(tree, cfg.learning_rate)
            # Pack the tiny node arrays into ONE f32 array so the host
            # needs a single device→host fetch per tree (separate
            # np.asarray calls each pay the full transfer round-trip —
            # measured ~90 ms apiece through a remote-attached chip, 4x the
            # tree's compute). int32 features/bins and booleans are exact
            # in f32 (values << 2^24).
            packed = _pack_tree(tree)
            return packed, delta

        # One positional jit signature per (mask?, quant?) combination:
        # the quantized programs take the traced tree id (the stochastic-
        # rounding key) as a real operand so tree k+1 never retraces.
        if with_mask and quant:
            grow = grow_full
        elif with_mask:
            def grow(Xb, g, h, fmask):
                return grow_full(Xb, g, h, fmask, None)
        elif quant:
            def grow(Xb, g, h, tid):
                return grow_full(Xb, g, h, None, tid)
        else:
            def grow(Xb, g, h):
                return grow_full(Xb, g, h, None, None)

        if self.distributed:
            lay = self.layout
            in_specs = lay.specs("data", "grad", "hess")
            if with_mask:
                in_specs = in_specs + lay.specs("mask")   # replicated
            if quant:
                in_specs = in_specs + lay.specs("scalar")  # tree id
            grow = mesh_lib.shard_map(
                grow,
                mesh=self.mesh,
                in_specs=in_specs,
                out_specs=(lay.replicated(), lay.row_vector()),
                # Feature-parallel growth replicates every output across the
                # feature axis BIT-IDENTICALLY by construction (split triples
                # come out of an all_gather + argmax every shard computes the
                # same way; node totals/leaf aggregates reduce feature-axis-
                # replicated row vectors with identical programs on every
                # shard; routing values ride a psum).
                # The static VMA checker cannot see through the gathered
                # argmax, so it is disabled for that path — and for
                # reduce-scatter split finding, whose winner combine is
                # the same gathered-argmax shape over the row axes.
                check_vma=(faxis is None
                           and self.split_comms != "reduce_scatter"),
            )
        # Cost observatory registration: on telemetry runs the first call
        # per shape pulls XLA's cost/memory analysis for the whole
        # per-tree growth program (telemetry/costmodel.py); inert wrapper
        # otherwise.
        return costed("grow", phase="grow")(jax.jit(grow))

    def grow_tree(self, data, g, h,
                  feature_mask=None, tree_id: int = 0) -> tuple[Any, Any]:
        """Returns (device packed-tree handle, delta) — no host sync here;
        the Driver resolves the handle via fetch_tree one round later.
        `tree_id` (absolute tree index) keys the quantized-gradient
        stochastic rounding when cfg.grad_dtype != 'f32' — a traced
        operand, so every round shares one compiled program."""
        tid = (np.int32(tree_id),) if self._grad_quant else ()
        if feature_mask is None:
            return self._grow_fn(data, g, h, *tid)
        # Pad the host mask to the (padded, global) feature count; padded
        # columns stay masked out.
        Fg = data.shape[1]
        m = np.zeros(Fg, bool)
        m[: feature_mask.shape[0]] = feature_mask
        return self._grow_masked_fn(data, g, h, jax.device_put(m), *tid)

    def sync(self, x) -> None:
        from ddt_tpu.utils.device import device_sync

        device_sync(x)

    @property
    def host_index(self) -> int:
        """This process's index in the pod (0 single-process) — stamped
        into run manifests so cross-host log merges (telemetry.merge)
        can label lanes."""
        return int(jax.process_index())

    def partition_ready_ms(self, handle) -> "list | None":
        """Per-device completion times of a dispatched output handle —
        [(device_id, perf_counter time)], the flight recorder's probe
        (telemetry.events.PartitionRecorder rides this; the probe is a
        barrier on the handle, so it runs only on mesh runs WITH a run
        log attached)."""
        return mesh_lib.shard_ready_times(handle)

    # Compiled callables and caches that close over self.mesh — every
    # entry must be dropped when the mesh changes (rotate_row_partitions)
    # or a stale program would keep placing shards on the old devices.
    _MESH_BOUND_CACHES = (
        "_hist_fns", "_grow_fn", "_grow_masked_fn", "_grad_fn",
        "_rounds_fns", "_rounds_masked_fns", "_rounds_eval_fns",
        "_eval_fns", "_stream_cache", "_apply_fn", "_row_mask_fn",
        "_loss_fn", "_predict_cache", "_predict_impl_resolved",
    )

    def rotate_row_partitions(self) -> bool:
        """Static row re-partitioning, rotation form (the straggler
        watchdog's action — docs/ROBUSTNESS.md): rebuild the mesh with
        the device order rotated by one, so each row shard moves to the
        next physical device. Shard CONTENTS are untouched — same global
        padded row layout, same psum structure — so the trained model is
        unchanged by construction; what moves is which device does which
        shard's work (the right response to a slow device; a no-op for
        pure data skew). Costs a recompile of every mesh-bound program
        plus the caller's reshard of live handles (reshard_rows) — why
        the Driver only triggers it at checkpoint boundaries. Returns
        False (and does nothing) on single-device backends and
        multi-process meshes (rotating a pod's global device list needs
        every process to agree; that is ROADMAP item 3's elastic
        rework)."""
        if not self.distributed or jax.process_count() > 1:
            return False
        # Rotate along the ROW axis of the device grid, feature (and
        # host) coordinates preserved: on a 2D (rows x features) mesh a
        # flat-list rotation would move devices ACROSS feature columns
        # — scrambling which device owns which column slab and forcing
        # an F-axis reshuffle of the data itself. Rolling the rows axis
        # moves every row shard to the next device IN ITS COLUMN, which
        # degenerates to the classic flat rotation on a pure row mesh.
        grid = self.mesh.devices
        rows_ax = list(self.mesh.axis_names).index(AXIS)
        rotated = np.roll(grid, 1, axis=rows_ax)
        # Mesh(ndarray) — NOT jax.make_mesh: make_mesh routes through
        # mesh_utils.create_device_mesh, whose TPU branch rebuilds the
        # order from physical torus coordinates of the device SET and
        # silently discards the rotation (the CPU branch preserves it,
        # which is why only a chip run would have noticed). The explicit
        # ndarray constructor keeps the caller's order everywhere.
        self.mesh = jax.sharding.Mesh(rotated, self.mesh.axis_names)
        for attr in self._MESH_BOUND_CACHES:
            self.__dict__.pop(attr, None)
        log.info("rotated row partitions: shard 0 now on device %s",
                 rotated.flat[0].id)
        return True

    def reshard_rows(self, handle, extra_dims: int = 0):
        """Move a live row-sharded handle onto the CURRENT mesh (after
        rotate_row_partitions) — a device-to-device copy, values
        untouched."""
        if handle is None or not self.distributed:
            return handle
        return jax.device_put(handle, self._row_sharding(extra_dims))

    def reshard_data(self, handle):
        """reshard_rows for the binned data handle: the 2D layout's
        COLUMN sharding is preserved (a plain row reshard would
        silently replicate every feature slab)."""
        if handle is None or not self.distributed:
            return handle
        return jax.device_put(handle, self._named(self.layout.binned_data()))

    # ------------------------------------------------------------------ #
    # fused multi-round training: a whole block of boosting rounds in ONE
    # device dispatch (lax.scan over rounds). Per-round dispatch economics
    # dominate wallclock through a remote-attached chip (~10-30 ms of host
    # overhead per call x 3 calls x 100 rounds); the scan collapses that to
    # one dispatch + ONE tree fetch per block. Colsample masks ride the
    # scan as xs; bagging masks are recomputed in-scan from the stateless
    # counter hash (ops/sampling); eval rides via grow_rounds_eval. Only
    # profiling and the bagging+eval combination fall back to the
    # granular path (driver.py fit()).
    # ------------------------------------------------------------------ #

    def grow_rounds(self, data, pred, y: "LabelHandle", n_rounds: int,
                    first_round: int = 0):
        """Run `n_rounds` boosting rounds on device. Returns device handles
        (packed_trees [n_rounds, C, 5, n_nodes] f32, new_pred,
        losses [n_rounds] f32 — loss AFTER each round, matching
        loss_value's semantics). With cfg.subsample < 1, bagging row
        masks are recomputed IN-SCAN from the counter-based hash of
        (cfg.seed, first_round + k, global row id) — ops/sampling — so
        `first_round` (the absolute round index of the block's first
        round) is part of the program's inputs, not its cache key."""
        fn = self._rounds_fns.get(n_rounds)
        if fn is None:
            fn = self._build_rounds_fn(n_rounds)
            self._rounds_fns[n_rounds] = fn
        args = (data, pred, y.y, y.valid)
        if self.cfg.subsample < 1.0 or self._grad_quant:
            args = args + (np.int32(first_round),)
        return fn(*args)

    @staticmethod
    def _pad_fmasks(data, fmasks: np.ndarray) -> np.ndarray:
        """Pad host [K, C, F] colsample masks to the GLOBAL (padded)
        column count; padded columns stay masked out."""
        K, C, F = fmasks.shape
        Fg = data.shape[1]          # jax.Array shape is GLOBAL (padded)
        m = np.zeros((K, C, Fg), bool)
        m[..., :F] = fmasks
        return m

    def grow_rounds_masked(self, data, pred, y: "LabelHandle",
                           n_rounds: int, fmasks: np.ndarray,
                           first_round: int = 0):
        """grow_rounds with per-round/per-class colsample feature masks
        riding the scan as xs: `fmasks` is host bool [n_rounds, C, F]
        (KBs). Composes with in-scan bagging (see grow_rounds)."""
        m = self._pad_fmasks(data, fmasks)
        fn = self._rounds_masked_fns.get(n_rounds)
        if fn is None:
            fn = self._build_rounds_fn(n_rounds, masked=True)
            self._rounds_masked_fns[n_rounds] = fn
        args = (data, pred, y.y, y.valid, m)
        if self.cfg.subsample < 1.0 or self._grad_quant:
            args = args + (np.int32(first_round),)
        return fn(*args)

    @functools.cached_property
    def _rounds_masked_fns(self) -> dict:
        return {}

    def grow_rounds_eval(self, data, pred, y: "LabelHandle", n_rounds: int,
                         val_data, val_pred, val_y: "LabelHandle",
                         metric: str, first_round: int = 0,
                         fmasks: "np.ndarray | None" = None):
        """grow_rounds with validation scoring INSIDE the scan: each
        round's trees are applied to the resident validation predictions
        and the metric's f32 device twin evaluates per round — eval runs
        at fused-dispatch speed (no per-round host round-trips; one [K]
        scores fetch per block). Metric must have a device twin — every
        shipped valid metric/loss combination has one since round 5's
        binned-rank auc (softmax-auc is rejected at fit; a future
        twin-less metric would ride the granular path). Composes with
        colsample (`fmasks`, riding the scan as xs) and bagging
        (in-scan counter masks keyed by first_round — see grow_rounds).
        Returns (packed_trees, new_pred, losses, new_val_pred,
        scores [n_rounds] f32)."""
        key = (n_rounds, metric, fmasks is not None)
        fn = self._rounds_eval_fns.get(key)
        if fn is None:
            fn = self._build_rounds_fn(n_rounds, eval_metric=metric,
                                       masked=fmasks is not None)
            self._rounds_eval_fns[key] = fn
        args = (data, pred, y.y, y.valid,
                val_data, val_pred, val_y.y, val_y.valid)
        if fmasks is not None:
            args = args + (self._pad_fmasks(data, fmasks),)
        if self.cfg.subsample < 1.0 or self._grad_quant:
            args = args + (np.int32(first_round),)
        return fn(*args)

    @functools.cached_property
    def _rounds_eval_fns(self) -> dict:
        return {}

    @functools.cached_property
    def _rounds_fns(self) -> dict:
        return {}

    def _build_rounds_fn(self, K: int, eval_metric: str | None = None,
                         masked: bool = False):
        # One program per (K, eval?, masked?) with bagging cfg-static:
        # every combination of colsample masks, in-scan bagging, and
        # in-scan eval composes in the single scan below (round 5).
        from ddt_tpu.ops import sampling as sampling_ops
        from ddt_tpu.ops import stream as stream_ops
        from ddt_tpu.utils.metrics import device_metric

        cfg = self.cfg
        bagging = cfg.subsample < 1.0
        quant = self._grad_quant
        # Quantized rounds need the absolute round id in-scan too (the
        # stochastic-rounding key is (seed, round * C + class, row)),
        # riding the same xs lane the bagging hash already uses.
        need_rids = bagging or quant
        C = cfg.n_classes if cfg.loss == "softmax" else 1
        axis = self._row_axes if self.distributed else None
        faxis = FAXIS if self.feature_partitions > 1 else None
        input_dtype = self._input_dtype
        mfn = device_metric(eval_metric, n_classes=C) if eval_metric \
            else None
        missing = cfg.missing_policy == "learn"
        subtract = grow_ops.resolve_hist_subtraction(
            cfg.hist_subtraction, integer_hists=quant)

        allreduce = _axis_allreduce(axis)

        def loss_of(pred, ya, valid):
            # Shared loss formulas (ops/grad.mean_loss); reductions psum'd
            # when row shards exist (inside shard_map the plain sums are
            # shard-local).
            return grad_ops.mean_loss(pred, ya, valid, cfg.loss,
                                      allreduce=allreduce)

        hp_n = self.n_partitions

        def rounds(data_a, pred0, ya, valid, *rest):
            rest = list(rest)
            rnd0 = rest.pop() if need_rids else None  # block's first round
            if masked:
                fmasks = rest.pop()           # [K, C, Fg] bool, scan xs
            if mfn is not None:
                val_data, vpred0, vy, vvalid = rest
                cat_vec = split_ops.cat_feature_vec(
                    cfg.cat_features,
                    val_data.shape[1] * self.feature_partitions)

            def one_round(pred, vpred, fmask_r=None, rid=None):
                g, h = grad_ops.grad_hess(pred, ya, cfg.loss)
                v = valid[:, None] if g.ndim == 2 else valid
                g = g * v
                h = h * v
                if bagging:
                    # Counter-based bagging bit per (round, global row) —
                    # exactly the granular path's host-drawn mask
                    # (ops/sampling twins are bit-identical; 0/1 f32
                    # multiplies commute exactly with the valid scaling).
                    keep = sampling_ops.row_keep_jax(
                        rid, _local_row_offset(axis, hp_n, ya.shape[0]),
                        ya.shape[0],
                        seed=cfg.seed, subsample=cfg.subsample)
                    kv = keep[:, None] if g.ndim == 2 else keep
                    g = g * kv
                    h = h * kv
                packs = []
                for c in range(C):
                    gc = g[:, c] if C > 1 else g
                    hc = h[:, c] if C > 1 else h
                    tree = grow_ops.grow_tree(
                        data_a, gc, hc,
                        max_depth=cfg.max_depth,
                        n_bins=cfg.n_bins,
                        reg_lambda=cfg.reg_lambda,
                        min_child_weight=cfg.min_child_weight,
                        min_split_gain=cfg.min_split_gain,
                        hist_impl=cfg.hist_impl,
                        input_dtype=input_dtype,
                        axis_name=axis,
                        feature_axis_name=faxis,
                        feature_mask=(
                            fmask_r[c] if fmask_r is not None else None),
                        missing_bin=missing,
                        cat_features=cfg.cat_features,
                        hist_subtraction=subtract,
                        split_comms=self.split_comms,
                        hist_comms_dtype=cfg.hist_comms_dtype,
                        comms_slabs=self.comms_slabs,
                        grad_dtype=cfg.grad_dtype,
                        quant_tree_id=(rid * C + c) if quant else None,
                        quant_seed=cfg.seed,
                    )
                    delta = grow_ops.tree_predict_delta(
                        tree, cfg.learning_rate)
                    pred = (pred.at[:, c].add(delta) if C > 1
                            else pred + delta)
                    if mfn is not None:
                        vpred = stream_ops.apply_tree_pred(
                            val_data, vpred,
                            tree.feature, tree.threshold_bin,
                            tree.is_leaf, tree.leaf_value,
                            tree.default_left if missing else None,
                            max_depth=cfg.max_depth,
                            learning_rate=cfg.learning_rate,
                            class_idx=c,
                            missing_bin_value=cfg.missing_bin_value,
                            cat_vec=cat_vec,
                            feature_axis_name=faxis,
                        )
                    packs.append(_pack_tree(tree))
                return pred, vpred, jnp.stack(packs), loss_of(
                    pred, ya, valid)

            # Scan xs: the round's colsample masks [C, Fg] and/or its
            # absolute round id (the bagging AND/OR grad-quant rounding
            # hash key) — any combination composes, with or without
            # in-scan eval.
            rids = (jnp.arange(K, dtype=jnp.int32) + rnd0) if need_rids \
                else None
            if masked and need_rids:
                xs = (fmasks, rids)
            elif masked:
                xs = fmasks
            elif need_rids:
                xs = rids
            else:
                xs = None

            def unpack(x):
                if masked and need_rids:
                    return x[0], x[1]
                if masked:
                    return x, None
                if need_rids:
                    return None, x
                return None, None

            if mfn is not None:
                def body(carry, x):
                    pred, vpred = carry
                    fm, rid = unpack(x)
                    pred, vpred, packs, loss = one_round(pred, vpred,
                                                         fm, rid)
                    return (pred, vpred), (
                        packs, loss, mfn(vy, vpred, vvalid, allreduce))

                (predf, vpredf), (trees, losses, scores) = jax.lax.scan(
                    body, (pred0, vpred0), xs,
                    length=K if xs is None else None)
                return trees, predf, losses, vpredf, scores

            def body(carry, x):
                fm, rid = unpack(x)
                pred, _, packs, loss = one_round(carry, None, fm, rid)
                return pred, (packs, loss)

            predf, (trees, losses) = jax.lax.scan(
                body, pred0, xs, length=K if xs is None else None)
            return trees, predf, losses

        if self.distributed:
            lay = self.layout
            pred_name = "pred" if C > 1 else "pred1d"
            pred_spec = lay.spec(pred_name)
            in_specs = lay.specs("data", pred_name, "y", "valid")
            out_specs = (lay.replicated(), pred_spec, lay.replicated())
            if mfn is not None:
                in_specs = in_specs + lay.specs("data", pred_name, "y",
                                                "valid")
                out_specs = out_specs + (pred_spec, lay.replicated())
            if masked:
                in_specs = in_specs + lay.specs("fmasks")   # replicated
            if need_rids:
                in_specs = in_specs + lay.specs("scalar")   # rnd0 repl.
            rounds = mesh_lib.shard_map(
                rounds,
                mesh=self.mesh,
                in_specs=in_specs,
                out_specs=out_specs,
                # Same rationale as _build_grow_fn: tree outputs are
                # replicated bit-identically by construction; the static
                # VMA checker cannot see through the gathered argmax
                # (feature-parallel OR reduce-scatter winner combine).
                check_vma=(faxis is None
                           and self.split_comms != "reduce_scatter"),
            )
        # Both block-reassigned prediction buffers are donated (the Driver
        # rebinds pred AND val_pred from the return every block).
        donate = (1, 5) if mfn is not None else (1,)
        # Cost registration for the fused block program (the roofline's
        # grow_block row folds in the fetch_tree barrier that carries the
        # block's device wallclock — telemetry/costmodel.roofline_table).
        return costed("grow_block", phase="grow_block")(
            jax.jit(rounds, donate_argnums=donate))

    # ------------------------------------------------------------------ #
    # device-side eval_set scoring (round-1 verdict, Weak #5): validation
    # predictions stay RESIDENT on device; each round's freshly grown
    # trees (still-on-device packed handles) are applied by the same
    # routing formulation as training, and the metric is computed on
    # device when its f32 twin exists (logloss/rmse/accuracy, plus
    # binary auc via the binned-rank twin since round 5 — one scalar
    # crosses the host boundary per round). The metric=None branch
    # (fetch a replicated raw-score copy for host evaluation) remains
    # as the generic fallback for twin-less metrics; no shipped metric
    # is twin-less anymore, so tests/test_metrics.py's
    # twinless-fallback test forces the registry empty to keep the
    # branch exercised on a pod mesh.
    # ------------------------------------------------------------------ #

    def eval_round(self, val_data, val_pred, handles, val_y: "LabelHandle",
                   metric: str | None):
        """Apply this round's trees (one packed handle per class) to the
        resident validation predictions. Returns (new_val_pred, score):
        score is a device scalar when the metric has an f32 device twin,
        else a REPLICATED copy of the predictions (safe to np.asarray even
        when the resident state spans a multi-host mesh) for host-side
        metric evaluation."""
        fn = self._eval_fns.get((len(handles), metric))
        if fn is None:
            fn = self._build_eval_fn(len(handles), metric)
            self._eval_fns[(len(handles), metric)] = fn
        return fn(val_data, val_pred, val_y.y, val_y.valid, *handles)

    @functools.cached_property
    def _eval_fns(self) -> dict:
        return {}

    def _build_eval_fn(self, C: int, metric: str | None):
        from ddt_tpu.ops import stream as stream_ops
        from ddt_tpu.utils.metrics import device_metric

        cfg = self.cfg
        faxis = FAXIS if self.feature_partitions > 1 else None
        mfn = device_metric(metric, n_classes=C) if metric else None
        missing = cfg.missing_policy == "learn"
        rax = self._row_axes

        def f(Xb, pred, y, valid, *packs):
            cat_vec = split_ops.cat_feature_vec(
                cfg.cat_features, Xb.shape[1] * self.feature_partitions)
            for c, pk in enumerate(packs):
                pred = stream_ops.apply_tree_pred(
                    Xb, pred,
                    pk[0].astype(jnp.int32), pk[1].astype(jnp.int32),
                    pk[2].astype(bool), pk[3],
                    pk[5].astype(bool) if missing else None,
                    max_depth=cfg.max_depth,
                    learning_rate=cfg.learning_rate,
                    class_idx=c,
                    missing_bin_value=cfg.missing_bin_value,
                    cat_vec=cat_vec,
                    feature_axis_name=faxis,
                )
            if mfn is None:
                # Host-metric path (auc): second output is a REPLICATED
                # copy of the predictions — np.asarray on the row-sharded
                # state itself would fail on a multi-host mesh (spans
                # non-addressable devices).
                gathered = (
                    comms_lib.all_gather(pred, rax, axis=0, tiled=True)
                    if self.distributed else pred
                )
                return pred, gathered
            return pred, mfn(y, pred, valid, _axis_allreduce(
                rax if self.distributed else None))

        if self.distributed:
            lay = self.layout
            pred_name = "pred" if C > 1 else "pred1d"
            pred_spec = lay.spec(pred_name)
            in_specs = (lay.specs("data", pred_name, "y", "valid")
                        + lay.specs(*(["tree"] * C)))
            out_specs = (pred_spec, lay.replicated())
            f = mesh_lib.shard_map(
                f, mesh=self.mesh, in_specs=in_specs, out_specs=out_specs,
                # Same rationale as _build_grow_fn: the feature-axis
                # psum-broadcast routing — and the tiled all_gather of the
                # host-metric path — defeat the static VMA checker even
                # though both outputs are replicated by construction.
                check_vma=faxis is None and mfn is not None,
            )
        return costed("eval", phase="eval")(jax.jit(f, donate_argnums=(1,)))

    def apply_row_mask(self, g, h, mask):
        # Upload bool (1 byte/row); the cast to f32 is a free fused device op.
        m = self._put_rows(mask.astype(bool))
        return self._row_mask_fn(g, h, m)

    @functools.cached_property
    def _row_mask_fn(self):
        @jax.jit
        def f(g, h, m):
            m = m.astype(jnp.float32)
            if g.ndim == 2:
                m = m[:, None]
            return g * m, h * m

        return f

    def fetch_tree(self, handle) -> HostTree:
        def _fetch():
            # The per-tree D2H round-trip is the Driver's one recurring
            # host<->device transfer — through a remote-attached chip it
            # is also the seam a tunnel reset tears first, so it retries
            # transient runtime faults (UNAVAILABLE/DEADLINE_EXCEEDED)
            # with backoff; the chaos harness injects here.
            faultplan.inject("fetch_tree")
            return np.asarray(handle)                    # ONE fetch

        packed = retry_lib.retry_call(_fetch, seam="fetch_tree")
        tele_counters.record_d2h(packed.nbytes)          # run-log counter
        return HostTree(
            feature=packed[0].astype(np.int32),
            threshold_bin=packed[1].astype(np.int32),
            is_leaf=packed[2].astype(bool),
            leaf_value=packed[3].astype(np.float32),
            split_gain=packed[4].astype(np.float32),
            default_left=packed[5].astype(bool),
        )

    @functools.cached_property
    def _apply_fn(self):
        @functools.partial(jax.jit, static_argnames=("class_idx",), donate_argnums=(0,))
        def f(pred, delta, class_idx):
            if pred.ndim == 2:
                return pred.at[:, class_idx].add(delta)
            return pred + delta

        return f

    def apply_delta(self, pred, delta, class_idx: int):
        return self._apply_fn(pred, delta, class_idx=class_idx)

    @functools.cached_property
    def _loss_fn(self):
        loss = self.cfg.loss

        @jax.jit
        def f(pred, y, valid):
            return grad_ops.mean_loss(pred, y, valid, loss)

        return f

    def loss_value(self, pred, y) -> float:
        return float(self._loss_fn(pred, y.y, y.valid))

    # ------------------------------------------------------------------ #
    # streaming (ops/stream.py): per-(chunk, level) work as one dispatch,
    # partial-tree traversal + grads + histogram on device; the host only
    # accumulates the small histograms and decides splits. Used by
    # streaming.fit_streaming when the backend exposes these.
    # ------------------------------------------------------------------ #

    @functools.cached_property
    def _stream_cache(self) -> dict:
        return {}

    def _stream_fn(self, kind: str, depth: int, class_idx: int,
                   left: bool = False):
        key = (kind, depth, class_idx, left)
        fn = self._stream_cache.get(key)
        if fn is not None:
            return fn
        from ddt_tpu.ops import sampling as sampling_ops
        from ddt_tpu.ops import stream as stream_ops

        cfg = self.cfg
        comms_mode = self.stream_hist_comms
        comms_dtype = cfg.hist_comms_dtype
        if self.feature_partitions > 1:
            raise NotImplementedError(
                "streaming with feature_partitions > 1 is not wired; "
                "stream rows (the long axis) instead"
            )
        axis = self._row_axes if self.distributed else None
        softmax = cfg.loss == "softmax"
        missing_val = cfg.missing_bin_value
        # Bagging ops take 3 extra traced scalars — (round id, chunk row
        # base lo/hi) — and recompute the counter-based keep mask on
        # device per chunk (ops/sampling; O(chunk), no mask shipping).
        # Quantized-gradient ops need the SAME scalars (the stochastic-
        # rounding key is (seed, tree, global row)) plus the round's two
        # host-reduced scales for hist/leaf builds.
        bagged = cfg.subsample < 1.0 and kind != "update"
        quant = self._grad_quant and kind in ("hist", "leaf",
                                              "roundstart", "gradstats")
        takes_rnd = bagged or quant
        takes_scales = quant and kind in ("hist", "leaf")
        hp_n = self.n_partitions
        Cq = cfg.n_classes if softmax else 1

        def parse_extra(extra):
            """(rnd, blo, bhi, gscale, hscale) from the trailing traced
            scalars — appended as (rnd, lo, hi[, gscale, hscale])."""
            it = list(extra)
            gsc = hsc = None
            if takes_scales:
                hsc = it.pop()
                gsc = it.pop()
            rnd = blo = bhi = None
            if takes_rnd:
                bhi = it.pop()
                blo = it.pop()
                rnd = it.pop()
            return rnd, blo, bhi, gsc, hsc

        def row_keep_for(n_rows, rnd, blo, bhi):
            if not bagged:
                return None
            return sampling_ops.row_keep_jax(
                rnd, _local_row_offset(axis, hp_n, n_rows),
                n_rows, seed=cfg.seed, subsample=cfg.subsample,
                row_start_lo=blo, row_start_hi=bhi)

        def quantizer_for(n_rows, rnd, blo, bhi, gsc, hsc):
            """The stream ops' quantize seam: this round's shared scales
            + this chunk's global-row-id base (ops/grad — tree_id =
            rnd * C + class keys the per-output-dim rounding)."""
            def q(gv, hv):
                return grad_ops.quantize_with_scales(
                    gv, hv, gsc, hsc, grad_dtype=cfg.grad_dtype,
                    tree_id=rnd * Cq + class_idx, seed=cfg.seed,
                    local_offset=_local_row_offset(axis, hp_n, n_rows),
                    row_start_lo=blo, row_start_hi=bhi)
            return q

        def cat_vec_for(Xb):
            return split_ops.cat_feature_vec(cfg.cat_features, Xb.shape[1])

        if kind == "hist":
            def f(Xb, pred, y, valid, feat, thr, leaf, dl, *extra):
                rnd, blo, bhi, gsc, hsc = parse_extra(extra)
                return stream_ops.stream_level_hist(
                    Xb, pred, y, valid, feat, thr, leaf, dl,
                    depth=depth, n_bins=cfg.n_bins, loss=cfg.loss,
                    class_idx=class_idx, hist_impl=cfg.hist_impl,
                    input_dtype=self._input_dtype, axis_name=axis,
                    missing_bin_value=missing_val, cat_vec=cat_vec_for(Xb),
                    row_keep=row_keep_for(Xb.shape[0], rnd, blo, bhi),
                    comms_mode=comms_mode, comms_dtype=comms_dtype,
                    build_left=left,
                    quantize=(quantizer_for(Xb.shape[0], rnd, blo, bhi,
                                            gsc, hsc) if quant else None),
                )
        elif kind == "leaf":
            def f(Xb, pred, y, valid, feat, thr, leaf, dl, *extra):
                rnd, blo, bhi, gsc, hsc = parse_extra(extra)
                return stream_ops.stream_leaf_gh(
                    Xb, pred, y, valid, feat, thr, leaf, dl,
                    max_depth=depth, loss=cfg.loss, class_idx=class_idx,
                    axis_name=axis,
                    missing_bin_value=missing_val, cat_vec=cat_vec_for(Xb),
                    row_keep=row_keep_for(Xb.shape[0], rnd, blo, bhi),
                    quantize=(quantizer_for(Xb.shape[0], rnd, blo, bhi,
                                            gsc, hsc) if quant else None),
                )
        elif kind == "update":
            def f(Xb, pred, feat, thr, leaf, val, dl):
                return stream_ops.stream_update_pred(
                    Xb, pred, feat, thr, leaf, val, dl,
                    max_depth=depth, learning_rate=cfg.learning_rate,
                    class_idx=class_idx,
                    missing_bin_value=missing_val, cat_vec=cat_vec_for(Xb),
                )
        elif kind == "gradstats":
            # Quantized streaming's scale-derivation pass: resident
            # pred/labels only — NO Xb operand, no chunk read.
            def f(pred, y, valid, *extra):
                rnd, blo, bhi, _, _ = parse_extra(extra)
                return stream_ops.stream_grad_stats(
                    pred, y, valid, loss=cfg.loss, n_classes=Cq,
                    axis_name=axis,
                    row_keep=row_keep_for(pred.shape[0], rnd, blo, bhi))
        elif kind == "roundstart":
            # `depth` carries the previous round's tree count (= C).
            n_prev = depth

            def f(Xb, pred, y, valid, *rest):
                extra = rest[5 * n_prev:]
                flat = rest[:5 * n_prev]
                rnd, blo, bhi, _, _ = parse_extra(extra)
                trees = tuple(
                    tuple(flat[5 * i: 5 * i + 5]) for i in range(n_prev))
                return stream_ops.stream_round_start(
                    Xb, pred, y, valid, trees,
                    max_depth=cfg.max_depth,
                    learning_rate=cfg.learning_rate,
                    n_bins=cfg.n_bins, loss=cfg.loss,
                    hist_impl=cfg.hist_impl,
                    input_dtype=self._input_dtype, axis_name=axis,
                    missing_bin_value=missing_val, cat_vec=cat_vec_for(Xb),
                    row_keep=row_keep_for(Xb.shape[0], rnd, blo, bhi),
                    comms_mode=comms_mode, comms_dtype=comms_dtype,
                    grad_stats_classes=Cq if quant else 0,
                )
        else:  # pragma: no cover
            raise ValueError(kind)

        if self.distributed:
            lay = self.layout
            # Under split_comms=reduce_scatter the streamed histogram
            # outputs come back F-sharded over the row axes (the wire
            # moved one slab per shard); the trainers slice the scatter
            # pad columns off after fetch.
            hist_spec = (lay.level_hist_scattered()
                         if self.stream_hist_comms == "reduce_scatter"
                         else lay.replicated())
            extra_specs = ()
            if takes_rnd:
                extra_specs = lay.specs("scalar", "scalar", "scalar")
            if takes_scales:
                extra_specs = extra_specs + lay.specs("scalar", "scalar")
            pred_name = "pred" if softmax else "pred1d"
            pred_spec = lay.spec(pred_name)
            if kind == "update":
                in_specs = lay.specs("data", pred_name) + \
                    lay.specs(*(["replicated"] * 5))
                out_specs = pred_spec
            elif kind == "gradstats":
                in_specs = lay.specs(pred_name, "y", "valid") + extra_specs
                out_specs = lay.replicated()
            elif kind == "roundstart":
                in_specs = lay.specs("data", pred_name, "y", "valid") + \
                    lay.specs(*(["replicated"] * (5 * depth))) + extra_specs
                # Quantized roundstart returns tiny replicated stats,
                # not a (possibly scattered) histogram.
                out_specs = (pred_spec,
                             lay.replicated() if quant else hist_spec)
            elif kind == "hist":
                in_specs = lay.specs("data", pred_name, "y", "valid") + \
                    lay.specs(*(["replicated"] * 4)) + extra_specs
                out_specs = hist_spec
            else:
                in_specs = lay.specs("data", pred_name, "y", "valid") + \
                    lay.specs(*(["replicated"] * 4)) + extra_specs
                out_specs = lay.replicated()
            f = mesh_lib.shard_map(f, mesh=self.mesh, in_specs=in_specs,
                              out_specs=out_specs)
        donate = (1,) if kind in ("update", "roundstart") else ()
        # Cost registration per streamed program: op = the stream kind,
        # phase = the fit_streaming phase its dispatches run under
        # (roundstart is the fused round-start inside the hist pass;
        # gradstats is the quantized path's scale pass under the same
        # phase; update applies finished trees to resident predictions —
        # the device loop's predict phase).
        stream_phase = {"hist": "hist", "leaf": "leaf",
                        "roundstart": "hist", "gradstats": "hist",
                        "update": "predict"}[kind]
        fn = costed(f"stream_{kind}", phase=stream_phase)(
            jax.jit(f, donate_argnums=donate))
        self._stream_cache[key] = fn
        return fn

    def _bag_args(self, rnd: int, row_start: int) -> tuple:
        """Traced scalars for the streamed bagging/rounding hashes:
        (round id, chunk global-row base as a uint32 pair — 10B-row
        bases overflow uint32). Empty when neither bagging nor
        quantized gradients need them (the compiled programs take no
        such operands then)."""
        if self.cfg.subsample >= 1.0 and not self._grad_quant:
            return ()
        return (np.int32(rnd),
                np.uint32(row_start & 0xFFFFFFFF),
                np.uint32(row_start >> 32))

    def _scale_args(self, quant_scales) -> tuple:
        """The round's host-reduced quantization scales as traced f32
        scalars (quantized streaming only — streaming.py derives them
        from the round's gradstats pass)."""
        if not self._grad_quant:
            return ()
        if quant_scales is None:
            raise ValueError(
                "grad_dtype != 'f32': the streamed hist/leaf ops need "
                "the round's (gscale, hscale) — derive them from "
                "stream_grad_stats first")
        gs, hs = quant_scales
        return (np.float32(gs), np.float32(hs))

    def stream_level_hist(self, data, pred, y: "LabelHandle", tree,
                          depth: int, class_idx: int = 0,
                          rnd: int = 0, row_start: int = 0,
                          build_left: bool = False, quant_scales=None):
        """Partial histogram [2^depth, F, B, 2] for one uploaded chunk
        (device handle; includes the cross-shard collective — psum, or
        the F/P reduce-scatter under split_comms=reduce_scatter, where
        the handle comes back F-sharded with zero pad columns the caller
        slices off). `tree` is the partial tree's host arrays (feature,
        threshold_bin, is_leaf, default_left). `rnd`/`row_start` feed
        the counter-based bagging mask when cfg.subsample < 1 and the
        quantized-gradient rounding key when cfg.grad_dtype != 'f32'
        (ignored otherwise). `build_left=True` is the streamed sibling-
        subtraction half-build: [2^(depth-1), F, B, 2] LEFT children
        keyed by parent slot (streaming._assemble_subtracted_level
        recovers the right children). `quant_scales` = the round's
        (gscale, hscale) under quantized gradients — the output is then
        the RAW int32 partial (dequantize after the level's last
        chunk)."""
        feat, thr, leaf, dl = tree
        return self._stream_fn("hist", depth, class_idx, left=build_left)(
            data, pred, y.y, y.valid, feat, thr, leaf, dl,
            *self._bag_args(rnd, row_start), *self._scale_args(quant_scales))

    def stream_leaf_gh(self, data, pred, y: "LabelHandle", tree,
                       max_depth: int, class_idx: int = 0,
                       rnd: int = 0, row_start: int = 0,
                       quant_scales=None):
        """Final-level (G, H) aggregates [2^max_depth, 2] for one chunk
        (int32 under quantized gradients — see stream_level_hist)."""
        feat, thr, leaf, dl = tree
        return self._stream_fn("leaf", max_depth, class_idx)(
            data, pred, y.y, y.valid, feat, thr, leaf, dl,
            *self._bag_args(rnd, row_start), *self._scale_args(quant_scales))

    def stream_grad_stats(self, pred, y: "LabelHandle",
                          rnd: int = 0, row_start: int = 0):
        """Per-class quantization stats [C, 4] (max|g|, sum|g|, max|h|,
        sum|h|) for one chunk's resident state — quantized streaming's
        scale-derivation pass (NO data operand: gradients need only
        pred/labels). streaming.py max/sum-reduces the chunks and
        derives the round's scales via ops/grad.quant_scale_np."""
        return self._stream_fn("gradstats", 0, 0)(
            pred, y.y, y.valid, *self._bag_args(rnd, row_start))

    def stream_update_pred(self, data, pred, tree_full, max_depth: int,
                           class_idx: int = 0):
        """pred updated by a finished tree (donated; device-resident).
        `tree_full` = (feature, threshold_bin, is_leaf, leaf_value,
        default_left)."""
        feat, thr, leaf, val, dl = tree_full
        return self._stream_fn("update", max_depth, class_idx)(
            data, pred, feat, thr, leaf, val, dl)

    def stream_round_start(self, data, pred, y: "LabelHandle",
                           prev_trees: list,
                           rnd: int = 0, row_start: int = 0):
        """Fused round-start pass for one chunk: apply the previous
        round's finished class trees to the resident pred, then return the
        NEXT round's class-0 depth-0 histogram — one dispatch, one data
        read (ops/stream.stream_round_start). Returns (new_pred, hist) —
        or (new_pred, [C, 4] quantization stats) under cfg.grad_dtype !=
        'f32' (the scales must exist before ANY of the round's builds, so
        the depth-0 histogram becomes a normal quantized pass).
        `rnd` is the NEW round (its bagging mask feeds the histogram/
        stats; the pred update applies to every row)."""
        flat = [a for t in prev_trees for a in t]
        return self._stream_fn("roundstart", len(prev_trees), 0)(
            data, pred, y.y, y.valid, *flat,
            *self._bag_args(rnd, row_start))

    # ------------------------------------------------------------------ #
    # inference (TreeEnsemble.predict → gather+compare, row-sharded)
    # ------------------------------------------------------------------ #

    # Host-side row chunk for batch scoring: bounds the device working set
    # (node state is [tree_chunk, rows_chunk] int32 plus traversal
    # temporaries) independently of how many rows the caller scores — the
    # 10M-row x 1000-tree config [BASELINE] OOM-kills the chip if scored in
    # one dispatch. 2M rows/chip/call keeps the peak well under 1 GB.
    PREDICT_ROW_CHUNK = 2_000_000
    # Device-resident CompiledEnsemble slots per backend instance: each
    # entry pins the model's pushed-down node tables on device (~MBs for a
    # 1000-tree model) across predict calls. Small because backend
    # instances are themselves cached and serving stacks typically score
    # a handful of live model versions.
    PREDICT_CACHE_MAX = 4

    def predict_raw(self, ens: TreeEnsemble, Xb: np.ndarray,
                    compiled=None) -> np.ndarray:
        """Score binned rows. `compiled` (a models/tree.CompiledEnsemble
        already built for THIS ens) skips the per-call content hash —
        the serving tier holds one per model version, so a micro-batch
        request pays upload + dispatch only (docs/SERVING.md)."""
        R = Xb.shape[0]
        chunk = self.PREDICT_ROW_CHUNK * max(1, self.row_shards)
        fn, ens_dev = self._predict_fn(ens, compiled=compiled)
        if isinstance(Xb, jax.Array) and (R <= chunk or self.distributed):
            # Device-resident input is only special-cased on the
            # single-chip big-batch loop below (where it skips the bulk
            # upload, isolating device compute for benchmarking); the
            # other paths pad/shard on host.
            Xb = np.asarray(Xb)
        if R > chunk:
            if self.distributed:
                # Per-chunk host→device upload (each chunk must be laid out
                # over the mesh); ensemble arrays + shard_map fn hoisted.
                outs = [
                    fn(*ens_dev, self._put_rows(Xb[i:i + chunk],
                                                extra_dims=1)
                       )[:min(chunk, R - i)]       # drop per-chunk pad rows
                    for i in range(0, R, chunk)
                ]
            else:
                # Single chip: upload the whole batch ONCE (uint8 — 4x less
                # host→device traffic than int32, which dominates wallclock
                # on a remote-attached chip), slice chunks on device, and
                # OVERLAP each chunk's device→host score fetch with the
                # later chunks' compute: async dispatch keeps the device
                # busy while finished chunks stream back, so the link and
                # the chip pay their costs concurrently instead of
                # back-to-back. Measured on the 10M x 1000 resident
                # config, the serial fetch-at-the-end was 65% of
                # wallclock (experiments/predict_phases.py; docs/PERF.md
                # round-5) — overlapping it is the predict path's one
                # first-order win.
                with phase_span("predict:upload"):
                    Xd = (Xb if isinstance(Xb, jax.Array)
                          else jax.device_put(np.ascontiguousarray(Xb)))
                outs = [
                    fn(*ens_dev, Xd[i:i + chunk]) for i in range(0, R, chunk)
                ]
                for o in outs:          # start all D2H copies in flight
                    o.copy_to_host_async()
                # Not a per-iter sync: the copies are already in flight
                # (copy_to_host_async above); asarray only materialises.
                return np.concatenate(
                    [np.asarray(o)  # ddtlint: disable=host-sync
                     for o in outs])[:R]
            return np.asarray(jnp.concatenate(outs))[:R]
        with phase_span("predict:upload"):
            Xc = self._put_rows(Xb, extra_dims=1)   # uint8; ops widen it
        out = fn(*ens_dev, Xc)
        return np.asarray(out)[:R]

    @functools.cached_property
    def _predict_cache(self) -> dict:
        # token -> (fn, device arrays); insertion order = LRU order.
        return {}

    @functools.cached_property
    def _predict_impl_resolved(self) -> dict:
        # token -> the tier _predict_fn actually compiled ("lut4" |
        # "lut" | "f32") — pruned with _predict_cache.
        return {}

    def resolved_predict_impl(self, token: str) -> str:
        """The scoring tier that ACTUALLY serves model `token` after
        the fallback ladder ("lut4" | "lut" | "f32"; "f32" when the
        model never scored here). The serving tier stamps this into
        /healthz and serve_latency so a silent VMEM-guard fallback is
        an observable fact, not a debug-log line."""
        return self._predict_impl_resolved.get(token, "f32")

    @property
    def _use_pallas(self) -> "bool | None":
        """cfg.predict_impl as predict_raw_effective's use_pallas value
        (None = auto-dispatch; ops/predict.resolve_use_pallas). "lut" /
        "lut4" resolve here to the f32 auto value — it is the FALLBACK
        the quantized dispatch in _predict_fn degrades to when the LUT
        kernels' VMEM budgets refuse the shape."""
        return {"auto": None, "pallas": True, "onehot": False,
                "lut": None, "lut4": None}[self.cfg.predict_impl]

    def _lut_fn(self, ce, n_features: int, tier: str = "lut"):
        """(jitted LUT scoring fn, device operand tuple) for one model
        version at quantization `tier` ("lut" = int8, "lut4" = int4
        bit-packed), or None when the shape exceeds that kernel's
        budget (predict_lut_fits / predict_lut4_fits — the
        pallas-vmem-guard contract; the caller walks the fallback
        ladder). Tables quantize on host once per model version; the
        error bound rides on the tables (docs/SERVING.md "Quantized
        serving")."""
        from ddt_tpu.ops import predict_lut

        # ce.quantize() memoizes: when the serving tier already
        # quantized this model version at publish (for its error-bound
        # reporting), this is a dict hit, not a second O(model) pass.
        if tier == "lut4":
            tables = ce.quantize(leaf_dtype="int4")
            packed = tables.pack_int4()
            if not predict_lut.predict_lut4_fits(
                    tables.n_trees_padded, tables.tree_chunk,
                    tables.max_depth, n_features, tables.n_classes_out,
                    thr_packed=packed.thr_packed):
                return None
            host_ops = packed.ops
            static = packed.static_kwargs()
            core = predict_lut.predict_effective_lut4_ops
        else:
            tables = ce.quantize()
            if not predict_lut.predict_lut_fits(
                    tables.n_trees_padded, tables.tree_chunk,
                    tables.max_depth, n_features, tables.n_classes_out):
                return None
            host_ops = predict_lut.lut_device_operands(tables)
            static = dict(
                max_depth=tables.max_depth,
                learning_rate=tables.learning_rate,
                base=tables.base_score, n_classes=tables.n_classes_out,
                tree_chunk=tables.tree_chunk,
                n_trees_padded=tables.n_trees_padded,
                missing_bin_value=tables.missing_bin_value,
                use_missing=tables.eff_dl is not None,
                use_cat=tables.eff_cat is not None,
                use_scale=tables.leaf_scale is not None,
            )
            core = predict_lut.predict_effective_lut_ops
        with phase_span("predict:upload"):
            dev_ops = tuple(self._put(a, self._named(
                self.layout.replicated())) for a in host_ops)

        def lut0(*args):
            *ops, Xc = args
            return core(tuple(ops), Xc, **static)

        return jax.jit(lut0), dev_ops

    def _predict_fn(self, ens: TreeEnsemble, compiled=None):
        """(jittable scoring fn, device-resident compiled-ensemble arrays).

        The pushed-down/padded scoring layout (models/tree.
        CompiledEnsemble) and its device copies are cached per model
        version: the cache key is a content digest of the node arrays, so
        in-place trainer mutation can never serve stale trees, and a hit
        skips pushdown AND re-upload entirely (the resident-vs-total
        bench gap showed ~27% of predict wall time there). Hits feed the
        run log's `compiled_ensemble_cache_hits` counter.

        `compiled` (a CompiledEnsemble snapshot the caller already
        built) keys the cache on its `token` directly — no per-call
        full-array hash — and seeds a miss without rebuilding the
        layout. The serving tier's request path rides this.

        With cfg.predict_impl="lut" the cached entry is the int8
        quantized path (ops/predict_lut.py): tables quantize + upload
        once per model version; shapes past the LUT kernel's VMEM
        budget fall back to the f32 path (predict_lut_fits). "lut4" is
        the bit-packed int4 tier one rung up, degrading int4 -> int8 ->
        f32 down the same guards; whatever rung actually serves is
        recorded per token (`resolved_predict_impl`) so the serving
        tier can stamp the TRUE tier into /healthz + serve_latency —
        a silent guard trip must be visible in telemetry, not only in
        debug logs."""
        token = compiled.token if compiled is not None \
            else ens.cache_token()
        hit = self._predict_cache.pop(token, None)
        if hit is not None:
            self._predict_cache[token] = hit     # most-recently-used
            tele_counters.record_compiled_ensemble_hit()
            return hit
        ce = compiled if compiled is not None else ens.compile(
            tree_chunk=64)
        impl_req = self.cfg.predict_impl
        lut = None
        resolved = "f32"
        if impl_req in ("lut", "lut4"):
            if impl_req == "lut4":
                lut = self._lut_fn(ce, ens.n_features, tier="lut4")
                if lut is not None:
                    resolved = "lut4"
                else:
                    log.warning(
                        "predict_impl='lut4': shape exceeds the int4 "
                        "kernel's VMEM budget; falling back to the int8 "
                        "LUT tier")
            if lut is None:
                lut = self._lut_fn(ce, ens.n_features, tier="lut")
                if lut is not None:
                    resolved = "lut"
        if lut is not None:
            fn0, ens_dev = lut
        else:
            if impl_req in ("lut", "lut4"):
                log.warning(
                    "predict_impl=%r: shape exceeds the LUT kernel's "
                    "VMEM budget; falling back to the f32 path",
                    impl_req)
            with phase_span("predict:upload"):
                ens_dev = tuple(self._put(a, self._named(
                    self.layout.replicated())) for a in ce.arrays())
            use_missing = ce.eff_dl is not None
            use_cat = ce.eff_cat is not None
            use_pallas = self._use_pallas

            def fn0(ef, et, bv, coh, *rest):
                *opt, Xc = rest
                opt = list(opt)
                dl = opt.pop(0) if use_missing else None
                cn = opt.pop(0) if use_cat else None
                return predict_ops.predict_raw_effective(
                    ef, et, bv, coh, Xc,
                    max_depth=ce.max_depth,
                    learning_rate=ce.learning_rate,
                    base=ce.base_score,
                    n_classes=ce.n_classes_out,
                    tree_chunk=ce.tree_chunk,
                    eff_dl=dl,
                    missing_bin_value=ce.missing_bin_value,
                    eff_cat=cn,
                    use_pallas=use_pallas,
                )

        fn = fn0
        n_rep = len(ens_dev)
        if self.distributed:
            # Row-sharded scoring is embarrassingly parallel: trees are
            # replicated, each shard traverses its own rows, no collectives
            # (SURVEY.md §3 predict stack). shard_map makes the row-gather
            # sharding explicit — XLA cannot infer it through the
            # take_along_axis traversal.
            lay = self.layout
            C = ce.n_classes_out
            out_spec = lay.row_vector() if C == 1 else lay.row_matrix()
            fn = mesh_lib.shard_map(
                fn,
                mesh=self.mesh,
                in_specs=(lay.replicated(),) * n_rep
                + (lay.row_matrix(),),     # rows sharded, F replicated:
                # scoring never feature-shards (trees are replicated)
                out_specs=out_spec,
                # predict_raw's scan carry starts replicated (zeros) and
                # becomes row-varying after the first accumulation; the
                # static VMA checker rejects that even though it is sound
                # here (no collectives anywhere in the traversal).
                check_vma=False,
            )
        self._predict_cache[token] = (fn, ens_dev)
        self._predict_impl_resolved[token] = resolved
        while len(self._predict_cache) > self.PREDICT_CACHE_MAX:
            gone = next(iter(self._predict_cache))
            self._predict_cache.pop(gone)
            self._predict_impl_resolved.pop(gone, None)
        return fn, ens_dev
