"""CPUDevice: the NumPy reference backend behind the DeviceBackend boundary.

The reference ships a CPU reference implementation of (at least) the histogram
kernel and compares device throughput against it [BASELINE: "≥5× the repo's
CPU-reference histogram throughput"]. This backend wraps the M0 oracle trainer
(reference/numpy_trainer.py) behind the L4 interface so:

- backend-parity tests can drive CPU vs TPU through the identical call
  surface (SURVEY.md §4 "Backend parity"), and
- the bench harness measures the baseline M-rows/sec on the same contract it
  measures the TPU path.

When the native C++ kernel (ddt_tpu/native) is built, `build_histograms` uses
it (that's the honest CPU baseline — a compiled kernel, like the reference's);
otherwise the NumPy np.add.at path runs. Both match the oracle bit-for-bit.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import numpy as np

from ddt_tpu.backends.base import DeviceBackend, HostTree
from ddt_tpu.config import TrainConfig
from ddt_tpu.models.tree import TreeEnsemble
from ddt_tpu.reference import numpy_trainer as ref


class CPULabels(NamedTuple):
    """Labels + optional instance weights — the opaque `y` handle (per-
    dataset state lives in handles, not on the cached backend instance;
    mirrors TPUDevice.LabelHandle)."""

    y: np.ndarray
    w: np.ndarray | None


class CPUDevice(DeviceBackend):
    """NumPy (optionally native-C++-accelerated) reference backend."""

    name = "cpu"

    def __init__(self, cfg: TrainConfig, use_native: bool | None = None):
        super().__init__(cfg)
        if cfg.grad_dtype != "f32":
            # This backend defines the f32 ground truth the quantized
            # path's agreement contracts are measured against — running
            # it quantized would be circular (and the numpy oracle has
            # no integer histogram path). Refuse loudly.
            raise NotImplementedError(
                f"grad_dtype={cfg.grad_dtype!r} is not supported on the "
                "CPU oracle backend; use backend='tpu' (runs on CPU XLA "
                "too)")
        self._native = None          # histogram kernel
        self._native_split = None    # split-gain kernel (plain contract)
        self._native_split_full = None  # full contract (mask/missing/cat)
        self._native_traverse = None  # batch predict traversal
        if use_native is not False:
            try:
                from ddt_tpu.native import (
                    histogram_native, split_gain_full_native,
                    split_gain_native, traverse_native)

                self._native = histogram_native
                self._native_split = split_gain_native
                self._native_split_full = split_gain_full_native
                self._native_traverse = traverse_native
            except Exception:
                if use_native:  # explicitly requested → surface the failure
                    raise

    # ------------------------------------------------------------------ #

    def upload(self, Xb: np.ndarray) -> np.ndarray:
        Xb = np.ascontiguousarray(Xb)
        if Xb.dtype != np.uint8:
            raise TypeError(f"binned data must be uint8, got {Xb.dtype}")
        return Xb

    def upload_labels(self, y: np.ndarray,
                      sample_weight: np.ndarray | None = None
                      ) -> "CPULabels":
        return CPULabels(
            np.asarray(y),
            None if sample_weight is None
            else np.asarray(sample_weight, np.float32),
        )

    # ------------------------------------------------------------------ #

    def build_histograms(self, data, g, h, node_index, n_nodes) -> np.ndarray:
        if self._native is not None:
            return self._native(
                data, g, h, node_index, n_nodes, self.cfg.n_bins
            )
        return ref.build_histograms(
            data, g, h, node_index, n_nodes, self.cfg.n_bins
        )

    def best_splits(self, hist):
        # Granular L4 surface: 3-tuple contract (missing-direction handling
        # lives in the grow path, which calls ref.best_splits directly).
        if self._native_split is not None:
            return self._native_split(
                hist, self.cfg.reg_lambda, self.cfg.min_child_weight
            )
        return ref.best_splits(
            hist, self.cfg.reg_lambda, self.cfg.min_child_weight
        )[:3]

    # ------------------------------------------------------------------ #

    def init_pred(self, y, base: float):
        R = y.y.shape[0]
        if self.cfg.loss == "softmax":
            return np.zeros((R, self.cfg.n_classes), np.float32)
        return np.full(R, base, np.float32)

    def load_pred(self, raw: np.ndarray):
        return np.array(raw, np.float32)

    def grad_hess(self, pred, y):
        g, h = ref.grad_hess(pred, y.y, self.cfg.loss)
        if y.w is not None:
            w = y.w[:, None] if g.ndim == 2 else y.w
            g = g * w
            h = h * w
        return g, h

    def grow_tree(self, data, g, h,
                  feature_mask=None, tree_id: int = 0) -> tuple[HostTree, Any]:
        # tree_id is the quantized-gradient rounding key — unused here:
        # this backend IS the f32 oracle (cfg.grad_dtype != "f32" is
        # refused at construction).
        split_full = None
        if self._native_split_full is not None:
            def split_full(hist, fm, missing, cm):
                return self._native_split_full(
                    hist, self.cfg.reg_lambda, self.cfg.min_child_weight,
                    feature_mask=fm, missing_bin=missing, cat_mask=cm)
        tree = ref.grow_tree(
            data, g, h, self.cfg,
            hist_fn=self.build_histograms,
            feature_mask=feature_mask, split_full_fn=split_full,
        )
        delta = (
            self.cfg.learning_rate * tree["leaf_value"][tree["leaf_of_row"]]
        ).astype(np.float32)
        host = HostTree(
            feature=tree["feature"],
            threshold_bin=tree["threshold_bin"],
            is_leaf=tree["is_leaf"],
            leaf_value=tree["leaf_value"],
            split_gain=tree["split_gain"],
            default_left=tree["default_left"],
        )
        return host, delta

    def apply_delta(self, pred, delta, class_idx: int):
        if pred.ndim == 2:
            pred[:, class_idx] += delta
        else:
            pred += delta
        return pred

    def loss_value(self, pred, yh) -> float:
        loss = self.cfg.loss
        y = yh.y
        w = yh.w

        def wmean(per_row):
            if w is None:
                return float(np.mean(per_row))
            return float(np.average(per_row, weights=w))

        if loss == "logloss":
            p = 1.0 / (1.0 + np.exp(-pred.astype(np.float64)))
            p = np.clip(p, 1e-12, 1 - 1e-12)
            return wmean(-(y * np.log(p) + (1 - y) * np.log(1 - p)))
        if loss == "mse":
            return wmean((pred - y) ** 2)
        z = pred - pred.max(axis=1, keepdims=True)
        logp = z - np.log(np.exp(z).sum(axis=1, keepdims=True))
        return wmean(-logp[np.arange(y.shape[0]), y.astype(np.int64)])

    # ------------------------------------------------------------------ #

    def predict_raw(self, ens: TreeEnsemble, Xb: np.ndarray,
                    compiled=None) -> np.ndarray:
        # `compiled` is accepted for interface parity (the serving tier
        # passes it unconditionally); the CPU traversal reads the
        # ensemble heap directly, so there is nothing to seed.
        if self._native_traverse is None:
            return ens.predict_raw(Xb, binned=True)
        # C++ batch traversal (the CPU twin of the device gather+compare
        # path); routing-flag derivation lives in ONE place
        # (TreeEnsemble._traverse_native), aggregation shared with
        # TreeEnsemble.predict_raw.
        leaf = ens._traverse_native(Xb)                         # [T, R]
        if leaf is None:                    # library unavailable after all
            return ens.predict_raw(Xb, binned=True)
        return ens.aggregate_leaves(leaf)
