"""Run-log -> Chrome trace-event JSON (loadable in ui.perfetto.dev).

The run log is an event stream with wall-clock stamps and durations;
this module re-expresses it in the trace-event format Perfetto (and
chrome://tracing, and TensorBoard's trace viewer) load natively, so a
training run's rounds, phases, and per-partition lanes become a
scrollable timeline without a profiler capture. Wholly host-side
post-processing — no jax, no device, works on a log copied off a pod.

Layout (one trace "process" per HOST, as stamped by the cross-host
merge — a single-host log is pid 0):

- tid 0, "rounds": one complete ("X") slice per `round` event, spanning
  the recorded ms_per_round and ENDING at the event's emit time (the
  round record is written at round end). Early-stop / fault / run_end
  land here as instant ("i") events.
- tid 1+d, "partition d": per-device lanes from `partition_phases`.
  The run log stores per-phase DURATIONS plus the event's emit time,
  not per-phase start stamps (the collection is one probe per phase,
  not a tracer), so each device's phases are laid out back-to-back
  ending at the emit time — true durations, synthesized offsets,
  documented here and in docs/OBSERVABILITY.md. Slice args carry the
  device id and the round's hist_allreduce payload estimate.
- `phase_timings` / `counters` become instant events on the rounds
  lane with their full payload in args (aggregates have no extent);
  `train_heartbeat` rides the same lane (it summarizes the adjacent
  round slices).
- tid 999, "events": the catch-all lane — every run-log kind without a
  dedicated mapping (drift, artifact, future schema additions) renders
  here as an instant with its full payload, never a silent drop.

Contract (tests/test_flight_recorder.py validates it field by field):
every record has string `name`, `ph` in {X, i, M}, numeric `ts` >= 0
(microseconds), integer `pid`/`tid`; every X record a numeric
`dur` >= 0; the top level is {"traceEvents": [...], "displayTimeUnit":
"ms"} — the JSON object form, which Perfetto's trace-event importer
accepts.
"""

from __future__ import annotations

import json

#: one metadata slot per aggregate event type on the rounds lane
#: (cost_analysis since schema v3: the observatory's per-op records ride
#: the export as instants so a trace viewer can read the cost model next
#: to the lanes; train_heartbeat since ISSUE 20: the checkpoint-cadence
#: progress pulse belongs next to the round slices it summarizes).
_INSTANT_EVENTS = ("early_stop", "fault", "run_end", "phase_timings",
                   "serve_latency",
                   "counters", "partition_skew", "cost_analysis",
                   "train_heartbeat")

#: the catch-all lane (ISSUE 20): run-log kinds with no dedicated
#: mapping — serve-era events like drift/artifact, and whatever schema
#: additions come next — used to be DROPPED silently, so the trace
#: looked complete while hiding whole subsystems. They now render as
#: instants on one "events" lane. The tid is fixed and high so it never
#: collides with the per-device partition lanes (tid 1+d).
_MISC_TID = 999


def _payload(rec: dict) -> dict:
    return {k: v for k, v in rec.items()
            if k not in ("event", "schema", "t", "seq", "host")}


def to_trace_events(events: list[dict]) -> dict:
    """Convert a (possibly merged) run-log event list into the
    trace-event JSON object. Timestamps are microseconds relative to
    the earliest event."""
    if not events:
        raise ValueError("no run-log events to export")
    base = min(e["t"] for e in events)

    def ts(t: float) -> float:
        return max(0.0, (t - base) * 1e6)

    out: list[dict] = []
    hosts_done: set[int] = set()
    lanes_done: set[tuple[int, int]] = set()

    def lane(pid: int, tid: int, name: str) -> None:
        if (pid, tid) in lanes_done:
            return
        lanes_done.add((pid, tid))
        out.append({"name": "thread_name", "ph": "M", "ts": 0.0,
                    "pid": pid, "tid": tid, "args": {"name": name}})

    for e in events:
        pid = int(e.get("host", 0))
        ev = e["event"]
        if ev == "run_manifest":
            if pid not in hosts_done:
                hosts_done.add(pid)
                m = _payload(e)
                label = (f"ddt host {pid} "
                         f"({m.get('trainer', '?')}/{m.get('backend', '?')})")
                out.append({"name": "process_name", "ph": "M", "ts": 0.0,
                            "pid": pid, "tid": 0, "args": {"name": label}})
            lane(pid, 0, "rounds")
            continue
        if ev == "round":
            lane(pid, 0, "rounds")
            dur_us = float(e["ms_per_round"]) * 1e3
            out.append({
                "name": f"round {e['round']}", "ph": "X",
                "ts": max(0.0, ts(e["t"]) - dur_us), "dur": dur_us,
                "pid": pid, "tid": 0, "args": _payload(e),
            })
            continue
        if ev == "partition_phases":
            for part in e["partitions"]:
                dev = int(part["device"])
                tid = 1 + dev
                lane(pid, tid, f"partition {dev}")
                phases = part.get("phases", {})
                total_us = sum(phases.values()) * 1e3
                cursor = max(0.0, ts(e["t"]) - total_us)
                for name, ms in phases.items():
                    dur_us = float(ms) * 1e3
                    out.append({
                        "name": f"ddt:{name}", "ph": "X",
                        "ts": cursor, "dur": dur_us,
                        "pid": pid, "tid": tid,
                        "args": {
                            "device": dev, "round": e["round"],
                            "hist_allreduce_bytes":
                                part.get("hist_allreduce_bytes"),
                        },
                    })
                    cursor += dur_us
            continue
        if ev in _INSTANT_EVENTS:
            lane(pid, 0, "rounds")
            out.append({"name": ev, "ph": "i", "ts": ts(e["t"]), "s": "t",
                        "pid": pid, "tid": 0, "args": _payload(e)})
            continue
        # Unmapped kinds (drift, artifact, future schema additions):
        # instants on the catch-all lane, never a silent drop.
        lane(pid, _MISC_TID, "events")
        out.append({"name": ev, "ph": "i", "ts": ts(e["t"]), "s": "t",
                    "pid": pid, "tid": _MISC_TID, "args": _payload(e)})
    return {"traceEvents": out, "displayTimeUnit": "ms"}


def write_trace(events: list[dict], path: str) -> int:
    """Serialize to_trace_events(events) to `path`; returns the trace
    record count (the CLI's summary line)."""
    trace = to_trace_events(events)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(trace, f)
    return len(trace["traceEvents"])
