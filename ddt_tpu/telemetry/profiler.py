"""Programmatic jax.profiler capture windows around selected rounds.

`--trace-dir` wraps the WHOLE fit in one jax.profiler trace — fine for a
3-round smoke, useless for a 1000-round run (multi-GB traces, warmup
compiles burying the steady state). This module captures a device trace
around a selected round RANGE instead:

    train --xprof-dir /tmp/prof --xprof-rounds 5:8 --run-log run.jsonl

starts the profiler at round 5's first dispatch and stops it after round
8 — warmup (round 1's compiles) skipped by choosing the window. The
trace lands under `<xprof-dir>/run_<run_id>/`, and the run manifest is
stamped with `xprof_dir` + `xprof_rounds`, so a flight-recorder lane and
an xprof session cross-reference by `run_id` in both directions: the
straggler table names the round, the manifest names the trace that holds
that round's device timeline (docs/OBSERVABILITY.md has the worked
example).

The fused Driver path dispatches whole BLOCKS of rounds; the window caps
block boundaries (`block_cap`) exactly like the checkpoint cadence does,
so capture starts and stops on true round edges there too. With no
window attached (`None`), the trainers skip every hook — the
zero-overhead disabled-telemetry contract extends here (no profiler
state, no file IO).
"""

from __future__ import annotations

import logging
import os

log = logging.getLogger("ddt_tpu.telemetry.profiler")


def parse_rounds(spec: str) -> tuple[int, int]:
    """"5:8" -> (5, 8), 1-based inclusive. A single "5" means 5:5."""
    s = str(spec).strip()
    try:
        if ":" in s:
            lo_s, hi_s = s.split(":", 1)
            lo, hi = int(lo_s), int(hi_s)
        else:
            lo = hi = int(s)
    except ValueError:
        raise ValueError(
            f"--xprof-rounds must be LO:HI (1-based, inclusive) or a "
            f"single round, got {spec!r}") from None
    if lo < 1 or hi < lo:
        raise ValueError(
            f"--xprof-rounds window {spec!r} is empty or starts before "
            "round 1")
    return lo, hi


class CaptureWindow:
    """One profiler capture around rounds [lo, hi] (1-based, inclusive).

    Protocol (the trainers drive it; every hook is a no-op once done):
    - bind(run_id): fix the trace directory to <dir>/run_<run_id> —
      called at manifest time so the path and the log cross-reference.
    - round_start(rnd0) / round_end(rnd0): 0-based round boundary hooks
      (granular Driver + both streaming loops).
    - block_cap(rnd0, K): cap a fused block's round count so block
      boundaries align with the window edges.
    - close(): stop a still-open capture (run ended inside the window,
      early stop, exception) — the trainers call it in `finally`.
    """

    def __init__(self, out_dir: str, rounds: str = "2:3"):
        self.out_dir = str(out_dir)
        self.lo, self.hi = parse_rounds(rounds)
        self.trace_dir = self.out_dir      # until bind() names the run
        self._started = False
        self._done = False

    def bind(self, run_id: str | None) -> None:
        if run_id:
            self.trace_dir = os.path.join(self.out_dir, f"run_{run_id}")

    def manifest_fields(self) -> dict:
        """The run-manifest extras (the cross-reference contract:
        scripts/profile_smoke.py asserts exactly these)."""
        return {"xprof_dir": self.trace_dir,
                "xprof_rounds": [self.lo, self.hi]}

    @property
    def active(self) -> bool:
        return self._started and not self._done

    def round_start(self, rnd0: int) -> None:
        """Start the capture when 0-based round `rnd0` enters the
        window (>= lo covers resume-into-window starts; a resume PAST
        the window retires it — capturing later rounds would contradict
        the xprof_rounds the manifest advertises)."""
        if rnd0 + 1 > self.hi:
            self.close()                 # also stops a straggling capture
            return
        if self._started or self._done or rnd0 + 1 < self.lo:
            return
        try:
            import jax
        except ImportError:
            self._done = True
            return
        os.makedirs(self.trace_dir, exist_ok=True)
        try:
            jax.profiler.start_trace(self.trace_dir)
        except RuntimeError as e:        # another capture already running
            log.warning("xprof capture not started: %s", e)
            self._done = True
            return
        self._started = True
        log.info("xprof capture started at round %d -> %s",
                 rnd0 + 1, self.trace_dir)

    def round_end(self, rnd0: int) -> None:
        if self.active and rnd0 + 1 >= self.hi:
            self._stop()

    def block_cap(self, rnd0: int, K: int) -> int:
        """Largest K' <= K such that the block [rnd0, rnd0+K') does not
        straddle a window edge (start edge lo-1, stop edge hi — both
        0-based block-boundary positions)."""
        for b in (self.lo - 1, self.hi):
            if rnd0 < b < rnd0 + K:
                K = b - rnd0
        return max(1, K)

    def close(self) -> None:
        if self.active:
            self._stop()
        self._done = True

    def _stop(self) -> None:
        self._done = True
        self._started = False
        import jax

        try:
            jax.profiler.stop_trace()
        except RuntimeError as e:        # lost the race with another stop
            log.warning("xprof capture stop failed: %s", e)
            return
        log.info("xprof capture written: %s", self.trace_dir)
