"""Structured telemetry (SURVEY.md §5 "Metrics/logging/observability").

The reference system's operational story is per-phase visibility into the
hist / allreduce / gain / predict pipeline. This package is that story for
the reproduction, in three always-available layers (zero overhead when no
run log is attached — the hot loops never sync, never touch a file, and
pay at most a handful of host integer adds):

- events   — schema-versioned JSONL run logs (`RunLog`): run manifest,
             per-round records, per-phase timings, early-stop decisions,
             fault/recovery events, device counters. An in-memory ring
             buffer mirrors the file so tests (and callers without a
             filesystem) can read events back without parsing JSONL.
- counters — process-wide device counters: jit recompiles (via a
             jax.monitoring listener on the backend-compile duration
             event), host↔device transfer bytes, estimated collective
             payload bytes, device-memory high-water marks.
- annotations — jax.profiler.TraceAnnotation / jax.named_scope wrappers
             that give host PhaseTimer phases and device Perfetto
             timelines the SAME `ddt:<phase>` names, so a trace captured
             with --trace-dir aligns with the run log's phase breakdown.

Since the distributed flight recorder (schema v2) two more consumers sit
on the same stream:

- merge    — joins N per-host JSONL logs of one pod run into a single
             host-0-clock timeline (run_id join key, manifest-estimated
             clock offsets).
- perfetto — converts a (possibly merged) log into Chrome trace-event
             JSON loadable in ui.perfetto.dev: round slices, per-device
             partition lanes, instant markers.

And mesh runs with a run log attached additionally record per-partition
phase completion times (`partition_phases` per round, a `partition_skew`
straggler reduction at run end — events.PartitionRecorder).

Since the device-truth cost observatory (schema v3) three more:

- costmodel — XLA compiled-executable cost/memory analysis captured at
             each jit entry point's first compile (telemetry runs only),
             emitted as `cost_analysis` events and joined against phase
             wall-times into the report's roofline table with a bound-by
             verdict (compute / HBM / recompile / host).
- profiler — programmatic jax.profiler capture windows around a selected
             round range (`train --xprof-dir --xprof-rounds`), cross-
             referenced to the run log through the manifest's
             xprof_dir/xprof_rounds extras and the run_id-named trace dir.
- diffing  — `cli report diff A B`: per-phase / per-counter deltas with
             benchwatch-band excursion flags ("gain +34%, jit_compiles
             12→48, hist bytes-accessed x2.1").

`report` renders a run summary from a JSONL log (`python -m ddt_tpu.cli
report --log run.jsonl`, repeat --log to merge hosts); `trace` exports
the Perfetto JSON; docs/OBSERVABILITY.md documents the schema and
workflow.
"""

from ddt_tpu.telemetry.events import (  # noqa: F401
    EVENT_FIELDS, SCHEMA_VERSION, PartitionRecorder, RoundRecorder,
    RunLog, derive_run_id, partition_skew_summary, validate_event)
from ddt_tpu.telemetry import counters  # noqa: F401
from ddt_tpu.telemetry.annotations import phase_span  # noqa: F401
