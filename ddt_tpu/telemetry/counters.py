"""Process-wide device counters behind the run log's `counters` event.

Four counters, each chosen because the literature says it is the silent
TPU perf killer the host wallclock alone cannot see:

- `jit_compiles` — every XLA backend compile, counted by a
  jax.monitoring listener on the `/jax/core/compile/
  backend_compile_duration` event (recompiles from shape churn are the
  classic hidden cost: arXiv:1810.09868). The listener installs lazily
  (install_jax_listener) so a process that never attaches telemetry
  never registers it; once installed it is a single host integer add
  per COMPILE — nothing per dispatch. The SAME listener accumulates
  `jit_compile_seconds` (cumulative backend-compile wall time) so the
  run log carries recompile COST, not just count — the roofline
  verdict's "recompile" leg reads it (telemetry/costmodel.py).
- `h2d_bytes` / `d2h_bytes` — host↔device transfer bytes recorded at
  the backends' upload/fetch funnels (TPUDevice._put / fetch_tree and
  the fused tree-fetch). Approximate by design: scalar metric
  readbacks (~bytes) are not counted, the row-matrix and tree traffic
  that actually loads the PCIe/tunnel link is.
- `collective_bytes_est` — ESTIMATED allreduce payload per round
  (hist_allreduce_bytes), recorded by the Driver only on distributed
  meshes. An estimate because the psum lives inside a fused device
  program where the host cannot observe the wire; the histogram shapes
  are static per config, so the estimate is exact up to XLA's own
  reduction scheduling.

All counters are monotonic process-wide integers; consumers take a
snapshot() at run start and publish delta() at run end, so concurrent
runs in one process each see their own traffic plus any overlap —
documented, not hidden (docs/OBSERVABILITY.md).

`device_peak_bytes()` reads the accelerator's high-water mark from
device.memory_stats() where the platform exposes one (TPU/GPU; CPU XLA
returns None).
"""

from __future__ import annotations

import contextlib

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

# Monotonic process-wide counters (plain ints: the GIL makes += atomic
# enough for counting; these feed reports, not invariants).
_c = {
    "jit_compiles": 0,
    # Cumulative backend-compile WALL TIME (seconds, float) from the same
    # jax.monitoring listener: the recompile COUNT says the silent killer
    # is present, the seconds say what it costs — a run whose compile
    # seconds rival a phase's wall time is recompile-bound no matter how
    # healthy its kernels are (the roofline verdict in
    # telemetry/costmodel.py reads exactly this).
    "jit_compile_seconds": 0.0,
    "h2d_bytes": 0,
    "d2h_bytes": 0,
    "collective_bytes_est": 0,
    # Device-resident CompiledEnsemble cache hits (TPUDevice._predict_fn):
    # a hit skips the per-call pushdown + ensemble re-upload (~27% of
    # predict wall time in the resident-vs-total bench gap). Zero hits
    # across a many-call scoring run means the cache is thrashing (more
    # live models than the LRU holds) or the model is being rebuilt
    # between calls.
    "compiled_ensemble_cache_hits": 0,
    # Robustness substrate (docs/ROBUSTNESS.md): failed attempts the
    # retry seams recovered from (utils/retry.py — each also emits a
    # `fault` event with the seam) and histogram OOM degradations (the
    # backend stepped down the hist-impl ladder after RESOURCE_EXHAUSTED
    # — backends/tpu.py). Nonzero values in a "healthy" run's counters
    # line are the signal the infrastructure is limping.
    "fault_retries": 0,
    "hist_oom_degrades": 0,
    # Serving tier (ddt_tpu/serve/, schema v4): requests completed,
    # micro-batches dispatched, and zero-downtime hot swaps. The ratio
    # requests/batches is the process-lifetime mean coalesce width — a
    # serving process whose ratio sits at ~1.0 under load has lost
    # admission batching (per-window quantiles live in the
    # serve_latency events, not here: quantiles are not monotonic).
    "serve_requests": 0,
    "serve_batches": 0,
    "serve_hot_swaps": 0,
    # Requests the express lane dispatched synchronously (ISSUE 12) —
    # serve_express/serve_requests is the lifetime share of traffic
    # that skipped the admission window (== idle-regime traffic).
    "serve_express": 0,
    # Fleet tenancy (ddt_tpu/serve/fleet.py, ISSUE 15): LRU demotions
    # of cold models to their AOT artifacts, and reloads of previously
    # evicted models on their next request. A fleet whose reloads track
    # its evictions 1:1 is thrashing (max_resident too small for the
    # live working set); per-model attribution lives in the
    # fault(kind=fleet_eviction/fleet_reload) events, not here.
    "fleet_evictions": 0,
    "fleet_reloads": 0,
    # SLO burn-rate breach transitions (serve/fleet.py, ISSUE 17): the
    # number of times a model's rolling burn rate crossed INTO breach
    # (latched — a model burning continuously counts once until it
    # recovers below a 1.0 burn and breaches again). Each transition
    # also emits a fault(kind=slo_breach) event with the model, burn
    # rate, and objective; this counter is the process-lifetime total
    # the /metrics exposition and report diff read.
    "slo_breaches": 0,
    # EFFECTIVE per-round g/h HBM stream bytes (grad_stream_bytes below;
    # recorded by the Driver and the streaming trainers every round) —
    # the quantized-gradient win's in-process witness: an f32 run and an
    # int8 run of the same shape record 4x different values here, read
    # back from their run logs' counters events (ISSUE 14).
    "grad_stream_bytes_est": 0,
    # Rounds that ran the quantized-gradient path (scale derivation +
    # stochastic rounding) — nonzero iff cfg.grad_dtype != "f32"
    # actually armed (the "is the integer path live" observability
    # counter; the per-round scales themselves are in-trace values, so
    # they surface via debug logs, not counters).
    "grad_quant_rounds": 0,
    # Drift alert transitions (serve/drift.py, ISSUE 19): the number of
    # times a model's rolling-window feature divergence (max per-feature
    # PSI vs the artifact's training reference histogram) crossed INTO
    # alert (latched — a model drifting continuously counts once until
    # it recovers below threshold and alerts again). Each transition
    # also emits a `drift` event with the model, divergence scores, and
    # worst feature; this counter is the process-lifetime total the
    # /metrics exposition and report diff read.
    "drift_alerts": 0,
    # Training rounds completed process-wide (ISSUE 20): one tick per
    # boosted round across every trainer path (Driver granular + fused,
    # streamed host + device loops). The live-ops plane's primary
    # liveness signal — statusd's /metrics renders it as
    # ddt_train_rounds_total, and the smoke harness asserts it strictly
    # advances between two mid-run scrapes.
    "train_rounds": 0,
    # train_heartbeat events emitted (ISSUE 20): one per checkpoint
    # cadence boundary on runs with a run log — the post-mortem
    # liveness trail a SIGKILLed run leaves behind (report progress).
    "train_heartbeats": 0,
}
_listener_installed = False
# When truthy, the compile listener drops events: the cost observatory's
# ANALYSIS compile (costmodel._capture re-compiles an already-compiled
# program purely to read XLA's cost model) must not inflate the
# recompile counters it exists to explain — a telemetry run's
# jit_compiles would otherwise read ~2x a telemetry-less run's, and
# `report diff` against a pre-v3 baseline would flag the observatory
# itself as a regression. XLA compiles synchronously on the calling
# thread, so a plain flag scoped by the context manager is sufficient.
_suppressed = False


@contextlib.contextmanager
def suppress_compile_counting():
    """Drop backend-compile counter events for the duration (the cost
    observatory's analysis compiles — see _suppressed above)."""
    global _suppressed
    prev = _suppressed
    _suppressed = True
    try:
        yield
    finally:
        _suppressed = prev


def install_jax_listener() -> None:
    """Register the recompile-counting jax.monitoring listener (idempotent;
    no-op when jax is absent — the cpu-backend CLI must run without it)."""
    global _listener_installed
    if _listener_installed:
        return
    try:
        from jax import monitoring
    except ImportError:
        return

    def _on_duration(event, duration_secs=None, **kw) -> None:
        if event == _COMPILE_EVENT and not _suppressed:
            _c["jit_compiles"] += 1
            _c["jit_compile_seconds"] += float(duration_secs or 0.0)

    monitoring.register_event_duration_secs_listener(_on_duration)
    _listener_installed = True


def record_h2d(nbytes: int) -> None:
    _c["h2d_bytes"] += int(nbytes)


def record_d2h(nbytes: int) -> None:
    _c["d2h_bytes"] += int(nbytes)


def record_collective(nbytes: int) -> None:
    _c["collective_bytes_est"] += int(nbytes)


def record_compiled_ensemble_hit() -> None:
    _c["compiled_ensemble_cache_hits"] += 1


def record_fault_retry() -> None:
    _c["fault_retries"] += 1


def record_hist_oom_degrade() -> None:
    _c["hist_oom_degrades"] += 1


def record_serve_requests(n: int) -> None:
    _c["serve_requests"] += int(n)


def record_serve_batch() -> None:
    _c["serve_batches"] += 1


def record_serve_hot_swap() -> None:
    _c["serve_hot_swaps"] += 1


def record_serve_express() -> None:
    _c["serve_express"] += 1


def record_fleet_eviction() -> None:
    _c["fleet_evictions"] += 1


def record_fleet_reload() -> None:
    _c["fleet_reloads"] += 1


def record_slo_breach() -> None:
    _c["slo_breaches"] += 1


def record_drift_alert() -> None:
    _c["drift_alerts"] += 1


def record_grad_stream(nbytes: int) -> None:
    _c["grad_stream_bytes_est"] += int(nbytes)


def record_grad_quant_round(n: int = 1) -> None:
    _c["grad_quant_rounds"] += int(n)


def record_train_round(n: int = 1) -> None:
    _c["train_rounds"] += int(n)


def record_train_heartbeat() -> None:
    _c["train_heartbeats"] += 1


def snapshot() -> dict:
    """Point-in-time copy of the monotonic counters."""
    return dict(_c)


def delta(start: dict, end: dict | None = None) -> dict:
    """Counter movement since `start` (a snapshot()); `end` defaults to
    now. Float counters (compile seconds) are rounded to keep the run
    log's JSON readable; integer counters pass through exact."""
    end = end if end is not None else snapshot()
    out = {k: end[k] - start.get(k, 0) for k in _c}
    out["jit_compile_seconds"] = round(out["jit_compile_seconds"], 4)
    return out


def device_peak_bytes() -> int | None:
    """Accelerator memory high-water mark, or None where the platform
    exposes no memory_stats (CPU XLA, some runtimes)."""
    try:
        import jax

        stats = jax.local_devices()[0].memory_stats()
    except (ImportError, RuntimeError, IndexError, AttributeError,
            NotImplementedError):
        return None
    if not stats:
        return None
    for key in ("peak_bytes_in_use", "bytes_in_use"):
        if key in stats:
            return int(stats[key])
    return None


def host_peak_rss_bytes() -> int | None:
    """Peak HOST resident-set size of this process (resource.getrusage
    ru_maxrss), or None where the resource module is unavailable
    (non-POSIX). The host-side twin of device_peak_bytes: the streaming
    trainers' O(chunk) host contract and the predict sink's bounded
    residency are claims about THIS number, so the run log records it
    next to the device high-water mark. Linux reports ru_maxrss in KiB,
    macOS in bytes — normalised to bytes here."""
    try:
        import resource
        import sys
    except ImportError:
        return None
    ru = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    return int(ru) if sys.platform == "darwin" else int(ru) * 1024


def hist_allreduce_bytes_by_level(
        max_depth: int, n_features: int, n_bins: int,
        *, partitions: int = 1, mode: str = "allreduce",
        subtraction: bool = False, comms_dtype: str = "f32",
        feature_partitions: int = 1,
        grad_dtype: str = "f32") -> "list[int]":
    """Per-LEVEL effective collective payload (levels 0..max_depth-1;
    the leaf-aggregate term is hist_allreduce_bytes' extra). The
    quantized-gradient acceptance contract reads this form: under
    integer hists subtraction is unconditionally exact, so every level
    >= 1 moves exactly HALF the f32-with-subtraction-off baseline's
    entries — a per-level >= 2x wire reduction the counters witness
    (docs/PERF.md "Quantized gradients"; whole-tree the ratio
    asymptotes to 2 from below because depth 0 has no parent).
    `grad_dtype` != "f32" means int32 partials on the wire (4 B/value —
    same as f32; the win is the halved entry count plus bit-stable
    merges without int32_fixed) and refuses compressed comms_dtype like
    the wire itself does (parallel/comms.hist_reduce)."""
    from ddt_tpu.parallel.comms import COMMS_DTYPE_BYTES

    if grad_dtype != "f32" and comms_dtype != "f32":
        raise ValueError(
            f"grad_dtype={grad_dtype!r} with comms_dtype={comms_dtype!r}: "
            "integer histogram partials refuse compression (the "
            "double-quantization hazard — config.py/comms.hist_reduce)")
    # int32 partials and f32 both move 4 B/value; the dict keeps the
    # spelling honest if a narrower integer wire ever lands.
    val_bytes = 4 if grad_dtype != "f32" else COMMS_DTYPE_BYTES[comms_dtype]
    per_entry = val_bytes * 2                            # (g, h) pairs
    P = max(1, partitions)
    Pf = max(1, feature_partitions)
    f_dev = -(-n_features // Pf)
    out = []
    for d in range(max_depth):
        nodes = 1 << d
        if subtraction and d >= 1:
            nodes //= 2                   # left children only
        if mode == "reduce_scatter":
            f_pad = -(-f_dev // P) * P
            total = nodes * (f_pad // P) * n_bins * per_entry
            # Winner combine: gain/feat/bin/dl x [n_level] from every
            # shard that owns a distinct slab (Pr row shards x Pf
            # feature shards on the 2D mesh).
            total += P * Pf * (1 << d) * 4 * 4
        else:
            total = nodes * f_dev * n_bins * per_entry
            if Pf > 1:
                # Column-sharded allreduce mode still combines winners
                # across the feature axis (tiny tuples per level).
                total += Pf * (1 << d) * 4 * 4
        out.append(total)
    return out


def hist_allreduce_bytes(max_depth: int, n_features: int, n_bins: int,
                         *, partitions: int = 1, mode: str = "allreduce",
                         subtraction: bool = False,
                         comms_dtype: str = "f32",
                         feature_partitions: int = 1,
                         grad_dtype: str = "f32") -> int:
    """EFFECTIVE per-device collective payload estimate for ONE tree's
    histogram phases (parallel/comms.py is the wire this models; the
    two must change together).

    Baseline (positional args only — the historical estimate): the
    [n_level, F, n_bins, 2] f32 histogram psum'd at every level plus the
    final level's [2^d, 2] leaf-aggregate reduction. The keyword knobs
    mirror the resolved comms configuration
    (TPUDevice.collective_bytes_per_tree passes them):

    - `subtraction` — sibling-subtraction levels (>= 1) move only LEFT
      children: half the level's entries.
    - `mode="reduce_scatter"` — each device receives its merged
      F_pad/P slab instead of the full table (F pads to the shard
      count), plus the split-winner combine's all_gather: 4 int/f32
      [n_level] vectors from each of the P shards.
    - `comms_dtype` — wire bytes per histogram value (f32/int32_fixed 4,
      bf16 2; parallel/comms.COMMS_DTYPE_BYTES).
    - `feature_partitions` — the 2D (rows x features) mesh's second
      axis (Pf): each device histograms only its F/Pf column slab, so
      the row-axis collective carries F/Pf columns per device —
      composed with reduce_scatter the per-device slab is F/(Pf·Pr),
      i.e. <= 1/(Pr·Pf) of the replicated-feature allreduce baseline
      (plus the O(Pr·Pf·nodes) winner term, which then gathers over
      both axes).

    - `grad_dtype` — the quantized-gradient path (int8/int16): partials
      ride the wire as int32 (4 B/value, like f32 — the wire win there
      is the unconditionally-exact subtraction halving every level >= 1
      plus bit-stable merges with no int32_fixed carve-out); the
      per-level form (hist_allreduce_bytes_by_level) is the acceptance
      contract's witness surface. Leaf aggregates stay 4 B/value
      either way (f32 psum or exact int32 psum).

    An estimate because the collective lives inside a fused device
    program where the host cannot observe the wire; shapes are static
    per config, so it is exact up to XLA's own reduction scheduling."""
    levels = hist_allreduce_bytes_by_level(
        max_depth, n_features, n_bins, partitions=partitions, mode=mode,
        subtraction=subtraction, comms_dtype=comms_dtype,
        feature_partitions=feature_partitions, grad_dtype=grad_dtype)
    return sum(levels) + (1 << max_depth) * 4 * 2   # leaf aggregates: psum


def grad_stream_bytes(rows: int, max_depth: int,
                      grad_dtype: str = "f32") -> int:
    """EFFECTIVE per-tree g/h HBM stream estimate: every histogram pass
    (max_depth levels + the leaf pass) re-reads both gradient rows at
    their STORED itemsize — 8 B/row/pass for f32, 4 for int16, 2 for
    int8 (ops/grad.GRAD_ITEMSIZE is the one home; node_index's 4 B/row
    is dtype-invariant and excluded so the ratio is the g/h story).
    The Driver and streaming trainers record this per round into
    `grad_stream_bytes_est` — the quantized path's >= 2x (int16) / 4x
    (int8) per-level byte cut, witnessed in-process from run-log
    counters rather than merely computed (ISSUE 14)."""
    from ddt_tpu.ops.grad import GRAD_ITEMSIZE

    return (max_depth + 1) * rows * 2 * GRAD_ITEMSIZE[grad_dtype]
