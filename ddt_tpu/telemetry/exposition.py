"""Prometheus text-exposition primitives shared by train and serve.

ONE dialect home (ISSUE 20): the writer helpers and the parser test
twin used by every `/metrics` endpoint in the system — the serve tier's
(`serve/metrics.py`, ISSUE 17) and the training operations plane's
(`telemetry/statusd.py`). Factored out of `serve/metrics.py` verbatim
so the two planes cannot drift into different escaping/formatting
rules; `serve/metrics.py` re-exports them, so its import surface is
unchanged.

Format is the Prometheus text exposition, version 0.0.4. STRICTLY
READ-ONLY semantics ride with every consumer: rendering never mutates
the counters it is handed.

No HTTP, no locks, no engine or trainer imports — callers collect the
snapshots and this module only formats. Host-side and dependency-free
by design.
"""

from __future__ import annotations

#: the Content-Type every /metrics endpoint sends (serve/http.py and
#: statusd both): Prometheus scrapers key on the version token.
EXPOSITION_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _esc(label: str) -> str:
    """Escape a label value per the exposition format."""
    return (str(label).replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _num(v) -> str:
    """Format a sample value: integers bare, floats as-is."""
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, float) and v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v) if isinstance(v, float) else str(v)


def render_counters(counters: dict) -> "list[str]":
    """Process counters -> one ``ddt_<name>_total`` series each."""
    out = []
    for key in sorted(counters):
        v = counters[key]
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            continue
        name = f"ddt_{key}_total"
        out.append(f"# TYPE {name} counter")
        out.append(f"{name} {_num(v)}")
    return out


def parse_exposition(text: str) -> dict:
    """Inverse of the renderers for tests and the smoke harness:
    {series_name: {frozenset(label items) or (): value}}. Tolerates
    comments and blank lines; not a general openmetrics parser."""
    out: dict = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        name_part, _, value = line.rpartition(" ")
        if "{" in name_part:
            name, _, rest = name_part.partition("{")
            labels = {}
            for item in rest.rstrip("}").split(","):
                if not item:
                    continue
                k, _, v = item.partition("=")
                labels[k] = v.strip('"')
            key = frozenset(labels.items())
        else:
            name, key = name_part, ()
        out.setdefault(name, {})[key] = float(value)
    return out
