"""Run-log events: schema-versioned JSONL records + in-memory ring buffer.

Every record is one JSON object per line with a fixed envelope
(`event`, `schema`, `t`, `seq`) plus the event type's required fields
(EVENT_FIELDS) and any optional extras. The schema is validated at EMIT
time (a malformed event is a bug at the producer, not something for the
report CLI to limp around) and again at READ time (report.read_events),
so a log that loads is a log every consumer can trust.

Writes are line-buffered appends of complete lines — a run killed mid-
round (the fault-injection story) loses at most its final partial line,
which read-side validation then skips with a warning rather than
discarding the run.
"""

from __future__ import annotations

import collections
import hashlib
import json
import time

# v2 (the distributed flight recorder): adds the per-partition events
# `partition_phases` / `partition_skew` and the `run_id` / `host`
# manifest extras the cross-host merge keys on.
# v3 (the device-truth cost observatory): adds the `cost_analysis` event
# (XLA compiled-executable cost/memory analysis per jit entry point —
# telemetry/costmodel.py) and the manifest's optional `xprof_dir` /
# `xprof_rounds` extras (telemetry/profiler.py capture windows).
# v4 (the low-latency serving tier): adds the `serve_latency` event
# (per-window request latency quantiles + admission-batching counters
# from ServeEngine — ddt_tpu/serve/engine.py) and the `hot_swap` fault
# kind. v1-v3 logs remain readable (no required field of an existing
# event ever changed — the back-compat contract tests/test_observatory.
# py and tests/test_serve.py pin).
# v5 (the AOT export + model registry): adds the `artifact` event
# (registry push/load/serve-publish records carrying the content
# digest, name@version, and the training run_id — ddt_tpu/registry/),
# plus the optional `artifact_digest` extra on serve_latency and the
# `old_artifact`/`new_artifact` extras on hot_swap faults. v1-v4 logs
# remain readable (tests/test_registry.py pins the v4 round trip).
# ISSUE 15 extras (schema-ADDITIVE, no version bump — the fleet tier):
# `model_name` on serve_latency and hot_swap faults (the multi-model
# dimension `report`'s fleet rollup groups on; absent on single-model
# logs, which render exactly as before), the fleet lifecycle fault
# kinds fleet_eviction / fleet_reload / fleet_remove (model_name +
# artifact_digest + running eviction/reload counts as extras), and the
# fleet_evictions / fleet_reloads process counters.
# ISSUE 17 extras (schema-ADDITIVE, no version bump — the serve-side
# operations plane): the `serve_trace` event (a flushed per-model ring
# of per-request timing breakdowns — trace id, accept→admit, queue/
# window wait, gate hold, device call, wake; flushed on demand via
# `GET /debug/requests?emit=1` or automatically on SLO breach), the
# `slo_breach` fault kind (burn_rate + objective_ms + window_s extras),
# the `slo_p99_ms` objective extra on serve_latency windows, and the
# slo_breaches process counter. Pre-SLO logs remain readable and render
# exactly as before (tests/test_fleet.py pins the mixed-era report).
# ISSUE 19 extras (schema-ADDITIVE, no version bump — the drift
# observatory): the `drift` event (a latched per-model alert transition
# when a model's rolling-window feature divergence against its
# artifact's training reference histogram crosses the PSI threshold —
# ddt_tpu/serve/drift.py; psi_max required, per-feature attribution +
# Jensen-Shannon score + window shape as extras), the drift_alerts
# process counter, and the drift/shadow extras on serve_latency windows
# (drift_psi_max, drift_js_max, shadow_model, shadow_mean_abs_diff,
# shadow_ms_p50 — how `report drift` recovers per-model drift and
# champion/challenger comparison from a log). Pre-drift logs remain
# readable and render exactly as before (tests/test_drift.py pins the
# mixed-era report).
# ISSUE 20 extras (schema-ADDITIVE, no version bump — the training
# operations plane): the `train_heartbeat` event, emitted by every
# trainer path at checkpoint cadence when a run log is attached (round
# required; total_rounds, checkpoint_round, ms_per_round, rows_per_s as
# extras) so a SIGKILLed run is diagnosable from its log's last
# heartbeat (`report progress`), plus the train_rounds /
# train_heartbeats process counters statusd's live /metrics exposition
# reads. Pre-heartbeat logs remain readable and render exactly as
# before (tests/test_statusd.py pins the mixed-era report).
SCHEMA_VERSION = 5

#: event type -> REQUIRED payload fields (extras are allowed and common:
#: e.g. `round` records carry `valid_<metric>` keys named by the run's
#: metric, and nullable fields like train_loss simply hold null).
EVENT_FIELDS: dict[str, set] = {
    # One per run, first record: what trained, on what, from where.
    # Since schema v2 manifests also carry `run_id` (deterministic config
    # digest, identical on every host of a pod run — the cross-host merge
    # key) and `host` (jax.process_index) as extras.
    "run_manifest": {"trainer", "backend", "loss", "n_trees", "max_depth",
                     "rows", "features"},
    # One per boosting round (the Driver.history record, as an event).
    "round": {"round", "ms_per_round"},
    # PhaseTimer.as_json() embedded verbatim under "phases".
    "phase_timings": {"phases"},
    # Per-partition attribution for ONE round (or fused block) of a mesh
    # run: `partitions` is [{device, phases: {name: ms}, rows?,
    # hist_allreduce_bytes}] — per-device completion wall times observed
    # by the host-side shard probe (PartitionRecorder).
    "partition_phases": {"round", "partitions"},
    # End-of-run straggler reduction over the partition_phases stream:
    # `phases` is [{phase, ms_max, ms_median, skew, max_device}]
    # (partition_skew_summary's exact output — tests recompute it
    # offline from the partition_phases events and compare).
    "partition_skew": {"phases"},
    # The early-stopping decision, when one fires.
    "early_stop": {"round", "best_round", "best_score", "metric"},
    # Fault/recovery events. Kinds (extras per kind; the catalog table
    # lives in docs/OBSERVABILITY.md): checkpoint_resume,
    # checkpoint_corrupt, checkpoint_fallback, checkpoint_unrecoverable
    # (utils/checkpoint.py); retry / retry_exhausted / retry_deadline
    # (utils/retry.py, with seam + attempt); injected (the chaos
    # harness, robustness/faultplan.py, with site); hist_oom_degrade
    # (backends/tpu.py); straggler_detected / repartition
    # (robustness/watchdog.py via the trainers); hot_swap
    # (serve/engine.py + fleet retag, with old/new tokens and the
    # ISSUE 15 model_name extra); fleet_eviction / fleet_reload /
    # fleet_remove (serve/fleet.py, with model_name + artifact_digest);
    # slo_breach (serve/fleet.py burn-rate tracker, with model_name +
    # burn_rate + objective_ms + window_s + requests).
    "fault": {"kind"},
    # Device-counter deltas over the run (telemetry.counters).
    "counters": {"jit_compiles", "h2d_bytes", "d2h_bytes",
                 "collective_bytes_est"},
    # XLA's own cost model for one jit-compiled op entry point at one
    # argument signature (telemetry/costmodel.py): per-call FLOPs and
    # bytes accessed from compile().cost_analysis(), plus extras —
    # phase (the phase_timings name the roofline join keys on), calls,
    # platform, arg/output/temp HBM bytes from memory_analysis(),
    # signature. Emitted in the run epilogue, one per (op, signature).
    "cost_analysis": {"op", "flops", "bytes_accessed"},
    # Registry provenance (schema v5, ddt_tpu/registry/): one per
    # artifact lifecycle step — action in {push, load}, digest = the
    # 16-hex content address. Extras: name, version, kind, the training
    # run_id (the cross-reference `report`'s registry section joins on),
    # model_token, and mode (the loader's restore ladder: aot-f32 /
    # aot-lut / aot-lut4 / tables-fallback / rebuild).
    "artifact": {"action", "digest"},
    # Serving-tier SLO window (schema v4, ddt_tpu/serve/engine.py): one
    # per emitted latency window — per-request latency quantiles
    # (p50/p99; extras p999_ms, max_ms), admission-batching shape
    # (batches, coalesce_mean/max, queue_depth_max), window_s, and the
    # served model's content-digest token. Additive ISSUE 12 extras:
    # `predict_impl` (the quantization tier ACTUALLY serving the window
    # — "lut4"/"lut"/"f32"; a silent VMEM-guard fallback is visible
    # here, not only in debug logs) and `express` (requests the express
    # lane dispatched without an admission window). Additive ISSUE 15
    # extra: `model_name` (the fleet tier emits one window per resident
    # model — `report`'s fleet rollup groups on it; absent on
    # single-model logs). Consumed by `report`'s serving section and
    # banded (via the bench stamps) by benchwatch.
    "serve_latency": {"requests", "p50_ms", "p99_ms"},
    # Serve-side request traces (ISSUE 17, schema-additive): one flushed
    # per-model ring of completed per-request timing breakdowns —
    # `traces` is [{trace_id, rows, express, handler_ms, queue_ms,
    # gate_ms, device_ms, wake_ms, total_ms}] (serve/batcher.py
    # trace_breakdown is the one shape home). Flushed on demand
    # (GET /debug/requests?emit=1) or on SLO breach, with the model
    # dimension and the flush reason as extras. Absent from pre-trace
    # logs; report ignores unknown-to-it events by construction.
    "serve_trace": {"traces"},
    # Drift alert transition (ISSUE 19, schema-additive): one per
    # latched crossing of a model's rolling-window feature divergence
    # into alert — psi_max is the worst per-feature population
    # stability index vs the artifact's training reference histogram
    # (serve/drift.py is the one divergence home). Extras carry the
    # model dimension, the worst feature, the companion Jensen-Shannon
    # score, and the window shape so the report can rank breaches.
    # Absent from pre-drift logs; report ignores unknown-to-it events
    # by construction.
    "drift": {"psi_max"},
    # Training-liveness heartbeat (ISSUE 20, schema-additive): one per
    # checkpoint cadence boundary on runs with a run log, from every
    # trainer path (Driver granular + fused, streamed host + device).
    # `round` is the 1-based count of completed rounds at emit time;
    # extras carry the configured total, the latest checkpoint round,
    # and the rolling rate — the post-mortem trail `report progress`
    # rolls up when a run dies between heartbeats.
    "train_heartbeat": {"round"},
    # Last record of a completed run.
    "run_end": {"completed_rounds", "wallclock_s"},
}

#: event type -> DECLARED optional extras (fnmatch globs allowed:
#: `round` records carry `valid_<metric>` keys named by the run's
#: metric). Extras stay runtime-optional — validate_event does not
#: require them — but they are no longer informal: ddtlint's
#: telemetry-contract pass (tools/ddtlint/telemetrycontract.py) checks
#: every literal emit-site keyword against this catalog, and
#: docs/OBSERVABILITY.md embeds the derived contract. Growing this dict
#: is the schema-ADDITIVE move (no version bump); growing a kind's
#: REQUIRED set is not (event-schema-additivity).
EVENT_EXTRAS: dict[str, tuple] = {
    "run_manifest": (
        # v1 shape facts + v2 merge keys.
        "n_bins", "n_classes", "seed", "distributed", "run_id", "host",
        # Streaming runs (n_chunks) and the resolved comms config
        # (comms_manifest_fields — ISSUE 10/11/14 extras).
        "n_chunks", "grad_dtype", "split_comms", "hist_comms_dtype",
        "hist_comms_slabs", "mesh_layout",
        # v3 xprof cross-reference (telemetry/profiler.py).
        "xprof_dir", "xprof_rounds",
    ),
    "round": ("train_loss", "valid_*"),
    "phase_timings": (),
    "partition_phases": ("rounds",),
    "partition_skew": ("n_partitions",),
    "early_stop": (),
    # The union of every fault kind's extras — the catalog table mapping
    # kind -> extras lives in docs/OBSERVABILITY.md; report reads them
    # per kind, the schema only promises they are declared names.
    "fault": (
        "round", "rotation", "device", "skew", "streak",      # stragglers
        "seam", "attempt", "error", "message", "deadline_s",  # retries
        "site",                                               # injected
        "from_impl", "to_impl", "row_chunk",                  # OOM degrade
        "old", "new", "old_artifact", "new_artifact",         # hot swap
        "model_name", "artifact_digest", "evictions",         # fleet
        "reloads", "failed_requests",
        "candidate", "reason",                                # checkpoints
        "burn_rate", "objective_ms", "window_s", "requests",  # slo_breach
    ),
    # Everything counters.delta() / the finish_run_log epilogue may
    # publish beyond the required four — kept in sync with the `_c`
    # registry by the undeclared-event-extra cross-check.
    "counters": (
        "jit_compile_seconds", "compiled_ensemble_cache_hits",
        "fault_retries", "hist_oom_degrades",
        "serve_requests", "serve_batches", "serve_hot_swaps",
        "serve_express", "fleet_evictions", "fleet_reloads",
        "slo_breaches", "drift_alerts",
        "grad_stream_bytes_est", "grad_quant_rounds",
        "train_rounds", "train_heartbeats",
        "device_peak_bytes", "host_peak_rss_bytes",
    ),
    "cost_analysis": ("phase", "calls", "platform", "signature",
                      "arg_bytes", "output_bytes", "temp_bytes"),
    "artifact": ("name", "version", "kind", "run_id", "model_token",
                 "mode"),
    "serve_latency": ("batches", "window_s", "p999_ms", "max_ms",
                      "coalesce_mean", "coalesce_max", "queue_depth_max",
                      "express", "model_token", "model_name",
                      "predict_impl", "artifact_digest", "slo_p99_ms",
                      # ISSUE 19 drift/shadow extras: the window's
                      # divergence scores and, on a shadowed champion,
                      # the challenger's comparison stats — the signals
                      # `report drift` joins per model.
                      "drift_psi_max", "drift_js_max", "drift_alerting",
                      "shadow_model", "shadow_rows",
                      "shadow_mean_abs_diff", "shadow_ms_p50",
                      "shadow_dropped"),
    "serve_trace": ("model_name", "model_token", "reason", "count"),
    # Training heartbeats (ISSUE 20): the run's configured round total,
    # the last checkpoint boundary crossed, and the rolling rate at
    # emit time — everything `report progress` needs to place a
    # mid-run death between two cadence marks.
    "train_heartbeat": ("total_rounds", "checkpoint_round",
                        "ms_per_round", "rows_per_s"),
    # Drift alert transitions (ISSUE 19): the model dimension, worst-
    # feature attribution, companion Jensen-Shannon score, window shape,
    # and the alert threshold that was crossed.
    "drift": ("model_name", "feature", "js_max", "psi_mean",
              "window_rows", "window_s", "threshold", "alerts"),
    "run_end": (),
}

#: every `fault` event kind any emitter may use — the undeclared-event-
#: kind rule checks literal kinds against this tuple, so a typo'd kind
#: is a lint finding, not a fault event report silently cannot group.
#: The per-kind extras table lives in docs/OBSERVABILITY.md.
FAULT_KINDS = (
    "checkpoint_resume", "checkpoint_corrupt", "checkpoint_fallback",
    "checkpoint_unrecoverable",
    "retry", "retry_exhausted", "retry_deadline",
    "injected", "hist_oom_degrade",
    "straggler_detected", "repartition",
    "hot_swap", "fleet_eviction", "fleet_reload", "fleet_remove",
    "slo_breach",
)

ENVELOPE_FIELDS = ("event", "schema", "t", "seq")


def validate_event(rec: dict) -> None:
    """Raise ValueError unless `rec` is a well-formed run-log record."""
    if not isinstance(rec, dict):
        raise ValueError(f"run-log record must be an object, got "
                         f"{type(rec).__name__}")
    missing = [k for k in ENVELOPE_FIELDS if k not in rec]
    if missing:
        raise ValueError(f"run-log record missing envelope fields {missing}")
    if not isinstance(rec["schema"], int) or isinstance(rec["schema"], bool):
        # A corrupt/hand-edited line must surface as the reader's clean
        # ValueError, not a TypeError from the comparison below.
        raise ValueError(
            f"run-log schema must be an integer, got {rec['schema']!r}")
    if rec["schema"] > SCHEMA_VERSION:
        raise ValueError(
            f"run-log schema {rec['schema']} is newer than this reader "
            f"(schema {SCHEMA_VERSION}); upgrade ddt_tpu to report on it")
    ev = rec["event"]
    if ev not in EVENT_FIELDS:
        raise ValueError(
            f"unknown run-log event {ev!r}; have {sorted(EVENT_FIELDS)}")
    missing = [k for k in EVENT_FIELDS[ev] if k not in rec]
    if missing:
        raise ValueError(f"{ev} record missing required fields {missing}")


class RunLog:
    """Append-only JSONL run log + bounded in-memory ring buffer.

    `path=None` keeps events in the ring only (tests, library callers).
    The file handle opens lazily on the first emit and is line-buffered;
    `close()` (or context-manager exit) releases it. Emission never
    touches the device — every field is host data the trainer already
    had in hand.
    """

    def __init__(self, path: str | None = None, ring_size: int = 4096):
        self.path = path
        self.ring: collections.deque = collections.deque(maxlen=ring_size)
        self._fh = None
        self._seq = 0
        # Bound by the trainer that derives it (Driver, fit_streaming) —
        # callers that only hold the log (the CLI's streaming save path)
        # read the run's identity here; survives close().
        self.run_id: str | None = None

    @classmethod
    def coerce(cls, run_log) -> "RunLog | None":
        """None | path-str | RunLog -> RunLog | None (the api.train /
        fit_streaming argument convention)."""
        if run_log is None or isinstance(run_log, cls):
            return run_log
        return cls(str(run_log))

    def emit(self, event: str, **fields) -> dict:
        rec = {"event": event, "schema": SCHEMA_VERSION,
               "t": time.time(), "seq": self._seq, **fields}
        validate_event(rec)
        self._seq += 1
        self.ring.append(rec)
        if self.path is not None:
            if self._fh is None:
                self._fh = open(self.path, "a", buffering=1,
                                encoding="utf-8")
            self._fh.write(json.dumps(rec, sort_keys=False) + "\n")
        return rec

    def events(self, event: str | None = None) -> list[dict]:
        """Ring-buffer contents (oldest first), optionally one type."""
        return [r for r in self.ring if event is None or r["event"] == event]

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def __enter__(self) -> "RunLog":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def emit_early_stop(run_log: "RunLog | None", stop_round: int, metric,
                    best_round: int, best_score) -> None:
    """The early_stop event, one emit site for the Driver's granular and
    fused loops and both streaming loops (rounds are 1-based here)."""
    if run_log is None:
        return
    run_log.emit("early_stop", round=stop_round, metric=metric,
                 best_round=best_round, best_score=best_score)


def finish_run_log(run_log: "RunLog | None", timer, counters_start,
                   completed_rounds: int, wallclock_s: float,
                   partitions: "PartitionRecorder | None" = None,
                   costs=None) -> None:
    """Run-log epilogue — [partition_skew +] [cost_analysis... +]
    phase_timings + counters + run_end — shared by Driver._finish_run
    and fit_streaming's _finish so the trainers' terminal records cannot
    drift. `timer` is a PhaseTimer or None; `counters_start` a
    telemetry.counters.snapshot() (or None); `partitions` the mesh run's
    PartitionRecorder (or None); `costs` the run's costmodel.Collector
    (or None). Closing path-owned logs is the trainers' ownership shims'
    job (Driver.fit / fit_streaming), which also covers the exception
    paths this helper never sees."""
    if run_log is None:
        return
    from ddt_tpu.telemetry import counters as tele_counters

    if partitions is not None:
        partitions.emit_skew()
    if costs is not None:
        from ddt_tpu.telemetry import costmodel

        costmodel.flush_into(run_log, costs)
    if timer is not None and timer.totals:
        run_log.emit("phase_timings", phases=timer.as_json())
    d = tele_counters.delta(counters_start or {})
    d["device_peak_bytes"] = tele_counters.device_peak_bytes()
    d["host_peak_rss_bytes"] = tele_counters.host_peak_rss_bytes()
    run_log.emit("counters", **d)
    run_log.emit("run_end", completed_rounds=completed_rounds,
                 wallclock_s=wallclock_s)


def emit_train_heartbeat(run_log, *, rnd, total_rounds,
                         checkpoint_round=None, ms_per_round=None,
                         rows_per_s=None) -> None:
    """One heartbeat at a checkpoint-cadence boundary — the ONE emit
    home shared by every trainer path (Driver granular + fused,
    streamed host + device loops) so the record shape cannot drift.
    `rnd` is 0-based (the loop variable); the event's `round` is the
    1-based completed count, matching `round` records. No-op without a
    run log (the disabled-telemetry contract)."""
    if run_log is None:
        return
    from ddt_tpu.telemetry import counters as tele_counters

    tele_counters.record_train_heartbeat()
    extras = {}
    if checkpoint_round is not None:
        extras["checkpoint_round"] = checkpoint_round
    if ms_per_round is not None:
        extras["ms_per_round"] = round(float(ms_per_round), 3)
    if rows_per_s is not None:
        extras["rows_per_s"] = round(float(rows_per_s), 1)
    run_log.emit("train_heartbeat", round=rnd + 1,
                 total_rounds=total_rounds, **extras)


def comms_manifest_fields(backend) -> dict:
    """run_manifest extras describing the RESOLVED split-finding comms
    configuration (ISSUE 10; schema extras only, no version bump —
    absent on single-device backends and in every pre-existing log, and
    report treats them as optional). The one home the Driver's and the
    streaming trainers' manifests share. ISSUE 14 extra: `grad_dtype`
    appears whenever the quantized-gradient path is armed (absent =
    f32), single-device runs included — the effective-bytes counters'
    byte model keys on it."""
    out = {}
    cfg = getattr(backend, "cfg", None)
    if cfg is not None and getattr(cfg, "grad_dtype", "f32") != "f32":
        out["grad_dtype"] = cfg.grad_dtype
    if not getattr(backend, "distributed", False):
        return out
    return {
        **out,
        "split_comms": getattr(backend, "split_comms", "allreduce"),
        "hist_comms_dtype": backend.cfg.hist_comms_dtype,
        "hist_comms_slabs": int(getattr(backend, "comms_slabs", 1)),
        # ISSUE 11 extra: the LIVE mesh's (row shards, feature shards)
        # pair — the second axis the partition_phases lanes and the
        # comms roofline's effective-bytes model account for. Named
        # mesh_LAYOUT, not mesh_shape: row_shards folds host_partitions
        # in (hosts x rows), so this is NOT replayable as
        # cfg.mesh_shape on pod runs. Schema extra like the rest:
        # absent in pre-2D logs, optional to report.
        "mesh_layout": [int(getattr(backend, "row_shards", 1)),
                        int(getattr(backend, "feature_partitions", 1))],
    }


def derive_run_id(**fields) -> str:
    """Deterministic 12-hex run id from the run's config facts. Every
    host of a multi-host run derives the IDENTICAL id from its (identical
    by SPMD construction) config — the key telemetry.merge joins per-host
    logs on. Same config rerun -> same id; the merge additionally keys on
    file identity, so that is a feature (retry logs join), not a
    collision."""
    blob = json.dumps(fields, sort_keys=True, default=str)
    return hashlib.sha1(blob.encode()).hexdigest()[:12]


def partition_skew_summary(totals: dict) -> list[dict]:
    """Host-side straggler reduction: {lane: {phase: ms}} accumulated
    per-lane phase wall times -> [{phase, ms_max, ms_median, skew,
    max_device}], phases sorted by ms_max descending. A lane key is a
    device id (single-host collection) or a (host, device) tuple (the
    report's cross-host recompute — the record then also carries
    max_host). `skew` is max/median (1.0 = perfectly balanced); the ONE
    reduction home — PartitionRecorder emits it and the tests recompute
    it offline from the partition_phases events, so the two cannot
    drift."""
    phases: dict[str, dict] = {}
    for lane, per_phase in totals.items():
        for name, ms in per_phase.items():
            # one value per (lane, phase) by construction — assign
            phases.setdefault(name, {})[lane] = ms
    out = []
    for name, by_lane in phases.items():
        vals = sorted(by_lane.values())
        n = len(vals)
        median = (vals[n // 2] if n % 2 else
                  (vals[n // 2 - 1] + vals[n // 2]) / 2.0)
        # max over sorted keys -> the SMALLEST lane wins exact ties
        # (deterministic for int and tuple keys alike)
        max_lane = max(sorted(by_lane), key=lambda k: by_lane[k])
        ms_max = by_lane[max_lane]
        rec = {
            "phase": name,
            "ms_max": round(ms_max, 3),
            "ms_median": round(median, 3),
            "skew": round(ms_max / median, 3) if median > 0 else None,
        }
        if isinstance(max_lane, tuple):
            rec["max_host"] = int(max_lane[0])
            rec["max_device"] = int(max_lane[1])
        else:
            rec["max_device"] = int(max_lane)
        out.append(rec)
    out.sort(key=lambda r: -r["ms_max"])
    return out


class PartitionRecorder:
    """Per-partition phase attribution for mesh runs (the distributed
    flight recorder's collection half).

    Protocol: at an instrumented phase boundary the trainer hands the
    phase's device OUTPUT handle plus the phase's host start time to
    observe(); the backend's shard probe (TPUDevice.partition_ready_ms,
    riding parallel.mesh.shard_ready_times) reports, per addressable
    device, the host-clock moment that device's shard of the output
    completed. The per-device wall time is that completion offset — the
    honest host-observable per-partition signal: inside a psum'd program
    every shard completes only after the collective, so what this
    measures is COMPLETION skew (a straggling partition delays its own
    shard's availability and shows up as the max lane).

    Cost: one device barrier per observed phase — paid ONLY on
    distributed runs with a run log attached. Single-device runs,
    host backends, and disabled telemetry construct an inactive recorder
    whose observe()/flush_round() are attribute checks (no probe, no
    sync, no allocation) — the PR-2 zero-overhead invariant, extended
    (tests/test_telemetry.py guard).

    Emits one `partition_phases` event per flushed round (per fused
    block on the fused path, with the block's first round and a
    `rounds` extra) and, via emit_skew() at run end, one
    `partition_skew` event reducing the whole run
    (partition_skew_summary)."""

    def __init__(self, run_log: "RunLog | None", backend,
                 bytes_per_round: int = 0):
        probe = getattr(backend, "partition_ready_ms", None)
        self.active = (run_log is not None and probe is not None
                       and bool(getattr(backend, "distributed", False)))
        self.run_log = run_log
        self._probe = probe
        self.bytes_per_round = int(bytes_per_round)
        # device -> phase -> ms, current round / whole run
        self._round: dict[int, dict[str, float]] = {}
        self._totals: dict[int, dict[str, float]] = {}

    def observe(self, phase: str, handle, t0: float) -> None:
        """Record the per-device wall time of one phase from its output
        handle (`t0` = the phase's host start, time.perf_counter())."""
        if not self.active:
            return
        ready = self._probe(handle)
        if not ready:
            return
        for dev, t_ready in ready:
            ms = max(0.0, (t_ready - t0) * 1e3)
            self._round.setdefault(dev, {})
            self._round[dev][phase] = self._round[dev].get(phase, 0.0) + ms

    def flush_round(self, rnd: int, n_rounds: int = 1) -> "dict | None":
        """Emit the round's partition_phases event (rnd is 0-based here;
        the event carries the 1-based round like every other record).
        `n_rounds` > 1 on the fused path: the event covers a whole
        block. Returns the flushed {device: {phase: ms}} dict — the
        straggler watchdog's per-round feed (robustness/watchdog.py) —
        or None when inactive/empty."""
        if not self.active or not self._round:
            return None
        # Chaos-harness straggler seam (robustness/faultplan.py): an
        # active plan may inflate one lane's observed time — a
        # DETERMINISTIC straggler (no real sleeping) that flows into the
        # event stream, the skew summary, and the watchdog exactly like
        # a slow device would. One module-global read per device when no
        # plan is active.
        from ddt_tpu.robustness import faultplan

        for dev in self._round:
            extra = faultplan.perturb_ms("straggler", device=int(dev),
                                         round=rnd + 1)
            if extra:
                self._round[dev]["straggler_injected"] = (
                    self._round[dev].get("straggler_injected", 0.0) + extra)
        parts = []
        for dev in sorted(self._round):
            phases = {k: round(v, 3) for k, v in self._round[dev].items()}
            parts.append({
                "device": int(dev), "phases": phases,
                "hist_allreduce_bytes": self.bytes_per_round * n_rounds,
            })
            tot = self._totals.setdefault(dev, {})
            for k, v in self._round[dev].items():
                tot[k] = tot.get(k, 0.0) + v
        self.run_log.emit("partition_phases", round=rnd + 1,
                          rounds=n_rounds, partitions=parts)
        flushed, self._round = self._round, {}
        return flushed

    def emit_skew(self) -> None:
        """End-of-run partition_skew event (finish_run_log calls this
        before the terminal phase_timings/counters/run_end triplet)."""
        if not self.active or not self._totals:
            return
        self.run_log.emit(
            "partition_skew", phases=partition_skew_summary(self._totals),
            n_partitions=len(self._totals))


class RoundRecorder:
    """Per-round history record + run-log event + progress log line — the
    ONE home of the round-record shape, shared by the Driver's granular
    and fused loops (it replaced Driver._record_round) and mirrored by
    the streaming trainer's round events.

    Semantics preserved from the Driver: train loss at `log_every`
    cadence only (the loss thunk may cost a device sync; off-cadence
    records carry train_loss=None so the schema stays uniform), eval
    metric EVERY round — the per-round series (sklearn evals_result_)
    must not depend on the logging knob. ms_per_round is the caller's
    number: real per-round wallclock on the granular path, the block
    average on the fused path (per-round wallclock does not exist there
    — that is the point of fusing).
    """

    def __init__(self, history: list, run_log: RunLog | None,
                 log_every: int, n_rounds: int, metric_name: str | None,
                 logger):
        self.history = history
        self.run_log = run_log
        self.log_every = log_every
        self.n_rounds = n_rounds
        self.metric_name = metric_name
        self.log = logger

    @staticmethod
    def make_record(r: int, ms: float, train_loss,
                    metric_name=None, val_score=None) -> dict:
        """THE round-record dict shape ({round, train_loss, ms_per_round
        [, valid_<metric>]}) — also used by the streaming trainer's round
        events so the two emitters cannot drift."""
        rec = {"round": r + 1, "train_loss": train_loss,
               "ms_per_round": ms}
        if val_score is not None:
            rec[f"valid_{metric_name}"] = val_score
        return rec

    def record(self, r: int, ms: float, val_score, loss_fn) -> None:
        on_cadence = (r + 1) % self.log_every == 0 or r == self.n_rounds - 1
        if not on_cadence and val_score is None and self.run_log is None:
            return                       # nothing records this round
        loss = loss_fn() if on_cadence else None
        rec = self.make_record(r, ms, loss, self.metric_name, val_score)
        if on_cadence or val_score is not None:
            self.history.append(rec)
        if self.run_log is not None:
            self.run_log.emit("round", **rec)
        if on_cadence:
            self.log.info(
                "round %4d/%d  loss=%.6f  %.1f ms/round%s",
                r + 1, self.n_rounds, loss, ms,
                f"  valid_{self.metric_name}={val_score:.6f}"
                if val_score is not None else "",
            )
